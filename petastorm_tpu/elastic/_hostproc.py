"""One elastic pod host as a subprocess — the chaos-test / bench target.

``python -m petastorm_tpu.elastic._hostproc --url ... --coord ... --host h0
--out h0.jsonl`` opens an elastic reader, consumes rows, and appends one JSON
line per epoch plus a final ``{"event": "exit"}`` line to ``--out``. The
driver (``tests/test_elastic.py``, ``bench_pod.py --chaos``) SIGKILLs one of
these mid-epoch and starts another to exercise the handoff protocol with
real process death — the coordination directory's commit logs and done
markers are the ground truth the driver asserts over.

``--sleep-per-row`` throttles consumption so an epoch stays open long enough
for the driver to kill/join deterministically.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _parse_args(argv):
    parser = argparse.ArgumentParser(prog='pstpu-elastic-host')
    parser.add_argument('--url', required=True)
    parser.add_argument('--coord', required=True)
    parser.add_argument('--host', required=True)
    parser.add_argument('--out', required=True)
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--lease-s', type=float, default=1.0)
    parser.add_argument('--poll-s', type=float, default=None)
    parser.add_argument('--num-epochs', type=int, default=1)
    parser.add_argument('--sleep-per-row', type=float, default=0.0)
    parser.add_argument('--field', default='id')
    parser.add_argument('--no-shuffle', action='store_true')
    parser.add_argument('--ready-file', default=None,
                        help='touched once the reader is up and iterating')
    return parser.parse_args(argv)


def main(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    from petastorm_tpu import make_reader
    from petastorm_tpu.elastic import ElasticConfig
    from petastorm_tpu.observability import blackbox

    # label flight files by host id so a post-mortem over the run directory
    # can name WHICH elastic host died (the chaos driver SIGKILLs one)
    blackbox.maybe_enable('elastic-host-' + args.host)
    cfg = ElasticConfig(coord_dir=args.coord, host_id=args.host,
                        lease_s=args.lease_s, poll_s=args.poll_s)
    out = open(args.out, 'a')

    def emit(record):
        out.write(json.dumps(record) + '\n')
        out.flush()

    emit({'event': 'start', 'host': args.host, 'pid': os.getpid()})
    reader = make_reader(args.url, schema_fields=[args.field],
                         reader_pool_type='dummy', seed=args.seed,
                         shuffle_row_groups=not args.no_shuffle,
                         num_epochs=args.num_epochs, elastic=cfg)
    if args.ready_file:
        with open(args.ready_file, 'w') as fh:
            fh.write(str(os.getpid()))
    try:
        values = []
        for row in reader:
            values.append(getattr(row, args.field))
            if args.sleep_per_row:
                time.sleep(args.sleep_per_row)
        status = reader.elastic_coordinator.status()
        emit({'event': 'done', 'host': args.host, 'rows': len(values),
              'values': [int(v) for v in values],
              'generation': status['generation'],
              'members': list(status['members'])})
    finally:
        reader.stop()
        reader.join()
    emit({'event': 'exit', 'host': args.host})
    out.close()
    return 0


if __name__ == '__main__':
    sys.exit(main())
