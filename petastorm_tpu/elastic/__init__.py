"""Elastic pod sharding: survive host join/leave mid-epoch.

``make_reader(elastic=True)`` (or an explicit :class:`ElasticConfig`)
replaces static ``cur_shard``/``shard_count`` arithmetic with a lease-based
membership registry, a generation-numbered shard map, and a resharding
protocol with exactly-once commits (sample delivery is at-least-once only
in the false-expiry window bounded by ``lease_s`` —
``docs/parallelism.md``), all coordinated through a shared filesystem
directory
— no coordinator process, no network channel (``docs/parallelism.md``,
"Elastic pod sharding").

The protocol is model-checked (``petastorm-tpu-modelcheck --elastic``,
spec in :mod:`petastorm_tpu.analysis.protocol.elastic_spec`) and watched
at runtime by :class:`~petastorm_tpu.analysis.protocol.monitor.
ElasticMonitor`; shard-map purity is lint-enforced (PT1200).
"""

from __future__ import annotations

import os

from petastorm_tpu.elastic.membership import (DEFAULT_LEASE_RETRY,
                                              MembershipRegistry)
from petastorm_tpu.elastic.shardmap import (ShardMap, global_order, owner_of,
                                            stable_hash)


def default_host_id():
    """A stable identity for this host: the JAX process index when a
    distributed runtime is initialized, else machine + pid (unique enough
    for single-machine pods and tests)."""
    try:
        from petastorm_tpu.parallel.mesh import reader_shard_for_process
        index, count = reader_shard_for_process()
        if count > 1:
            return 'host{}'.format(index)
    except Exception:       # noqa: PT300 - jax absent/uninitialized: fall back
        pass
    try:
        node = os.uname().nodename
    except (AttributeError, OSError):
        node = 'host'
    return '{}-{}'.format(node, os.getpid())


class ElasticConfig(object):
    """Configuration for an elastic reader.

    :param coord_dir: shared coordination directory all pod hosts can
        reach (NFS/GCS-fuse mount). ``None`` derives ``<dataset>/_elastic``
        from the dataset path — fine whenever the dataset itself lives on
        a shared writable filesystem.
    :param host_id: this host's stable identity; ``None`` derives it from
        ``jax.process_index()`` (falling back to machine+pid)
    :param lease_s: membership lease duration — the worst-case time a dead
        host pins its in-flight row groups, AND the bound on duplicate
        sample delivery after a false expiry (a host stalled longer than
        ``lease_s`` but still running may have its in-flight row groups
        adopted while it is still delivering them; commits stay exclusive)
    :param poll_s: membership/scoreboard scan period (default ``lease_s/4``)
    :param monitor: an :class:`~petastorm_tpu.analysis.protocol.monitor.
        ElasticMonitor` (or ``None`` to resolve from ``PSTPU_ELASTIC_MONITOR``)
    :param retry: a :class:`~petastorm_tpu.retry.RetryPolicy` for all lease
        and scoreboard I/O (default: bounded short-backoff policy) — slow
        shared-fs metadata ops retry instead of false-positiving a death
    """

    __slots__ = ('coord_dir', 'host_id', 'lease_s', 'poll_s', 'monitor',
                 'retry')

    def __init__(self, coord_dir=None, host_id=None, lease_s=5.0,
                 poll_s=None, monitor=None, retry=None):
        if lease_s <= 0:
            raise ValueError('lease_s must be positive, got {!r}'
                             .format(lease_s))
        if poll_s is None:
            poll_s = max(lease_s / 4.0, 0.02)
        if poll_s <= 0:
            raise ValueError('poll_s must be positive, got {!r}'
                             .format(poll_s))
        self.coord_dir = coord_dir
        self.host_id = host_id
        self.lease_s = float(lease_s)
        self.poll_s = float(poll_s)
        self.monitor = monitor
        self.retry = retry

    def retry_policy(self):
        return self.retry if self.retry is not None else DEFAULT_LEASE_RETRY

    def describe(self):
        return ('coord_dir={} host={} lease_s={} poll_s={}'
                .format(self.coord_dir, self.host_id, self.lease_s,
                        self.poll_s))


def resolve_elastic(value, dataset_path=None):
    """Normalize ``make_reader``'s ``elastic=`` argument into a fully
    resolved :class:`ElasticConfig` (filling in the derived coordination
    directory, host identity, and env-resolved monitor)."""
    if value is True:
        cfg = ElasticConfig()
    elif isinstance(value, ElasticConfig):
        cfg = value
    else:
        raise ValueError('elastic= must be True or an ElasticConfig, got '
                         '{!r}'.format(value))
    coord_dir = cfg.coord_dir
    if coord_dir is None:
        if dataset_path is None:
            raise ValueError('elastic=True needs a dataset on a local/shared '
                             'path to derive the coordination directory; '
                             'pass ElasticConfig(coord_dir=...) explicitly')
        coord_dir = os.path.join(dataset_path, '_elastic')
    host_id = cfg.host_id if cfg.host_id is not None else default_host_id()
    from petastorm_tpu.analysis.protocol.monitor import elastic_monitor_from_env
    monitor = elastic_monitor_from_env(cfg.monitor,
                                       name='elastic:{}'.format(host_id))
    resolved = ElasticConfig(coord_dir=coord_dir, host_id=str(host_id),
                             lease_s=cfg.lease_s, poll_s=cfg.poll_s,
                             monitor=monitor, retry=cfg.retry)
    return resolved


__all__ = ['DEFAULT_LEASE_RETRY', 'ElasticConfig', 'MembershipRegistry',
           'ShardMap', 'default_host_id', 'global_order', 'owner_of',
           'resolve_elastic', 'stable_hash']
