"""Columnar decode worker: one row group -> one dict of numpy column arrays.

Parity: /root/reference/petastorm/arrow_reader_worker.py — same task protocol as
the row worker but columnar output with ``batched_output=True`` (:36-37);
vectorized predicate evaluation (:181-240); TransformSpec applied on the column
batch (:163-177); NGram unsupported (:97-98).

TPU-first departure: the worker publishes a dict of numpy arrays (not a pandas
frame or pyarrow table) — the exact container the JAX collator stages into
device host buffers; string columns come out as numpy unicode arrays, list
columns as stacked 2-D arrays when lengths are uniform.
"""

from __future__ import annotations


import numpy as np
import pyarrow as pa

from petastorm_tpu import observability as obs
from petastorm_tpu.columnar import BlockResultsReaderBase
from petastorm_tpu.row_worker import _cache_key, select_row_drop_indices
from petastorm_tpu.native import open_parquet
from petastorm_tpu.predicates import evaluate_predicate_mask
from petastorm_tpu.workers.worker_base import WorkerBase


def _column_to_numpy(column, name):
    """pyarrow ChunkedArray -> numpy array (reference arrow_reader_worker.py:39-79)."""
    t = column.type
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        values = column.to_pylist()
        lengths = {len(v) for v in values if v is not None}
        if len(lengths) == 1 and None not in values:
            return np.asarray(values)
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            out[i] = None if v is None else np.asarray(v)
        return out
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        values = column.to_pylist()
        if any(v is None for v in values):
            # preserve nulls: np.str_ would stringify None into 'None'
            out = np.empty(len(values), dtype=object)
            out[:] = values
            return out
        return np.asarray(values, dtype=np.str_)
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        return np.asarray(column.to_pylist(), dtype=object)
    if pa.types.is_timestamp(t) or pa.types.is_date(t):
        return column.to_pandas().to_numpy()
    if pa.types.is_decimal(t):
        return np.asarray(column.to_pylist(), dtype=object)
    return column.to_numpy(zero_copy_only=False)


class ArrowBatchWorker(WorkerBase):
    """``args``: dataset_path, filesystem_factory, pieces, schema (inferred or
    stored), output_schema, transform_spec, transformed_schema, cache."""

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        self._fs = None
        self._open_files = {}

    def _parquet_file(self, path):
        if self._fs is None:
            self._fs = self.args['filesystem_factory']()
        if path not in self._open_files:
            if len(self._open_files) > 8:
                _, old = self._open_files.popitem()
                old.close()
            self._open_files[path] = open_parquet(
                path, self._fs, chunk_cache=self.args.get('chunk_cache'))
        return self._open_files[path]

    def shutdown(self):
        for f in self._open_files.values():
            try:
                f.close()
            except Exception:  # noqa: BLE001
                pass
        self._open_files = {}

    def process(self, piece_index, worker_predicate=None, shuffle_row_drop_partition=None):
        args = self.args
        piece = args['pieces'][piece_index]
        out_schema = args['output_schema']
        needed = list(out_schema.fields)

        if worker_predicate is None and shuffle_row_drop_partition is None:
            key = _cache_key(args['dataset_path'], piece, needed)
            batch = args['cache'].get(key, lambda: self._load_batch(piece, needed, None))
        else:
            batch = None
            fused_served = False
            if worker_predicate is not None and shuffle_row_drop_partition is None:
                fast = self._load_batch_with_predicate(piece, needed, worker_predicate)
                if fast is not None:
                    batch = fast or None  # {} -> no surviving rows
                    fused_served = True
            if not fused_served:
                # predicate columns are read even when excluded from the output
                # selection (reference arrow_reader_worker.py:181-240)
                load_cols = needed
                if worker_predicate is not None:
                    load_cols = sorted(set(needed) | set(worker_predicate.get_fields()))
                batch = self._load_batch(piece, load_cols, shuffle_row_drop_partition)
                if worker_predicate is not None:
                    batch = self._apply_predicate(batch, worker_predicate)
                    if batch is not None:
                        batch = {k: v for k, v in batch.items() if k in needed}

        if batch is None or not batch:
            return
        n = len(next(iter(batch.values())))
        if n == 0:
            return

        transform = args['transform_spec']
        if transform is not None:
            if transform.func is not None:
                with obs.stage('transform', cat='worker'):
                    batch = transform.func(batch)
            final_fields = set(args['transformed_schema'].fields)
            batch = {k: v for k, v in batch.items() if k in final_fields}

        obs.count('worker_rows_decoded_total', len(next(iter(batch.values()))) if batch else 0)
        self.publish(batch)

    def _load_batch(self, piece, column_names, shuffle_row_drop_partition):
        schema = self.args['schema']
        physical = [c for c in column_names
                    if c not in piece.partition_keys and c in schema.fields]
        pf = self._parquet_file(piece.path)
        # full-group reads serve qualifying columns through the fused native
        # read→decode→collate pass (one GIL-released call; docs/native.md) and
        # Arrow only for the remainder; row subsets need Arrow's take
        pre = {}
        if shuffle_row_drop_partition is None and physical and hasattr(pf, 'read_fused'):
            try:
                # schema_fields=None: the batch reader's contract is RAW
                # columns (no codec decode — encoded images stay bytes), so
                # only plain fixed-width numeric columns fuse here
                pre, _rest = pf.read_fused(piece.row_group, physical, None)
            except Exception:  # noqa: BLE001 - any surprise: Arrow path serves it all
                pre = {}
        rest = [c for c in physical if c not in pre]
        if rest or not pre:
            with obs.stage('read', cat='worker', piece=piece.path,
                           row_group=piece.row_group):
                table = pf.read_row_group(piece.row_group, columns=rest)
                if shuffle_row_drop_partition is not None:
                    indices = select_row_drop_indices(table.num_rows,
                                                      shuffle_row_drop_partition)
                    table = table.take(indices)
            num_rows = table.num_rows
        else:
            table = None
            num_rows = len(next(iter(pre.values())))
        with obs.stage('decode', cat='worker', rows=num_rows):
            batch = {name: (pre[name] if name in pre
                            else _column_to_numpy(table.column(name), name))
                     for name in physical}
        for key, value in piece.partition_keys.items():
            if key in column_names:
                batch[key] = np.full(num_rows, value)
        return batch

    def _load_batch_with_predicate(self, piece, needed, predicate):
        """Native predicate pushdown for the batch reader: clauses, page-stat
        skipping and selected-row collation run in one GIL-released call
        (docs/native.md); Arrow serves only the non-fused columns, taken at
        the surviving row indices. Returns the filtered batch ({} when no row
        survives), or None when the predicate shape / columns are not natively
        evaluable — the caller then runs the Python pushdown path."""
        pf = self._parquet_file(piece.path)
        if not hasattr(pf, 'read_fused_predicate'):
            return None
        clauses = getattr(predicate, 'native_clauses', lambda: None)()
        if clauses is None:
            return None
        schema = self.args['schema']
        pred_fields = sorted(predicate.get_fields())
        if any(f in piece.partition_keys or f not in schema.fields
               for f in pred_fields):
            return None  # partition-key predicates: piece-level path decides
        physical = [c for c in needed
                    if c not in piece.partition_keys and c in schema.fields]
        if not physical:
            return None
        try:
            # schema_fields=None: the batch reader's raw-column contract, same
            # as the unfiltered fused pass above
            res = pf.read_fused_predicate(piece.row_group, physical,
                                          pred_fields, clauses, None)
        except Exception:  # noqa: BLE001 - any surprise: Python pushdown serves it
            return None
        if res is None:
            return None
        block, rest, sel_mask, _n_selected, _pages_skipped = res
        kept = np.flatnonzero(sel_mask)
        if not len(kept):
            return {}
        batch = dict(block)
        if rest:
            with obs.stage('read', cat='worker', piece=piece.path,
                           row_group=piece.row_group):
                table = pf.read_row_group(piece.row_group, columns=rest)
                table = table.take(kept)
            with obs.stage('decode', cat='worker', rows=len(kept)):
                for name in rest:
                    batch[name] = _column_to_numpy(table.column(name), name)
        for key, value in piece.partition_keys.items():
            if key in needed:
                batch[key] = np.full(len(kept), value)
        return batch

    def _apply_predicate(self, batch, predicate):
        """Vectorized when the predicate supports it, else a per-row loop over
        only the predicate columns (reference arrow_reader_worker.py:181-240)."""
        fields = sorted(predicate.get_fields())
        missing = [f for f in fields if f not in batch]
        if missing:
            raise ValueError('Predicate fields {} not available in batch columns {}'.format(
                missing, sorted(batch)))
        n = len(next(iter(batch.values())))
        mask = evaluate_predicate_mask(predicate, {f: batch[f] for f in fields}, n)
        if mask is None:  # vectorized path declined: per-row semantics
            mask = np.empty(n, dtype=bool)
            for i in range(n):
                mask[i] = predicate.do_include({f: batch[f][i] for f in fields})
        if not mask.any():
            return None
        return {k: v[mask] for k, v in batch.items()}


class BatchResultsQueueReader(BlockResultsReaderBase):
    """Consumer-side: one namedtuple-of-arrays per published batch
    (reference arrow_reader_worker.py:39-79, ``batched_output=True``).
    Delivered/checkpoint bookkeeping lives in the shared base."""

    def _convert(self, batch):
        return self._schema.make_namedtuple(**batch)
