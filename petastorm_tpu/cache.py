"""Row-group cache protocol.

Parity: /root/reference/petastorm/cache.py:21-39 (``CacheBase`` read-through
protocol, ``NullCache`` passthrough).
"""

from __future__ import annotations


class CacheBase(object):
    def get(self, key, fill_cache_func):
        """Return the cached value for ``key``; on miss call ``fill_cache_func()``,
        store its result, and return it."""
        raise NotImplementedError

    def cleanup(self):
        """Remove cache resources (optional)."""


class NullCache(CacheBase):
    """Never caches: always calls through."""

    def get(self, key, fill_cache_func):
        return fill_cache_func()
