"""Copy a dataset with optional column subsetting and not-null filtering.

Parity: /root/reference/petastorm/tools/copy_dataset.py (regex column subset,
not-null row filter, row-group size override :35-92; CLI :95-151) — without the
Spark session; the copy streams through a reader into a local writer.
"""

from __future__ import annotations

import argparse
import sys

from petastorm_tpu import make_reader
from petastorm_tpu.etl.dataset_metadata import materialize_dataset
from petastorm_tpu.predicates import in_lambda
from petastorm_tpu.unischema import Unischema


def copy_dataset(source_url, target_url, field_regex=None, not_null_fields=None,
                 rows_per_row_group=None, row_group_size_mb=None, rows_per_file=None,
                 workers_count=5):
    """Stream-copy ``source_url`` to ``target_url``.

    :param field_regex: list of regexes selecting the columns to copy
    :param not_null_fields: rows where any of these fields is null are skipped
    :param rows_per_row_group / row_group_size_mb / rows_per_file: output layout
    """
    predicate = None
    if not_null_fields:
        predicate = in_lambda(list(not_null_fields),
                              lambda v: all(v[f] is not None for f in not_null_fields))
    with make_reader(source_url, schema_fields=field_regex, predicate=predicate,
                     reader_pool_type='thread', workers_count=workers_count,
                     shuffle_row_groups=False) as reader:
        out_schema = Unischema('CopiedSchema', list(reader.transformed_schema.fields.values()))
        with materialize_dataset(target_url, out_schema,
                                 rows_per_row_group=rows_per_row_group,
                                 row_group_size_mb=row_group_size_mb,
                                 rows_per_file=rows_per_file) as writer:
            count = 0
            for row in reader:
                writer.write(row._asdict())
                count += 1
    return count


def main(argv=None):
    parser = argparse.ArgumentParser(description='Copy a petastorm_tpu dataset '
                                     '(reference petastorm-copy-dataset.py parity).')
    parser.add_argument('source_url')
    parser.add_argument('target_url')
    parser.add_argument('--field-regex', nargs='+', default=None)
    parser.add_argument('--not-null-fields', nargs='+', default=None)
    parser.add_argument('--rows-per-row-group', type=int, default=None)
    parser.add_argument('--row-group-size-mb', type=int, default=None)
    parser.add_argument('--rows-per-file', type=int, default=None)
    parser.add_argument('-w', '--workers-count', type=int, default=5)
    args = parser.parse_args(argv)
    count = copy_dataset(args.source_url, args.target_url, field_regex=args.field_regex,
                         not_null_fields=args.not_null_fields,
                         rows_per_row_group=args.rows_per_row_group,
                         row_group_size_mb=args.row_group_size_mb,
                         rows_per_file=args.rows_per_file, workers_count=args.workers_count)
    print('Copied {} rows'.format(count))
    return 0


if __name__ == '__main__':
    sys.exit(main())
