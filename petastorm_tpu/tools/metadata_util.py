"""Inspect dataset metadata: schema and row-group indexes.

Parity: /root/reference/petastorm/etl/metadata_util.py (:24-70).
"""

from __future__ import annotations

import argparse
import sys

from petastorm_tpu.etl import dataset_metadata
from petastorm_tpu.etl.rowgroup_indexing import get_row_group_indexes


def main(argv=None):
    parser = argparse.ArgumentParser(description='Inspect petastorm_tpu dataset metadata.')
    parser.add_argument('dataset_url')
    parser.add_argument('--schema', action='store_true', help='print the unischema')
    parser.add_argument('--index', action='store_true', help='print row-group index summaries')
    parser.add_argument('--skip-index-values', action='store_true',
                        help='with --index: omit the indexed values listing')
    parser.add_argument('--pieces', action='store_true', help='print row-group pieces')
    args = parser.parse_args(argv)

    if args.schema:
        schema = dataset_metadata.get_schema(args.dataset_url)
        print(repr(schema))
    if args.index:
        indexes = get_row_group_indexes(args.dataset_url)
        for name, indexer in sorted(indexes.items()):
            print('index {!r} on columns {}:'.format(name, indexer.column_names))
            values = indexer.indexed_values
            print('  {} indexed values'.format(len(values)))
            if not args.skip_index_values:
                for value in values:
                    print('   {!r} -> {}'.format(value, sorted(indexer.get_row_group_indexes(value))))
    if args.pieces:
        for i, piece in enumerate(dataset_metadata.load_row_groups(args.dataset_url)):
            print('{:4d}: {}'.format(i, piece))
    return 0


if __name__ == '__main__':
    sys.exit(main())
