"""Retrofit petastorm_tpu metadata onto an existing Parquet store.

Parity: /root/reference/petastorm/etl/petastorm_generate_metadata.py (:48-110)
— regenerates the unischema + row-group-count keys in ``_common_metadata`` for
a dataset whose metadata was lost, or for a store written by another tool.
"""

from __future__ import annotations

import argparse
import importlib
import json
import sys

from petastorm_tpu.etl import dataset_metadata
from petastorm_tpu.fs import FilesystemResolver
from petastorm_tpu.unischema import Unischema


def _load_schema_object(dotted):
    """'pkg.module.SCHEMA_ATTR' -> Unischema object."""
    module_name, _, attr = dotted.rpartition('.')
    if not module_name:
        raise ValueError('--unischema-class must be a dotted path like mypkg.schema.MySchema')
    module = importlib.import_module(module_name)
    schema = getattr(module, attr)
    if not isinstance(schema, Unischema):
        raise TypeError('{} is not a Unischema (got {})'.format(dotted, type(schema)))
    return schema


def generate_metadata(dataset_url, unischema_class=None, use_footer_counts=True):
    """Write/overwrite the dataset's ``_common_metadata``.

    :param unischema_class: dotted path to a Unischema object; when omitted the
        existing stored schema is reused, else inferred from the Arrow schema
        (codec information cannot be recovered by inference — pass the class for
        petastorm-written datasets whose metadata was lost).
    """
    if unischema_class is not None:
        schema = _load_schema_object(unischema_class)
    else:
        schema = dataset_metadata.infer_or_load_unischema(dataset_url)

    # row-group counts from the file footers (the ground truth) — never trust
    # a stale _common_metadata / _metadata left behind by a previous write
    pieces = dataset_metadata.load_row_groups(dataset_url, schema=schema,
                                              use_cached_metadata=False)
    resolver = FilesystemResolver(dataset_url)
    root = resolver.get_dataset_path()
    counts = {}
    import os
    for piece in pieces:
        rel = os.path.relpath(piece.path, root).replace(os.sep, '/')
        counts.setdefault(rel, []).append(piece.num_rows)
    dataset_metadata._write_dataset_metadata(dataset_url, schema, counts)
    return schema, sum(len(v) for v in counts.values())


def main(argv=None):
    parser = argparse.ArgumentParser(description='(Re)generate petastorm_tpu metadata '
                                     '(reference petastorm-generate-metadata.py parity).')
    parser.add_argument('dataset_url')
    parser.add_argument('--unischema-class', default=None,
                        help='dotted path to the Unischema object, e.g. examples.hello_world.schema.HelloWorldSchema')
    args = parser.parse_args(argv)
    schema, n_row_groups = generate_metadata(args.dataset_url, args.unischema_class)
    print('Wrote metadata: schema={} fields, {} row groups'.format(len(schema), n_row_groups))
    return 0


if __name__ == '__main__':
    sys.exit(main())
