"""Reader throughput benchmark.

Parity: /root/reference/petastorm/benchmark/throughput.py (warmup + measured
cycles, samples/sec, RSS, CPU% via psutil :113-174) and benchmark/cli.py.

TPU-first addition: ``--read-method jax`` measures the full device-feed
pipeline and reports **input-stall fraction** — the share of wall time the
consumer spent waiting on the host pipeline vs. consuming — which is the
BASELINE.md north-star metric (>=95% duty cycle == <=5% stall).
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from dataclasses import dataclass, field


@dataclass
class BenchmarkResult:
    samples_per_second: float
    duration_s: float
    samples: int
    memory_rss_mb: float = 0.0
    cpu_percent: float = 0.0
    input_stall_fraction: float = None
    extra: dict = field(default_factory=dict)

    def __str__(self):
        s = '{:.2f} samples/sec; {:.2f} MB RSS; {:.2f}% CPU'.format(
            self.samples_per_second, self.memory_rss_mb, self.cpu_percent)
        if self.input_stall_fraction is not None:
            s += '; {:.2f}% input stall'.format(100 * self.input_stall_fraction)
        return s


_psutil_proc = None


def _process_stats():
    """RSS MB + CPU%% since the previous call ON THE SAME Process instance —
    psutil's cpu_percent returns 0.0 for the first call of a fresh instance,
    so the instance must be shared with the priming call."""
    global _psutil_proc
    import psutil
    if _psutil_proc is None:
        _psutil_proc = psutil.Process()
    return (_psutil_proc.memory_info().rss / (1 << 20),
            _psutil_proc.cpu_percent(interval=None))


def reader_throughput(dataset_url, field_regex=None, warmup_cycles=200, measure_cycles=1000,
                      pool_type='thread', workers_count=3, shuffle_row_groups=True,
                      read_method='python', batch_size=64, make_reader_fn=None,
                      telemetry=None):
    """Measure read throughput in samples/sec.

    :param read_method: 'python' — iterate raw reader rows (reference parity);
        'columnar' — JaxDataLoader batches on the host block path, no device
        staging (the per-core host rate the ``cores_needed`` budget formula
        uses); 'jax' — JaxDataLoader + device staging with stall accounting.
    :param telemetry: pipeline telemetry level forwarded to ``make_reader``
        ('off'/'counters'/'spans'/None). With the loader-based read methods the
        result's ``extra['stall_report']`` carries the per-stage attribution of
        the measured reader wait (``petastorm_tpu.observability.stall_report``).
    """
    from petastorm_tpu import make_reader

    extra = {}
    if read_method in ('jax', 'columnar') and make_reader_fn is None:
        # device-feed benchmarks ride the columnar hot path: blocks, not rows
        extra['output'] = 'columnar'
    if telemetry is not None:
        extra['telemetry'] = telemetry
    make_reader_fn = make_reader_fn or make_reader
    reader = make_reader_fn(dataset_url,
                            schema_fields=field_regex,
                            reader_pool_type=pool_type,
                            workers_count=workers_count,
                            shuffle_row_groups=shuffle_row_groups,
                            num_epochs=None, **extra)
    result_extra = {}
    try:
        _process_stats()  # prime the CPU%% counter (shared Process instance)
        if read_method == 'python':
            it = iter(reader)
            for _ in range(warmup_cycles):
                next(it)
            t0 = time.perf_counter()
            for _ in range(measure_cycles):
                next(it)
            duration = time.perf_counter() - t0
            samples = measure_cycles
            stall = None
        elif read_method == 'columnar':
            from petastorm_tpu.jax import JaxDataLoader
            loader = JaxDataLoader(reader, batch_size=batch_size)
            warmup_batches = max(1, warmup_cycles // batch_size)
            measure_batches = max(1, measure_cycles // batch_size)
            it = iter(loader)
            for _ in range(warmup_batches):
                next(it)
            t0 = time.perf_counter()
            for _ in range(measure_batches):
                next(it)
            duration = time.perf_counter() - t0
            samples = measure_batches * batch_size
            stall = None
            result_extra['stall_report'] = _loader_stall_report(loader)
        elif read_method == 'jax':
            import jax
            from petastorm_tpu.jax import JaxDataLoader, prefetch_to_device
            jax_loader = JaxDataLoader(reader, batch_size=batch_size)
            loader = prefetch_to_device(jax_loader, jax.devices()[0], size=2)
            warmup_batches = max(1, warmup_cycles // batch_size)
            measure_batches = max(1, measure_cycles // batch_size)
            it = iter(loader)
            for _ in range(warmup_batches):
                jax.block_until_ready(next(it))
            wait_time = 0.0
            t0 = time.perf_counter()
            for _ in range(measure_batches):
                w0 = time.perf_counter()
                batch = next(it)
                jax.block_until_ready(batch)
                wait_time += time.perf_counter() - w0
            duration = time.perf_counter() - t0
            samples = measure_batches * batch_size
            stall = wait_time / duration if duration > 0 else 0.0
            result_extra['stall_report'] = _loader_stall_report(jax_loader)
        else:
            raise ValueError('Unknown read_method {!r}'.format(read_method))
        rss_mb, cpu = _process_stats()
        return BenchmarkResult(samples_per_second=samples / duration, duration_s=duration,
                               samples=samples, memory_rss_mb=rss_mb, cpu_percent=cpu,
                               input_stall_fraction=stall, extra=result_extra)
    finally:
        reader.stop()
        reader.join()


def _loader_stall_report(loader):
    """Per-stage attribution of the loader's measured reader wait (None when
    telemetry is off — there are no stage timers to attribute against)."""
    from petastorm_tpu import observability as obs
    if not obs.counters_on():
        return None
    return obs.stall_report(loader.diagnostics)


def pipeline_duty_cycle(dataset_url, step_fn, batch_to_args, batch_size=64, steps=50,
                        warmup_steps=5, loader_kwargs=None, reader_kwargs=None):
    """Measure input-stall % while running an actual jitted training step: the
    BASELINE configuration. ``step_fn(*batch_to_args(batch))`` is executed per
    batch; stall = time blocked waiting for data / total wall time."""
    import jax

    from petastorm_tpu import make_reader
    from petastorm_tpu.jax import JaxDataLoader, prefetch_to_device

    kwargs = {'num_epochs': None, **(reader_kwargs or {})}
    if 'output' not in kwargs and kwargs.get('ngram') is None:
        kwargs['output'] = 'columnar'  # the device-feed hot path, unless rows are required
    reader = make_reader(dataset_url, **kwargs)
    try:
        loader = prefetch_to_device(
            JaxDataLoader(reader, batch_size=batch_size, **(loader_kwargs or {})),
            jax.devices()[0], size=2)
        it = iter(loader)
        out = None
        for _ in range(warmup_steps):
            out = step_fn(*batch_to_args(next(it)))
        jax.block_until_ready(out)
        wait = 0.0
        t0 = time.perf_counter()
        for _ in range(steps):
            w0 = time.perf_counter()
            batch = next(it)
            wait += time.perf_counter() - w0
            out = step_fn(*batch_to_args(batch))
        jax.block_until_ready(out)
        duration = time.perf_counter() - t0
        return BenchmarkResult(
            samples_per_second=steps * batch_size / duration, duration_s=duration,
            samples=steps * batch_size, input_stall_fraction=wait / duration,
            extra={'steps': steps})
    finally:
        reader.stop()
        reader.join()


def main(argv=None):
    parser = argparse.ArgumentParser(
        description='Measure reader throughput (reference petastorm-throughput.py parity).')
    parser.add_argument('dataset_url')
    parser.add_argument('-f', '--field-regex', nargs='+', default=None,
                        help='only read fields matching these regexes')
    parser.add_argument('-m', '--warmup-cycles', type=int, default=200)
    parser.add_argument('-n', '--measure-cycles', type=int, default=1000)
    parser.add_argument('-p', '--pool-type', choices=('thread', 'process', 'dummy'),
                        default='thread')
    parser.add_argument('-w', '--workers-count', type=int, default=3)
    parser.add_argument('-d', '--read-method', choices=('python', 'columnar', 'jax'),
                        default='python')
    parser.add_argument('--batch-size', type=int, default=64)
    parser.add_argument('--no-shuffle', action='store_true')
    parser.add_argument('--telemetry', choices=('off', 'counters', 'spans'), default=None,
                        help='pipeline telemetry level (default: counters; '
                             '--trace-out implies spans)')
    parser.add_argument('--trace-out', default=None,
                        help='write a Perfetto-loadable Chrome trace of the run here '
                             '(implies --telemetry spans)')
    parser.add_argument('--fresh-process', action='store_true',
                        help='re-run the measurement in a newly spawned interpreter so the '
                             'reported RSS reflects only this benchmark (reference '
                             'benchmark/throughput.py:146-151 always does this)')
    args = parser.parse_args(argv)

    if args.fresh_process and not os.environ.get('_PSTPU_THROUGHPUT_CHILD'):
        import subprocess
        child_argv = [a for a in (argv if argv is not None else sys.argv[1:])
                      if a != '--fresh-process']
        env = dict(os.environ, _PSTPU_THROUGHPUT_CHILD='1')
        return subprocess.run(
            [sys.executable, '-m', 'petastorm_tpu.tools.throughput'] + child_argv,
            env=env).returncode

    telemetry = args.telemetry
    if args.trace_out and telemetry in (None, 'off', 'counters'):
        telemetry = 'spans'
    result = reader_throughput(
        args.dataset_url, field_regex=args.field_regex, warmup_cycles=args.warmup_cycles,
        measure_cycles=args.measure_cycles, pool_type=args.pool_type,
        workers_count=args.workers_count, shuffle_row_groups=not args.no_shuffle,
        read_method=args.read_method, batch_size=args.batch_size, telemetry=telemetry)
    print(result)
    report = result.extra.get('stall_report')
    if report is not None:
        # the input-stall fraction says HOW MUCH the consumer waited; this
        # says WHY — which stage the wait decomposes into
        from petastorm_tpu.observability import format_stall_report
        print(format_stall_report(report))
    if args.trace_out:
        from petastorm_tpu.observability import export_chrome_trace
        n = export_chrome_trace(args.trace_out)
        print('wrote {} trace events to {} (open in https://ui.perfetto.dev)'.format(
            n, args.trace_out))
    return 0


if __name__ == '__main__':
    sys.exit(main())
