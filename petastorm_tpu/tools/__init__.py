"""CLI tools: throughput benchmark, dataset copy, metadata generation/inspection.

Parity: /root/reference/petastorm/tools/ and petastorm/benchmark/ (console
scripts petastorm-throughput.py, petastorm-copy-dataset.py,
petastorm-generate-metadata.py, setup.py:89-95). Run as modules:
``python -m petastorm_tpu.tools.throughput <url>`` etc.
"""
