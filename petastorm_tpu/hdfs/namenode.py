"""HDFS namenode resolution and high-availability failover.

Behavioral parity with the reference's HA-HDFS stack
(/root/reference/petastorm/hdfs/namenode.py:34-313): Hadoop site config
discovery from the environment, nameservice -> namenode-list resolution, and a
client wrapper that transparently fails over to the standby namenode when an
operation raises an IO error (max 2 failovers, round-robin reconnect).

Design differences from the reference (TPU-first build):

* The underlying driver is ``pyarrow.fs.HadoopFileSystem`` (Arrow C++ libhdfs),
  not the removed pyarrow<1 ``hdfs.connect`` / libhdfs3 pair. The connector is
  injectable, so the HA machinery is testable with zero Hadoop (mirroring the
  reference's own MockHdfs strategy, hdfs/tests/test_hdfs_namenode.py:250-341).
* Failover wrapping is done dynamically per call via ``__getattr__`` proxying
  instead of enumerating every filesystem method by hand.
"""

from __future__ import annotations

import logging
import os
import xml.etree.ElementTree as ET
from urllib.parse import urlparse

logger = logging.getLogger(__name__)

#: maximum failover attempts before an operation is abandoned
#: (reference hdfs/namenode.py:146-151)
MAX_FAILOVER_ATTEMPTS = 2

#: environment variables probed, in order, for a Hadoop installation
#: (reference hdfs/namenode.py:44-48)
_HADOOP_ENV_VARS = ('HADOOP_HOME', 'HADOOP_PREFIX', 'HADOOP_INSTALL')


class HdfsConnectError(IOError):
    """Raised when no namenode of a nameservice accepts a connection."""


class MaxFailoversExceeded(RuntimeError):
    """Raised when an operation kept failing after exhausting failover attempts
    (reference hdfs/namenode.py:166-177)."""

    def __init__(self, failed_exceptions, max_failover_attempts, func_name):
        self.failed_exceptions = failed_exceptions
        self.max_failover_attempts = max_failover_attempts
        self.__name__ = func_name
        message = 'Failover attempts exceeded maximum ({}) for action "{}". ' \
                  'Exceptions: {}'.format(max_failover_attempts, func_name, failed_exceptions)
        super().__init__(message)


class HadoopConfiguration(dict):
    """Flat dict of Hadoop config properties parsed from ``hdfs-site.xml`` and
    ``core-site.xml`` (reference hdfs/namenode.py:66-74)."""

    @classmethod
    def from_environment(cls):
        """Locate a Hadoop installation via HADOOP_HOME/HADOOP_PREFIX/
        HADOOP_INSTALL (or an explicit HADOOP_CONF_DIR) and parse its site
        files. Returns an empty configuration when none is found."""
        conf = cls()
        conf_dir = os.environ.get('HADOOP_CONF_DIR')
        if conf_dir is None:
            for env in _HADOOP_ENV_VARS:
                if env in os.environ:
                    conf_dir = os.path.join(os.environ[env], 'etc', 'hadoop')
                    break
        if conf_dir is None:
            logger.warning(
                'No Hadoop installation found (checked HADOOP_CONF_DIR, %s). '
                'Namenode resolution will be empty.', ', '.join(_HADOOP_ENV_VARS))
            return conf
        for name in ('hdfs-site.xml', 'core-site.xml'):
            conf.load_site_xml(os.path.join(conf_dir, name))
        return conf

    def load_site_xml(self, xml_path):
        """Merge ``<property><name>/<value>`` pairs from a Hadoop site file."""
        try:
            root = ET.parse(xml_path).getroot()
        except (ET.ParseError, OSError) as e:
            logger.error('Could not parse Hadoop site file %s: %s', xml_path, e)
            return
        for prop in root.iter('property'):
            name, value = prop.find('name'), prop.find('value')
            if name is not None and value is not None:
                self[name.text] = value.text


class HdfsNamenodeResolver(object):
    """Resolves HDFS nameservices to concrete namenode ``host:port`` lists
    (reference hdfs/namenode.py:30-128)."""

    def __init__(self, hadoop_configuration=None):
        if hadoop_configuration is None:
            hadoop_configuration = HadoopConfiguration.from_environment()
        self._conf = hadoop_configuration

    def resolve_hdfs_name_service(self, nameservice):
        """Namenode ``host:port`` list for a nameservice, or ``None`` when the
        name is not a configured nameservice (it may simply be a hostname)."""
        namenode_ids = self._conf.get('dfs.ha.namenodes.' + nameservice)
        if not namenode_ids:
            return None
        namenodes = []
        for nn in namenode_ids.split(','):
            key = 'dfs.namenode.rpc-address.{}.{}'.format(nameservice, nn.strip())
            address = self._conf.get(key)
            if not address:
                raise RuntimeError(
                    'Inconsistent Hadoop configuration: "{}" lists namenode "{}" but '
                    'property "{}" is missing'.format(nameservice, nn, key))
            namenodes.append(address)
        return namenodes

    def resolve_default_hdfs_service(self):
        """``(nameservice, namenode list)`` for ``fs.defaultFS``
        (reference hdfs/namenode.py:111-128)."""
        default_fs = self._conf.get('fs.defaultFS')
        if not default_fs:
            raise RuntimeError('Hadoop configuration has no "fs.defaultFS" property; '
                               'cannot resolve a default HDFS service')
        nameservice = urlparse(default_fs).netloc
        namenodes = self.resolve_hdfs_name_service(nameservice)
        if namenodes is None:
            raise IOError('Unable to resolve namenodes of default service "{}"'.format(default_fs))
        return nameservice, namenodes


def _is_io_error(exc):
    """IO-shaped errors trigger failover; programming errors do not. Arrow C++
    raises OSError subclasses (pyarrow.lib.ArrowIOError is an alias of OSError
    in modern Arrow)."""
    return isinstance(exc, OSError)


def namenode_failover(func):
    """Decorator for :class:`HAHdfsClient` proxy methods: on IO error,
    reconnect to the next namenode (round-robin) and retry, up to
    :data:`MAX_FAILOVER_ATTEMPTS` reconnects (reference hdfs/namenode.py:146-208)."""

    def wrapper(client, *args, **kwargs):
        failures = []
        while True:
            try:
                return func(client, *args, **kwargs)
            except Exception as e:  # noqa: BLE001 - filtered just below
                if not _is_io_error(e):
                    raise
                failures.append(e)
                if len(failures) > MAX_FAILOVER_ATTEMPTS:
                    # wrapper.__name__ is patched to the proxied method's name
                    raise MaxFailoversExceeded(failures, MAX_FAILOVER_ATTEMPTS,
                                               wrapper.__name__)
                # HdfsConnectError (every namenode refused the reconnect) is
                # terminal — _do_failover already tried the whole ring
                client._do_failover(e)

    wrapper.__name__ = getattr(func, '__name__', 'wrapped')
    return wrapper


class HAHdfsClient(object):
    """Filesystem facade with namenode failover.

    Proxies every attribute of the underlying filesystem; callables are wrapped
    so an IO error reconnects round-robin to the next namenode and retries
    (the reference wraps each HadoopFileSystem method explicitly,
    hdfs/namenode.py:211-238).
    """

    def __init__(self, connector_cls, list_of_namenodes, user=None):
        if not list_of_namenodes:
            raise HdfsConnectError('HAHdfsClient requires at least one namenode')
        self._connector_cls = connector_cls
        self._list_of_namenodes = list(list_of_namenodes)
        self._user = user
        self._index_of_nn = -1
        self._filesystem = None
        self._do_failover()  # initial connect = failover from "nowhere"

    def _do_failover(self, cause=None):
        """Advance round-robin to the next namenode that accepts a connection.
        Trying every namenode (not just the next) means the initial connect —
        and any reconnect — survives a hard-down first-listed namenode."""
        connect_errors = []
        for _ in range(len(self._list_of_namenodes)):
            self._index_of_nn = (self._index_of_nn + 1) % len(self._list_of_namenodes)
            namenode = self._list_of_namenodes[self._index_of_nn]
            if cause is not None:
                logger.warning('HDFS operation failed (%s); failing over to namenode %s',
                               cause, namenode)
            try:
                self._filesystem = self._connector_cls.hdfs_connect_namenode(
                    namenode, user=self._user)
                return
            except OSError as e:
                connect_errors.append((namenode, e))
        raise HdfsConnectError('Unable to connect to any namenode of {}: {}'.format(
            self._list_of_namenodes, connect_errors))

    # pickling support for spawned worker processes: reconnect on unpickle
    def __getstate__(self):
        return {'connector_cls': self._connector_cls,
                'list_of_namenodes': self._list_of_namenodes,
                'user': self._user}

    def __setstate__(self, state):
        self.__init__(state['connector_cls'], state['list_of_namenodes'], state['user'])

    def __getattr__(self, name):
        # only called for attributes NOT found on HAHdfsClient itself
        attr = getattr(self._filesystem, name)
        if not callable(attr):
            return attr

        @namenode_failover
        def proxied(client, *args, **kwargs):
            # re-fetch from the *current* filesystem: failover replaces it
            return getattr(client._filesystem, name)(*args, **kwargs)

        proxied.__name__ = name
        return lambda *args, **kwargs: proxied(self, *args, **kwargs)


class HdfsConnector(object):
    """Namenode connection factory (reference hdfs/namenode.py:241-313).
    Subclass and override :meth:`hdfs_connect_namenode` to inject mocks."""

    # connection timeout handling is delegated to libhdfs config; the reference's
    # MAX_NAMENODES constant reflected the 2-namenode HA convention
    MAX_NAMENODES = 2

    @classmethod
    def hdfs_connect_namenode(cls, url_or_address, user=None):
        """Connect to one namenode. Accepts ``host:port``, ``hdfs://host:port``
        or ``user@host:port`` (URI userinfo wins only when ``user`` is None)."""
        import pyarrow.fs as pafs
        if '://' not in url_or_address:
            url_or_address = 'hdfs://' + url_or_address
        parsed = urlparse(url_or_address)
        host = parsed.hostname or 'default'
        port = parsed.port or 8020
        return pafs.HadoopFileSystem(host, port, user=user or parsed.username)

    @classmethod
    def connect_to_either_namenode(cls, list_of_namenodes, user=None):
        """Try each namenode once and return the first filesystem that answers;
        raise :class:`HdfsConnectError` when all fail
        (reference hdfs/namenode.py:272-313)."""
        errors = []
        for namenode in list_of_namenodes[:cls.MAX_NAMENODES]:
            try:
                return cls.hdfs_connect_namenode(namenode, user=user)
            except OSError as e:
                errors.append((namenode, e))
        raise HdfsConnectError(
            'Unable to connect to any namenode of {}: {}'.format(list_of_namenodes, errors))

    @classmethod
    def connect_ha_client(cls, list_of_namenodes, user=None):
        """An :class:`HAHdfsClient` bound to this connector."""
        return HAHdfsClient(cls, list_of_namenodes, user=user)


def as_pyarrow_filesystem(ha_client):
    """Wrap an :class:`HAHdfsClient` into a genuine ``pyarrow.fs.FileSystem``
    (via ``PyFileSystem``/``FileSystemHandler``) so pyarrow APIs that validate
    their ``filesystem=`` argument (``pq.write_to_dataset`` etc.) accept it.
    Every handler call rides the HA proxy, so failover still applies."""
    import pyarrow.fs as pafs

    from petastorm_tpu.pafs_util import DelegatingHandler

    class _HaHandler(DelegatingHandler):
        # self.fs is the HAHdfsClient: same method surface as a pyarrow
        # filesystem (its __getattr__ proxies the live HadoopFileSystem with
        # failover), so the shared delegation base applies verbatim

        def get_type_name(self):
            return 'ha-hdfs'

        def __eq__(self, other):
            return isinstance(other, _HaHandler) and \
                self.fs._list_of_namenodes == other.fs._list_of_namenodes

        def __ne__(self, other):
            return not self.__eq__(other)

        def __hash__(self):
            # __eq__ without __hash__ would make the handler (and the
            # PyFileSystem over it) unhashable (PT600)
            return hash((type(self), tuple(self.fs._list_of_namenodes or ())))

    return pafs.PyFileSystem(_HaHandler(ha_client))


def resolve_and_connect(dataset_url, hadoop_configuration=None, connector=HdfsConnector,
                        user=None, pyarrow_wrap=False):
    """Resolve an ``hdfs://`` URL to an HA filesystem + path.

    ``hdfs://nameservice/path`` with a configured HA nameservice yields an
    :class:`HAHdfsClient` over its namenodes; ``hdfs:///path`` (no netloc) uses
    ``fs.defaultFS``; a plain ``hdfs://[user@]host:port/path`` connects
    directly. ``pyarrow_wrap=True`` returns HA clients wrapped as genuine
    pyarrow filesystems (:func:`as_pyarrow_filesystem`).
    """
    parsed = urlparse(dataset_url)
    if parsed.scheme != 'hdfs':
        raise ValueError('Not an hdfs:// URL: {}'.format(dataset_url))
    resolver = HdfsNamenodeResolver(hadoop_configuration)
    # case-preserving host extraction: parsed.hostname lowercases, but Hadoop
    # nameservice config keys are case-sensitive; bracketed IPv6 literals keep
    # their colons
    host_port = parsed.netloc.rpartition('@')[2]
    if host_port.startswith('['):
        nameservice = host_port[1:host_port.index(']')] if ']' in host_port else host_port
    else:
        nameservice = host_port.partition(':')[0]
    if not parsed.netloc:
        _, namenodes = resolver.resolve_default_hdfs_service()
    else:
        namenodes = resolver.resolve_hdfs_name_service(nameservice)
    user = user or parsed.username
    if namenodes:
        client = HAHdfsClient(connector, namenodes, user=user)
        return (as_pyarrow_filesystem(client) if pyarrow_wrap else client), parsed.path
    # not a nameservice: direct host[:port] connection, no HA wrapping
    return connector.hdfs_connect_namenode(parsed.netloc, user=user), parsed.path
