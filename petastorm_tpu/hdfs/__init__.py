"""High-availability HDFS support (reference: petastorm/hdfs/)."""

from petastorm_tpu.hdfs.namenode import (HadoopConfiguration,  # noqa: F401
                                         HAHdfsClient, HdfsConnector,
                                         HdfsNamenodeResolver, MaxFailoversExceeded,
                                         as_pyarrow_filesystem, namenode_failover)
