"""Ring attention: context-parallel attention over a mesh axis.

The reference's only long-sequence feature is NGram windowed readout on the
data side (reference ngram.py; SURVEY.md §5 "long-context"). On TPU the
framework also has to FEED long-context training, where the sequence axis is
sharded across devices ("context parallelism"). This module supplies the
model-side op that consumes such sequence-sharded batches: blockwise (online
softmax) attention where key/value shards rotate around the mesh axis ring via
``jax.lax.ppermute``, so each device only ever holds ``T / ring_size`` keys —
memory per device is O(T/n) while computing exact full attention.

Pure JAX + XLA collectives (psum/ppermute ride ICI), composed with
``jax.shard_map`` — no hand-rolled communication runtime, per the platform's
compilation model. The blockwise accumulation is the standard public
flash/ring-attention recipe (log-sum-exp running max).

Use :func:`ring_attention` under ``shard_map`` yourself, or
:func:`make_ring_attention` for a ready-made sharded callable on a mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from petastorm_tpu.jax.compat import shard_map

_NEG_INF = -1e30


def _block_update(q, k_blk, v_blk, mask, m, l, acc, scale):
    """One online-softmax accumulation step.

    q: [B,H,Tq,D]; k_blk/v_blk: [B,H,Tk,D]; mask: [Tq,Tk] bool (True = keep);
    m/l: [B,H,Tq] running max / normalizer; acc: [B,H,Tq,D] running numerator.
    """
    s = jnp.einsum('bhqd,bhkd->bhqk', q, k_blk,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    correction = jnp.exp(m - m_new)
    l_new = l * correction + p.sum(axis=-1)
    acc_new = acc * correction[..., None] + jnp.einsum(
        'bhqk,bhkd->bhqd', p, v_blk.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis_name, causal=False):
    """Exact attention over a sequence sharded on ``axis_name``.

    Call under ``shard_map`` with q/k/v sharded on their sequence axis:
    q: [B, H, Tq_local, D], k/v: [B, H, Tk_local, D] (local shards).
    Returns the local output shard [B, H, Tq_local, D] in q's dtype.

    ``causal`` masks with GLOBAL positions: query global index >= key global
    index. Shards must be laid out contiguously (shard i holds positions
    [i*T_local, (i+1)*T_local)), which is how the loader stages time-major
    sequence batches.
    """
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, tq, d = q.shape
    tk = k.shape[2]
    scale = 1.0 / (d ** 0.5)

    q32 = q.astype(jnp.float32)
    # derive the accumulators from q (zeroed) rather than fresh constants:
    # under shard_map the scan carry's device-varying axes must match the
    # body's outputs, and q already varies over every mesh axis in play
    m = q32[..., 0] * 0 + _NEG_INF
    l = q32[..., 0] * 0
    acc = q32 * 0

    q_pos = my_idx * tq + jnp.arange(tq)

    def step(carry, t):
        k_blk, v_blk, m, l, acc = carry
        # device i holds k-shard (i - t) mod n at ring step t
        blk_idx = jnp.mod(my_idx - t, n)
        if causal:
            k_pos = blk_idx * tk + jnp.arange(tk)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((tq, tk), bool)
        m, l, acc = _block_update(q32, k_blk.astype(jnp.float32),
                                  v_blk, mask, m, l, acc, scale)
        # rotate k/v shards one step around the ring
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, m, l, acc), None

    (_, _, m, l, acc), _ = jax.lax.scan(step, (k, v, m, l, acc), jnp.arange(n))
    # fully-masked rows (never possible for causal with contiguous layout, but
    # cheap insurance): avoid 0/0
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def make_sharded_ring_attention(mesh, seq_axis='seq', batch_axis=None, causal=False):
    """The un-jitted shard_map'd ``(q, k, v) -> out`` on [B, H, T, D] with the
    sequence axis sharded over ``mesh[seq_axis]`` — composable inside a larger
    jitted computation (e.g. a transformer's attention_fn). The ONE place the
    partition spec + shard_map wiring lives."""
    spec = P(batch_axis, None, seq_axis, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    def _sharded(q, k, v):
        return ring_attention(q, k, v, seq_axis, causal=causal)

    return _sharded


def make_ring_attention(mesh, seq_axis='seq', batch_axis=None, causal=False):
    """A jitted ``(q, k, v) -> out`` computing exact attention with the
    sequence axis sharded over ``mesh[seq_axis]`` (and optionally batch over
    ``batch_axis``). Inputs/outputs are global arrays of shape [B, H, T, D]."""
    from jax.sharding import NamedSharding

    spec = P(batch_axis, None, seq_axis, None)
    fn = jax.jit(make_sharded_ring_attention(mesh, seq_axis, batch_axis, causal))

    def apply(q, k, v):
        sharding = NamedSharding(mesh, spec)
        q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
        return fn(q, k, v)

    return apply
