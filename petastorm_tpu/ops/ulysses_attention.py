"""Ulysses-style all-to-all sequence parallelism: the second context-parallel
attention strategy (sibling of :mod:`petastorm_tpu.ops.ring_attention`).

Where ring attention keeps every device on its own sequence shard and rotates
key/value shards around the mesh ring (n-1 ``ppermute`` steps, O(T/n) memory,
communication overlapped with compute), Ulysses redistributes ONCE: an
``all_to_all`` converts the sequence-sharded layout [B, H, T/n, D] into a
head-sharded layout [B, H/n, T, D], each device runs exact attention for its
own heads over the FULL sequence with zero further communication, and a second
``all_to_all`` restores the sequence sharding. Public recipe: DeepSpeed-Ulysses
(arXiv:2309.14509).

Trade-offs (why both exist):
  * Ulysses needs ``num_heads % ring_size == 0`` and holds full-length K/V for
    its head subset — O(T) memory per device, so it suits moderate T with many
    heads; ring attention holds O(T/n) and scales to extreme T.
  * Ulysses communicates in 2 all-to-all phases (4 ``all_to_all`` ops: q, k, v
    forward + the output back — XLA is free to fuse/overlap the forward
    three); ring does n-1 ppermute rotations but overlaps them with block
    compute.

The local per-head attention reuses the same online-softmax block update as
ring attention (one implementation of the math), scanning k/v chunks so the
[T, T] score matrix never materializes.

Pure JAX: ``lax.all_to_all`` + ``shard_map``, collectives ride ICI. No
reference counterpart — the reference has no model-side sequence code at all
(SURVEY.md §2.9/§5); this exists because BASELINE-scale long-context training
needs the data pipeline's time-major sequence batches consumed by a
context-parallel op.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from petastorm_tpu.jax.compat import shard_map
from petastorm_tpu.ops.ring_attention import _NEG_INF, _block_update


def _chunked_full_attention(q, k, v, causal, kv_chunk):
    """Exact attention of q [B,H,T,D] over full-length k/v [B,H,T,D], scanning
    k/v in chunks of ``kv_chunk`` with the shared online-softmax update."""
    b, h, t, d = q.shape
    scale = 1.0 / (d ** 0.5)
    num_chunks = t // kv_chunk
    q32 = q.astype(jnp.float32)
    m = q32[..., 0] * 0 + _NEG_INF
    l = q32[..., 0] * 0
    acc = q32 * 0
    q_pos = jnp.arange(t)

    k_chunks = k.reshape(b, h, num_chunks, kv_chunk, d).transpose(2, 0, 1, 3, 4)
    v_chunks = v.reshape(b, h, num_chunks, kv_chunk, d).transpose(2, 0, 1, 3, 4)

    def step(carry, inputs):
        m, l, acc = carry
        c, k_blk, v_blk = inputs
        if causal:
            k_pos = c * kv_chunk + jnp.arange(kv_chunk)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((t, kv_chunk), bool)
        m, l, acc = _block_update(q32, k_blk.astype(jnp.float32), v_blk, mask,
                                  m, l, acc, scale)
        return (m, l, acc), None

    (m, l, acc), _ = jax.lax.scan(
        step, (m, l, acc), (jnp.arange(num_chunks), k_chunks, v_chunks))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, causal=False, kv_chunk=None):
    """Exact attention over a sequence sharded on ``axis_name`` via head
    redistribution.

    Call under ``shard_map`` with q/k/v local sequence shards [B, H, T_local, D]
    laid out contiguously (shard i holds positions [i*T_local, (i+1)*T_local) —
    how the loader stages time-major sequence batches). Requires
    ``H % axis_size == 0``. Returns the local output shard in q's dtype.

    ``kv_chunk`` bounds the score-block width of the local attention
    (default: T_local, the natural chunking).
    """
    n = jax.lax.psum(1, axis_name)  # axis size: static under shard_map
    h, t_local = q.shape[1], q.shape[2]
    if h % n:
        # guard at the op so EVERY entry point (including direct
        # make_sharded_ulysses_attention use) fails loudly, not with a cryptic
        # all_to_all split-axis error from inside shard_map
        raise ValueError('ulysses attention needs num_heads ({}) divisible by the '
                         '{!r} axis size ({}); use ring attention otherwise'.format(
                             h, axis_name, n))
    # all_to_all(tiled): split the head axis n ways, concatenate the received
    # pieces along the sequence axis -> [B, H/n, T, D] with the full sequence
    # in device order (contiguous layout preserved)
    seq_to_heads = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                                     split_axis=1, concat_axis=2, tiled=True)
    q_full, k_full, v_full = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)

    t = t_local * n
    chunk = t_local if kv_chunk is None else int(kv_chunk)
    if chunk < 1 or t % chunk:
        raise ValueError('kv_chunk ({}) must be a positive divisor of the full sequence '
                         'length ({})'.format(kv_chunk, t))
    out = _chunked_full_attention(q_full, k_full, v_full, causal, chunk)

    # inverse redistribution: split the sequence axis, concatenate heads back
    return jax.lax.all_to_all(out, axis_name=axis_name,
                              split_axis=2, concat_axis=1, tiled=True)


def make_sharded_ulysses_attention(mesh, seq_axis='seq', batch_axis=None,
                                   causal=False, kv_chunk=None):
    """The un-jitted shard_map'd ``(q, k, v) -> out`` on [B, H, T, D] with the
    sequence axis sharded over ``mesh[seq_axis]`` — composable inside a larger
    jitted computation (drop-in for ``make_sharded_ring_attention``)."""
    spec = P(batch_axis, None, seq_axis, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    def _sharded(q, k, v):
        return ulysses_attention(q, k, v, seq_axis, causal=causal, kv_chunk=kv_chunk)

    return _sharded


def make_ulysses_attention(mesh, seq_axis='seq', batch_axis=None, causal=False,
                           kv_chunk=None):
    """A jitted ``(q, k, v) -> out`` computing exact attention with the
    sequence axis sharded over ``mesh[seq_axis]`` via all-to-all head
    redistribution. Inputs/outputs are global [B, H, T, D] arrays; the head
    count must be divisible by the ``seq_axis`` size."""
    from jax.sharding import NamedSharding

    spec = P(batch_axis, None, seq_axis, None)
    fn = jax.jit(make_sharded_ulysses_attention(mesh, seq_axis, batch_axis,
                                                causal, kv_chunk))

    def apply(q, k, v):
        if q.shape[1] % mesh.shape[seq_axis]:
            raise ValueError(
                'ulysses attention needs num_heads ({}) divisible by the {} axis '
                'size ({}); use ring attention otherwise'.format(
                    q.shape[1], seq_axis, mesh.shape[seq_axis]))
        sharding = NamedSharding(mesh, spec)
        q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
        return fn(q, k, v)

    return apply
