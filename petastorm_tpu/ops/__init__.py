"""Device-side data-pipeline ops (JAX/Pallas).

The reference does all preprocessing on the CPU host with OpenCV/numpy
(codecs.py, TransformSpec) and ships float tensors to the accelerator. On TPU
the bandwidth-efficient split is different: ship compact uint8 batches over
PCIe, then cast/normalize/augment ON DEVICE, where the work is free relative
to HBM bandwidth and overlaps with the training step. These ops are that
device-side half of the input pipeline.
"""

from petastorm_tpu.ops.preprocess import normalize_images  # noqa: F401
from petastorm_tpu.ops.augment import (random_flip, random_crop,  # noqa: F401
                                       mixup, cutmix)
from petastorm_tpu.ops.ring_attention import make_ring_attention, ring_attention  # noqa: F401
from petastorm_tpu.ops.ulysses_attention import (make_ulysses_attention,  # noqa: F401
                                                 ulysses_attention)
