"""On-device batch augmentation (random flip/crop, mixup, cutmix).

The reference leaves augmentation to user TransformSpec functions running on
CPU workers (reference transform.py:19-40, examples/mnist/pytorch_example.py).
These equivalents run inside jit on the TPU: static output shapes, no Python
control flow, randomness from threaded `jax.random` keys — reproducible under
the reader's seed, zero host CPU. Flip/crop draw PER-IMAGE randomness; mixup
and cutmix follow their papers' standard batch formulation (ONE lam — and for
cutmix one rectangle — per step, shared across the batch).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def random_flip(images, key, prob=0.5):
    """Per-image horizontal flip (width axis) with probability ``prob``.

    :param images: ``(B, H, W, C)`` batch
    :param key: ``jax.random`` key
    """
    if images.ndim != 4:
        raise ValueError('images must be (B, H, W, C), got shape {}'.format(images.shape))
    flip = jax.random.bernoulli(key, prob, (images.shape[0],))
    flipped = images[:, :, ::-1, :]
    return jnp.where(flip[:, None, None, None], flipped, images)


def random_crop(images, key, crop_h, crop_w):
    """Per-image random crop to ``(crop_h, crop_w)``.

    Offsets are drawn uniformly per image; the gather is a vmapped
    ``dynamic_slice``, so shapes stay static under jit.
    """
    if images.ndim != 4:
        raise ValueError('images must be (B, H, W, C), got shape {}'.format(images.shape))
    b, h, w, c = images.shape
    if crop_h > h or crop_w > w:
        raise ValueError('crop ({}, {}) larger than image ({}, {})'.format(
            crop_h, crop_w, h, w))
    ky, kx = jax.random.split(key)
    ys = jax.random.randint(ky, (b,), 0, h - crop_h + 1)
    xs = jax.random.randint(kx, (b,), 0, w - crop_w + 1)

    def crop_one(img, y, x):
        return jax.lax.dynamic_slice(img, (y, x, 0), (crop_h, crop_w, c))

    return jax.vmap(crop_one)(images, ys, xs)


def mixup(images, labels, key, alpha=0.2, num_classes=None):
    """Batch mixup (Zhang et al. 2018) on device: each image blends with a
    permuted partner, ``lam ~ Beta(alpha, alpha)`` shared across the batch
    (the standard formulation — one draw per step keeps the op a fused
    elementwise blend on the TPU, no per-image gathers beyond the permutation).

    ``labels``: integer ``(B,)`` (requires ``num_classes``; returns soft
    ``(B, num_classes)``) or already-soft ``(B, num_classes)``.
    Returns ``(mixed_images, mixed_labels)``; images blend in float32 and are
    cast back to the input dtype (uint8 batches round).
    """
    if images.ndim != 4:
        raise ValueError('images must be (B, H, W, C), got shape {}'.format(images.shape))
    b = images.shape[0]
    kperm, klam = jax.random.split(key)
    perm = jax.random.permutation(kperm, b)
    lam = jax.random.beta(klam, alpha, alpha)
    lam = jnp.maximum(lam, 1.0 - lam)  # keep the ORIGINAL image dominant
    soft = _soft_labels(labels, num_classes)
    x = images.astype(jnp.float32)
    mixed = lam * x + (1.0 - lam) * x[perm]
    if jnp.issubdtype(images.dtype, jnp.integer):
        mixed = jnp.round(mixed)
    return mixed.astype(images.dtype), lam * soft + (1.0 - lam) * soft[perm]


def cutmix(images, labels, key, alpha=1.0, num_classes=None):
    """Batch CutMix (Yun et al. 2019) on device: ONE random rectangle per
    step (shared across the batch, per the paper's batch formulation) is
    replaced in each image by its permuted partner's pixels; labels blend by
    the realized pasted-area fraction. The rectangle is applied as a coordinate MASK
    (broadcasted iota comparisons), so shapes stay static under jit — no
    dynamic-size slices.
    """
    if images.ndim != 4:
        raise ValueError('images must be (B, H, W, C), got shape {}'.format(images.shape))
    b, h, w, _ = images.shape
    kperm, klam, ky, kx = jax.random.split(key, 4)
    perm = jax.random.permutation(kperm, b)
    lam = jax.random.beta(klam, alpha, alpha)
    cut_ratio = jnp.sqrt(1.0 - lam)
    cut_h = (cut_ratio * h).astype(jnp.int32)
    cut_w = (cut_ratio * w).astype(jnp.int32)
    cy = jax.random.randint(ky, (), 0, h)
    cx = jax.random.randint(kx, (), 0, w)
    y0 = jnp.clip(cy - cut_h // 2, 0, h)
    y1 = jnp.clip(cy + cut_h // 2, 0, h)
    x0 = jnp.clip(cx - cut_w // 2, 0, w)
    x1 = jnp.clip(cx + cut_w // 2, 0, w)
    rows = jnp.arange(h)[:, None]
    cols = jnp.arange(w)[None, :]
    in_box = ((rows >= y0) & (rows < y1) & (cols >= x0) & (cols < x1))
    mixed = jnp.where(in_box[None, :, :, None], images[perm], images)
    # label weight from the REALIZED box (clipping can shrink it)
    box_frac = ((y1 - y0) * (x1 - x0)) / float(h * w)
    lam_adj = 1.0 - box_frac.astype(jnp.float32)
    soft = _soft_labels(labels, num_classes)
    return mixed, lam_adj * soft + (1.0 - lam_adj) * soft[perm]


def _soft_labels(labels, num_classes):
    if labels.ndim == 1:
        if num_classes is None:
            raise ValueError('integer labels need num_classes for the soft-label blend')
        return jax.nn.one_hot(labels, num_classes)
    if labels.ndim == 2:
        return labels.astype(jnp.float32)
    raise ValueError('labels must be (B,) ints or (B, num_classes), got shape {}'.format(
        labels.shape))
