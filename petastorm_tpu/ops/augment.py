"""On-device batch augmentation (random flip, random crop).

The reference leaves augmentation to user TransformSpec functions running on
CPU workers (reference transform.py:19-40, examples/mnist/pytorch_example.py).
These equivalents run inside jit on the TPU: static output shapes, no Python
control flow, per-image randomness from a single threaded `jax.random` key —
so the augmentation is reproducible under the reader's seed and costs no host
CPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def random_flip(images, key, prob=0.5):
    """Per-image horizontal flip (width axis) with probability ``prob``.

    :param images: ``(B, H, W, C)`` batch
    :param key: ``jax.random`` key
    """
    if images.ndim != 4:
        raise ValueError('images must be (B, H, W, C), got shape {}'.format(images.shape))
    flip = jax.random.bernoulli(key, prob, (images.shape[0],))
    flipped = images[:, :, ::-1, :]
    return jnp.where(flip[:, None, None, None], flipped, images)


def random_crop(images, key, crop_h, crop_w):
    """Per-image random crop to ``(crop_h, crop_w)``.

    Offsets are drawn uniformly per image; the gather is a vmapped
    ``dynamic_slice``, so shapes stay static under jit.
    """
    if images.ndim != 4:
        raise ValueError('images must be (B, H, W, C), got shape {}'.format(images.shape))
    b, h, w, c = images.shape
    if crop_h > h or crop_w > w:
        raise ValueError('crop ({}, {}) larger than image ({}, {})'.format(
            crop_h, crop_w, h, w))
    ky, kx = jax.random.split(key)
    ys = jax.random.randint(ky, (b,), 0, h - crop_h + 1)
    xs = jax.random.randint(kx, (b,), 0, w - crop_w + 1)

    def crop_one(img, y, x):
        return jax.lax.dynamic_slice(img, (y, x, 0), (crop_h, crop_w, c))

    return jax.vmap(crop_one)(images, ys, xs)
