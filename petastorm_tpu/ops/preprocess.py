"""Fused on-device image normalization (uint8 -> float, mean/std).

Replaces the host-side half of the reference's image path: there,
``CompressedImageCodec.decode`` hands numpy uint8 to user TransformSpecs that
cast and normalize on CPU (reference codecs.py:92-111), quadrupling the bytes
shipped to the accelerator. Here the reader ships uint8 and this op performs
cast + mean-subtract + std-divide in one pass on the TPU.

The Pallas kernel views an NHWC batch as a 2-D (N*H, W*C) array — elementwise
math has no layout semantics, so the only thing that matters is hardware
tiling: lanes of 128 along W*C, sublane blocks along rows. The per-channel
mean/std become a (1, W*C) row (the channel pattern repeats with period C)
broadcast down the block. One read of uint8, one write of bf16/f32: the
fusion XLA would need three ops and an f32 intermediate for.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# rows per block: multiple of every dtype's sublane minimum (uint8 needs 32)
_BLOCK_ROWS = 256
_BLOCK_COLS = 512  # lanes: multiple of 128


def _kernel(img_ref, mean_ref, inv_std_ref, out_ref):
    x = img_ref[:]
    if jnp.issubdtype(x.dtype, jnp.integer):
        # Mosaic has no direct uint8->f32 cast; widen through int32 first.
        # Float inputs must NOT take this path — int32 would truncate them.
        x = x.astype(jnp.int32)
    x = x.astype(jnp.float32)
    out_ref[:] = ((x - mean_ref[:]) * inv_std_ref[:]).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=('out_dtype', 'interpret'))
def _normalize_pallas(flat, mean_row, inv_std_row, out_dtype, interpret=False):
    n, m = flat.shape
    grid = (pl.cdiv(n, _BLOCK_ROWS), pl.cdiv(m, _BLOCK_COLS))
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((n, m), out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, _BLOCK_COLS), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BLOCK_COLS), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BLOCK_COLS), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, _BLOCK_COLS), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(flat, mean_row, inv_std_row)


def _as_channel_row(values, channels, width, name):
    arr = np.asarray(values, dtype=np.float32)
    if arr.ndim == 0:
        arr = np.full(channels, float(arr), np.float32)
    if arr.shape != (channels,):
        raise ValueError('{} must be a scalar or shape ({},), got {}'.format(
            name, channels, arr.shape))
    return np.tile(arr, width)[None, :]  # (1, W*C): channel pattern repeated


def normalize_images(images, mean, std, out_dtype=jnp.bfloat16, use_pallas=None,
                     interpret=False):
    """``(images - mean) / std`` with cast, fused on device.

    :param images: ``(B, H, W, C)`` (or ``(H, W, C)``) uint8/integer/float array
    :param mean/std: scalar or per-channel ``(C,)`` values, in the same units
        as ``images`` (e.g. 0-255 for uint8 ImageNet stats)
    :param out_dtype: output dtype (default bfloat16, the TPU matmul input type)
    :param use_pallas: force the Pallas kernel on/off; default: on when the
        default backend is TPU, else a pure-jnp path (identical math)
    :param interpret: run the Pallas kernel in interpreter mode (tests)
    """
    squeeze = images.ndim == 3
    if squeeze:
        images = images[None]
    if images.ndim != 4:
        raise ValueError('images must be (B, H, W, C) or (H, W, C), got shape {}'.format(
            images.shape))
    b, h, w, c = images.shape
    mean_row = _as_channel_row(mean, c, w, 'mean')
    std_row = _as_channel_row(std, c, w, 'std')
    if np.any(std_row == 0):
        raise ValueError('std must be non-zero')
    inv_std_row = 1.0 / std_row

    if use_pallas is None:
        use_pallas = jax.default_backend() == 'tpu'

    if use_pallas or interpret:
        flat = images.reshape(b * h, w * c)
        out = _normalize_pallas(flat, jnp.asarray(mean_row), jnp.asarray(inv_std_row),
                                jnp.dtype(out_dtype), interpret=interpret)
        out = out.reshape(b, h, w, c)
    else:
        mean_a = jnp.asarray(mean_row.reshape(w, c), jnp.float32)
        inv_a = jnp.asarray(inv_std_row.reshape(w, c), jnp.float32)
        out = ((images.astype(jnp.float32) - mean_a) * inv_a).astype(out_dtype)
    return out[0] if squeeze else out
