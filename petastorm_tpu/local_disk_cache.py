"""Local disk cache with size-bounded LRU eviction.

Parity: /root/reference/petastorm/local_disk_cache.py:22-63 (which delegates to
the ``diskcache`` package). This is a self-contained implementation: one file
per key (pickle), atomic tmp+rename writes so concurrent worker processes never
observe partial entries, and least-recently-used eviction driven by file mtimes
(reads bump mtime).

On a TPU pod each host caches its own shard's row groups, so the cache is
per-host local NVMe/ssd — exactly the reference's deployment model.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import shutil
import tempfile
import threading

from petastorm_tpu.cache import CacheBase

logger = logging.getLogger(__name__)

_DEFAULT_SIZE_LIMIT = 10 * 2 ** 30  # 10 GiB


class LocalDiskCache(CacheBase):
    """
    :param path: cache directory (created if absent)
    :param size_limit_bytes: total cache size bound; eviction keeps usage under it
    :param expected_cell_size_bytes: rough per-entry size estimate, used to decide
        whether caching is worthwhile at all (reference guards tiny limits)
    :param cleanup: remove the cache directory on ``cleanup()``
    """

    def __init__(self, path, size_limit_bytes=_DEFAULT_SIZE_LIMIT, expected_cell_size_bytes=None,
                 cleanup=False):
        self._path = path
        self._size_limit = size_limit_bytes
        self._cleanup = cleanup
        self._lock = threading.Lock()
        if expected_cell_size_bytes and size_limit_bytes < 100 * expected_cell_size_bytes:
            logger.warning('Cache size limit %d holds fewer than 100 expected entries '
                           '(%d bytes each); the cache may thrash.',
                           size_limit_bytes, expected_cell_size_bytes)
        os.makedirs(self._path, exist_ok=True)

    def _entry_path(self, key):
        digest = hashlib.sha1(key.encode('utf-8')).hexdigest()
        return os.path.join(self._path, digest[:2], digest + '.pkl')

    def get(self, key, fill_cache_func):
        entry = self._entry_path(key)
        try:
            with open(entry, 'rb') as f:
                value = pickle.load(f)
            os.utime(entry, None)  # bump mtime: LRU recency
            return value
        except (FileNotFoundError, EOFError, pickle.UnpicklingError):
            pass
        value = fill_cache_func()
        self._store(entry, value)
        return value

    def _store(self, entry, value):
        os.makedirs(os.path.dirname(entry), exist_ok=True)
        blob = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        if len(blob) > self._size_limit:
            logger.warning('Entry of %d bytes exceeds the cache size limit; not caching', len(blob))
            return
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(entry), suffix='.tmp')
        try:
            with os.fdopen(fd, 'wb') as f:
                f.write(blob)
            os.replace(tmp, entry)  # atomic: readers never see partial entries
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._evict_if_needed()

    def _evict_if_needed(self):
        with self._lock:
            entries = []
            total = 0
            for dirpath, _, filenames in os.walk(self._path):
                for name in filenames:
                    if not name.endswith('.pkl'):
                        continue
                    full = os.path.join(dirpath, name)
                    try:
                        st = os.stat(full)
                    except OSError:
                        continue
                    entries.append((st.st_mtime, st.st_size, full))
                    total += st.st_size
            if total <= self._size_limit:
                return
            entries.sort()  # oldest mtime first
            for _, size, full in entries:
                try:
                    os.unlink(full)
                    total -= size
                except OSError:
                    pass
                if total <= self._size_limit:
                    break

    def cleanup(self):
        if self._cleanup:
            shutil.rmtree(self._path, ignore_errors=True)

    # picklable across process-pool spawn (the lock is per-process state)
    def __getstate__(self):
        state = self.__dict__.copy()
        del state['_lock']
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
