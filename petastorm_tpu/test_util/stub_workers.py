"""Stub workers for pool protocol tests
(reference /root/reference/petastorm/workers_pool/tests/stub_workers.py)."""

from __future__ import annotations

import time

from petastorm_tpu.workers import protocol
from petastorm_tpu.workers.worker_base import WorkerBase


class IdentityWorker(WorkerBase):
    """Publishes each ventilated value unchanged."""

    def process(self, value):
        self.publish(value)


class DoubleOutputWorker(WorkerBase):
    """Publishes two results per item."""

    def process(self, value):
        self.publish(value)
        self.publish(value + 1000)


class ZeroOutputWorker(WorkerBase):
    """Consumes items without publishing anything."""

    def process(self, value):
        pass


class SleepyIdentityWorker(WorkerBase):
    """Sleeps then publishes — for concurrency/backpressure tests."""

    def process(self, value, sleep_s=0.01):
        time.sleep(sleep_s)
        self.publish(value)


class ExceptionEveryNWorker(WorkerBase):
    """Raises on every item whose value % n == 0; args is n."""

    def process(self, value):
        n = self.args or 5
        if value % n == 0:
            raise ValueError('stub failure on {}'.format(value))
        self.publish(value)


class ArrowTableWorker(WorkerBase):
    """Publishes a pyarrow table of n rows — for serializer tests."""

    def process(self, n):
        import numpy as np
        import pyarrow as pa
        self.publish(pa.table({'x': np.arange(n)}))


class SetupArgsEchoWorker(WorkerBase):
    """Publishes its setup args — verifies setup args survive process spawn."""

    def process(self, value):
        self.publish((value, self.args))


class BlobWorker(WorkerBase):
    """Publishes ``args['count']`` deterministic blobs of ``args['size']``
    bytes per item — sized-payload stress for the results transport."""

    def process(self, item):
        size = self.args['size']
        for j in range(self.args.get('count', 1)):
            self.publish({'item': item, 'j': j,
                          'blob': bytes([(item + j) % 251]) * size})


class HardExitWorker(WorkerBase):
    """Simulates a worker CRASH (``os._exit``, no exception forwarding) on a
    chosen item; other items pass through."""

    def process(self, item):
        import os
        if item == self.args.get('crash_on', 0):
            os._exit(13)
        self.publish([item])


class CrashOnceWorker(WorkerBase):
    """SIGKILLs the worker process the FIRST time it sees ``args['crash_on']``
    (coordinated across respawns through ``args['flag_path']``); every other
    item — and the retried crash item — passes through. The minimal
    recover-and-deliver-exactly-once scenario."""

    def process(self, item):
        import os
        if item == self.args['crash_on']:
            try:
                fd = os.open(self.args['flag_path'], os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                pass  # already crashed once; succeed this time
            else:
                os.close(fd)
                os.kill(os.getpid(), 9)
        self.publish(item)


class EnvEchoWorker(WorkerBase):
    """Publishes the value of the env var named in ``args`` as seen INSIDE the
    worker (process pools: the spawned child's environment)."""

    def process(self, item):
        import os
        self.publish((item, os.environ.get(self.args)))


class ProtocolEchoWorker(WorkerBase):
    """Publishes the canonical message-kind table as resolved INSIDE the
    worker — proves a spawned worker and the supervisor share ONE protocol
    module (``workers/protocol.py``), the single-definition-site property
    PT801 enforces statically."""

    def process(self, item):
        self.publish((item, sorted(protocol.MESSAGE_KINDS.values()),
                      protocol.RING_HEADER_LEN))


class PublishThenErrorWorker(WorkerBase):
    """Publishes its item, THEN raises — on the first attempt per item in
    ``args['fail_on']`` (one-shot via an ``O_EXCL`` flag file under
    ``args['state_dir']``, so it coordinates across spawned processes).

    This is the runnable form of the protocol model checker's
    ``requeue_published`` counterexample: dispatch -> claim -> publish ->
    error. A pool that requeues here delivers the published rows twice; the
    conforming pool must complete the item as delivered instead
    (``tests/test_fault_tolerance.py``)."""

    def process(self, item):
        import os
        self.publish(item)
        if item in self.args.get('fail_on', ()):
            token = os.path.join(self.args['state_dir'], 'pub_err_{}'.format(item))
            try:
                fd = os.open(token, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                return  # already failed once; succeed this attempt
            os.close(fd)
            raise ValueError('post-publish failure on {}'.format(item))


class NumpyBatchWorker(WorkerBase):
    """Publishes one deterministic numpy column-dict per item — the
    zero-copy parity tests replay the same items through copy and
    zero-copy pools and demand bit-identical arrays."""

    def process(self, n):
        import numpy as np
        self.publish({'x': np.arange(n, dtype=np.int64),
                      'y': (np.arange(n, dtype=np.float64) * 0.5).reshape(n, 1),
                      'tag': np.full(n, n % 7, dtype=np.uint8)})
