"""minispark: a faithful local executor of the *pyspark API surface* that
petastorm_tpu's Spark adapters consume.

This image has no JVM/pyspark and no network egress, so the adapter code
paths gated on pyspark (``spark_utils.dataset_as_rdd``, the Spark-DataFrame
branch of ``spark.dataset_converter``) could never EXECUTE — their tests
skipped. This module implements exactly the API slice those adapters touch —
``SparkSession``/``sparkContext.parallelize``/``RDD.flatMap/collect``,
``DataFrame.schema/withColumn/write.parquet/count``, ``pyspark.sql.functions
.col``/``types`` — as a real local engine over pyarrow, faithful to pyspark
semantics (lazy RDD transforms, partition-preserving flatMap, logical-plan
fingerprint via ``_jdf``). Tests install it as ``pyspark`` in ``sys.modules``
(:func:`install`) and the adapters run unmodified, every line for real.

This stands in for the real thing ONLY where the environment cannot provide
it; against a genuine pyspark install the same tests run unchanged (the
fixture prefers the real module when importable).
"""

from __future__ import annotations

import os
import sys
import types as _types_mod

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

# ---------------------------------------------------------------------------
# pyspark.sql.types
# ---------------------------------------------------------------------------


class DataType(object):
    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __ne__(self, other):
        return not self.__eq__(other)

    def __hash__(self):
        # coarse but consistent with __eq__ (equal => same type => same hash);
        # without it, __eq__ alone sets __hash__ = None (PT600)
        return hash(type(self))

    def __repr__(self):
        return type(self).__name__ + '()'


class FloatType(DataType):
    pass


class DoubleType(DataType):
    pass


class IntegerType(DataType):
    pass


class LongType(DataType):
    pass


class StringType(DataType):
    pass


class BooleanType(DataType):
    pass


class ArrayType(DataType):
    def __init__(self, elementType, containsNull=True):
        self.elementType = elementType
        self.containsNull = containsNull

    def __repr__(self):
        return 'ArrayType({!r})'.format(self.elementType)


class StructField(object):
    def __init__(self, name, dataType, nullable=True):
        self.name = name
        self.dataType = dataType
        self.nullable = nullable


class StructType(object):
    def __init__(self, fields=None):
        self.fields = list(fields or [])

    def __iter__(self):
        return iter(self.fields)

    def __len__(self):
        return len(self.fields)


_ARROW_TO_SPARK = (
    (pa.types.is_float32, FloatType),
    (pa.types.is_float64, DoubleType),
    (pa.types.is_int32, IntegerType),
    (pa.types.is_int64, LongType),
    (pa.types.is_string, StringType),
    (pa.types.is_boolean, BooleanType),
)


def _arrow_to_spark_type(arrow_type):
    for pred, spark_type in _ARROW_TO_SPARK:
        if pred(arrow_type):
            return spark_type()
    if pa.types.is_list(arrow_type):
        return ArrayType(_arrow_to_spark_type(arrow_type.value_type))
    raise TypeError('minispark: unmapped arrow type {}'.format(arrow_type))


def _spark_to_arrow_type(spark_type):
    mapping = {FloatType: pa.float32(), DoubleType: pa.float64(),
               IntegerType: pa.int32(), LongType: pa.int64(),
               StringType: pa.string(), BooleanType: pa.bool_()}
    if isinstance(spark_type, ArrayType):
        return pa.list_(_spark_to_arrow_type(spark_type.elementType))
    return mapping[type(spark_type)]


# ---------------------------------------------------------------------------
# pyspark.sql.functions
# ---------------------------------------------------------------------------


class Column(object):
    """A column reference, optionally with a pending cast — the only
    expression form the adapters build (``col(name).cast(T())``)."""

    def __init__(self, name, cast_to=None):
        self.name = name
        self.cast_to = cast_to

    def cast(self, dataType):
        return Column(self.name, cast_to=dataType)


def col(name):
    return Column(name)


# ---------------------------------------------------------------------------
# RDD / SparkContext (lazy transform chain, partition-preserving)
# ---------------------------------------------------------------------------


class RDD(object):
    """Lazy like the real thing: transforms record thunks; work happens at an
    action (collect/count/take), partition by partition."""

    def __init__(self, partitions, transforms=()):
        self._partitions = [list(p) for p in partitions]
        self._transforms = tuple(transforms)

    def getNumPartitions(self):
        return len(self._partitions)

    def _derive(self, kind, f):
        return RDD(self._partitions, self._transforms + ((kind, f),))

    def map(self, f):
        return self._derive('map', f)

    def flatMap(self, f):
        return self._derive('flatMap', f)

    def filter(self, f):
        return self._derive('filter', f)

    def _compute(self, part):
        for kind, f in self._transforms:
            if kind == 'map':
                part = [f(x) for x in part]
            elif kind == 'flatMap':
                part = [y for x in part for y in f(x)]
            else:
                part = [x for x in part if f(x)]
        return part

    def collect(self):
        return [x for part in self._partitions for x in self._compute(part)]

    def count(self):
        return sum(len(self._compute(p)) for p in self._partitions)

    def take(self, n):
        out = []
        for part in self._partitions:  # early-exit across partitions, as pyspark does
            out.extend(self._compute(part))
            if len(out) >= n:
                break
        return out[:n]

    def first(self):
        got = self.take(1)
        if not got:
            raise ValueError('RDD is empty')
        return got[0]


class SparkContext(object):
    def __init__(self, defaultParallelism=None):
        self.defaultParallelism = defaultParallelism or (os.cpu_count() or 2)

    def parallelize(self, data, numSlices=None):
        data = list(data)
        n = numSlices or self.defaultParallelism
        n = max(1, min(n, len(data)) if data else 1)
        # pyspark's range partitioning: contiguous, near-equal slices
        slices = []
        base, extra = divmod(len(data), n)
        start = 0
        for i in range(n):
            size = base + (1 if i < extra else 0)
            slices.append(data[start:start + size])
            start += size
        return RDD(slices)


# ---------------------------------------------------------------------------
# DataFrame (arrow-backed) + writer + session
# ---------------------------------------------------------------------------


class _QueryExecution(object):
    """The ``_jdf.queryExecution().analyzed().toString()`` chain the converter
    fingerprints. The 'logical plan' of a materialized local frame is its
    schema + content digest — stable across re-created identical frames, like
    pyspark's analyzed plan for identical source data."""

    def __init__(self, table):
        self._table = table

    def queryExecution(self):
        return self

    def analyzed(self):
        return self

    def toString(self):
        import hashlib
        digest = hashlib.sha1(str(self._table.schema).encode())
        for batch in self._table.to_batches():
            for col_ in batch.columns:
                for buf in col_.buffers():
                    if buf is not None:
                        digest.update(buf)
        return 'minispark-plan:' + digest.hexdigest()


class DataFrameWriter(object):
    def __init__(self, df):
        self._df = df
        self._options = {}

    def option(self, key, value):
        self._options[key] = value
        return self

    def parquet(self, url):
        from petastorm_tpu.fs import FilesystemResolver
        from petastorm_tpu.spark.dataset_converter import rows_per_row_group_for_bytes
        resolver = FilesystemResolver(url)
        fs, path = resolver.filesystem(), resolver.get_dataset_path()
        fs.create_dir(path, recursive=True)
        table = self._df._table
        block_bytes = int(self._options.get('parquet.block.size', 32 * 1024 * 1024))
        with fs.open_output_stream(path + '/part-00000-minispark.parquet') as f:
            pq.write_table(table, f,
                           row_group_size=rows_per_row_group_for_bytes(table, block_bytes),
                           compression=self._options.get('compression', 'snappy'))


class DataFrame(object):
    def __init__(self, table, session=None):
        self._table = table
        self._session = session
        self._jdf = _QueryExecution(table)

    @property
    def schema(self):
        return StructType([StructField(f.name, _arrow_to_spark_type(f.type))
                           for f in self._table.schema])

    def withColumn(self, name, column):
        if not isinstance(column, Column) or column.cast_to is None:
            raise TypeError('minispark supports withColumn(name, col(...).cast(T)) only')
        idx = self._table.schema.get_field_index(column.name)
        target = _spark_to_arrow_type(column.cast_to)
        casted = self._table.column(idx).cast(target)
        if name == column.name:
            table = self._table.set_column(idx, pa.field(name, target), casted)
        else:
            table = self._table.append_column(pa.field(name, target), casted)
        return DataFrame(table, self._session)

    def count(self):
        return self._table.num_rows

    def collect(self):
        return self._table.to_pylist()

    def toPandas(self):
        return self._table.to_pandas()

    @property
    def write(self):
        return DataFrameWriter(self)


# _is_spark_df dispatches on type(df).__module__.startswith('pyspark.') — the
# class must claim the module it stands in for
DataFrame.__module__ = 'pyspark.sql.dataframe'


class SparkSession(object):
    def __init__(self, defaultParallelism=None):
        self.sparkContext = SparkContext(defaultParallelism)

    class _Builder(object):
        """Immutable chain: every step returns a FRESH builder, so state from
        one ``SparkSession.builder...`` chain never leaks into the next (the
        shared class-level root stays untouched, like pyspark's per-chain
        config)."""

        def __init__(self, parallelism=None):
            self._parallelism = parallelism

        def master(self, url):
            # 'local[N]' controls parallelism, as in pyspark
            p = self._parallelism
            if url.startswith('local[') and url.endswith(']') and url[6:-1].isdigit():
                p = int(url[6:-1])
            return type(self)(p)

        def appName(self, name):
            return type(self)(self._parallelism)

        def config(self, *args, **kwargs):
            return type(self)(self._parallelism)

        def getOrCreate(self):
            return SparkSession(self._parallelism)

    def createDataFrame(self, data, schema=None):
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            table = pa.Table.from_pandas(data, preserve_index=False)
        elif isinstance(data, pa.Table):
            table = data
        else:  # list of tuples + column-name list
            names = list(schema) if schema is not None else None
            table = pa.table({n: [row[i] for row in data] for i, n in enumerate(names)})
        return DataFrame(table, self)

    def stop(self):
        pass


SparkSession.builder = SparkSession._Builder()


# ---------------------------------------------------------------------------
# sys.modules installation
# ---------------------------------------------------------------------------


def _module(name, **attrs):
    mod = _types_mod.ModuleType(name)
    for k, v in attrs.items():
        setattr(mod, k, v)
    return mod


def install(target=None):
    """Register this implementation as ``pyspark`` in ``sys.modules``
    (``target`` defaults to ``sys.modules``; pass a dict for scoped use with
    ``pytest.MonkeyPatch.setitem``). Returns the module names registered."""
    target = sys.modules if target is None else target
    functions = _module('pyspark.sql.functions', col=col, Column=Column)
    types_mod = _module(
        'pyspark.sql.types', DataType=DataType, FloatType=FloatType,
        DoubleType=DoubleType, IntegerType=IntegerType, LongType=LongType,
        StringType=StringType, BooleanType=BooleanType, ArrayType=ArrayType,
        StructField=StructField, StructType=StructType)
    dataframe = _module('pyspark.sql.dataframe', DataFrame=DataFrame,
                        DataFrameWriter=DataFrameWriter)
    session = _module('pyspark.sql.session', SparkSession=SparkSession)
    sql = _module('pyspark.sql', SparkSession=SparkSession, DataFrame=DataFrame,
                  functions=functions, types=types_mod, dataframe=dataframe,
                  session=session)
    pyspark = _module('pyspark', SparkContext=SparkContext, RDD=RDD, sql=sql,
                      __version__='minispark')
    mods = {'pyspark': pyspark, 'pyspark.sql': sql,
            'pyspark.sql.functions': functions, 'pyspark.sql.types': types_mod,
            'pyspark.sql.dataframe': dataframe, 'pyspark.sql.session': session}
    for name, mod in mods.items():
        target[name] = mod
    return list(mods)
