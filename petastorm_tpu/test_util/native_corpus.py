"""Handwritten/fuzzed Parquet chunk corpus for the native decode kernels.

One source of adversarial inputs, consumed from two directions:

* ``tests/test_fused_decode.py`` replays it through the **release** kernels
  and asserts the error-sentinel contract (malformed bytes return a status,
  never crash or over-read);
* ``tests/test_sanitized_native.py`` replays the identical corpus through
  **ASan/UBSan-instrumented** kernels (``PSTPU_SANITIZE=address,undefined``,
  see ``native/build.py``), where an over-read the release build happens to
  survive becomes a hard failure.

The builders handwrite thrift compact-protocol page headers byte by byte, so
the corpus covers inputs no real writer produces (declared counts of
``2**61``, truncated headers, spliced garbage) — exactly the class both PR 6
review bugs lived in.
"""

from __future__ import annotations

import ctypes
import struct

import numpy as np


def tvarint(v):
    """Thrift compact-protocol unsigned varint."""
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def tzigzag(v):
    return tvarint((v << 1) ^ (v >> 63))


def stats_struct(min_value=None, max_value=None, null_count=None,
                 max_len=None, min_len=None):
    """Thrift compact ``Statistics`` struct (fields 3/5/6 — null_count,
    max_value, min_value). ``max_len``/``min_len`` override the declared
    binary lengths to build over-declared (corrupt) stats."""
    out = b''
    last = 0
    if null_count is not None:
        out += bytes([((3 - last) << 4) | 6]) + tzigzag(null_count)
        last = 3
    if max_value is not None:
        out += (bytes([((5 - last) << 4) | 8])
                + tvarint(len(max_value) if max_len is None else max_len)
                + max_value)
        last = 5
    if min_value is not None:
        out += (bytes([((6 - last) << 4) | 8])
                + tvarint(len(min_value) if min_len is None else min_len)
                + min_value)
        last = 6
    return out + b'\x00'


def plain_page(num_values, itemsize=8, value=0, values=None, encoding=0,
               declared_raw=None, stats=None):
    """One handwritten v1 data page (thrift compact header + values).
    ``declared_raw`` overrides the declared UNCOMPRESSED size (for pages whose
    ``values`` bytes are a handwritten compressed frame); ``stats`` embeds a
    :func:`stats_struct` as DataPageHeader field 5."""
    if values is None:
        values = struct.pack('<q', value)[:itemsize] * num_values
    dph = (bytes([0x15]) + tzigzag(num_values)   # 1: num_values
           + bytes([0x15]) + tzigzag(encoding)   # 2: encoding
           + bytes([0x15]) + tzigzag(3)          # 3: def-levels RLE
           + bytes([0x15]) + tzigzag(3)          # 4: rep-levels RLE
           + (bytes([0x1C]) + stats if stats is not None else b'')  # 5: stats
           + b'\x00')
    raw_len = len(values) if declared_raw is None else declared_raw
    header = (bytes([0x15]) + tzigzag(0)                  # 1: type DATA_PAGE
              + bytes([0x15]) + tzigzag(raw_len)          # 2: uncompressed
              + bytes([0x15]) + tzigzag(len(values))      # 3: compressed
              + bytes([0x2C]) + dph                       # 5: DataPageHeader
              + b'\x00')
    return header + values


def v2_page(num_values, itemsize=8, value=0, values=None, encoding=0,
            num_nulls=0, def_len=0, rep_len=0, levels=b''):
    """One handwritten DATA_PAGE_V2 (thrift compact header + body). The body
    is ``levels + values`` — v2 keeps the def/rep level blocks as an
    uncompressed prefix with explicit byte lengths (fields 5/6), and field 7
    (is_compressed) is written FALSE so the builder needs no codec."""
    if values is None:
        values = struct.pack('<q', value)[:itemsize] * num_values
    body = levels + values
    dph2 = (bytes([0x15]) + tzigzag(num_values)   # 1: num_values
            + bytes([0x15]) + tzigzag(num_nulls)  # 2: num_nulls
            + bytes([0x15]) + tzigzag(num_values)  # 3: num_rows
            + bytes([0x15]) + tzigzag(encoding)   # 4: encoding
            + bytes([0x15]) + tzigzag(def_len)    # 5: def-levels byte length
            + bytes([0x15]) + tzigzag(rep_len)    # 6: rep-levels byte length
            + bytes([0x12])                        # 7: is_compressed = FALSE
            + b'\x00')
    header = (bytes([0x15]) + tzigzag(3)               # 1: type DATA_PAGE_V2
              + bytes([0x15]) + tzigzag(len(body))     # 2: uncompressed
              + bytes([0x15]) + tzigzag(len(body))     # 3: compressed
              + bytes([0x5C]) + dph2                   # 8: DataPageHeaderV2
              + b'\x00')
    return header + body


def v2_overdeclared_levels_chunk():
    """A corrupt v2 page whose declared def-levels length exceeds the whole
    page body: skipping it blindly would read past the chunk. Must be
    rejected at scan time (def-levels status), never dereferenced."""
    return v2_page(4, def_len=1 << 20)


def dict_page(num_values, values):
    """One handwritten v1 DICTIONARY page declaring ``num_values`` entries."""
    header = (bytes([0x15]) + tzigzag(2)              # 1: type DICTIONARY_PAGE
              + bytes([0x15]) + tzigzag(len(values))  # 2: uncompressed
              + bytes([0x15]) + tzigzag(len(values))  # 3: compressed
              + bytes([0x4C])                         # 7: DictionaryPageHeader
              + bytes([0x15]) + tzigzag(num_values)   # 1: num_values
              + bytes([0x15]) + tzigzag(0)            # 2: encoding PLAIN
              + b'\x00'
              + b'\x00')
    return header + values


def overflow_dict_chunk():
    """The PR 6 regression: a dictionary page declaring ``2**61`` entries
    over ONE real 8-byte value, indexed far out of range — the
    multiplication-form bounds product used to wrap to 0 and pass."""
    dict_vals = struct.pack('<q', 42)
    idx = bytes([8]) + tvarint(4 << 1) + bytes([200])  # RLE run: 4 x index 200
    return dict_page(1 << 61, dict_vals) + plain_page(4, values=idx, encoding=2)


# ---------------------------------------------------------------------------
# handwritten zstd / lz4 frames (PR 15): the first-party decompressors are
# driven with frames no real encoder emits — truncations, over-declared
# content sizes, corrupt compressed blocks — plus byte-exact positive
# controls built from raw/RLE blocks only (no entropy coding needed).
# ---------------------------------------------------------------------------

ZSTD_MAGIC = 0xFD2FB528
LZ4_FRAME_MAGIC = 0x184D2204


def zstd_frame_bytes(payload, content_size=None, block_kind='raw'):
    """One handwritten RFC 8878 frame: single-segment header with a 4-byte
    frame-content-size, then ONE block. ``block_kind``:

    * ``'raw'`` — a stored block carrying ``payload`` verbatim;
    * ``'rle'`` — an RLE block regenerating ``len(payload)`` copies of
      ``payload[0]``;
    * ``'corrupt'`` — a block flagged COMPRESSED whose body is ``payload``
      (garbage to the FSE/huffman parsers: must be rejected, never decoded).

    ``content_size`` overrides the declared frame content size (over- or
    under-declaring what the block regenerates)."""
    if content_size is None:
        content_size = len(payload)
    fhd = (2 << 6) | 0x20  # fcs_code 2 (4-byte FCS) + single-segment
    out = struct.pack('<I', ZSTD_MAGIC) + bytes([fhd]) + struct.pack(
        '<I', content_size)
    if block_kind == 'raw':
        bh = (len(payload) << 3) | 1                  # type 0 (raw), last
        out += struct.pack('<I', bh)[:3] + payload
    elif block_kind == 'rle':
        bh = (len(payload) << 3) | (1 << 1) | 1       # type 1 (RLE), last
        out += struct.pack('<I', bh)[:3] + payload[:1]
    else:
        bh = (len(payload) << 3) | (2 << 1) | 1       # type 2 (compressed)
        out += struct.pack('<I', bh)[:3] + payload
    return out


def lz4_raw_block_bytes(payload):
    """One raw LZ4 block holding ``payload`` as a single literals-only final
    sequence (valid per spec: the last sequence carries no match)."""
    lit = len(payload)
    out = bytearray([min(lit, 15) << 4])
    if lit >= 15:
        rem = lit - 15
        while rem >= 255:
            out.append(255)
            rem -= 255
        out.append(rem)
    return bytes(out) + payload


def lz4_raw_match_block():
    """A raw LZ4 block exercising the overlapping match-copy path: 4
    literals, a match of 8 at offset 4 (self-overlapping), then a
    literals-only tail. Returns ``(block_bytes, decoded_bytes)``."""
    block = (bytes([(4 << 4) | (8 - 4)]) + b'abcd' + struct.pack('<H', 4)
             + bytes([2 << 4]) + b'zz')
    return block, b'abcd' * 3 + b'zz'


def lz4_frame_bytes(payload, stored=False):
    """One handwritten LZ4 frame (magic, FLG/BD/HC, one block, EndMark).
    ``stored=True`` writes the block uncompressed (high bit of the size)."""
    flg = (1 << 6) | 0x20  # version 01, block-independent
    out = struct.pack('<I', LZ4_FRAME_MAGIC) + bytes([flg, 0x40, 0])
    block = payload if stored else lz4_raw_block_bytes(payload)
    bsz = len(block) | (0x80000000 if stored else 0)
    return out + struct.pack('<I', bsz) + block + struct.pack('<I', 0)


def lz4_hadoop_bytes(payload, declared_raw=None):
    """One hadoop-framed LZ4 chunk (what parquet's legacy LZ4 codec writes):
    big-endian [decompressed size][compressed size] then a raw block.
    ``declared_raw`` over/under-declares the decompressed size."""
    block = lz4_raw_block_bytes(payload)
    want = len(payload) if declared_raw is None else declared_raw
    return struct.pack('>II', want, len(block)) + block


def compressed_frame_corpus():
    """(chunk_bytes, codec, expect_ok) triples: handwritten compressed pages
    driven through the fused kernel — positive controls that MUST decode
    byte-exactly, and malformed frames that MUST be rejected with a status.
    Replayed by the release fuzz lane and the ASan/UBSan lane alike."""
    vals = struct.pack('<qqqq', 7, 7, 7, 7)
    # an RLE block regenerates payload[0] x declared-size, so its positive
    # control uses a byte-uniform payload that IS its own regeneration
    rle_vals = b'\x07' * 32
    return [
        # -- positive controls (expect_ok=True, output must equal expect bytes)
        (plain_page(4, values=zstd_frame_bytes(vals), declared_raw=32), 2, True, vals),
        (plain_page(4, values=zstd_frame_bytes(rle_vals, block_kind='rle'),
                    declared_raw=32), 2, True, rle_vals),
        (plain_page(4, values=lz4_raw_block_bytes(vals), declared_raw=32), 3, True, vals),
        (plain_page(4, values=lz4_frame_bytes(vals), declared_raw=32), 4, True, vals),
        (plain_page(4, values=lz4_frame_bytes(vals, stored=True),
                    declared_raw=32), 4, True, vals),
        (plain_page(4, values=lz4_hadoop_bytes(vals), declared_raw=32), 4, True, vals),
        # -- truncated frames: every prefix check must hold
        (plain_page(4, values=zstd_frame_bytes(vals)[:11], declared_raw=32), 2, False, vals),
        (plain_page(4, values=lz4_raw_block_bytes(vals)[:3], declared_raw=32), 3, False, vals),
        (plain_page(4, values=lz4_hadoop_bytes(vals)[:7], declared_raw=32), 4, False, vals),
        # -- over-declared sizes: the declared regeneration exceeds reality
        (plain_page(4, values=zstd_frame_bytes(vals, content_size=1 << 20),
                    declared_raw=32), 2, False, vals),
        (plain_page(4, values=lz4_hadoop_bytes(vals, declared_raw=1 << 20),
                    declared_raw=32), 4, False, vals),
        # -- under-declared: frame regenerates more than the page claims
        (plain_page(4, values=zstd_frame_bytes(vals * 2, content_size=16),
                    declared_raw=32), 2, False, vals),
        # -- corrupt compressed block: garbage to the FSE/huffman parsers
        (plain_page(4, values=zstd_frame_bytes(b'\x9e\x42' * 8,
                                               block_kind='corrupt'),
                    declared_raw=32), 2, False, vals),
        # -- codec mismatch: a valid zstd frame fed to the lz4 decoder
        (plain_page(4, values=zstd_frame_bytes(vals), declared_raw=32), 3, False, vals),
    ]


def page_stats_corpus():
    """Pages with handwritten min/max Statistics, valid and corrupt — the
    page-stat parser must bounds-check declared binary lengths."""
    vals = struct.pack('<qqqq', 1, 2, 3, 4)
    lo, hi = struct.pack('<q', 1), struct.pack('<q', 4)
    return [
        # valid stats: page decodes, stats parse
        (plain_page(4, values=vals,
                    stats=stats_struct(min_value=lo, max_value=hi,
                                       null_count=0)), True),
        # over-declared binary length: must be rejected at header-parse time,
        # never read past the chunk
        (plain_page(4, values=vals,
                    stats=stats_struct(min_value=lo, max_value=hi,
                                       max_len=1 << 20)), False),
        (plain_page(4, values=vals,
                    stats=stats_struct(min_value=lo, min_len=1 << 20)), False),
        # stats struct with only a null count (min/max absent): decodes fine,
        # the skip logic must simply distrust the page
        (plain_page(4, values=vals, stats=stats_struct(null_count=2)), True),
    ]


def fuzz_corpus(seed=0xF05ED, mutated=150, garbage=60, max_garbage=96):
    """The seeded corpus the release fuzz test replays: byte mutations /
    truncations / splices of a valid two-page chunk, then pure garbage.
    Yields ``bytes`` (deterministic for a given seed)."""
    rng = np.random.default_rng(seed)
    # v1 + v2 pages in the base chunk: mutations/truncations exercise both
    # header parsers (and the v2 level-skip arithmetic) under the sanitizers
    valid = bytearray(plain_page(4) * 2 + v2_page(4))
    for _ in range(mutated):
        data = bytearray(valid)
        for _ in range(rng.integers(1, 8)):
            op = rng.integers(0, 3)
            if op == 0 and len(data) > 1:           # mutate
                data[rng.integers(0, len(data))] = rng.integers(0, 256)
            elif op == 1 and len(data) > 2:         # truncate
                del data[int(rng.integers(1, len(data))):]
            else:                                    # splice random bytes
                data += bytes(rng.integers(0, 256, rng.integers(1, 32),
                                           dtype=np.uint8))
        yield bytes(data)
    for _ in range(garbage):
        yield bytes(rng.integers(0, 256, rng.integers(0, max_garbage),
                                 dtype=np.uint8))


def replay_chunk_through_kernels(lib, data, reason_by_status):
    """Drive one corpus entry through every parser at the native boundary:
    the plain-page scanner (both def-level modes) and the fused kernel in
    every mode x codec combination. Raises AssertionError when a kernel
    breaks the sentinel contract; under sanitizers an over-read aborts the
    process before any assertion fires."""
    from petastorm_tpu.native import fused

    chunk = np.frombuffer(bytes(data), dtype=np.uint8) if len(data) else \
        np.zeros(1, np.uint8)[:0]
    offs = (ctypes.c_ulonglong * 16)()
    counts = (ctypes.c_longlong * 16)()
    vlens = (ctypes.c_ulonglong * 16)()
    for has_def in (0, 1):
        n = lib.pstpu_scan_plain_pages(
            chunk.ctypes.data_as(ctypes.c_void_p), chunk.size, offs, counts,
            vlens, 16, has_def)
        assert -1 <= n <= 16, n
    if chunk.size == 0:
        return
    # every mode x codec the dispatch accepts: UNCOMPRESSED, SNAPPY, ZSTD,
    # LZ4_RAW and auto-detected LZ4 all walk the same page/decompress path
    for mode in (0, 1):
        for codec in (0, 1, 2, 3, 4):
            plan = fused.ColumnPlan('f')
            plan.mode = mode
            plan.codec = codec
            plan.itemsize = 8
            plan.strip_npy = mode == 1
            plan.out_dtype = np.dtype(np.int64)
            plan.out_shape = (4,)
            plan.chunk_len = chunk.size
            plan.out_bound = 64
            out = np.zeros(64, np.uint8)
            (res,) = fused.read_into(lib, [chunk], [plan], 4, out, [0])
            assert res[0] in reason_by_status or res[0] == 0, res
    replay_chunk_through_pred_kernel(lib, chunk)


def _pred_plan_for_chunk(fused, chunk, codec):
    plan = fused.ColumnPlan('f')
    plan.mode = 0
    plan.codec = codec
    plan.itemsize = 8
    plan.phys_dtype = np.dtype(np.int64)
    plan.out_dtype = np.dtype(np.int64)
    plan.out_shape = (4,)
    plan.chunk_len = chunk.size
    plan.out_bound = 64
    plan.known_size = True
    return plan


def replay_chunk_through_pred_kernel(lib, chunk):
    """Drive one chunk through the fused *predicate* entry point: the chunk
    serves as both the output column and the predicate column, under an IN
    clause and a negated RANGE clause. The kernel must honour the same
    sentinel contract as the unfiltered pass — a selection bitmap and status,
    never a crash or over-read (the ASan lane replays this identically)."""
    from petastorm_tpu.native import fused

    operand = np.arange(2, dtype=np.int64).view(np.uint8)
    bound = np.zeros(16, dtype=np.uint8)
    for codec in (0, 1, 2, 3, 4):
        plan = _pred_plan_for_chunk(fused, chunk, codec)
        pred_plan = _pred_plan_for_chunk(fused, chunk, codec)
        for op, negate in ((fused.PRED_IN, 0), (fused.PRED_RANGE, 1)):
            preds = (fused.FusedPredStruct * 1)()
            pr = preds[0]
            if op == fused.PRED_IN:
                pr.values = operand.ctypes.data
                pr.values_cap = operand.nbytes
                pr.count = 2
            else:
                pr.values = bound.ctypes.data
                pr.values_cap = bound.nbytes
                pr.count = 0
                pr.has_lo = 1
                pr.lo_incl = 1
            pr.col = 0
            pr.op = op
            pr.dtype = 1  # i64
            pr.negate = negate
            plan_obj = fused.FusedPlan([plan], [], {}, 4)
            res = fused.read_block_pred(lib, [chunk], plan_obj, [chunk],
                                        [pred_plan], preds,
                                        [operand, bound])
            if res is not None:
                _block, _reasons, sel_mask, n_selected, _skipped = res
                assert 0 <= n_selected <= 4
                assert int(sel_mask.sum()) == n_selected, (n_selected, sel_mask)


def replay_corrupt_chunk_regressions(lib):
    """The handwritten corrupt-chunk regressions (the shipped PR 6 bug
    class), asserting each is rejected with the expected status."""
    from petastorm_tpu.native import fused

    chunk = np.frombuffer(overflow_dict_chunk(), dtype=np.uint8)
    plan = fused.ColumnPlan('x')
    plan.itemsize = 8
    plan.phys_dtype = np.dtype(np.int64)
    plan.out_dtype = np.dtype(np.int64)
    plan.out_shape = (4,)
    plan.chunk_len = chunk.size
    plan.out_bound = 4 * 8
    out = np.zeros(32, np.uint8)
    (res,) = fused.read_into(lib, [chunk], [plan], 4, out, [0])
    assert res[0] == 9, res  # kColDict: rejected, never dereferenced

    # v2 page declaring a def-levels block longer than its whole body: the
    # level skip must be bounds-checked, not trusted
    chunk_v2 = np.frombuffer(v2_overdeclared_levels_chunk(), dtype=np.uint8)
    plan_v2 = fused.ColumnPlan('v2')
    plan_v2.itemsize = 8
    plan_v2.phys_dtype = np.dtype(np.int64)
    plan_v2.out_dtype = np.dtype(np.int64)
    plan_v2.out_shape = (4,)
    plan_v2.chunk_len = chunk_v2.size
    plan_v2.out_bound = 4 * 8
    out_v2 = np.zeros(32, np.uint8)
    (res_v2,) = fused.read_into(lib, [chunk_v2], [plan_v2], 4, out_v2, [0])
    assert res_v2[0] == 5, res_v2  # kColDefLevels: rejected, never skipped-past

    # stale-metadata precheck: a failing column must not shift its
    # neighbors' aux buffers (the aux_bufs index-misalignment regression)
    import io
    cells = []
    for i in range(2):
        buf = io.BytesIO()
        np.save(buf, np.arange(3, dtype=np.int64) + i)
        cells.append(buf.getvalue())
    values = b''.join(struct.pack('<I', len(c)) + c for c in cells)
    chunk2 = np.frombuffer(plain_page(2, values=values), dtype=np.uint8)
    payload = 3 * 8
    bad = fused.ColumnPlan('bad')
    bad.chunk_len = chunk2.size + 1
    bad.out_bound = 16
    good = fused.ColumnPlan('good')
    good.mode = fused.MODE_BINARY_RAW
    good.strip_npy = True
    good.chunk_len = chunk2.size
    good.out_bound = 2 * payload
    out2 = np.zeros(16 + 2 * payload, np.uint8)
    res2 = fused.read_into(lib, [chunk2, chunk2], [bad, good], 2, out2, [0, 16])
    assert res2[0][0] != 0 and res2[1][0] == 0, res2
    assert res2[1][3] > 0 and res2[1][4] == cells[0][:res2[1][3]], res2

    replay_compressed_frames(lib)
    replay_page_stats(lib)


def replay_compressed_frames(lib):
    """Handwritten zstd/lz4 frames through the fused kernel: positive
    controls must decode byte-exactly, malformed frames must be rejected
    with a status — never a crash or over-read."""
    from petastorm_tpu.native import fused

    for data, codec, expect_ok, vals in compressed_frame_corpus():
        chunk = np.frombuffer(data, dtype=np.uint8)
        plan = fused.ColumnPlan('c')
        plan.codec = codec
        plan.itemsize = 8
        plan.phys_dtype = np.dtype(np.int64)
        plan.out_dtype = np.dtype(np.int64)
        plan.out_shape = (4,)
        plan.chunk_len = chunk.size
        plan.out_bound = len(vals)
        out = np.zeros(len(vals), np.uint8)
        (res,) = fused.read_into(lib, [chunk], [plan], 4, out, [0])
        if expect_ok:
            assert res[0] == 0, (res, codec)
            assert bytes(out) == vals, (codec, bytes(out))
        else:
            assert res[0] != 0, (res, codec)


def replay_page_stats(lib):
    """Pages carrying handwritten Statistics structs: valid stats must not
    disturb the decode, over-declared binary lengths must be rejected at
    header-parse time."""
    from petastorm_tpu.native import fused

    for data, expect_ok in page_stats_corpus():
        chunk = np.frombuffer(data, dtype=np.uint8)
        plan = fused.ColumnPlan('s')
        plan.itemsize = 8
        plan.phys_dtype = np.dtype(np.int64)
        plan.out_dtype = np.dtype(np.int64)
        plan.out_shape = (4,)
        plan.chunk_len = chunk.size
        plan.out_bound = 32
        out = np.zeros(32, np.uint8)
        (res,) = fused.read_into(lib, [chunk], [plan], 4, out, [0])
        if expect_ok:
            assert res[0] == 0, res
        else:
            assert res[0] != 0, res


def replay_ring_cycles(ring_mod, name_suffix):
    """Reserve/commit/abort + pad-marker wrap cycles and the never-fit
    reservation through a (possibly sanitized) shm ring build."""
    ring = ring_mod.ShmRing.create('/pstpu_san_{}'.format(name_suffix), 4096)
    try:
        for i in range(60):
            payload = bytes([i % 251]) * (i * 37 % 900 + 10)
            mv = ring.try_reserve(len(payload))
            assert mv is not None
            mv[:len(payload)] = payload
            ring.commit(len(payload))
            assert ring.try_read() == payload
        ring.try_reserve(100)
        ring.abort()
        assert ring.try_read() is None
        assert ring.try_write(b'x' * 1992) and ring.try_read() is not None
        try:
            ring.try_reserve(3000)  # wrap pad + header + payload can never fit
        except ValueError:
            pass
        else:
            raise AssertionError('never-fit reservation did not raise')
    finally:
        ring.close()


def replay_lifetime_cycles(ring_mod, name_suffix):
    """Zero-copy peek/release cycles through a (possibly sanitized) shm ring
    build: borrowed in-place views, wrapped-message copies, out-of-order
    consumer releases retired FIFO by the ledger, peek-aware ``has_message``
    probes, and the drain-deferred close. Every byte of every borrowed view
    is read back while live — under ASan an over-read of the mapped data
    area aborts the replay."""
    from petastorm_tpu.native.lifetime import RingBorrowLedger, SlotRegistry

    ring = ring_mod.ShmRing.create('/pstpu_lt_{}'.format(name_suffix), 8192)
    registry = SlotRegistry()
    try:
        ledger = RingBorrowLedger(ring, registry_=registry)
        for round_no in range(40):
            payloads = [bytes([(round_no + i) % 251]) * (i * 53 % 900 + 16)
                        for i in range(4)]
            for p in payloads:
                assert ring.try_write(p)
            taken = []
            while True:
                item = ring.try_read_zero_copy()
                if item is None:
                    break
                view, span, borrowed = item
                slot = ledger.take(view, span, borrowed)
                taken.append((bytes(view), slot))  # full read of the view
            assert [p for p, _ in taken] == payloads
            assert not ring.has_message()  # peeked past: nothing pending
            # rotate the release order per round; the ledger must retire
            # spans FIFO regardless
            order = [(i + round_no) % len(taken) for i in range(len(taken))]
            for i in order:
                taken[i][1].release_now()
            assert ledger.live == 0
        assert registry.counters()['lifetime_live_borrows'] == 0
        # deferred close: a live borrow blocks the munmap until it dies
        assert ring.try_write(b'q' * 64)
        view, span, borrowed = ring.try_read_zero_copy()
        slot = ledger.take(view, span, borrowed)
        closed = []
        assert not ledger.close_when_drained(lambda: closed.append(1))
        slot.release_now()
        assert closed == [1]
    finally:
        ring.close()  # idempotent: the drained ledger may have closed it
