"""Shuffle-quality analysis: correlation of a shuffled id stream vs the
unshuffled order (reference test_util/shuffling_analysis.py:52-85).

Used by tests (and tuning sessions) to quantify decorrelation instead of just
asserting "order changed": a well-shuffled stream's rank correlation against
the sequential order should be near zero, and the distribution over repeated
runs should be tight around it.
"""

from __future__ import annotations

import numpy as np


def rank_correlation(ids):
    """Spearman rank correlation of the observed stream against 0..N-1 order.

    1.0 = unshuffled, ~0 = decorrelated, -1.0 = exactly reversed.
    """
    ids = np.asarray(ids, dtype=np.float64)
    n = len(ids)
    if n < 2:
        return 1.0
    position = np.arange(n, dtype=np.float64)
    ranks = np.argsort(np.argsort(ids)).astype(np.float64)
    pc = np.corrcoef(position, ranks)[0, 1]
    return float(pc)


def compute_correlation_distribution(reader_factory, num_runs=5, id_field='id'):
    """Run ``reader_factory()`` ``num_runs`` times, collecting the rank
    correlation of each run's id stream (reference shuffling_analysis.py:52-85
    does the same over pairs of shuffled readouts)."""
    correlations = []
    for _ in range(num_runs):
        with reader_factory() as reader:
            ids = [getattr(row, id_field) for row in reader]
        correlations.append(abs(rank_correlation(ids)))
    return np.asarray(correlations)
