"""A storage-less Reader stand-in for testing adapters in isolation.

Parity: reference /root/reference/petastorm/test_util/reader_mock.py:19-82 —
extended with ``batched_output`` (the reference mock only covered the
row-oriented path) and a bounded ``num_rows`` so iteration can terminate, which
the infinite reference mock could not.
"""

from __future__ import annotations

import numpy as np

from petastorm_tpu.generator import generate_datapoint


def schema_data_generator_example(schema, rng=None):
    """Random row dict for ``schema`` (reference reader_mock.py:67-82, but
    random instead of zeros so correlation/shuffle tests are meaningful)."""
    return generate_datapoint(schema, rng=rng)


class ReaderMock(object):
    """Yields schema-conformant synthetic rows with the Reader interface
    (iteration, ``stop``/``join``, context manager, ``batched_output``,
    ``reset``) and no storage underneath.

    :param schema: a Unischema
    :param schema_data_generator: ``f(schema) -> row dict`` (default: seeded
        random rows via :func:`generate_datapoint`)
    :param num_rows: rows (or batches when ``batch_size``) per epoch;
        ``None`` = infinite, like the reference mock
    :param batch_size: when set, emits namedtuples of stacked column arrays
        with this many rows (``batched_output=True``)
    """

    def __init__(self, schema, schema_data_generator=None, ngram=None,
                 num_rows=None, batch_size=None, seed=0):
        if ngram is not None:
            raise ValueError('NGram is not supported by ReaderMock')
        self.schema = schema
        self.ngram = None
        self._rng = np.random.default_rng(seed)
        self._generator = (schema_data_generator if schema_data_generator is not None
                           else (lambda s: schema_data_generator_example(s, rng=self._rng)))
        self._num_rows = num_rows
        self._batch_size = batch_size
        self._emitted = 0
        self.batched_output = batch_size is not None
        self.last_row_consumed = False

    def fetch(self):
        if self._batch_size is None:
            return self.schema.make_namedtuple(**self._generator(self.schema))
        rows = [self._generator(self.schema) for _ in range(self._batch_size)]
        columns = {name: np.stack([np.asarray(r[name]) for r in rows])
                   for name in self.schema.fields}
        return self.schema.make_namedtuple(**columns)

    def __iter__(self):
        return self

    def __next__(self):
        if self._num_rows is not None and self._emitted >= self._num_rows:
            self.last_row_consumed = True
            raise StopIteration
        self._emitted += 1
        return self.fetch()

    next = __next__

    def reset(self):
        self._emitted = 0
        self.last_row_consumed = False

    def stop(self):
        pass

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
