"""Synthetic dataset generation for tests and benchmarks.

Mirrors the reference's central fixture pattern (tests/test_common.py:38-157):
a rich ``TestSchema`` exercising scalars, images, ndarrays, nullable and
variable-shape fields, written to a local tmpdir with real Parquet — no cluster.
"""

from __future__ import annotations

from decimal import Decimal

import numpy as np

from petastorm_tpu.codecs import (CompressedImageCodec, CompressedNdarrayCodec, NdarrayCodec,
                                  ScalarCodec)
from petastorm_tpu.etl.dataset_metadata import materialize_dataset
from petastorm_tpu.etl.rowgroup_indexers import FieldNotNullIndexer, SingleFieldIndexer
from petastorm_tpu.etl.rowgroup_indexing import build_rowgroup_index
from petastorm_tpu.unischema import Unischema, UnischemaField

TestSchema = Unischema('TestSchema', [
    UnischemaField('partition_key', np.str_, (), ScalarCodec(), False),
    UnischemaField('id', np.int64, (), ScalarCodec(), False),
    UnischemaField('id2', np.int32, (), ScalarCodec(), False),
    UnischemaField('id_float', np.float64, (), ScalarCodec(), False),
    UnischemaField('id_odd', np.bool_, (), ScalarCodec(), False),
    UnischemaField('python_primitive_uint8', np.uint8, (), ScalarCodec(), False),
    UnischemaField('image_png', np.uint8, (128, 256, 3), CompressedImageCodec('png'), False),
    UnischemaField('matrix', np.float32, (32, 16, 3), NdarrayCodec(), False),
    UnischemaField('decimal', Decimal, (), ScalarCodec(), False),
    UnischemaField('matrix_uint16', np.uint16, (2, 3), NdarrayCodec(), False),
    UnischemaField('matrix_string', np.bytes_, (None,), NdarrayCodec(), False),
    UnischemaField('empty_matrix_string', np.bytes_, (None,), NdarrayCodec(), False),
    UnischemaField('matrix_nullable', np.uint16, (None, 14), NdarrayCodec(), True),
    UnischemaField('sensor_name', np.str_, (1,), NdarrayCodec(), False),
    UnischemaField('string_array_nullable', np.str_, (None,), NdarrayCodec(), True),
    UnischemaField('compressed_matrix', np.float32, (10,), CompressedNdarrayCodec(), False),
])


def create_test_row(idx, rng, image_shape=(128, 256, 3)):
    """One synthetic TestSchema row (reference tests/test_common.py:59-94)."""
    nullable_matrix = None if idx % 5 == 0 else rng.integers(
        0, 2 ** 16 - 1, (rng.integers(1, 10), 14), dtype=np.uint16)
    nullable_strings = None if idx % 3 == 0 else np.asarray(
        ['a' * (idx % 7), 'bc', ''][:(idx % 3) + 1], dtype=np.str_)
    return {
        'partition_key': 'p_{}'.format(idx % 10),
        'id': idx,
        'id2': idx % 231,
        'id_float': float(idx),
        'id_odd': bool(idx % 2),
        'python_primitive_uint8': (idx % 255),
        'image_png': rng.integers(0, 255, image_shape, dtype=np.uint8),
        'matrix': rng.random((32, 16, 3), dtype=np.float32),
        'decimal': Decimal('{}.{}'.format(idx, idx % 100)),
        'matrix_uint16': rng.integers(0, 2 ** 16 - 1, (2, 3), dtype=np.uint16),
        'matrix_string': np.asarray([b'row', b'of', b'strings'][:idx % 3 + 1], dtype=np.bytes_),
        'empty_matrix_string': np.asarray([], dtype=np.bytes_),
        'matrix_nullable': nullable_matrix,
        'sensor_name': np.asarray(['sensor_{}'.format(idx % 4)], dtype=np.str_),
        'string_array_nullable': nullable_strings,
        'compressed_matrix': rng.random(10, dtype=np.float32),
    }


def create_test_dataset(dataset_url, num_rows=100, rows_per_row_group=10, rows_per_file=30,
                        seed=0, build_indexes=True, image_shape=(128, 256, 3)):
    """Write the synthetic TestSchema dataset and (optionally) its row-group
    indexes (reference tests/test_common.py:97-157)."""
    rng = np.random.default_rng(seed)
    rows = [create_test_row(i, rng, image_shape) for i in range(num_rows)]
    with materialize_dataset(dataset_url, TestSchema, rows_per_row_group=rows_per_row_group,
                             rows_per_file=rows_per_file) as writer:
        for row in rows:
            writer.write(row)
    if build_indexes:
        build_rowgroup_index(dataset_url, [
            SingleFieldIndexer('id_index', 'id'),
            SingleFieldIndexer('sensor_name_index', 'sensor_name'),
            SingleFieldIndexer('partition_index', 'partition_key'),
            FieldNotNullIndexer('matrix_nullable_index', 'matrix_nullable'),
        ])
    return rows


def create_scalar_dataset(dataset_url, num_rows=100, rows_per_row_group=10, seed=0,
                          partition_by=None):
    """Plain scalar-only dataset for the batch-reader path
    (reference tests/conftest.py scalar_dataset, test_common.py:160-245)."""
    import datetime
    rng = np.random.default_rng(seed)
    schema = Unischema('ScalarSchema', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('int_fixed_size_list', np.int64, (3,), NdarrayCodec(), False),
        UnischemaField('float64', np.float64, (), ScalarCodec(), False),
        UnischemaField('string', np.str_, (), ScalarCodec(), False),
        UnischemaField('string2', np.str_, (), ScalarCodec(), False),
        UnischemaField('datetime', np.datetime64, (), ScalarCodec(), True),
    ])
    rows = [{
        'id': i,
        'int_fixed_size_list': np.arange(3, dtype=np.int64) + i,
        'float64': float(i) * 0.66,
        'string': 'hello_{}'.format(i),
        'string2': 'world_{}'.format(i % 5),
        'datetime': np.datetime64(datetime.date(2020, 1, 1 + i % 28)),
    } for i in range(num_rows)]
    # write as a PLAIN parquet store (no petastorm metadata): exercise inference
    import pyarrow as pa
    import pyarrow.parquet as pq
    from petastorm_tpu.fs import FilesystemResolver
    resolver = FilesystemResolver(dataset_url)
    fs, root = resolver.filesystem(), resolver.get_dataset_path()
    fs.create_dir(root, recursive=True)
    table = pa.Table.from_pydict({
        'id': [r['id'] for r in rows],
        'int_fixed_size_list': [list(r['int_fixed_size_list']) for r in rows],
        'float64': [r['float64'] for r in rows],
        'string': [r['string'] for r in rows],
        'string2': [r['string2'] for r in rows],
        'datetime': [r['datetime'].astype('datetime64[us]').item() for r in rows],
    })
    if partition_by:
        pq.write_to_dataset(table, root, partition_cols=partition_by, filesystem=fs,
                            row_group_size=rows_per_row_group)
    else:
        with fs.open_output_stream(root + '/data-00000.parquet') as sink:
            pq.write_table(table, sink, row_group_size=rows_per_row_group)
    return rows, schema


def create_many_columns_dataset(dataset_url, num_columns=1000, num_rows=10,
                                rows_per_row_group=5):
    """Plain parquet store with ``num_columns`` int64 columns named col_0..N
    (reference tests/conftest.py:248-294 many_columns_non_petastorm_dataset):
    exercises wide-schema inference and >255-field namedtuple handling."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from petastorm_tpu.fs import FilesystemResolver
    resolver = FilesystemResolver(dataset_url)
    fs, root = resolver.filesystem(), resolver.get_dataset_path()
    fs.create_dir(root, recursive=True)
    names = ['col_{}'.format(i) for i in range(num_columns)]
    table = pa.Table.from_pydict(
        {name: list(range(num_rows)) for name in names})
    with fs.open_output_stream(root + '/data-00000.parquet') as sink:
        pq.write_table(table, sink, row_group_size=rows_per_row_group)
    return names
