"""Test utilities: synthetic dataset writers and reader mocks."""
