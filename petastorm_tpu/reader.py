"""Reader factories and orchestrator — the framework's main read path.

Parity: /root/reference/petastorm/reader.py —
  * ``make_reader`` (:50-174): petastorm datasets, row-oriented output
  * ``make_batch_reader`` (:177-289): any Parquet store, columnar batches
  * ``Reader`` (:292-624): ctor pipeline (open dataset -> load schema -> schema
    view/transform -> list pieces -> filter by predicate/selector/shard ->
    ventilator + pool), iterator protocol, ``reset()``, ``stop/join``,
    ``diagnostics``, ``last_row_consumed``

TPU-first notes:
  * ``cur_shard``/``shard_count`` default from ``jax.process_index()`` /
    ``jax.process_count()`` via the parallel helpers, so each pod host reads a
    disjoint row-group subset with zero coordination (share-nothing, like the
    reference's arithmetic sharding at reader.py:485-502).
  * all shuffling honors ``seed`` (ventilator epoch reshuffle + row-drop), making
    runs reproducible — a deliberate improvement over the reference.
"""

from __future__ import annotations

import logging
import warnings

from petastorm_tpu import observability as obs
from petastorm_tpu.batch_worker import ArrowBatchWorker, BatchResultsQueueReader
from petastorm_tpu.cache import NullCache
from petastorm_tpu.errors import NoDataAvailableError, PetastormTpuError
from petastorm_tpu.etl import dataset_metadata
from petastorm_tpu.etl.rowgroup_indexing import get_row_group_indexes
from petastorm_tpu.fs import FilesystemResolver
from petastorm_tpu.local_disk_cache import LocalDiskCache
from petastorm_tpu.row_worker import (NgramBlockResultsQueueReader, RowGroupDecoderWorker,
                                      RowResultsQueueReader)
from petastorm_tpu.serializers import NumpyBlockSerializer
from petastorm_tpu.transform import transform_schema
from petastorm_tpu.workers import DummyPool, EmptyResultError, ProcessPool, ThreadPool

logger = logging.getLogger(__name__)

# extra row groups ventilated beyond worker count: bounds decoded-data memory
# while keeping workers busy (reference reader.py:47)
_VENTILATE_EXTRA_ROWGROUPS = 2


def _make_pool(reader_pool_type, workers_count, results_queue_size, serializer=None,
               on_error='raise', max_item_retries=None, protocol_monitor=None,
               zero_copy=False):
    """Pool construction incl. IPC serializer selection. The reference picks a
    columnar serializer only for its batch readers (reference reader.py:269);
    here EVERY worker publishes column blocks, so the raw-buffer
    :class:`NumpyBlockSerializer` is the process-pool default (its embedded
    pickle covers NGram window lists and other non-block payloads).
    Note: block columns crossing the process boundary arrive as WRITABLE numpy
    views over the IPC message (zero-copy receive: shm-ring bytearray, blob
    copy-on-write mmap; the zmq fallback copies once to match) — the same
    mutate-in-place affordance thread-pool blocks have.
    ``zero_copy`` (process pool, shm transport) goes further: batches are
    delivered as lifetime-tracked views straight into the ring slot, skipping
    the per-message consumer copy (docs/native.md). Thread/dummy pools hand
    over in-process arrays already — for them the flag is a documented no-op,
    not an error, so callers can set it uniformly.
    ``on_error``/``max_item_retries`` (docs/robustness.md) are implemented by
    every pool type, so failure behavior is pool-independent."""
    policy = {'on_error': _resolve_error_policy(on_error, max_item_retries),
              'protocol_monitor': protocol_monitor}
    if reader_pool_type == 'thread':
        return ThreadPool(workers_count, results_queue_size, **policy)
    if reader_pool_type == 'process':
        return ProcessPool(workers_count, results_queue_size,
                           serializer=serializer or NumpyBlockSerializer(),
                           zero_copy=zero_copy, **policy)
    if reader_pool_type == 'dummy':
        return DummyPool(**policy)
    raise ValueError('Unknown reader_pool_type {!r} (expected thread/process/dummy)'.format(
        reader_pool_type))


def _resolve_error_policy(on_error, max_item_retries):
    """Validate the item-failure knobs EARLY — a typo'd policy must fail
    before any dataset IO happens, not after listing row groups."""
    from petastorm_tpu.workers.supervision import ErrorPolicy
    if isinstance(on_error, ErrorPolicy):
        return on_error
    return ErrorPolicy(on_error, **({} if max_item_retries is None
                                    else {'max_item_retries': max_item_retries}))


def _columnar_results_reader_factory(output, batch_size, drop_last, rows_factory):
    """Results-queue-reader factory for the requested output mode: row slicing,
    raw row-group blocks, or fixed-size rebatched blocks."""
    if output == 'rows':
        if drop_last:
            raise ValueError('drop_last requires batch_size (without rebatching there is '
                             'no "last short batch" to drop)')
        return rows_factory
    if batch_size is not None:
        from petastorm_tpu.rebatch import RebatchingResultsQueueReader
        return lambda schema: RebatchingResultsQueueReader(schema, batch_size,
                                                           drop_last=drop_last)
    if drop_last:
        raise ValueError('drop_last requires batch_size (without rebatching, batches are '
                         'row-group-sized and there is no "last short batch" to drop)')
    return BatchResultsQueueReader


def _make_cache(cache_type, cache_location, cache_size_limit, cache_row_size_estimate):
    if cache_type in (None, 'null'):
        return NullCache()
    if cache_type == 'local-disk':
        if not cache_location:
            raise ValueError("cache_type='local-disk' requires cache_location")
        kwargs = {}
        if cache_size_limit:
            kwargs['size_limit_bytes'] = cache_size_limit
        if cache_row_size_estimate:
            kwargs['expected_cell_size_bytes'] = cache_row_size_estimate
        return LocalDiskCache(cache_location, **kwargs)
    raise ValueError('Unknown cache_type {!r} (expected null/local-disk)'.format(cache_type))


def make_reader(dataset_url,
                schema_fields=None,
                reader_pool_type='thread', workers_count=10, results_queue_size=50,
                seed=None,
                shuffle_row_groups=True, shuffle_row_drop_partitions=1,
                predicate=None,
                rowgroup_selector=None,
                num_epochs=1,
                cur_shard=None, shard_count=None,
                cache_type='null', cache_location=None, cache_size_limit=None,
                cache_row_size_estimate=None,
                transform_spec=None,
                ngram=None,
                output='rows', batch_size=None, drop_last=False,
                resume_state=None,
                storage_retry_policy=None,
                chunk_cache=None, chunk_cache_size_limit=None,
                telemetry=None,
                autotune=None,
                on_error='raise', max_item_retries=None,
                protocol_monitor=None,
                serve=None, serve_weight=1,
                zero_copy=False,
                elastic=None,
                piece_filter=None):
    """Reader for datasets written by :func:`materialize_dataset` — rows decoded
    through the stored Unischema's codecs (reference reader.py:50-174).

    :param schema_fields: list of field names / regex patterns / UnischemaField
        to read (``None`` = all)
    :param reader_pool_type: 'thread' | 'process' | 'dummy'
    :param seed: seeds every shuffle (row groups, row drop); None = nondeterministic
    :param shuffle_row_groups: shuffle row-group order each epoch
    :param shuffle_row_drop_partitions: split each row group into N parts, each
        ventilated separately, trading extra reads for finer shuffling
    :param predicate: :class:`petastorm_tpu.predicates.PredicateBase` row filter
    :param rowgroup_selector: :class:`petastorm_tpu.selectors.RowGroupSelectorBase`
    :param num_epochs: passes over the dataset; ``None`` = infinite
    :param cur_shard/shard_count: this reader consumes row groups where
        ``index % shard_count == cur_shard``
    :param cache_type/...: 'null' or 'local-disk' row-group cache
    :param ngram: :class:`petastorm_tpu.ngram.NGram` for windowed sequence readout
    :param storage_retry_policy: :class:`petastorm_tpu.retry.RetryPolicy` for
        transient object-store (s3/gs) IO errors; ``None`` = sensible defaults,
        ``False`` = disable retry wrapping. Carried into worker processes.
    :param chunk_cache: REMOTE stores only — ``'auto'`` (per-dataset dir under
        the system temp dir), a cache directory path, or a
        :class:`petastorm_tpu.chunkstore.ChunkCacheConfig`. Mirrors qualifying
        raw column chunks to local disk so the zero-copy page scanner serves
        them exactly as it does local files; epoch 2+ reads at local speed.
        Counters surface as ``chunk_cache_*`` keys in :attr:`Reader.diagnostics`.
        Ignored (with no effect) for local ``file://`` datasets. ``None``
        disables. See ``docs/cache.md``.
    :param chunk_cache_size_limit: on-disk byte bound of the chunk cache
        (default 10 GiB); LRU eviction keeps usage under it without ever
        invalidating chunks a live batch still references.
    :param output: 'rows' (default) yields one schema namedtuple per row —
        reference ``make_reader`` parity; 'columnar' yields one namedtuple of
        decoded column arrays per row group (``batched_output=True``) — the TPU
        hot path: no per-row Python objects ever exist, and ``JaxDataLoader``
        slices device batches straight out of the blocks. A capability the
        reference only offered for plain Parquet stores (``make_batch_reader``),
        here available with full Unischema codec decode. With ``ngram``,
        columnar output yields nested window blocks
        ``{offset: {field: [W, ...]}}`` per row group, assembled with zero
        per-row Python (``NGram.form_ngram_columnar``).
    :param batch_size: (columnar only) rebatch blocks to exactly this many rows
    :param drop_last: (columnar + batch_size only) drop the ragged final batch
    :param resume_state: dict from :meth:`Reader.state_dict` — continue reading
        from a checkpointed position (construct with otherwise-identical args)
    :param telemetry: pipeline telemetry level — ``'off'`` (near-zero
        overhead), ``'counters'`` (the process default: per-stage timers and
        counters, :attr:`Reader.diagnostics` becomes a view over the metrics
        registry), ``'spans'`` (adds Chrome-trace span recording, exportable
        via ``petastorm_tpu.observability.export_chrome_trace``), or a
        :class:`petastorm_tpu.observability.TelemetryConfig`. ``None`` keeps
        the process's current configuration. Applied process-wide and carried
        into worker processes. See ``docs/observability.md``.
    :param autotune: closed-loop autotuning (``docs/autotune.md``): ``True``
        (defaults) or a :class:`petastorm_tpu.autotune.AutotuneConfig` starts
        a feedback controller that watches windowed telemetry history and
        adjusts, at runtime and within explicit bounds: the worker pool size
        (grow/retire supervised slots), the chunk-prefetch in-flight byte
        budget, and (once a :class:`~petastorm_tpu.jax.loader.JaxDataLoader`
        attaches) the shuffle-buffer capacity. Every change is recorded as an
        ``autotune.decision`` trace span and a structured decision-log
        record carrying the evidence window. Default ``None``/``False``:
        off, with zero overhead (no recorder, no thread). The controller is
        exposed as :attr:`Reader.autotuner`.
    :param on_error: item-failure policy, identical across pool types
        (``docs/robustness.md``): ``'raise'`` (default) surfaces the first
        worker error to the iterating thread with the worker-side traceback
        attached; ``'retry'`` re-runs a failed row group up to
        ``max_item_retries`` times before raising; ``'skip'`` retries, then
        *quarantines* — the row group is recorded
        (:attr:`Reader.quarantined_items`), counted in
        ``diagnostics['items_quarantined']``, and the epoch completes without
        it. Worker-process DEATH (process pools) is always survived via
        respawn + requeue regardless of this policy; ``on_error`` only
        decides what happens when the same item exhausts its retry budget.
    :param max_item_retries: consecutive failures (errors or worker-killing
        crashes) one item may cause before the policy's terminal action
        (default 2 — an item runs at most 3 times).
    :param protocol_monitor: opt-in runtime conformance checking of the
        worker-pool supervision protocol (``docs/protocol.md``): truthy
        attaches a fresh monitor to the pool, a
        :class:`~petastorm_tpu.analysis.protocol.monitor.ProtocolMonitor`
        instance is used as-is, None honors the ``PSTPU_PROTOCOL_MONITOR``
        env var. Any event sequence the protocol spec rejects raises
        :class:`~petastorm_tpu.errors.ProtocolViolation` on the iterating
        thread.
    :param serve: read through the per-host SHARED reader service instead of
        a private pipeline (``docs/serve.md``): ``'auto'`` spawns-or-joins the
        per-user daemon, a path uses that service directory (hermetic daemons
        for tests/CI). N collocated jobs on one dataset then share ONE decode:
        the daemon fans finished batches out over a broadcast shm ring and
        returns a drop-in :class:`~petastorm_tpu.serve.ServedReader`.
        ``reader_pool_type``/``workers_count`` shape the daemon when this call
        spawns it (an already-running daemon keeps its fleet). Not supported
        with ``serve``: ``resume_state`` and ``autotune``.
    :param serve_weight: this consumer's fair-share weight in the daemon's
        scheduler (>= 1; a weight-2 tenant's stream gets twice the decode
        share of a weight-1 tenant's under contention).
    :param zero_copy: ``reader_pool_type='process'`` with the shm transport —
        deliver batches as numpy views STRAIGHT into the shared-memory ring
        slot instead of copying each message out (docs/native.md). Every view
        is lifetime-tracked (``petastorm_tpu.native.lifetime``): the slot's
        ring bytes are recycled only after the batch's arrays are garbage
        collected, so holding a batch applies backpressure rather than
        corrupting it. Values are bit-identical to the copy path. Thread and
        dummy pools already hand over in-process arrays — the flag is a
        no-op for them. Ignored with ``serve=`` (the served blob path maps
        batches zero-copy by default, with the same lifetime tracking).
    :param elastic: elastic pod sharding (``docs/parallelism.md``, "Elastic
        pod sharding"): ``True`` (defaults) or an
        :class:`~petastorm_tpu.elastic.ElasticConfig` replaces the static
        ``cur_shard``/``shard_count`` arithmetic with a lease-based
        membership registry and a generation-numbered shard map coordinated
        through a shared directory (default ``<dataset>/_elastic``). Hosts
        may join or leave MID-EPOCH: survivors adopt a departed host's
        unfinished row groups after its lease expires, filesystem
        ``O_EXCL`` commit markers make the COMMIT exactly-once pod-wide
        (sample delivery is at-least-once only in the false-expiry window:
        a host stalled past ``lease_s`` but still running may deliver rows
        its adopter also delivers — ``lease_s`` bounds that exposure), and
        the seeded global shuffle order depends only on ``(seed, epoch)``
        — bit-identical with or without churn. Not supported with
        ``elastic``: ``cur_shard``/``shard_count``, ``resume_state``
        (the pod-wide commit scoreboard IS the read position), ``serve``.
    :param piece_filter: ``callable(RowGroupPiece) -> bool`` applied to the
        piece list straight after ``load_row_groups``, BEFORE selector /
        predicate / shard — scopes the reader to a subset of row groups
        identified by ``(path, row_group)``. This is how
        :class:`~petastorm_tpu.sequence.tail.TailFollowingReader` pins each
        inner epoch to one published snapshot delta (docs/sequence.md); note
        selector index sets and v2 resume cursors are then expressed in the
        FILTERED enumeration. Not supported with ``serve``.
    """
    if serve and piece_filter is not None:
        raise ValueError('piece_filter is not supported with serve=: the shared '
                         'daemon owns one static stream plan (docs/serve.md)')
    if serve and elastic:
        raise ValueError('elastic is not supported with serve=: the shared '
                         'daemon owns one static stream plan (docs/serve.md)')
    if serve:
        return _make_served(dataset_url, batch_reader=False,
                            schema_fields=schema_fields, seed=seed,
                            shuffle_row_groups=shuffle_row_groups,
                            shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                            predicate=predicate, rowgroup_selector=rowgroup_selector,
                            num_epochs=num_epochs, cur_shard=cur_shard,
                            shard_count=shard_count, cache_type=cache_type,
                            cache_location=cache_location,
                            cache_size_limit=cache_size_limit,
                            cache_row_size_estimate=cache_row_size_estimate,
                            transform_spec=transform_spec, ngram=ngram,
                            output=output, batch_size=batch_size,
                            drop_last=drop_last, resume_state=resume_state,
                            storage_retry_policy=storage_retry_policy,
                            chunk_cache=chunk_cache,
                            chunk_cache_size_limit=chunk_cache_size_limit,
                            telemetry=telemetry, autotune=autotune,
                            serve=serve, serve_weight=serve_weight,
                            reader_pool_type=reader_pool_type,
                            workers_count=workers_count)
    error_policy = _resolve_error_policy(on_error, max_item_retries)
    try:
        schema = dataset_metadata.get_schema(dataset_url, retry_policy=storage_retry_policy)
    except dataset_metadata.PetastormMetadataError:
        raise PetastormTpuError(
            'Dataset at {} is missing unischema metadata. If it is a plain Parquet store, '
            'use make_batch_reader instead.'.format(dataset_url))

    if output not in ('rows', 'columnar'):
        raise ValueError("output must be 'rows' or 'columnar', got {!r}".format(output))
    if output == 'rows' and batch_size is not None:
        raise ValueError("batch_size requires output='columnar' (row output is one row "
                         'per iteration; batch with JaxDataLoader instead)')
    columnar_ngram = output == 'columnar' and ngram is not None
    if columnar_ngram:
        if batch_size is not None:
            raise ValueError('batch_size rebatching is not supported with ngram (window '
                             'blocks are nested); batch with JaxDataLoader instead')
        if drop_last:
            raise ValueError('drop_last requires batch_size (without rebatching there is '
                             'no "last short batch" to drop)')
        results_queue_reader_factory = (
            lambda out_schema: NgramBlockResultsQueueReader(out_schema, ngram))
    else:
        results_queue_reader_factory = _columnar_results_reader_factory(
            output, batch_size, drop_last,
            lambda out_schema: RowResultsQueueReader(out_schema, ngram))

    cache = _make_cache(cache_type, cache_location, cache_size_limit, cache_row_size_estimate)
    pool = _make_pool(reader_pool_type, workers_count, results_queue_size,
                      on_error=error_policy, protocol_monitor=protocol_monitor,
                      zero_copy=zero_copy)
    return Reader(dataset_url, schema,
                  worker_class=RowGroupDecoderWorker,
                  results_queue_reader_factory=results_queue_reader_factory,
                  pool=pool, schema_fields=schema_fields, seed=seed,
                  shuffle_row_groups=shuffle_row_groups,
                  shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                  predicate=predicate, rowgroup_selector=rowgroup_selector,
                  num_epochs=num_epochs, cur_shard=cur_shard, shard_count=shard_count,
                  cache=cache, transform_spec=transform_spec, ngram=ngram,
                  columnar_ngram=columnar_ngram,
                  resume_state=resume_state,
                  storage_retry_policy=storage_retry_policy,
                  chunk_cache=chunk_cache,
                  chunk_cache_size_limit=chunk_cache_size_limit,
                  telemetry=telemetry,
                  autotune=autotune,
                  elastic=elastic,
                  piece_filter=piece_filter)


def _make_served(dataset_url, batch_reader, schema_fields, seed,
                 shuffle_row_groups, shuffle_row_drop_partitions, predicate,
                 rowgroup_selector, num_epochs, cur_shard, shard_count,
                 cache_type, cache_location, cache_size_limit,
                 cache_row_size_estimate, transform_spec, ngram, output,
                 batch_size, drop_last, resume_state, storage_retry_policy,
                 chunk_cache, chunk_cache_size_limit, telemetry, autotune,
                 serve, serve_weight, reader_pool_type, workers_count):
    """The ``serve=`` path of the reader factories: validate the combination,
    assemble the canonical stream spec, and attach through the shared daemon
    (``docs/serve.md``). The consumer-side results assembly (rows / columnar /
    rebatch) is identical to the private path — same factories, same readers —
    which is what makes :class:`~petastorm_tpu.serve.ServedReader` drop-in."""
    if resume_state is not None:
        raise ValueError('resume_state is not supported with serve=: the read '
                         'position belongs to the shared stream (docs/serve.md)')
    if autotune:
        raise ValueError('autotune is not supported with serve=: the daemon '
                         'owns the shared worker fleet')
    obs.configure(telemetry)
    columnar_ngram = output == 'columnar' and ngram is not None
    if output not in ('rows', 'columnar'):
        raise ValueError("output must be 'rows' or 'columnar', got {!r}".format(output))
    if output == 'rows' and batch_size is not None:
        raise ValueError("batch_size requires output='columnar'")
    if columnar_ngram:
        if batch_size is not None:
            raise ValueError('batch_size rebatching is not supported with ngram')
        results_queue_reader_factory = (
            lambda out_schema: NgramBlockResultsQueueReader(out_schema, ngram))
    elif batch_reader:
        results_queue_reader_factory = _columnar_results_reader_factory(
            'columnar', batch_size, drop_last, None)
    else:
        results_queue_reader_factory = _columnar_results_reader_factory(
            output, batch_size, drop_last,
            lambda out_schema: RowResultsQueueReader(out_schema, ngram))
    cache = _make_cache(cache_type, cache_location, cache_size_limit,
                        cache_row_size_estimate)
    spec = {
        'dataset_url': dataset_url,
        'batch_reader': batch_reader,
        'schema_fields': schema_fields,
        'seed': seed,
        'shuffle_row_groups': shuffle_row_groups,
        'shuffle_row_drop_partitions': shuffle_row_drop_partitions,
        'predicate': predicate,
        'rowgroup_selector': rowgroup_selector,
        'num_epochs': num_epochs,
        'cur_shard': cur_shard,
        'shard_count': shard_count,
        'transform_spec': transform_spec,
        'ngram': ngram,
        'columnar_ngram': columnar_ngram,
        'storage_retry_policy': storage_retry_policy,
        'chunk_cache': chunk_cache,
        'chunk_cache_size_limit': chunk_cache_size_limit,
        'cache': cache,
    }
    from petastorm_tpu.serve.client import make_served_reader
    return make_served_reader(
        spec, serve, results_queue_reader_factory, weight=serve_weight,
        spawn_args={'pool_type': reader_pool_type,
                    'workers_count': workers_count})


def make_batch_reader(dataset_url,
                      schema_fields=None,
                      reader_pool_type='thread', workers_count=10, results_queue_size=50,
                      seed=None,
                      shuffle_row_groups=True, shuffle_row_drop_partitions=1,
                      predicate=None,
                      num_epochs=1,
                      cur_shard=None, shard_count=None,
                      cache_type='null', cache_location=None, cache_size_limit=None,
                      cache_row_size_estimate=None,
                      transform_spec=None,
                      batch_size=None, drop_last=False,
                      resume_state=None,
                      storage_retry_policy=None,
                      chunk_cache=None, chunk_cache_size_limit=None,
                      telemetry=None,
                      autotune=None,
                      on_error='raise', max_item_retries=None,
                      protocol_monitor=None,
                      serve=None, serve_weight=1,
                      zero_copy=False,
                      elastic=None,
                      piece_filter=None):
    """Columnar reader for ANY Parquet store (reference reader.py:177-289):
    yields one namedtuple of numpy column arrays per row group
    (``batched_output=True``). Schema is inferred from the Arrow schema unless
    petastorm metadata is present.

    ``batch_size``: when given, output batches have exactly this many rows
    instead of row-group-sized batches — constant shapes keep XLA compilation
    caches warm (the reference built this re-chunking but never wired it in:
    pyarrow_helpers/batching_table_queue.py:20-79, SURVEY.md §2.6). The final
    short batch is emitted unless ``drop_last``.

    ``chunk_cache``/``chunk_cache_size_limit``: local chunk mirror for remote
    stores — identical semantics to :func:`make_reader`.

    ``telemetry``: pipeline telemetry level ('off' | 'counters' | 'spans' |
    TelemetryConfig) — identical semantics to :func:`make_reader`.

    ``autotune``: closed-loop autotuning (True | AutotuneConfig,
    docs/autotune.md) — identical semantics to :func:`make_reader`.

    ``on_error``/``max_item_retries``: item-failure policy ('raise' | 'skip' |
    'retry', docs/robustness.md) — identical semantics to :func:`make_reader`.

    ``protocol_monitor``: opt-in runtime conformance checking of the pool
    supervision protocol (docs/protocol.md) — identical semantics to
    :func:`make_reader`.

    ``serve``/``serve_weight``: read through the per-host shared reader
    service (docs/serve.md) — identical semantics to :func:`make_reader`.

    ``zero_copy``: lifetime-tracked batch views straight out of the process
    pool's shm ring (docs/native.md) — identical semantics to
    :func:`make_reader`.

    ``elastic``: lease-based elastic pod sharding with exactly-once commit
    handoff (docs/parallelism.md) — identical semantics to
    :func:`make_reader`.

    ``piece_filter``: row-group scoping predicate applied before any other
    filtering (docs/sequence.md) — identical semantics to :func:`make_reader`.
    """
    if serve and piece_filter is not None:
        raise ValueError('piece_filter is not supported with serve=: the shared '
                         'daemon owns one static stream plan (docs/serve.md)')
    if serve and elastic:
        raise ValueError('elastic is not supported with serve=: the shared '
                         'daemon owns one static stream plan (docs/serve.md)')
    if serve:
        return _make_served(dataset_url, batch_reader=True,
                            schema_fields=schema_fields, seed=seed,
                            shuffle_row_groups=shuffle_row_groups,
                            shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                            predicate=predicate, rowgroup_selector=None,
                            num_epochs=num_epochs, cur_shard=cur_shard,
                            shard_count=shard_count, cache_type=cache_type,
                            cache_location=cache_location,
                            cache_size_limit=cache_size_limit,
                            cache_row_size_estimate=cache_row_size_estimate,
                            transform_spec=transform_spec, ngram=None,
                            output='columnar', batch_size=batch_size,
                            drop_last=drop_last, resume_state=resume_state,
                            storage_retry_policy=storage_retry_policy,
                            chunk_cache=chunk_cache,
                            chunk_cache_size_limit=chunk_cache_size_limit,
                            telemetry=telemetry, autotune=autotune,
                            serve=serve, serve_weight=serve_weight,
                            reader_pool_type=reader_pool_type,
                            workers_count=workers_count)
    error_policy = _resolve_error_policy(on_error, max_item_retries)
    schema = dataset_metadata.infer_or_load_unischema(dataset_url,
                                                      retry_policy=storage_retry_policy)
    cache = _make_cache(cache_type, cache_location, cache_size_limit, cache_row_size_estimate)
    pool = _make_pool(reader_pool_type, workers_count, results_queue_size,
                      on_error=error_policy, protocol_monitor=protocol_monitor,
                      zero_copy=zero_copy)
    results_queue_reader_factory = _columnar_results_reader_factory(
        'columnar', batch_size, drop_last, None)
    return Reader(dataset_url, schema,
                  worker_class=ArrowBatchWorker,
                  results_queue_reader_factory=results_queue_reader_factory,
                  pool=pool, schema_fields=schema_fields, seed=seed,
                  shuffle_row_groups=shuffle_row_groups,
                  shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                  predicate=predicate, rowgroup_selector=None,
                  num_epochs=num_epochs, cur_shard=cur_shard, shard_count=shard_count,
                  cache=cache, transform_spec=transform_spec, ngram=None,
                  resume_state=resume_state,
                  storage_retry_policy=storage_retry_policy,
                  chunk_cache=chunk_cache,
                  chunk_cache_size_limit=chunk_cache_size_limit,
                  telemetry=telemetry,
                  autotune=autotune,
                  elastic=elastic,
                  piece_filter=piece_filter)


class Reader(object):
    """Orchestrates piece listing/filtering, the worker pool, and iteration
    (reference reader.py:292-624)."""

    def __init__(self, dataset_url, schema, worker_class, results_queue_reader_factory, pool,
                 schema_fields=None, seed=None, shuffle_row_groups=True,
                 shuffle_row_drop_partitions=1, predicate=None, rowgroup_selector=None,
                 num_epochs=1, cur_shard=None, shard_count=None, cache=None,
                 transform_spec=None, ngram=None, columnar_ngram=False, resume_state=None,
                 storage_retry_policy=None, chunk_cache=None, chunk_cache_size_limit=None,
                 telemetry=None, autotune=None, elastic=None, piece_filter=None):
        if (cur_shard is None) != (shard_count is None):
            raise ValueError('cur_shard and shard_count must be specified together')
        if cur_shard is not None and not 0 <= cur_shard < shard_count:
            raise ValueError('cur_shard {} out of range for shard_count {}'.format(
                cur_shard, shard_count))
        if shuffle_row_drop_partitions < 1:
            raise ValueError('shuffle_row_drop_partitions must be >= 1')
        if elastic:
            if cur_shard is not None or shard_count is not None:
                raise ValueError(
                    'elastic replaces static sharding: every host opens the FULL '
                    'piece list and the generation shard map partitions it — pass '
                    'neither cur_shard nor shard_count (docs/parallelism.md)')
            if resume_state is not None:
                raise ValueError(
                    'resume_state is not supported with elastic=: the pod-wide '
                    'commit scoreboard in the coordination directory IS the read '
                    'position — restarted hosts rejoin and skip committed groups')

        # telemetry: apply the requested level process-wide (None keeps the
        # current configuration) and remember the effective config so worker
        # processes inherit it through worker_args
        self._telemetry_config = obs.configure(telemetry)

        self._dataset_url = dataset_url
        self.schema = schema  # full stored/inferred schema
        resolver = FilesystemResolver(dataset_url, retry_policy=storage_retry_policy)
        self._dataset_path = resolver.get_dataset_path()
        from petastorm_tpu.chunkstore import resolve_chunk_cache
        self._chunk_cache_config = resolve_chunk_cache(
            chunk_cache, dataset_url, resolver.is_local,
            size_limit_bytes=chunk_cache_size_limit)

        # (2-3) schema view + ngram resolution + transform schema
        if ngram is not None:
            ngram.resolve_regex_field_names(schema)
            needed = [n for n in ngram.get_field_names_at_all_timesteps() if n in schema.fields]
            output_schema = schema.create_schema_view([schema.fields[n] for n in needed])
        elif schema_fields is not None:
            output_schema = schema.create_schema_view(schema_fields)
        else:
            output_schema = schema
        self.ngram = ngram
        self.transform_spec = transform_spec
        self.transformed_schema = (transform_schema(output_schema, transform_spec)
                                   if transform_spec is not None else output_schema)
        self.output_schema = output_schema

        if ngram is not None and not ngram.timestamp_overlap and shuffle_row_drop_partitions > 1:
            raise NotImplementedError(
                'shuffle_row_drop_partitions > 1 with timestamp_overlap=False would duplicate '
                'rows across partition-boundary windows (reference reader.py:372 refuses too)')

        # (4) list pieces and filter: selector (index sets refer to the ORIGINAL
        # load_row_groups enumeration, so it must run first) -> predicate -> shard
        pieces = dataset_metadata.load_row_groups(dataset_url, schema=schema,
                                                  retry_policy=storage_retry_policy)
        if piece_filter is not None:
            # scoping comes FIRST: everything downstream (selector index sets,
            # the global resume cursor) is expressed in the filtered enumeration
            pieces = [p for p in pieces if piece_filter(p)]
        if rowgroup_selector is not None:
            pieces = self._apply_rowgroup_selector(dataset_url, pieces, rowgroup_selector,
                                                   storage_retry_policy)
        pieces, worker_predicate = self._apply_predicate_to_pieces(pieces, predicate)
        # the pre-shard enumeration is identical on every host (selector and
        # predicate run before sharding), which is what makes checkpoints
        # portable across shard counts: the v2 resume cursor is expressed in
        # these GLOBAL piece indices (state_dict / merge_resume_states)
        self._num_global_pieces = len(pieces)
        self._global_piece_indices = self._shard_piece_indices(
            len(pieces), cur_shard, shard_count)
        pieces = [pieces[i] for i in self._global_piece_indices]
        if not pieces:
            raise NoDataAvailableError(
                'No row groups selected for reading (dataset={}, shard {}/{}). Check predicate/'
                'selector, or reduce shard_count.'.format(dataset_url, cur_shard, shard_count))
        self._pieces = pieces
        self._cur_shard = cur_shard
        self._shard_count = shard_count
        self._shuffle_row_drop_partitions = shuffle_row_drop_partitions

        # (5) ventilator + pool — the item list is the same plan the serve
        # broker builds per stream (serve/plan.py)
        from petastorm_tpu.serve.plan import build_work_items
        from petastorm_tpu.workers.ventilator import ConcurrentVentilator
        items = build_work_items(len(pieces), shuffle_row_drop_partitions,
                                 worker_predicate)
        ventilator_resume = None
        if resume_state is not None:
            ventilator_resume = self._resolve_resume_state(
                resume_state, dataset_url, len(pieces), len(items),
                shuffle_row_drop_partitions)
        self._num_items = len(items)
        self._elastic_coordinator = None
        if elastic:
            # imports stay inside the branch: a plain reader must not even
            # load the elastic package (tier-1 guards this structurally)
            from petastorm_tpu.elastic import resolve_elastic
            from petastorm_tpu.elastic.coordinator import (ElasticCoordinator,
                                                           ElasticVentilator)
            elastic_config = resolve_elastic(elastic,
                                             dataset_path=self._dataset_path)
            self._elastic_coordinator = ElasticCoordinator(
                elastic_config, num_items=len(items), seed=seed,
                shuffle=shuffle_row_groups)
            self._ventilator = ElasticVentilator(
                pool.ventilate, items, self._elastic_coordinator,
                iterations=num_epochs,
                max_ventilation_queue_size=pool.workers_count + _VENTILATE_EXTRA_ROWGROUPS)
        else:
            self._ventilator = ConcurrentVentilator(
                pool.ventilate, items, iterations=num_epochs,
                max_ventilation_queue_size=pool.workers_count + _VENTILATE_EXTRA_ROWGROUPS,
                randomize_item_order=shuffle_row_groups, random_seed=seed, tag_items=True,
                resume_state=ventilator_resume)

        worker_args = {
            'dataset_path': self._dataset_path,
            'filesystem_factory': resolver.filesystem_factory(),
            'pieces': pieces,
            'schema': schema,
            'output_schema': output_schema,
            'transform_spec': transform_spec,
            'transformed_schema': self.transformed_schema,
            'ngram': ngram,
            'columnar_ngram': columnar_ngram,
            'cache': cache or NullCache(),
            'chunk_cache': self._chunk_cache_config,
            'telemetry': self._telemetry_config,
        }
        self._pool = pool
        # async chunk prefetcher: walks the ventilator's exact upcoming order
        # and mirrors remote chunks before workers demand them
        self._chunk_prefetcher = None
        if self._chunk_cache_config is not None:
            from petastorm_tpu.chunkstore.prefetch import ChunkPrefetcher
            prefetch_cols = [n for n in output_schema.fields]
            if worker_predicate is not None:
                # predicate columns are read (fused or Arrow) before anything
                # else in every filtered batch — mirror their chunks too
                prefetch_cols += [f for f in sorted(worker_predicate.get_fields())
                                  if f not in prefetch_cols]
            self._chunk_prefetcher = ChunkPrefetcher(
                self._ventilator, pieces, prefetch_cols,
                resolver.filesystem_factory(), self._chunk_cache_config)
            self._chunk_prefetcher.start()
        self._results_queue_reader = results_queue_reader_factory(self.transformed_schema)
        # checkpoint wiring (before pool.start — items may flow immediately):
        # the results-queue reader marks items delivered as their last row is
        # yielded; completion sentinels cover items that published no rows
        rqr = self._results_queue_reader
        if hasattr(rqr, 'delivered_callback'):
            rqr.delivered_callback = self._ventilator.mark_delivered
        if hasattr(rqr, 'on_item_done') and hasattr(pool, 'done_callback'):
            pool.done_callback = rqr.on_item_done
        self.last_row_consumed = False
        self._stopped = False
        pool.start(worker_class, worker_args, ventilator=self._ventilator)

        # closed-loop autotuning (docs/autotune.md): started AFTER the pool so
        # the first evidence window observes a running pipeline. Default off —
        # no recorder, no thread, no snapshot work.
        self.autotuner = None
        from petastorm_tpu.autotune import Autotuner, resolve_autotune
        autotune_config = resolve_autotune(autotune)
        if autotune_config is not None:
            self.autotuner = Autotuner(
                autotune_config, pool=pool,
                chunk_cache=self._chunk_cache_config,
                ventilator=self._ventilator,
                diagnostics_fn=lambda: self.diagnostics)
            self.autotuner.start()

    # -- piece filtering ----------------------------------------------------

    @staticmethod
    def _apply_predicate_to_pieces(pieces, predicate):
        """Partition-level pushdown: when every predicate field is a partition
        key, whole pieces are dropped with zero I/O and no worker predicate
        remains (reference reader.py:525-556)."""
        if predicate is None:
            return pieces, None
        predicate_fields = set(predicate.get_fields())
        if pieces and predicate_fields and all(
                predicate_fields <= set(p.partition_keys) for p in pieces):
            kept = [p for p in pieces
                    if predicate.do_include({f: p.partition_keys[f] for f in predicate_fields})]
            return kept, None
        return pieces, predicate

    @staticmethod
    def _apply_rowgroup_selector(dataset_url, pieces, selector, retry_policy=None):
        """Filter pieces through precomputed row-group indexes
        (reference reader.py:504-523). Selector indexes refer to the unfiltered
        piece enumeration, so this runs before sharding."""
        indexes = get_row_group_indexes(dataset_url, retry_policy=retry_policy)
        for name in selector.get_index_names():
            if name not in indexes:
                raise PetastormTpuError('Index {!r} does not exist in the dataset'.format(name))
        selected = selector.select_row_groups(indexes)
        return [p for i, p in enumerate(pieces) if i in selected]

    @staticmethod
    def _shard_piece_indices(num_pieces, cur_shard, shard_count):
        """Global indices of the pieces a round-robin shard keeps
        (reference reader.py:485-502). ``cur_shard=None`` keeps everything."""
        if cur_shard is None:
            return list(range(num_pieces))
        return [i for i in range(num_pieces) if i % shard_count == cur_shard]

    @staticmethod
    def _partition_pieces(pieces, cur_shard, shard_count):
        """Round-robin shard assignment (reference reader.py:485-502)."""
        keep = Reader._shard_piece_indices(len(pieces), cur_shard, shard_count)
        return [pieces[i] for i in keep]

    # -- iteration ----------------------------------------------------------

    @property
    def batched_output(self):
        return self._results_queue_reader.batched_output

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return self._results_queue_reader.read_next(self._pool)
        except EmptyResultError:
            self.last_row_consumed = True
            raise StopIteration

    next = __next__

    # -- checkpoint / resume ------------------------------------------------

    def _resolve_resume_state(self, state, dataset_url, num_pieces, num_items,
                              shuffle_row_drop_partitions):
        """Validate ``resume_state`` and produce the ventilator sub-state.

        Three paths: a state taken over the SAME piece/item selection AND the
        same ``cur_shard``/``shard_count`` (v2 states record the taking
        shard; v1 states predate the field and are trusted) resumes exactly
        (v1 semantics — replay order and RNG state preserved); a v2
        state over the same GLOBAL piece universe but different shard
        arithmetic resumes portably (the global row-group cursor is remapped
        onto this shard's local items — the N-hosts-checkpoint,
        M-hosts-restore path, usually via :func:`merge_resume_states`);
        anything else is rejected."""
        if not isinstance(state, dict) or state.get('version') not in (1, 2):
            raise ValueError('Unrecognized resume_state (expected a dict produced by '
                             'Reader.state_dict())')
        if state.get('dataset_url') not in (None, dataset_url):
            warnings.warn('resume_state was taken from {} but this reader opens {}; resuming '
                          'anyway since piece counts match (dataset may have moved)'.format(
                              state.get('dataset_url'), dataset_url))
        ckpt_shard = state.get('shard')
        shard_matches = (ckpt_shard is None
                         or list(ckpt_shard) == [self._cur_shard, self._shard_count])
        if state.get('num_pieces') == num_pieces and state.get('num_items') == num_items \
                and shard_matches:
            return state['ventilator']
        sdp = shuffle_row_drop_partitions
        if (state.get('version') == 2
                and state.get('num_global_pieces') == self._num_global_pieces
                and state.get('shuffle_row_drop_partitions') == sdp):
            # portable path: same dataset-wide selection, different shard
            # count. Keep the global (piece, drop-part) cells that land on
            # this shard; row-group granularity is preserved, the per-host
            # shuffle RNG is not (it described a different item list), so
            # remaining epochs reshuffle from the constructor seed.
            local_of = {g: lp for lp, g in enumerate(self._global_piece_indices)}
            replay = sorted(local_of[g] * sdp + part
                            for g, part in state.get('remaining_global_parts', ())
                            if g in local_of)
            return {'replay_indices': replay,
                    'iterations_remaining': state.get('iterations_remaining'),
                    'rng_state': None}
        if not shard_matches:
            raise ValueError(
                'resume_state was taken on shard {}/{} but this reader is shard {}/{}, and '
                'the state carries no matching portable cursor to remap — an exact resume '
                'would replay the other shard\'s positions. Restore each state onto its own '
                'shard, or merge all hosts\' states with merge_resume_states.'.format(
                    ckpt_shard[0], ckpt_shard[1], self._cur_shard, self._shard_count))
        raise ValueError(
            'resume_state does not match this reader: it was taken over {} pieces / {} work '
            'items ({} dataset-wide), but this reader selected {} / {} ({} dataset-wide). '
            'Construct the resumed reader with the same arguments (dataset, predicate, '
            'selector, shuffle_row_drop_partitions) as the checkpointed one; only the '
            'cur_shard/shard_count split may differ for v2 states.'.format(
                state.get('num_pieces'), state.get('num_items'),
                state.get('num_global_pieces'), num_pieces, num_items,
                self._num_global_pieces))

    def state_dict(self):
        """Snapshot the read position (picklable dict). Pass it as
        ``resume_state=`` to :func:`make_reader`/:func:`make_batch_reader`
        (called with otherwise-identical arguments) to continue reading where
        this reader left off — a capability the reference lacks entirely
        (SURVEY.md §5: "No checkpoint/resume of read state").

        Granularity is one row group: groups whose rows were all yielded are
        never re-read; groups in flight (including one partially yielded) are
        re-read in full on resume. At an epoch boundary the resume is exact.
        Remaining epochs re-shuffle from the checkpointed RNG state, so seeded
        runs produce the same row-group order they would have without the
        interruption.

        Version-2 states additionally carry the cursor in GLOBAL piece
        indices (``remaining_global_parts``), making them portable across
        shard counts: checkpoint on N hosts, :func:`merge_resume_states` the
        N dicts, restore on M hosts — every unfinished row group lands on
        exactly one new shard."""
        vent = self._ventilator.state_dict()
        sdp = self._shuffle_row_drop_partitions
        remaining = sorted({(int(self._global_piece_indices[i // sdp]), int(i % sdp))
                            for i in vent['replay_indices']})
        return {
            'version': 2,
            'dataset_url': self._dataset_url,
            'num_pieces': len(self._pieces),
            'num_items': self._num_items,
            'ventilator': vent,
            'num_global_pieces': self._num_global_pieces,
            'shard': [self._cur_shard, self._shard_count],
            'shuffle_row_drop_partitions': sdp,
            'remaining_global_parts': [list(cell) for cell in remaining],
            'iterations_remaining': vent['iterations_remaining'],
        }

    def reset(self):
        """Re-read the dataset for another ``num_epochs`` pass. Only valid after
        the previous pass finished (reference reader.py:416-440)."""
        if not self.last_row_consumed:
            raise PetastormTpuError(
                'reset() called mid-epoch. Consume all rows (or use num_epochs=None) '
                'before resetting.')
        self._ventilator.reset()
        self.last_row_consumed = False

    def stop(self):
        if self.autotuner is not None:
            self.autotuner.stop()
        if self._chunk_prefetcher is not None:
            self._chunk_prefetcher.stop()
        self._pool.stop()
        self._stopped = True

    def join(self):
        if self._chunk_prefetcher is not None:
            self._chunk_prefetcher.join()
        self._pool.join()

    @property
    def elastic_coordinator(self):
        """The :class:`~petastorm_tpu.elastic.coordinator.ElasticCoordinator`
        when this reader runs elastically, else None. Its ``status()`` dict
        (host, generation, members, alive) backs ``petastorm-tpu-diagnose
        --pod`` membership rows."""
        return self._elastic_coordinator

    @property
    def quarantined_items(self):
        """Structured error records of row groups quarantined under
        ``on_error='skip'`` (docs/robustness.md): dicts with
        seq/item/attempts/kind ('error'|'crash')/error/traceback/worker_id."""
        return getattr(self._pool, 'quarantined_items', [])

    @property
    def last_trace(self):
        """Virtual-root :class:`~petastorm_tpu.observability.TraceContext` of
        the most recently returned item, or None when tracing is off (telemetry
        level below ``'spans'``) or nothing was read yet. Downstream consumers
        (the loader's collate stage, infeed) link their spans to it so a
        batch's span tree stays causally connected across process boundaries
        (docs/observability.md, "Causal tracing")."""
        return getattr(self._pool, 'last_result_trace', None)

    @property
    def diagnostics(self):
        """Pipeline health view: the unified pool schema (``workers_count``,
        ``items_ventilated``/``items_completed``/``items_in_flight``,
        ``results_queue_depth``, and the recovery counters
        ``worker_restarts``/``items_requeued``/``items_quarantined`` —
        identical keys and units for every pool
        type), the telemetry registry's counters/gauges (this process's
        registry merged with the pool workers' shipped snapshots — per-stage
        ``stage_*_s`` timers, page-scan vs Arrow column counts, …), and the
        ``chunk_cache_*`` counters when the chunk store is engaged. See
        ``docs/observability.md`` for the full catalog."""
        snapshots = [obs.snapshot()]
        tele = getattr(self._pool, 'telemetry_snapshots', None)
        if tele is not None:  # custom/mock pools may predate the telemetry API
            snapshots.extend(tele())
        diag = obs.flatten_snapshot(obs.merge_snapshots(snapshots))
        diag.update(self._pool.diagnostics)
        if self._chunk_cache_config is not None:
            from petastorm_tpu.chunkstore import cache_diagnostics
            diag.update(cache_diagnostics(self._chunk_cache_config))
        return diag

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        if not self._stopped:
            self.stop()
            self.join()


def merge_resume_states(states):
    """Union per-host checkpoint dicts into ONE portable ``resume_state``.

    Checkpoint a pod by calling :meth:`Reader.state_dict` on every host,
    merge the dicts here, and pass the result as ``resume_state=`` to
    readers constructed with ANY shard count (including 1): the merged
    state carries the pod-wide set of unfinished row groups in global piece
    indices, and each restoring shard replays exactly the cells that land
    on it — no row group is dropped or read twice across the new pod.

    All states must come from readers over the same dataset-wide selection
    (same dataset, predicate, selector, ``shuffle_row_drop_partitions``) —
    pass EVERY host's state, or the missing host's unfinished groups are
    silently treated as delivered. Per-host shuffle RNG state is not
    portable across item lists, so remaining epochs reshuffle from the
    restoring readers' ``seed``.
    """
    states = list(states)
    if not states:
        raise ValueError('merge_resume_states needs at least one state')
    base = None
    cells = set()
    iterations = ()
    for state in states:
        if not isinstance(state, dict) or state.get('version') != 2:
            raise ValueError('merge_resume_states needs version-2 dicts from '
                             'Reader.state_dict(); got {!r}'.format(
                                 state.get('version') if isinstance(state, dict)
                                 else type(state).__name__))
        if base is None:
            base = state
        if (state.get('num_global_pieces') != base.get('num_global_pieces')
                or state.get('shuffle_row_drop_partitions')
                != base.get('shuffle_row_drop_partitions')):
            raise ValueError(
                'resume states disagree on the dataset-wide selection '
                '({} pieces x {} drop parts vs {} x {}): they were not taken '
                'over the same dataset/predicate/selector'.format(
                    base.get('num_global_pieces'),
                    base.get('shuffle_row_drop_partitions'),
                    state.get('num_global_pieces'),
                    state.get('shuffle_row_drop_partitions')))
        cells.update((int(g), int(part))
                     for g, part in state.get('remaining_global_parts', ()))
        iterations += (state.get('iterations_remaining'),)
    finite = [it for it in iterations if it is not None]
    return {
        'version': 2,
        'dataset_url': base.get('dataset_url'),
        # None sentinels: a merged state can never take the exact-resume
        # path — it always remaps through the portable global cursor
        'num_pieces': None,
        'num_items': None,
        'ventilator': None,
        'num_global_pieces': base.get('num_global_pieces'),
        'shard': None,
        'shuffle_row_drop_partitions': base.get('shuffle_row_drop_partitions'),
        'remaining_global_parts': [list(cell) for cell in sorted(cells)],
        'iterations_remaining': (None if len(finite) < len(iterations)
                                 else min(finite)),
    }
