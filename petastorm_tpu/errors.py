"""Framework-level exceptions.

Parity: /root/reference/petastorm/errors.py:16 (``NoDataAvailableError``).
"""


class PetastormTpuError(Exception):
    """Base class for all framework errors."""


class NoDataAvailableError(PetastormTpuError):
    """Raised when a reader configuration selects zero row groups.

    For example when ``shard_count`` exceeds the number of row groups, or a
    predicate/selector filters out every row group.
    """


class SchemaError(PetastormTpuError):
    """Raised for schema definition / encoding / decoding violations."""
