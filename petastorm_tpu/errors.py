"""Framework-level exceptions.

Parity: /root/reference/petastorm/errors.py:16 (``NoDataAvailableError``).

The worker-plane exceptions (``EmptyResultError``,
``TimeoutWaitingForResultError``, ``WorkerTerminationRequested``) historically
lived in ``workers/worker_base.py``; they are defined here so the whole
taxonomy roots at :class:`PetastormTpuError` and a consumer can catch one base
class. ``workers.worker_base`` keeps import aliases for compatibility.
"""


class PetastormTpuError(Exception):
    """Base class for all framework errors."""


class NoDataAvailableError(PetastormTpuError):
    """Raised when a reader configuration selects zero row groups.

    For example when ``shard_count`` exceeds the number of row groups, or a
    predicate/selector filters out every row group.
    """


class SchemaError(PetastormTpuError):
    """Raised for schema definition / encoding / decoding violations."""


class EmptyResultError(PetastormTpuError):
    """Raised by ``pool.get_results()`` when all ventilated work has been
    processed and no further results will arrive."""


class TimeoutWaitingForResultError(PetastormTpuError):
    """Raised when a pool timed out waiting for worker results. The message
    carries a per-worker liveness snapshot (alive/exitcode, heartbeat age,
    item ownership) when the pool tracks one."""


class WorkerTerminationRequested(PetastormTpuError):
    """Raised inside a worker's ``process`` by ``publish`` when the pool is
    stopping, to unwind the worker promptly."""


class PoisonItemError(PetastormTpuError):
    """A single work item failed (errored, or killed its worker process)
    ``max_item_retries + 1`` consecutive times. Raised under
    ``on_error='raise'``/``'retry'``; under ``on_error='skip'`` the item is
    quarantined instead (see ``docs/robustness.md``)."""


class WorkerPoolDepletedError(PetastormTpuError):
    """Worker respawn kept failing and the pool degraded to zero live
    workers — nothing is left to process ventilated items."""


class ProtocolViolation(PetastormTpuError):
    """An observed worker-pool event sequence the supervision protocol spec
    rejects (``petastorm_tpu/analysis/protocol/``): a reused dispatch id, a
    message for a never-issued id, a live/stale misclassification, a second
    completion for one item, or diverged accounting at epoch drain. Raised by
    the opt-in runtime conformance monitor (``docs/protocol.md``)."""


class ServeError(PetastormTpuError):
    """Base class for shared-reader-service errors (``docs/serve.md``)."""


class ConsumerEvictedError(ServeError):
    """This consumer lagged beyond the serve daemon's bound and was evicted
    from the broadcast ring so the rest of the fleet could keep flowing
    (``docs/serve.md`` — eviction policy). Re-attach with
    ``make_reader(serve=...)``, consume faster, or raise the daemon's
    ``ring_bytes``/lag bound. Carries ``tenant_id`` when known."""

    def __init__(self, message, tenant_id=None):
        super().__init__(message)
        self.tenant_id = tenant_id


class ServeDaemonDiedError(ServeError):
    """The serve daemon this consumer was attached to is gone (process died
    or its control endpoint vanished) — raised instead of hanging on a quiet
    ring. A fresh ``make_reader(serve=...)`` spawns a replacement daemon."""
