"""Mesh and sharding helpers.

The reference's only distributed mechanism is arithmetic sharding:
``index % shard_count == cur_shard`` (reference reader.py:485-502) — each
training node reads a disjoint row-group subset with zero communication. Here
the same share-nothing topology is derived from the JAX distributed runtime:

  * ``reader_shard_for_process()`` -> (jax.process_index(), jax.process_count())
    gives each pod host its reader shard;
  * each host's loader produces the host-local rows of a global batch;
  * ``make_global_batch`` assembles the global ``jax.Array`` via
    ``jax.make_array_from_process_local_data`` — XLA moves nothing between
    hosts for the data path (ICI/DCN are used only by model collectives).
"""

from __future__ import annotations

import numpy as np


def make_mesh(axis_names=('data',), axis_shapes=None, devices=None):
    """Build a ``jax.sharding.Mesh``.

    :param axis_names: mesh axis names, e.g. ``('data',)`` or ``('data', 'model')``
    :param axis_shapes: sizes per axis — a sequence aligned with ``axis_names``,
        or a dict ``{axis_name: size}``. ``None``/``-1`` entries (or a missing
        dict key — at most one) absorb the remaining devices. Default: all
        devices on the first axis.
    :param devices: device list (default ``jax.devices()``)
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axis_shapes is None:
        shapes = [n] + [1] * (len(axis_names) - 1)
    else:
        if isinstance(axis_shapes, dict):
            unknown_names = set(axis_shapes) - set(axis_names)
            if unknown_names:
                raise ValueError('axis_shapes names {} not in axis_names {}'.format(
                    sorted(unknown_names), axis_names))
            axis_shapes = [axis_shapes.get(name, -1) for name in axis_names]
        shapes = list(axis_shapes)
        if len(shapes) != len(axis_names):
            raise ValueError('axis_shapes and axis_names must have equal length')
        unknown = [i for i, s in enumerate(shapes) if s is None or s == -1]
        known = int(np.prod([s for s in shapes if s not in (None, -1)])) if shapes else 1
        if len(unknown) > 1:
            raise ValueError('At most one axis size may be None/-1')
        if unknown:
            if n % known:
                raise ValueError('{} devices not divisible by fixed axis product {}'.format(n, known))
            shapes[unknown[0]] = n // known
        if int(np.prod(shapes)) != n:
            raise ValueError('Mesh shape {} does not use all {} devices'.format(shapes, n))
    mesh_devices = np.asarray(devices).reshape(shapes)
    return Mesh(mesh_devices, axis_names)


def data_sharding(mesh, batch_axes='data'):
    """NamedSharding that splits the leading (batch) dimension over the given
    mesh axis (or axes)."""
    from jax.sharding import NamedSharding, PartitionSpec
    if isinstance(batch_axes, str):
        batch_axes = (batch_axes,)
    return NamedSharding(mesh, PartitionSpec(batch_axes))


def reader_shard_for_process():
    """(cur_shard, shard_count) for this host — pass straight to make_reader
    (replaces the reference's manual rank plumbing)."""
    import jax
    return jax.process_index(), jax.process_count()


def process_local_batch_size(global_batch_size):
    """Rows this host's loader must produce per global batch."""
    import jax
    if global_batch_size % jax.process_count():
        raise ValueError('global_batch_size {} not divisible by process_count {}'.format(
            global_batch_size, jax.process_count()))
    return global_batch_size // jax.process_count()


def make_global_batch(local_batch, sharding):
    """dict of host-local numpy arrays -> dict of global sharded ``jax.Array``.

    Non-numeric columns (strings, objects, datetimes) pass through as numpy —
    host-side metadata cannot live on device."""
    from petastorm_tpu.jax.infeed import stage_batch
    return stage_batch(local_batch, sharding)
