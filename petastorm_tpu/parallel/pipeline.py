"""Pipeline parallelism (pp): GPipe-style microbatched execution over a mesh
axis.

The last of the framework's parallelism strategies (with dp/tp/sp/ep): the
model's layers are split into S stages, one per device along ``stage_axis``;
the batch is split into M microbatches that flow through the stages in a
skewed schedule (stage s processes microbatch ``t - s`` at tick t), with
activations hopping stage-to-stage via ``lax.ppermute`` on ICI. After the
S + M - 1 fill-and-drain ticks every microbatch has traversed every stage.
Public recipe: GPipe (arXiv:1811.06965), expressed SPMD-style — all stages
run the same program under ``shard_map``, per-stage parameters are a stacked
``[S, ...]`` pytree sharded ``P(stage_axis)``, and validity masking replaces
control flow (XLA-friendly: one ``lax.fori_loop``, no data-dependent Python).

Bubble fraction is the usual (S-1)/(S+M-1) — raise ``num_microbatches`` to
amortize. Exactness: outputs equal running the stages sequentially (tested).

This module is the generic machinery; compose it with any per-stage function
(``stage_fn(stage_params, activation) -> activation``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from petastorm_tpu.jax.compat import legacy_shard_map_kwargs, shard_map


def pipeline_spmd(stage_fn, stage_params, microbatches, axis_name):
    """Run the pipeline from INSIDE ``shard_map`` over ``axis_name``.

    :param stage_fn: ``(stage_params, act) -> act`` applied by every stage to
        its current microbatch activation (same shapes in and out).
    :param stage_params: THIS stage's parameter pytree (the shard_map-local
        slice of the stacked parameters, leading stage axis already squeezed).
    :param microbatches: ``[M, mb, ...]`` the full microbatched input
        (replicated across stages; stage 0 ingests microbatch t at tick t).
    :returns: ``[M, mb, ...]`` outputs (identical on every stage).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    num_mb = microbatches.shape[0]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(t, carry):
        act, out = carry
        # stage 0 ingests a fresh microbatch; later stages use the activation
        # that arrived from the previous stage on the last tick
        mb_t = jnp.clip(t, 0, num_mb - 1)
        inp = jnp.where(stage == 0, microbatches[mb_t], act)
        y = stage_fn(stage_params, inp)
        # stage s holds microbatch t - s at tick t; outside [0, M) it is
        # pipeline bubble — computed SPMD anyway, writes masked out
        mb_i = t - stage
        valid = jnp.logical_and(mb_i >= 0, mb_i < num_mb)
        mb_w = jnp.clip(mb_i, 0, num_mb - 1)
        write = jnp.logical_and(valid, stage == n_stages - 1)
        out = out.at[mb_w].set(jnp.where(write, y, out[mb_w]))
        act = jax.lax.ppermute(y, axis_name, perm)
        return act, out

    # the carries are updated with device-varying values inside the loop, so
    # their initial values must already be device-varying (shard_map rejects a
    # replicated->varying carry): derive them from axis_index, which varies
    varying_zero = (jax.lax.axis_index(axis_name) * 0).astype(microbatches.dtype)
    act0 = jnp.zeros_like(microbatches[0]) + varying_zero
    out0 = jnp.zeros_like(microbatches) + varying_zero
    _, out = jax.lax.fori_loop(0, n_stages + num_mb - 1, tick, (act0, out0))
    # results live on the last stage; psum of masked copies replicates them
    return jax.lax.psum(jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out)),
                        axis_name)


def make_pipelined_apply(mesh, stage_fn, stage_axis='stage', num_microbatches=None):
    """A jitted ``(stacked_params, x) -> y`` running ``stage_fn`` as a
    pipeline over ``mesh[stage_axis]``.

    ``stacked_params``: pytree whose every leaf has a leading ``[S, ...]``
    stage axis (S = the mesh axis size) — sharded ``P(stage_axis)`` so each
    device holds only its own stage's parameters. ``x``: ``[B, ...]`` global
    batch with ``B`` divisible by ``num_microbatches`` (default S, the
    minimum that keeps every stage busy at steady state).
    """
    n_stages = mesh.shape[stage_axis]
    num_mb = num_microbatches or n_stages

    def _squeeze(tree):
        return jax.tree_util.tree_map(lambda leaf: leaf[0], tree)

    # P(stage_axis) is a pytree PREFIX: it applies to every parameter leaf
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(stage_axis), P()), out_specs=P(),
        **legacy_shard_map_kwargs())
    def _run(stacked_params, microbatches):
        # shard_map hands each stage its [1, ...] parameter slice
        return pipeline_spmd(stage_fn, _squeeze(stacked_params), microbatches,
                             stage_axis)

    @jax.jit
    def apply(stacked_params, x):
        # shard_map would happily split a WRONG-but-divisible stage count
        # (e.g. 4 stacked stages over a 2-device axis keeps stages 0 and 2
        # and silently computes garbage) — reject anything but an exact match
        for path, leaf in jax.tree_util.tree_flatten_with_path(stacked_params)[0]:
            if leaf.shape[0] != n_stages:
                raise ValueError(
                    'stacked stage params leaf {} has leading dim {} but the {!r} mesh '
                    'axis has {} stages; one stage per device is required'.format(
                        jax.tree_util.keystr(path), leaf.shape[0], stage_axis, n_stages))
        b = x.shape[0]
        if b % num_mb:
            raise ValueError('batch ({}) must be divisible by num_microbatches '
                             '({})'.format(b, num_mb))
        mb = x.reshape((num_mb, b // num_mb) + x.shape[1:])
        out = _run(stacked_params, mb)
        return out.reshape((b,) + out.shape[2:])

    return apply
