"""Mesh/sharding helpers: map the share-nothing reader topology onto a JAX mesh."""

from petastorm_tpu.parallel.mesh import (  # noqa: F401
    make_mesh, data_sharding, reader_shard_for_process, make_global_batch,
    process_local_batch_size,
)
from petastorm_tpu.parallel.pipeline import (  # noqa: F401
    make_pipelined_apply, pipeline_spmd,
)
