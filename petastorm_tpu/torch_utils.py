"""PyTorch adapter — capability parity with the reference's ``petastorm.pytorch``
(/root/reference/petastorm/pytorch.py:94-215): dtype sanitization, client-side
shuffling buffer, fixed-size collation, partial final batch, context-manager
stop. Torch is NOT the primary interface of this framework (the JAX loader is);
this adapter exists so reference users can migrate incrementally.
"""

from __future__ import annotations

from decimal import Decimal

import numpy as np

from petastorm_tpu.jax.loader import _rows_from_columnar_batch
from petastorm_tpu.shuffling_buffer import make_shuffling_buffer_factory

_TORCH_HOSTILE_PROMOTIONS = {
    np.dtype(np.uint16): np.int32,
    np.dtype(np.uint32): np.int64,
    np.dtype(np.uint64): np.int64,
}


def _sanitize_torch_types(row_dict):
    """Promote torch-hostile dtypes (reference pytorch.py:36-66)."""
    out = {}
    for name, value in row_dict.items():
        if isinstance(value, Decimal):
            value = float(value)
        elif isinstance(value, np.datetime64):
            value = value.astype('datetime64[ns]').astype(np.int64)
        elif isinstance(value, np.ndarray):
            if value.dtype in _TORCH_HOSTILE_PROMOTIONS:
                value = value.astype(_TORCH_HOSTILE_PROMOTIONS[value.dtype])
            elif value.dtype.kind in ('U', 'S', 'O'):
                raise TypeError(
                    'Field {!r} is a string/object array; torch tensors cannot hold it. '
                    'Exclude it via schema_fields or convert it in a TransformSpec.'.format(name))
        elif isinstance(value, np.generic) and value.dtype in _TORCH_HOSTILE_PROMOTIONS:
            value = value.astype(_TORCH_HOSTILE_PROMOTIONS[value.dtype])
        out[name] = value
    return out


def decimal_friendly_collate(batch):
    """default_collate that tolerates Decimals (reference pytorch.py:69-91)."""
    import torch
    from torch.utils.data._utils.collate import default_collate
    if isinstance(batch[0], Decimal):
        return torch.tensor([float(x) for x in batch], dtype=torch.float64)
    if isinstance(batch[0], dict):
        return {k: decimal_friendly_collate([b[k] for b in batch]) for k in batch[0]}
    return default_collate(batch)


def _collate_columns_to_torch(batch_columns):
    """Column block -> dict of torch tensors, one ``from_numpy`` per column
    (the columnar analog of ``decimal_friendly_collate`` on row dicts).

    Dtype handling is delegated to the JAX loader's shared column sanitizer
    (datetime -> int64 ns ticks incl. object datetime columns, Decimal ->
    float64, ``None`` cells preserved as object columns) so the two loaders
    cannot drift; columns torch fundamentally cannot hold (strings, nullable
    anything) raise with guidance."""
    import torch
    from petastorm_tpu.jax.loader import _sanitize_batch_columns
    batch = _sanitize_batch_columns(dict(batch_columns))
    out = {}
    for name, col in batch.items():
        if not isinstance(col, np.ndarray):
            raise TypeError('Field {!r} is not a numpy column'.format(name))
        if col.dtype == object or col.dtype.kind in ('U', 'S'):
            raise TypeError(
                'Field {!r} is a string/object/nullable column; torch tensors cannot hold '
                'it. Exclude it via schema_fields or convert it in a '
                'TransformSpec.'.format(name))
        if col.dtype in _TORCH_HOSTILE_PROMOTIONS:
            col = col.astype(_TORCH_HOSTILE_PROMOTIONS[col.dtype])
        # 'W': defensive — process-pool blocks are writable on all current
        # channels, but torch.from_numpy hard-requires writable memory, so any
        # read-only input (e.g. a user-supplied view) copies instead of raising
        out[name] = torch.from_numpy(np.require(col, requirements=['C', 'W']))
    return out


class DataLoader(object):
    """Iterates a reader, accumulates ``batch_size`` rows, collates to torch
    tensors; optional client-side shuffling buffer.

    Columnar readers (``make_batch_reader``, ``make_reader(output='columnar')``)
    with the default collate ride the block fast path: no per-row Python, one
    ``torch.from_numpy`` per column (same architecture as the JAX loader). A
    custom ``collate_fn`` keeps the row path — its contract is a list of row
    dicts."""

    def __init__(self, reader, batch_size=1, collate_fn=decimal_friendly_collate,
                 shuffling_queue_capacity=0, min_after_retrieve=None, seed=None):
        if reader.batched_output and getattr(reader, 'ngram', None) is not None:
            raise ValueError(
                'torch DataLoader does not support columnar NGram readers (nested window '
                "blocks); use make_reader(output='rows', ngram=...) here, or JaxDataLoader "
                'for the columnar window path.')
        self.reader = reader
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self._columnar = (reader.batched_output and collate_fn is decimal_friendly_collate)
        if self._columnar:
            from petastorm_tpu.columnar import FifoColumnarBuffer, ShuffledColumnarBuffer
            from petastorm_tpu.shuffling_buffer import default_min_after
            if shuffling_queue_capacity > 0:
                floor = default_min_after(shuffling_queue_capacity, min_after_retrieve)
                self._make_buffer = lambda: ShuffledColumnarBuffer(
                    shuffling_queue_capacity, floor, seed)
            else:
                self._make_buffer = FifoColumnarBuffer
        else:
            self._make_buffer = make_shuffling_buffer_factory(
                shuffling_queue_capacity, min_after_retrieve, seed, batch_size,
                batched_reader=reader.batched_output)

    def __iter__(self):
        if self._columnar:
            yield from self._iter_columnar()
            return
        buffer = self._make_buffer()
        pending = []
        for item in self.reader:
            if self.reader.batched_output:
                rows = _rows_from_columnar_batch(item)
                buffer.add_many([_sanitize_torch_types(r) for r in rows])
            else:
                buffer.add_many([_sanitize_torch_types(item._asdict())])
            while buffer.can_retrieve():
                pending.append(buffer.retrieve())
                if len(pending) == self.batch_size:
                    yield self.collate_fn(pending)
                    pending = []
        buffer.finish()
        while buffer.can_retrieve():
            pending.append(buffer.retrieve())
            if len(pending) == self.batch_size:
                yield self.collate_fn(pending)
                pending = []
        if pending:  # partial final batch (reference pytorch.py:182-192)
            yield self.collate_fn(pending)

    def _iter_columnar(self):
        buffer = self._make_buffer()
        bs = self.batch_size
        for item in self.reader:
            buffer.add_block(dict(item._asdict()))
            while buffer.can_emit(bs):
                yield _collate_columns_to_torch(buffer.emit(bs))
        buffer.finish()
        while buffer.size >= bs:
            yield _collate_columns_to_torch(buffer.emit(bs))
        if buffer.size:  # partial final batch (reference pytorch.py:182-192)
            yield _collate_columns_to_torch(buffer.emit(buffer.size))

    def stop(self):
        self.reader.stop()

    def join(self):
        self.reader.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self.stop()
        self.join()
