"""Fabric serving side: a small daemon thread that streams mirrored chunks.

One listener socket, one accept-loop daemon thread, one short-lived handler
thread per connection (a connection carries exactly one request/response
exchange, so handlers are bounded by the peer's deadline budget). The server
never fetches anything: it answers ``miss`` for chunks its local
:class:`~petastorm_tpu.chunkstore.store.ChunkStore` does not mirror, and the
asking client falls back to the object store — serving is strictly a cache
tier, never a dependency.

While a chunk is being read and streamed, its mirror file is pinned through
:meth:`ChunkStore.pin_for_send` (a manual borrow on the chunk's lifetime
slot), so the LRU evictor refuses it with a counted skip instead of
unlinking a file out from under an in-flight transfer.

Injected network faults (``faults.NetFaultPlan``) act at the payload-send
point: stalls sleep before the body (the window a chaos driver SIGKILLs a
peer in), resets abort the TCP stream mid-body, truncations close it cleanly
half-way, corruptions flip bytes — the content hash in the header is always
computed from the TRUE bytes, so every destructive fault is detectable on
the receiving side.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
import time

from petastorm_tpu import faults
from petastorm_tpu import observability as obs
from petastorm_tpu.fabric import protocol as P
from petastorm_tpu.observability import blackbox

logger = logging.getLogger(__name__)

#: accept-loop poll period: how fast stop() is noticed, NOT a request timeout
_ACCEPT_POLL_S = 0.2


class FabricServer(object):
    """Chunk-serving daemon for one host's chunkstore mirror.

    :param store: the host's :class:`ChunkStore` (chunks it mirrors locally)
    :param listen_host: bind address (default loopback; a real pod binds the
        pod-network interface)
    :param port: bind port (default 0 = ephemeral; read :attr:`endpoint`)
    :param io_timeout_s: per-socket-operation timeout for request/response IO
    :param request_deadline_s: end-to-end budget for one exchange — a client
        that stops reading cannot pin a handler thread forever
    :param on_request: optional callable ``(key)`` invoked when a request
        arrives (chaos drills use it to mark "a transfer is now in flight")
    """

    def __init__(self, store, listen_host='127.0.0.1', port=0,
                 io_timeout_s=2.0, request_deadline_s=30.0, on_request=None):
        self._store = store
        self._listen_host = listen_host
        self._port = int(port)
        self.io_timeout_s = float(io_timeout_s)
        self.request_deadline_s = float(request_deadline_s)
        self._on_request = on_request
        self._sock = None
        self._thread = None
        self._stop = threading.Event()
        self._endpoint = None

    @property
    def endpoint(self):
        """``(address, port)`` once started, else None."""
        return self._endpoint

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """Bind, listen, and start the accept-loop daemon thread."""
        if self._thread is not None:
            return self
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((self._listen_host, self._port))
            sock.listen(16)
            sock.settimeout(_ACCEPT_POLL_S)
        except BaseException:
            sock.close()
            raise
        self._sock = sock
        self._endpoint = sock.getsockname()[:2]
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._accept_loop, name='pstpu-fabric-serve', daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Stop accepting and close the listener. In-flight handler threads
        finish their (deadline-bounded) exchange on their own."""
        self._stop.set()
        thread = self._thread
        self._thread = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if thread is not None:
            thread.join(timeout=_ACCEPT_POLL_S * 10)
        self._endpoint = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- serving -------------------------------------------------------------

    def _accept_loop(self):
        sock = self._sock
        while not self._stop.is_set():
            try:
                sock.settimeout(_ACCEPT_POLL_S)
                conn, addr = sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed by stop()
            handler = threading.Thread(
                target=self._handle, args=(conn, addr),
                name='pstpu-fabric-handler', daemon=True)
            handler.start()

    def _handle(self, conn, addr):
        try:
            deadline = P.Deadline(self.request_deadline_s)
            msg = P.decode_message(
                P.recv_frame(conn, deadline, self.io_timeout_s))
            if msg.get('op') != 'get':
                P.send_frame(conn, P.encode_error(
                    'unsupported op {!r}'.format(msg.get('op'))),
                    deadline, self.io_timeout_s)
                return
            key = msg.get('key')
            length = int(msg.get('length') or 0)
            if not isinstance(key, str) or length <= 0:
                P.send_frame(conn, P.encode_error('malformed get request'),
                             deadline, self.io_timeout_s)
                return
            if self._on_request is not None:
                self._on_request(key)
            with obs.stage('fabric_serve', cat='fabric', bytes=length):
                self._serve_chunk(conn, key, length, deadline)
        except (OSError, P.FabricError) as e:
            # a dead/flaky CLIENT is not this host's problem: log and move on
            logger.debug('fabric handler for %s failed: %s', addr, e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_chunk(self, conn, key, length, deadline):
        with self._store.pin_for_send(key) as path:
            data = None
            if path is not None:
                try:
                    with open(path, 'rb') as f:
                        data = f.read()
                except OSError:
                    data = None
            if data is None or len(data) != length:
                P.send_frame(conn, P.encode_miss(), deadline, self.io_timeout_s)
                return
            # the header hash is ALWAYS of the true bytes: any injected
            # corruption/truncation below is detectable by the receiver
            digest = P.content_hash(data)
            action = faults.net_payload_action()
            if action is not None and action[0] == 'corrupt':
                corrupted = bytearray(data)
                mid = len(corrupted) // 2
                corrupted[mid] ^= 0xFF
                corrupted[0] ^= 0xFF
                data = bytes(corrupted)
            P.send_frame(conn, P.encode_ok(length, digest), deadline,
                         self.io_timeout_s)
            if action is not None and action[0] == 'stall':
                self._stall(action[1])
            if action is not None and action[0] == 'reset':
                P.send_all(conn, data[:length // 2], deadline, self.io_timeout_s)
                # RST instead of FIN: the client sees ECONNRESET mid-body
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                struct.pack('ii', 1, 0))
                return
            if action is not None and action[0] == 'truncate':
                P.send_all(conn, data[:length // 2], deadline, self.io_timeout_s)
                return
            P.send_all(conn, data, deadline, self.io_timeout_s)
        obs.count('fabric_chunks_served')
        obs.count('fabric_bytes_served', length)
        blackbox.record_event({'kind': 'fabric', 'op': 'serve', 'key': key,
                               'bytes': length})

    def _stall(self, stall_s):
        """Sleep in small slices so stop() is still honored mid-stall."""
        t_end = time.monotonic() + float(stall_s)
        while time.monotonic() < t_end and not self._stop.is_set():
            time.sleep(min(0.05, max(0.0, t_end - time.monotonic())))


__all__ = ['FabricServer']
