"""Fabric fetching side: peer-first chunk acquisition with graceful fallback.

:class:`FabricClient.fetch` is the ``chunkstore.store.PEER_SOURCE`` hook — it
runs on a chunkstore miss, inside the fetch stage, and returns the chunk
bytes (peer or object store) or ``None`` when a concurrent fetch of the same
chunk already populated the mirror (the single-flight follower path; the
store re-stats and treats it as a hit).

The failure contract is strict: a fabric problem NEVER fails the batch. Every
peer-path failure — refused connect, reset, timeout, torn stream, corrupt
payload, protocol garbage — lands in the object-store fallback
(``retry.fetch_range`` via the reader's ordinary ``fetch_fn``), and only a
genuine storage error from that fallback propagates. Peer bytes are admitted
only after the sha256 in the response header verifies; anything else is
discarded on the spot.

Per-peer circuit breakers (``breaker.py``) keep a flaky peer from taxing
every fetch: once open, requests skip the peer entirely (zero round trips)
until a half-open probe proves it healthy again.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import tempfile
import threading
import time

from petastorm_tpu import faults
from petastorm_tpu import observability as obs
from petastorm_tpu.fabric import protocol as P
from petastorm_tpu.fabric.breaker import CircuitBreaker
from petastorm_tpu.fabric.peers import rank_peers
from petastorm_tpu.observability import blackbox

logger = logging.getLogger(__name__)

#: how often the per-peer stats file is rewritten at most (plus on close)
_STATS_FLUSH_INTERVAL_S = 0.5

#: bound on how long a single-flight follower waits for the leader before
#: assuming it died and taking over the fetch itself
_INFLIGHT_WAIT_S = 60.0


def _new_peer_stats():
    return {'hits': 0, 'failures': 0, 'fallbacks': 0, 'bytes': 0,
            'latency_sum': 0.0, 'latency_n': 0}


class FabricClient(object):
    """Peer-first chunk fetcher for one host.

    :param store: the host's :class:`ChunkStore` (for digests + the
        single-flight follower's populated check)
    :param peer_registry: a :class:`~petastorm_tpu.fabric.peers.PeerRegistry`
        over the pod's membership leases
    :param coord_dir: the pod coordination directory; per-peer stats are
        flushed under ``<coord_dir>/fabric/stats/`` for ``diagnose --fabric``
    :param deadline_s: end-to-end budget for one peer transfer (connect +
        request + response + payload); what remains after a failed peer
        attempt is handed to the fallback as its retry deadline
    :param io_timeout_s: per-socket-operation timeout
    :param connect_timeout_s: TCP connect timeout (kept tight — a dead peer
        must cost little)
    :param failure_threshold: consecutive failures that open a peer's breaker
    :param breaker_reset_s: open-breaker cooldown before a half-open probe
    :param monitor: optional :class:`~petastorm_tpu.analysis.protocol.
        monitor.FabricMonitor` asserting protocol invariants at runtime
    """

    def __init__(self, store, peer_registry, coord_dir, deadline_s=10.0,
                 io_timeout_s=2.0, connect_timeout_s=1.0,
                 failure_threshold=3, breaker_reset_s=5.0, monitor=None):
        self._store = store
        self._peers = peer_registry
        self._coord_dir = coord_dir
        self.deadline_s = float(deadline_s)
        self.io_timeout_s = float(io_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self._failure_threshold = int(failure_threshold)
        self._breaker_reset_s = float(breaker_reset_s)
        self._monitor = monitor
        self._lock = threading.Lock()
        self._breakers = {}     # peer host -> CircuitBreaker
        self._inflight = {}     # chunk digest -> threading.Event
        self._peer_stats = {}   # peer host -> counter dict
        self._last_flush = 0.0
        self._stats_dir = os.path.join(coord_dir, 'fabric', 'stats')
        self._stats_path = os.path.join(
            self._stats_dir, '{}-pid{}.json'.format(
                peer_registry.host_id, os.getpid()))

    # -- the PEER_SOURCE hook ------------------------------------------------

    def fetch(self, key, length, fetch_fn):
        """Produce ``length`` bytes for ``key``: peer first, object store on
        any fabric trouble, ``None`` when a concurrent fetch won the race.

        Exactly one thread per chunk runs the transfer (single-flight):
        concurrent callers wait, then report ``None`` so the chunkstore
        re-stats the now-populated mirror instead of fetching twice.
        """
        digest = self._store.digest(key)
        while True:
            with self._lock:
                event = self._inflight.get(digest)
                if event is None:
                    self._inflight[digest] = threading.Event()
                    break
            event.wait(timeout=_INFLIGHT_WAIT_S)
            if self._store.contains(key, length):
                return None  # leader populated it; ensure() re-stats as a hit
            # leader failed or died without populating: loop to take over
        try:
            return self._fetch_once(key, length, digest, fetch_fn)
        finally:
            with self._lock:
                event = self._inflight.pop(digest, None)
            if event is not None:
                event.set()

    def _fetch_once(self, key, length, digest, fetch_fn):
        if self._monitor is not None:
            # reaching here means ensure() missed: any earlier population of
            # this chunk has been evicted, so populating again is legitimate
            self._monitor.on_invalidate(digest)
        deadline = P.Deadline(self.deadline_s)
        peer = self._pick_peer(digest)
        if peer is not None:
            t0 = time.monotonic()
            try:
                with obs.stage('fabric_peer_fetch', cat='fabric',
                               bytes=length, peer=peer.host):
                    data = self._fetch_from_peer(peer, key, length, deadline)
            except (OSError, P.FabricError) as e:
                self._note_failure(peer, e)
            else:
                if data is not None:
                    self._note_success(peer, key, digest, length,
                                       time.monotonic() - t0)
                    return data
                # miss: the peer is healthy, it just does not mirror this
                # chunk — no breaker penalty, straight to the fallback
        return self._fallback(key, length, peer, deadline, fetch_fn)

    # -- peer path -----------------------------------------------------------

    def _pick_peer(self, digest):
        """The rendezvous-best alive peer whose breaker admits a request."""
        for peer in rank_peers(digest, self._peers.alive_peers()):
            if self._breaker_for(peer.host).allow():
                if self._monitor is not None:
                    self._monitor.on_request(peer.host, allowed=True)
                return peer
        return None

    def _fetch_from_peer(self, peer, key, length, deadline):
        faults.on_net_connect()
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.settimeout(deadline.op_timeout(self.connect_timeout_s))
            sock.connect(peer.endpoint)
            P.send_frame(sock, P.encode_request(key, length), deadline,
                         self.io_timeout_s)
            msg = P.decode_message(
                P.recv_frame(sock, deadline, self.io_timeout_s))
            status = msg.get('status')
            if status == 'miss':
                return None
            if status != 'ok':
                raise P.FabricProtocolError('peer {} answered {}: {}'.format(
                    peer.host, status, msg.get('message')))
            n = int(msg.get('length') or 0)
            if n != length:
                raise P.FabricProtocolError(
                    'peer {} offered {} bytes for a {} byte chunk'.format(
                        peer.host, n, length))
            data = P.recv_exactly(sock, n, deadline, self.io_timeout_s)
            if P.content_hash(data) != msg.get('sha256'):
                raise P.FabricProtocolError(
                    'content hash mismatch from peer {} — {} bytes '
                    'discarded'.format(peer.host, n))
            return data
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _breaker_for(self, host):
        with self._lock:
            breaker = self._breakers.get(host)
            if breaker is None:
                breaker = CircuitBreaker(
                    failure_threshold=self._failure_threshold,
                    reset_after_s=self._breaker_reset_s)
                self._breakers[host] = breaker
            return breaker

    def _note_success(self, peer, key, digest, length, latency_s):
        self._breaker_for(peer.host).record_success()
        obs.count('fabric_peer_hits')
        obs.count('fabric_bytes_from_peers', length)
        if self._monitor is not None:
            self._monitor.on_populate(digest, verified=True)
            self._monitor.on_outcome(key, 'peer')
        blackbox.record_event({'kind': 'fabric', 'op': 'peer_hit',
                               'peer': peer.host, 'key': key,
                               'bytes': length,
                               'latency_ms': round(latency_s * 1e3, 3)})
        with self._lock:
            stats = self._peer_stats.setdefault(peer.host, _new_peer_stats())
            stats['hits'] += 1
            stats['bytes'] += length
            stats['latency_sum'] += latency_s
            stats['latency_n'] += 1
        self._flush_stats()

    def _note_failure(self, peer, error):
        tripped = self._breaker_for(peer.host).record_failure()
        logger.debug('fabric fetch from peer %s failed: %s', peer.host, error)
        if tripped:
            obs.count('fabric_breaker_open')
            blackbox.record_event({'kind': 'fabric', 'op': 'breaker_open',
                                   'peer': peer.host, 'error': str(error)[:200]})
        with self._lock:
            stats = self._peer_stats.setdefault(peer.host, _new_peer_stats())
            stats['failures'] += 1
        self._flush_stats()

    # -- fallback path -------------------------------------------------------

    def _fallback(self, key, length, peer, deadline, fetch_fn):
        obs.count('fabric_fallbacks')
        blackbox.record_event({'kind': 'fabric', 'op': 'fallback', 'key': key,
                               'peer': peer.host if peer else None})
        with self._lock:
            host = peer.host if peer is not None else '-'
            stats = self._peer_stats.setdefault(host, _new_peer_stats())
            stats['fallbacks'] += 1
        self._flush_stats()
        try:
            with obs.stage('fabric_fallback', cat='fabric', bytes=length):
                remaining = deadline.remaining()
                if remaining > 0 and getattr(fetch_fn, 'supports_deadline',
                                             False):
                    data = fetch_fn(deadline_s=remaining)
                else:
                    # budget burned on a stalled peer (or plain fetch_fn):
                    # the fallback still runs under its own retry policy —
                    # degradation must not turn into failure
                    data = fetch_fn()
        except Exception:
            if self._monitor is not None:
                self._monitor.on_outcome(key, 'error')
            raise  # a genuine storage error: the one thing we do propagate
        if self._monitor is not None:
            digest = self._store.digest(key)
            self._monitor.on_populate(digest, verified=True)
            self._monitor.on_outcome(key, 'fallback')
        return data

    # -- stats for diagnose --------------------------------------------------

    def _flush_stats(self, force=False):
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_flush < _STATS_FLUSH_INTERVAL_S:
                return
            self._last_flush = now
            snapshot = {
                'host': self._peers.host_id,
                'peers': {h: dict(s) for h, s in self._peer_stats.items()},
                'breakers': {h: b.state for h, b in self._breakers.items()},
            }
        try:
            os.makedirs(self._stats_dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self._stats_dir, suffix='.tmp')
            with os.fdopen(fd, 'w') as f:
                json.dump(snapshot, f)
            os.replace(tmp, self._stats_path)
        except OSError as e:
            logger.debug('fabric stats flush failed: %s', e)

    def close(self):
        self._flush_stats(force=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


__all__ = ['FabricClient']
