"""Per-peer circuit breaker: stop hammering a peer that keeps failing.

Classic three-state breaker (``docs/fabric.md``, "Breaker semantics"):

* **closed** — requests flow; each success resets the consecutive-failure
  count, each failure increments it; K consecutive failures trip the breaker
  **open**;
* **open** — requests are refused locally (the client goes straight to the
  object-store fallback, costing zero network round trips on a peer that is
  known-bad) until ``reset_after_s`` has elapsed;
* **half-open** — after the cooldown, exactly ONE probe request is let
  through: success closes the breaker, failure re-opens it (and restarts the
  cooldown clock).

The breaker is deliberately per-peer and local — no coordination, no shared
state: each host learns its own view of which peers are healthy, which is
exactly the view that predicts ITS next request's fate.
"""

from __future__ import annotations

import threading
import time

CLOSED, OPEN, HALF_OPEN = 'closed', 'open', 'half-open'


class CircuitBreaker(object):
    """Thread-safe per-peer breaker.

    :param failure_threshold: consecutive failures that trip the breaker open
    :param reset_after_s: cooldown before an open breaker admits one probe
    :param clock: monotonic time source (tests inject a fake)
    """

    def __init__(self, failure_threshold=3, reset_after_s=5.0,
                 clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError('failure_threshold must be >= 1, got {}'.format(
                failure_threshold))
        self.failure_threshold = int(failure_threshold)
        self.reset_after_s = float(reset_after_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False

    @property
    def state(self):
        with self._lock:
            return self._state

    def allow(self):
        """May a request be sent to this peer right now?

        Open breakers whose cooldown elapsed transition to half-open and
        admit exactly one probe; further calls are refused until that probe
        resolves through :meth:`record_success` / :meth:`record_failure`.
        """
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.reset_after_s:
                    return False
                self._state = HALF_OPEN
                self._probe_in_flight = True
                return True
            # HALF_OPEN: one probe at a time
            if self._probe_in_flight:
                return False
            self._probe_in_flight = True
            return True

    def record_success(self):
        """A request to this peer completed (bytes verified)."""
        with self._lock:
            self._state = CLOSED
            self._failures = 0
            self._probe_in_flight = False

    def record_failure(self):
        """A request to this peer failed (connect/timeout/torn/corrupt).
        Returns True when THIS failure tripped the breaker open — the
        caller's signal to count a ``fabric_breaker_open`` transition."""
        with self._lock:
            self._failures += 1
            self._probe_in_flight = False
            if self._state == HALF_OPEN or \
                    self._failures >= self.failure_threshold:
                opened = self._state != OPEN
                self._state = OPEN
                self._opened_at = self._clock()
                return opened
            return False


__all__ = ['CLOSED', 'CircuitBreaker', 'HALF_OPEN', 'OPEN']
