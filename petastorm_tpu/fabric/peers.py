"""Peer discovery for the chunk fabric: ride the elastic lease machinery.

There is deliberately NO new discovery protocol here. A fabric host publishes
its endpoint as an annotation inside the membership lease it already renews
(``elastic/membership.py``): ``notes = {'fabric': [address, port]}``. Peer
liveness is therefore EXACTLY lease liveness — a host whose lease expires is
a dead peer, a host that left gracefully disappears with its lease, and the
false-expiry window documented for elastic sharding applies verbatim.

Peer selection uses rendezvous (highest-random-weight) hashing of
``(chunk digest, peer host)``: every host independently ranks the same peers
in the same order for a given chunk, so a pod's fetches for one chunk
converge on one peer (its mirror warms once and serves everyone) while the
overall key space spreads evenly across peers — and a peer's death only
remaps the chunks it owned.
"""

from __future__ import annotations

import hashlib


class PeerInfo(object):
    """One live fabric peer: lease identity + published endpoint."""

    __slots__ = ('host', 'address', 'port')

    def __init__(self, host, address, port):
        self.host = host
        self.address = address
        self.port = int(port)

    @property
    def endpoint(self):
        return (self.address, self.port)

    def __repr__(self):
        return 'PeerInfo(host={!r}, endpoint={}:{})'.format(
            self.host, self.address, self.port)


class PeerRegistry(object):
    """Live fabric peers, read straight off the membership lease scan.

    :param membership: a :class:`~petastorm_tpu.elastic.membership.
        MembershipRegistry` over the pod's coordination directory. It does
        not need to be joined — a fetch-only process (e.g. a spawned worker)
        scans leases without holding one.
    """

    def __init__(self, membership):
        self._membership = membership

    @property
    def host_id(self):
        return self._membership.host_id

    def alive_peers(self):
        """Every OTHER host with a live lease and a published fabric
        endpoint, sorted by host id (deterministic iteration order)."""
        peers = []
        for m in self._membership.scan():
            if not m.alive or m.host == self._membership.host_id:
                continue
            endpoint = m.notes.get('fabric') if m.notes else None
            if (not isinstance(endpoint, (list, tuple)) or len(endpoint) != 2):
                continue
            try:
                peers.append(PeerInfo(m.host, str(endpoint[0]), int(endpoint[1])))
            except (TypeError, ValueError):
                continue
        peers.sort(key=lambda p: p.host)
        return peers


def rank_peers(digest, peers):
    """Rendezvous-hash ranking of ``peers`` for one chunk ``digest``: best
    candidate first. Stable across hosts for identical peer sets."""
    def weight(peer):
        h = hashlib.sha1('{}|{}'.format(digest, peer.host).encode('utf-8'))
        return h.hexdigest()

    return sorted(peers, key=weight, reverse=True)


__all__ = ['PeerInfo', 'PeerRegistry', 'rank_peers']
