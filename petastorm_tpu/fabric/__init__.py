"""Fault-tolerant peer-to-peer chunk fabric (``docs/fabric.md``).

In a pod, every host mirrors the column chunks it reads into its local
:mod:`~petastorm_tpu.chunkstore`. Without the fabric, N hosts reading the
same dataset pay N object-store GETs per chunk. With it, a host that misses
a chunk first asks a pod peer that already mirrors it — one object-store
read plus N-1 LAN copies — and degrades to the ordinary object-store read on
ANY fabric trouble. The fabric is strictly an optimization tier: a dead,
slow, flaky, or lying peer can cost latency, never correctness and never a
failed batch.

The moving parts:

* :mod:`~petastorm_tpu.fabric.protocol` — length-prefixed wire protocol,
  per-operation timeouts under an end-to-end :class:`Deadline` budget;
* :mod:`~petastorm_tpu.fabric.peers` — peer discovery riding the elastic
  membership leases (endpoint published as a lease annotation; expired
  lease = dead peer; NO second discovery protocol);
* :mod:`~petastorm_tpu.fabric.breaker` — per-peer circuit breaker;
* :mod:`~petastorm_tpu.fabric.server` — chunk-serving daemon thread
  (mirror files pinned against eviction for the duration of a send);
* :mod:`~petastorm_tpu.fabric.client` — peer-first fetch with sha256
  verification, single-flight per chunk, and object-store fallback.

The protocol's invariants (at-most-once population per host, verified-or-
discarded bytes, guaranteed termination, breaker discipline) are model-
checked by ``analysis/protocol/fabric_spec.py`` (``petastorm-tpu-modelcheck
--fabric``) and assertable at runtime via ``PSTPU_FABRIC_MONITOR=1``.

Wiring: :func:`start_node` builds a :class:`FabricNode` (store + optional
server + membership + client), :func:`install` points the chunkstore's
``PEER_SOURCE`` hook at its client. Reader worker processes receive the
node's :meth:`FabricConfig.for_worker` config through the process pool's
``worker_setup_args`` and install a fetch-only node (no server, no lease —
the HOST owns the pod's lease and serving socket).
"""

from __future__ import annotations

import threading

from petastorm_tpu.fabric.breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from petastorm_tpu.fabric.client import FabricClient
from petastorm_tpu.fabric.peers import PeerInfo, PeerRegistry, rank_peers
from petastorm_tpu.fabric.protocol import (Deadline, FabricError,
                                           FabricProtocolError, FabricTimeout)
from petastorm_tpu.fabric.server import FabricServer


class FabricConfig(object):
    """Picklable description of one host's fabric participation.

    :param coord_dir: the pod's shared coordination directory (the same one
        elastic membership uses)
    :param host_id: this host's stable identity in the pod
    :param cache: the host's :class:`~petastorm_tpu.chunkstore.store.
        ChunkCacheConfig` (the mirror the fabric serves and populates)
    :param serve: start a :class:`FabricServer` over the mirror
    :param join: hold a membership lease (publishing the endpoint when
        serving); fetch-only processes scan leases without holding one
    :param listen_host: serving bind address
    :param port: serving bind port (0 = ephemeral)
    :param lease_s: membership lease duration
    :param deadline_s: end-to-end budget per peer transfer
    :param io_timeout_s: per-socket-operation timeout
    :param connect_timeout_s: TCP connect timeout
    :param failure_threshold: consecutive failures opening a peer's breaker
    :param breaker_reset_s: open-breaker cooldown before a half-open probe
    """

    def __init__(self, coord_dir, host_id, cache, serve=True, join=True,
                 listen_host='127.0.0.1', port=0, lease_s=5.0,
                 deadline_s=10.0, io_timeout_s=2.0, connect_timeout_s=1.0,
                 failure_threshold=3, breaker_reset_s=5.0):
        self.coord_dir = coord_dir
        self.host_id = str(host_id)
        self.cache = cache
        self.serve = bool(serve)
        self.join = bool(join)
        self.listen_host = listen_host
        self.port = int(port)
        self.lease_s = float(lease_s)
        self.deadline_s = float(deadline_s)
        self.io_timeout_s = float(io_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.failure_threshold = int(failure_threshold)
        self.breaker_reset_s = float(breaker_reset_s)

    def for_worker(self):
        """The fetch-only clone shipped to reader worker processes: no
        server (the host already serves this mirror) and no lease (the pod
        has one member per host, not per process)."""
        return FabricConfig(
            coord_dir=self.coord_dir, host_id=self.host_id, cache=self.cache,
            serve=False, join=False, listen_host=self.listen_host,
            port=self.port, lease_s=self.lease_s, deadline_s=self.deadline_s,
            io_timeout_s=self.io_timeout_s,
            connect_timeout_s=self.connect_timeout_s,
            failure_threshold=self.failure_threshold,
            breaker_reset_s=self.breaker_reset_s)

    def __repr__(self):
        return ('FabricConfig(host_id={!r}, coord_dir={!r}, serve={}, '
                'join={})'.format(self.host_id, self.coord_dir, self.serve,
                                  self.join))


class FabricNode(object):
    """One process's fabric presence: store + optional server + membership +
    client, started and stopped as a unit."""

    def __init__(self, config, monitor=None, on_request=None):
        from petastorm_tpu.chunkstore.store import open_store

        self.config = config
        self._on_request = on_request
        self._monitor = monitor
        self.store = open_store(config.cache)
        self.server = None
        self.membership = None
        self.client = None
        self._started = False

    def start(self):
        from petastorm_tpu.analysis.protocol.monitor import \
            fabric_monitor_from_env

        if self._started:
            return self
        cfg = self.config
        annotations = None
        if cfg.serve:
            self.server = FabricServer(
                self.store, listen_host=cfg.listen_host, port=cfg.port,
                io_timeout_s=cfg.io_timeout_s,
                on_request=self._on_request).start()
            annotations = {'fabric': list(self.server.endpoint)}
        from petastorm_tpu.elastic.membership import MembershipRegistry
        self.membership = MembershipRegistry(
            cfg.coord_dir, cfg.host_id, lease_s=cfg.lease_s,
            annotations=annotations)
        if cfg.join:
            self.membership.join()
        self.client = FabricClient(
            self.store, PeerRegistry(self.membership), cfg.coord_dir,
            deadline_s=cfg.deadline_s, io_timeout_s=cfg.io_timeout_s,
            connect_timeout_s=cfg.connect_timeout_s,
            failure_threshold=cfg.failure_threshold,
            breaker_reset_s=cfg.breaker_reset_s,
            monitor=fabric_monitor_from_env(self._monitor,
                                            'fabric:' + cfg.host_id))
        self._started = True
        return self

    def stop(self):
        if not self._started:
            return
        self._started = False
        if self.server is not None:
            self.server.stop()
            self.server = None
        if self.membership is not None and self.config.join:
            self.membership.leave()
        if self.client is not None:
            self.client.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def start_node(config, monitor=None, on_request=None):
    """Build and start a :class:`FabricNode` for ``config``."""
    return FabricNode(config, monitor=monitor, on_request=on_request).start()


# -- process-wide installation ------------------------------------------------

_install_lock = threading.Lock()
_active_node = None


def install(node):
    """Point the chunkstore's ``PEER_SOURCE`` hook at ``node``'s client:
    from here on, every chunk miss in this process tries the fabric first.
    Accepts a :class:`FabricNode` (tracked for :func:`shippable_config`) or a
    bare :class:`FabricClient`."""
    global _active_node
    from petastorm_tpu.chunkstore import store as store_mod

    client = node.client if isinstance(node, FabricNode) else node
    with _install_lock:
        store_mod.PEER_SOURCE = client.fetch
        _active_node = node if isinstance(node, FabricNode) else None


def uninstall():
    """Detach the fabric from the chunkstore (misses go straight to the
    object store again)."""
    global _active_node
    from petastorm_tpu.chunkstore import store as store_mod

    with _install_lock:
        store_mod.PEER_SOURCE = None
        _active_node = None


def installed_node():
    """The currently installed :class:`FabricNode`, if any."""
    with _install_lock:
        return _active_node


def shippable_config():
    """The worker-shippable (fetch-only) config of the installed node, or
    None when no fabric is installed — the process pool calls this when
    assembling ``worker_setup_args`` so reader workers join the fabric
    automatically, exactly like fault plans and flight recorders ship."""
    with _install_lock:
        node = _active_node
    if node is None:
        return None
    return node.config.for_worker()


def install_from_config(config, monitor=None):
    """Worker-side bootstrap: start a (fetch-only) node for a shipped config
    and install it. Returns the node."""
    node = start_node(config, monitor=monitor)
    install(node)
    return node


__all__ = ['CLOSED', 'CircuitBreaker', 'Deadline', 'FabricClient',
           'FabricConfig', 'FabricError', 'FabricNode', 'FabricProtocolError',
           'FabricServer', 'FabricTimeout', 'HALF_OPEN', 'OPEN', 'PeerInfo',
           'PeerRegistry', 'install', 'install_from_config', 'installed_node',
           'rank_peers', 'shippable_config', 'start_node', 'uninstall']
