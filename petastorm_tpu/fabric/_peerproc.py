"""One fabric peer as a subprocess — the chaos-test SIGKILL target.

``python -m petastorm_tpu.fabric._peerproc --url ... --coord ... --host pA
--cache-root ...`` warms its local chunk mirror by reading the dataset once,
then joins the pod membership (publishing its fabric endpoint as a lease
annotation) and serves chunks until killed. The chaos drill
(``tests/test_fabric.py``) arms ``--stall-s`` so every payload send sleeps
first, waits for ``--request-marker`` to appear (a transfer is now in
flight), and SIGKILLs this process mid-transfer — proving the fetching side
degrades to the object store and still populates its mirror exactly once.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _parse_args(argv):
    parser = argparse.ArgumentParser(prog='pstpu-fabric-peer')
    parser.add_argument('--url', required=True)
    parser.add_argument('--coord', required=True)
    parser.add_argument('--host', required=True)
    parser.add_argument('--cache-root', required=True)
    parser.add_argument('--lease-s', type=float, default=2.0)
    parser.add_argument('--stall-s', type=float, default=0.0,
                        help='stall every payload send this long (the '
                             'SIGKILL window for the chaos drill)')
    parser.add_argument('--request-marker', default=None,
                        help='file touched when the first request arrives')
    parser.add_argument('--ready-file', default=None,
                        help='touched once warmed, joined, and serving')
    return parser.parse_args(argv)


def main(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    from petastorm_tpu import fabric, faults, make_reader
    from petastorm_tpu.chunkstore import ChunkCacheConfig
    from petastorm_tpu.observability import blackbox

    blackbox.maybe_enable('fabric-peer-' + args.host)
    cache = ChunkCacheConfig(args.cache_root)
    # warm the mirror: one full epoch mirrors every cacheable chunk locally
    with make_reader(args.url, reader_pool_type='dummy',
                     shuffle_row_groups=False, chunk_cache=cache) as reader:
        for _ in reader:
            pass

    if args.stall_s:
        faults.install_net(faults.NetFaultPlan(stall_payloads=1_000_000,
                                               stall_s=args.stall_s))

    def on_request(key):
        if args.request_marker:
            tmp = args.request_marker + '.tmp'
            with open(tmp, 'w') as f:
                f.write(key)
            os.replace(tmp, args.request_marker)

    node = fabric.start_node(
        fabric.FabricConfig(args.coord, args.host, cache, serve=True,
                            join=True, lease_s=args.lease_s),
        on_request=on_request)
    try:
        if args.ready_file:
            tmp = args.ready_file + '.tmp'
            with open(tmp, 'w') as f:
                f.write(str(os.getpid()))
            os.replace(tmp, args.ready_file)
        while True:  # serve until SIGKILLed (or terminated) by the driver
            time.sleep(0.2)
    finally:
        node.stop()
    return 0


if __name__ == '__main__':
    sys.exit(main())
