"""Wire protocol of the chunk-transfer fabric (``docs/fabric.md``).

One request/response exchange per connection, built from length-prefixed
frames so a reader always knows exactly how many bytes remain — a torn TCP
stream surfaces as a :class:`FabricProtocolError` (truncated frame), never as
silently short data:

* **frame** — ``b'PTFB'`` magic + big-endian u32 body length + body;
* **request** — one JSON frame ``{"op": "get", "key": ..., "length": ...}``;
* **response** — one JSON header frame (``{"status": "ok", "length": N,
  "sha256": hex}`` / ``{"status": "miss"}`` / ``{"status": "error",
  "message": ...}``), then — for ``ok`` only — exactly N raw payload bytes.

The payload travels OUTSIDE the JSON frame so chunk bytes are never
base64-inflated, and its sha256 rides the header so the receiver can verify
content integrity before the bytes are allowed anywhere near the mirror.

Every socket operation here runs with an explicit per-operation timeout AND
under a :class:`Deadline` — the end-to-end budget one transfer may spend
across all of its connects, sends, and recvs. Helpers take the deadline as a
parameter; lint rule PT1500 (``analysis/fabric_lints.py``) rejects any fabric
code that touches a socket without both.
"""

from __future__ import annotations

import hashlib
import json
import struct
import time

from petastorm_tpu.errors import PetastormTpuError

MAGIC = b'PTFB'
VERSION = 1

#: hard bound on any frame body — a corrupt length prefix must not make the
#: receiver allocate unbounded memory (column chunks are row-group sized)
MAX_FRAME_BYTES = 512 * 2 ** 20

_HEADER = struct.Struct('>4sI')

#: recv granularity: large enough to amortize syscalls, small enough that a
#: per-operation timeout stays responsive on a stalled link
_IO_CHUNK = 256 * 1024


class FabricError(PetastormTpuError):
    """Base class of chunk-fabric transfer failures (all retryable via the
    object-store fallback — a fabric error must never fail the batch)."""


class FabricTimeout(FabricError):
    """A transfer's end-to-end deadline budget ran out."""


class FabricProtocolError(FabricError):
    """The peer sent bytes that do not parse as the fabric protocol, or the
    stream ended mid-frame (a torn/truncated transfer)."""


class Deadline(object):
    """End-to-end time budget for one logical transfer.

    Each socket operation asks :meth:`op_timeout` for its timeout: the
    per-operation cap, shrunk to whatever remains of the overall budget —
    so N slow-but-not-stalled operations cannot stack their individual
    timeouts past the transfer budget. An exhausted budget raises
    :class:`FabricTimeout` instead of returning a non-positive timeout.
    """

    __slots__ = ('budget_s', '_t_end', '_clock')

    def __init__(self, budget_s, clock=time.monotonic):
        self.budget_s = float(budget_s)
        self._clock = clock
        self._t_end = clock() + self.budget_s

    def remaining(self):
        """Seconds left in the budget (may be negative once expired)."""
        return self._t_end - self._clock()

    @property
    def expired(self):
        return self.remaining() <= 0.0

    def op_timeout(self, cap_s):
        """The timeout the next socket operation may use: ``min(cap_s,
        remaining)``. Raises :class:`FabricTimeout` when the budget is gone."""
        remaining = self.remaining()
        if remaining <= 0.0:
            raise FabricTimeout(
                'fabric deadline of {:.3f}s exhausted'.format(self.budget_s))
        return min(float(cap_s), remaining)


def send_all(sock, data, deadline, io_timeout_s):
    """Send every byte of ``data``, re-arming the per-operation timeout from
    ``deadline`` before each partial send."""
    view = memoryview(data)
    sent = 0
    while sent < len(view):
        sock.settimeout(deadline.op_timeout(io_timeout_s))
        sent += sock.send(view[sent:sent + _IO_CHUNK])


def recv_exactly(sock, n, deadline, io_timeout_s):
    """Receive exactly ``n`` bytes or raise. EOF mid-count means the peer
    died or cut the stream: a truncated transfer, surfaced loudly."""
    parts = []
    got = 0
    while got < n:
        sock.settimeout(deadline.op_timeout(io_timeout_s))
        part = sock.recv(min(_IO_CHUNK, n - got))
        if not part:
            raise FabricProtocolError(
                'peer closed the stream after {} of {} bytes (truncated '
                'transfer)'.format(got, n))
        parts.append(part)
        got += len(part)
    return b''.join(parts)


def send_frame(sock, body, deadline, io_timeout_s):
    """Send one length-prefixed frame."""
    if len(body) > MAX_FRAME_BYTES:
        raise FabricProtocolError(
            'frame of {} bytes exceeds the {} byte bound'.format(
                len(body), MAX_FRAME_BYTES))
    send_all(sock, _HEADER.pack(MAGIC, len(body)) + bytes(body), deadline,
             io_timeout_s)


def recv_frame(sock, deadline, io_timeout_s, max_bytes=MAX_FRAME_BYTES):
    """Receive one length-prefixed frame body."""
    header = recv_exactly(sock, _HEADER.size, deadline, io_timeout_s)
    magic, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FabricProtocolError(
            'bad frame magic {!r} (not a fabric peer?)'.format(magic))
    if length > max_bytes:
        raise FabricProtocolError(
            'frame length {} exceeds the {} byte bound'.format(
                length, max_bytes))
    return recv_exactly(sock, length, deadline, io_timeout_s)


# -- message encoding --------------------------------------------------------

def encode_request(key, length):
    return json.dumps({'v': VERSION, 'op': 'get', 'key': key,
                       'length': int(length)}).encode('utf-8')


def encode_ok(length, sha256_hex):
    return json.dumps({'v': VERSION, 'status': 'ok', 'length': int(length),
                       'sha256': sha256_hex}).encode('utf-8')


def encode_miss():
    return json.dumps({'v': VERSION, 'status': 'miss'}).encode('utf-8')


def encode_error(message):
    return json.dumps({'v': VERSION, 'status': 'error',
                       'message': str(message)[:512]}).encode('utf-8')


def decode_message(body):
    """Decode a JSON control frame, raising :class:`FabricProtocolError` on
    anything that does not parse as one."""
    try:
        msg = json.loads(body.decode('utf-8'))
    except (UnicodeDecodeError, ValueError) as e:
        raise FabricProtocolError('unparseable control frame: {}'.format(e))
    if not isinstance(msg, dict):
        raise FabricProtocolError('control frame is not an object')
    return msg


def content_hash(data):
    """The content digest carried in every ``ok`` header: bytes that do not
    match it are discarded, never written to the mirror."""
    return hashlib.sha256(data).hexdigest()


__all__ = ['Deadline', 'FabricError', 'FabricProtocolError', 'FabricTimeout',
           'MAGIC', 'MAX_FRAME_BYTES', 'VERSION', 'content_hash',
           'decode_message', 'encode_error', 'encode_miss', 'encode_ok',
           'encode_request', 'recv_exactly', 'recv_frame', 'send_all',
           'send_frame']
