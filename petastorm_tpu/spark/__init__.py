"""DataFrame -> dataset converter (reference: petastorm/spark/)."""

from petastorm_tpu.spark.dataset_converter import (DatasetConverter,  # noqa: F401
                                                   SparkDatasetConverter,
                                                   make_converter,
                                                   make_spark_converter,
                                                   register_delete_dir_handler)
