"""DataFrame-to-dataset converter: materialize a dataframe once as Parquet,
then mint JAX/torch/TF loaders off the cached copy.

Behavioral parity with the reference's Databricks-contributed converter
(/root/reference/petastorm/spark/spark_dataset_converter.py:40-526):
materialize-to-cache-dir with dedup so repeated conversions of the same frame
reuse one copy, float precision normalization, atexit cleanup of cache dirs, a
pluggable delete handler, and loader factories riding on ``make_batch_reader``.

Design differences (TPU-first build):

* Backend-neutral: accepts pandas DataFrames and pyarrow Tables natively (no
  Spark needed — materialization is a local Arrow write), and pyspark
  DataFrames when pyspark is importable.
* Dedup keys on a content/plan fingerprint: Spark frames use logical-plan
  equality like the reference (:384-390); pandas/Arrow inputs use a content
  hash, which additionally dedupes across *recreated* identical frames.
* The flagship loader is ``make_jax_loader`` (sharded ``jax.Array`` batches);
  ``make_torch_dataloader``/``make_tf_dataset`` mirror the reference surface.
"""

from __future__ import annotations

import atexit
import hashlib
import logging
import os
import threading
import uuid
import warnings
from urllib.parse import urlparse

from petastorm_tpu.fs import FilesystemResolver

logger = logging.getLogger(__name__)

DEFAULT_ROW_GROUP_SIZE_BYTES = 32 * 1024 * 1024

#: environment variable naming the parent cache directory URL (the reference
#: uses the spark conf key ``petastorm.spark.converter.parentCacheDirUrl``)
CACHE_DIR_ENV_VAR = 'PETASTORM_TPU_CONVERTER_CACHE_DIR'

_cache_lock = threading.Lock()
_cache_entries = []  # list of _CachedFrameMeta


class _CachedFrameMeta(object):
    def __init__(self, fingerprint, cache_dir_url, dataset_size):
        self.fingerprint = fingerprint
        self.cache_dir_url = cache_dir_url
        self.dataset_size = dataset_size


def _default_delete_dir_handler(dataset_url):
    import shutil
    resolver = FilesystemResolver(dataset_url)
    parsed = urlparse(dataset_url)
    if parsed.scheme == 'file':
        shutil.rmtree(parsed.path, ignore_errors=False)
    else:
        resolver.filesystem().delete_dir(resolver.get_dataset_path())


_delete_dir_handler = _default_delete_dir_handler


def register_delete_dir_handler(handler):
    """Override how cache directories are deleted (reference :86-99);
    ``None`` restores the default."""
    global _delete_dir_handler
    _delete_dir_handler = handler if handler is not None else _default_delete_dir_handler


def _delete_cache_data_atexit(dataset_url):
    try:
        _delete_dir_handler(dataset_url)
    except FileNotFoundError:
        pass  # already deleted explicitly via converter.delete()
    except Exception:  # noqa: BLE001 - interpreter is exiting; warn, don't die
        warnings.warn('delete cache data {} failed.'.format(dataset_url))


# -- input normalization -------------------------------------------------------

def _is_spark_df(df):
    mod = type(df).__module__
    return mod.startswith('pyspark.')


def _to_arrow_table(df, precision):
    """pyarrow.Table cast to the given float precision (reference
    _convert_precision, :406-421). pandas frames are converted to Tables once,
    up front, in make_converter."""
    import pyarrow as pa

    if not isinstance(df, pa.Table):
        raise TypeError('Unsupported dataframe type: {} (expected pyarrow.Table '
                        'or pyspark DataFrame)'.format(type(df)))
    source, target = (pa.float64(), pa.float32()) if precision == 'float32' \
        else (pa.float32(), pa.float64())
    fields = [pa.field(f.name, target) if f.type == source else f for f in df.schema]
    return df.cast(pa.schema(fields))


class _HashSink(object):
    """File-like sink feeding an Arrow IPC stream straight into a hash."""

    def __init__(self, digest):
        self._digest = digest

    def write(self, data):
        self._digest.update(data)
        return len(data)

    def flush(self):
        pass

    def close(self):
        pass

    @property
    def closed(self):
        return False


def _fingerprint(df, parent_cache_dir_url, row_group_size, compression, precision):
    """Cache key. Spark: logical plan (like the reference); local frames: a
    content hash of the Arrow IPC stream — O(rows) but exact (handles list/
    tensor columns that pandas hashing cannot), and stable across re-created
    frames. The parent cache dir is part of the key so switching dirs
    rematerializes instead of pointing at the old location."""
    suffix = '|dir={}|rg={}|cc={}|p={}'.format(parent_cache_dir_url, row_group_size,
                                               compression, precision)
    if _is_spark_df(df):
        plan = df._jdf.queryExecution().analyzed().toString()
        return 'spark:' + hashlib.sha1(plan.encode()).hexdigest() + suffix
    import pyarrow as pa
    if not isinstance(df, pa.Table):
        # make_converter converts pandas frames up front; direct callers must too
        raise TypeError('Unsupported dataframe type: {} (expected pyarrow.Table '
                        'or pyspark DataFrame)'.format(type(df)))
    table = df
    digest = hashlib.sha1()
    digest.update(str(table.schema).encode())
    with pa.ipc.new_stream(_HashSink(digest), table.schema) as writer:
        writer.write_table(table)
    return 'local:' + digest.hexdigest() + suffix


# -- materialization -----------------------------------------------------------

def rows_per_row_group_for_bytes(table, row_group_size_bytes):
    """Bytes target -> rows (Arrow writers take rows): the one sizing
    heuristic, shared with the minispark test engine's writer."""
    row_bytes = max(1, table.nbytes // max(1, table.num_rows))
    return max(1, row_group_size_bytes // row_bytes)


def _gen_cache_dir_name():
    # {datetime}-{uuid}: greppable for manual cleanup if atexit never ran
    # (reference _gen_cache_dir_name, :424-436)
    import datetime
    return '{}-{}'.format(datetime.datetime.now().strftime('%Y%m%d%H%M%S'), uuid.uuid4())


def _materialize(df, parent_cache_dir_url, row_group_size_bytes, compression, precision):
    """Write the frame as Parquet under a fresh subdir; returns (url, n_rows)."""
    import pyarrow.parquet as pq

    cache_dir_url = parent_cache_dir_url.rstrip('/') + '/' + _gen_cache_dir_name()
    if _is_spark_df(df):
        from pyspark.sql.functions import col
        from pyspark.sql.types import ArrayType, DoubleType, FloatType
        source, target = (DoubleType, FloatType) if precision == 'float32' \
            else (FloatType, DoubleType)
        for field in df.schema:
            if isinstance(field.dataType, source):
                df = df.withColumn(field.name, col(field.name).cast(target()))
            elif isinstance(field.dataType, ArrayType) and \
                    isinstance(field.dataType.elementType, source):
                df = df.withColumn(field.name, col(field.name).cast(ArrayType(target())))
        df.write.option('compression', compression or 'snappy') \
            .option('parquet.block.size', row_group_size_bytes).parquet(cache_dir_url)
        n_rows = df.count()
    else:
        table = _to_arrow_table(df, precision)
        resolver = FilesystemResolver(cache_dir_url)
        fs, path = resolver.filesystem(), resolver.get_dataset_path()
        fs.create_dir(path, recursive=True)
        with fs.open_output_stream(path + '/part-00000.parquet') as f:
            pq.write_table(table, f,
                           row_group_size=rows_per_row_group_for_bytes(table, row_group_size_bytes),
                           compression=compression or 'snappy')
        n_rows = table.num_rows
    atexit.register(_delete_cache_data_atexit, cache_dir_url)
    logger.info('Materialized dataframe to %s (%d rows)', cache_dir_url, n_rows)
    return cache_dir_url, n_rows


# -- converter -----------------------------------------------------------------

class DatasetConverter(object):
    """Holds one materialized dataframe; mints loaders over it. Picklable —
    remote processes re-open the cache URL (reference :117-124)."""

    def __init__(self, cache_dir_url, dataset_size):
        self.cache_dir_url = cache_dir_url
        self.dataset_size = dataset_size

    def __len__(self):
        return self.dataset_size

    def make_jax_loader(self, batch_size=32, num_epochs=None, workers_count=10,
                        to_device=None, shuffling_queue_capacity=0, seed=None,
                        drop_last=True, cur_shard=None, shard_count=None,
                        **reader_kwargs):
        """A :class:`petastorm_tpu.jax.JaxDataLoader` over the cache — use as a
        context manager so the reader is closed on exit. The TPU-native
        replacement for the reference's two framework factories."""
        from petastorm_tpu import make_batch_reader
        from petastorm_tpu.jax import JaxDataLoader
        reader = make_batch_reader(self.cache_dir_url, num_epochs=num_epochs,
                                   workers_count=workers_count, seed=seed,
                                   cur_shard=cur_shard, shard_count=shard_count,
                                   **reader_kwargs)
        return JaxDataLoader(reader, batch_size=batch_size, to_device=to_device,
                             shuffling_queue_capacity=shuffling_queue_capacity,
                             seed=seed, drop_last=drop_last)

    def make_torch_dataloader(self, batch_size=32, num_epochs=None, workers_count=10,
                              cur_shard=None, shard_count=None, **reader_kwargs):
        """A torch DataLoader context manager over the cache (reference
        :174-215)."""
        from petastorm_tpu import make_batch_reader
        from petastorm_tpu.torch_utils import DataLoader
        reader = make_batch_reader(self.cache_dir_url, num_epochs=num_epochs,
                                   workers_count=workers_count, cur_shard=cur_shard,
                                   shard_count=shard_count, **reader_kwargs)
        return DataLoader(reader, batch_size=batch_size)

    def make_tf_dataset(self, batch_size=32, num_epochs=None, workers_count=10,
                        **reader_kwargs):
        """A ``tf.data.Dataset`` context manager over the cache (reference
        :142-172). Requires tensorflow."""
        from petastorm_tpu import make_batch_reader
        from petastorm_tpu.tf_utils import make_tf_dataset_context
        reader = make_batch_reader(self.cache_dir_url, num_epochs=num_epochs,
                                   workers_count=workers_count, **reader_kwargs)
        return make_tf_dataset_context(reader, batch_size=batch_size)

    def delete(self):
        """Delete the cache files now instead of at interpreter exit."""
        with _cache_lock:
            global _cache_entries
            _cache_entries = [m for m in _cache_entries
                              if m.cache_dir_url != self.cache_dir_url]
        _delete_dir_handler(self.cache_dir_url)


#: reference-compatible alias
SparkDatasetConverter = DatasetConverter


def _resolve_parent_cache_dir(parent_cache_dir_url):
    url = parent_cache_dir_url or os.environ.get(CACHE_DIR_ENV_VAR)
    if not url:
        raise ValueError(
            'No converter cache dir configured. Pass parent_cache_dir_url= or set '
            'the {} environment variable (the reference uses the spark conf key '
            'petastorm.spark.converter.parentCacheDirUrl).'.format(CACHE_DIR_ENV_VAR))
    FilesystemResolver(url)  # validates the scheme early
    return url


def make_converter(df, parent_cache_dir_url=None,
                   parquet_row_group_size_bytes=DEFAULT_ROW_GROUP_SIZE_BYTES,
                   compression_codec=None, precision='float32'):
    """Materialize ``df`` (pandas / pyarrow / pyspark) to a Parquet cache and
    return a :class:`DatasetConverter`. Converting the same frame again (same
    row-group size, codec, and precision) reuses the cached copy
    (reference make_spark_converter, :474-526)."""
    if precision not in ('float32', 'float64'):
        raise ValueError("precision {} is not supported. Use 'float32' or "
                         "'float64'".format(precision))
    parent = _resolve_parent_cache_dir(parent_cache_dir_url)
    if not _is_spark_df(df):
        import pandas as pd
        if isinstance(df, pd.DataFrame):
            # convert once up front: fingerprinting and materialization both
            # need the Arrow table, and for multi-GB frames a second
            # from_pandas doubles peak memory
            import pyarrow as pa
            df = pa.Table.from_pandas(df, preserve_index=False)
    key = _fingerprint(df, parent, parquet_row_group_size_bytes, compression_codec, precision)
    with _cache_lock:
        for meta in _cache_entries:
            if meta.fingerprint == key:
                return DatasetConverter(meta.cache_dir_url, meta.dataset_size)
        url, n_rows = _materialize(df, parent, parquet_row_group_size_bytes,
                                   compression_codec, precision)
        _cache_entries.append(_CachedFrameMeta(key, url, n_rows))
        return DatasetConverter(url, n_rows)


#: reference-compatible alias
make_spark_converter = make_converter
