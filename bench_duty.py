"""bench_duty.py — the north-star duty-cycle benchmark as one command.

Builds a synthetic ImageNet-Parquet store (photo-like PNGs), runs a REAL jitted
ResNet-50 bf16 train step on whatever device is present, and measures how much
wall time the step loop spends blocked on input (`pipeline_duty_cycle`,
BASELINE.md methodology). Variants isolate where the host budget goes:

  png        PNG decode + resize transform on the host (the baseline config)
  jpeg       realistic-size (320-560px) JPEG store, scaled DCT decode to
             ~target resolution + small resize — the format real ImageNet
             pipelines actually run
  raw        pre-resized uint8 RawTensorCodec store (zero-copy columnar
             decode) — the decode-free ceiling
  png_cached second epoch with a pre-filled local-disk cache (cache stores
             decoded rows, so PNG decode is skipped; resize still runs)

Emits one JSON line per variant:
  {"metric": "duty_cycle_<variant>", "examples_per_sec": ..,
   "input_stall_fraction": .., "host_cores": .., "device": ..}

Usage: python bench_duty.py [--steps 30] [--batch-size 64] [--image-size 160]
                            [--variants png,raw,png_cached] [--num-classes 1000]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

import numpy as np

# bump when build_raw_store's on-disk layout changes (reused --keep-dir stores
# are rebuilt instead of silently benchmarked under the new label)
RAW_STORE_FORMAT = 'v3-flba-pagescan'

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def build_png_store(url, rows, seed=0, image_codec='png', min_dim=64, max_dim=160):
    from examples.imagenet.generate_petastorm_imagenet import generate_synthetic_imagenet
    images_per_synset = 32
    generate_synthetic_imagenet(url, num_synsets=max(1, rows // images_per_synset),
                                images_per_synset=images_per_synset,
                                rows_per_row_group=16, seed=seed, image_codec=image_codec,
                                min_dim=min_dim, max_dim=max_dim)


def build_raw_store(url, rows, image_size, num_classes, seed=0):
    """Pre-resized uint8 tensors + integer labels: zero host decode work.
    RawTensorCodec stores headerless cells, so whole-column decode is a
    zero-copy view of the Arrow buffer (~2.4x the NdarrayCodec block rate)."""
    from examples.imagenet.generate_petastorm_imagenet import synthetic_image
    from petastorm_tpu.codecs import RawTensorCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import materialize_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('RawImagenet', [
        UnischemaField('image', np.uint8, (image_size, image_size, 3), RawTensorCodec(), False),
        UnischemaField('label', np.int64, (), ScalarCodec(np.int64), False),
    ])
    rng = np.random.default_rng(seed)
    # uncompressed: the raw variant is the decode-free ceiling; snappy on raw
    # pixel tensors costs read-side decompression for a marginal size win
    with materialize_dataset(url, schema, rows_per_row_group=64, compression='none') as writer:
        for i in range(rows):
            writer.write({'image': synthetic_image(rng, image_size, image_size),
                          'label': int(i % num_classes)})
    return schema


def make_step(image_size, num_classes, seed=0, model_factory=None):
    import jax
    import jax.numpy as jnp

    from examples.imagenet.jax_resnet_example import device_preprocess
    from petastorm_tpu.models import resnet50
    from petastorm_tpu.models.train import create_train_state, make_train_step

    model = (model_factory or resnet50)(num_classes=num_classes, dtype=jnp.bfloat16)
    state = create_train_state(model, jax.random.PRNGKey(seed),
                               jnp.zeros((1, image_size, image_size, 3)))
    state = jax.device_put(state, jax.devices()[0])
    train_step = make_train_step(donate=False, preprocess_fn=device_preprocess,
                                 preprocess_seed=seed)
    holder = {'state': state}

    def step_fn(images, labels):
        holder['state'], metrics = train_step(holder['state'], images, labels)
        return metrics['loss']

    return step_fn


def measure_kwargs(args):
    """The one measurement configuration shared by the variant runs and the
    sweep — points from both stay comparable."""
    return ({'seed': 7, 'shuffle_row_groups': True, 'workers_count': args.workers},
            {'shuffling_queue_capacity': 512, 'seed': 7})


def run_variant(variant, args, png_url, raw_url, jpeg_url, tmpdir):
    from examples.imagenet.jax_resnet_example import make_transform
    from petastorm_tpu import make_reader
    from petastorm_tpu.tools.throughput import pipeline_duty_cycle

    step_fn = make_step(args.image_size, args.num_classes)
    reader_kwargs, loader_kwargs = measure_kwargs(args)
    batch_to_args = lambda b: (b['image'], b['label'])  # noqa: E731
    if variant in ('png', 'png_cached'):
        url = png_url
        reader_kwargs['transform_spec'] = make_transform(args.image_size, args.num_classes)
    elif variant == 'jpeg':
        url = jpeg_url
        reader_kwargs['transform_spec'] = make_transform(args.image_size, args.num_classes)
    elif variant == 'raw':
        url = raw_url
    else:
        raise ValueError(variant)

    if variant == 'png_cached':
        cache_dir = os.path.join(tmpdir, 'disk_cache')
        reader_kwargs.update({'cache_type': 'local-disk', 'cache_location': cache_dir,
                              'cache_size_limit': 10 << 30,
                              'cache_row_size_estimate': 200 << 10})
        # pre-fill: one full epoch populates the decoded-row cache, so the
        # measured pass below behaves like every epoch after the first
        with make_reader(url, num_epochs=1, **reader_kwargs) as reader:
            for _ in reader:
                pass

    res = pipeline_duty_cycle(
        url, step_fn, batch_to_args, batch_size=args.batch_size, steps=args.steps,
        warmup_steps=args.warmup_steps, reader_kwargs=reader_kwargs,
        loader_kwargs=loader_kwargs)
    return res


#: the --sweep ladder: step cost rises ~monotonically (deeper, then wider);
#: bytes/example stay CONSTANT, so the sweep isolates "can the fixed host+
#: staging budget hide under a growing step" — the duty-vs-step-cost curve
SWEEP_MODELS = (
    ('resnet18', 'resnet18', 1),
    ('resnet50', 'resnet50', 1),
    ('resnet101', 'resnet101', 1),
    ('resnet152', 'resnet152', 1),
    ('resnet152w2', 'resnet152', 2),  # double width = ~4x FLOPs vs resnet152
)


def measure_step_ms(step_fn, batch_size, image_size, repeats=10):
    """Device-only cost of one train step (median of ``repeats``), staged
    input, fully blocked — the x-axis of the duty-vs-step-cost curve."""
    import statistics
    import time

    import jax
    import jax.numpy as jnp

    images = jax.device_put(jnp.zeros((batch_size, image_size, image_size, 3),
                                      dtype=jnp.uint8))
    labels = jax.device_put(jnp.zeros((batch_size,), dtype=jnp.int64))
    jax.block_until_ready(step_fn(images, labels))  # compile + warm
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(step_fn(images, labels))
        times.append(time.perf_counter() - t0)
    return statistics.median(times) * 1000


def run_sweep(args, raw_url):
    """The duty-vs-step-cost curve on the raw store: one point per ladder
    model. Emits a JSON line per point; the curve demonstrates (or refutes)
    that the loader hides input time once the step is heavy enough — the
    BASELINE north-star claim, measured instead of inferred."""
    import functools

    from petastorm_tpu import models as model_zoo
    from petastorm_tpu.tools.throughput import pipeline_duty_cycle

    reader_kwargs, loader_kwargs = measure_kwargs(args)
    ladder = SWEEP_MODELS
    if args.sweep_models:
        wanted = {m.strip() for m in args.sweep_models.split(',')}
        unknown = wanted - {m[0] for m in SWEEP_MODELS}
        if unknown:
            raise SystemExit('unknown --sweep-models: {}'.format(sorted(unknown)))
        ladder = [m for m in SWEEP_MODELS if m[0] in wanted]
    results = []
    for label, factory_name, width in ladder:
        base = getattr(model_zoo, factory_name)
        factory = functools.partial(base, num_filters=64 * width)
        step_fn = make_step(args.image_size, args.num_classes, model_factory=factory)
        step_ms = measure_step_ms(step_fn, args.batch_size, args.image_size)
        res = pipeline_duty_cycle(
            raw_url, step_fn, lambda b: (b['image'], b['label']),
            batch_size=args.batch_size, steps=args.steps,
            warmup_steps=args.warmup_steps,
            reader_kwargs=reader_kwargs, loader_kwargs=loader_kwargs)
        point = {
            'metric': 'duty_sweep',
            'model': label,
            'step_ms': round(step_ms, 2),
            'consumption_ex_per_s': round(args.batch_size / (step_ms / 1000), 1),
            'examples_per_sec': round(res.samples_per_second, 1),
            'input_stall_fraction': round(res.input_stall_fraction, 4),
            'duty_cycle': round(1 - res.input_stall_fraction, 4),
            'batch_size': args.batch_size,
            'image_size': args.image_size,
            'steps': args.steps,
        }
        print(json.dumps(point), flush=True)
        results.append(point)
    best = min(results, key=lambda p: p['input_stall_fraction'])
    print(json.dumps({'metric': 'duty_sweep_best', **{k: best[k] for k in
                      ('model', 'step_ms', 'input_stall_fraction', 'duty_cycle',
                       'examples_per_sec')}}), flush=True)
    return results


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--steps', type=int, default=30)
    parser.add_argument('--warmup-steps', type=int, default=5)
    parser.add_argument('--batch-size', type=int, default=64)
    parser.add_argument('--image-size', type=int, default=160)
    parser.add_argument('--num-classes', type=int, default=1000)
    parser.add_argument('--rows', type=int, default=256)
    parser.add_argument('--workers', type=int, default=max(1, os.cpu_count() or 1))
    parser.add_argument('--variants', default='png,jpeg,raw,png_cached')
    parser.add_argument('--sweep', action='store_true',
                        help='duty-vs-step-cost curve on the raw store across '
                             'the model ladder (instead of --variants)')
    parser.add_argument('--sweep-models', default=None,
                        help='comma-separated subset of the ladder '
                             '(default: all of {})'.format(
                                 ','.join(m[0] for m in SWEEP_MODELS)))
    parser.add_argument('--keep-dir', default=None,
                        help='reuse/keep the dataset dir (default: fresh tempdir)')
    args = parser.parse_args(argv)

    import jax
    device = str(jax.devices()[0].platform)

    tmpdir = args.keep_dir or tempfile.mkdtemp(prefix='bench_duty_')
    png_dir = os.path.join(tmpdir, 'imagenet_png')
    raw_dir = os.path.join(tmpdir, 'imagenet_raw')
    jpeg_dir = os.path.join(tmpdir, 'imagenet_jpeg')
    png_url, raw_url = 'file://' + png_dir, 'file://' + raw_dir
    jpeg_url = 'file://' + jpeg_dir
    variants = ['raw'] if args.sweep else \
        [v.strip() for v in args.variants.split(',') if v.strip()]
    try:
        if not os.path.exists(png_dir) and any(v.startswith('png') for v in variants):
            build_png_store(png_url, args.rows)
        # format stamp: a reused --keep-dir store from before a layout change
        # (e.g. the NdarrayCodec -> RawTensorCodec switch) must be rebuilt, not
        # silently measured under the new label
        raw_stamp = os.path.join(raw_dir, '.format_stamp')
        # layout version + build params: a stale --keep-dir store (older codec
        # OR different rows/size/classes) is rebuilt, never silently measured
        raw_spec = '{}:rows={}:image_size={}:num_classes={}'.format(
            RAW_STORE_FORMAT, args.rows, args.image_size, args.num_classes)
        raw_fresh = (os.path.exists(raw_stamp) and
                     open(raw_stamp).read().strip() == raw_spec)
        if 'raw' in variants and not raw_fresh:
            shutil.rmtree(raw_dir, ignore_errors=True)
            build_raw_store(raw_url, args.rows, args.image_size, args.num_classes)
            with open(raw_stamp, 'w') as f:
                f.write(raw_spec)
        if not os.path.exists(jpeg_dir) and 'jpeg' in variants:
            # realistic ImageNet photo sizes; scaled DCT decode shines here
            build_png_store(jpeg_url, args.rows, image_codec='jpeg',
                            min_dim=320, max_dim=560)

        if args.sweep:
            run_sweep(args, raw_url)
            return
        for variant in variants:
            res = run_variant(variant, args, png_url, raw_url, jpeg_url, tmpdir)
            print(json.dumps({
                'metric': 'duty_cycle_{}'.format(variant),
                'examples_per_sec': round(res.samples_per_second, 1),
                'input_stall_fraction': round(res.input_stall_fraction, 4),
                'duty_cycle': round(1 - res.input_stall_fraction, 4),
                'host_cores': os.cpu_count(),
                'device': device,
                'batch_size': args.batch_size,
                'image_size': args.image_size,
                'steps': args.steps,
            }), flush=True)
    finally:
        if args.keep_dir is None:
            shutil.rmtree(tmpdir, ignore_errors=True)


if __name__ == '__main__':
    main()
