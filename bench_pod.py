#!/usr/bin/env python
"""bench_pod.py — BASELINE.md config 5 as one command: sharded multi-host
reading + NGram sequence readout feeding a ('data','seq')-sharded step.

Runs TODAY on a virtual CPU mesh (default: 8 forced host devices, 4 simulated
hosts in one process — the same strategy the reference uses to test multi-node
sharding without a cluster, reference test_end_to_end.py:426-448) and
UNCHANGED on a real pod: on v5e-16 each JAX process executes exactly one
host's branch (``cur_shard=jax.process_index()``), the loop over simulated
hosts disappears, and the mesh spans the real chips.

Per simulated host it builds: make_reader(cur_shard=h, shard_count=H,
ngram=window) -> JaxDataLoader -> stack_ngram_time_axis -> [B, T, ...] batches
staged over the ('data','seq') mesh -> a jitted sequence-model step. Emits one
JSON line per host plus an aggregate:
  {"metric": "pod_host", "host": h, "examples_per_sec": .., "stall": ..}
  {"metric": "pod_aggregate", "hosts": H, "examples_per_sec_total": .., ...}

With ``--telemetry-out DIR`` each (simulated) host also appends its
host-stamped diagnostics JSONL to ``DIR/host<h>.jsonl`` — feed the directory
to ``petastorm-tpu-diagnose --pod DIR`` for the fleet view / straggler callout.

Usage: python bench_pod.py [--hosts 4] [--steps 20] [--seq-len 4]
       [--telemetry-out DIR]
       (set JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
        off-pod; the script forces them itself when no pod is present)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def _ensure_devices(n):
    """Shared bring-up with __graft_entry__._ensure_devices (killable ambient
    probe off-pod, inline trust on managed pod runtimes, forced-CPU respawn
    otherwise — a wedged TPU tunnel cannot hang the benchmark)."""
    import __graft_entry__ as g
    if g._ensure_devices(n, '_PSTPU_POD_CHILD'):
        return True
    if os.environ.get('_PSTPU_POD_CHILD'):
        raise RuntimeError('need {} devices; forced-CPU child came up short'.format(n))
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS='cpu', _PSTPU_POD_CHILD='1')
    env['XLA_FLAGS'] = g._force_device_count_flag(env.get('XLA_FLAGS', ''), n)
    env['PYTHONPATH'] = REPO_ROOT + os.pathsep + env.get('PYTHONPATH', '')
    rc = subprocess.run([sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
                        env=env).returncode
    sys.exit(rc)


def build_sequence_store(url, rows, feature_dim):
    """Timestamped telemetry-style rows: NGram's native shape."""
    import numpy as np
    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('PodSeq', [
        UnischemaField('ts', np.int64, (), ScalarCodec(), False),
        UnischemaField('features', np.float32, (feature_dim,), NdarrayCodec(), False),
    ])
    rng = np.random.default_rng(0)
    write_petastorm_dataset(url, schema, ({
        'ts': i,
        'features': rng.standard_normal(feature_dim).astype(np.float32),
    } for i in range(rows)), rows_per_row_group=64)
    return schema


def _run_chaos(args):
    """The ``--chaos`` lane: elastic pod churn with real process death.

    Spawns host subprocesses (``petastorm_tpu.elastic._hostproc``) over one
    shared coordination directory, SIGKILLs one once the pod has committed
    ``--chaos-kill-after`` row groups, immediately joins a replacement, and
    waits for the survivors. The emitted ``pod_chaos`` line carries the
    scoreboard-derived ground truth: committed/double-committed counts, the
    final generation, and per-host commit shares — on a healthy protocol
    ``double_committed`` is 0 and ``committed`` equals the row-group count.
    """
    import subprocess

    tmpdir = tempfile.mkdtemp(prefix='bench_pod_chaos_')
    url = 'file://' + os.path.join(tmpdir, 'store')
    build_sequence_store(url, args.rows, args.feature_dim)
    coord = os.path.join(tmpdir, 'coord')
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO_ROOT + os.pathsep + os.environ.get('PYTHONPATH', ''))

    def spawn(host):
        return subprocess.Popen(
            [sys.executable, '-m', 'petastorm_tpu.elastic._hostproc',
             '--url', url, '--coord', coord, '--host', host,
             '--out', os.path.join(tmpdir, host + '.jsonl'),
             '--field', 'ts', '--seed', '13', '--lease-s', '1.0',
             '--sleep-per-row', '0.002'], env=env)

    from petastorm_tpu.faults import HostChurnPlan, drive_host_churn
    initial = max(2, min(args.hosts, 4))
    procs = {'host{}'.format(h): spawn('host{}'.format(h))
             for h in range(initial)}
    plan = HostChurnPlan(kill_host='host1',
                         kill_after_commits=args.chaos_kill_after,
                         join_host='host{}'.format(initial))
    timeline = drive_host_churn(
        coord, procs, plan,
        spawn_joiner=lambda: spawn(plan.join_host), timeout_s=300)
    rcs = {h: p.wait(timeout=300) for h, p in procs.items()}

    commits = {}
    commits_dir = os.path.join(coord, 'commits')
    for name in sorted(os.listdir(commits_dir)):
        with open(os.path.join(commits_dir, name)) as f:
            for line in f:
                rec = json.loads(line)
                commits.setdefault((rec['epoch'], rec['item']), []).append(rec)
    double = sum(1 for v in commits.values() if len(v) > 1)
    per_host = {}
    for v in commits.values():
        per_host[v[0]['host']] = per_host.get(v[0]['host'], 0) + 1
    generations = len(os.listdir(os.path.join(coord, 'generations')))
    survivors_ok = all(rc == 0 for h, rc in rcs.items() if h != plan.kill_host)
    print(json.dumps({'metric': 'pod_chaos', 'hosts': initial,
                      'killed': timeline['killed'], 'joined': timeline['joined'],
                      'commits_at_kill': timeline['commits_at_kill'],
                      'committed': len(commits), 'double_committed': double,
                      'per_host_commits': per_host,
                      'generations': generations,
                      'survivor_exit_codes_ok': survivors_ok}), flush=True)
    if double or not survivors_ok:
        return 1
    return 0


def _run_fabric(args):
    """The ``--fabric`` lane: N simulated hosts sharing chunks peer-to-peer.

    Each host gets its own chunk-mirror root and a ``FabricNode`` (server +
    lease membership + client); hosts read the same ``mock-remote://`` store
    one after another with that host's fabric client installed. Host 0 finds
    no peers and reads everything from the object store; every later host
    should source (nearly) every chunk from an earlier peer's mirror — on a
    healthy N-host run the verdict reports ≈1 object-store read plus (N-1)
    LAN copies per chunk. ``--chaos net`` injects a connection reset and a
    truncated payload into the peer serves and asserts the readers still
    complete with the losses accounted as fallbacks.

    The emitted ``pod_fabric`` line carries the conservation check straight
    off the counters: every chunk-mirror miss must be satisfied exactly once,
    by a peer copy or by an object-store fallback (docs/fabric.md).
    """
    from petastorm_tpu import fabric, faults, make_reader, native
    from petastorm_tpu import observability as obs
    from petastorm_tpu.chunkstore import ChunkCacheConfig, cache_diagnostics

    if not native.is_available():
        print(json.dumps({'metric': 'pod_fabric', 'skipped': True,
                          'reason': 'native kernel unavailable (chunk mirrors '
                                    'need the page scanner)'}), flush=True)
        return 0

    obs.configure('counters')
    tmpdir = tempfile.mkdtemp(prefix='bench_pod_fabric_')
    store_path = os.path.join(tmpdir, 'store')
    build_sequence_store('file://' + store_path, args.rows, args.feature_dim)
    url = 'mock-remote://' + store_path
    coord = os.path.join(tmpdir, 'coord')
    hosts = max(2, min(args.hosts, 4))

    faults_injected = 0
    if args.chaos == 'net':
        faults_injected = 2
        faults.install_net(faults.NetFaultPlan(reset_payloads=1,
                                               truncate_payloads=1))

    def counters():
        flat = obs.flatten_snapshot(obs.snapshot())
        return {k: flat.get(k, 0) for k in ('fabric_peer_hits',
                                            'fabric_fallbacks',
                                            'fabric_bytes_from_peers',
                                            'fabric_breaker_open')}

    nodes = []
    rows_ok = True
    misses_total = 0
    t0 = time.perf_counter()
    try:
        for h in range(hosts):
            cache = ChunkCacheConfig(root=os.path.join(tmpdir, 'cache%d' % h),
                                     size_limit_bytes=1 << 30)
            node = fabric.start_node(fabric.FabricConfig(
                coord_dir=coord, host_id='host%d' % h, cache=cache))
            nodes.append(node)
            fabric.install(node)
            before = counters()
            try:
                with make_reader(url, reader_pool_type='thread',
                                 workers_count=args.workers, num_epochs=1,
                                 shuffle_row_groups=False,
                                 chunk_cache=cache) as reader:
                    rows_read = sum(1 for _ in reader)
            finally:
                fabric.uninstall()
            after = counters()
            misses = cache_diagnostics(cache)['chunk_cache_misses']
            misses_total += misses
            rows_ok = rows_ok and rows_read == args.rows
            print(json.dumps({
                'metric': 'pod_fabric_host', 'host': h, 'rows': rows_read,
                'chunk_misses': misses,
                'peer_copies': after['fabric_peer_hits'] - before['fabric_peer_hits'],
                'object_store_reads':
                    after['fabric_fallbacks'] - before['fabric_fallbacks'],
            }), flush=True)
        final = counters()
    finally:
        fabric.uninstall()
        for node in nodes:
            node.stop()
        if args.chaos == 'net':
            faults.uninstall_net()

    dt = time.perf_counter() - t0
    peer_copies = final['fabric_peer_hits']
    object_store_reads = final['fabric_fallbacks']
    # conservation: every mirror miss is satisfied exactly once — by a peer
    # copy or by an object-store fallback (never neither, never both)
    accounted = (peer_copies + object_store_reads) == misses_total
    ok = rows_ok and accounted and peer_copies > 0
    if args.chaos != 'net':
        # healthy pod: host 0 pays the object store once per chunk, every
        # later host rides the fabric
        chunks = misses_total // hosts
        ok = ok and object_store_reads == chunks \
            and peer_copies == (hosts - 1) * chunks
    print(json.dumps({
        'metric': 'pod_fabric', 'hosts': hosts, 'rows': args.rows,
        'chunk_misses': misses_total, 'peer_copies': peer_copies,
        'object_store_reads': object_store_reads,
        'bytes_from_peers': final['fabric_bytes_from_peers'],
        'breakers_tripped': final['fabric_breaker_open'],
        'chaos': args.chaos, 'faults_injected': faults_injected,
        'accounted': accounted, 'elapsed_s': round(dt, 2), 'ok': ok,
    }), flush=True)
    return 0 if ok else 1


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--hosts', type=int, default=4)
    parser.add_argument('--devices', type=int, default=8)
    parser.add_argument('--steps', type=int, default=20)
    parser.add_argument('--batch-size', type=int, default=16)
    parser.add_argument('--seq-len', type=int, default=4)
    parser.add_argument('--feature-dim', type=int, default=64)
    parser.add_argument('--rows', type=int, default=4096)
    parser.add_argument('--workers', type=int, default=2)
    parser.add_argument('--context', choices=('ring', 'ulysses'), default='ring',
                        help='context-parallel attention strategy over the seq axis')
    parser.add_argument('--telemetry-out', default=None, metavar='DIR',
                        help='write one host-stamped telemetry JSONL per '
                             '(simulated) host into DIR — the input format of '
                             'petastorm-tpu-diagnose --pod (docs/observability.md)')
    parser.add_argument('--chaos', nargs='?', const='churn', default=None,
                        choices=('churn', 'net'),
                        help='fault lane: bare --chaos (= "churn") runs '
                             'elastic pod churn (docs/parallelism.md) — REAL '
                             'host subprocesses, SIGKILL one mid-epoch, join '
                             'a replacement, assert exactly-once coverage '
                             'from the commit scoreboard; "--chaos net" '
                             '(with --fabric) injects connection resets and '
                             'truncated payloads into the peer transfers '
                             'instead. No devices needed.')
    parser.add_argument('--chaos-kill-after', type=int, default=4,
                        help='commit count that triggers the --chaos kill')
    parser.add_argument('--fabric', action='store_true',
                        help='peer-to-peer chunk fabric lane (docs/fabric.md): '
                             'N simulated hosts with per-host chunk mirrors '
                             'read the same remote store in turn; the verdict '
                             'reports object-store reads vs LAN peer copies '
                             '(healthy: ~1 + (N-1) copies per chunk). Combine '
                             'with --chaos net for fault injection. No '
                             'devices needed; emits a pod_fabric JSON line.')
    args = parser.parse_args(argv)

    if args.chaos == 'net' and not args.fabric:
        parser.error('--chaos net is a fabric fault lane — pass --fabric too')
    if args.fabric:
        return _run_fabric(args)
    if args.chaos:
        return _run_chaos(args)

    _ensure_devices(args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from petastorm_tpu import make_reader
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.jax.loader import stack_ngram_time_axis
    from petastorm_tpu.ngram import NGram
    from petastorm_tpu.parallel import make_mesh
    from petastorm_tpu.unischema import UnischemaField

    tmpdir = tempfile.mkdtemp(prefix='bench_pod_')
    url = 'file://' + os.path.join(tmpdir, 'store')
    schema = build_sequence_store(url, args.rows, args.feature_dim)

    seq_axis = 2 if args.devices % 2 == 0 else 1
    data_axis = args.devices // seq_axis
    # SPMD divisibility (shard_map): fail fast with a clear message instead of
    # a deep jax error inside the transformer's attention
    if args.batch_size % data_axis:
        parser.error('--batch-size {} must be divisible by the data mesh axis ({}; '
                     '--devices {} / seq {})'.format(args.batch_size, data_axis,
                                                     args.devices, seq_axis))
    if args.seq_len % seq_axis:
        parser.error('--seq-len {} must be divisible by the seq mesh axis ({})'.format(
            args.seq_len, seq_axis))
    mesh = make_mesh(('data', 'seq'), axis_shapes=(-1, seq_axis),
                     devices=jax.devices()[:args.devices])
    batch_sharding = NamedSharding(mesh, P('data', 'seq'))

    fields = {i: [UnischemaField('ts', np.int64, ()),
                  UnischemaField('features', np.float32, (args.feature_dim,))]
              for i in range(args.seq_len)}

    # the REAL long-context training load: a ring-attention sequence
    # transformer (petastorm_tpu.models.transformer) — attention sharded over
    # mesh['seq'] (context parallelism), dp over mesh['data']
    from petastorm_tpu.models import make_sequence_transformer
    from petastorm_tpu.models.train import (create_train_state, make_train_step,
                                            shard_train_state)

    num_classes = 16
    model = make_sequence_transformer(num_classes=num_classes, mesh=mesh,
                                      d_model=64, num_layers=2,
                                      context_parallelism=args.context)
    state = create_train_state(
        model, jax.random.PRNGKey(0),
        jnp.zeros((args.batch_size, args.seq_len, args.feature_dim)))

    if args.telemetry_out:
        os.makedirs(args.telemetry_out, exist_ok=True)

    def _telemetry_snapshot(host, loader):
        """One pod-aggregator line: the loader's flat diagnostics under this
        simulated host's identity stamp (on a real pod every process writes
        its own file; here 'host<h>' keys keep the series distinct)."""
        if not args.telemetry_out:
            return
        from petastorm_tpu import observability as obs
        rec = {'ts': round(time.time(), 3),
               'host': obs.host_identity('host{}'.format(host)),
               'metrics': {k: v for k, v in loader.diagnostics.items()
                           if isinstance(v, (int, float))}}
        path = os.path.join(args.telemetry_out, 'host{}.jsonl'.format(host))
        with open(path, 'a') as f:
            f.write(json.dumps(rec) + '\n')

    total_rate = 0.0
    worst_stall = 0.0
    with mesh:
        state = shard_train_state(state, mesh)
        step = make_train_step(donate=False)
        for host in range(args.hosts):
            ngram = NGram(fields, delta_threshold=1,
                          timestamp_field=UnischemaField('ts', np.int64, ()))
            with make_reader(url, reader_pool_type='thread', workers_count=args.workers,
                             ngram=ngram, output='columnar',
                             cur_shard=host, shard_count=args.hosts,
                             shuffle_row_groups=True, seed=13, num_epochs=None) as reader:
                loader = JaxDataLoader(reader, batch_size=args.batch_size, seed=13)
                it = iter(loader)

                def stage(stacked):
                    x = jax.device_put(stacked['features'], batch_sharding)
                    labels = jnp.asarray(np.asarray(stacked['ts'][:, 0]) % num_classes)
                    return x, labels

                metrics = None
                for _ in range(3):  # warmup + compile
                    x, labels = stage(stack_ngram_time_axis(next(it)))
                    state, metrics = step(state, x, labels)
                jax.block_until_ready(metrics['loss'])
                _telemetry_snapshot(host, loader)
                wait = 0.0
                t0 = time.perf_counter()
                for _ in range(args.steps):
                    # 'stall' times ONLY the input-pipeline wait (window batch
                    # production); staging stays outside, like every other
                    # duty-cycle measurement in this repo
                    w0 = time.perf_counter()
                    stacked = stack_ngram_time_axis(next(it))
                    wait += time.perf_counter() - w0
                    x, labels = stage(stacked)
                    state, metrics = step(state, x, labels)
                jax.block_until_ready(metrics['loss'])
                dt = time.perf_counter() - t0
                _telemetry_snapshot(host, loader)
            rate = args.steps * args.batch_size / dt
            stall = wait / dt
            total_rate += rate
            worst_stall = max(worst_stall, stall)
            print(json.dumps({'metric': 'pod_host', 'host': host,
                              'examples_per_sec': round(rate, 1),
                              'stall': round(stall, 4)}), flush=True)
    print(json.dumps({'metric': 'pod_aggregate', 'hosts': args.hosts,
                      'devices': args.devices, 'seq_len': args.seq_len,
                      'examples_per_sec_total': round(total_rate, 1),
                      'worst_host_stall': round(worst_stall, 4),
                      'simulated': True,
                      'note': 'hosts run serially in one process off-pod; on a '
                              'real pod each process runs its own shard'}), flush=True)


if __name__ == '__main__':
    sys.exit(main() or 0)
