#!/usr/bin/env python
"""Worker-pool scaling curve: samples/sec at workers in {1,2,4,8} for thread
and process pools, on a PNG-decode workload (the reader's dominant real cost).

One JSON line per point:
  {"metric": "scaling", "pool": "thread", "workers": 4, "samples_per_sec": ...,
   "host_cores": N}

The docs/benchmarks.md "cores_needed" budget formula is backed by this curve —
run it on the host whose budget you are sizing (scaling is flat on a 1-core
host by construction; the 8-CPU dryrun environment shows the real slope).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def build_store(url, rows):
    from bench_duty import build_png_store
    build_png_store(url, rows)


def measure(url, pool, workers, measure_rows=2000, warmup_rows=200):
    from petastorm_tpu import make_reader
    with make_reader(url, reader_pool_type=pool, workers_count=workers,
                     output='columnar', shuffle_row_groups=True, seed=0,
                     num_epochs=None) as reader:
        it = iter(reader)
        seen = 0
        while seen < warmup_rows:
            seen += len(next(it)[0])
        seen = 0
        t0 = time.perf_counter()
        while seen < measure_rows:
            seen += len(next(it)[0])
        dt = time.perf_counter() - t0
    return seen / dt


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--workers', default='1,2,4,8')
    parser.add_argument('--pools', default='thread,process')
    parser.add_argument('--rows', type=int, default=512)
    parser.add_argument('--measure-rows', type=int, default=2000)
    parser.add_argument('--keep-dir', default=None)
    args = parser.parse_args(argv)

    tmpdir = args.keep_dir or tempfile.mkdtemp(prefix='bench_scaling_')
    # stamp the kept store with its row count so a changed --rows rebuilds
    # instead of silently measuring a stale store
    store_dir = os.path.join(tmpdir, 'store_{}rows'.format(args.rows))
    url = 'file://' + store_dir
    if not os.path.exists(os.path.join(store_dir, '_common_metadata')):
        build_store(url, args.rows)

    for pool in args.pools.split(','):
        for w in (int(x) for x in args.workers.split(',')):
            rate = measure(url, pool.strip(), w, measure_rows=args.measure_rows)
            print(json.dumps({'metric': 'scaling', 'pool': pool.strip(), 'workers': w,
                              'samples_per_sec': round(rate, 1),
                              'host_cores': os.cpu_count()}), flush=True)


if __name__ == '__main__':
    main()
