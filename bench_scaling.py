#!/usr/bin/env python
"""Worker-pool scaling curve: samples/sec at workers in {1,2,4,8} for thread
and process pools, on a PNG-decode workload (the reader's dominant real cost)
or the decode-free raw-tensor store (``--store raw`` — the pure-transport
stress case).

One JSON line per point:
  {"metric": "scaling", "pool": "thread", "workers": 4, "samples_per_sec": ...,
   "host_cores": N}

Each point is the MEDIAN of ``--reps`` runs of ``--measure-rows`` rows —
sub-second single runs on a contended 1-core host spread +-20% and made the
round-4 table misleading (process/thread looked like 0.64 when the stable
ratio is ~0.78).

The docs/benchmarks.md "cores_needed" budget formula is backed by this curve —
run it on the host whose budget you are sizing (scaling is flat on a 1-core
host by construction; the 8-CPU dryrun environment shows the real slope).

``--store raw --remote-mock`` measures the CHUNK-CACHED remote path (local
files behind the retry/remote wrapper + the chunk store): the warmup pass
fills the cache, so the reported rate is the epoch-2+ warm-cache rate —
comparable head-to-head with the plain ``--store raw`` local number, the
"remote store at local speed" claim measured instead of asserted.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def build_store(url, rows, store='png', image_size=160, num_classes=1000):
    if store == 'png':
        from bench_duty import build_png_store
        build_png_store(url, rows)
    else:
        from bench_duty import build_raw_store
        build_raw_store(url, rows, image_size, num_classes)


def measure(url, pool, workers, measure_rows=2000, warmup_rows=200,
            chunk_cache=None, telemetry=None, chaos=False, protocol_monitor=False):
    from petastorm_tpu import faults, make_reader
    recovery = None
    if chaos:
        # deterministic faults on the real code paths (docs/robustness.md):
        # process pools take a SIGKILL mid-item (supervised respawn + requeue);
        # in-process pools take one injected transient error (requeue). Each
        # run gets a fresh one-shot state dir so every rep recovers once.
        state_dir = tempfile.mkdtemp(prefix='bench_chaos_')
        if pool == 'process':
            plan = faults.FaultPlan(kill_items=(0,), kill_once=True, state_dir=state_dir)
        else:
            plan = faults.FaultPlan(error_items=(0,), error_times=1, state_dir=state_dir)
        faults.install(plan)
    try:
        with make_reader(url, reader_pool_type=pool, workers_count=workers,
                         output='columnar', shuffle_row_groups=True, seed=0,
                         num_epochs=None, chunk_cache=chunk_cache,
                         telemetry=telemetry,
                         protocol_monitor=True if protocol_monitor else None,
                         on_error='skip' if chaos else 'raise') as reader:
            it = iter(reader)
            seen = 0
            while seen < warmup_rows:
                seen += len(next(it)[0])
            seen = 0
            t0 = time.perf_counter()
            while seen < measure_rows:
                seen += len(next(it)[0])
            dt = time.perf_counter() - t0
            if chaos:
                diag = reader.diagnostics
                recovery = {k: diag.get(k, 0) for k in
                            ('worker_restarts', 'items_requeued', 'items_quarantined')}
    finally:
        if chaos:
            faults.uninstall()
    return seen / dt, recovery


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--workers', default='1,2,4,8')
    parser.add_argument('--pools', default='thread,process')
    parser.add_argument('--store', default='png', choices=('png', 'raw'))
    parser.add_argument('--rows', type=int, default=512)
    parser.add_argument('--measure-rows', type=int, default=9000)
    parser.add_argument('--reps', type=int, default=3,
                        help='runs per point; the median is reported')
    parser.add_argument('--warmup-rows', type=int, default=200)
    parser.add_argument('--remote-mock', action='store_true',
                        help='read through mock-remote:// (local fs behind the '
                             'retry/remote wrapper) with the chunk store enabled '
                             '— measures the chunk-cached remote path; the '
                             'warmup pass fills the cache, so the measured '
                             'region is the epoch-2+ (warm-cache) rate')
    parser.add_argument('--keep-dir', default=None)
    parser.add_argument('--telemetry', choices=('off', 'counters', 'spans'), default=None,
                        help='pipeline telemetry level (default: counters; '
                             '--trace-out implies spans)')
    parser.add_argument('--trace-out', default=None,
                        help='write a Perfetto-loadable Chrome trace of the sweep here')
    parser.add_argument('--chaos', action='store_true',
                        help='seeded fault injection per run (process pools: one '
                             'SIGKILLed worker mid-item; thread/dummy: one injected '
                             'transient error) — the measured rate then INCLUDES '
                             'recovery overhead, and each point reports the '
                             'recovery counters (docs/robustness.md)')
    parser.add_argument('--protocol-monitor', action='store_true',
                        help='attach the worker-pool protocol conformance monitor '
                             '(docs/protocol.md) to every measured reader — a '
                             '--chaos sweep then also PROVES each recovery followed '
                             'the supervision protocol, not just that row counts '
                             'came out right')
    args = parser.parse_args(argv)
    telemetry = args.telemetry
    if args.trace_out and telemetry in (None, 'off', 'counters'):
        telemetry = 'spans'

    tmpdir = args.keep_dir or tempfile.mkdtemp(prefix='bench_scaling_')
    # stamp the kept store with its flavor+layout+row count so changed args or
    # a writer-layout change rebuild instead of silently measuring stale bytes
    from bench_duty import RAW_STORE_FORMAT
    flavor = '{}-{}'.format(args.store, RAW_STORE_FORMAT) if args.store == 'raw' else args.store
    store_dir = os.path.join(tmpdir, 'store_{}_{}rows'.format(flavor, args.rows))
    if not os.path.exists(os.path.join(store_dir, '_common_metadata')):
        build_store('file://' + store_dir, args.rows, store=args.store)
    chunk_cache = None
    if args.remote_mock:
        # the chunk-cached remote path: local files behind the retry wrapper
        # ride the exact remote code (retrying streams, ranged chunk fetches,
        # mirror mmaps) without a cloud credential
        url = 'mock-remote://' + store_dir
        chunk_cache = os.path.join(tmpdir, 'chunk_cache')
    else:
        url = 'file://' + store_dir

    for pool in args.pools.split(','):
        for w in (int(x) for x in args.workers.split(',')):
            results = [measure(url, pool.strip(), w, measure_rows=args.measure_rows,
                               warmup_rows=args.warmup_rows, chunk_cache=chunk_cache,
                               telemetry=telemetry, chaos=args.chaos,
                               protocol_monitor=args.protocol_monitor)
                       for _ in range(args.reps)]
            runs = [r for r, _ in results]
            point = {'metric': 'scaling', 'pool': pool.strip(), 'workers': w,
                     'store': args.store,
                     'remote_mock': bool(args.remote_mock),
                     'samples_per_sec': round(statistics.median(runs), 1),
                     'runs': [round(r, 1) for r in runs],
                     'host_cores': os.cpu_count()}
            if args.chaos:
                recoveries = [rec for _, rec in results if rec]
                point['chaos'] = {
                    k: sum(rec.get(k, 0) for rec in recoveries)
                    for k in ('worker_restarts', 'items_requeued', 'items_quarantined')}
            print(json.dumps(point), flush=True)

    if args.trace_out:
        from petastorm_tpu import observability as obs
        n_events = obs.export_chrome_trace(args.trace_out)
        print(json.dumps({'metric': 'trace_exported', 'path': args.trace_out,
                          'events': n_events}), flush=True)


if __name__ == '__main__':
    main()
