"""Packaging: the framework must install and expose console entry points
(reference setup.py:32-95 — extras, console_scripts, shipped package data)."""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:
    import tomllib
except ImportError:  # py<3.11
    tomllib = None


def _pyproject():
    if tomllib is None:
        pytest.skip('tomllib unavailable')
    with open(os.path.join(REPO, 'pyproject.toml'), 'rb') as f:
        return tomllib.load(f)


def test_console_scripts_declared_and_resolvable():
    proj = _pyproject()['project']
    scripts = proj['scripts']
    assert set(scripts) == {'pstpu-throughput', 'pstpu-copy-dataset',
                            'pstpu-generate-metadata', 'pstpu-metadata-util',
                            'petastorm-tpu-lint', 'petastorm-tpu-race',
                            'petastorm-tpu-diagnose',
                            'petastorm-tpu-modelcheck', 'petastorm-tpu-autotune',
                            'petastorm-tpu-serve', 'petastorm-tpu-blackbox'}
    import importlib
    for target in scripts.values():
        mod_name, func_name = target.split(':')
        func = getattr(importlib.import_module(mod_name), func_name)
        assert callable(func)


def test_extras_cover_optional_adapters():
    extras = _pyproject()['project']['optional-dependencies']
    assert {'torch', 'tf', 'spark', 'test'} <= set(extras)


def test_native_sources_ship_as_package_data():
    data = _pyproject()['tool']['setuptools']['package-data']
    assert '*.cpp' in data['petastorm_tpu.native']
    # every native kernel source actually present, matching build.py's inputs
    from petastorm_tpu.native import build
    for src in (build.SOURCE, build.SHM_SOURCE, build.IMG_SOURCE):
        assert os.path.exists(src), src


def test_installed_entry_points_run():
    """When the package is installed (the dev/CI environment does
    ``pip install -e .``), every console script must execute ``--help``."""
    missing = [s for s in ('pstpu-throughput', 'pstpu-copy-dataset',
                           'pstpu-generate-metadata', 'pstpu-metadata-util')
               if shutil.which(s) is None]
    if missing:
        pytest.skip('package not installed into this environment: %s' % missing)
    for script in ('pstpu-throughput', 'pstpu-copy-dataset',
                   'pstpu-generate-metadata', 'pstpu-metadata-util'):
        out = subprocess.run([script, '--help'], capture_output=True, timeout=120)
        assert out.returncode == 0, (script, out.stderr[-500:])

@pytest.fixture(scope='module')
def built_wheel(tmp_path_factory):
    """Stage a pristine source copy and build the wheel ONCE for the module
    (building in the live tree would drop build/ + egg-info into the repo, and
    setuptools reuses a stale build/lib without cleaning). Skips when pip is
    unavailable."""
    try:
        subprocess.run([sys.executable, '-m', 'pip', '--version'],
                       capture_output=True, check=True, timeout=60)
    except (subprocess.CalledProcessError, OSError):
        pytest.skip('pip unavailable')
    d = tmp_path_factory.mktemp('wheelbuild')
    srcdir = os.path.join(str(d), 'src')
    os.makedirs(srcdir)
    for f in ('pyproject.toml', 'README.md'):
        shutil.copy(os.path.join(REPO, f), srcdir)
    shutil.copytree(
        os.path.join(REPO, 'petastorm_tpu'), os.path.join(srcdir, 'petastorm_tpu'),
        ignore=shutil.ignore_patterns('__pycache__', '*.so', '*.so.*', '*.lock', '*.stamp'))
    wheeldir = os.path.join(str(d), 'wheels')
    out = subprocess.run(
        [sys.executable, '-m', 'pip', 'wheel', srcdir, '--no-build-isolation',
         '--no-deps', '-w', wheeldir, '-q'], capture_output=True, timeout=600)
    # offline-safe flags: a nonzero exit is a real packaging regression
    assert out.returncode == 0, out.stderr[-1000:]
    wheels = [f for f in os.listdir(wheeldir) if f.endswith('.whl')]
    assert len(wheels) == 1
    return os.path.join(wheeldir, wheels[0])


def test_wheel_builds_with_sources_and_without_tests(built_wheel):
    """The wheel ships the .cpp kernel sources (compiled on first use) but
    neither tests nor prebuilt .so."""
    import zipfile
    names = zipfile.ZipFile(built_wheel).namelist()
    from petastorm_tpu.native import build
    expected = {'petastorm_tpu/native/' + os.path.basename(s)
                for s in (build.SOURCE, build.SHM_SOURCE, build.IMG_SOURCE)}
    assert {n for n in names if n.endswith('.cpp')} == expected
    assert not any(n.startswith('tests/') for n in names)
    assert not any(n.endswith('.so') for n in names)


def test_wheel_installs_and_imports_from_target(built_wheel, tmp_path):
    """The built wheel must actually import when installed standalone (catches
    missing py files/package-data that content listing alone would not)."""
    target = str(tmp_path / 'site')
    out = subprocess.run(
        [sys.executable, '-m', 'pip', 'install', built_wheel, '--no-deps',
         '--target', target, '-q'], capture_output=True, timeout=600)
    assert out.returncode == 0, out.stderr[-500:]
    # import ONLY from the target (cwd moved away; repo not on path)
    probe = ("import sys; sys.path.insert(0, {!r}); "
             "import petastorm_tpu; "
             "assert petastorm_tpu.__file__.startswith({!r}), petastorm_tpu.__file__; "
             "from petastorm_tpu import make_reader, make_batch_reader; "
             "from petastorm_tpu.native import build; "
             "import os; assert os.path.exists(build.IMG_SOURCE); "
             "print('WHEEL IMPORT OK')").format(target, target)
    out = subprocess.run([sys.executable, '-c', probe], capture_output=True,
                         text=True, timeout=120, cwd=str(tmp_path))
    assert out.returncode == 0, out.stderr[-500:]
    assert 'WHEEL IMPORT OK' in out.stdout
