"""Native C++ row-group reader kernel tests (SURVEY.md §2.10 component)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from petastorm_tpu import native


pytestmark = pytest.mark.skipif(not native.is_available(),
                                reason='native kernel not built/available')


@pytest.fixture(scope='module')
def parquet_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp('native') / 'data.parquet')
    rng = np.random.default_rng(7)
    table = pa.table({
        'id': pa.array(np.arange(1000, dtype=np.int64)),
        'value': pa.array(rng.random(1000)),
        'name': pa.array(['row_{}'.format(i) for i in range(1000)]),
        'blob': pa.array([bytes([i % 256] * 10) for i in range(1000)], type=pa.binary()),
        'tags': pa.array([[i, i + 1] for i in range(1000)], type=pa.list_(pa.int64())),
    })
    pq.write_table(table, path, row_group_size=100)
    return path


def test_metadata(parquet_file):
    with native.NativeParquetFile(parquet_file) as f:
        assert f.num_rows == 1000
        assert f.num_row_groups == 10
        assert f.metadata.num_row_groups == 10
        assert f.metadata.row_group(3).num_rows == 100


def test_read_full_row_group_matches_pyarrow(parquet_file):
    with native.NativeParquetFile(parquet_file) as f:
        table = f.read_row_group(2)
    expected = pq.ParquetFile(parquet_file).read_row_group(2)
    assert table.num_rows == 100
    assert table.column_names == expected.column_names
    assert table.equals(expected)


def test_read_column_subset(parquet_file):
    with native.NativeParquetFile(parquet_file) as f:
        table = f.read_row_group(0, columns=['value', 'id'])
    assert set(table.column_names) == {'id', 'value'}
    assert table['id'].to_pylist() == list(range(100))


def test_read_nested_list_column(parquet_file):
    with native.NativeParquetFile(parquet_file) as f:
        table = f.read_row_group(1, columns=['tags'])
    assert table.column_names == ['tags']
    assert table['tags'][0].as_py() == [100, 101]


def test_unknown_column_raises(parquet_file):
    with native.NativeParquetFile(parquet_file) as f:
        with pytest.raises(KeyError, match='nope'):
            f.read_row_group(0, columns=['nope'])


def test_row_group_out_of_range(parquet_file):
    with native.NativeParquetFile(parquet_file) as f:
        with pytest.raises(IOError):
            f.read_row_group(99)


def test_open_missing_file_raises(tmp_path):
    with pytest.raises(IOError):
        native.NativeParquetFile(str(tmp_path / 'missing.parquet'))


def test_open_parquet_dispatch_local(parquet_file):
    import pyarrow.fs as pafs
    f = native.open_parquet(parquet_file, pafs.LocalFileSystem())
    assert isinstance(f, native.NativeParquetFile)
    f.close()


def test_open_parquet_nonlocal_fs_falls_back(parquet_file):
    # non-local filesystems dispatch to the pyarrow path
    import pyarrow.fs as pafs

    class FakeFs(pafs.SubTreeFileSystem):
        pass

    fs = FakeFs('/', pafs.LocalFileSystem())
    f = native.open_parquet(parquet_file.lstrip('/'), fs)
    assert isinstance(f, pq.ParquetFile)


def test_open_parquet_disable_env(parquet_file, monkeypatch):
    # the kill switch must force the pyarrow path even on a local filesystem;
    # reset the module-level load cache so the env check actually re-runs
    import pyarrow.fs as pafs

    monkeypatch.setenv('PETASTORM_TPU_DISABLE_NATIVE', '1')
    # monkeypatch restores the cached handle/flag after the test
    monkeypatch.setattr(native, '_lib', None)
    monkeypatch.setattr(native, '_load_failed', False)
    f = native.open_parquet(parquet_file, pafs.LocalFileSystem())
    assert isinstance(f, pq.ParquetFile)


def test_reader_end_to_end_uses_native(synthetic_dataset):
    """Full make_reader path over the native kernel (workers call open_parquet)."""
    from petastorm_tpu import make_reader
    with make_reader(synthetic_dataset.url, num_epochs=1,
                     schema_fields=['id', 'matrix']) as reader:
        rows = list(reader)
    assert len(rows) == len(synthetic_dataset.data)
    assert rows[0].matrix.shape == (32, 16, 3)


def test_native_concurrent_reads(parquet_file):
    """Shared handle: reads serialize on the handle mutex, no corruption."""
    import threading
    with native.NativeParquetFile(parquet_file) as f:
        results = [None] * 8
        def read(i):
            results[i] = f.read_row_group(i % 10, columns=['id'])
        threads = [threading.Thread(target=read, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    for i, table in enumerate(results):
        assert table['id'].to_pylist() == list(range((i % 10) * 100, (i % 10) * 100 + 100))
