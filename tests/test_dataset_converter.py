"""DataFrame converter tests (reference petastorm/tests/test_spark_dataset_converter.py,
re-targeted at the backend-neutral pandas/Arrow core — no Spark required)."""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from petastorm_tpu.spark import (DatasetConverter, make_converter, make_spark_converter,
                                 register_delete_dir_handler)
from petastorm_tpu.spark import dataset_converter as dc
from petastorm_tpu.spark_utils import dataset_as_dataframe


@pytest.fixture
def cache_dir(tmp_path):
    d = tmp_path / 'converter_cache'
    d.mkdir()
    return 'file://' + str(d)


@pytest.fixture(autouse=True)
def _clear_cache_registry():
    with dc._cache_lock:
        dc._cache_entries.clear()
    yield
    with dc._cache_lock:
        dc._cache_entries.clear()


def _df(n=100):
    rng = np.random.default_rng(0)
    return pd.DataFrame({
        'id': np.arange(n, dtype=np.int64),
        'value': rng.random(n),  # float64 on purpose (precision test)
        'label': (np.arange(n) % 3).astype(np.int32),
    })


def test_converter_roundtrip_jax(cache_dir):
    conv = make_converter(_df(), parent_cache_dir_url=cache_dir)
    assert len(conv) == 100
    with conv.make_jax_loader(batch_size=10, num_epochs=1) as loader:
        batches = list(loader)
    assert sum(b['id'].shape[0] for b in batches) == 100
    assert batches[0]['value'].dtype == np.float32  # default precision


def test_converter_precision_float64(cache_dir):
    conv = make_converter(_df(), parent_cache_dir_url=cache_dir, precision='float64')
    with conv.make_jax_loader(batch_size=50, num_epochs=1) as loader:
        batch = next(iter(loader))
    assert batch['value'].dtype == np.float64


def test_converter_invalid_precision(cache_dir):
    with pytest.raises(ValueError, match='precision'):
        make_converter(_df(), parent_cache_dir_url=cache_dir, precision='float16')


def test_converter_dedups_same_content(cache_dir):
    conv1 = make_converter(_df(), parent_cache_dir_url=cache_dir)
    conv2 = make_converter(_df(), parent_cache_dir_url=cache_dir)  # re-created, equal
    assert conv1.cache_dir_url == conv2.cache_dir_url


def test_converter_distinct_content_not_deduped(cache_dir):
    conv1 = make_converter(_df(), parent_cache_dir_url=cache_dir)
    df2 = _df()
    df2['value'] = df2['value'] + 1.0
    conv2 = make_converter(df2, parent_cache_dir_url=cache_dir)
    assert conv1.cache_dir_url != conv2.cache_dir_url


def test_converter_distinct_options_not_deduped(cache_dir):
    conv1 = make_converter(_df(), parent_cache_dir_url=cache_dir)
    conv2 = make_converter(_df(), parent_cache_dir_url=cache_dir, precision='float64')
    assert conv1.cache_dir_url != conv2.cache_dir_url


def test_converter_array_columns(cache_dir):
    # tensor/feature-vector columns are the core use case: the fingerprint and
    # materialization must both handle ndarray cells
    df = pd.DataFrame({
        'id': np.arange(4, dtype=np.int64),
        'feat': [np.full(3, float(i), dtype=np.float32) for i in range(4)],
    })
    conv = make_converter(df, parent_cache_dir_url=cache_dir)
    conv2 = make_converter(df.copy(), parent_cache_dir_url=cache_dir)
    assert conv.cache_dir_url == conv2.cache_dir_url  # dedup still works
    with conv.make_jax_loader(batch_size=4, num_epochs=1) as loader:
        batch = next(iter(loader))
    assert batch['feat'].shape == (4, 3)
    assert batch['feat'][2][0] == 2.0


def test_converter_new_parent_dir_rematerializes(tmp_path):
    dir_a = 'file://' + str(tmp_path / 'a')
    dir_b = 'file://' + str(tmp_path / 'b')
    (tmp_path / 'a').mkdir()
    (tmp_path / 'b').mkdir()
    conv_a = make_converter(_df(), parent_cache_dir_url=dir_a)
    conv_b = make_converter(_df(), parent_cache_dir_url=dir_b)
    assert conv_a.cache_dir_url.startswith(dir_a)
    assert conv_b.cache_dir_url.startswith(dir_b)


def test_converter_accepts_arrow_table(cache_dir):
    table = pa.table({'id': np.arange(10, dtype=np.int64),
                      'x': np.linspace(0, 1, 10)})
    conv = make_converter(table, parent_cache_dir_url=cache_dir)
    assert len(conv) == 10
    with conv.make_jax_loader(batch_size=5, num_epochs=1) as loader:
        batch = next(iter(loader))
    assert batch['x'].dtype == np.float32


def test_converter_rejects_unsupported_type(cache_dir):
    with pytest.raises(TypeError):
        make_converter([1, 2, 3], parent_cache_dir_url=cache_dir)


def test_converter_requires_cache_dir(monkeypatch):
    monkeypatch.delenv(dc.CACHE_DIR_ENV_VAR, raising=False)
    with pytest.raises(ValueError, match='cache dir'):
        make_converter(_df())


def test_converter_env_var_cache_dir(tmp_path, monkeypatch):
    monkeypatch.setenv(dc.CACHE_DIR_ENV_VAR, 'file://' + str(tmp_path))
    conv = make_converter(_df())
    assert conv.cache_dir_url.startswith('file://' + str(tmp_path))


def test_converter_delete(cache_dir):
    conv = make_converter(_df(), parent_cache_dir_url=cache_dir)
    local_path = conv.cache_dir_url[len('file://'):]
    assert os.path.exists(local_path)
    conv.delete()
    assert not os.path.exists(local_path)
    # deleting removed it from the dedup registry: converting again rematerializes
    conv2 = make_converter(_df(), parent_cache_dir_url=cache_dir)
    assert conv2.cache_dir_url != conv.cache_dir_url


def test_register_delete_dir_handler(cache_dir):
    calls = []
    register_delete_dir_handler(lambda url: calls.append(url))
    try:
        conv = make_converter(_df(), parent_cache_dir_url=cache_dir)
        conv.delete()
        assert calls == [conv.cache_dir_url]
    finally:
        register_delete_dir_handler(None)


def test_converter_pickle(cache_dir):
    import pickle
    conv = make_converter(_df(), parent_cache_dir_url=cache_dir)
    restored = pickle.loads(pickle.dumps(conv))
    assert restored.cache_dir_url == conv.cache_dir_url
    assert len(restored) == len(conv)


def test_converter_torch_dataloader(cache_dir):
    conv = make_converter(_df(), parent_cache_dir_url=cache_dir)
    with conv.make_torch_dataloader(batch_size=20, num_epochs=1) as loader:
        total = sum(batch['id'].shape[0] for batch in loader)
    assert total == 100


def test_converter_tf_dataset(cache_dir):
    tf = pytest.importorskip('tensorflow')
    conv = make_converter(_df(), parent_cache_dir_url=cache_dir)
    with conv.make_tf_dataset(batch_size=25, num_epochs=1) as dataset:
        batches = list(dataset)
    assert sum(int(b.id.shape[0]) for b in batches) == 100
    assert batches[0].value.dtype == tf.float32


def test_converter_sharded_loaders(cache_dir):
    conv = make_converter(_df(), parent_cache_dir_url=cache_dir,
                          parquet_row_group_size_bytes=1024)
    seen = []
    for shard in range(2):
        with conv.make_jax_loader(batch_size=10, num_epochs=1, drop_last=False,
                                  cur_shard=shard, shard_count=2) as loader:
            for b in loader:
                seen.extend(b['id'].tolist())
    assert sorted(seen) == list(range(100))


def test_make_spark_converter_alias():
    assert make_spark_converter is make_converter
    assert DatasetConverter is dc.SparkDatasetConverter


def test_dataset_as_dataframe(tmp_path):
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('S', [UnischemaField('id', np.int64, (), ScalarCodec(), False)])
    url = 'file://' + str(tmp_path / 'ds')
    write_petastorm_dataset(url, schema, ({'id': i} for i in range(30)),
                            rows_per_row_group=10)
    frame = dataset_as_dataframe(url)
    assert sorted(frame['id'].tolist()) == list(range(30))


# The pyspark-gated surfaces (dataset_as_rdd through a SparkSession, the
# Spark-DataFrame branch of make_spark_converter) EXECUTE in
# tests/test_spark_execution.py — against real pyspark when importable, else
# against the in-repo pyspark-API engine (petastorm_tpu/test_util/minispark.py).
