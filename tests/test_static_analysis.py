"""First-party invariant linter (petastorm_tpu.analysis).

Two layers:

* **Per-checker unit tests** — minimal positive/negative fixtures for each
  rule family, including one fixture per round-5 ADVICE defect proving that
  re-introducing it makes the corresponding checker fire (the acceptance
  contract of the analysis subsystem).
* **The tier-1 gate** — the full pass over the installed ``petastorm_tpu``
  package must be clean: any new violation fails pytest immediately.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from petastorm_tpu.analysis import ALL_CHECKERS, ALL_RULE_CODES, run_analysis
from petastorm_tpu.analysis.abi import AbiConformanceChecker
from petastorm_tpu.analysis.cpp_safety import CppSafetyChecker
from petastorm_tpu.analysis.buffers import NativeBufferChecker
from petastorm_tpu.analysis.core import (Baseline, SourceFile, load_baseline,
                                         run_checkers, write_baseline)
from petastorm_tpu.analysis.exceptions import (BaseExceptionContainmentChecker,
                                               ExceptionHygieneChecker)
from petastorm_tpu.analysis.hashability import HashabilityChecker
from petastorm_tpu.analysis.jax_purity import JaxPurityChecker
from petastorm_tpu.analysis.lifecycle import ResourceLifecycleChecker
from petastorm_tpu.analysis.locks import LockDisciplineChecker
from petastorm_tpu.analysis.protocol_lints import ProtocolLintChecker
from petastorm_tpu.analysis.races import RaceChecker
from petastorm_tpu.analysis.telemetry import TelemetrySpanChecker

import petastorm_tpu

PKG_DIR = os.path.dirname(os.path.abspath(petastorm_tpu.__file__))
BASELINE_PATH = os.path.join(PKG_DIR, 'analysis', 'analysis_baseline.json')


def _findings(checker, code_text, relpath='workers/fixture.py'):
    src = SourceFile('<fixture>', relpath, textwrap.dedent(code_text))
    assert src.parse_error is None, src.parse_error
    return [f for f in checker.check(src) if not src.is_suppressed(f.line, f.code)]


def _codes(checker, code_text, relpath='workers/fixture.py'):
    return [f.code for f in _findings(checker, code_text, relpath)]


# ---------------------------------------------------------------------------
# PT100/PT101 lock discipline
# ---------------------------------------------------------------------------

_LOCKED_CLASS = '''
    import threading

    class Pool(object):
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0

        def inc(self):
            with self._lock:
                self._count += 1

        def unsafe_reset(self):
            self._count = 0
'''


def test_pt100_flags_unguarded_write():
    findings = _findings(LockDisciplineChecker(), _LOCKED_CLASS)
    assert [f.code for f in findings] == ['PT100']
    assert '_count' in findings[0].message
    assert findings[0].snippet == 'self._count = 0'


def test_pt100_guarded_write_passes():
    clean = _LOCKED_CLASS.replace(
        'def unsafe_reset(self):\n            self._count = 0',
        'def safe_reset(self):\n            with self._lock:\n                self._count = 0')
    assert _codes(LockDisciplineChecker(), clean) == []


def test_pt100_init_writes_exempt():
    # __init__ writes happen before any other thread can exist
    code = '''
        import threading

        class C(object):
            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0

            def touch(self):
                with self._lock:
                    self._x += 1
    '''
    assert _codes(LockDisciplineChecker(), code) == []


def test_pt100_unguarded_attributes_ignored():
    # attributes never touched under the lock are not lock-guarded state
    code = '''
        import threading

        class C(object):
            def __init__(self):
                self._lock = threading.Lock()
                self._guarded = 0
                self._flag = False

            def work(self):
                with self._lock:
                    self._guarded += 1

            def stop(self):
                self._flag = True
    '''
    assert _codes(LockDisciplineChecker(), code) == []


def test_pt100_container_mutation_counts():
    code = '''
        import threading

        class C(object):
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items.append(x)

            def drop_all(self):
                self._items.clear()
    '''
    findings = _findings(LockDisciplineChecker(), code)
    assert [f.code for f in findings] == ['PT100']
    assert 'mutation' in findings[0].message


def test_pt100_scope_excludes_non_dataplane():
    checker = LockDisciplineChecker()
    src = SourceFile('<fixture>', 'etl/whatever.py', textwrap.dedent(_LOCKED_CLASS))
    assert not checker.matches(src)


def test_pt101_lock_order_cycle():
    code = '''
        import threading

        class AB(object):
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._x = 0
                self._y = 0

            def one(self):
                with self._a:
                    with self._b:
                        self._x = 1

            def two(self):
                with self._b:
                    with self._a:
                        self._y = 1
    '''
    codes = _codes(LockDisciplineChecker(), code)
    assert 'PT101' in codes


def test_pt101_consistent_order_passes():
    code = '''
        import threading

        class AB(object):
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._x = 0

            def one(self):
                with self._a:
                    with self._b:
                        self._x = 1

            def two(self):
                with self._a:
                    with self._b:
                        self._x = 2
    '''
    assert 'PT101' not in _codes(LockDisciplineChecker(), code)


def test_pt101_cycle_through_method_call():
    # one level of self.method() indirection while holding a lock
    code = '''
        import threading

        class AB(object):
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._x = 0
                self._y = 0

            def notify(self):
                with self._a:
                    self._x = 1

            def one(self):
                with self._b:
                    self.notify()

            def two(self):
                with self._a:
                    with self._b:
                        self._y = 1
    '''
    assert 'PT101' in _codes(LockDisciplineChecker(), code)


# ---------------------------------------------------------------------------
# PT200/PT201 resource lifecycle
# ---------------------------------------------------------------------------

def test_pt200_orphaned_construction():
    code = '''
        class Res(object):
            def close(self):
                pass

        def leak():
            r = Res()
            r.poke()
    '''
    codes = _codes(ResourceLifecycleChecker(), code, relpath='reader.py')
    assert codes == ['PT200']


def test_pt200_discarded_construction():
    code = '''
        class Res(object):
            def close(self):
                pass

        def fire_and_forget():
            Res()
    '''
    findings = _findings(ResourceLifecycleChecker(), code, relpath='reader.py')
    assert [f.code for f in findings] == ['PT200']
    assert 'discarded' in findings[0].message


def test_pt200_clean_lifecycles_pass():
    code = '''
        class Res(object):
            def close(self):
                pass

        def ok_with():
            with Res() as r:
                return r.read()

        def ok_release():
            r = Res()
            try:
                return r.read()
            finally:
                r.close()

        def ok_escapes(sink):
            r = Res()
            sink.register(r)

        def ok_returned():
            return Res()

        class Owner(object):
            def __init__(self):
                self._r = Res()
    '''
    assert _codes(ResourceLifecycleChecker(), code, relpath='reader.py') == []


def test_pt200_known_resource_classes():
    # pool/reader types from other modules are recognized by name
    code = '''
        def broken(worker_cls):
            pool = ThreadPool(4)
            pool.start(worker_cls)
    '''
    codes = _codes(ResourceLifecycleChecker(), code, relpath='examples/foo.py')
    assert codes == ['PT200']


def test_pt201_del_only_cleanup():
    code = '''
        class Leaky(object):
            def __del__(self):
                self._free()
    '''
    findings = _findings(ResourceLifecycleChecker(), code, relpath='native/x.py')
    assert [f.code for f in findings] == ['PT201']


def test_pt201_del_as_backstop_passes():
    code = '''
        class Fine(object):
            def close(self):
                pass

            def __del__(self):
                self.close()
    '''
    assert _codes(ResourceLifecycleChecker(), code, relpath='native/x.py') == []


# ---------------------------------------------------------------------------
# PT300 exception hygiene
# ---------------------------------------------------------------------------

def test_pt300_swallowing_handler():
    code = '''
        def pump(q):
            try:
                q.get()
            except Exception:
                pass
    '''
    assert _codes(ExceptionHygieneChecker(), code) == ['PT300']


def test_pt300_bare_except():
    code = '''
        def pump(q):
            try:
                q.get()
            except:
                return None
    '''
    assert _codes(ExceptionHygieneChecker(), code) == ['PT300']


def test_pt300_handled_paths_pass():
    code = '''
        import logging
        logger = logging.getLogger(__name__)

        def forwards(q, publish):
            try:
                q.get()
            except Exception as e:
                publish(e)

        def logs(q):
            try:
                q.get()
            except Exception:
                logger.exception('boom')

        def reraises(q):
            try:
                q.get()
            except Exception:
                raise

        def narrow(q):
            try:
                q.get()
            except KeyError:
                pass
    '''
    assert _codes(ExceptionHygieneChecker(), code) == []


def test_pt300_ble001_alias_suppresses():
    code = '''
        def pump(q):
            try:
                q.get()
            except Exception:  # noqa: BLE001 - teardown race, nothing to forward
                pass
    '''
    assert _codes(ExceptionHygieneChecker(), code) == []


def test_pt300_scope_excludes_etl():
    src = SourceFile('<fixture>', 'etl/metadata.py', 'x = 1\n')
    assert not ExceptionHygieneChecker().matches(src)


# ---------------------------------------------------------------------------
# PT701 BaseException containment in worker loops
# ---------------------------------------------------------------------------

def test_pt701_swallowed_baseexception():
    code = '''
        def worker_loop(q):
            try:
                q.get()
            except BaseException:
                pass
    '''
    assert _codes(BaseExceptionContainmentChecker(), code) == ['PT701']


def test_pt701_logging_alone_is_not_containment():
    """Stricter than PT300: a KeyboardInterrupt handler that logs and carries
    on still eats the cancellation — the pool wedges."""
    code = '''
        import logging
        logger = logging.getLogger(__name__)

        def worker_loop(q):
            try:
                q.get()
            except KeyboardInterrupt:
                logger.info('interrupted, continuing')
    '''
    assert _codes(BaseExceptionContainmentChecker(), code) == ['PT701']


def test_pt701_tuple_clause_matched():
    code = '''
        def worker_loop(q):
            try:
                q.get()
            except (ValueError, SystemExit):
                return None
    '''
    assert _codes(BaseExceptionContainmentChecker(), code) == ['PT701']


def test_pt701_reraise_forward_and_exit_pass():
    code = '''
        import os

        def cleanup_reraise(path, write, unlink):
            try:
                write(path)
            except BaseException:
                unlink(path)
                raise

        def forwards_to_error_channel(pump, q, put_final):
            try:
                pump(q)
            except BaseException as exc:
                put_final(exc)

        def deliberate_suicide(run):
            try:
                run()
            except KeyboardInterrupt:
                os._exit(1)

        def narrow_is_not_pt701(q):
            try:
                q.get()
            except Exception:  # noqa: BLE001 - PT300 territory, not PT701
                pass
    '''
    assert _codes(BaseExceptionContainmentChecker(), code) == []


def test_pt701_scope_excludes_etl():
    src = SourceFile('<fixture>', 'etl/metadata.py', 'x = 1\n')
    assert not BaseExceptionContainmentChecker().matches(src)


# ---------------------------------------------------------------------------
# PT400 JAX purity
# ---------------------------------------------------------------------------

def test_pt400_host_rng_and_time_in_jit():
    code = '''
        import functools
        import time

        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return x * time.time()

        @functools.partial(jax.jit, static_argnames=('n',))
        def noisy(x, n):
            return x + np.random.rand(n)
    '''
    codes = _codes(JaxPurityChecker(), code, relpath='ops/fixture.py')
    assert codes == ['PT400', 'PT400']


def test_pt400_jit_call_wiring():
    code = '''
        import jax
        import numpy as np

        def impure(x):
            return x * np.random.rand()

        fast = jax.jit(impure)
    '''
    assert _codes(JaxPurityChecker(), code, relpath='ops/fixture.py') == ['PT400']


def test_pt400_item_and_mutation():
    code = '''
        import jax

        @jax.jit
        def syncs(x):
            return float(x.sum().item())

        @jax.jit
        def mutates(x):
            x[0] = 1
            return x
    '''
    findings = _findings(JaxPurityChecker(), code, relpath='jax/fixture.py')
    assert [f.code for f in findings] == ['PT400', 'PT400']
    assert 'device sync' in findings[0].message
    assert 'at[...]' in findings[1].message


def test_pt400_pure_and_untraced_pass():
    code = '''
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def pure(x, key):
            noise = jax.random.normal(key, x.shape)
            y = jnp.zeros_like(x)
            return x.at[0].set(1.0) + noise + y

        def host_side(x):
            # not traced: host RNG is fine here
            return x * np.random.rand()

        @jax.jit
        def local_scratch(x):
            # subscript writes to locally-created names are trace-time constants
            lookup = {}
            lookup['a'] = 1
            return x * lookup['a']
    '''
    assert _codes(JaxPurityChecker(), code, relpath='ops/fixture.py') == []


# ---------------------------------------------------------------------------
# PT500/PT501/PT502 native-buffer safety
# ---------------------------------------------------------------------------

def test_pt500_escaping_views():
    code = '''
        import numpy as np

        def returns_view(buf):
            return np.frombuffer(buf, np.uint8)

        def stores_view(out, buf):
            out[0] = np.frombuffer(buf, np.uint8).reshape(-1)
    '''
    codes = _codes(NativeBufferChecker(), code, relpath='serializers.py')
    assert codes == ['PT500', 'PT500']


def test_pt500_serializer_defect_reintroduction():
    # the round-5 serializers.py defect: ragged object cells deserialized as
    # frombuffer views land read-only off the zmq transport
    code = '''
        import numpy as np

        def deserialize_ragged(mv, shapes, dt):
            col = np.empty(len(shapes), dtype=object)
            off = 0
            for i, shp in enumerate(shapes):
                n = dt.itemsize * shp[0]
                col[i] = np.frombuffer(mv[off:off + n], dtype=dt).reshape(shp)
                off += n
            return col
    '''
    assert _codes(NativeBufferChecker(), code, relpath='serializers.py') == ['PT500']


def test_pt500_copy_and_guard_pass():
    code = '''
        import numpy as np

        def copies(buf):
            return np.frombuffer(buf, np.uint8).copy()

        def guarded(buf):
            a = np.frombuffer(buf, np.uint8)
            return a if a.flags.writeable else a.copy()

        def consumed(buf):
            return int(np.frombuffer(buf, np.uint8)[0])

        def internal(buf):
            view = np.frombuffer(buf, np.uint8)
            return view.sum()
    '''
    assert _codes(NativeBufferChecker(), code, relpath='serializers.py') == []


def test_pt501_pagescan_defect_reintroduction():
    # the round-5 pagescan defect: the view length checked only against the
    # whole file, never the page's values region
    code = '''
        import pyarrow as pa

        def chunk_to_view(mm, off, nbytes):
            if off + nbytes > mm.size:
                return None
            return pa.py_buffer(memoryview(mm)[off:off + nbytes])
    '''
    codes = _codes(NativeBufferChecker(), code, relpath='native/pagescan.py')
    assert codes == ['PT501']


def test_pt501_per_page_bound_passes():
    code = '''
        import pyarrow as pa

        def chunk_to_view(mm, off, nbytes, region_len):
            if nbytes > region_len:
                return None
            if off + nbytes > mm.size:
                return None
            return pa.py_buffer(memoryview(mm)[off:off + nbytes])
    '''
    assert _codes(NativeBufferChecker(), code, relpath='native/pagescan.py') == []


_CPP_UNBOUNDED = '''
struct TReader {
  void skip_struct() {
    skip_value(12);
  }
  void skip_value(int type);
};

void TReader::skip_value(int type) {
  if (type == 12) skip_struct();
}
'''

_CPP_BOUNDED = '''
struct TReader {
  void skip_struct(int depth) {
    if (depth > 32) return;
    skip_value(12, depth);
  }
  void skip_value(int type, int depth);
};

void TReader::skip_value(int type, int depth) {
  if (type == 12) skip_struct(depth + 1);
}
'''


def test_pt502_cpp_recursion_defect_reintroduction():
    # the round-5 rowgroup_reader.cpp defect: unbounded thrift skip recursion
    src = SourceFile('<fixture>', 'native/fixture.cpp', _CPP_UNBOUNDED)
    codes = sorted(f.code for f in NativeBufferChecker().check(src))
    assert codes == ['PT502', 'PT502']


def test_pt502_depth_bounded_passes():
    src = SourceFile('<fixture>', 'native/fixture.cpp', _CPP_BOUNDED)
    assert list(NativeBufferChecker().check(src)) == []


def test_pt502_non_recursive_cpp_passes():
    code = '''
int helper(int x) { return x + 1; }
int caller(int x) { return helper(x); }
'''
    src = SourceFile('<fixture>', 'native/fixture.cpp', code)
    assert list(NativeBufferChecker().check(src)) == []


def test_pt503_pointer_from_temporary_flagged():
    # the fused-ABI lifetime defect: np.empty(...).ctypes.data dies before
    # the kernel dereferences it
    code = '''
    import numpy as np

    def call(lib, n):
        lib.pstpu_read_fused(np.empty(n).ctypes.data, n)
    '''
    assert _codes(NativeBufferChecker(), code,
                  relpath='native/fused.py') == ['PT503']


def test_pt503_descriptor_pointer_without_capacity_flagged():
    code = '''
    def fill(desc, buf):
        desc.out = buf.ctypes.data
        desc.chunk = buf.ctypes.data
        desc.chunk_len = buf.nbytes
    '''
    # .out set without .out_cap -> one finding; .chunk has its .chunk_len
    assert _codes(NativeBufferChecker(), code,
                  relpath='native/fused.py') == ['PT503']


def test_pt503_anchored_pointer_with_bounds_passes():
    code = '''
    def fill(desc, buf):
        desc.out = buf.ctypes.data
        desc.out_cap = buf.nbytes
        desc.chunk = buf.ctypes.data
        desc.chunk_len = buf.nbytes
    '''
    assert _codes(NativeBufferChecker(), code, relpath='native/fused.py') == []


# ---------------------------------------------------------------------------
# PT600 hashability
# ---------------------------------------------------------------------------

def test_pt600_retry_defect_reintroduction():
    # the round-5 retry.py defect: a filesystem handler growing __eq__ without
    # __hash__ silently unhashes itself and the PyFileSystem wrapping it
    code = '''
        class RetryingHandler(object):
            def __init__(self, fs, policy):
                self.fs = fs
                self.policy = policy

            def __eq__(self, other):
                return self.fs == other.fs and self.policy == other.policy
    '''
    codes = _codes(HashabilityChecker(), code, relpath='retry.py')
    assert codes == ['PT600']


def test_pt600_hash_defined_passes():
    code = '''
        class Fine(object):
            def __eq__(self, other):
                return True

            def __hash__(self):
                return 0

        class ExplicitlyUnhashable(object):
            __hash__ = None

            def __eq__(self, other):
                return True

        class NoEq(object):
            pass
    '''
    assert _codes(HashabilityChecker(), code, relpath='x.py') == []


# ---------------------------------------------------------------------------
# framework: noqa, baseline, syntax errors, runner
# ---------------------------------------------------------------------------

def test_noqa_suppresses_specific_code():
    code = '''
        class C(object):
            def __eq__(self, other):  # noqa: PT600 - identity map key, never hashed
                return True
    '''
    assert _codes(HashabilityChecker(), code, relpath='x.py') == []


def test_bare_noqa_suppresses_everything():
    code = '''
        class C(object):
            def __eq__(self, other):  # noqa
                return True
    '''
    assert _codes(HashabilityChecker(), code, relpath='x.py') == []


def test_noqa_other_code_does_not_suppress():
    code = '''
        class C(object):
            def __eq__(self, other):  # noqa: PT500
                return True
    '''
    assert _codes(HashabilityChecker(), code, relpath='x.py') == ['PT600']


def test_noqa_inside_string_is_ignored():
    code = '''
        class C(object):
            def __eq__(self, other):
                return "# noqa: PT600"
    '''
    assert _codes(HashabilityChecker(), code, relpath='x.py') == ['PT600']


# ---------------------------------------------------------------------------
# PT700 telemetry span hygiene
# ---------------------------------------------------------------------------

def test_pt700_flags_discarded_span():
    code = '''
        from petastorm_tpu import observability as obs

        def process():
            obs.stage('decode')
            do_work()
    '''
    findings = _findings(TelemetrySpanChecker(), code, relpath='x.py')
    assert [f.code for f in findings] == ['PT700']
    assert 'stage' in findings[0].message


def test_pt700_flags_unclosed_assigned_span():
    code = '''
        def process():
            t = start_span('decode')
            do_work()
    '''
    assert _codes(TelemetrySpanChecker(), code, relpath='x.py') == ['PT700']


def test_pt700_with_block_passes():
    code = '''
        from petastorm_tpu import observability as obs

        def process():
            with obs.stage('decode', cat='worker'):
                do_work()
            with obs.span('emit'):
                emit()
    '''
    assert _codes(TelemetrySpanChecker(), code, relpath='x.py') == []


def test_pt700_try_finally_close_passes():
    code = '''
        def process():
            t = start_span('decode')
            try:
                do_work()
            finally:
                t.finish()
    '''
    assert _codes(TelemetrySpanChecker(), code, relpath='x.py') == []


def test_pt700_escaping_span_passes():
    # ownership moves: returned, or handed to another call
    code = '''
        from petastorm_tpu import observability as obs

        def make_timer():
            return obs.stage('decode')

        def wrapped():
            run_with(obs.span('x'))
    '''
    assert _codes(TelemetrySpanChecker(), code, relpath='x.py') == []


def test_pt700_ignores_non_telemetry_receivers():
    # re.Match.span() and friends must not match
    code = '''
        import re

        def bounds(m):
            start, end = m.span()
            return m.span(1)
    '''
    assert _codes(TelemetrySpanChecker(), code, relpath='x.py') == []


def test_pt700_runs_clean_over_the_observability_subsystem():
    """The checklist acceptance: the new subsystem itself lints clean under
    its own rule (every span/timer it opens is context-managed)."""
    obs_dir = os.path.join(PKG_DIR, 'observability')
    findings = run_analysis([obs_dir], select=['PT700'])
    assert findings == [], '\n'.join(f.format() for f in findings)


# ---------------------------------------------------------------------------
# PT702 autotune action discipline
# ---------------------------------------------------------------------------

def test_pt702_unwrapped_actuator_flagged():
    from petastorm_tpu.analysis.autotune_lints import AutotuneActionChecker
    code = '''
        def grow(self):
            self._pool.add_worker_slot()
    '''
    codes = _codes(AutotuneActionChecker(), code, relpath='autotune/controller.py')
    assert codes == ['PT702']


def test_pt702_unclamped_value_flagged():
    from petastorm_tpu.analysis.autotune_lints import AutotuneActionChecker
    code = '''
        def raise_budget(self):
            with decision_span(knob='prefetch_bytes'):
                self._cache.set_prefetch_budget(self._cache.prefetch_budget_bytes * 2)
    '''
    codes = _codes(AutotuneActionChecker(), code, relpath='autotune/controller.py')
    assert codes == ['PT702']


def test_pt702_span_wrapped_and_clamped_passes():
    from petastorm_tpu.analysis.autotune_lints import AutotuneActionChecker
    code = '''
        def raise_budget(self):
            with decision_span(knob='prefetch_bytes'):
                target = clamp(self._before * 2, lo, hi)
                self._cache.set_prefetch_budget(target)

        def grow(self):
            with decision_span(knob='workers'):
                self._pool.add_worker_slot()

        def direct(self):
            with obs.span('autotune.decision'):
                self._loader.set_shuffle_capacity(clamp(8, 2, 64))
    '''
    assert _codes(AutotuneActionChecker(), code,
                  relpath='autotune/controller.py') == []


def test_pt702_scope_is_autotune_only():
    from petastorm_tpu.analysis.autotune_lints import AutotuneActionChecker
    src = SourceFile('<fixture>', 'workers/thread_pool.py',
                     'def f(pool):\n    pool.add_worker_slot()\n')
    assert not AutotuneActionChecker().matches(src)


def test_pt702_runs_clean_over_the_autotune_package():
    """The checklist acceptance: the controller itself obeys its own rule —
    every knob actuation is decision_span-wrapped and clamp-bounded."""
    autotune_dir = os.path.join(PKG_DIR, 'autotune')
    findings = run_analysis([autotune_dir], select=['PT702'])
    assert findings == [], '\n'.join(f.format() for f in findings)


# ---------------------------------------------------------------------------
# PT800/PT801 worker-pool protocol discipline
# ---------------------------------------------------------------------------

def test_pt800_flags_non_exhaustive_kind_dispatch():
    """The crafted violation of the acceptance criteria: a consumer switch
    missing a declared kind byte (here MSG_METRICS and friends) with no
    default silently drops that message class."""
    code = '''
        from petastorm_tpu.workers.protocol import MSG_DATA, MSG_DONE

        def consume(kind, payload):
            if kind == MSG_DATA:
                return payload
            elif kind == MSG_DONE:
                return None
    '''
    findings = _findings(ProtocolLintChecker(), code)
    assert [f.code for f in findings] == ['PT800']
    assert 'METRICS' in findings[0].message and 'ERROR' in findings[0].message


def test_pt800_legacy_underscore_names_recognized():
    code = '''
        from petastorm_tpu.workers.protocol import MSG_DATA as _DATA, MSG_DONE as _DONE

        def consume(msg):
            if msg[0] == _DATA:
                return msg
            elif msg[0] == _DONE:
                return None
    '''
    assert _codes(ProtocolLintChecker(), code) == ['PT800']


def test_pt800_else_default_passes():
    code = '''
        from petastorm_tpu.workers.protocol import MSG_DATA, MSG_DONE

        def consume(kind, payload):
            if kind == MSG_DATA:
                return payload
            elif kind == MSG_DONE:
                return None
            else:
                raise RuntimeError(kind)
    '''
    assert _codes(ProtocolLintChecker(), code) == []


def test_pt800_full_coverage_passes():
    code = '''
        from petastorm_tpu.workers.protocol import (MSG_BLOB, MSG_DATA, MSG_DONE,
            MSG_ERROR, MSG_HEARTBEAT, MSG_METRICS, MSG_STARTED)

        def consume(kind):
            if kind == MSG_DATA or kind == MSG_BLOB:
                return 1
            elif kind == MSG_DONE:
                return 2
            elif kind in (MSG_METRICS, MSG_HEARTBEAT):
                return 3
            elif kind == MSG_ERROR:
                return 4
            elif kind == MSG_STARTED:
                return 5
    '''
    assert _codes(ProtocolLintChecker(), code) == []


def test_pt800_single_comparison_is_a_guard_not_a_dispatch():
    code = '''
        from petastorm_tpu.workers.protocol import MSG_STARTED

        def is_handshake(kind):
            if kind == MSG_STARTED:
                return True
            return False
    '''
    assert _codes(ProtocolLintChecker(), code) == []


def test_pt801_local_constant_definition_flagged():
    """The crafted violation: a pool module growing its own kind table —
    exactly the drift the canonical workers/protocol.py exists to end."""
    findings = _findings(ProtocolLintChecker(), '_DATA, _DONE, _ERROR = 0, 1, 2\n')
    assert [f.code for f in findings] == ['PT801', 'PT801', 'PT801']
    assert 'workers.protocol' in findings[0].message


def test_pt801_raw_kind_byte_comparison_flagged():
    code = '''
        def consume(msg):
            return msg[0] == b'D'
    '''
    assert _codes(ProtocolLintChecker(), code) == ['PT801']


def test_pt801_canonical_module_and_imports_exempt():
    canonical = SourceFile('<fixture>', 'workers/protocol.py',
                           "MSG_DATA = b'D'\nCONTROL_FINISHED = b'FINISHED'\n")
    assert [f for f in ProtocolLintChecker().check(canonical)] == []
    code = '''
        from petastorm_tpu.workers.protocol import MSG_DATA, ring_header

        def frame(seq):
            return ring_header(MSG_DATA, seq)
    '''
    assert _codes(ProtocolLintChecker(), code) == []


def test_pt801_scope_is_workers_only():
    src = SourceFile('<fixture>', 'observability/metrics.py', "_DATA = 0\n")
    assert not ProtocolLintChecker().matches(src)


def test_pt8xx_run_clean_over_the_workers_package():
    """The checklist acceptance: the migrated pools themselves satisfy the
    new rules — every kind dispatch exhaustive, every constant imported from
    the canonical module."""
    findings = run_analysis([os.path.join(PKG_DIR, 'workers')], select=['PT8'])
    assert findings == [], '\n'.join(f.format() for f in findings)


def test_baseline_absorbs_with_multiplicity(tmp_path):
    src = SourceFile('<fixture>', 'x.py', textwrap.dedent('''
        class A(object):
            def __eq__(self, other):
                return True

        class B(object):
            def __eq__(self, other):
                return True
    '''))
    findings = run_checkers([HashabilityChecker()], [src])
    assert len(findings) == 2
    path = str(tmp_path / 'analysis_baseline.json')
    write_baseline(path, findings)
    baseline = load_baseline(path)
    assert baseline.absorb(findings) == []
    # a THIRD violation with identical text is NOT absorbed (count exceeded)
    findings3 = findings + [findings[0]]
    assert len(baseline.absorb(findings3)) == 1


def test_baseline_survives_line_moves(tmp_path):
    v1 = SourceFile('<fixture>', 'x.py', textwrap.dedent('''
        class A(object):
            def __eq__(self, other):
                return True
    '''))
    path = str(tmp_path / 'b.json')
    write_baseline(path, run_checkers([HashabilityChecker()], [v1]))
    v2 = SourceFile('<fixture>', 'x.py', textwrap.dedent('''
        import os

        UNRELATED = os.sep

        class A(object):
            def __eq__(self, other):
                return True
    '''))
    assert run_checkers([HashabilityChecker()], [v2], load_baseline(path)) == []


def test_missing_baseline_is_empty(tmp_path):
    assert load_baseline(str(tmp_path / 'nope.json')).absorb([]) == []


def test_syntax_error_reported_not_skipped():
    src = SourceFile('<fixture>', 'x.py', 'def broken(:\n')
    findings = run_checkers([HashabilityChecker()], [src])
    assert [f.code for f in findings] == ['PT000']


# ---------------------------------------------------------------------------
# PT900/PT901/PT902/PT903/PT904 — ABI conformance + C++ overflow/bounds
# ---------------------------------------------------------------------------

NATIVE_SRC = os.path.join(PKG_DIR, 'native')


def _mutated_native_tree(tmp_path, mutations):
    """Copy the REAL native sources with seeded text mutations applied —
    the teeth proof runs the checkers against the production code, not a
    toy (a rule that only fires on fixtures is not protecting the tree)."""
    nat = tmp_path / 'native'
    nat.mkdir()
    for fn in os.listdir(NATIVE_SRC):
        if not fn.endswith(('.py', '.cpp')):
            continue
        with open(os.path.join(NATIVE_SRC, fn)) as f:
            text = f.read()
        for old, new in mutations.get(fn, ()):
            assert old in text, 'mutation anchor vanished from {}'.format(fn)
            text = text.replace(old, new)
        (nat / fn).write_text(text)
    return str(tmp_path)


def _mutant_codes(tmp_path, mutations, select):
    return [f.code for f in run_analysis(
        [_mutated_native_tree(tmp_path, mutations)], select=select)]


def test_real_native_tree_is_abi_clean(tmp_path):
    """The unmutated native sources pass every PT9xx rule (the same property
    the tier-1 gate enforces, isolated here for debuggability)."""
    clean = _mutated_native_tree(tmp_path, {})
    assert run_analysis([clean], select=['PT9']) == []


def test_pt900_field_reorder_flagged(tmp_path):
    codes = _mutant_codes(tmp_path, {'rowgroup_reader.cpp': [(
        'uint64_t chunk_len;\n  uint8_t* out;',
        'uint8_t* out;\n  uint64_t chunk_len;')]}, ['PT900'])
    assert 'PT900' in codes


def test_pt900_widened_type_flagged(tmp_path):
    codes = _mutant_codes(tmp_path, {'rowgroup_reader.cpp': [(
        'int32_t itemsize;', 'int64_t itemsize;')]}, ['PT900'])
    assert 'PT900' in codes


def test_pt900_added_field_flagged(tmp_path):
    codes = _mutant_codes(tmp_path, {'rowgroup_reader.cpp': [(
        'uint64_t aux1;', 'uint64_t aux1;\n  uint64_t aux2;')]}, ['PT900'])
    assert 'PT900' in codes


def test_pt900_abi_version_literal_sync(tmp_path):
    """The satellite acceptance: EXPECTED_ABI and pstpu_abi_version() are
    literal-synced — bumping one without the other is a PT900 finding."""
    from petastorm_tpu.native import fused
    with open(os.path.join(NATIVE_SRC, 'rowgroup_reader.cpp')) as f:
        cpp = f.read()
    assert 'return {};'.format(fused.EXPECTED_ABI) in \
        cpp.split('pstpu_abi_version()', 1)[1][:40]
    findings = run_analysis([_mutated_native_tree(tmp_path, {
        'rowgroup_reader.cpp': [(
            'int pstpu_abi_version() {{ return {}; }}'.format(fused.EXPECTED_ABI),
            'int pstpu_abi_version() {{ return {}; }}'.format(fused.EXPECTED_ABI + 1),
        )]})], select=['PT900'])
    assert any('EXPECTED_ABI' in f.message for f in findings), findings


def test_pt901_dropped_parameter_flagged(tmp_path):
    codes = _mutant_codes(tmp_path, {'shm_ring.cpp': [(
        'int pstpu_ring_write(void* h, const void* data, uint64_t len) {',
        'int pstpu_ring_write(void* h, const void* data) {')]}, ['PT901'])
    assert 'PT901' in codes


def test_pt901_return_type_drift_flagged(tmp_path):
    codes = _mutant_codes(tmp_path, {'shm_ring.cpp': [(
        'uint64_t pstpu_ring_capacity(void* h) {',
        'int pstpu_ring_capacity(void* h) {')]}, ['PT901'])
    assert 'PT901' in codes


def test_pt902_dropped_capacity_param_flagged(tmp_path):
    codes = _mutant_codes(tmp_path, {'shm_ring.cpp': [(
        'int pstpu_ring_write(void* h, const void* data, uint64_t len) {',
        'int pstpu_ring_write(void* h, const void* data) {')]}, ['PT902'])
    assert 'PT902' in codes


def test_pt903_mult_form_bound_flagged(tmp_path):
    """Re-introducing the shipped PR 6 dictionary bounds bug fires PT903."""
    codes = _mutant_codes(tmp_path, {'rowgroup_reader.cpp': [(
        'if (uint64_t(pg.num_values) > vlen / w) return kColDict;',
        'if (uint64_t(pg.num_values) * w > vlen) return kColDict;')]}, ['PT903'])
    assert codes == ['PT903']


def test_pt903_gather_dict_bound_flagged(tmp_path):
    """The decompressor-fed twin in the filtered gather: the DECOMPRESSED
    dictionary region's bound must stay division-form — a corrupt zstd/lz4
    page declaring a huge count would wrap the product past the check."""
    codes = _mutant_codes(tmp_path, {'rowgroup_reader.cpp': [(
        'if (dict_n > vlen / w) return kColDict;',
        'if (dict_n * w > vlen) return kColDict;')]}, ['PT903'])
    assert codes == ['PT903']


def test_pt903_gather_plain_bound_flagged(tmp_path):
    """The PLAIN gather's decompressed values-region bound: num_values * w
    wraps for a corrupt page of a compressed chunk."""
    codes = _mutant_codes(tmp_path, {'rowgroup_reader.cpp': [(
        'if (nv > vlen / w) return kColBounds;',
        'if (nv * w > vlen) return kColBounds;')]}, ['PT903'])
    assert codes == ['PT903']


def test_pt904_dropped_capacity_check_flagged(tmp_path):
    """Dropping the aux_cap check before the aux_buf memcpy fires PT904."""
    codes = _mutant_codes(tmp_path, {'rowgroup_reader.cpp': [(
        'if (prefix > c->aux_cap || c->aux_buf == nullptr) '
        'return kColNonUniform;\n    ', '')]}, ['PT904'])
    assert codes == ['PT904']


def test_pt903_cpp_noqa_suppresses(tmp_path):
    src = SourceFile('<fixture>', 'native/x.cpp', textwrap.dedent('''
        int check(uint64_t n, uint64_t w, uint64_t cap) {
          if (n * w > cap) return -1;  // noqa: PT903 - n capped by caller
          return 0;
        }
        '''))
    findings = [f for f in CppSafetyChecker().check(src)
                if not src.is_suppressed(f.line, f.code)]
    assert findings == []


def test_abi_checker_ignores_fixture_without_cpp():
    src = SourceFile('<fixture>', 'native/fused.py',
                     'import ctypes\nlib = None\n')
    assert list(AbiConformanceChecker().check(src)) == []


# ---------------------------------------------------------------------------
# PT1300-PT1303 whole-program race lints
# ---------------------------------------------------------------------------

def _program_findings(files):
    """Run the whole-program RaceChecker over a dict of relpath -> source."""
    sources = [SourceFile('<fixture:{}>'.format(rp), rp, textwrap.dedent(txt))
               for rp, txt in sorted(files.items())]
    for src in sources:
        assert src.parse_error is None, (src.relpath, src.parse_error)
    return run_checkers([RaceChecker()], sources)


_ABBA_POOL = '''
    import threading

    class GrowablePool(object):
        def __init__(self):
            self._pool_lock = threading.Lock()

        def grow(self, vent):
            with self._pool_lock:
                vent.pause_inner()

        def grow_inner(self):
            with self._pool_lock:
                pass
'''

_ABBA_VENT = '''
    import threading

    class PausableVentilator(object):
        def __init__(self):
            self._vent_lock = threading.Lock()

        def pause(self, pool):
            with self._vent_lock:
                pool.grow_inner()

        def pause_inner(self):
            with self._vent_lock:
                pass
'''


def test_pt1300_cross_module_abba_cycle_flagged():
    findings = _program_findings({'workers/pool.py': _ABBA_POOL,
                                  'workers/vent.py': _ABBA_VENT})
    assert [f.code for f in findings] == ['PT1300']
    assert '_pool_lock' in findings[0].message
    assert '_vent_lock' in findings[0].message


def test_pt1300_consistent_cross_module_order_passes():
    # both entry paths take pool-lock before vent-lock: an order, not a cycle
    vent = _ABBA_VENT.replace('pool.grow_inner()', 'pass')
    assert _program_findings({'workers/pool.py': _ABBA_POOL,
                              'workers/vent.py': vent}) == []


def test_pt1300_deep_call_chain_cycle_flagged():
    """Two levels of self-call indirection: beyond PT101's one-level limit,
    so PT1300 owns it even within a single class."""
    code = '''
        import threading

        class C(object):
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    self._mid_b()

            def two(self):
                with self._b:
                    self._mid_a()

            def _mid_a(self):
                self._take_a()

            def _mid_b(self):
                self._take_b()

            def _take_a(self):
                with self._a:
                    pass

            def _take_b(self):
                with self._b:
                    pass
    '''
    findings = _program_findings({'workers/c.py': code})
    assert [f.code for f in findings] == ['PT1300']


def test_pt1300_pt101_dedup_class_local_cycle():
    """A single-class, one-level-indirection ABBA is PT101's territory:
    PT101 reports it, PT1300 must NOT double-report."""
    code = '''
        import threading

        class C(object):
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._na = 0
                self._nb = 0

            def one(self):
                with self._a:
                    self._take_b()

            def two(self):
                with self._b:
                    self._take_a()

            def _take_a(self):
                with self._a:
                    self._na += 1

            def _take_b(self):
                with self._b:
                    self._nb += 1
    '''
    src = SourceFile('<fixture>', 'workers/c.py', textwrap.dedent(code))
    codes = [f.code for f in
             run_checkers([LockDisciplineChecker(), RaceChecker()], [src])]
    assert codes == ['PT101']


def test_pt1300_uncorrelated_ambiguous_receiver_resolves_to_nothing():
    """Two classes define ``drain``; the receiver name shares no token with
    either class, so no call edge is invented and no cycle is reported."""
    a = _ABBA_POOL.replace('vent.pause_inner()', 'zz.drain()') \
                  .replace('def grow_inner', 'def drain_a')
    b = '''
        import threading

        class First(object):
            def __init__(self):
                self._f = threading.Lock()

            def drain(self):
                with self._f:
                    pass

        class Second(object):
            def __init__(self):
                self._s = threading.Lock()

            def drain(self):
                with self._s:
                    pass
    '''
    assert _program_findings({'workers/a.py': a, 'workers/b.py': b}) == []


def test_pt1301_unguarded_read_of_guarded_container():
    code = '''
        import threading

        class Q(object):
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items.append(x)

            def snapshot(self):
                return list(self._items)
    '''
    findings = _program_findings({'workers/q.py': code})
    assert [f.code for f in findings] == ['PT1301']
    assert '_items' in findings[0].message


def test_pt1301_guarded_by_inference_through_helper():
    """A private helper invoked only under the lock inherits the guard — the
    '# noqa: caller holds the lock' convention, computed."""
    code = '''
        import threading

        class Q(object):
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items.append(x)
                    self._drain()

            def _drain(self):
                for item in self._items:
                    pass
    '''
    assert _program_findings({'workers/q.py': code}) == []


def test_pt1301_scalar_flags_not_flagged():
    # GIL-atomic scalar flags are PT100's domain, not a torn-view hazard
    code = '''
        import threading

        class Q(object):
            def __init__(self):
                self._lock = threading.Lock()
                self._stop = False

            def stop(self):
                with self._lock:
                    self._stop = True

            def running(self):
                return not self._stop
    '''
    assert _program_findings({'workers/q.py': code}) == []


def test_pt1302_live_reference_escape_flagged():
    code = '''
        import threading

        class Q(object):
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items.append(x)

            def items(self):
                with self._lock:
                    return self._items
    '''
    findings = _program_findings({'workers/q.py': code})
    assert [f.code for f in findings] == ['PT1302']
    assert 'copy out' in findings[0].message


def test_pt1302_copy_out_passes():
    code = '''
        import threading

        class Q(object):
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items.append(x)

            def items(self):
                with self._lock:
                    return list(self._items)
    '''
    assert _program_findings({'workers/q.py': code}) == []


def test_pt1303_unbounded_wait_under_lock_flagged():
    code = '''
        import threading

        class Q(object):
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._ready = False

            def set_ready(self):
                with self._cv:
                    self._ready = True
                    self._cv.notify_all()

            def wait_ready(self):
                with self._cv:
                    while not self._ready:
                        self._cv.wait()
    '''
    findings = _program_findings({'workers/q.py': code})
    assert [f.code for f in findings] == ['PT1303']
    assert 'wait(timeout=...)' in findings[0].message


def test_pt1303_timed_wait_passes():
    code = '''
        import threading

        class Q(object):
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._ready = False

            def set_ready(self):
                with self._cv:
                    self._ready = True
                    self._cv.notify_all()

            def wait_ready(self):
                with self._cv:
                    while not self._ready:
                        self._cv.wait(timeout=0.1)
    '''
    assert _program_findings({'workers/q.py': code}) == []


def test_pt1303_out_of_scope_modules_ignored():
    code = '''
        import threading

        class Q(object):
            def __init__(self):
                self._cv = threading.Condition()

            def wait_forever(self):
                with self._cv:
                    self._cv.wait()
    '''
    # the identical shape fires inside the concurrency domains...
    assert [f.code for f in _program_findings({'workers/q.py': code})] \
        == ['PT1303']
    # ...and is ignored outside them
    sources = [SourceFile('<f>', 'codecs/q.py', textwrap.dedent(code))]
    assert run_checkers([RaceChecker()], sources) == []


# ---------------------------------------------------------------------------
# PT13xx seeded mutations of REAL sources: re-introducing the defect class
# into the live tree must make the checker fire (and the live tree is clean)
# ---------------------------------------------------------------------------

_SEEDED_ABBA = '''

class _SeededA(object):
    def __init__(self):
        self._lock_a = threading.Lock()

    def forward(self, other):
        with self._lock_a:
            other.backward_inner()

    def forward_inner(self):
        with self._lock_a:
            pass


class _SeededB(object):
    def __init__(self):
        self._lock_b = threading.Lock()

    def backward(self, other):
        with self._lock_b:
            other.forward_inner()

    def backward_inner(self):
        with self._lock_b:
            pass
'''

# (rule, real file, fixed fragment, broken fragment); appending instead of
# replacing when the fixed fragment is the empty suffix
_SEEDED_MUTATIONS = [
    ('PT1300', 'workers/ventilator.py', None, _SEEDED_ABBA),
    ('PT1301', 'elastic/coordinator.py',
     'with self._lock:\n'
     '            # consumer threads retire stale epochs (del) under the lock; an\n'
     '            # unlocked get here races the dict resize. The state dict itself\n'
     '            # stays valid once fetched — per-epoch state is only ever dropped,\n'
     '            # never rebound.\n'
     '            state = self._epoch_state.get(epoch)',
     'state = self._epoch_state.get(epoch)'),
    ('PT1302', 'workers/thread_pool.py',
     'return list(self._quarantined)', 'return self._quarantined'),
    ('PT1303', 'workers/ventilator.py',
     'self._in_flight_cv.wait(timeout=0.1)', 'self._in_flight_cv.wait()'),
]


@pytest.mark.parametrize('rule,relpath,fixed,broken',
                         _SEEDED_MUTATIONS,
                         ids=[m[0] for m in _SEEDED_MUTATIONS])
def test_pt13xx_seeded_mutation_of_real_source(rule, relpath, fixed, broken):
    path = os.path.join(PKG_DIR, relpath)
    with open(path) as f:
        original = f.read()
    checker = RaceChecker()
    clean = run_checkers([checker],
                         [SourceFile(path, relpath, original)])
    assert rule not in {f.code for f in clean}, (
        'real source {} already carries an open {}'.format(relpath, rule))
    if fixed is None:
        mutated = original + broken
    else:
        assert fixed in original, (
            'expected fixed fragment vanished from {} — update the seeded '
            'mutation to track the source'.format(relpath))
        mutated = original.replace(fixed, broken)
    findings = run_checkers([RaceChecker()],
                            [SourceFile(path, relpath, mutated)])
    assert rule in {f.code for f in findings}, (
        'seeded {} defect in {} not caught'.format(rule, relpath))


# ---------------------------------------------------------------------------
# SARIF output (--format sarif)
# ---------------------------------------------------------------------------

def _sarif_run(path, extra=()):
    proc = subprocess.run(
        [sys.executable, '-m', 'petastorm_tpu.analysis', str(path),
         '--format', 'sarif'] + list(extra),
        capture_output=True, text=True, timeout=120)
    return proc, json.loads(proc.stdout)


def test_sarif_document_structure(tmp_path):
    """Structural validation against the subset of the SARIF 2.1.0 schema
    the linter emits (jsonschema is not an install dependency)."""
    bad = tmp_path / 'bad.py'
    bad.write_text('class C(object):\n'
                   '    def __eq__(self, other):\n'
                   '        return True\n'
                   'class D(object):\n'
                   '    def __eq__(self, other):  # noqa: PT600 - identity only\n'
                   '        return True\n')
    proc, doc = _sarif_run(bad)
    assert proc.returncode == 1  # exit-code contract is format-independent
    from petastorm_tpu.analysis.sarif import SARIF_SCHEMA, SARIF_VERSION
    assert doc['$schema'] == SARIF_SCHEMA
    assert doc['version'] == SARIF_VERSION
    assert isinstance(doc['runs'], list) and len(doc['runs']) == 1
    run = doc['runs'][0]
    driver = run['tool']['driver']
    assert driver['name'] == 'petastorm-tpu-lint'
    rule_ids = [r['id'] for r in driver['rules']]
    assert rule_ids == sorted(set(rule_ids), key=rule_ids.index)  # unique
    for r in driver['rules']:
        assert set(r) >= {'id', 'name', 'shortDescription'}
        assert r['shortDescription']['text']
    # the full registered catalog is advertised, plus the parse-error rule
    assert set(rule_ids) == set(ALL_RULE_CODES) | {'PT000'}
    assert len(run['results']) == 2
    for result in run['results']:
        assert result['ruleId'] == 'PT600'
        assert result['level'] == 'error'
        assert result['message']['text']
        assert driver['rules'][result['ruleIndex']]['id'] == result['ruleId']
        loc = result['locations'][0]['physicalLocation']
        assert loc['artifactLocation']['uri'] == 'bad.py'
        assert isinstance(loc['region']['startLine'], int)
        assert loc['region']['startLine'] >= 1


def test_sarif_suppression_kinds(tmp_path):
    """noqa -> inSource, baseline -> external; open results carry none."""
    bad = tmp_path / 'bad.py'
    bad.write_text('class C(object):\n'
                   '    def __eq__(self, other):\n'
                   '        return True\n'
                   'class D(object):\n'
                   '    def __eq__(self, other):  # noqa: PT600 - identity only\n'
                   '        return True\n')
    proc, doc = _sarif_run(bad)
    results = doc['runs'][0]['results']
    kinds = sorted(r['suppressions'][0]['kind'] if 'suppressions' in r
                   else 'open' for r in results)
    assert kinds == ['inSource', 'open']
    baseline = tmp_path / 'baseline.json'
    subprocess.run(
        [sys.executable, '-m', 'petastorm_tpu.analysis', str(bad),
         '--write-baseline', str(baseline)],
        capture_output=True, text=True, timeout=120)
    proc, doc = _sarif_run(bad, ['--baseline', str(baseline)])
    assert proc.returncode == 0  # everything suppressed
    kinds = sorted(r['suppressions'][0]['kind'] if 'suppressions' in r
                   else 'open' for r in doc['runs'][0]['results'])
    assert kinds == ['external', 'inSource']


def test_sarif_package_tree_has_no_open_results():
    proc = subprocess.run(
        [sys.executable, '-m', 'petastorm_tpu.analysis', PKG_DIR,
         '--format', 'sarif', '--baseline', BASELINE_PATH],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    open_results = [r for r in doc['runs'][0]['results']
                    if 'suppressions' not in r]
    assert open_results == []


# ---------------------------------------------------------------------------
# the whole-program pass through --cache / --changed
# ---------------------------------------------------------------------------

def _write_abba_tree(root):
    pkg = root / 'workers'
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / 'pool.py').write_text(textwrap.dedent(_ABBA_POOL))
    (pkg / 'vent.py').write_text(textwrap.dedent(_ABBA_VENT))
    return [(str(pkg / n), 'workers/' + n) for n in ('pool.py', 'vent.py')]


def test_program_pass_is_cached_and_invalidated(tmp_path):
    from petastorm_tpu.analysis.cache import (ResultCache,
                                              run_analysis_incremental)
    entries = _write_abba_tree(tmp_path)
    cache_dir = str(tmp_path / 'cache')

    cache = ResultCache(cache_dir)
    first = run_analysis_incremental(entries, cache=cache)
    assert 'PT1300' in {f.code for f in first}

    cache = ResultCache(cache_dir)
    second = run_analysis_incremental(entries, cache=cache)
    assert [f.to_dict() for f in second] == [f.to_dict() for f in first]
    assert cache.misses == 0  # per-file AND program entries all warm

    # editing a scoped file invalidates the aggregate program key
    fixed = textwrap.dedent(_ABBA_VENT).replace('pool.grow_inner()', 'pass')
    (tmp_path / 'workers' / 'vent.py').write_text(fixed)
    cache = ResultCache(cache_dir)
    third = run_analysis_incremental(entries, cache=cache)
    assert 'PT1300' not in {f.code for f in third}


def test_changed_subset_still_runs_whole_program_pass(tmp_path):
    """--changed semantics: per-file checkers see only the changed subset,
    but the PT13xx pass runs over the FULL listing — a cross-module cycle
    must not vanish just because only one of its files changed."""
    from petastorm_tpu.analysis.cache import run_analysis_incremental
    entries = _write_abba_tree(tmp_path)
    changed_only = entries[:1]
    findings = run_analysis_incremental(changed_only,
                                        program_entries=entries)
    assert 'PT1300' in {f.code for f in findings}
    # the subset alone cannot prove the cycle
    subset_only = run_analysis_incremental(changed_only,
                                           program_entries=changed_only)
    assert 'PT1300' not in {f.code for f in subset_only}


# ---------------------------------------------------------------------------
# the linter meta-test: every registered rule id has committed teeth
# ---------------------------------------------------------------------------

FIXTURE_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            'lint_fixtures')


@pytest.mark.parametrize('rule', ALL_RULE_CODES)
def test_rule_fires_on_fixture_and_stays_silent_on_clean_twin(rule):
    """THE meta-gate: a registered rule must fire on its committed bad
    fixture and stay silent on the clean twin — registering a toothless (or
    overreaching) rule fails tier-1."""
    bad = os.path.join(FIXTURE_ROOT, rule, 'bad')
    clean = os.path.join(FIXTURE_ROOT, rule, 'clean')
    assert os.path.isdir(bad) and os.path.isdir(clean), (
        'rule {} is registered in ALL_CHECKERS but has no committed fixture '
        'pair under tests/lint_fixtures/{}/ — add bad/ and clean/ trees '
        'proving it has teeth'.format(rule, rule))
    bad_codes = {f.code for f in run_analysis([bad])}
    assert rule in bad_codes, (
        'rule {} did not fire on its own bad fixture (toothless rule); '
        'found only: {}'.format(rule, sorted(bad_codes)))
    clean_codes = {f.code for f in run_analysis([clean])}
    assert rule not in clean_codes, (
        'rule {} fired on its clean twin (overreaching rule)'.format(rule))


def test_no_orphan_fixture_directories():
    dirs = {d for d in os.listdir(FIXTURE_ROOT)
            if os.path.isdir(os.path.join(FIXTURE_ROOT, d))}
    assert dirs == set(ALL_RULE_CODES), (
        'fixture dirs and registered rule ids diverged: extra={}, missing={}'
        .format(sorted(dirs - set(ALL_RULE_CODES)),
                sorted(set(ALL_RULE_CODES) - dirs)))


# ---------------------------------------------------------------------------
# the tier-1 gate + CLI
# ---------------------------------------------------------------------------

def test_package_tree_is_clean():
    """THE gate: the full pass over petastorm_tpu/ has zero non-baselined
    findings. A new violation anywhere in the package fails this test."""
    findings = run_analysis([PKG_DIR], baseline=load_baseline(BASELINE_PATH))
    assert findings == [], 'new static-analysis findings:\n' + '\n'.join(
        f.format() for f in findings)


def test_cli_json_clean_exit():
    """A clean tree exits 0; the JSONL stream may still carry noqa/baselined
    findings, but none with status 'open'."""
    proc = subprocess.run(
        [sys.executable, '-m', 'petastorm_tpu.analysis', PKG_DIR,
         '--format', 'json', '--baseline', BASELINE_PATH],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    records = [json.loads(line) for line in proc.stdout.splitlines()]
    assert all(r['status'] in ('noqa', 'baselined') for r in records)
    # the tree uses noqa (with reasons): the machine stream surfaces them
    assert any(r['status'] == 'noqa' for r in records)


def test_cli_json_one_stable_object_per_line(tmp_path):
    """The documented JSONL contract: one finding per line with the stable
    key set, status distinguishing open from noqa-suppressed."""
    bad = tmp_path / 'bad.py'
    bad.write_text('class C(object):\n'
                   '    def __eq__(self, other):\n'
                   '        return True\n'
                   'class D(object):\n'
                   '    def __eq__(self, other):  # noqa: PT600 - identity only\n'
                   '        return True\n')
    proc = subprocess.run(
        [sys.executable, '-m', 'petastorm_tpu.analysis', str(bad),
         '--format', 'json'],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1  # one OPEN finding drives the exit code
    records = [json.loads(line) for line in proc.stdout.splitlines()]
    assert len(records) == 2
    for r in records:
        assert set(r) == {'rule', 'path', 'line', 'message', 'snippet', 'status'}
        assert r['rule'] == 'PT600' and r['path'] == 'bad.py'
    assert sorted(r['status'] for r in records) == ['noqa', 'open']


def test_cli_json_baselined_status(tmp_path):
    bad = tmp_path / 'bad.py'
    bad.write_text('class C(object):\n'
                   '    def __eq__(self, other):\n'
                   '        return True\n')
    baseline = tmp_path / 'baseline.json'
    subprocess.run(
        [sys.executable, '-m', 'petastorm_tpu.analysis', str(bad),
         '--write-baseline', str(baseline)],
        capture_output=True, text=True, timeout=120)
    proc = subprocess.run(
        [sys.executable, '-m', 'petastorm_tpu.analysis', str(bad),
         '--format', 'json', '--baseline', str(baseline)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    records = [json.loads(line) for line in proc.stdout.splitlines()]
    assert [r['status'] for r in records] == ['baselined']


def test_cli_reports_findings_and_exits_1(tmp_path):
    bad = tmp_path / 'bad.py'
    bad.write_text('class C(object):\n'
                   '    def __eq__(self, other):\n'
                   '        return True\n')
    proc = subprocess.run(
        [sys.executable, '-m', 'petastorm_tpu.analysis', str(bad)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert 'PT600' in proc.stdout


def test_cli_rules_lists_all_families():
    proc = subprocess.run(
        [sys.executable, '-m', 'petastorm_tpu.analysis', '--rules'],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    for cls in ALL_CHECKERS:
        assert cls.code in proc.stdout


def test_console_script_target_resolves():
    # the entry point target of `petastorm-tpu-lint` (declaration coverage in
    # test_packaging.py, which owns the pyproject assertions)
    import importlib
    func = getattr(importlib.import_module('petastorm_tpu.analysis.cli'), 'main')
    assert callable(func)
    assert func(['--rules']) == 0
