"""Closed-loop autotuning tests: windowed history, the feedback controller
(convergence, bounds, hysteresis/oscillation guards), pool slot grow/retire,
the shuffle/prefetch knobs, decision spans + JSONL log, the offline replay
CLI, and the zero-overhead-when-off guarantee."""

import json
import threading
import time

import pytest

from petastorm_tpu import make_reader
from petastorm_tpu import observability as obs
from petastorm_tpu.autotune import AutotuneConfig, Autotuner, resolve_autotune
from petastorm_tpu.autotune.cli import (_SimChunkCache, _SimLoader, _SimPool,
                                        main as autotune_cli_main, replay,
                                        windows_from_trace)
from petastorm_tpu.jax.loader import JaxDataLoader
from petastorm_tpu.observability import history as hist


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    """Telemetry state is process-global (same stance as
    tests/test_observability.py): save/restore level, clear registry + ring."""
    saved = obs.current_config()
    obs.get_registry().reset()
    obs.get_ring().clear()
    yield
    obs.configure(saved)
    obs.get_registry().reset()
    obs.get_ring().clear()


# ---------------------------------------------------------------------------
# windowed history
# ---------------------------------------------------------------------------

def _snap(ts, **diag):
    return {'ts': ts, 'diag': diag}


def test_window_delta_counters_subtract_gauges_latest():
    older = _snap(100.0, stage_decode_s=5.0, rows_emitted=100,
                  reader_wait_s=2.0, shuffle_buffer_occupancy=50,
                  workers_count=2, results_queue_depth=7)
    newer = _snap(102.0, stage_decode_s=5.5, rows_emitted=300,
                  reader_wait_s=2.4, shuffle_buffer_occupancy=10,
                  workers_count=3, results_queue_depth=1)
    win = hist.window_delta(older, newer)
    assert win['stage_decode_s'] == pytest.approx(0.5)
    assert win['rows_emitted'] == 200
    assert win['rows_per_s'] == pytest.approx(100.0)
    # gauges carry the NEWER reading, not a meaningless difference
    assert win['shuffle_buffer_occupancy'] == 10
    assert win['workers_count'] == 3
    assert win['results_queue_depth'] == 1
    # the wait fraction is recomputed over the window span
    assert win['reader_wait_fraction'] == pytest.approx(0.4 / 2.0)
    assert win['wait_proxy'] is None


def test_window_delta_pool_wait_proxy_without_loader():
    """A bare Reader records no reader_wait_s: the window falls back to the
    pool-wait stage and says so, instead of reporting an un-attributable 0."""
    older = _snap(10.0, stage_pool_wait_s=1.0, stage_decode_s=0.5)
    newer = _snap(12.0, stage_pool_wait_s=2.6, stage_decode_s=1.2)
    win = hist.window_delta(older, newer)
    assert win['wait_proxy'] == 'pool_wait'
    assert win['reader_wait_s'] == pytest.approx(1.6)
    assert win['reader_wait_fraction'] == pytest.approx(0.8)


def test_windowed_report_names_recent_not_cumulative_bottleneck():
    """THE point of the time dimension: the run-cumulative report blames
    decode, but the last window is transform-bound — windowed attribution
    must name transform."""
    older = _snap(0.0, reader_wait_s=100.0, stage_pool_wait_s=100.0,
                  stage_decode_s=95.0, stage_transform_s=0.0)
    newer = _snap(10.0, reader_wait_s=108.0, stage_pool_wait_s=108.0,
                  stage_decode_s=95.5, stage_transform_s=7.0)
    cumulative = obs.stall_report(newer['diag'])
    assert cumulative['bottleneck'] == 'worker.decode'
    windowed = hist.windowed_stall_report(hist.window_delta(older, newer))
    assert windowed['bottleneck'] == 'worker.transform'
    assert windowed['window_s'] == pytest.approx(10.0)


def test_detect_regression_throughput_and_stall():
    base = {'rows_per_s': 1000.0, 'reader_wait_fraction': 0.1}
    assert hist.detect_regression(base, {'rows_per_s': 900.0,
                                         'reader_wait_fraction': 0.1}) is None
    drop = hist.detect_regression(base, {'rows_per_s': 500.0,
                                         'reader_wait_fraction': 0.1})
    assert drop['kind'] == 'throughput_drop' and drop['ratio'] == pytest.approx(0.5)
    rise = hist.detect_regression(base, {'rows_per_s': 990.0,
                                         'reader_wait_fraction': 0.5})
    assert rise['kind'] == 'stall_rise'


def test_history_recorder_bounded_save_load(tmp_path):
    ticks = {'n': 0}

    def diag():
        ticks['n'] += 1
        return {'rows_emitted': ticks['n'] * 10, 'reader_wait_s': 0.0}

    rec = hist.HistoryRecorder(diag, interval_s=0.5, capacity=4)
    for _ in range(10):
        rec.record_now()
    assert len(rec) == 4  # bounded: oldest rotated out
    path = tmp_path / 'history.jsonl'
    assert rec.save(str(path)) == 4
    snaps = hist.load_history(str(path))
    assert len(snaps) == 4 and snaps[-1]['diag']['rows_emitted'] == 100
    assert len(hist.history_windows(snaps)) == 3
    # JsonlExporter format ({'ts','metrics'}) loads too
    path2 = tmp_path / 'exporter.jsonl'
    path2.write_text('{"ts": 1.0, "metrics": {"a": 1}}\n'
                     'garbage line\n'
                     '{"ts": 2.0, "metrics": {"a": 5}}\n')
    snaps2 = hist.load_history(str(path2))
    assert [s['diag']['a'] for s in snaps2] == [1, 5]


def test_history_recorder_overhead_guard(synthetic_dataset):
    """<1% at the default cadence: one snapshot must cost well under 1% of
    the 1s default interval, measured over a live reader's diagnostics."""
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', output='columnar',
                         telemetry='counters')
    with JaxDataLoader(reader, batch_size=20, drop_last=False) as loader:
        for _ in loader:
            pass
        rec = hist.HistoryRecorder(lambda: loader.diagnostics)
        rec.record_now()  # warm the path
        t0 = time.perf_counter()
        n = 20
        for _ in range(n):
            rec.record_now()
        per_snapshot = (time.perf_counter() - t0) / n
    assert per_snapshot < 0.01 * hist.DEFAULT_INTERVAL_S, per_snapshot


def test_autotune_off_is_structurally_free(synthetic_dataset):
    """autotune=False (the default) builds NO recorder and NO thread — the
    overhead guarantee is structural, not a timing measurement."""
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', output='columnar')
    try:
        assert reader.autotuner is None
        names = {t.name for t in threading.enumerate()}
        assert not any(n.startswith(('pstpu-autotune', 'pstpu-history'))
                       for n in names)
    finally:
        reader.stop()
        reader.join()


# ---------------------------------------------------------------------------
# controller decisions (simulated knobs: the identical path the CLI replays)
# ---------------------------------------------------------------------------

def _stalled_window(bottleneck_stage='stage_decode_s', wait=0.9, span=1.0,
                    **extra):
    win = {'window_s': span, 'reader_wait_s': wait,
           'reader_wait_fraction': wait / span, 'stage_pool_wait_s': wait,
           'rows_per_s': 100.0, 'wait_proxy': None,
           bottleneck_stage: wait * 0.9}
    win.update(extra)
    return win


def _calm_window(span=1.0):
    return {'window_s': span, 'reader_wait_s': 0.0,
            'reader_wait_fraction': 0.0, 'stage_pool_wait_s': 0.0,
            'rows_per_s': 100.0, 'wait_proxy': None}


def _tuner(config=None, workers=1, prefetch=64 << 20, shuffle=0):
    pool = _SimPool(workers)
    cache = _SimChunkCache(prefetch)
    loader = _SimLoader(shuffle) if shuffle else None
    cfg = config or AutotuneConfig(interval_s=1.0)
    return Autotuner(cfg, pool=pool, chunk_cache=cache, loader=loader), pool, cache, loader


def test_controller_grows_workers_and_respects_max():
    tuner, pool, _, _ = _tuner(AutotuneConfig(interval_s=1.0, max_workers=3,
                                              cooldown_s=1.0))
    now = 0.0
    for _ in range(10):
        now += 10.0  # far past every cooldown
        tuner.evaluate(_stalled_window(), now=now)
    assert pool.workers_count == 3  # clamped at max, never beyond
    grows = [d for d in tuner.decision_records() if d['action'] == 'grow']
    assert len(grows) == 2
    for d in grows:
        assert d['knob'] == 'workers'
        assert d['window']['bottleneck'] == 'worker.decode'
        assert d['window']['span_s'] == pytest.approx(1.0)
        assert d['window']['stages']  # evidence attached


def test_controller_raises_prefetch_on_chunk_fetch_bound():
    cfg = AutotuneConfig(interval_s=1.0, max_prefetch_bytes=256 << 20)
    tuner, pool, cache, _ = _tuner(cfg, prefetch=64 << 20)
    d = tuner.evaluate(_stalled_window('stage_chunk_fetch_s',
                                       stage_read_s=0.81), now=100.0)
    assert d['knob'] == 'prefetch_bytes'
    assert cache.prefetch_budget_bytes == 128 << 20
    # once the budget is capped, the fallback is more IO parallelism
    cache.prefetch_budget_bytes = 256 << 20
    d2 = tuner.evaluate(_stalled_window('stage_chunk_fetch_s',
                                        stage_read_s=0.81), now=200.0)
    assert d2['knob'] == 'workers' and pool.workers_count == 2


def test_controller_shrinks_shuffle_on_assembly_bound():
    cfg = AutotuneConfig(interval_s=1.0, min_shuffle_capacity=4)
    tuner, _, _, loader = _tuner(cfg, shuffle=64)
    win = _calm_window()
    win.update(reader_wait_s=0.9, reader_wait_fraction=0.9,
               stage_pool_wait_s=0.0)  # all wait is consumer-side assembly
    d = tuner.evaluate(win, now=50.0)
    assert d['knob'] == 'shuffle_capacity' and d['action'] == 'shrink'
    assert loader.shuffle_capacity == 32
    # clamp floor: repeated shrinks stop at min_shuffle_capacity
    now = 50.0
    for _ in range(10):
        now += 100.0
        tuner.evaluate(win, now=now)
    assert loader.shuffle_capacity == 4


def test_controller_shrinks_only_slots_it_grew():
    """Calm windows retire a controller-grown slot, but never shrink the pool
    below what the user configured."""
    cfg = AutotuneConfig(interval_s=1.0, shrink_after_windows=2,
                         cooldown_s=1.0, reverse_cooldown_s=2.0, max_workers=8)
    tuner, pool, _, _ = _tuner(cfg, workers=2)
    now = 100.0
    for _ in range(10):  # calm forever, but nothing was grown: no shrink
        now += 10.0
        assert tuner.evaluate(_calm_window(), now=now) is None
    assert pool.workers_count == 2
    tuner.evaluate(_stalled_window(), now=now + 10)
    assert pool.workers_count == 3
    d = None
    for _ in range(4):
        now += 100.0
        d = d or tuner.evaluate(_calm_window(), now=now)
    assert d is not None and d['action'] == 'shrink'
    assert pool.workers_count == 2


def test_oscillation_guard_alternating_bottlenecks_do_not_thrash():
    """Alternating stalled/calm phases flip the workers knob's direction;
    after the reversal budget is spent the knob freezes instead of
    oscillating, so the total number of moves stays small and no A/B/A/B
    thrash pattern develops."""
    cfg = AutotuneConfig(interval_s=1.0, cooldown_s=1.0, reverse_cooldown_s=1.5,
                         freeze_s=1000.0, shrink_after_windows=1,
                         max_workers=8)
    tuner, pool, _, _ = _tuner(cfg, workers=1)
    now = 0.0
    tuner.evaluate(_stalled_window(), now=now)  # net grow: shrink is armed
    for _ in range(40):
        now += 10.0
        tuner.evaluate(_stalled_window(), now=now)
        now += 10.0
        tuner.evaluate(_calm_window(), now=now)
    actions = [d['action'] for d in tuner.decision_records()
               if d['knob'] == 'workers']
    # without the guard this would be ~40 grow/shrink pairs
    assert len(actions) <= 5, actions
    state = tuner._knobs['workers']
    assert state.frozen_until > now - 1000.0  # the freeze engaged
    assert 1 <= pool.workers_count <= 3


def test_decision_span_records_at_counters_level():
    """Every knob change must land in the trace ring as an autotune.decision
    event even when per-stage spans are off — decisions are rare and must
    stay explainable in any exported trace."""
    obs.configure('counters')
    tuner, _, _, _ = _tuner(AutotuneConfig(interval_s=1.0))
    tuner.evaluate(_stalled_window(), now=100.0)
    events = [e for e in obs.get_ring().snapshot()
              if e['name'] == 'autotune.decision']
    assert len(events) == 1
    assert events[0]['args']['knob'] == 'workers'
    assert events[0]['args']['action'] == 'grow'
    assert events[0]['args']['after'] == 2


def _regressed_window(rows_per_s=30.0):
    """An A/B window whose throughput collapsed versus _stalled_window()."""
    win = _stalled_window()
    win['rows_per_s'] = rows_per_s
    return win


def test_rollback_reverts_regressed_worker_grow():
    """The A/B contract: a knob move whose next evidence window regresses is
    reverted, frozen, and recorded as a 'rollback' decision carrying the
    regression evidence."""
    tuner, pool, _, _ = _tuner(AutotuneConfig(interval_s=1.0, cooldown_s=1.0,
                                              freeze_s=500.0, max_workers=8))
    grow = tuner.evaluate(_stalled_window(), now=10.0)
    assert grow['action'] == 'grow' and pool.workers_count == 2
    d = tuner.evaluate(_regressed_window(), now=20.0)
    assert d['action'] == 'rollback' and d['knob'] == 'workers'
    assert d['from'] == 2 and d['to'] == 1 and pool.workers_count == 1
    assert d['regression']['kind'] == 'throughput_drop'
    assert 'regression after grow' in d['reason']
    # the knob is frozen: the still-stalled pipeline cannot re-grow it
    for now in (30.0, 120.0, 400.0):
        assert tuner.evaluate(_stalled_window(), now=now) is None
    assert pool.workers_count == 1
    # ...until the freeze expires
    assert tuner.evaluate(_stalled_window(), now=600.0)['action'] == 'grow'


def test_rollback_stall_rise_and_prefetch_restore():
    cfg = AutotuneConfig(interval_s=1.0, freeze_s=500.0)
    tuner, _pool, cache, _ = _tuner(cfg, prefetch=64 << 20)
    d = tuner.evaluate(_stalled_window('stage_chunk_fetch_s'), now=10.0)
    assert d['knob'] == 'prefetch_bytes' and cache.prefetch_budget_bytes == 128 << 20
    # throughput held (no drop) but the windowed wait fraction rose by more
    # than rollback_stall_rise: the stall_rise arm of detect_regression
    regressed = _stalled_window('stage_chunk_fetch_s', wait=0.95, span=0.9)
    regressed['rows_per_s'] = 95.0
    rb = tuner.evaluate(regressed, now=20.0)
    assert rb['action'] == 'rollback' and rb['knob'] == 'prefetch_bytes'
    assert cache.prefetch_budget_bytes == 64 << 20
    assert rb['regression']['kind'] == 'stall_rise'


def test_no_rollback_when_ab_window_holds():
    """A move whose next window holds (no regression) keeps its effect, and
    the A/B arm is consumed — a later regression is attributed to nothing."""
    tuner, pool, _, _ = _tuner(AutotuneConfig(interval_s=1.0, cooldown_s=100.0,
                                              max_workers=8))
    tuner.evaluate(_stalled_window(), now=10.0)
    assert tuner._pending_ab is not None
    d = tuner.evaluate(_stalled_window(), now=10.5)  # held: within cooldown, no new move
    assert d is None and pool.workers_count == 2
    assert tuner._pending_ab is None
    # a regression two windows later is NOT pinned on the old move
    d = tuner.evaluate(_regressed_window(), now=11.0)
    assert d is None
    assert pool.workers_count == 2


def test_rollback_disabled_keeps_the_move():
    tuner, pool, _, _ = _tuner(AutotuneConfig(interval_s=1.0, rollback=False,
                                              cooldown_s=100.0, max_workers=8))
    tuner.evaluate(_stalled_window(), now=10.0)
    d = tuner.evaluate(_regressed_window(), now=20.0)
    assert d is None and pool.workers_count == 2
    assert not any(r['action'] == 'rollback' for r in tuner.decision_records())


def test_rollback_recorded_in_decision_log(tmp_path):
    log_path = tmp_path / 'decisions.jsonl'
    cfg = AutotuneConfig(interval_s=1.0, cooldown_s=1.0, freeze_s=500.0,
                         max_workers=8, decision_log=str(log_path))
    tuner, _, _, _ = _tuner(cfg)
    tuner.evaluate(_stalled_window(), now=10.0)
    tuner.evaluate(_regressed_window(), now=20.0)
    lines = [json.loads(line) for line in log_path.read_text().splitlines()]
    assert [r['action'] for r in lines] == ['grow', 'rollback']
    assert lines[1]['regression']['kind'] == 'throughput_drop'
    assert lines[1]['window']['rows_per_s'] == 30.0  # the regressed evidence


def test_rollback_decision_span_recorded():
    obs.configure('counters')
    tuner, _, _, _ = _tuner(AutotuneConfig(interval_s=1.0, cooldown_s=1.0,
                                           max_workers=8))
    tuner.evaluate(_stalled_window(), now=10.0)
    tuner.evaluate(_regressed_window(), now=20.0)
    events = [e for e in obs.get_ring().snapshot()
              if e['name'] == 'autotune.decision']
    assert [e['args']['action'] for e in events] == ['grow', 'rollback']


def test_decision_log_jsonl(tmp_path):
    log_path = tmp_path / 'decisions.jsonl'
    cfg = AutotuneConfig(interval_s=1.0, decision_log=str(log_path))
    tuner, _, _, _ = _tuner(cfg)
    tuner.evaluate(_stalled_window(), now=10.0)
    lines = [json.loads(line) for line in log_path.read_text().splitlines()]
    assert len(lines) == 1
    rec = lines[0]
    assert rec['knob'] == 'workers' and rec['action'] == 'grow'
    assert rec['from'] == 1 and rec['to'] == 2 and rec['clamped'] is False
    assert rec['window']['bottleneck'] == 'worker.decode'
    assert rec['window']['span_s'] > 0


def test_resolve_autotune_and_config_validation():
    assert resolve_autotune(None) is None
    assert resolve_autotune(False) is None
    assert isinstance(resolve_autotune(True), AutotuneConfig)
    cfg = AutotuneConfig(interval_s=0.5)
    assert resolve_autotune(cfg) is cfg
    with pytest.raises(ValueError):
        resolve_autotune('yes')
    with pytest.raises(ValueError):
        AutotuneConfig(interval_s=0)
    with pytest.raises(ValueError):
        AutotuneConfig(stall_threshold=0.1, low_water=0.2)
    with pytest.raises(ValueError):
        AutotuneConfig(min_workers=3, max_workers=2)


# ---------------------------------------------------------------------------
# knob actuators
# ---------------------------------------------------------------------------

def test_thread_pool_grow_and_retire_mid_epoch(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='thread', workers_count=1,
                         output='columnar', num_epochs=2,
                         shuffle_row_groups=False)
    pool = reader._pool
    try:
        it = iter(reader)
        blocks = [next(it)]
        assert pool.add_worker_slot() == 2
        assert pool.retire_worker_slot() == 1
        assert pool.retire_worker_slot() == 1  # never below 1
        blocks.extend(it)
        assert sum(len(b.id) for b in blocks) == 200  # nothing lost or doubled
    finally:
        reader.stop()
        reader.join()


def test_ventilator_max_queue_size_resize():
    from petastorm_tpu.workers.ventilator import ConcurrentVentilator
    seen = []
    vent = ConcurrentVentilator(lambda **kw: seen.append(kw),
                                [{'i': i} for i in range(6)],
                                max_ventilation_queue_size=1)
    vent.start()
    time.sleep(0.2)
    assert len(seen) == 1  # budget of 1: one in flight
    vent.set_max_queue_size(6)
    deadline = time.monotonic() + 5
    while len(seen) < 6 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert len(seen) == 6  # raised budget released the feeding thread
    for _ in range(6):
        vent.processed_item()
    vent.stop()


def test_prefetch_budget_setter_validates():
    from petastorm_tpu.chunkstore import ChunkCacheConfig
    cfg = ChunkCacheConfig('/tmp/x')
    cfg.set_prefetch_budget(123456)
    assert cfg.prefetch_budget_bytes == 123456
    with pytest.raises(ValueError):
        cfg.set_prefetch_budget(0)


def test_loader_shuffle_capacity_resize(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', output='columnar',
                         seed=7)
    with JaxDataLoader(reader, batch_size=10, drop_last=False,
                       shuffling_queue_capacity=40, seed=7) as loader:
        it = iter(loader)
        ids = list(next(it)['id'])
        assert loader.set_shuffle_capacity(4) == 4
        assert loader.shuffle_capacity == 4
        with pytest.raises(ValueError):
            loader.set_shuffle_capacity(1)
        for batch in it:
            ids.extend(batch['id'])
        assert sorted(ids) == list(range(100))  # exactly-once through resize


def test_loader_without_buffer_rejects_shuffle_knob(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', output='columnar')
    with JaxDataLoader(reader, batch_size=10) as loader:
        assert loader.shuffle_capacity == 0
        with pytest.raises(RuntimeError):
            loader.set_shuffle_capacity(16)


@pytest.mark.slow
def test_process_pool_grow_and_retire(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='process', workers_count=1,
                         output='columnar', num_epochs=2,
                         shuffle_row_groups=False)
    pool = reader._pool
    try:
        it = iter(reader)
        blocks = [next(it)]
        assert pool.add_worker_slot() == 2
        blocks.extend(it)
        assert sum(len(b.id) for b in blocks) == 200
        assert pool.workers_alive() == 2
        assert pool.retire_worker_slot() == 1
        deadline = time.monotonic() + 15
        while pool.workers_alive() > 1 and time.monotonic() < deadline:
            pool._supervise(idle=True)
            time.sleep(0.05)
        assert pool.workers_alive() == 1
    finally:
        reader.stop()
        reader.join()


# ---------------------------------------------------------------------------
# the closed loop end to end: mis-configured reader converges
# ---------------------------------------------------------------------------

def _slow_batched_transform(batch):
    time.sleep(0.015)
    return batch


def test_autotune_converges_on_synthetic_slow_decode(synthetic_dataset, tmp_path):
    """The acceptance loop: a deliberately under-provisioned reader (1
    worker) with a synthetic slow decode-side stage must grow its pool —
    within max_workers — and every change must carry its evidence window in
    both the decision log and an autotune.decision trace event."""
    from petastorm_tpu.transform import TransformSpec
    log_path = tmp_path / 'decisions.jsonl'
    cfg = AutotuneConfig(interval_s=0.15, cooldown_s=0.2, stall_threshold=0.1,
                         max_workers=3, decision_log=str(log_path))
    spec = TransformSpec(_slow_batched_transform, batched=True)
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='thread', workers_count=1,
                         output='columnar', transform_spec=spec,
                         num_epochs=None, telemetry='counters', autotune=cfg)
    pool = reader._pool
    try:
        assert reader.autotuner is not None
        with JaxDataLoader(reader, batch_size=20, drop_last=False) as loader:
            it = iter(loader)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                next(it)
                if pool.workers_count >= 2:
                    break
            assert pool.workers_count >= 2, 'controller never grew the pool'
            assert pool.workers_count <= 3
            decisions = reader.autotuner.decision_records()
            assert decisions, 'no decision recorded'
            for d in decisions:
                assert d['knob'] == 'workers' and d['action'] == 'grow'
                assert d['window']['span_s'] > 0
                assert d['window']['bottleneck'] in (
                    'worker.transform', 'worker.decode', 'worker.fused_decode',
                    'worker.read_io', 'pool.unattributed')
                assert d['window']['stages']
            logged = [json.loads(line)
                      for line in log_path.read_text().splitlines()]
            assert len(logged) == len(decisions)
            span_events = [e for e in obs.get_ring().snapshot()
                           if e['name'] == 'autotune.decision']
            assert len(span_events) >= len(decisions)
    finally:
        # loader context already stopped the reader
        pass


# ---------------------------------------------------------------------------
# offline replay CLI
# ---------------------------------------------------------------------------

def _write_history(path, windows=6, stage='stage_decode_s'):
    """Synthesize a stalled-run history: each 1s window accumulates 0.9s of
    pool wait dominated by ``stage``."""
    with open(path, 'w') as f:
        wait = 0.0
        busy = 0.0
        for i in range(windows + 1):
            f.write(json.dumps({'ts': 1000.0 + i, 'diag': {
                'stage_pool_wait_s': wait, stage: busy,
                'rows_emitted': i * 100}}) + '\n')
            wait += 0.9
            busy += 0.85


def test_offline_replay_proposes_growth(tmp_path):
    path = tmp_path / 'history.jsonl'
    _write_history(str(path))
    proposal, decisions, _ = replay(
        hist.history_windows(hist.load_history(str(path))),
        config=AutotuneConfig(interval_s=1.0, cooldown_s=1.0, max_workers=4),
        workers=1)
    assert proposal['workers_count'] > 1
    assert proposal['workers_count'] <= 4
    assert all(d['knob'] == 'workers' for d in decisions)


def test_offline_cli_json_and_text(tmp_path, capsys):
    path = tmp_path / 'history.jsonl'
    _write_history(str(path))
    rc = autotune_cli_main([str(path), '--workers', '1', '--interval-s', '1.0',
                            '--json'])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc['proposal']['workers_count'] > 1
    assert doc['windows'] == 6
    rc = autotune_cli_main([str(path), '--workers', '1', '--interval-s', '1.0'])
    out = capsys.readouterr().out
    assert rc == 0
    assert 'proposed configuration' in out and 'workers_count' in out


def test_offline_cli_trace_replay(tmp_path, capsys):
    """A Chrome trace (e.g. bench.py --trace-out) replays too: stage spans
    bucket into windows, pool_wait doubles as the wait signal."""
    events = []
    for second in range(5):
        base = int((1000 + second) * 1e6)
        events.append({'name': 'pool_wait', 'cat': 'pool', 'ph': 'X',
                       'ts': base, 'dur': int(0.9e6), 'pid': 1, 'tid': 1})
        events.append({'name': 'decode', 'cat': 'worker', 'ph': 'X',
                       'ts': base, 'dur': int(0.85e6), 'pid': 1, 'tid': 2})
    trace = tmp_path / 'trace.json'
    trace.write_text(json.dumps({'traceEvents': events}))
    windows = windows_from_trace(str(trace), interval_s=1.0)
    assert len(windows) == 5
    assert windows[0]['wait_proxy'] == 'pool_wait'
    rc = autotune_cli_main(['--trace', str(trace), '--interval-s', '1.0',
                            '--workers', '1', '--json'])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc['proposal']['workers_count'] > 1


def test_offline_cli_usage_errors(tmp_path, capsys):
    with pytest.raises(SystemExit):
        autotune_cli_main([])  # neither history nor --trace
    empty = tmp_path / 'empty.jsonl'
    empty.write_text('')
    assert autotune_cli_main([str(empty)]) == 1


# ---------------------------------------------------------------------------
# diagnose --watch (windowed live mode)
# ---------------------------------------------------------------------------

def test_diagnose_watch_json_ticks(synthetic_dataset, capsys):
    from petastorm_tpu.observability.diagnose import main as diagnose_main
    rc = diagnose_main([synthetic_dataset.url, '--watch', '0.3', '--ticks', '2',
                        '--batch-size', '10', '-p', 'dummy', '-w', '1',
                        '--json'])
    assert rc == 0
    lines = [json.loads(line)
             for line in capsys.readouterr().out.strip().splitlines()]
    assert len(lines) == 2
    for i, rec in enumerate(lines, start=1):
        assert rec['tick'] == i
        assert 'window' in rec and 'fused_fallbacks' in rec
        assert rec['window']['window_s'] == pytest.approx(0.3, abs=0.25)


def test_diagnose_watch_text(synthetic_dataset, capsys):
    from petastorm_tpu.observability.diagnose import watch
    n = watch(synthetic_dataset.url, interval_s=0.3, ticks=2, batch_size=10,
              pool_type='dummy', workers_count=1)
    out = capsys.readouterr().out
    assert n == 2
    assert 'watch tick 1' in out and 'watch tick 2' in out
    assert 'stall report' in out
