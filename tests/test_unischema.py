"""Unischema unit tests (modeled on reference petastorm/tests/test_unischema.py)."""

from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
from petastorm_tpu.errors import SchemaError
from petastorm_tpu.unischema import (Unischema, UnischemaField, decode_row, encode_row,
                                     insert_explicit_nulls, match_unischema_fields)


def _sample_schema():
    return Unischema('Sample', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('name', np.str_, (), ScalarCodec(), False),
        UnischemaField('image', np.uint8, (16, 32, 3), CompressedImageCodec('png'), False),
        UnischemaField('embedding', np.float32, (None, 8), NdarrayCodec(), True),
    ])


def test_fields_sorted_and_attribute_access():
    schema = _sample_schema()
    assert list(schema.fields) == ['embedding', 'id', 'image', 'name']
    assert schema.id.numpy_dtype is np.int64
    assert schema.fields['image'].shape == (16, 32, 3)


def test_field_equality_ignores_codec_instance():
    f1 = UnischemaField('x', np.int32, (), ScalarCodec(), False)
    f2 = UnischemaField('x', np.int32, (), ScalarCodec(), False)
    assert f1 == f2
    assert hash(f1) == hash(f2)
    f3 = UnischemaField('x', np.int64, (), ScalarCodec(), False)
    assert f1 != f3


def test_create_schema_view_by_name_and_field():
    schema = _sample_schema()
    view = schema.create_schema_view(['id', schema.image])
    assert set(view.fields) == {'id', 'image'}


def test_create_schema_view_regex():
    schema = _sample_schema()
    view = schema.create_schema_view(['i.*'])
    assert set(view.fields) == {'id', 'image'}


def test_create_schema_view_no_match_raises():
    schema = _sample_schema()
    with pytest.raises(SchemaError):
        schema.create_schema_view(['nonexistent_field'])


def test_match_unischema_fields_fullmatch():
    schema = _sample_schema()
    # 'i' alone must NOT match 'id' (fullmatch semantics)
    assert match_unischema_fields(schema, ['i']) == []
    names = {f.name for f in match_unischema_fields(schema, ['id', 'na.*'])}
    assert names == {'id', 'name'}


def test_namedtuple_type_identity():
    schema = _sample_schema()
    assert schema.namedtuple is schema.namedtuple
    row = schema.make_namedtuple(id=1, name='a', image=None, embedding=None)
    assert row.id == 1


def test_json_roundtrip():
    schema = _sample_schema()
    restored = Unischema.from_json(schema.to_json())
    assert list(restored.fields) == list(schema.fields)
    for name in schema.fields:
        assert restored.fields[name] == schema.fields[name]
        assert restored.fields[name].codec.to_json() == schema.fields[name].codec.to_json()


def test_json_roundtrip_special_dtypes():
    schema = Unischema('S', [
        UnischemaField('d', Decimal, (), ScalarCodec(), False),
        UnischemaField('s', np.str_, (), ScalarCodec(), False),
        UnischemaField('b', np.bytes_, (), ScalarCodec(), False),
        UnischemaField('t', np.datetime64, (), ScalarCodec(), False),
    ])
    restored = Unischema.from_json(schema.to_json())
    assert restored.fields['d'].numpy_dtype is Decimal
    assert restored.fields['s'].numpy_dtype is np.str_
    assert restored.fields['t'].numpy_dtype is np.datetime64


def test_encode_decode_row_roundtrip():
    schema = _sample_schema()
    image = np.random.default_rng(0).integers(0, 255, (16, 32, 3), dtype=np.uint8)
    emb = np.arange(24, dtype=np.float32).reshape(3, 8)
    row = {'id': 7, 'name': 'hello', 'image': image, 'embedding': emb}
    encoded = encode_row(schema, row)
    assert isinstance(encoded['image'], bytes)
    decoded = decode_row(encoded, schema)
    np.testing.assert_array_equal(decoded['image'], image)
    np.testing.assert_array_equal(decoded['embedding'], emb)
    assert decoded['id'] == 7
    assert decoded['name'] == 'hello'


def test_encode_row_unknown_field_raises():
    schema = _sample_schema()
    with pytest.raises(SchemaError):
        encode_row(schema, {'bogus': 1})


def test_encode_row_missing_non_nullable_raises():
    schema = _sample_schema()
    with pytest.raises(SchemaError):
        encode_row(schema, {'id': 1})


def test_insert_explicit_nulls():
    schema = Unischema('S', [
        UnischemaField('a', np.int32, (), ScalarCodec(), False),
        UnischemaField('b', np.int32, (), ScalarCodec(), True),
    ])
    row = {'a': 1}
    insert_explicit_nulls(schema, row)
    assert row == {'a': 1, 'b': None}


def test_nullable_field_encodes_none():
    schema = _sample_schema()
    image = np.zeros((16, 32, 3), dtype=np.uint8)
    encoded = encode_row(schema, {'id': 1, 'name': 'x', 'image': image, 'embedding': None})
    assert encoded['embedding'] is None
    decoded = decode_row(encoded, schema)
    assert decoded['embedding'] is None


def test_as_arrow_schema():
    schema = _sample_schema()
    arrow = schema.as_arrow_schema()
    assert arrow.field('id').type == pa.int64()
    assert arrow.field('name').type == pa.string()
    assert arrow.field('image').type == pa.binary()
    assert arrow.field('embedding').nullable


def test_from_arrow_schema_inference():
    arrow = pa.schema([
        pa.field('i32', pa.int32()),
        pa.field('f64', pa.float64()),
        pa.field('s', pa.string()),
        pa.field('ts', pa.timestamp('us')),
        pa.field('dec', pa.decimal128(10, 2)),
        pa.field('lst', pa.list_(pa.int64())),
    ])
    schema = Unischema.from_arrow_schema(arrow)
    assert schema.fields['i32'].numpy_dtype is np.int32
    assert schema.fields['ts'].numpy_dtype is np.datetime64
    assert schema.fields['dec'].numpy_dtype is Decimal
    assert schema.fields['lst'].shape == (None,)


def test_from_arrow_schema_unsupported_omitted():
    arrow = pa.schema([
        pa.field('ok', pa.int32()),
        pa.field('bad', pa.struct([pa.field('x', pa.int32())])),
    ])
    schema = Unischema.from_arrow_schema(arrow)
    assert list(schema.fields) == ['ok']
    with pytest.raises(SchemaError):
        Unischema.from_arrow_schema(arrow, omit_unsupported_fields=False)


def test_duplicate_field_names_raise():
    with pytest.raises(SchemaError):
        Unischema('S', [
            UnischemaField('x', np.int32, (), ScalarCodec(), False),
            UnischemaField('x', np.float64, (), ScalarCodec(), False),
        ])


def test_create_schema_view_bare_string():
    schema = Unischema('S', [
        UnischemaField('a', np.int32, (), ScalarCodec(), False),
        UnischemaField('b', np.int32, (), ScalarCodec(), False),
        UnischemaField('ab', np.int32, (), ScalarCodec(), False),
    ])
    view = schema.create_schema_view('ab')  # single pattern, not chars 'a','b'
    assert list(view.fields) == ['ab']


def test_create_schema_view_mismatched_field_raises():
    schema = _sample_schema()
    with pytest.raises(SchemaError):
        schema.create_schema_view([UnischemaField('id', np.float64, (5,), None, False)])


def test_decode_row_unknown_field_raises_schema_error():
    schema = _sample_schema()
    with pytest.raises(SchemaError):
        decode_row({'bogus': b'x'}, schema)


def test_inferred_list_field_roundtrips():
    arrow = pa.schema([pa.field('lst', pa.list_(pa.int64()))])
    schema = Unischema.from_arrow_schema(arrow)
    field = schema.fields['lst']
    arr = np.array([1, 2, 3], dtype=np.int64)
    encoded = field.codec.encode(field, arr)
    np.testing.assert_array_equal(field.codec.decode(field, encoded), arr)
    assert schema.as_arrow_schema().field('lst').type == pa.list_(pa.int64())


def test_decimal_encodes_as_string():
    schema = Unischema('S', [UnischemaField('d', Decimal, (), ScalarCodec(), False)])
    encoded = encode_row(schema, {'d': Decimal('1.5')})
    assert isinstance(encoded['d'], str)
    # and it is writable into the declared arrow column type
    pa.array([encoded['d']], type=schema.as_arrow_schema().field('d').type)
    assert decode_row(encoded, schema)['d'] == Decimal('1.5')
