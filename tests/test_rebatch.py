"""Fixed-size rebatching: BatchingColumnQueue unit tests (reference
pyarrow_helpers/tests/test_batching_table_queue.py semantics, columnar) and
make_batch_reader(batch_size=...) end-to-end."""

import numpy as np
import pytest

from petastorm_tpu.rebatch import BatchingColumnQueue
from petastorm_tpu.reader import make_batch_reader


def _batch(start, n):
    return {'id': np.arange(start, start + n),
            'x': np.arange(start, start + n, dtype=np.float32) * 2.0}


def test_queue_basic_rechunk():
    q = BatchingColumnQueue(4)
    assert q.empty()
    q.put(_batch(0, 10))
    assert not q.empty()
    b1 = q.get()
    np.testing.assert_array_equal(b1['id'], [0, 1, 2, 3])
    b2 = q.get()
    np.testing.assert_array_equal(b2['id'], [4, 5, 6, 7])
    assert q.empty()  # only 2 rows left
    assert len(q) == 2


def test_queue_spans_segments_preserving_order():
    q = BatchingColumnQueue(7)
    q.put(_batch(0, 3))
    assert q.empty()
    q.put(_batch(3, 3))
    q.put(_batch(6, 5))
    b = q.get()
    np.testing.assert_array_equal(b['id'], np.arange(7))
    np.testing.assert_array_equal(b['x'], np.arange(7) * 2.0)
    assert len(q) == 4


def test_queue_drain_and_empty_put():
    q = BatchingColumnQueue(4)
    q.put(_batch(0, 0))  # no-op
    assert q.drain() is None
    q.put(_batch(0, 3))
    d = q.drain()
    np.testing.assert_array_equal(d['id'], [0, 1, 2])
    assert len(q) == 0


def test_queue_exact_multiple_leaves_nothing():
    q = BatchingColumnQueue(5)
    q.put(_batch(0, 10))
    q.get()
    q.get()
    assert q.drain() is None


def test_queue_ragged_batch_rejected():
    q = BatchingColumnQueue(2)
    with pytest.raises(ValueError, match='ragged'):
        q.put({'a': np.arange(3), 'b': np.arange(4)})


def test_queue_object_dtype_columns():
    q = BatchingColumnQueue(3)
    col = np.empty(4, dtype=object)
    col[:] = [b'a', b'bb', None, b'dddd']
    q.put({'s': col})
    q.put({'s': col.copy()})
    got = q.get()
    assert list(got['s']) == [b'a', b'bb', None]


def test_queue_mixed_uniform_and_ragged_list_segments():
    # batch_worker decodes list columns as 2-D when uniform-length, 1-D object
    # otherwise; a batch spanning such segments must degrade to object rows
    q = BatchingColumnQueue(5)
    q.put({'v': np.arange(6, dtype=np.float32).reshape(3, 2)})
    ragged = np.empty(3, dtype=object)
    ragged[0] = np.asarray([1.0])
    ragged[1] = np.asarray([2.0, 3.0, 4.0])
    ragged[2] = None
    q.put({'v': ragged})
    b = q.get()
    assert b['v'].dtype == object
    np.testing.assert_array_equal(b['v'][0], [0.0, 1.0])
    np.testing.assert_array_equal(b['v'][3], [1.0])
    assert len(q) == 1


def test_queue_mismatched_inner_width_segments():
    q = BatchingColumnQueue(4)
    q.put({'v': np.zeros((2, 3), dtype=np.float32)})
    q.put({'v': np.ones((2, 5), dtype=np.float32)})
    b = q.get()
    assert b['v'].dtype == object
    assert b['v'][0].shape == (3,) and b['v'][2].shape == (5,)


def test_drop_last_without_batch_size_rejected(scalar_dataset):
    with pytest.raises(ValueError, match='drop_last requires batch_size'):
        make_batch_reader(scalar_dataset.url, drop_last=True)


def test_batch_reader_fixed_batch_size(scalar_dataset):
    # 100 rows in 10-row groups; batch_size=32 -> 32,32,32,4
    with make_batch_reader(scalar_dataset.url, batch_size=32, workers_count=3,
                           shuffle_row_groups=False) as reader:
        sizes = [len(b.id) for b in reader]
    assert sizes == [32, 32, 32, 4]


def test_batch_reader_fixed_batch_drop_last(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, batch_size=32, workers_count=3,
                           shuffle_row_groups=False) as reader:
        ids = np.concatenate([b.id for b in reader])
    with make_batch_reader(scalar_dataset.url, batch_size=32, drop_last=True,
                           workers_count=3, shuffle_row_groups=False) as reader:
        sizes = [len(b.id) for b in reader]
    assert sizes == [32, 32, 32]
    assert sorted(ids.tolist()) == sorted(r['id'] for r in scalar_dataset.data)


def test_batch_reader_rebatch_preserves_order_unshuffled(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, batch_size=16, workers_count=1,
                           reader_pool_type='dummy', shuffle_row_groups=False) as reader:
        ids = np.concatenate([b.id for b in reader])
    assert ids.tolist() == sorted(r['id'] for r in scalar_dataset.data)


def test_batch_reader_rebatch_multiple_epochs(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, batch_size=64, num_epochs=2,
                           workers_count=3, shuffle_row_groups=False) as reader:
        total = sum(len(b.id) for b in reader)
    assert total == 200


def test_batch_reader_drop_last_discards_tail_across_reset(scalar_dataset):
    # leftover rows from pass 1 must not leak into pass 2's first batch
    reader = make_batch_reader(scalar_dataset.url, batch_size=32, drop_last=True,
                               reader_pool_type='dummy', shuffle_row_groups=False)
    try:
        first = [len(b.id) for b in reader]
        reader.reset()
        second_first_batch = next(iter(reader)).id
        rest = [len(b.id) for b in reader]
    finally:
        reader.stop()
        reader.join()
    assert first == [32, 32, 32]
    assert len(second_first_batch) == 32
    # unshuffled: pass 2 must start from row 0 again, not from pass 1's tail
    assert second_first_batch[0] == min(r['id'] for r in scalar_dataset.data)
    assert rest == [32, 32]


def test_batch_reader_rebatch_with_reset(scalar_dataset):
    reader = make_batch_reader(scalar_dataset.url, batch_size=30, workers_count=2,
                               shuffle_row_groups=False)
    try:
        first = sum(len(b.id) for b in reader)
        reader.reset()
        second = sum(len(b.id) for b in reader)
    finally:
        reader.stop()
        reader.join()
    assert first == second == 100
