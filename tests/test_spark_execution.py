"""EXECUTE the pyspark-gated adapter surfaces (reference spark_utils.py:23-52,
spark/spark_dataset_converter.py:474-526).

This image cannot install pyspark (no JVM, no network egress), so these tests
run the adapters — unmodified, every line — against
``petastorm_tpu.test_util.minispark``, a local engine implementing the exact
pyspark API slice the adapters consume. When a real pyspark IS importable,
the same tests use it instead (the fixture prefers the genuine module), so
nothing here depends on the stand-in beyond this environment's limits.
"""

import sys

import numpy as np
import pytest


def _using_minispark():
    try:
        import pyspark  # noqa: F401
        return False
    except ImportError:
        return True


@pytest.fixture()
def spark(monkeypatch):
    """A SparkSession: real pyspark when available, minispark otherwise
    (monkeypatch pops the scoped module registrations on teardown)."""
    if _using_minispark():
        from petastorm_tpu.test_util import minispark
        scoped = {}
        minispark.install(scoped)
        for name, mod in scoped.items():
            monkeypatch.setitem(sys.modules, name, mod)
    from pyspark.sql import SparkSession
    session = SparkSession.builder.master('local[3]').appName('pstpu-test').getOrCreate()
    yield session
    session.stop()


@pytest.fixture()
def petastorm_store(tmp_path):
    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('S', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('vec', np.float32, (4,), NdarrayCodec(), False),
    ])
    url = 'file://' + str(tmp_path / 'store')
    rng = np.random.default_rng(7)
    rows = {i: rng.random(4).astype(np.float32) for i in range(60)}
    write_petastorm_dataset(url, schema, ({'id': i, 'vec': rows[i]} for i in range(60)),
                            rows_per_row_group=15)
    return url, rows


def test_dataset_as_rdd_executes(spark, petastorm_store):
    """The real dataset_as_rdd chain: schema load, parallelize over shard
    indices, per-partition readers, flatMap — every row exactly once."""
    from petastorm_tpu.spark_utils import dataset_as_rdd

    url, rows = petastorm_store
    rdd = dataset_as_rdd(url, spark)
    collected = rdd.collect()
    assert sorted(int(r.id) for r in collected) == list(range(60))
    for r in collected:
        np.testing.assert_array_equal(np.asarray(r.vec), rows[int(r.id)])
    assert rdd.getNumPartitions() == spark.sparkContext.defaultParallelism


def test_dataset_as_rdd_schema_fields_subset(spark, petastorm_store):
    from petastorm_tpu.spark_utils import dataset_as_rdd

    url, _ = petastorm_store
    collected = dataset_as_rdd(url, spark, schema_fields=['id']).collect()
    assert sorted(int(r.id) for r in collected) == list(range(60))
    assert not hasattr(collected[0], 'vec')


def test_make_spark_converter_dataframe_roundtrip(spark, tmp_path):
    """The Spark-DataFrame branch of the converter: logical-plan fingerprint,
    withColumn float precision casts (scalars AND arrays), df.write.parquet
    materialization, loader readback, cache-hit dedup, delete()."""
    import pandas as pd
    from petastorm_tpu import make_batch_reader
    from petastorm_tpu.spark import make_spark_converter

    # plain-list array cells: real pyspark cannot infer np.ndarray field types
    pdf = pd.DataFrame({
        'idx': np.arange(20, dtype=np.int64),
        'feature': np.linspace(0.0, 1.0, 20).astype(np.float64),
        'emb': [list(np.arange(3, dtype=np.float64) + i) for i in range(20)],
    })
    df = spark.createDataFrame(pdf)
    cache = 'file://' + str(tmp_path / 'cache')

    converter = make_spark_converter(df, parent_cache_dir_url=cache)
    assert len(converter) == 20

    with make_batch_reader(converter.cache_dir_url) as reader:
        blocks = list(reader)
    idx = np.concatenate([np.asarray(b.idx) for b in blocks])
    feat = np.concatenate([np.asarray(b.feature) for b in blocks])
    assert sorted(idx.tolist()) == list(range(20))
    assert feat.dtype == np.float32  # precision='float32' cast applied by withColumn
    # ArrayType(DoubleType) -> ArrayType(FloatType): assert on the STORED
    # schema (readback through python lists re-promotes to float64)
    import pyarrow.fs as pafs
    import pyarrow.parquet as pq
    from petastorm_tpu.fs import FilesystemResolver
    resolver = FilesystemResolver(converter.cache_dir_url)
    fs, root = resolver.filesystem(), resolver.get_dataset_path()
    part = [i.path for i in fs.get_file_info(pafs.FileSelector(root))
            if i.path.endswith('.parquet')][0]
    import pyarrow as pa
    stored = pq.read_schema(fs.open_input_file(part))
    assert stored.field('emb').type == pa.list_(pa.float32())

    # same DataFrame -> same logical plan -> cache hit, no second
    # materialization (same-object reuse is the contract that holds under BOTH
    # engines; a re-created frame gets fresh exprIds under real pyspark)
    converter2 = make_spark_converter(df, parent_cache_dir_url=cache)
    assert converter2.cache_dir_url == converter.cache_dir_url
    if _using_minispark():
        # minispark's plan is a content digest: re-created identical frames
        # dedup too
        converter3 = make_spark_converter(spark.createDataFrame(pdf),
                                          parent_cache_dir_url=cache)
        assert converter3.cache_dir_url == converter.cache_dir_url

    converter.delete()
    info = fs.get_file_info(root)
    assert info.type == pafs.FileType.NotFound


def test_make_spark_converter_jax_loader(spark, tmp_path):
    import pandas as pd
    from petastorm_tpu.spark import make_spark_converter

    pdf = pd.DataFrame({'x': np.arange(32, dtype=np.int64),
                        'y': np.arange(32).astype(np.float64) / 8.0})
    converter = make_spark_converter(spark.createDataFrame(pdf),
                                     parent_cache_dir_url='file://' + str(tmp_path / 'c'))
    seen = []
    with converter.make_jax_loader(batch_size=8, num_epochs=1,
                                   shuffle_row_groups=False) as loader:
        for batch in loader:
            assert batch['y'].dtype == np.float32
            seen.extend(np.asarray(batch['x']).tolist())
    assert sorted(seen) == list(range(32))


def test_dataset_as_rdd_more_partitions_than_row_groups(spark, tmp_path):
    """defaultParallelism > row groups: surplus partitions come back empty
    (reference warns-and-yields-nothing semantics) instead of raising the
    Reader's NoDataAvailableError through the Spark job."""
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.spark_utils import dataset_as_rdd
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('S', [UnischemaField('id', np.int64, (), ScalarCodec(), False)])
    url = 'file://' + str(tmp_path / 'tiny')
    write_petastorm_dataset(url, schema, ({'id': i} for i in range(5)),
                            rows_per_row_group=5)  # ONE row group, local[3] session
    rows = dataset_as_rdd(url, spark).collect()
    assert sorted(r.id for r in rows) == list(range(5))
