"""Flight recorder, hang watchdog, and post-mortem forensics
(observability/blackbox.py; docs/observability.md "Flight recorder").

The acceptance chaos scenario: a process-pool run where one worker is
SIGKILLed and another SIGSEGVs mid-epoch must be reconstructible from the
flight files of the dead processes alone — the crash signal, the dying
stage, and a windowed stall report, with a named probable cause. A
hang-injection run must leave the watchdog's all-thread stack dump in the
flight file. Recording must be structurally free when off.
"""

import json
import os
import signal
import struct
import subprocess
import sys
import time

import pytest

from petastorm_tpu import faults, make_reader
from petastorm_tpu import observability as obs
from petastorm_tpu.observability import blackbox

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _protocol_monitor_on(monkeypatch):
    monkeypatch.setenv('PSTPU_PROTOCOL_MONITOR', '1')


@pytest.fixture
def fault_state(tmp_path):
    d = tmp_path / 'faults'
    d.mkdir()
    yield str(d)
    faults.uninstall()


@pytest.fixture
def flight_dir(tmp_path, monkeypatch):
    """A private run directory; the process-wide singleton is reset on both
    sides so recorders from other tests never leak in (or out)."""
    d = str(tmp_path / 'flight')
    monkeypatch.setenv('PSTPU_FLIGHT_DIR', d)
    monkeypatch.setenv('PSTPU_FLIGHT_INTERVAL', '0.1')
    blackbox.disable()
    yield d
    blackbox.disable()


def _drain_ids(reader):
    ids = []
    for batch in reader:
        ids.extend(int(x) for x in batch.id)
    return ids


def _subprocess_env(flight_dir):
    env = dict(os.environ, PSTPU_FLIGHT_DIR=flight_dir,
               PSTPU_FLIGHT_INTERVAL='0.1')
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    return env


# ---------------------------------------------------------------------------
# the ring: roundtrip, wraparound, torn-tail tolerance
# ---------------------------------------------------------------------------

def test_ring_roundtrip(tmp_path):
    path = str(tmp_path / 'flight-t-1-1.bin')
    rec = blackbox.FlightRecorder(path, label='unit')
    for i in range(5):
        assert rec.record(blackbox.K_EVENT, {'i': i})
    rec.close()
    flight = blackbox.load_flight(path)
    assert flight['label'] == 'unit'
    assert flight['pid'] == os.getpid()
    assert flight['clean_shutdown'] is True
    assert flight['crash_signal'] is None
    assert flight['torn'] == 0
    events = [r for r in flight['records'] if r['kind'] == blackbox.K_EVENT]
    assert [r['data']['i'] for r in events] == [0, 1, 2, 3, 4]
    # close() appends a final snapshot and a 'closing' mark after the events
    assert flight['records'][-1]['data'] == {'event': 'closing'}
    # sequence numbers are contiguous across the whole intact window
    seqs = [r['seq'] for r in flight['records']]
    assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))


def test_ring_wraparound_evicts_whole_records(tmp_path):
    path = str(tmp_path / 'flight-t-1-1.bin')
    rec = blackbox.FlightRecorder(path, capacity=4096, label='wrap')
    for i in range(300):  # ~60 bytes/record: wraps the 4 KiB ring many times
        rec.record(blackbox.K_EVENT, {'i': i, 'pad': 'x' * 16})
    rec.close()
    flight = blackbox.load_flight(path)
    assert flight['torn'] == 0
    events = [r['data']['i'] for r in flight['records']
              if r['kind'] == blackbox.K_EVENT]
    # the oldest records were evicted; the surviving tail is contiguous
    # and ends at the newest write
    assert events[-1] == 299
    assert events == list(range(events[0], 300))
    assert 0 < len(events) < 300


def test_reader_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / 'flight-t-1-1.bin')
    rec = blackbox.FlightRecorder(path, label='torn')
    for i in range(10):
        rec.record(blackbox.K_EVENT, {'i': i})
    # corrupt the LAST record's trailer in place (a crash mid-overwrite):
    # logical [start, size) of the newest record, physical = header + start%cap
    start, size = rec._live[-1]
    tail_at = blackbox.HEADER_SIZE + (start + size - 8) % rec.capacity
    rec._mm[tail_at:tail_at + 8] = struct.pack('<Q', 0xDEAD)
    rec._mm.flush()
    flight = blackbox.load_flight(path)
    assert flight['torn'] == 1
    good = [r['data']['i'] for r in flight['records']
            if r['kind'] == blackbox.K_EVENT]
    assert good == list(range(9)), 'every record before the torn tail is intact'
    rec.close()


def test_oversized_payload_dropped_not_raised(tmp_path):
    path = str(tmp_path / 'flight-t-1-1.bin')
    rec = blackbox.FlightRecorder(path, capacity=4096)
    assert rec.record(blackbox.K_EVENT, {'blob': 'x' * 8192}) is False
    assert rec.dropped == 1
    assert rec.record(blackbox.K_EVENT, {'ok': 1}) is True
    rec.close()
    assert blackbox.load_flight(path)['torn'] == 0


def test_load_flight_rejects_non_flight_file(tmp_path):
    path = str(tmp_path / 'not-a-flight.bin')
    with open(path, 'wb') as f:
        f.write(b'\x00' * 8192)
    with pytest.raises(blackbox.FlightFileError):
        blackbox.load_flight(path)


# ---------------------------------------------------------------------------
# activity slot + enable/disable mechanics
# ---------------------------------------------------------------------------

def test_activity_slot_tracks_stage_timers(flight_dir):
    rec = blackbox.maybe_enable('unit')
    assert rec is not None
    with obs.stage('outer', cat='consumer'):
        with obs.stage('inner', cat='worker'):
            assert rec._activity == 'worker.inner'
        assert rec._activity == 'consumer.outer', 'exit restores the parent stage'
    flight = blackbox.load_flight(rec.path)
    assert flight['activity'] == '', 'outermost exit clears the slot'
    with obs.stage('dying', cat='worker'):
        flight = blackbox.load_flight(rec.path)
        assert flight['activity'] == 'worker.dying'
        assert flight['activity_ts'] is not None


def test_enable_is_idempotent_first_label_wins(flight_dir):
    a = blackbox.maybe_enable('serve-daemon')
    b = blackbox.maybe_enable('consumer')
    assert a is b is blackbox.get_recorder()
    assert 'flight-serve-daemon-' in os.path.basename(a.path)


def test_flight_env_kill_switch(flight_dir, monkeypatch):
    monkeypatch.setenv('PSTPU_FLIGHT', '0')
    assert blackbox.maybe_enable('x') is None
    assert blackbox._ACTIVITY is None
    assert not os.path.exists(flight_dir)


def test_telemetry_off_disables_recording(flight_dir):
    from petastorm_tpu.observability import metrics as _metrics
    level = _metrics.level_name()
    try:
        _metrics.set_level('off')
        assert blackbox.maybe_enable('x') is None
    finally:
        _metrics.set_level(level)


def test_off_is_structurally_free(flight_dir, monkeypatch):
    """With recording off, the stage-timer and record hooks must do ZERO
    blackbox work — booby-trap every recorder entry point and walk the hot
    paths."""
    monkeypatch.setenv('PSTPU_FLIGHT', '0')
    assert blackbox.maybe_enable('x') is None

    def _tripped(*a, **k):
        raise AssertionError('blackbox touched while disabled')
    for name in ('record', 'set_activity', 'watch', 'register_lock'):
        monkeypatch.setattr(blackbox.FlightRecorder, name, _tripped)
    with obs.stage('hot', cat='worker'):
        pass
    blackbox.record_event({'event': 'x'})
    blackbox.record_stall({'reader_wait_s': 0})
    blackbox.watch_progress('p', lambda: 0)
    blackbox.unwatch_progress('p')
    blackbox.register_lock('l', None)
    blackbox.unregister_lock('l')


# ---------------------------------------------------------------------------
# hang watchdog
# ---------------------------------------------------------------------------

def test_watchdog_dumps_stacks_once_per_episode(tmp_path):
    path = str(tmp_path / 'flight-t-1-1.bin')
    rec = blackbox.FlightRecorder(path, label='wd', stall_threshold_s=0.05)
    lock = __import__('threading').Lock()
    lock.acquire()
    rec.register_lock('test.lock', lock)
    rec.watch('progress', lambda: 42)  # frozen source: never resets the timer
    rec.set_activity('worker.fused_decode')
    before = obs.get_registry().counter('watchdog_stall_total').value
    now = time.monotonic()
    rec._pump_once(now=now)            # first tick: baselines the signature
    time.sleep(0.12)                   # stage age crosses the threshold
    rec._pump_once(now=now + 10)       # second tick: stalled -> dump
    rec._pump_once(now=now + 20)       # third tick: same episode, no re-dump
    rec.close()
    lock.release()
    flight = blackbox.load_flight(path)
    dumps = [r for r in flight['records'] if r['kind'] == blackbox.K_WATCHDOG]
    assert len(dumps) == 1, 'one dump per stall episode'
    dump = dumps[0]['data']
    assert dump['activity'] == 'worker.fused_decode'
    assert dump['age_s'] >= 0.05
    assert dump['locks'] == {'test.lock': True}
    assert dump['watch'] == {'progress': 42}
    # the dump carries every thread's Python stack, including this one's
    stacks = '\n'.join(dump['threads'].values())
    assert 'test_watchdog_dumps_stacks_once_per_episode' in stacks
    assert obs.get_registry().counter('watchdog_stall_total').value == before + 1


def test_watchdog_rearms_on_progress(tmp_path):
    path = str(tmp_path / 'flight-t-1-1.bin')
    rec = blackbox.FlightRecorder(path, label='wd', stall_threshold_s=0.05)
    box = {'n': 0}
    rec.watch('progress', lambda: box['n'])
    rec.set_activity('worker.item')
    now = time.monotonic()
    rec._pump_once(now=now)
    time.sleep(0.12)
    rec._pump_once(now=now + 10)       # episode 1 dump
    box['n'] += 1                      # progress: re-arms the watchdog
    rec._pump_once(now=now + 20)       # no dump (progress just moved)
    time.sleep(0.12)
    rec._pump_once(now=now + 40)       # episode 2 dump
    rec.close()
    flight = blackbox.load_flight(path)
    dumps = [r for r in flight['records'] if r['kind'] == blackbox.K_WATCHDOG]
    assert len(dumps) == 2


def test_stall_report_surfaces_watchdog(tmp_path):
    report = obs.stall_report({'reader_wait_s': 1.0, 'rows_read_total': 10,
                               'watchdog_stall_total': 2,
                               'watchdog_last_dump_ts': time.time() - 5})
    assert report['watchdog']['stalls'] == 2
    assert report['watchdog']['last_dump_age_s'] >= 4
    text = obs.format_stall_report(report)
    assert 'watchdog: 2 stall dump(s)' in text
    assert 'petastorm-tpu-blackbox' in text


# ---------------------------------------------------------------------------
# crash capture: footer, sidecar, clean marker — real dead processes
# ---------------------------------------------------------------------------

_DIE_SCRIPT = """\
import os, signal, sys
from petastorm_tpu import observability as obs
from petastorm_tpu.observability import blackbox
rec = blackbox.enable('victim')
rec.record(blackbox.K_EVENT, {{'event': 'about_to_die'}})
with obs.stage('doom', cat='worker'):
    {die}
"""


def _run_victim(flight_dir, die, check_rc=None):
    out = subprocess.run([sys.executable, '-c', _DIE_SCRIPT.format(die=die)],
                         env=_subprocess_env(flight_dir), capture_output=True,
                         timeout=60, cwd=REPO)
    if check_rc is not None:
        assert out.returncode == check_rc, out.stderr[-500:]
    files = [f for f in os.listdir(flight_dir) if f.endswith('.bin')]
    assert len(files) == 1, files
    return os.path.join(flight_dir, files[0])


def test_sigterm_marker_stamps_crash_footer(flight_dir):
    path = _run_victim(flight_dir, 'os.kill(os.getpid(), signal.SIGTERM)',
                       check_rc=-signal.SIGTERM)
    flight = blackbox.load_flight(path)
    assert flight['clean_shutdown'] is False
    assert flight['crash_signal'] == signal.SIGTERM
    assert flight['activity'] == 'worker.doom', 'the dying stage survives'
    report = blackbox.postmortem_report(flight_dir)
    (proc,) = report['processes']
    assert (proc['status'], proc['signal']) == ('crashed', 'SIGTERM')
    assert 'died on SIGTERM mid `worker.doom`' in report['probable_cause']


def test_sigsegv_sidecar_names_the_signal(flight_dir):
    path = _run_victim(flight_dir, 'os.kill(os.getpid(), signal.SIGSEGV)')
    sidecar = blackbox.parse_crash_sidecar(path + '.crash')
    assert sidecar is not None
    assert sidecar['signal'] == 'SIGSEGV'
    assert 'Current thread' in sidecar['text'] or 'Thread' in sidecar['text']
    report = blackbox.postmortem_report(flight_dir)
    (proc,) = report['processes']
    assert (proc['status'], proc['signal']) == ('crashed', 'SIGSEGV')
    assert proc['crash_stacks'], 'the faulthandler stacks ride into the report'
    assert 'died on SIGSEGV mid `worker.doom`' in report['probable_cause']


def test_sigkill_is_inferred_from_absence(flight_dir):
    _run_victim(flight_dir, 'os.kill(os.getpid(), signal.SIGKILL)',
                check_rc=-signal.SIGKILL)
    report = blackbox.postmortem_report(flight_dir)
    (proc,) = report['processes']
    assert (proc['status'], proc['signal']) == ('killed', 'SIGKILL')
    assert 'SIGKILL/OOM' in report['probable_cause']


def test_clean_exit_leaves_shutdown_marker(flight_dir):
    path = _run_victim(flight_dir, 'pass', check_rc=0)  # atexit closes
    flight = blackbox.load_flight(path)
    assert flight['clean_shutdown'] is True
    report = blackbox.postmortem_report(flight_dir)
    assert report['processes'][0]['status'] == 'exited'
    assert 'exited cleanly' in report['probable_cause']


# ---------------------------------------------------------------------------
# post-mortem analyzer
# ---------------------------------------------------------------------------

def _dead_pid():
    """A real, certainly-dead pid (a just-reaped child)."""
    proc = subprocess.Popen([sys.executable, '-c', 'pass'])
    proc.wait()
    return proc.pid


def test_probable_cause_wedged_consumer_dead_daemon(tmp_path):
    """The serve scenario: the consumer is wedged in pool_wait and the daemon
    pid is dead — the cause names both."""
    run_dir = str(tmp_path)
    # daemon: killed (no clean marker, no footer, dead pid patched in)
    daemon = blackbox.FlightRecorder(
        os.path.join(run_dir, 'flight-serve-daemon-1-1.bin'), label='serve-daemon')
    daemon.record(blackbox.K_EVENT, {'event': 'serve_started'})
    daemon.close(clean=False)
    pid = _dead_pid()
    with open(daemon.path, 'r+b') as f:   # pid lives at header offset 12
        f.seek(12)
        f.write(struct.pack('<I', pid))
    # consumer: alive (our pid), with a watchdog dump on record
    consumer = blackbox.FlightRecorder(
        os.path.join(run_dir, 'flight-consumer-2-1.bin'), label='consumer',
        stall_threshold_s=0.01)
    consumer.set_activity('consumer.pool_wait')
    now = time.monotonic()
    consumer._pump_once(now=now)
    time.sleep(0.03)
    consumer._pump_once(now=now + 10)
    consumer.close(clean=False)

    report = blackbox.postmortem_report(run_dir)
    by_label = {p['label']: p for p in report['processes']}
    assert by_label['serve-daemon']['status'] == 'killed'
    assert by_label['consumer']['status'] == 'running'
    assert by_label['consumer']['watchdog_dumps'] == 1
    cause = report['probable_cause']
    assert 'consumer' in cause and 'wedged in `consumer.pool_wait`' in cause
    assert 'peer serve-daemon' in cause


def test_postmortem_skips_garbage_files(tmp_path):
    run_dir = str(tmp_path)
    with open(os.path.join(run_dir, 'flight-junk-1-1.bin'), 'wb') as f:
        f.write(b'garbage')
    rec = blackbox.FlightRecorder(
        os.path.join(run_dir, 'flight-ok-2-1.bin'), label='ok')
    rec.close()
    report = blackbox.postmortem_report(run_dir)
    assert len(report['processes']) == 1
    assert len(report['skipped']) == 1
    assert 'truncated' in report['skipped'][0]['error']


def test_blackbox_cli(tmp_path, capsys):
    rec = blackbox.FlightRecorder(
        os.path.join(str(tmp_path), 'flight-cli-1-1.bin'), label='cli')
    rec.record(blackbox.K_EVENT, {'event': 'hello'})
    rec.close()
    assert blackbox.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert 'post-mortem of' in out
    assert 'cli (pid {})'.format(os.getpid()) in out
    assert blackbox.main([str(tmp_path), '--json']) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed['processes'][0]['label'] == 'cli'
    missing = str(tmp_path / 'nope')
    assert blackbox.main([missing]) == 1


def test_diagnose_postmortem_flag(tmp_path, capsys):
    from petastorm_tpu.observability import diagnose
    rec = blackbox.FlightRecorder(
        os.path.join(str(tmp_path), 'flight-d-1-1.bin'), label='d')
    rec.close()
    assert diagnose.main(['--postmortem', str(tmp_path)]) == 0
    assert 'post-mortem of' in capsys.readouterr().out


# ---------------------------------------------------------------------------
# the acceptance chaos scenario (slow: real process pool, real signals)
# ---------------------------------------------------------------------------

def test_chaos_sigkill_and_sigsegv_reconstructed_postmortem(
        synthetic_dataset, fault_state, flight_dir):
    """One worker SIGKILLed, another SIGSEGVed mid-epoch. The epoch still
    completes exactly once — and afterwards the post-mortem reconstructs,
    from the dead processes' flight files alone, WHICH signal killed each
    worker, the stage each died in, and a named probable cause."""
    faults.install(faults.FaultPlan(kill_items=(3,), segv_items=(6,),
                                    state_dir=fault_state))
    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type='process', workers_count=2,
                     output='columnar', seed=0) as reader:
        ids = _drain_ids(reader)
        assert sorted(ids) == list(range(100)), 'exactly-once delivery held'
        assert reader.diagnostics['worker_restarts'] >= 2

    report = blackbox.postmortem_report(flight_dir)
    dead = {}
    for p in report['processes']:
        if p['status'] in ('crashed', 'killed') and p['label'].startswith('worker'):
            dead[p['signal']] = p
    assert set(dead) == {'SIGSEGV', 'SIGKILL'}, \
        [(p['label'], p['status'], p['signal']) for p in report['processes']]
    # the dying stage: both workers died inside the item wrapper stage
    assert dead['SIGSEGV']['activity'] == 'worker.item'
    assert dead['SIGKILL']['activity'] == 'worker.item'
    # the SIGSEGV is witnessed by the faulthandler sidecar, stacks included
    assert dead['SIGSEGV']['crash_stacks']
    # the consumer recorded the supervision events for both deaths
    consumer = [p for p in report['processes'] if p['label'] == 'consumer']
    assert consumer, [p['label'] for p in report['processes']]
    death_events = [e for e in consumer[0]['events']
                    if isinstance(e, dict) and e.get('event') == 'worker_death']
    assert len(death_events) >= 2
    # the consumer lived the whole epoch at a 0.1s snapshot cadence: the
    # last-N-seconds stall report reconstructs from its snapshots alone
    assert consumer[0]['window_stall_report'] is not None
    assert 'reader_wait_s' in consumer[0]['window_stall_report']
    # the probable cause names the crash, not the kill (crash evidence wins)
    assert 'died on SIGSEGV mid `worker.item`' in report['probable_cause']
    # and the forensics survive rendering
    text = blackbox.format_postmortem(report)
    assert 'probable cause' in text and 'SIGSEGV' in text


def test_chaos_hang_watchdog_dump_lands_in_flight_file(
        synthetic_dataset, fault_state, flight_dir, monkeypatch):
    """A worker wedges mid-item: the in-process watchdog dumps all-thread
    stacks into the flight file while the process is still hung, and the
    post-mortem surfaces the wedge."""
    monkeypatch.setenv('PSTPU_FLIGHT_STALL_S', '0.3')
    faults.install(faults.FaultPlan(hang_items=(4,), hang_s=2.0,
                                    state_dir=fault_state))
    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type='process', workers_count=2,
                     output='columnar', seed=0) as reader:
        ids = _drain_ids(reader)
        assert sorted(ids) == list(range(100))

    report = blackbox.postmortem_report(flight_dir)
    # the consumer may legitimately dump too (pool_wait starves during the
    # hang); the proof is the WORKER's dump naming the wedged fault stage
    wedged = [p for p in report['processes']
              if p['watchdog_dumps']
              and (p['last_watchdog'] or {}).get('activity') == 'fault.fault_hang']
    assert wedged, [(p['label'], p['watchdog_dumps'],
                     (p['last_watchdog'] or {}).get('activity'))
                    for p in report['processes']]
    dump = wedged[0]['last_watchdog']
    assert dump['age_s'] >= 0.3
    stacks = '\n'.join(dump['threads'].values())
    assert 'on_item' in stacks, 'the wedged stack names the hanging frame'


def test_fault_plan_segv_and_hang_one_shot_need_state_dir():
    with pytest.raises(ValueError, match='state_dir'):
        faults.FaultPlan(segv_items=(1,))
    with pytest.raises(ValueError, match='state_dir'):
        faults.FaultPlan(hang_items=(1,))
    plan = faults.FaultPlan(segv_items=(1,), segv_once=False,
                            hang_items=(2,), hang_once=False, hang_s=0.5)
    assert 'segv_items=(1,)' in repr(plan)
    assert 'hang_items=(2,)' in repr(plan)
