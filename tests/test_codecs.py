"""Codec unit tests (modeled on reference tests/test_codec_*.py)."""

from decimal import Decimal

import numpy as np
import pytest

from petastorm_tpu.codecs import (CompressedImageCodec, CompressedNdarrayCodec, NdarrayCodec,
                                  RawTensorCodec, ScalarCodec, codec_from_json)
from petastorm_tpu.errors import SchemaError
from petastorm_tpu.unischema import UnischemaField


def _field(name='f', dtype=np.uint8, shape=(2, 3), codec=None, nullable=False):
    return UnischemaField(name, dtype, shape, codec, nullable)


class TestScalarCodec:
    def test_int_roundtrip(self):
        codec = ScalarCodec()
        field = _field(dtype=np.int32, shape=(), codec=codec)
        encoded = codec.encode(field, 42)
        assert codec.decode(field, encoded) == np.int32(42)

    def test_string_roundtrip(self):
        codec = ScalarCodec()
        field = _field(dtype=np.str_, shape=(), codec=codec)
        assert codec.decode(field, codec.encode(field, 'abc')) == 'abc'

    def test_decimal_roundtrip(self):
        codec = ScalarCodec()
        field = _field(dtype=Decimal, shape=(), codec=codec)
        encoded = codec.encode(field, Decimal('123.45'))
        assert codec.decode(field, '123.45') == Decimal('123.45')
        assert isinstance(encoded, Decimal) or isinstance(encoded, str)

    def test_storage_dtype_override(self):
        codec = ScalarCodec(dtype=np.int16)
        field = _field(dtype=np.int64, shape=(), codec=codec)
        import pyarrow as pa
        assert codec.arrow_type(field) == pa.int16()

    def test_rejects_non_scalar_field(self):
        codec = ScalarCodec()
        field = _field(dtype=np.int32, shape=(2,), codec=NdarrayCodec())
        with pytest.raises(SchemaError):
            codec.encode(field, np.zeros(2, dtype=np.int32))


class TestNdarrayCodec:
    def test_roundtrip(self):
        codec = NdarrayCodec()
        field = _field(dtype=np.float32, shape=(3, 4), codec=codec)
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        out = codec.decode(field, codec.encode(field, arr))
        np.testing.assert_array_equal(out, arr)
        assert out.dtype == np.float32

    def test_wildcard_shape(self):
        codec = NdarrayCodec()
        field = _field(dtype=np.int64, shape=(None, 2), codec=codec)
        arr = np.zeros((7, 2), dtype=np.int64)
        np.testing.assert_array_equal(codec.decode(field, codec.encode(field, arr)), arr)

    def test_wrong_rank_raises(self):
        codec = NdarrayCodec()
        field = _field(dtype=np.int64, shape=(None, 2), codec=codec)
        with pytest.raises(SchemaError):
            codec.encode(field, np.zeros((7,), dtype=np.int64))

    def test_wrong_dim_raises(self):
        codec = NdarrayCodec()
        field = _field(dtype=np.int64, shape=(None, 2), codec=codec)
        with pytest.raises(SchemaError):
            codec.encode(field, np.zeros((7, 3), dtype=np.int64))

    def test_wrong_dtype_raises(self):
        codec = NdarrayCodec()
        field = _field(dtype=np.float32, shape=(2,), codec=codec)
        with pytest.raises(SchemaError):
            codec.encode(field, np.zeros(2, dtype=np.float64))


class TestRawTensorCodec:
    def _codec_field(self, dtype=np.float32, shape=(3, 4)):
        codec = RawTensorCodec()
        return codec, _field(dtype=dtype, shape=shape, codec=codec)

    def test_roundtrip(self):
        codec, field = self._codec_field()
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        encoded = codec.encode(field, arr)
        assert len(encoded) == arr.nbytes  # raw payload, no header
        out = codec.decode(field, encoded)
        np.testing.assert_array_equal(out, arr)
        assert out.flags.writeable

    def test_wildcard_shape_rejected(self):
        codec, field = self._codec_field(shape=(None, 2))
        with pytest.raises(SchemaError, match='fully-specified'):
            codec.encode(field, np.zeros((7, 2), dtype=np.float32))

    def test_non_numeric_dtype_rejected(self):
        codec, field = self._codec_field(dtype=np.str_, shape=(2,))
        with pytest.raises(SchemaError):
            codec.encode(field, np.array(['a', 'b']))

    def test_wrong_cell_length_raises(self):
        codec, field = self._codec_field(dtype=np.int16, shape=(4,))
        with pytest.raises(SchemaError, match='expected'):
            codec.decode(field, b'\x00' * 7)

    def test_decode_column_is_zero_copy_view(self):
        import pyarrow as pa
        codec, field = self._codec_field(dtype=np.uint8, shape=(2, 5))
        cells = [codec.encode(field, np.full((2, 5), i, dtype=np.uint8)) for i in range(6)]
        column = pa.chunked_array([pa.array(cells, type=pa.binary())])
        out = codec.decode_column(field, column)
        assert out.shape == (6, 2, 5)
        for i in range(6):
            assert (out[i] == i).all()
        base = np.frombuffer(column.chunk(0).buffers()[2], dtype=np.uint8)
        assert np.shares_memory(out, base)

    def test_decode_column_sliced_array(self):
        import pyarrow as pa
        codec, field = self._codec_field(dtype=np.int32, shape=(3,))
        cells = [codec.encode(field, np.array([i, i, i], dtype=np.int32)) for i in range(8)]
        column = pa.chunked_array([pa.array(cells, type=pa.binary()).slice(2, 5)])
        out = codec.decode_column(field, column)
        assert out.shape == (5, 3)
        np.testing.assert_array_equal(out[:, 0], np.arange(2, 7))

    def test_decode_column_bad_cell_falls_back(self):
        import pyarrow as pa
        codec, field = self._codec_field(dtype=np.int32, shape=(3,))
        cells = [codec.encode(field, np.zeros(3, dtype=np.int32)), b'short']
        column = pa.chunked_array([pa.array(cells, type=pa.binary())])
        assert codec.decode_column(field, column) is None

    def test_decode_column_nulls_fall_back(self):
        import pyarrow as pa
        codec, field = self._codec_field(dtype=np.int32, shape=(3,))
        cells = [codec.encode(field, np.zeros(3, dtype=np.int32)), None]
        column = pa.chunked_array([pa.array(cells, type=pa.binary())])
        assert codec.decode_column(field, column) is None

    def test_json_roundtrip(self):
        codec = RawTensorCodec()
        assert codec_from_json(codec.to_json()) == codec


class TestCompressedNdarrayCodec:
    def test_roundtrip(self):
        codec = CompressedNdarrayCodec()
        field = _field(dtype=np.float64, shape=(100, 10), codec=codec)
        arr = np.random.default_rng(1).random((100, 10))
        out = codec.decode(field, codec.encode(field, arr))
        np.testing.assert_array_equal(out, arr)

    def test_compresses_redundant_data(self):
        codec = CompressedNdarrayCodec()
        raw = NdarrayCodec()
        field = _field(dtype=np.float64, shape=(1000,), codec=codec)
        arr = np.zeros(1000)
        assert len(codec.encode(field, arr)) < len(raw.encode(field, arr))


class TestCompressedImageCodec:
    def test_png_lossless_roundtrip(self, rng):
        codec = CompressedImageCodec('png')
        field = _field(dtype=np.uint8, shape=(32, 16, 3), codec=codec)
        img = rng.integers(0, 255, (32, 16, 3), dtype=np.uint8)
        out = codec.decode(field, codec.encode(field, img))
        np.testing.assert_array_equal(out, img)  # png is lossless; RGB order preserved

    def test_grayscale_roundtrip(self, rng):
        codec = CompressedImageCodec('png')
        field = _field(dtype=np.uint8, shape=(32, 16), codec=codec)
        img = rng.integers(0, 255, (32, 16), dtype=np.uint8)
        out = codec.decode(field, codec.encode(field, img))
        np.testing.assert_array_equal(out, img)

    def test_jpeg_lossy_close(self, rng):
        codec = CompressedImageCodec('jpeg', quality=95)
        field = _field(dtype=np.uint8, shape=(64, 64, 3), codec=codec)
        img = np.full((64, 64, 3), 128, dtype=np.uint8)
        out = codec.decode(field, codec.encode(field, img))
        assert out.shape == img.shape
        assert np.abs(out.astype(int) - img.astype(int)).mean() < 5

    def test_uint16_png(self, rng):
        codec = CompressedImageCodec('png')
        field = _field(dtype=np.uint16, shape=(8, 8), codec=codec)
        img = rng.integers(0, 2 ** 16 - 1, (8, 8), dtype=np.uint16)
        out = codec.decode(field, codec.encode(field, img))
        np.testing.assert_array_equal(out, img)

    def test_uint16_jpeg_rejected(self):
        codec = CompressedImageCodec('jpeg')
        field = _field(dtype=np.uint16, shape=(8, 8), codec=codec)
        with pytest.raises(SchemaError):
            codec.encode(field, np.zeros((8, 8), dtype=np.uint16))

    def test_bad_format_rejected(self):
        with pytest.raises(SchemaError):
            CompressedImageCodec('webm')


def test_codec_json_roundtrip():
    for codec in [ScalarCodec(), ScalarCodec(dtype=np.int16), NdarrayCodec(),
                  CompressedNdarrayCodec(), CompressedImageCodec('jpeg', quality=77)]:
        restored = codec_from_json(codec.to_json())
        assert restored.to_json() == codec.to_json()
        assert type(restored) is type(codec)


def test_unknown_codec_id_raises():
    with pytest.raises(SchemaError):
        codec_from_json({'codec_id': 'nope'})


def test_datetime_scalar_writable_to_arrow_column():
    import pyarrow as pa
    codec = ScalarCodec()
    field = _field(dtype=np.datetime64, shape=(), codec=codec)
    # second-precision input must normalize to ns so it fits timestamp('ns')
    encoded = codec.encode(field, np.datetime64('2024-01-02T03:04:05'))
    pa.array([encoded], type=codec.arrow_type(field))
    assert codec.decode(field, encoded) == np.datetime64('2024-01-02T03:04:05', 'ns')
