"""Shuffling buffer tests (modeled on reference tests/test_shuffling_buffer.py)."""

import numpy as np
import pytest

from petastorm_tpu.shuffling_buffer import NoopShufflingBuffer, RandomShufflingBuffer


class TestNoop:
    def test_fifo(self):
        buf = NoopShufflingBuffer()
        buf.add_many([1, 2, 3])
        assert [buf.retrieve() for _ in range(3)] == [1, 2, 3]
        assert not buf.can_retrieve()


class TestRandom:
    def test_all_items_out(self):
        buf = RandomShufflingBuffer(100, min_after_retrieve=10, seed=0)
        buf.add_many(range(50))
        out = []
        while buf.can_retrieve():
            out.append(buf.retrieve())
        assert len(out) == 40  # stalls at the watermark
        buf.finish()
        while buf.can_retrieve():
            out.append(buf.retrieve())
        assert sorted(out) == list(range(50))

    def test_shuffles(self):
        buf = RandomShufflingBuffer(1000, min_after_retrieve=1, seed=7)
        buf.add_many(range(500))
        buf.finish()
        out = [buf.retrieve() for _ in range(500)]
        assert out != list(range(500))
        assert sorted(out) == list(range(500))

    def test_seeded_reproducible(self):
        outs = []
        for _ in range(2):
            buf = RandomShufflingBuffer(100, min_after_retrieve=1, seed=42)
            buf.add_many(range(100))
            buf.finish()
            outs.append([buf.retrieve() for _ in range(100)])
        assert outs[0] == outs[1]

    def test_can_add_respects_capacity(self):
        buf = RandomShufflingBuffer(10, min_after_retrieve=2, extra_capacity=100)
        assert buf.can_add()
        buf.add_many(range(10))
        assert not buf.can_add()

    def test_overflow_raises(self):
        buf = RandomShufflingBuffer(10, min_after_retrieve=2, extra_capacity=5)
        with pytest.raises(RuntimeError):
            buf.add_many(range(100))

    def test_add_after_finish_raises(self):
        buf = RandomShufflingBuffer(10, min_after_retrieve=2)
        buf.finish()
        with pytest.raises(RuntimeError):
            buf.add_many([1])

    def test_bad_watermark(self):
        with pytest.raises(ValueError):
            RandomShufflingBuffer(10, min_after_retrieve=10)

    def test_decorrelation_quality(self):
        """Rank correlation of shuffled vs input order should be near zero
        (reference test_util/shuffling_analysis.py:52-85 methodology)."""
        n = 2000
        buf = RandomShufflingBuffer(n + 1, min_after_retrieve=1, extra_capacity=n, seed=1)
        buf.add_many(range(n))
        buf.finish()
        out = np.array([buf.retrieve() for _ in range(n)])
        corr = np.corrcoef(np.arange(n), out)[0, 1]
        assert abs(corr) < 0.1

    def test_rng_state_restore_reproduces_retrieval_order(self):
        # loader checkpoints save/restore this mid-stream: restoring the state
        # must replay the exact retrieval sequence from that point on
        buf = RandomShufflingBuffer(50, min_after_retrieve=1, extra_capacity=100, seed=9)
        buf.add_many(range(40))
        for _ in range(10):
            buf.retrieve()
        snapshot_state = buf.rng_state
        snapshot_items = list(buf._items)
        expected = [buf.retrieve() for _ in range(10)]

        replay = RandomShufflingBuffer(50, min_after_retrieve=1, extra_capacity=100, seed=9)
        replay.add_many(snapshot_items)
        replay.rng_state = snapshot_state
        assert [replay.retrieve() for _ in range(10)] == expected
