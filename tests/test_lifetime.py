"""Runtime half of the shared-plane borrow checker (native/lifetime.py):
slot refcounts via finalizers, blocked/forced reclamation, the ring's FIFO
release ledger, zero-copy delivery parity, and the PROT_NONE guard.

Served-reader parity rides on tests/test_serve.py — the serve blob path
adopts every delivered batch into a registry slot by default, so its
row-equality tests exercise the borrowed path end to end. The static half
(PT1100–PT1103) is proven in tests/test_static_analysis.py; the SEEDED
use-after-release defect is caught both there (the PT1100 fixture) and here
(``test_guard_faults_use_after_release``)."""

import gc
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from petastorm_tpu.native.lifetime import (COUNTER_KEYS, RingBorrowLedger,
                                           SlotRegistry, buffer_region,
                                           registry)
from petastorm_tpu.native.shm_ring import ShmRing


# ---------------------------------------------------------------------------
# Slot units
# ---------------------------------------------------------------------------

def test_last_borrow_death_fires_release_once():
    reg = SlotRegistry()
    fired = []
    slot = reg.open_slot(on_release=lambda: fired.append(1))
    a = np.arange(8)
    b = {'nested': [a[2:]]}  # derived view: base rides along
    slot.adopt(a)
    slot.adopt(b)
    slot.seal()
    assert slot.live == 2 and fired == []
    del a
    gc.collect()
    assert fired == []  # the slice in b keeps its base alive
    del b
    gc.collect()
    assert fired == [1]
    assert reg.counters()['lifetime_live_borrows'] == 0


def test_seal_with_no_borrows_releases_immediately():
    reg = SlotRegistry()
    fired = []
    slot = reg.open_slot(on_release=lambda: fired.append(1))
    slot.seal()
    assert fired == [1] and slot.released


def test_release_now_is_idempotent_and_reclaim_agrees():
    reg = SlotRegistry()
    fired = []
    slot = reg.open_slot(on_release=lambda: fired.append(1))
    slot.release_now()
    slot.release_now()
    assert fired == [1]
    assert slot.try_reclaim() is True  # already gone: reclaimer proceeds
    assert fired == [1]
    assert reg.counters()['lifetime_blocked_reclaims'] == 0


def test_try_reclaim_refuses_while_borrows_live():
    reg = SlotRegistry()
    slot = reg.open_slot()
    arr = np.zeros(4)
    slot.adopt(arr)
    slot.seal()
    assert slot.try_reclaim() is False
    assert reg.counters()['lifetime_blocked_reclaims'] == 1
    del arr
    gc.collect()
    assert slot.try_reclaim() is True


def test_force_reclaim_over_live_borrow_counts_guard_fault(monkeypatch):
    monkeypatch.delenv('PSTPU_LIFETIME_GUARD', raising=False)
    reg = SlotRegistry()
    fired = []
    slot = reg.open_slot(on_release=lambda: fired.append(1))
    arr = np.zeros(4)
    slot.adopt(arr)
    slot.seal()
    slot.force_reclaim()
    assert fired == [1]
    assert reg.counters()['lifetime_guard_faults'] == 1
    del arr  # the late finalizer must not double-fire
    gc.collect()
    assert fired == [1]


def test_buffer_region_resolves_arrays_and_views():
    arr = np.arange(16, dtype=np.uint8)
    addr, nbytes = buffer_region(arr)
    assert addr == arr.ctypes.data and nbytes == 16
    assert buffer_region(memoryview(arr)) == (addr, 16)
    assert buffer_region(object()) is None


def test_pool_diagnostics_carry_the_lifetime_family():
    from petastorm_tpu.test_util.stub_workers import IdentityWorker
    from petastorm_tpu.workers import ThreadPool
    pool = ThreadPool(1)
    pool.start(IdentityWorker)
    try:
        assert set(COUNTER_KEYS) <= set(pool.diagnostics)
    finally:
        pool.stop(); pool.join()


# ---------------------------------------------------------------------------
# RingBorrowLedger: FIFO retirement over arbitrary finalizer order
# ---------------------------------------------------------------------------

def _fresh_ring(capacity=1 << 16):
    return ShmRing.create('/pstpu_lt_{}_{}'.format(os.getpid(), _fresh_ring.n),
                          capacity)


_fresh_ring.n = 0


@pytest.fixture
def ring():
    _fresh_ring.n += 1
    r = _fresh_ring()
    yield r
    r.close()


def _take_all(ring, ledger):
    """[(payload_copy, slot)] for every pending message, borrowed or not."""
    out = []
    while True:
        item = ring.try_read_zero_copy()
        if item is None:
            return out
        view, span, borrowed = item
        slot = ledger.take(view, span, borrowed)
        out.append((bytes(view), slot))


def test_ledger_retires_fifo_despite_out_of_order_release(ring):
    reg = SlotRegistry()
    ledger = RingBorrowLedger(ring, registry_=reg)
    for i in range(3):
        assert ring.try_write(bytes([i]) * 64)
    taken = _take_all(ring, ledger)
    assert [p[0] for p, _ in taken] == [0, 1, 2]
    # release the LAST take first: the head may not move past unreleased
    # earlier spans, so the ring still looks full to the producer
    taken[2][1].release_now()
    taken[1][1].release_now()
    assert ledger.live == 1
    taken[0][1].release_now()
    assert ledger.live == 0
    # all spans retired: the ring accepts a capacity-straining write again
    assert ring.try_write(b'z' * 1024)


def test_ledger_defers_close_until_drained(ring):
    reg = SlotRegistry()
    ledger = RingBorrowLedger(ring, registry_=reg)
    assert ring.try_write(b'x' * 32)
    (_, slot), = _take_all(ring, ledger)
    closed = []
    assert ledger.close_when_drained(lambda: closed.append(1)) is False
    assert closed == [] and reg.counters()['lifetime_blocked_reclaims'] == 1
    slot.release_now()
    assert closed == [1]


def test_ledger_closes_immediately_when_empty(ring):
    ledger = RingBorrowLedger(ring, registry_=SlotRegistry())
    closed = []
    assert ledger.close_when_drained(lambda: closed.append(1)) is True
    assert closed == [1]


def test_has_message_skips_peeked_but_unreleased(ring):
    ledger = RingBorrowLedger(ring, registry_=SlotRegistry())
    assert ring.try_write(b'a' * 16) and ring.try_write(b'b' * 16)
    assert ring.has_message()
    taken = _take_all(ring, ledger)
    assert len(taken) == 2
    # both delivered (still unreleased): nothing is PENDING anymore
    assert not ring.has_message()
    for _, slot in taken:
        slot.release_now()
    assert not ring.has_message()


def test_ledger_release_order_fuzz(ring):
    """Randomized release orders never wedge the FIFO ledger or corrupt
    payloads (hypothesis-gated; skipped where hypothesis is absent)."""
    hyp = pytest.importorskip('hypothesis')
    from hypothesis import strategies as st

    @hyp.given(st.permutations(range(8)), st.integers(16, 512))
    @hyp.settings(max_examples=25, deadline=None)
    def run(order, size):
        reg = SlotRegistry()
        ledger = RingBorrowLedger(ring, registry_=reg)
        payloads = [bytes([i]) * size for i in range(8)]
        for p in payloads:
            assert ring.try_write(p)
        taken = _take_all(ring, ledger)
        assert [p for p, _ in taken] == payloads
        for i in order:
            taken[i][1].release_now()
        assert ledger.live == 0
        assert reg.counters()['lifetime_live_borrows'] == 0
        assert not ring.has_message()

    run()


# ---------------------------------------------------------------------------
# zero-copy delivery parity: same bits as the copy path
# ---------------------------------------------------------------------------

def _drain_sorted(pool):
    from petastorm_tpu.workers import EmptyResultError
    out = []
    while True:
        try:
            out.append(pool.get_results())
        except EmptyResultError:
            return sorted(out, key=lambda b: b['x'].shape[0])


def _batch_bits(batch):
    return {k: (v.dtype.str, v.shape, v.tobytes()) for k, v in batch.items()}


def test_process_pool_zero_copy_parity():
    from petastorm_tpu.serializers import NumpyBlockSerializer
    from petastorm_tpu.test_util.stub_workers import NumpyBatchWorker
    from petastorm_tpu.workers import ProcessPool
    # the registry is process-global and other suites legitimately hold
    # long-lived borrows (pagescan's pinned mmaps), so assert the DELTA
    gc.collect()
    base_live = registry().counters()['lifetime_live_borrows']
    results = {}
    for zc in (False, True):
        pool = ProcessPool(2, serializer=NumpyBlockSerializer(),
                           transport='shm', zero_copy=zc)
        pool.start(NumpyBatchWorker)
        try:
            for n in range(1, 13):
                pool.ventilate(n)
            batches = _drain_sorted(pool)
            assert pool.diagnostics['zero_copy'] is zc
        finally:
            pool.stop(); pool.join()
        results[zc] = [_batch_bits(b) for b in batches]
        del batches  # the bits are copies; drop the borrowed arrays
    assert results[True] == results[False]
    gc.collect()
    assert registry().counters()['lifetime_live_borrows'] == base_live


def test_zero_copy_batch_survives_pool_shutdown():
    """A consumer may hold the delivered arrays past stop/join: the ledger
    defers the ring unmap, so the bytes stay valid and intact."""
    from petastorm_tpu.serializers import NumpyBlockSerializer
    from petastorm_tpu.test_util.stub_workers import NumpyBatchWorker
    from petastorm_tpu.workers import ProcessPool
    pool = ProcessPool(1, serializer=NumpyBlockSerializer(),
                       transport='shm', zero_copy=True)
    pool.start(NumpyBatchWorker)
    pool.ventilate(9)
    batch = _drain_sorted(pool)[0]
    want = _batch_bits(batch)
    pool.stop(); pool.join()
    assert _batch_bits(batch) == want  # still readable after join
    del batch
    gc.collect()


def test_make_reader_zero_copy_thread_noop(synthetic_dataset):
    """zero_copy is a no-op for in-process pools: identical rows, no
    borrows."""
    from petastorm_tpu import make_reader
    with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                     workers_count=2, shuffle_row_groups=False,
                     zero_copy=True) as reader:
        ids = sorted(row.id for row in reader)
    assert ids == sorted(r['id'] for r in synthetic_dataset.data)


# ---------------------------------------------------------------------------
# the PROT_NONE guard: use-after-release faults loudly
# ---------------------------------------------------------------------------

_GUARD_PROBE = textwrap.dedent('''
    import mmap
    import numpy as np
    from petastorm_tpu.native.lifetime import SlotRegistry, buffer_region
    mm = mmap.mmap(-1, 4096)
    arr = np.frombuffer(mm, dtype=np.uint8)
    reg = SlotRegistry()
    slot = reg.open_slot(guard_region=buffer_region(arr), label='probe')
    view = arr[:64]
    slot.adopt(view)
    slot.seal()
    slot.force_reclaim()  # live borrow: counted + PROT_NONE under the guard
    assert reg.counters()['lifetime_guard_faults'] == 1
    print('PRE-TOUCH', flush=True)
    print(int(view[0]))  # use-after-release: must DIE here under the guard
    print('POST-TOUCH', flush=True)
''')


def _run_guard_probe(guard):
    env = dict(os.environ, PSTPU_LIFETIME_GUARD='1' if guard else '0',
               PYTHONPATH=os.pathsep.join(sys.path))
    return subprocess.run([sys.executable, '-c', _GUARD_PROBE],
                          capture_output=True, text=True, env=env, timeout=60)


def test_guard_faults_use_after_release():
    res = _run_guard_probe(guard=True)
    assert 'PRE-TOUCH' in res.stdout
    assert 'POST-TOUCH' not in res.stdout
    assert res.returncode != 0  # SIGSEGV/SIGBUS, not a clean exit


def test_no_guard_means_no_fault():
    res = _run_guard_probe(guard=False)
    assert res.returncode == 0, res.stderr
    assert 'POST-TOUCH' in res.stdout
