"""End-to-end causal tracing tests: TraceContext minting and propagation,
the zero-extra-messages structural guard, cross-process span-tree
completeness (thread pool, process pool, served reader), critical-path
attribution (timeline sweep + seeded slow stage), pod aggregation and
straggler naming, and the host-stamped/rotating JSONL exporter.

See docs/observability.md ("Causal tracing") for the span taxonomy these
tests pin down.
"""

import json
import os
import time

import pytest

from petastorm_tpu import make_reader
from petastorm_tpu import observability as obs
from petastorm_tpu.jax.loader import JaxDataLoader
from petastorm_tpu.test_util.stub_workers import IdentityWorker
from petastorm_tpu.transform import TransformSpec
from petastorm_tpu.workers import ConcurrentVentilator, EmptyResultError, ThreadPool


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    """Telemetry state is process-global: save/restore the level and clear
    registry + ring around every test (same contract as
    tests/test_observability.py)."""
    saved = obs.current_config()
    obs.get_registry().reset()
    obs.get_ring().clear()
    yield
    obs.configure(saved)
    obs.get_registry().reset()
    obs.get_ring().clear()


def _drain_loader(reader, batch_size=20):
    with JaxDataLoader(reader, batch_size=batch_size, drop_last=False) as loader:
        total = 0
        for batch in loader:
            first = next(iter(batch.values()))
            total += len(first)
        return total, loader.last_trace


def _tree_names(tree):
    names = []
    stack = [tree]
    while stack:
        node = stack.pop()
        if node['name'] != '<root>':
            names.append(node['name'])
        stack.extend(node['children'])
    return names


def _tree_pids(tree):
    pids = set()
    stack = [tree]
    while stack:
        node = stack.pop()
        if node['name'] != '<root>':
            pids.add(node['pid'])
        stack.extend(node['children'])
    return pids


def _assert_causally_linked(events, tree):
    """Every event of the trace must have landed in the tree (no orphans cut
    loose), and every non-root node's parent must be a span that exists."""
    ids = {tree['span']}
    stack = [tree]
    count = 0
    while stack:
        node = stack.pop()
        if node['name'] != '<root>':
            count += 1
            ids.add(node['span'])
        stack.extend(node['children'])
    stack = [tree]
    while stack:
        node = stack.pop()
        for child in node['children']:
            assert child['parent'] in ids or child['parent'] is None
        stack.extend(node['children'])
    stamped = [e for e in events
               if (e.get('args') or {}).get('trace') == tree['trace']]
    assert count == len(stamped)


# ---------------------------------------------------------------------------
# trace-context primitives
# ---------------------------------------------------------------------------

def test_trace_context_minting_and_nesting():
    obs.configure('spans')
    assert obs.current_trace() is None
    with obs.mint_trace('abcd1234', 7):
        ctx = obs.current_trace()
        assert ctx.trace == 'abcd1234:7'
        # the freshly minted context IS the virtual root
        assert ctx.span == ctx.trace
        with obs.stage('ventilate', cat='ventilator'):
            inner = obs.current_trace()
            assert inner.trace == ctx.trace and inner.span != ctx.span
        assert obs.root_of(obs.current_trace()) == obs.trace_root('abcd1234', 7)
    assert obs.current_trace() is None
    # the stage recorded its identity stamps
    (ev,) = [e for e in obs.get_ring().snapshot() if e.get('name') == 'ventilate']
    assert ev['args']['trace'] == 'abcd1234:7'
    assert ev['args']['parent'] == 'abcd1234:7'


def test_trace_context_free_below_spans_level():
    obs.configure('counters')
    with obs.mint_trace('abcd1234', 1):
        assert obs.current_trace() is None
        with obs.stage('ventilate', cat='ventilator'):
            pass
    assert len(obs.get_ring()) == 0


# ---------------------------------------------------------------------------
# propagation: zero extra messages, existing channels only
# ---------------------------------------------------------------------------

def _run_counted_pool(level, items=24):
    """Run one tagged-ventilator workload through a ThreadPool, counting every
    task-queue and results-queue put and recording the tuple arities."""
    obs.configure(level)
    pool = ThreadPool(2)
    counts = {'task': 0, 'results': 0}
    arities = {'task': set(), 'results': set()}
    orig_task_put = pool._task_queue.put
    orig_results_put = pool._results_queue.put

    def task_put(item, *a, **k):
        counts['task'] += 1
        if isinstance(item, tuple):
            arities['task'].add(len(item))
        return orig_task_put(item, *a, **k)

    def results_put(item, *a, **k):
        counts['results'] += 1
        if isinstance(item, tuple):
            arities['results'].add(len(item))
        return orig_results_put(item, *a, **k)

    pool._task_queue.put = task_put
    pool._results_queue.put = results_put
    vent = ConcurrentVentilator(pool.ventilate,
                                [{'value': i} for i in range(items)],
                                tag_items=True)
    pool.start(IdentityWorker, ventilator=vent)
    got = []
    while len(got) < items:
        try:
            got.append(pool.get_results())
        except EmptyResultError:
            time.sleep(0.01)
    pool.stop()
    pool.join()
    assert sorted(got) == list(range(items))
    return counts, arities


def test_tracing_adds_zero_queue_messages():
    """The structural guard: the TraceContext rides the EXISTING task/result
    tuples. Turning spans on must not change the number of queue messages or
    the tuple shapes — only the value in the reserved context slot."""
    off_counts, off_arities = _run_counted_pool('off')
    on_counts, on_arities = _run_counted_pool('spans')
    assert on_counts == off_counts
    assert on_arities == off_arities


def test_telemetry_off_reader_is_trace_free(synthetic_dataset):
    obs.configure('off')
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='thread', workers_count=1,
                         output='columnar')
    total, last_trace = _drain_loader(reader)
    assert total == 100
    assert last_trace is None
    assert reader.last_trace is None
    assert len(obs.get_ring()) == 0


# ---------------------------------------------------------------------------
# span-tree completeness across processes
# ---------------------------------------------------------------------------

def test_thread_pool_batch_span_tree(synthetic_dataset):
    obs.configure('spans')
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='thread', workers_count=2,
                         output='columnar')
    total, last_trace = _drain_loader(reader)
    assert total == 100
    assert last_trace is not None
    events = obs.get_ring().snapshot()
    tree = obs.span_tree(events, last_trace.trace)
    assert tree is not None
    names = _tree_names(tree)
    # dispatch -> worker decode -> consumer wait -> loader collate: the whole
    # batch journey, >= 4 causally linked stages
    assert 'ventilate' in names
    assert 'pool_wait' in names
    assert 'collate' in names
    assert any(n in names for n in ('fused_decode', 'decode', 'read'))
    assert len(set(names)) >= 4
    _assert_causally_linked(events, tree)


def test_process_pool_batch_span_tree_crosses_processes(synthetic_dataset):
    obs.configure('spans')
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='process', workers_count=2,
                         output='columnar')
    total, last_trace = _drain_loader(reader)
    assert total == 100
    assert last_trace is not None
    events = obs.get_ring().snapshot()
    tree = obs.span_tree(events, last_trace.trace)
    assert tree is not None
    names = set(_tree_names(tree))
    assert len(names) >= 4
    # worker spans were recorded in a different process and shipped home on
    # the metrics piggyback: the tree must span >= 2 pids
    assert len(_tree_pids(tree)) >= 2
    _assert_causally_linked(events, tree)


def test_served_reader_batch_span_tree_crosses_processes(tmp_path, synthetic_dataset):
    obs.configure('spans')
    svc_dir = str(tmp_path / 'svc')
    reader = make_reader(synthetic_dataset.url, serve=svc_dir, seed=0,
                         shuffle_row_groups=False, workers_count=2)
    try:
        rows = [r for r in reader]
        assert len(rows) == 100
        last_trace = reader.last_trace
        assert last_trace is not None
        # absorb the daemon-side spans into the local ring, then reconstruct
        fetched = reader.service_trace_events()
        assert fetched
    finally:
        reader.stop()
        reader.join()
    events = obs.get_ring().snapshot()
    tree = obs.span_tree(events, last_trace.trace)
    assert tree is not None
    assert len(set(_tree_names(tree))) >= 4
    # daemon pid (ventilate/decode) + this process (pool_wait on the ring)
    assert len(_tree_pids(tree)) >= 2
    _assert_causally_linked(events, tree)
    from petastorm_tpu.serve.client import connect_service
    conn = connect_service(svc_dir)
    conn.send({'op': 'shutdown'})
    conn.recv()
    conn.close()


# ---------------------------------------------------------------------------
# critical-path attribution
# ---------------------------------------------------------------------------

def _ev(name, cat, ts, dur, span, parent, trace='t:1', pid=1):
    return {'name': name, 'cat': cat, 'ph': 'X', 'ts': ts, 'dur': dur,
            'pid': pid, 'tid': 1,
            'args': {'trace': trace, 'span': span, 'parent': parent}}


def test_critical_path_sweep_covers_makespan_exactly():
    """Async handoff shape: the ventilate span closes long before the worker
    subtree it caused even starts. The sweep must attribute every instant —
    segments sum exactly to the makespan, the handoff gap surfaces as
    '<untraced>', and the dominant stage is the decode, not the parent that
    merely contains it."""
    events = [
        _ev('ventilate', 'ventilator', 0, 100, 'v', 't:1'),
        # worker starts 50us after ventilate ended: an untraced gap
        _ev('decode', 'worker', 150, 800, 'd', 'v', pid=2),
        _ev('pool_wait', 'pool', 950, 250, 'w', 't:1'),
    ]
    tree = obs.span_tree(events, 't:1')
    assert tree['dur'] == 1200
    path = obs.critical_path(tree)
    assert sum(seg['dur_us'] for seg in path) == tree['dur']
    names = [seg['name'] for seg in path]
    assert names == ['ventilate', '<untraced>', 'decode', 'pool_wait']
    dominant = max(path, key=lambda s: s['dur_us'])
    assert dominant['name'] == 'decode' and dominant['pid'] == 2


def test_critical_path_deepest_span_owns_the_instant():
    """A child doing the actual work owns the time over the stage containing
    it, and self time nets out the nesting."""
    events = [
        _ev('read', 'worker', 0, 1000, 'r', 't:1'),
        _ev('arrow_decode', 'native', 200, 600, 'a', 'r'),
    ]
    tree = obs.span_tree(events, 't:1')
    path = obs.critical_path(tree)
    assert [s['name'] for s in path] == ['read', 'arrow_decode', 'read']
    assert sum(s['dur_us'] for s in path) == 1000
    breakdown = obs.stage_breakdown(tree)
    assert breakdown == {'read': 400, 'arrow_decode': 600}


def test_orphan_spans_attach_to_virtual_root():
    """A span whose parent rotated out of the ring must still appear in the
    tree (attached to the root), never silently vanish."""
    events = [_ev('decode', 'worker', 0, 500, 'd', 'gone-parent')]
    tree = obs.span_tree(events, 't:1')
    assert [c['name'] for c in tree['children']] == ['decode']
    assert tree['dur'] == 500


def test_critical_path_names_seeded_slow_stage(synthetic_dataset):
    """Seed a deliberately slow transform; the slowest batch's critical path
    must name it as the dominant stage — the per-batch answer the flat stall
    report cannot give."""
    obs.configure('spans')

    def slow(row):
        time.sleep(0.005)
        return row

    reader = make_reader(synthetic_dataset.url,
                         reader_pool_type='thread', workers_count=1,
                         transform_spec=TransformSpec(slow))
    with reader:
        for _, _row in zip(range(30), reader):
            pass
    events = obs.get_ring().snapshot()
    # the first-dispatched item hits an idle worker: no queue wait, so its
    # dispatch-to-delivery time is genuinely transform-bound
    first = next(t for t in obs.traces_in(events) if t.endswith(':0'))
    tree = obs.span_tree(events, first)
    dominant = max(obs.critical_path(tree), key=lambda s: s['dur_us'])
    assert dominant['name'] == 'transform'
    assert obs.stage_breakdown(tree).get('transform', 0) >= 5000  # >= 5 ms
    # later items queued behind the single busy worker: that wait must not
    # vanish — it surfaces as '<untraced>' on the slowest batch's path
    worst = obs.slowest_batches(events, top=1)[0]
    assert worst['stages'].get('transform', 0) >= 5000
    assert any(s['name'] == '<untraced>' for s in worst['critical_path'])


def test_critical_path_summary_schema(synthetic_dataset):
    obs.configure('spans')
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='thread', workers_count=1,
                         output='columnar')
    total, _ = _drain_loader(reader)
    assert total == 100
    summary = obs.critical_path_summary(top=2)
    assert summary['traced_batches'] >= 10  # one trace per ventilated item
    assert 0 < len(summary['slowest']) <= 2
    entry = summary['slowest'][0]
    assert {'trace', 'makespan_us', 'spans', 'processes', 'stages',
            'critical_path'} <= set(entry)
    # the summary must round-trip through JSON (bench harness embeds it)
    json.dumps(summary)


# ---------------------------------------------------------------------------
# pod aggregation + straggler naming
# ---------------------------------------------------------------------------

def _write_host_series(path, host, points, wait_per_point=0.0):
    """One exporter-style JSONL file: ``points`` is [(ts, rows_emitted)]."""
    ident = obs.host_identity(host)
    with open(path, 'w') as f:
        for i, (ts, rows) in enumerate(points):
            rec = {'ts': ts, 'host': ident,
                   'metrics': {'rows_emitted': rows,
                               'reader_wait_s': wait_per_point * i}}
            f.write(json.dumps(rec) + '\n')


def test_pod_report_names_throughput_straggler(tmp_path):
    pod = tmp_path / 'pod'
    pod.mkdir()
    _write_host_series(str(pod / 'a.jsonl'), 'host0',
                       [(100.0, 0), (110.0, 10000)])
    _write_host_series(str(pod / 'b.jsonl'), 'host1',
                       [(100.0, 0), (110.0, 9000)])
    _write_host_series(str(pod / 'c.jsonl'), 'host2',
                       [(100.0, 0), (110.0, 2000)])
    report = obs.pod_report(str(pod))
    assert len(report['hosts']) == 3
    assert report['straggler'] is not None
    assert report['straggler']['host'] == 'host2'
    assert report['straggler']['reason'] == 'throughput'
    assert report['throughput_skew'] == pytest.approx(0.2)
    text = obs.format_pod_report(report)
    assert 'STRAGGLER host2' in text


def test_pod_report_names_stall_straggler(tmp_path):
    """Equal throughput, but one host spends most of its wall time starving:
    the stall-skew check catches what the throughput check cannot."""
    pod = tmp_path / 'pod'
    pod.mkdir()
    _write_host_series(str(pod / 'a.jsonl'), 'host0',
                       [(100.0, 0), (110.0, 5000)], wait_per_point=0.5)
    _write_host_series(str(pod / 'b.jsonl'), 'host1',
                       [(100.0, 0), (110.0, 5000)], wait_per_point=0.5)
    _write_host_series(str(pod / 'c.jsonl'), 'host2',
                       [(100.0, 0), (110.0, 5000)], wait_per_point=8.0)
    report = obs.pod_report(str(pod))
    assert report['straggler'] is not None
    assert report['straggler']['host'] == 'host2'
    assert report['straggler']['reason'] == 'stall'


def test_pod_report_balanced_pod_has_no_straggler(tmp_path):
    pod = tmp_path / 'pod'
    pod.mkdir()
    for i in range(3):
        _write_host_series(str(pod / 'h{}.jsonl'.format(i)), 'host{}'.format(i),
                           [(100.0, 0), (110.0, 5000 + 100 * i)])
    report = obs.pod_report(str(pod))
    assert report['straggler'] is None
    assert 'no straggler' in obs.format_pod_report(report)


def test_pod_report_merges_rotated_and_restarted_series(tmp_path):
    """A host's rotated backup (.jsonl.1) and a same-key second file must fold
    into one series, and a single-snapshot host reports but does not crash."""
    pod = tmp_path / 'pod'
    pod.mkdir()
    _write_host_series(str(pod / 'a.jsonl.1'), 'host0', [(100.0, 0)])
    # note: load_host_series reads path+'.1' first, then path
    _write_host_series(str(pod / 'a.jsonl'), 'host0', [(110.0, 10000)])
    _write_host_series(str(pod / 'b.jsonl'), 'host1', [(105.0, 500)])
    report = obs.pod_report(str(pod))
    by_host = {h['host']: h for h in report['hosts']}
    assert by_host['host0']['rows_per_s'] == pytest.approx(1000.0)
    assert by_host['host1']['rows_per_s'] is None  # 1 snapshot: no window


def test_diagnose_pod_cli(tmp_path, capsys):
    from petastorm_tpu.observability.diagnose import main as diagnose_main
    pod = tmp_path / 'pod'
    pod.mkdir()
    _write_host_series(str(pod / 'a.jsonl'), 'host0', [(100.0, 0), (110.0, 10000)])
    _write_host_series(str(pod / 'b.jsonl'), 'host1', [(100.0, 0), (110.0, 1000)])
    rc = diagnose_main(['--pod', str(pod)])
    assert rc == 0
    out = capsys.readouterr().out
    assert 'host0' in out and 'STRAGGLER host1' in out


def test_diagnose_batch_cli(synthetic_dataset, capsys):
    from petastorm_tpu.observability.diagnose import main as diagnose_main
    rc = diagnose_main([synthetic_dataset.url, '--batches', '3',
                        '--batch-size', '10', '-p', 'thread', '-w', '1',
                        '--batch', 'slowest'])
    assert rc == 0
    out = capsys.readouterr().out
    assert 'dominant stage' in out
    assert 'critical path:' in out
    assert 'makespan' in out


# ---------------------------------------------------------------------------
# host identity + exporter rotation
# ---------------------------------------------------------------------------

def test_host_identity_fields():
    ident = obs.host_identity()
    assert set(ident) == {'host', 'process_index', 'hostname', 'pid', 'boot_ts'}
    assert ident['pid'] == os.getpid()
    assert isinstance(ident['boot_ts'], float)
    assert obs.host_identity('host7')['host'] == 'host7'
    # the default key is stable within a process
    assert obs.host_identity()['host'] == ident['host']


def test_jsonl_exporter_stamps_host(tmp_path):
    obs.get_registry().counter('rows_total').inc(3)
    path = tmp_path / 'metrics.jsonl'
    with obs.JsonlExporter(str(path), interval_s=60, host_key='hostX'):
        pass  # the stop flush writes one line
    (rec,) = [json.loads(line) for line in path.read_text().splitlines()]
    assert rec['host']['host'] == 'hostX'
    assert rec['host']['pid'] == os.getpid()
    assert rec['metrics']['rows_total'] == 3


def test_jsonl_exporter_rotation_bounds_disk_and_counts_drops(tmp_path):
    obs.configure('counters')
    pad = {'counters': {'pad': 1, 'filler': 12345678}, 'gauges': {},
           'histograms': {}}
    path = tmp_path / 'metrics.jsonl'
    cap = 600
    exporter = obs.JsonlExporter(str(path), interval_s=60, max_bytes=cap,
                                 snapshot_fn=lambda: pad, host_key='h')
    for _ in range(40):
        exporter._flush()
    assert os.path.exists(str(path) + '.1')
    # one backup generation: on-disk use stays under ~2x the cap
    total = os.path.getsize(path) + os.path.getsize(str(path) + '.1')
    line_len = len(path.read_text().splitlines()[0]) + 1
    assert total <= 2 * cap + line_len
    dropped = obs.get_registry().snapshot()['counters'].get(
        'telemetry_export_dropped_total', 0)
    assert dropped > 0
    # every surviving line still parses and carries the stamp
    for line in path.read_text().splitlines():
        assert json.loads(line)['host']['host'] == 'h'


def test_jsonl_exporter_rotated_series_still_loads(tmp_path):
    """The pod loader reads backup + live file as one series."""
    pad = {'counters': {'rows_emitted': 100}, 'gauges': {}, 'histograms': {}}
    path = tmp_path / 'h.jsonl'
    exporter = obs.JsonlExporter(str(path), interval_s=60, max_bytes=400,
                                 snapshot_fn=lambda: pad, host_key='h0')
    for _ in range(10):
        exporter._flush()
    series = obs.load_host_series(str(path))
    assert series['host'] == 'h0'
    live = len(path.read_text().splitlines())
    backup = len((tmp_path / 'h.jsonl.1').read_text().splitlines())
    assert len(series['snapshots']) == live + backup
