"""Shuffle decorrelation measured quantitatively (reference
test_end_to_end.py:309-349 rank-correlation test + shuffling_analysis tool)."""

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.jax import JaxDataLoader
from petastorm_tpu.test_util.shuffling_analysis import (
    compute_correlation_distribution, rank_correlation)


def test_rank_correlation_identity_and_reverse():
    assert rank_correlation(list(range(50))) == pytest.approx(1.0)
    assert rank_correlation(list(range(50))[::-1]) == pytest.approx(-1.0)


def test_unshuffled_stream_fully_correlated(synthetic_dataset):
    corr = compute_correlation_distribution(
        lambda: make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                            shuffle_row_groups=False, schema_fields=['id']),
        num_runs=1)
    assert corr[0] == pytest.approx(1.0)


def test_row_group_shuffle_decorrelates(synthetic_dataset):
    # row-group shuffle alone leaves rows ordered WITHIN each 10-row group, so
    # correlation drops but stays visible; it must be well below unshuffled.
    # Per-run seeds keep the distribution deterministic (with only 10 groups a
    # single unseeded permutation can legitimately land above any fixed cutoff).
    corrs = []
    for seed in range(5):
        corrs.append(compute_correlation_distribution(
            lambda: make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                                shuffle_row_groups=True, seed=seed,
                                schema_fields=['id']),
            num_runs=1)[0])
    assert np.mean(corrs) < 0.5, corrs


def test_row_drop_partitions_improve_decorrelation(synthetic_dataset):
    def mean_corr(**kwargs):
        vals = []
        for seed in range(5):
            vals.append(compute_correlation_distribution(
                lambda: make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                                    shuffle_row_groups=True, seed=seed,
                                    schema_fields=['id'], **kwargs),
                num_runs=1)[0])
        return np.mean(vals)

    base = mean_corr()
    dropped = mean_corr(shuffle_row_drop_partitions=5)
    assert dropped <= base + 0.1  # finer ventilation units never hurt much


def test_shuffling_buffer_reaches_near_zero_correlation(synthetic_dataset):
    # full client-side shuffling buffer on top of group shuffle: near-random
    corrs = []
    for seed in range(5):
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         shuffle_row_groups=True, seed=seed,
                         schema_fields=['id']) as reader:
            loader = JaxDataLoader(reader, batch_size=1, shuffling_queue_capacity=60,
                                   seed=seed, drop_last=False)
            ids = [int(b['id'][0]) for b in loader]
        assert sorted(ids) == list(range(100))
        corrs.append(abs(rank_correlation(ids)))
    assert np.mean(corrs) < 0.35, corrs


def test_columnar_shuffling_buffer_reaches_near_zero_correlation(synthetic_dataset):
    # the index-permutation columnar buffer must match the row buffer's
    # decorrelation contract (same capacity -> comparable rank correlation)
    corrs = []
    for seed in range(5):
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         output='columnar', shuffle_row_groups=True, seed=seed,
                         schema_fields=['id']) as reader:
            loader = JaxDataLoader(reader, batch_size=10, shuffling_queue_capacity=60,
                                   seed=seed, drop_last=False)
            ids = [int(i) for b in loader for i in b['id']]
        assert sorted(ids) == list(range(100))
        corrs.append(abs(rank_correlation(ids)))
    assert np.mean(corrs) < 0.35, corrs
