"""Model + sharded-train-step tests (tiny configs: CPU compile time matters)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from petastorm_tpu.models import MnistCNN, ResNet
from petastorm_tpu.models.resnet import BasicBlock
from petastorm_tpu.models.train import (create_train_state, make_eval_step, make_train_step,
                                        shard_train_state, state_shardings)
from petastorm_tpu.parallel import data_sharding, make_mesh


def _tiny_resnet(num_classes=4):
    return ResNet(stage_sizes=[1, 1], block_cls=BasicBlock, num_classes=num_classes,
                  num_filters=8, dtype=jnp.float32)


def test_mnist_cnn_forward():
    model = MnistCNN()
    x = jnp.zeros((2, 28, 28, 1))
    variables = model.init(jax.random.PRNGKey(0), x)
    logits = model.apply(variables, x)
    assert logits.shape == (2, 10)


def test_tiny_resnet_forward_and_grad():
    model = _tiny_resnet()
    x = jnp.ones((2, 16, 16, 3))
    state = create_train_state(model, jax.random.PRNGKey(0), x)
    step = make_train_step(donate=False)
    labels = jnp.array([0, 1])
    new_state, metrics = step(state, x, labels)
    assert np.isfinite(float(metrics['loss']))
    assert int(new_state.step) == 1
    # params actually changed
    k0 = state.params['head']['kernel']
    k1 = new_state.params['head']['kernel']
    assert not np.allclose(np.asarray(k0), np.asarray(k1))


def test_batchnorm_stats_update():
    model = _tiny_resnet()
    x = jnp.ones((2, 16, 16, 3))
    state = create_train_state(model, jax.random.PRNGKey(0), x)
    step = make_train_step(donate=False)
    new_state, _ = step(state, x, jnp.array([0, 1]))
    before = state.batch_stats['bn_init']['mean']
    after = new_state.batch_stats['bn_init']['mean']
    assert not np.allclose(np.asarray(before), np.asarray(after))


def test_train_loss_decreases():
    model = MnistCNN(num_classes=4)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((16, 14, 14, 1), dtype=np.float32))
    labels = jnp.asarray(rng.integers(0, 4, 16))
    state = create_train_state(model, jax.random.PRNGKey(0), x, learning_rate=0.05)
    step = make_train_step(donate=False)
    losses = []
    for _ in range(10):
        state, metrics = step(state, x, labels)
        losses.append(float(metrics['loss']))
    assert losses[-1] < losses[0]


def test_sharded_train_step_dp_tp():
    mesh = make_mesh(('data', 'model'), axis_shapes=(4, 2))
    model = _tiny_resnet(num_classes=8)
    x = jnp.ones((8, 16, 16, 3))
    state = create_train_state(model, jax.random.PRNGKey(0), x)
    with mesh:
        state = shard_train_state(state, mesh)
        # head kernel is tensor-parallel over 'model'
        assert 'model' in str(state.params['head']['kernel'].sharding.spec)
        images = jax.device_put(x, NamedSharding(mesh, P('data')))
        labels = jax.device_put(jnp.arange(8) % 8, NamedSharding(mesh, P('data')))
        step = make_train_step()
        state, metrics = step(state, images, labels)
    assert np.isfinite(float(metrics['loss']))


def test_eval_step():
    model = _tiny_resnet()
    x = jnp.ones((4, 16, 16, 3))
    state = create_train_state(model, jax.random.PRNGKey(0), x)
    metrics = make_eval_step()(state, x, jnp.array([0, 1, 2, 3]))
    assert 0.0 <= float(metrics['accuracy']) <= 1.0


def test_state_shardings_tree_matches():
    mesh = make_mesh(('data', 'model'), axis_shapes=(4, 2))
    model = _tiny_resnet()
    state = create_train_state(model, jax.random.PRNGKey(0), jnp.ones((1, 16, 16, 3)))
    shardings = state_shardings(state, mesh)
    assert jax.tree_util.tree_structure(shardings) == jax.tree_util.tree_structure(state)


def test_pipeline_to_train_step(synthetic_dataset):
    """Input pipeline -> loader -> sharded batch -> train step: the full slice."""
    from petastorm_tpu import make_reader, TransformSpec
    from petastorm_tpu.jax import JaxDataLoader

    def to_sample(row):
        row['image'] = (row['image_png'][:16, :16].astype(np.float32) / 255.0)
        row['label'] = np.int64(row['id'] % 4)
        return row

    spec = TransformSpec(to_sample,
                         edit_fields=[('image', np.float32, (16, 16, 3), False),
                                      ('label', np.int64, (), False)],
                         removed_fields=['image_png'],
                         selected_fields=['image', 'label'])
    mesh = make_mesh(('data',))
    sharding = data_sharding(mesh)
    model = _tiny_resnet(num_classes=4)
    state = create_train_state(model, jax.random.PRNGKey(0), jnp.ones((1, 16, 16, 3)))
    with mesh:
        state = shard_train_state(state, mesh)
        step = make_train_step(donate=False)
        with make_reader(synthetic_dataset.url, reader_pool_type='thread', workers_count=2,
                         schema_fields=['id', 'image_png'], transform_spec=spec,
                         shuffle_row_groups=True, seed=0) as reader:
            loader = JaxDataLoader(reader, batch_size=16, to_device=sharding)
            n_steps = 0
            for batch in loader:
                state, metrics = step(state, batch['image'], batch['label'])
                n_steps += 1
    assert n_steps == 6  # 100 rows / 16, drop_last
    assert np.isfinite(float(metrics['loss']))


def test_train_step_with_device_preprocess():
    # uint8 batch in, ops normalize/augment fused inside the jitted step
    from petastorm_tpu import ops

    def preprocess(images, rng):
        images = ops.random_flip(images, rng)
        return ops.normalize_images(images, 127.5, 127.5, out_dtype=jnp.float32,
                                    use_pallas=False)

    model = _tiny_resnet()
    state = create_train_state(model, jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)))
    step = make_train_step(donate=False, preprocess_fn=preprocess, preprocess_seed=3)
    images = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 16, 16, 3),
                                                           dtype=np.uint8))
    labels = jnp.array([0, 1])
    new_state, metrics = step(state, images, labels)
    assert np.isfinite(float(metrics['loss']))
    assert int(new_state.step) == 1


class TestSequenceTransformer:
    """Long-context model family: pluggable ring attention over a seq-sharded
    mesh, fed by NGram window stacks."""

    def _data(self, b=8, t=4, f=16, classes=6, seed=0):
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((b, t, f)).astype(np.float32)
        y = rng.integers(0, classes, b)
        return x, y

    def test_forward_shapes(self):
        from petastorm_tpu.models import make_sequence_transformer
        from petastorm_tpu.models.train import create_train_state
        x, _ = self._data()
        model = make_sequence_transformer(num_classes=6)
        state = create_train_state(model, jax.random.PRNGKey(0), jnp.asarray(x))
        logits = state.apply_fn({'params': state.params}, jnp.asarray(x))
        assert logits.shape == (8, 6)

    def test_ring_attention_model_matches_plain(self):
        """Same params, seq-sharded ring attention == single-device full
        attention (ring attention is exact, not an approximation)."""
        from petastorm_tpu.models import make_sequence_transformer
        from petastorm_tpu.parallel import make_mesh
        x, _ = self._data(b=4, t=8, f=16)
        mesh = make_mesh(('data', 'seq'), axis_shapes=(-1, 2))
        plain = make_sequence_transformer(num_classes=6)
        ring = make_sequence_transformer(num_classes=6, mesh=mesh)
        params = plain.init(jax.random.PRNGKey(1), jnp.asarray(x))['params']
        out_plain = plain.apply({'params': params}, jnp.asarray(x))
        with mesh:
            out_ring = jax.jit(lambda p, xx: ring.apply({'params': p}, xx))(
                params, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out_plain), np.asarray(out_ring),
                                   rtol=2e-4, atol=2e-5)

    def test_ulysses_attention_model_matches_plain(self):
        """Same params, Ulysses all-to-all context parallelism == single-device
        full attention (exact, like ring — the two strategies interchange)."""
        from petastorm_tpu.models import make_sequence_transformer
        from petastorm_tpu.parallel import make_mesh
        x, _ = self._data(b=4, t=8, f=16)
        mesh = make_mesh(('data', 'seq'), axis_shapes=(-1, 2))
        plain = make_sequence_transformer(num_classes=6)
        uly = make_sequence_transformer(num_classes=6, mesh=mesh,
                                        context_parallelism='ulysses')
        params = plain.init(jax.random.PRNGKey(1), jnp.asarray(x))['params']
        out_plain = plain.apply({'params': params}, jnp.asarray(x))
        with mesh:
            out_uly = jax.jit(lambda p, xx: uly.apply({'params': p}, xx))(
                params, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out_plain), np.asarray(out_uly),
                                   rtol=2e-4, atol=2e-5)

    def test_ulysses_indivisible_heads_rejected(self):
        from petastorm_tpu.models import make_sequence_transformer
        from petastorm_tpu.parallel import make_mesh
        mesh = make_mesh(('data', 'seq'), axis_shapes=(-1, 4))
        with pytest.raises(ValueError, match='divisible'):
            make_sequence_transformer(num_classes=6, mesh=mesh, num_heads=6,
                                      context_parallelism='ulysses')

    def test_sharded_train_step_from_columnar_ngram(self, tmp_path):
        """The full long-context stack: columnar NGram reader -> time-major
        stacks -> ('data','seq') sharded batches -> ring-attention transformer
        train steps; loss finite and decreasing over a few steps."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from petastorm_tpu import make_reader
        from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
        from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
        from petastorm_tpu.jax import JaxDataLoader
        from petastorm_tpu.jax.loader import stack_ngram_time_axis
        from petastorm_tpu.models import make_sequence_transformer
        from petastorm_tpu.models.train import (create_train_state, make_train_step,
                                                shard_train_state)
        from petastorm_tpu.ngram import NGram
        from petastorm_tpu.parallel import make_mesh
        from petastorm_tpu.unischema import Unischema, UnischemaField

        ts = UnischemaField('ts', np.int64, (), ScalarCodec(), False)
        feat = UnischemaField('f', np.float32, (16,), NdarrayCodec(), False)
        schema = Unischema('Seq', [ts, feat])
        url = 'file://' + str(tmp_path / 'seq')
        rng = np.random.default_rng(0)
        write_petastorm_dataset(
            url, schema,
            ({'ts': i, 'f': rng.standard_normal(16).astype(np.float32)}
             for i in range(200)), rows_per_row_group=25)

        mesh = make_mesh(('data', 'seq'), axis_shapes=(-1, 2))
        window = 4
        ngram = NGram({i: [ts, feat] for i in range(window)}, delta_threshold=1,
                      timestamp_field=ts)
        model = make_sequence_transformer(num_classes=4, mesh=mesh, d_model=32,
                                          num_layers=1)
        # SPMD: init/apply shapes must divide the mesh axes (B by 'data', T by 'seq')
        state = create_train_state(model, jax.random.PRNGKey(0),
                                   jnp.zeros((8, window, 16)), learning_rate=0.05)
        batch_sharding = NamedSharding(mesh, P('data', 'seq', None))
        with mesh:
            state = shard_train_state(state, mesh)
            step = make_train_step(donate=False)
            losses = []
            with make_reader(url, reader_pool_type='dummy', ngram=ngram,
                             output='columnar', shuffle_row_groups=False,
                             num_epochs=None, seed=1) as reader:
                loader = JaxDataLoader(reader, batch_size=8, drop_last=True)
                it = iter(loader)
                for _ in range(8):
                    nested = next(it)
                    stacked = stack_ngram_time_axis(nested)
                    x = jax.device_put(stacked['f'], batch_sharding)
                    labels = jnp.asarray(
                        np.asarray(stacked['ts'][:, 0]) % 4)  # arbitrary labels
                    state, metrics = step(state, x, labels)
                    losses.append(float(metrics['loss']))
        # the labels carry no learnable signal (features are noise); the
        # contract under test is that the full sharded stack RUNS and stays
        # numerically sane, not that this toy task converges
        assert all(np.isfinite(losses))
        assert int(state.step) == 8


class TestMoE:
    def test_moe_layer_ep_sharded_matches_unsharded(self):
        """Same params, expert-parallel execution == unsharded execution:
        sharding constraints change placement, never values."""
        from petastorm_tpu.models import MoEMlp
        from petastorm_tpu.parallel import make_mesh
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((2, 8, 16)).astype(np.float32))
        dense = MoEMlp(num_experts=4, d_hidden=32)
        params = dense.init(jax.random.PRNGKey(0), x)['params']
        y_ref, aux_ref = dense.apply({'params': params}, x)

        mesh = make_mesh(('expert',), devices=jax.devices()[:4])
        ep = MoEMlp(num_experts=4, d_hidden=32, mesh=mesh)
        with mesh:
            y_ep, aux_ep = jax.jit(lambda p, xx: ep.apply({'params': p}, xx))(params, x)
        np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y_ep),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(float(aux_ref), float(aux_ep), rtol=1e-5)

    def test_moe_capacity_drops_overflow_tokens(self):
        """With capacity 1 and every token routed to one expert, only one
        token produces output — the rest are zero (residual passthrough)."""
        from petastorm_tpu.models import MoEMlp
        x = jnp.ones((1, 6, 8))  # identical tokens -> identical routing
        moe = MoEMlp(num_experts=6, d_hidden=4, capacity_factor=1.0)
        params = moe.init(jax.random.PRNGKey(2), x)['params']
        y, _ = moe.apply({'params': params}, x)
        y = np.asarray(y)[0]
        nonzero_rows = int((np.abs(y).sum(axis=1) > 1e-7).sum())
        assert nonzero_rows == 1  # capacity = ceil(6/6 * 1.0) = 1

    def test_moe_aux_loss_balanced_routing_near_one(self):
        """Perfectly balanced routing gives aux_loss ~ 1 (Switch eq. 4 lower
        bound); degenerate routing gives ~ E."""
        from petastorm_tpu.models import MoEMlp
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((4, 32, 16)).astype(np.float32))
        moe = MoEMlp(num_experts=4, d_hidden=8)
        params = moe.init(jax.random.PRNGKey(3), x)['params']
        _, aux = moe.apply({'params': params}, x)
        assert 0.9 <= float(aux) <= 4.0

    def test_moe_transformer_forward_with_ep_and_dp(self):
        from petastorm_tpu.models import MoESequenceTransformer
        from petastorm_tpu.parallel import make_mesh
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.standard_normal((4, 8, 16)).astype(np.float32))
        mesh = make_mesh(('data', 'expert'), axis_shapes=(2, 4))
        model = MoESequenceTransformer(num_classes=5, num_experts=4, d_model=16,
                                       num_heads=2, num_layers=1, mesh=mesh)
        params = model.init(jax.random.PRNGKey(4), x)['params']
        with mesh:
            logits, aux = jax.jit(lambda p, xx: model.apply({'params': p}, xx))(params, x)
        assert logits.shape == (4, 5)
        assert np.isfinite(np.asarray(logits)).all() and np.isfinite(float(aux))

    def test_moe_rejects_indivisible_experts(self):
        from petastorm_tpu.models import MoEMlp
        from petastorm_tpu.parallel import make_mesh
        mesh = make_mesh(('expert',), devices=jax.devices()[:4])
        moe = MoEMlp(num_experts=6, d_hidden=8, mesh=mesh)
        with pytest.raises(ValueError, match='divisible'):
            moe.init(jax.random.PRNGKey(0), jnp.zeros((1, 4, 8)))


def test_expert_capacity_formula():
    from petastorm_tpu.models.moe import expert_capacity
    # ceil AFTER the slack multiply: 8 tokens / 4 experts * 1.25 -> ceil(2.5) = 3
    assert expert_capacity(8, 4, 1.25) == 3
    assert expert_capacity(8, 4, 1.0) == 2
    assert expert_capacity(3, 8, 1.0) == 1   # floor clamp
    assert expert_capacity(8, 1, 2.0) == 8   # ceiling clamp at N


def test_moe_bf16_compute_dtype():
    from petastorm_tpu.models import MoEMlp
    moe = MoEMlp(num_experts=2, d_hidden=8, dtype=jnp.bfloat16)
    x = jnp.ones((1, 4, 8), jnp.bfloat16)
    params = moe.init(jax.random.PRNGKey(0), x)['params']
    y, aux = moe.apply({'params': params}, x)
    assert y.dtype == jnp.bfloat16
    # the FFN actually runs in bf16: jaxpr contains bf16 dot_generals
    jaxpr = str(jax.make_jaxpr(lambda p, xx: moe.apply({'params': p}, xx))(params, x))
    assert 'bf16' in jaxpr
