"""Columnar hot-path tests: block utilities, the columnar shuffling buffer,
``make_reader(output='columnar')``, batched TransformSpec, and loader
checkpoint/resume on the block path.

Mirrors the reference's strategy of exercising reader flavors end-to-end on the
synthetic dataset (reference tests/test_end_to_end.py:37-54) — here for the
block-oriented output the reference never had.
"""

from decimal import Decimal

import numpy as np
import pytest

from petastorm_tpu import TransformSpec, make_batch_reader, make_reader
from petastorm_tpu.columnar import (FifoColumnarBuffer, ShuffledColumnarBuffer,
                                    block_to_rows, concat_blocks, rows_to_block,
                                    stack_cells)
from petastorm_tpu.jax import JaxDataLoader


# -- block utilities ---------------------------------------------------------

def test_stack_cells_uniform_arrays():
    out = stack_cells([np.ones((2, 3)), np.zeros((2, 3))])
    assert out.shape == (2, 2, 3) and out.dtype == np.float64


def test_stack_cells_ragged_to_object():
    out = stack_cells([np.ones(2), np.zeros(3)])
    assert out.dtype == object and out[1].shape == (3,)


def test_stack_cells_none_and_scalars():
    out = stack_cells([None, np.ones(2)])
    assert out.dtype == object and out[0] is None
    nums = stack_cells([np.int64(1), np.int64(2)])
    assert nums.dtype == np.int64 and nums.tolist() == [1, 2]
    strs = stack_cells(['a', 'bb'])
    assert strs.dtype == object and strs.tolist() == ['a', 'bb']


def test_rows_block_round_trip():
    rows = [{'a': np.int64(i), 'b': np.full((2,), i)} for i in range(4)]
    block = rows_to_block(rows)
    assert block['b'].shape == (4, 2)
    back = block_to_rows(block)
    assert [r['a'] for r in back] == [0, 1, 2, 3]


def test_concat_blocks_mixed_layout_degrades_to_object():
    a = {'x': np.ones((2, 3))}
    b = {'x': stack_cells([np.ones(2), np.zeros(4)])}  # object column
    out = concat_blocks([a, b])
    assert out['x'].dtype == object and len(out['x']) == 4


# -- columnar buffers --------------------------------------------------------

def _blocks(num_blocks=10, rows=20):
    for b in range(num_blocks):
        base = b * rows
        yield {'id': np.arange(base, base + rows),
               'v': np.arange(base, base + rows, dtype=np.float32).reshape(rows, 1)}


def test_fifo_buffer_preserves_order():
    buf = FifoColumnarBuffer()
    for blk in _blocks(3, 10):
        buf.add_block(blk)
    out = [buf.emit(7)['id'] for _ in range(4)]
    assert np.concatenate(out).tolist() == list(range(28))
    assert buf.size == 2


def test_shuffled_buffer_emits_every_row_once():
    buf = ShuffledColumnarBuffer(50, 25, seed=3)
    seen = []
    for blk in _blocks(10, 20):
        buf.add_block(blk)
        while buf.can_emit(16):
            seen.append(buf.emit(16)['id'])
    buf.finish()
    while buf.size:
        seen.append(buf.emit(min(16, buf.size))['id'])
    allv = np.concatenate(seen)
    assert sorted(allv.tolist()) == list(range(200))
    # decorrelated: not the identity order
    assert allv.tolist() != list(range(200))


def test_shuffled_buffer_block_larger_than_capacity():
    buf = ShuffledColumnarBuffer(10, 5, seed=0)
    buf.add_block({'id': np.arange(1000)})
    got = []
    while buf.can_emit(64):
        got.append(buf.emit(64)['id'])
    buf.finish()
    while buf.size:
        got.append(buf.emit(min(64, buf.size))['id'])
    assert sorted(np.concatenate(got).tolist()) == list(range(1000))


def test_shuffled_buffer_seed_determinism():
    def stream(seed):
        buf = ShuffledColumnarBuffer(40, 20, seed=seed)
        out = []
        for blk in _blocks(6, 20):
            buf.add_block(blk)
            while buf.can_emit(10):
                out.append(buf.emit(10)['id'])
        buf.finish()
        while buf.size:
            out.append(buf.emit(min(10, buf.size))['id'])
        return np.concatenate(out)

    assert np.array_equal(stream(7), stream(7))
    assert not np.array_equal(stream(7), stream(8))


def test_shuffled_buffer_snapshot_rows_cover_remainder():
    buf = ShuffledColumnarBuffer(50, 25, seed=1)
    for blk in _blocks(4, 20):
        buf.add_block(blk)
    emitted = [buf.emit(16)['id'] for _ in range(2)]
    rows = buf.snapshot_rows()
    rest = [r['id'] for r in rows]
    assert sorted(np.concatenate(emitted).tolist() + rest) == list(range(80))


def test_shuffled_buffer_mixed_segment_layout():
    """A column that is stacked in one block and ragged-object in another must
    still gather without error."""
    buf = ShuffledColumnarBuffer(10, 2, seed=0)
    buf.add_block({'x': np.ones((8, 3)), 'id': np.arange(8)})
    buf.add_block({'x': stack_cells([np.ones(2), np.ones(5)] * 4), 'id': np.arange(8, 16)})
    buf.finish()
    seen = 0
    while buf.size:
        out = buf.emit(min(6, buf.size))
        seen += len(out['id'])
    assert seen == 16


# -- make_reader(output='columnar') -----------------------------------------

def test_columnar_reader_covers_all_rows(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                     schema_fields=['id', 'matrix'], shuffle_row_groups=False) as reader:
        assert reader.batched_output
        ids, mats = [], []
        for block in reader:
            ids.extend(block.id.tolist())
            mats.append(block.matrix)
        assert sorted(ids) == sorted(r['id'] for r in synthetic_dataset.data)
        assert all(m.shape[1:] == (32, 16, 3) for m in mats)


def test_columnar_reader_batch_size_rebatches(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                     batch_size=7, shuffle_row_groups=False,
                     schema_fields=['id']) as reader:
        sizes = [len(b.id) for b in reader]
    assert set(sizes[:-1]) == {7}
    assert sum(sizes) == 100


def test_columnar_reader_decoded_values_match_row_reader(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     shuffle_row_groups=False) as rows_reader:
        row_by_id = {int(r.id): r for r in rows_reader}
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                     shuffle_row_groups=False) as reader:
        for block in reader:
            d = block._asdict()
            for i, row_id in enumerate(d['id'].tolist()):
                ref = row_by_id[int(row_id)]
                np.testing.assert_array_equal(d['matrix'][i], ref.matrix)
                np.testing.assert_array_equal(d['image_png'][i], ref.image_png)
                assert d['decimal'][i] == ref.decimal
                assert d['partition_key'][i] == ref.partition_key
                if ref.matrix_nullable is None:
                    assert d['matrix_nullable'][i] is None
                else:
                    np.testing.assert_array_equal(d['matrix_nullable'][i],
                                                  ref.matrix_nullable)


def test_columnar_reader_rejects_ngram_and_bad_args(synthetic_dataset):
    from petastorm_tpu.ngram import NGram
    from petastorm_tpu.test_util.dataset_utils import TestSchema
    ngram = NGram({0: [TestSchema.id]}, delta_threshold=1, timestamp_field=TestSchema.id)
    # columnar + ngram is supported; rebatching of nested window blocks is not
    with pytest.raises(ValueError, match='ngram'):
        make_reader(synthetic_dataset.url, output='columnar', ngram=ngram, batch_size=4)
    with pytest.raises(ValueError, match='batch_size'):
        make_reader(synthetic_dataset.url, output='rows', batch_size=4)
    with pytest.raises(ValueError, match='output'):
        make_reader(synthetic_dataset.url, output='bogus')


def test_columnar_reader_with_predicate(synthetic_dataset):
    from petastorm_tpu.predicates import in_lambda
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                     schema_fields=['id', 'id2'], shuffle_row_groups=False,
                     predicate=in_lambda(['id'], lambda row: row['id'] % 2 == 0)) as reader:
        ids = [i for b in reader for i in b.id.tolist()]
    expected = sorted(r['id'] for r in synthetic_dataset.data if r['id'] % 2 == 0)
    assert sorted(ids) == expected


def test_batched_transform_spec_columnar(synthetic_dataset):
    """TransformSpec(batched=True) funcs receive/return whole column dicts."""
    def double_ids(cols):
        cols['id'] = cols['id'] * 2
        return cols

    spec = TransformSpec(double_ids, batched=True)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                     schema_fields=['id'], shuffle_row_groups=False,
                     transform_spec=spec) as reader:
        ids = [i for b in reader for i in b.id.tolist()]
    assert sorted(ids) == sorted(2 * r['id'] for r in synthetic_dataset.data)


def test_batched_transform_spec_row_reader(synthetic_dataset):
    """batched=True applies on the row reader's internal blocks too — rows out
    still see transformed values."""
    spec = TransformSpec(lambda cols: {**cols, 'id': cols['id'] + 1000}, batched=True)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     schema_fields=['id'], shuffle_row_groups=False,
                     transform_spec=spec) as reader:
        ids = sorted(int(r.id) for r in reader)
    assert ids == sorted(r['id'] + 1000 for r in synthetic_dataset.data)


# -- loader on the columnar path --------------------------------------------

def test_loader_columnar_shuffled_covers_all_rows(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                     schema_fields=['id'], shuffle_row_groups=False) as reader:
        loader = JaxDataLoader(reader, batch_size=10, shuffling_queue_capacity=30,
                               seed=5, drop_last=False)
        ids = [i for b in loader for i in b['id'].tolist()]
    assert sorted(ids) == sorted(r['id'] for r in synthetic_dataset.data)


def test_loader_columnar_checkpoint_resume_covers_rest(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                         schema_fields=['id'], shuffle_row_groups=False, seed=3)
    loader = JaxDataLoader(reader, batch_size=10, shuffling_queue_capacity=30, seed=3,
                           drop_last=False)
    it = iter(loader)
    seen = [next(it)['id'].tolist() for _ in range(3)]
    state = loader.state_dict()
    reader.stop(); reader.join()

    resumed_reader = make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                                 output='columnar', schema_fields=['id'],
                                 shuffle_row_groups=False, seed=3,
                                 resume_state=state['reader'])
    with JaxDataLoader(resumed_reader, batch_size=10, shuffling_queue_capacity=30, seed=3,
                       drop_last=False, resume_state=state) as resumed:
        rest = [i for b in resumed for i in b['id'].tolist()]
    got = sorted([i for b in seen for i in b] + rest)
    assert got == sorted(r['id'] for r in synthetic_dataset.data)


def test_loader_columnar_seeded_resume_deterministic(synthetic_dataset):
    def run(split_after):
        reader = make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                             output='columnar', schema_fields=['id'],
                             shuffle_row_groups=True, seed=11)
        loader = JaxDataLoader(reader, batch_size=10, shuffling_queue_capacity=30,
                               seed=11, drop_last=False)
        it = iter(loader)
        out = [next(it)['id'].tolist() for _ in range(split_after)]
        state = loader.state_dict()
        reader.stop(); reader.join()
        r2 = make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         output='columnar', schema_fields=['id'],
                         shuffle_row_groups=True, seed=11, resume_state=state['reader'])
        with JaxDataLoader(r2, batch_size=10, shuffling_queue_capacity=30, seed=11,
                           drop_last=False, resume_state=state) as l2:
            out.extend(b['id'].tolist() for b in l2)
        return [i for b in out for i in b]

    # resuming at different points yields one identical seeded stream tail set
    a, b = run(2), run(5)
    assert sorted(a) == sorted(b)


def test_loader_from_batch_reader_shuffled_datetime(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                           shuffle_row_groups=False) as reader:
        loader = JaxDataLoader(reader, batch_size=16, shuffling_queue_capacity=50,
                               seed=2, drop_last=False)
        batches = list(loader)
    ids = np.concatenate([b['id'] for b in batches])
    assert sorted(ids.tolist()) == list(range(100))
    # datetime columns sanitized to int64 ns ticks on the columnar path too
    assert all(b['datetime'].dtype in (np.int64, object) for b in batches)


def test_loader_columnar_decimal_promoted(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                     schema_fields=['id', 'decimal'], shuffle_row_groups=False) as reader:
        batch = next(iter(JaxDataLoader(reader, batch_size=8)))
    assert batch['decimal'].dtype == np.float64


def test_loader_columnar_nullable_datetime_preserves_none(tmp_path):
    """Regression: _sanitize_batch_columns must keep None cells of nullable
    datetime/Decimal columns (row-path parity), not crash or coerce to NaN."""
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('NullTs', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('ts', np.datetime64, (), ScalarCodec(), True),
        UnischemaField('dec', Decimal, (), ScalarCodec(), True),
    ])
    url = 'file://' + str(tmp_path / 'nullts')
    write_petastorm_dataset(url, schema, ({
        'id': i,
        'ts': None if i % 2 else np.datetime64('2024-01-01'),
        'dec': None if i % 3 == 0 else Decimal(i),
    } for i in range(20)), rows_per_row_group=10)
    with make_reader(url, reader_pool_type='dummy', output='columnar',
                     shuffle_row_groups=False) as reader:
        batches = list(JaxDataLoader(reader, batch_size=10, drop_last=False))
    ts = np.concatenate([b['ts'] for b in batches])
    dec = np.concatenate([b['dec'] for b in batches])
    assert ts.dtype == object and sum(v is None for v in ts) == 10
    assert all(v is None or isinstance(v, np.int64) for v in ts)
    assert dec.dtype == object and sum(v is None for v in dec) == 7
    assert all(v is None or isinstance(v, np.float64) for v in dec)


def test_columnar_partition_key_column_is_typed(tmp_path):
    """Regression: partition-key columns in columnar blocks must come out
    typed (np.full), not dtype=object, so they can stage to device."""
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('Part', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('label', np.int64, (), ScalarCodec(), False),
    ])
    url = 'file://' + str(tmp_path / 'part')
    write_petastorm_dataset(url, schema, ({'id': i, 'label': i % 3} for i in range(30)),
                            rows_per_row_group=5, partition_by=['label'])
    with make_reader(url, reader_pool_type='dummy', output='columnar',
                     shuffle_row_groups=False) as reader:
        blocks = [b._asdict() for b in reader]
    labels = np.concatenate([b['label'] for b in blocks])
    assert labels.dtype == np.int64
    assert sorted(labels.tolist()) == sorted(i % 3 for i in range(30))


def test_loader_columnar_multi_epoch_after_drop_last(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                         schema_fields=['id'], shuffle_row_groups=False, num_epochs=None)
    with JaxDataLoader(reader, batch_size=30, drop_last=True) as loader:
        it = iter(loader)
        for _ in range(7):  # crosses the 100-row epoch boundary
            assert len(next(it)['id']) == 30


# -- columnar NGram (round 3) ------------------------------------------------

def _make_ngram(length=3, delta=1, overlap=True):
    from petastorm_tpu.ngram import NGram
    from petastorm_tpu.test_util.dataset_utils import TestSchema
    fields = {i: [TestSchema.id, TestSchema.matrix] if i == 0 else [TestSchema.id]
              for i in range(length)}
    return NGram(fields, delta_threshold=delta, timestamp_field=TestSchema.id,
                 timestamp_overlap=overlap)


def test_form_ngram_columnar_matches_row_path():
    from petastorm_tpu.ngram import NGram
    from petastorm_tpu.unischema import Unischema, UnischemaField
    ts_field = UnischemaField('t', np.int64, ())
    val_field = UnischemaField('v', np.float32, (2,))
    schema = Unischema('S', [ts_field, val_field])
    ngram = NGram({0: [ts_field, val_field], 1: [ts_field]},
                  delta_threshold=2, timestamp_field=ts_field)
    rng = np.random.default_rng(0)
    # unsorted timestamps with gaps that exceed the threshold
    t = np.array([5, 1, 2, 9, 4, 14, 15, 3], dtype=np.int64)
    v = rng.standard_normal((8, 2)).astype(np.float32)
    rows = [{'t': t[i], 'v': v[i]} for i in range(8)]
    row_windows = ngram.form_ngram(rows, schema)
    col_windows = ngram.form_ngram_columnar({'t': t, 'v': v})
    assert len(row_windows) == len(col_windows[0]['t'])
    for w, rw in enumerate(row_windows):
        assert col_windows[0]['t'][w] == rw[0]['t']
        assert col_windows[1]['t'][w] == rw[1]['t']
        np.testing.assert_array_equal(col_windows[0]['v'][w], rw[0]['v'])


@pytest.mark.parametrize('overlap', [True, False])
def test_form_ngram_columnar_overlap_semantics(overlap):
    from petastorm_tpu.ngram import NGram
    from petastorm_tpu.unischema import Unischema, UnischemaField
    ts_field = UnischemaField('t', np.int64, ())
    schema = Unischema('S', [ts_field])
    ngram = NGram({0: [ts_field], 1: [ts_field], 2: [ts_field]},
                  delta_threshold=1, timestamp_field=ts_field,
                  timestamp_overlap=overlap)
    t = np.arange(10, dtype=np.int64)
    rows = [{'t': x} for x in t]
    expected = [w[0]['t'] for w in ngram.form_ngram(rows, schema)]
    got = ngram.form_ngram_columnar({'t': t})[0]['t'].tolist()
    assert got == expected


def test_form_ngram_columnar_no_windows_returns_none():
    from petastorm_tpu.ngram import NGram
    from petastorm_tpu.unischema import UnischemaField
    ts_field = UnischemaField('t', np.int64, ())
    ngram = NGram({0: [ts_field], 1: [ts_field]}, delta_threshold=1,
                  timestamp_field=ts_field)
    assert ngram.form_ngram_columnar({'t': np.array([0], dtype=np.int64)}) is None
    assert ngram.form_ngram_columnar({'t': np.array([0, 5], dtype=np.int64)}) is None


def test_columnar_ngram_reader_matches_row_reader(synthetic_dataset):
    ngram = _make_ngram(length=2, delta=1)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                     shuffle_row_groups=False) as reader:
        row_windows = list(reader)
    ngram2 = _make_ngram(length=2, delta=1)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram2,
                     output='columnar', shuffle_row_groups=False) as reader:
        assert reader.batched_output
        blocks = list(reader)
    col_ids_t0 = [int(i) for b in blocks for i in b[0]['id']]
    row_ids_t0 = [int(w[0].id) for w in row_windows]
    assert col_ids_t0 == row_ids_t0
    col_ids_t1 = [int(i) for b in blocks for i in b[1]['id']]
    assert col_ids_t1 == [int(w[1].id) for w in row_windows]
    # per-offset field sets respected: matrix only at offset 0
    assert 'matrix' in blocks[0][0] and 'matrix' not in blocks[0][1]
    first_row_matrix = row_windows[0][0].matrix
    np.testing.assert_array_equal(blocks[0][0]['matrix'][0], first_row_matrix)


def test_loader_columnar_ngram_time_major_batches(synthetic_dataset):
    from petastorm_tpu.jax.loader import stack_ngram_time_axis
    ngram = _make_ngram(length=3, delta=1)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                     output='columnar', shuffle_row_groups=False) as reader:
        loader = JaxDataLoader(reader, batch_size=4)
        batch = next(iter(loader))
    assert sorted(batch.keys()) == [0, 1, 2]
    assert batch[0]['matrix'].shape == (4, 32, 16, 3)
    np.testing.assert_array_equal(batch[1]['id'], batch[0]['id'] + 1)
    stacked = stack_ngram_time_axis(batch)
    assert stacked['id'].shape == (4, 3)


def test_loader_columnar_ngram_shuffled_covers_all_windows(synthetic_dataset):
    ngram = _make_ngram(length=2, delta=1)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                     shuffle_row_groups=False) as reader:
        expected = sorted(int(w[0].id) for w in reader)
    ngram2 = _make_ngram(length=2, delta=1)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram2,
                     output='columnar', shuffle_row_groups=False) as reader:
        loader = JaxDataLoader(reader, batch_size=8, shuffling_queue_capacity=24,
                               seed=4, drop_last=False)
        got = sorted(int(i) for b in loader for i in b[0]['id'])
    assert got == expected


def test_columnar_ngram_rejected_by_torch_and_tf_surfaces(synthetic_dataset):
    """Nested window blocks are a JaxDataLoader shape; the torch/TF adapters
    reject them with guidance instead of crashing on the first block."""
    from petastorm_tpu.torch_utils import DataLoader as TorchDataLoader
    ngram = _make_ngram(length=2, delta=1)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                     output='columnar', shuffle_row_groups=False) as reader:
        with pytest.raises(ValueError, match='columnar NGram'):
            TorchDataLoader(reader, batch_size=4)
        from petastorm_tpu.tf_utils import make_petastorm_dataset
        with pytest.raises(ValueError, match='columnar NGram'):
            make_petastorm_dataset(reader)


def test_columnar_ngram_rejects_drop_last(synthetic_dataset):
    ngram = _make_ngram(length=2, delta=1)
    with pytest.raises(ValueError, match='drop_last'):
        make_reader(synthetic_dataset.url, output='columnar', ngram=ngram, drop_last=True)


@pytest.mark.parametrize('pool', ['dummy', 'thread', 'process'],
                         ids=['dummy', 'thread', 'process'])
def test_columnar_reader_pool_matrix(synthetic_dataset, pool):
    """Columnar output across every pool type (the e2e matrix's columnar leg):
    full coverage + decoded-image equality through each transport."""
    workers = 1 if pool == 'process' else 3  # spawn cost: one process is enough
    with make_reader(synthetic_dataset.url, reader_pool_type=pool, workers_count=workers,
                     output='columnar', schema_fields=['id', 'image_png'],
                     shuffle_row_groups=False) as reader:
        got = {}
        for block in reader:
            d = block._asdict()
            for i, row_id in enumerate(d['id'].tolist()):
                got[int(row_id)] = np.asarray(d['image_png'][i])
    expected = {r['id']: r['image_png'] for r in synthetic_dataset.data}
    assert sorted(got) == sorted(expected)
    for k in (0, 42, 99):
        np.testing.assert_array_equal(got[k], expected[k])


def test_weighted_sampling_mixes_columnar_readers(synthetic_dataset):
    """WeightedSamplingReader over columnar readers: blocks sample per draw,
    schemas/batched-ness enforced (reference weighted_sampling_reader.py:64-77)."""
    from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader
    r1 = make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                     schema_fields=['id'], shuffle_row_groups=False)
    r2 = make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                     schema_fields=['id'], shuffle_row_groups=False)
    with WeightedSamplingReader([r1, r2], [0.5, 0.5], seed=3) as mixed:
        assert mixed.batched_output
        blocks = [next(mixed) for _ in range(6)]
    assert all(len(b.id) > 0 for b in blocks)
    # mixing a columnar with a row reader is rejected
    r3 = make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     schema_fields=['id'], shuffle_row_groups=False)
    r4 = make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                     schema_fields=['id'], shuffle_row_groups=False)
    try:
        with pytest.raises(Exception, match='batched_output'):
            WeightedSamplingReader([r3, r4], [0.5, 0.5])
    finally:
        for r in (r3, r4):
            r.stop(); r.join()


# -- property tests (hypothesis) ---------------------------------------------

try:
    from hypothesis import given, settings, strategies as st  # noqa: E402
except ImportError:  # only the property tests skip; the module must collect
    class _HypothesisStub(object):
        def __call__(self, *args, **kwargs):
            return self

        def __getattr__(self, name):
            return self

    st = _HypothesisStub()

    def given(*_args, **_kwargs):
        return pytest.mark.skip(reason='hypothesis not installed')

    def settings(*_args, **_kwargs):
        return lambda fn: fn


@settings(max_examples=40, deadline=None)
@given(st.data())
def test_shuffled_buffer_random_interleaving_emits_each_row_once(data):
    """Invariant under ANY interleaving of adds and emits: every row is
    emitted exactly once, sizes always reconcile, no crash."""
    n_blocks = data.draw(st.integers(1, 8))
    block_sizes = [data.draw(st.integers(1, 40)) for _ in range(n_blocks)]
    capacity = data.draw(st.integers(2, 60))
    min_after = data.draw(st.integers(1, capacity - 1))
    seed = data.draw(st.integers(0, 2 ** 31))
    buf = ShuffledColumnarBuffer(capacity, min_after, seed=seed)
    next_id = 0
    emitted = []
    blocks = []
    for size in block_sizes:
        blocks.append(np.arange(next_id, next_id + size))
        next_id += size
    pending = list(blocks)
    while pending or buf.size:
        do_add = pending and (not buf.size or data.draw(st.booleans()))
        if do_add:
            buf.add_block({'id': pending.pop(0)})
        elif buf.size:
            if not pending:
                buf.finish()
            count = data.draw(st.integers(1, max(1, min(buf.size, 16))))
            before = buf.size
            out = buf.emit(count)
            emitted.extend(out['id'].tolist())
            assert buf.size == before - len(out['id'])
    assert sorted(emitted) == list(range(next_id))


@settings(max_examples=30, deadline=None)
@given(st.data())
def test_fifo_buffer_random_interleaving_preserves_order(data):
    sizes = data.draw(st.lists(st.integers(1, 30), min_size=1, max_size=8))
    buf = FifoColumnarBuffer()
    next_id = 0
    emitted = []
    pending = []
    for s in sizes:
        pending.append(np.arange(next_id, next_id + s))
        next_id += s
    while pending or buf.size:
        if pending and (not buf.size or data.draw(st.booleans())):
            buf.add_block({'id': pending.pop(0)})
        elif buf.size:
            out = buf.emit(data.draw(st.integers(1, buf.size)))
            emitted.extend(out['id'].tolist())
    assert emitted == list(range(next_id))  # FIFO: exact order preserved


def test_loader_columnar_resume_through_thread_pool(synthetic_dataset):
    """Columnar checkpoint/resume through the THREAD pool (the product
    default), not just dummy: union of pre- and post-checkpoint rows covers
    the dataset exactly once at row-group granularity."""
    reader = make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, output='columnar', schema_fields=['id'],
                         shuffle_row_groups=True, seed=9)
    loader = JaxDataLoader(reader, batch_size=10, shuffling_queue_capacity=30,
                           seed=9, drop_last=False)
    it = iter(loader)
    seen = [i for _ in range(3) for i in next(it)['id'].tolist()]
    state = loader.state_dict()
    reader.stop(); reader.join()

    resumed_reader = make_reader(synthetic_dataset.url, reader_pool_type='thread',
                                 workers_count=2, output='columnar',
                                 schema_fields=['id'], shuffle_row_groups=True,
                                 seed=9, resume_state=state['reader'])
    with JaxDataLoader(resumed_reader, batch_size=10, shuffling_queue_capacity=30,
                       seed=9, drop_last=False, resume_state=state) as resumed:
        rest = [i for b in resumed for i in b['id'].tolist()]
    assert sorted(seen + rest) == sorted(r['id'] for r in synthetic_dataset.data)


# -- RawTensorCodec end-to-end (the zero-copy store format) ------------------

@pytest.fixture(scope='module')
def raw_tensor_dataset(tmp_path_factory):
    from petastorm_tpu.codecs import RawTensorCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    path = tmp_path_factory.mktemp('raw_tensor_store')
    url = 'file://' + str(path)
    schema = Unischema('RawTensor', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('vec', np.float32, (4, 3), RawTensorCodec(), False),
    ])
    rng = np.random.default_rng(7)
    data = [{'id': i, 'vec': rng.standard_normal((4, 3)).astype(np.float32)}
            for i in range(50)]
    write_petastorm_dataset(url, schema, iter(data), rows_per_row_group=10)
    return url, data


def test_raw_tensor_columnar_round_trip(raw_tensor_dataset):
    url, data = raw_tensor_dataset
    seen = {}
    with make_reader(url, reader_pool_type='dummy', output='columnar',
                     shuffle_row_groups=False) as reader:
        for block in reader:
            for i, row_id in enumerate(block.id.tolist()):
                seen[row_id] = np.asarray(block.vec[i])
    assert len(seen) == len(data)
    for row in data:
        np.testing.assert_array_equal(seen[row['id']], row['vec'])


def test_raw_tensor_row_reader_round_trip(raw_tensor_dataset):
    url, data = raw_tensor_dataset
    by_id = {row['id']: row['vec'] for row in data}
    n = 0
    with make_reader(url, reader_pool_type='dummy', shuffle_row_groups=False) as reader:
        for row in reader:
            np.testing.assert_array_equal(row.vec, by_id[row.id])
            assert row.vec.dtype == np.float32
            n += 1
    assert n == len(data)


def test_raw_tensor_loader_shuffled_covers_all_rows(raw_tensor_dataset):
    url, data = raw_tensor_dataset
    ids = []
    with make_reader(url, output='columnar', reader_pool_type='thread',
                     workers_count=2, seed=3) as reader:
        with JaxDataLoader(reader, 8, shuffling_queue_capacity=32, seed=3,
                           drop_last=False) as loader:
            for batch in loader:
                ids.extend(batch['id'].tolist())
                assert batch['vec'].shape[1:] == (4, 3)
    assert sorted(ids) == [row['id'] for row in data]


def test_raw_tensor_transform_can_mutate_in_place(raw_tensor_dataset):
    # zero-copy columnar decode hands out read-only Arrow-buffer views; a user
    # TransformSpec is entitled to mutate rows in place (decode()'s writable
    # contract), so the worker must copy before applying transforms
    url, data = raw_tensor_dataset
    by_id = {row['id']: row['vec'] for row in data}

    def double(row):
        row['vec'] *= 2.0
        return row

    with make_reader(url, reader_pool_type='dummy', shuffle_row_groups=False,
                     transform_spec=TransformSpec(double)) as reader:
        for row in reader:
            np.testing.assert_array_equal(row.vec, by_id[row.id] * 2.0)


def test_columnar_ngram_composes_with_image_resize(synthetic_dataset):
    # decode-time resize runs before NGram window assembly: every timestep's
    # image field arrives uniformly resized inside the vectorized window path
    from petastorm_tpu import TransformSpec
    from petastorm_tpu.ngram import NGram

    fields = {0: ['id', 'image_png'], 1: ['id', 'image_png']}
    ngram = NGram(fields=fields, delta_threshold=10, timestamp_field='id')
    spec = TransformSpec(image_resize={'image_png': (20, 26)})
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', output='columnar',
                     ngram=ngram, transform_spec=spec, shuffle_row_groups=False) as reader:
        saw = 0
        for window_block in reader:
            for offset, fields_block in window_block.items():
                assert fields_block['image_png'].shape[1:] == (20, 26, 3)
            saw += 1
        assert saw > 0
