"""TransformSpec tests (modeled on reference tests/test_transform.py)."""

import numpy as np
import pytest

from petastorm_tpu.codecs import ScalarCodec
from petastorm_tpu.transform import TransformSpec, transform_schema
from petastorm_tpu.unischema import Unischema, UnischemaField


def _schema():
    return Unischema('S', [
        UnischemaField('a', np.int32, (), ScalarCodec(), False),
        UnischemaField('b', np.float32, (10,), None, False),
        UnischemaField('c', np.str_, (), ScalarCodec(), False),
    ])


def test_remove_field():
    spec = TransformSpec(removed_fields=['c'])
    out = transform_schema(_schema(), spec)
    assert set(out.fields) == {'a', 'b'}


def test_edit_field_tuple_form():
    spec = TransformSpec(edit_fields=[('b', np.float64, (5,), False)])
    out = transform_schema(_schema(), spec)
    assert out.fields['b'].numpy_dtype is np.float64
    assert out.fields['b'].shape == (5,)


def test_add_field():
    spec = TransformSpec(edit_fields=[UnischemaField('d', np.int64, (), None, False)])
    out = transform_schema(_schema(), spec)
    assert 'd' in out.fields


def test_selected_fields():
    spec = TransformSpec(selected_fields=['c', 'a'])
    out = transform_schema(_schema(), spec)
    assert set(out.fields) == {'a', 'c'}


def test_selected_missing_raises():
    spec = TransformSpec(removed_fields=['c'], selected_fields=['c'])
    with pytest.raises(ValueError):
        transform_schema(_schema(), spec)


def test_image_resize_scalar_rejected_with_clear_error():
    # A scalar size must raise the descriptive ValueError, not a bare
    # TypeError from len() (ADVICE r3).
    with pytest.raises(ValueError, match='positive \\(out_h, out_w\\)'):
        TransformSpec(image_resize={'image': 224})
    with pytest.raises(ValueError, match='positive \\(out_h, out_w\\)'):
        TransformSpec(image_resize={'image': (224,)})
    with pytest.raises(ValueError, match='positive \\(out_h, out_w\\)'):
        TransformSpec(image_resize={'image': (0, 224)})
    spec = TransformSpec(image_resize={'image': [224, 128]})
    assert spec.image_resize['image'] == (224, 128)
