"""Shared pytest configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding logic is
exercised without TPU hardware (mirrors the reference's strategy of simulating
multi-node sharding in-process, test_end_to_end.py:426-448).
"""

import os

# Must be set before jax (or anything importing jax) initializes its backends.
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
xla_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in xla_flags:
    os.environ['XLA_FLAGS'] = (xla_flags + ' --xla_force_host_platform_device_count=8').strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
