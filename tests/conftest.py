"""Shared pytest configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding logic is
exercised without TPU hardware (mirrors the reference's strategy of simulating
multi-node sharding in-process, test_end_to_end.py:426-448).
"""

import os

# Must run before jax initializes its backends. Force CPU (overriding any
# ambient TPU platform, which this image pins via jax.config in sitecustomize):
# the suite simulates an 8-device mesh so sharding logic is tested without pod
# hardware.
os.environ['JAX_PLATFORMS'] = 'cpu'
xla_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in xla_flags:
    os.environ['XLA_FLAGS'] = (xla_flags + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        'markers', 'slow: spawn-heavy end-to-end matrix tests (process pool)')


@pytest.fixture
def rng():
    return np.random.default_rng(42)


class SyntheticDataset(object):
    def __init__(self, url, data, path):
        self.url = url
        self.data = data  # list of row dicts (in-memory representation)
        self.path = path


@pytest.fixture(scope='session')
def synthetic_dataset(tmp_path_factory):
    """100-row TestSchema dataset with row-group indexes
    (mirrors reference tests/conftest.py:86-120)."""
    from petastorm_tpu.test_util.dataset_utils import create_test_dataset
    path = tmp_path_factory.mktemp('synthetic_dataset')
    url = 'file://' + str(path)
    data = create_test_dataset(url, num_rows=100, rows_per_row_group=10, rows_per_file=30)
    return SyntheticDataset(url=url, data=data, path=str(path))


@pytest.fixture(scope='session')
def scalar_dataset(tmp_path_factory):
    """Plain (non-petastorm) parquet store for the batch-reader path."""
    from petastorm_tpu.test_util.dataset_utils import create_scalar_dataset
    path = tmp_path_factory.mktemp('scalar_dataset')
    url = 'file://' + str(path)
    data, schema = create_scalar_dataset(url, num_rows=100, rows_per_row_group=10)
    ds = SyntheticDataset(url=url, data=data, path=str(path))
    ds.schema = schema
    return ds


@pytest.fixture(scope='session')
def many_columns_dataset(tmp_path_factory):
    """1000-column plain parquet store (mirrors reference conftest.py:248-294)."""
    from petastorm_tpu.test_util.dataset_utils import create_many_columns_dataset
    path = tmp_path_factory.mktemp('many_columns')
    url = 'file://' + str(path)
    names = create_many_columns_dataset(url, num_columns=1000, num_rows=10,
                                        rows_per_row_group=5)
    ds = SyntheticDataset(url=url, data=None, path=str(path))
    ds.column_names = names
    return ds
