"""The deterministic interleaving explorer (petastorm_tpu.analysis.schedule).

Four layers, mirroring the acceptance contract of the dynamic race pass:

* **teeth** — the seeded-defect fixtures MUST fail (a torn read-modify-write,
  the pre-fix ventilator flag protocol, an ABBA deadlock): an explorer that
  cannot catch a planted defect proves nothing when it passes.
* **soundness** — the race-free twin survives 500+ schedules with zero
  reports: no false positives from the vector-clock tracker.
* **replayability** — same seed => byte-for-byte identical schedules;
  ``PSTPU_SCHEDULE=<schedule>`` reproduces a recorded failure exactly.
* **the tier-1 floor** — every real-component scenario (ventilators,
  shuffling buffer, slot registry, autotune actuator) passes >= 300
  schedules per run of this file.
"""

import threading

import pytest

from petastorm_tpu.analysis.schedule import scenarios
from petastorm_tpu.analysis.schedule.cli import (EXIT_CLEAN, EXIT_FINDINGS,
                                                 EXIT_INCONCLUSIVE,
                                                 EXIT_USAGE, main)
from petastorm_tpu.analysis.schedule.explorer import explore, replay, run_one
from petastorm_tpu.analysis.schedule.scenarios import (DEFECT_SCENARIOS,
                                                       SCENARIOS, lookup)
from petastorm_tpu.analysis.schedule.scheduler import (SCHEDULE_ENV,
                                                       RandomStrategy,
                                                       SchedulerError,
                                                       parse_schedule)

#: the tier-1 floor from the issue contract: every real-component scenario
#: must survive at least this many explored schedules
SCHEDULE_FLOOR = 300


# ---------------------------------------------------------------------------
# teeth: seeded defects must be caught within the default budget
# ---------------------------------------------------------------------------

def test_torn_counter_caught():
    report = explore(scenarios.torn_counter, name='torn_counter',
                     schedules=SCHEDULE_FLOOR)
    assert not report.ok
    failure = report.failure
    assert failure.races, failure.describe()
    assert any(r.attr == 'value' for r in failure.races)
    assert failure.schedule  # every failure is replayable
    assert 'PSTPU_SCHEDULE' in report.describe()


def test_prefix_ventilator_flag_protocol_caught():
    """Regression teeth: the explorer catches the EXACT defect class the
    static+dynamic pass removed from ConcurrentVentilator/FairShareVentilator
    (bare ``_stop_requested``/``_completed`` flag reads/writes beside a
    Condition-guarded protocol)."""
    report = explore(scenarios.prefix_ventilator_flags,
                     name='prefix_ventilator_flags',
                     schedules=SCHEDULE_FLOOR)
    assert not report.ok
    raced = {r.attr for r in report.failure.races}
    assert raced & {'_stop_requested', '_completed'}, \
        report.failure.describe()


def test_abba_deadlock_detected():
    report = explore(scenarios.abba_deadlock, name='abba_deadlock',
                     schedules=SCHEDULE_FLOOR)
    assert not report.ok
    failure = report.failure
    assert failure.deadlock is not None, failure.describe()
    assert 'deadlock' in failure.describe()
    assert failure.schedule


# ---------------------------------------------------------------------------
# soundness: the race-free twin survives 500+ schedules
# ---------------------------------------------------------------------------

def test_safe_counter_soundness_500_schedules():
    report = explore(scenarios.safe_counter, name='safe_counter',
                     schedules=500, dfs_budget=100)
    assert report.ok, report.failure.describe()
    assert report.schedules_run >= 500
    assert report.dfs_runs > 0  # the DFS phase actually ran


# ---------------------------------------------------------------------------
# determinism + replay
# ---------------------------------------------------------------------------

def test_same_seed_same_schedules():
    first = explore(scenarios.torn_counter, name='torn_counter',
                    schedules=50, seed=7)
    second = explore(scenarios.torn_counter, name='torn_counter',
                     schedules=50, seed=7)
    assert first.failure.schedule == second.failure.schedule
    assert [r.describe() for r in first.failure.races] \
        == [r.describe() for r in second.failure.races]


def test_env_replay_is_byte_for_byte():
    report = explore(scenarios.torn_counter, name='torn_counter',
                     schedules=50)
    recorded = report.failure.schedule
    replayed = explore(scenarios.torn_counter, name='torn_counter',
                       schedules=50, environ={SCHEDULE_ENV: recorded})
    assert replayed.replayed
    assert replayed.schedules_run == 1  # one exact replay, no exploration
    assert replayed.failure.schedule == recorded
    assert [r.key() for r in replayed.failure.races] \
        == [r.key() for r in report.failure.races]


def test_replay_helper_reproduces_failure():
    report = explore(scenarios.torn_counter, name='torn_counter',
                     schedules=50)
    result = replay(scenarios.torn_counter, report.failure.schedule)
    assert result.schedule == report.failure.schedule
    assert [r.key() for r in result.races] \
        == [r.key() for r in report.failure.races]


def test_replay_divergence_is_inconclusive_not_a_pass():
    # thread 9 never exists: the recorded choice is not runnable at step 0
    result = replay(scenarios.safe_counter, '9')
    assert result.divergence
    assert result.inconclusive
    assert not result.ok


def test_step_budget_exhaustion_is_inconclusive():
    sched, result = run_one(scenarios.concurrent_ventilator,
                            RandomStrategy(0), max_steps=3)
    assert result.steps_exhausted
    assert result.inconclusive and not result.ok


def test_parse_schedule():
    assert parse_schedule('0,1,2,0') == [0, 1, 2, 0]
    assert parse_schedule(' 3 , 4 ') == [3, 4]
    with pytest.raises(SchedulerError):
        parse_schedule('0,x,1')


def test_threading_restored_after_runs():
    """The monkeypatches must be scoped to the run — including failing and
    aborted runs — or everything after the first explore() breaks."""
    explore(scenarios.torn_counter, name='torn_counter', schedules=10)
    explore(scenarios.abba_deadlock, name='abba_deadlock', schedules=10)
    lock_cls = type(threading.Lock())
    assert lock_cls.__module__ in ('_thread', 'threading')
    ev = threading.Event()
    ev.set()
    assert ev.wait(timeout=1)


# ---------------------------------------------------------------------------
# the tier-1 floor: real components, >= 300 schedules each
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('name', sorted(SCENARIOS))
def test_real_component_survives_schedule_floor(name):
    report = explore(SCENARIOS[name], name=name, schedules=SCHEDULE_FLOOR,
                     dfs_budget=100)
    assert report.ok, report.failure.describe()
    assert report.schedules_run >= SCHEDULE_FLOOR


def test_scenario_registry_lookup():
    assert set(SCENARIOS) & set(DEFECT_SCENARIOS) == set()
    for name in list(SCENARIOS) + list(DEFECT_SCENARIOS):
        assert callable(lookup(name))
    with pytest.raises(KeyError):
        lookup('no_such_scenario')


# ---------------------------------------------------------------------------
# petastorm-tpu-race: the documented exit-code contract
# ---------------------------------------------------------------------------

def test_cli_clean_scenario_exits_0(capsys):
    assert main(['explore', 'safe_counter', '--schedules', '20']) \
        == EXIT_CLEAN
    assert 'safe_counter' in capsys.readouterr().out


def test_cli_finding_exits_1(capsys):
    assert main(['explore', 'torn_counter', '--schedules', '50']) \
        == EXIT_FINDINGS
    out = capsys.readouterr().out
    assert 'race' in out
    assert 'PSTPU_SCHEDULE' in out  # the replay handle is printed


def test_cli_unknown_scenario_exits_2(capsys):
    assert main(['explore', 'no_such_scenario']) == EXIT_USAGE


def test_cli_no_subcommand_exits_2(capsys):
    assert main([]) == EXIT_USAGE


def test_cli_env_replay_needs_exactly_one_scenario(monkeypatch, capsys):
    monkeypatch.setenv(SCHEDULE_ENV, '0,1,0')
    assert main(['explore', 'torn_counter', 'safe_counter']) == EXIT_USAGE


def test_cli_env_replay_reproduces_failure(monkeypatch, capsys):
    report = explore(scenarios.torn_counter, name='torn_counter',
                     schedules=50)
    monkeypatch.setenv(SCHEDULE_ENV, report.failure.schedule)
    assert main(['explore', 'torn_counter']) == EXIT_FINDINGS
    assert report.failure.schedule in capsys.readouterr().out


def test_cli_inconclusive_exits_3(capsys):
    assert main(['explore', 'concurrent_ventilator', '--max-steps', '3',
                 '--schedules', '5', '--dfs-budget', '0']) \
        == EXIT_INCONCLUSIVE


def test_cli_list_catalogs_everything(capsys):
    assert main(['list']) == EXIT_CLEAN
    out = capsys.readouterr().out
    for name in list(SCENARIOS) + list(DEFECT_SCENARIOS):
        assert name in out


def test_cli_lint_mode_selects_pt13_family(capsys, tmp_path):
    clean = tmp_path / 'clean.py'
    # a PT600 violation: out of the PT13 family, so `lint` must NOT report it
    clean.write_text('class C(object):\n'
                     '    def __eq__(self, other):\n'
                     '        return True\n')
    assert main(['lint', str(clean)]) == EXIT_CLEAN
