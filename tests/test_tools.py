"""CLI tools tests (modeled on reference tests/test_copy_dataset.py,
tests/test_generate_metadata.py, benchmark smoke)."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, __file__.rsplit('/tests/', 1)[0])  # repo-root bench modules

from petastorm_tpu import make_reader
from petastorm_tpu.etl.dataset_metadata import get_schema, load_row_groups
from petastorm_tpu.fs import path_to_url
from petastorm_tpu.tools.copy_dataset import copy_dataset, main as copy_main
from petastorm_tpu.tools.generate_metadata import generate_metadata
from petastorm_tpu.tools.metadata_util import main as metadata_main
from petastorm_tpu.tools.throughput import main as throughput_main, reader_throughput


class TestCopyDataset:
    def test_full_copy(self, synthetic_dataset, tmp_path):
        target = path_to_url(tmp_path / 'copy')
        count = copy_dataset(synthetic_dataset.url, target, rows_per_row_group=25)
        assert count == 100
        with make_reader(target, reader_pool_type='dummy', shuffle_row_groups=False) as r:
            ids = sorted(row.id for row in r)
        assert ids == list(range(100))
        assert len(load_row_groups(target)) == 4

    def test_column_subset(self, synthetic_dataset, tmp_path):
        target = path_to_url(tmp_path / 'copy')
        copy_dataset(synthetic_dataset.url, target, field_regex=['id', 'matrix'],
                     rows_per_row_group=50)
        schema = get_schema(target)
        assert set(schema.fields) == {'id', 'matrix'}

    def test_not_null_filter(self, synthetic_dataset, tmp_path):
        target = path_to_url(tmp_path / 'copy')
        count = copy_dataset(synthetic_dataset.url, target,
                             field_regex=['id', 'matrix_nullable'],
                             not_null_fields=['matrix_nullable'],
                             rows_per_row_group=50)
        # matrix_nullable is null when id % 5 == 0
        assert count == 80

    def test_cli(self, synthetic_dataset, tmp_path, capsys):
        target = path_to_url(tmp_path / 'copy')
        assert copy_main([synthetic_dataset.url, target, '--field-regex', 'id',
                          '--rows-per-row-group', '100']) == 0
        assert 'Copied 100 rows' in capsys.readouterr().out


class TestGenerateMetadata:
    def test_regenerate_after_loss(self, tmp_path):
        from petastorm_tpu.test_util.dataset_utils import create_test_dataset
        url = path_to_url(tmp_path / 'ds')
        create_test_dataset(url, num_rows=30, rows_per_row_group=10, build_indexes=False)
        (tmp_path / 'ds' / '_common_metadata').unlink()
        schema, n_rg = generate_metadata(
            url, unischema_class='petastorm_tpu.test_util.dataset_utils.TestSchema')
        assert n_rg == 3
        # reader works again, with codecs intact
        with make_reader(url, reader_pool_type='dummy', schema_fields=['id', 'image_png'],
                         shuffle_row_groups=False) as r:
            row = next(r)
        assert row.image_png.shape == (128, 256, 3)

    def test_infer_for_plain_store(self, scalar_dataset, tmp_path):
        # COPY the plain store first: generate_metadata writes into the store,
        # and mutating the session-scoped fixture makes make_reader-on-plain-
        # parquet tests pass/fail depending on execution order
        import shutil
        store = tmp_path / 'plain_copy'
        shutil.copytree(scalar_dataset.path, store)
        url = path_to_url(store)
        schema, n_rg = generate_metadata(url)
        assert 'id' in schema.fields
        assert n_rg == 10
        assert get_schema(url) is not None

    def test_bad_class_path(self, scalar_dataset):
        with pytest.raises(ValueError):
            generate_metadata(scalar_dataset.url, unischema_class='NotDotted')


class TestThroughput:
    def test_python_read(self, synthetic_dataset):
        result = reader_throughput(synthetic_dataset.url, field_regex=['id'],
                                   warmup_cycles=10, measure_cycles=50,
                                   pool_type='dummy', workers_count=1)
        assert result.samples_per_second > 0
        assert result.samples == 50

    def test_jax_read_with_stall(self, synthetic_dataset):
        result = reader_throughput(synthetic_dataset.url, field_regex=['id', 'matrix'],
                                   warmup_cycles=16, measure_cycles=64,
                                   pool_type='thread', workers_count=2,
                                   read_method='jax', batch_size=16)
        assert result.samples_per_second > 0
        assert 0.0 <= result.input_stall_fraction <= 1.0

    def test_columnar_read_method(self, synthetic_dataset):
        result = reader_throughput(synthetic_dataset.url, field_regex=['id', 'matrix'],
                                   warmup_cycles=16, measure_cycles=64,
                                   pool_type='dummy', workers_count=1,
                                   read_method='columnar', batch_size=16)
        assert result.samples_per_second > 0
        assert result.samples == 64
        assert result.input_stall_fraction is None  # host-only: no staging to stall on

    def test_cli(self, synthetic_dataset, capsys):
        assert throughput_main([synthetic_dataset.url, '-f', 'id', '-m', '5', '-n', '20',
                                '-p', 'dummy', '-w', '1']) == 0
        assert 'samples/sec' in capsys.readouterr().out

    def test_cli_trace_out_and_stall_breakdown(self, synthetic_dataset, tmp_path, capsys):
        """--trace-out writes a Perfetto-loadable Chrome trace and the stall
        attribution prints next to the input-stall fraction (the acceptance
        configuration: --read-method jax --trace-out)."""
        trace = tmp_path / 'trace.json'
        assert throughput_main([synthetic_dataset.url, '-f', 'id', 'matrix',
                                '-m', '8', '-n', '32', '-w', '2',
                                '-d', 'jax', '--batch-size', '8',
                                '--trace-out', str(trace)]) == 0
        out = capsys.readouterr().out
        assert 'input stall' in out
        assert 'stall report' in out and 'bottleneck' in out
        assert 'attributed' in out
        doc = json.loads(trace.read_text())
        events = doc['traceEvents']
        assert events, 'trace must contain span events'
        for event in events:
            assert {'ph', 'ts', 'dur', 'pid', 'tid', 'name'} <= set(event)
        from petastorm_tpu import observability as obs
        obs.configure('counters')  # restore the process default for later tests


class TestMetadataUtil:
    def test_print_schema_and_pieces(self, synthetic_dataset, capsys):
        assert metadata_main([synthetic_dataset.url, '--schema', '--pieces']) == 0
        out = capsys.readouterr().out
        assert 'image_png' in out
        assert 'rg=' in out

    def test_print_index(self, synthetic_dataset, capsys):
        assert metadata_main([synthetic_dataset.url, '--index',
                              '--skip-index-values']) == 0
        out = capsys.readouterr().out
        assert 'id_index' in out


def test_duty_cycle_measurement(synthetic_dataset):
    import jax
    import jax.numpy as jnp
    from petastorm_tpu.tools.throughput import pipeline_duty_cycle
    from petastorm_tpu import TransformSpec

    def to_sample(row):
        return {'x': row['matrix'], 'label': np.int64(row['id'] % 4)}

    spec = TransformSpec(to_sample, edit_fields=[('x', np.float32, (32, 16, 3), False),
                                                 ('label', np.int64, (), False)],
                         selected_fields=['x', 'label'])
    step = jax.jit(lambda x, y: (jnp.mean(x), jnp.sum(y)))
    result = pipeline_duty_cycle(
        synthetic_dataset.url, step, lambda b: (b['x'], b['label']),
        batch_size=16, steps=10, warmup_steps=2,
        reader_kwargs={'schema_fields': ['id', 'matrix'], 'transform_spec': spec,
                       'reader_pool_type': 'thread', 'workers_count': 2})
    assert result.samples == 160
    assert 0.0 <= result.input_stall_fraction <= 1.0


class _StubRDD(object):
    """Executes the pyspark RDD chain locally (reference-style mock testing:
    the reference exercised HDFS failover with MockHdfs the same way)."""

    def __init__(self, items, num_slices):
        self.items = list(items)
        self.num_slices = num_slices

    def flatMap(self, fn):
        out = []
        for item in self.items:
            out.extend(fn(item))
        return _StubRDD(out, self.num_slices)

    def collect(self):
        return list(self.items)


class _StubSparkContext(object):
    def __init__(self, parallelism):
        self.defaultParallelism = parallelism
        self.parallelize_calls = []

    def parallelize(self, seq, num_slices):
        self.parallelize_calls.append((list(seq), num_slices))
        return _StubRDD(seq, num_slices)


class _StubSparkSession(object):
    def __init__(self, parallelism):
        self.sparkContext = _StubSparkContext(parallelism)


def test_dataset_as_rdd_shard_math_with_stub_spark(synthetic_dataset):
    """dataset_as_rdd partitions the dataset by cur_shard/shard_count and the
    union of all partitions covers every row exactly once
    (reference spark_utils.py:23-52 semantics, no pyspark needed)."""
    from petastorm_tpu.spark_utils import dataset_as_rdd

    session = _StubSparkSession(parallelism=4)
    rdd = dataset_as_rdd(synthetic_dataset.url, session, schema_fields=['id'])
    rows = rdd.collect()
    # one parallelize over exactly shard indices 0..3, 4 slices
    assert session.sparkContext.parallelize_calls == [([0, 1, 2, 3], 4)]
    assert sorted(int(r.id) for r in rows) == sorted(r['id'] for r in synthetic_dataset.data)


def test_dataset_as_rdd_rejects_non_spark_session(synthetic_dataset):
    from petastorm_tpu.spark_utils import dataset_as_rdd
    with pytest.raises(TypeError, match='SparkSession'):
        dataset_as_rdd(synthetic_dataset.url, object())


def _scaling_records(capsys):
    return [json.loads(ln) for ln in capsys.readouterr().out.strip().splitlines()
            if ln.startswith('{')]


def test_bench_scaling_smoke(tmp_path, capsys):
    """2-point smoke of the measurement path (1 worker, tiny raw store): the
    scaling curve script must run end to end and report a positive rate —
    this was 0-coverage code (VERDICT r5 Next #8)."""
    import bench_scaling
    bench_scaling.main(['--workers', '1', '--pools', 'thread', '--store', 'raw',
                        '--rows', '64', '--measure-rows', '64',
                        '--warmup-rows', '32', '--reps', '1',
                        '--keep-dir', str(tmp_path)])
    recs = _scaling_records(capsys)
    assert len(recs) == 1
    rec = recs[0]
    assert rec['metric'] == 'scaling' and rec['store'] == 'raw'
    assert rec['workers'] == 1 and rec['pool'] == 'thread'
    assert rec['remote_mock'] is False
    assert rec['samples_per_sec'] > 0


def test_bench_scaling_chaos_smoke(tmp_path, capsys):
    """--chaos --protocol-monitor runs the sweep under seeded fault injection
    (docs/robustness.md) with the protocol conformance monitor attached
    (docs/protocol.md): the run must complete end to end — i.e. the recovery
    also CONFORMED to the supervision protocol spec — report a positive rate,
    carry the recovery counters, and have actually recovered from at least one
    injected fault; the hooks must be disarmed afterwards."""
    import bench_scaling
    from petastorm_tpu import faults, retry
    bench_scaling.main(['--workers', '1', '--pools', 'thread', '--store', 'raw',
                        '--rows', '64', '--measure-rows', '64',
                        '--warmup-rows', '32', '--reps', '1', '--chaos',
                        '--protocol-monitor',
                        '--keep-dir', str(tmp_path)])
    recs = _scaling_records(capsys)
    assert len(recs) == 1
    rec = recs[0]
    assert rec['samples_per_sec'] > 0
    assert rec['chaos']['items_requeued'] >= 1
    assert rec['chaos']['items_quarantined'] == 0  # transient, not poison
    assert faults.get_plan() is None and retry.FAULT_POINT is None


def test_bench_scaling_remote_mock_exercises_chunk_store(tmp_path, capsys):
    """--store raw --remote-mock measures the chunk-cached remote path: the
    run must complete with a positive warm-cache rate AND have actually
    populated the chunk store (mirrored chunk files on disk)."""
    import bench_scaling
    bench_scaling.main(['--workers', '1', '--pools', 'thread', '--store', 'raw',
                        '--rows', '64', '--measure-rows', '64',
                        '--warmup-rows', '32', '--reps', '1', '--remote-mock',
                        '--keep-dir', str(tmp_path)])
    recs = _scaling_records(capsys)
    assert len(recs) == 1 and recs[0]['remote_mock'] is True
    assert recs[0]['samples_per_sec'] > 0
    cache_dir = tmp_path / 'chunk_cache'
    chunks = [f for _root, _dirs, files in os.walk(cache_dir) for f in files
              if f.endswith('.chunk')]
    assert chunks, 'the remote-mock run must mirror chunks into the store'


def test_throughput_fresh_process_respawn(synthetic_dataset):
    """--fresh-process re-executes the measurement in a spawned interpreter so
    RSS excludes the caller (reference benchmark/throughput.py:146-151)."""
    from petastorm_tpu.tools import throughput
    rc = throughput.main([synthetic_dataset.url, '-m', '2', '-n', '10', '-w', '1',
                          '--fresh-process'])
    assert rc == 0


def test_reader_throughput_jax_method_columnar(synthetic_dataset):
    """read_method='jax' measures the device-feed pipeline (columnar default)
    and reports a stall fraction, plus a stall report attributing >=90% of
    the measured reader wait to named stages (the acceptance bar)."""
    from petastorm_tpu.tools.throughput import reader_throughput
    res = reader_throughput(synthetic_dataset.url, field_regex=['id', 'matrix'],
                            warmup_cycles=10, measure_cycles=40, workers_count=2,
                            read_method='jax', batch_size=10)
    assert res.samples_per_second > 0
    assert 0.0 <= res.input_stall_fraction <= 1.0
    report = res.extra['stall_report']
    assert report['coverage'] >= 0.9
    assert set(report['stages']) <= {'worker.read_io', 'worker.chunk_fetch',
                                     'worker.fused_decode', 'worker.decode',
                                     'worker.transform', 'consumer.assembly',
                                     'pool.unattributed'}


def test_bench_serve_smoke(tmp_path, capsys):
    """End-to-end smoke of the serve benchmark (docs/serve.md): one fleet
    size, a tiny store, real consumer subprocesses and a real spawned daemon.
    The headline line must carry both aggregates and the ratios."""
    import json as _json

    import numpy as np

    import bench_serve
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('S', [
        UnischemaField('x', np.int64, (), ScalarCodec(np.int64), False)])
    url = 'file://' + str(tmp_path / 'store')
    write_petastorm_dataset(url, schema, ({'x': i} for i in range(200)),
                            rows_per_row_group=20)
    bench_serve.main(['--url', url, '--consumers', '2',
                      '--rows', '150', '--warmup-rows', '40', '--rounds', '1'])
    lines = [l for l in capsys.readouterr().out.splitlines() if l.startswith('{')]
    recs = [_json.loads(l) for l in lines]
    headline = [r for r in recs if r.get('metric') == 'serve_bench']
    assert len(headline) == 1
    h = headline[0]
    assert h['single_plain_rate'] > 0
    assert h['sweep']['2']['served_aggregate'] > 0
    assert h['sweep']['2']['independent_aggregate'] > 0
    assert h['single_served_rate'] > 0
    assert h['pool_copy_rate'] > 0
    assert h['pool_zero_copy_rate'] > 0
    assert h['zero_copy_ratio'] is not None
    assert isinstance(h['meets_bar'], bool)


# ---------------------------------------------------------------------------
# elastic: modelcheck --elastic exit-code contract + bench_pod --chaos smoke
# ---------------------------------------------------------------------------

def test_modelcheck_elastic_cli_exit_code_contract():
    """The --elastic lane honors the same exit-code contract as the worker
    and serve lanes: 0 exhausted-clean, 1 counterexample, 2 usage error,
    3 below the declared canonical-state floor."""
    import subprocess
    base = [sys.executable, '-m', 'petastorm_tpu.analysis.protocol.modelcheck']
    clean = subprocess.run(base + ['--elastic', '--budget-s', '300'],
                           capture_output=True, text=True, timeout=420)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert 'exhausted: all invariants hold' in clean.stdout

    bad = subprocess.run(base + ['--elastic', '--mutate', 'skip_done_check',
                                 '--budget-s', '300'],
                         capture_output=True, text=True, timeout=420)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert 'counterexample' in bad.stdout

    floor = subprocess.run(base + ['--elastic', '--min-states', '99999999',
                                   '--budget-s', '300'],
                           capture_output=True, text=True, timeout=420)
    assert floor.returncode == 3
    assert 'below the declared floor' in floor.stderr

    usage = subprocess.run(base + ['--serve', '--elastic'],
                           capture_output=True, text=True, timeout=120)
    assert usage.returncode == 2
    assert 'mutually exclusive' in usage.stderr


def test_bench_pod_chaos_smoke():
    """bench_pod --chaos end to end: a small pod of real host subprocesses
    with a SIGKILL + join must finish with full exactly-once coverage and
    exit 0 — the pod_chaos metric line is the machine-readable verdict."""
    import subprocess
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), 'bench_pod.py'),
         '--chaos', '--rows', '512', '--chaos-kill-after', '2'],
        capture_output=True, text=True, timeout=420,
        env=dict(os.environ, JAX_PLATFORMS='cpu'))
    assert out.returncode == 0, out.stdout + out.stderr
    recs = [json.loads(line) for line in out.stdout.splitlines()
            if line.startswith('{')]
    chaos = [r for r in recs if r.get('metric') == 'pod_chaos']
    assert len(chaos) == 1
    rec = chaos[0]
    assert rec['double_committed'] == 0
    assert rec['committed'] == 512 // 64
    assert rec['killed'] and rec['joined']
    assert rec['survivor_exit_codes_ok'] is True


def test_bench_pod_fabric_smoke():
    """bench_pod --fabric end to end: a 3-host simulated pod must source
    chunks peer-to-peer — exactly one object-store read per chunk plus
    (N-1) LAN copies — and exit 0; the pod_fabric line is the verdict."""
    from petastorm_tpu import native
    if not native.is_available():
        pytest.skip('chunk mirrors need the native page scanner')
    import subprocess
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), 'bench_pod.py'),
         '--fabric', '--hosts', '3', '--rows', '512'],
        capture_output=True, text=True, timeout=420,
        env=dict(os.environ, JAX_PLATFORMS='cpu'))
    assert out.returncode == 0, out.stdout + out.stderr
    recs = [json.loads(line) for line in out.stdout.splitlines()
            if line.startswith('{')]
    verdict = [r for r in recs if r.get('metric') == 'pod_fabric']
    assert len(verdict) == 1
    rec = verdict[0]
    assert rec['ok'] is True
    assert rec['accounted'] is True
    chunks = rec['object_store_reads']
    assert chunks > 0
    # the whole point of the fabric: each chunk leaves the object store once
    # and every other host copies it over the LAN
    assert rec['peer_copies'] == 2 * chunks
    assert rec['chunk_misses'] == 3 * chunks
    assert rec['bytes_from_peers'] > 0
