class Res(object):
    def close(self):
        pass


def leak():
    r = Res()
    r.poke()
