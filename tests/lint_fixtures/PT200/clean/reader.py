class Res(object):
    def close(self):
        pass


def ok_with():
    with Res() as r:
        return r.read()
