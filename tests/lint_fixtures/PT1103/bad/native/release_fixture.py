"""A manually-released borrow whose releaser is reachable on only some
paths: the empty-read branch (and any exception) leaks the mapping."""

import mmap


def copy_header(fd, n):
    mm = mmap.mmap(fd, n)
    head = mm.read(64)
    if head:
        mm.close()
    return head
