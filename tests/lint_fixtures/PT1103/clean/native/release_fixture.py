"""Clean twin: the release sits in a ``finally``, dominating every exit."""

import mmap


def copy_header(fd, n):
    mm = mmap.mmap(fd, n)
    try:
        return mm.read(64)
    finally:
        mm.close()
