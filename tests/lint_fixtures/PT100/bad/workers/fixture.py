import threading


class Pool(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def inc(self):
        with self._lock:
            self._count += 1

    def unsafe_reset(self):
        self._count = 0
