from petastorm_tpu.workers.protocol import MSG_DATA, MSG_DONE


def consume(kind, payload):
    if kind == MSG_DATA:
        return payload
    elif kind == MSG_DONE:
        return None
    else:
        raise RuntimeError(kind)
