"""PT1303 bad fixture: blocking calls made while holding a lock — a
blocking queue get under the lock, and an unbounded Condition.wait."""

import queue
import threading


class Feeder(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self._tasks = queue.Queue()
        self._done = False

    def pump(self):
        with self._lock:
            item = self._tasks.get()
        return item

    def wait_done(self):
        with self._cv:
            while not self._done:
                self._cv.wait()
