"""PT1303 clean twin: the queue get is nonblocking under the lock, and the
wait is bounded (the shutdown-safe re-check-loop convention)."""

import queue
import threading


class Feeder(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self._tasks = queue.Queue()
        self._done = False

    def pump(self):
        with self._lock:
            try:
                item = self._tasks.get_nowait()
            except queue.Empty:
                item = None
        return item

    def wait_done(self):
        with self._cv:
            while not self._done:
                self._cv.wait(timeout=0.5)
