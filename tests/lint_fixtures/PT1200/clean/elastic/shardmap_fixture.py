"""Clean twin: everything derives from (seed, epoch, members)."""

import hashlib

import numpy as np


def stable_hash(*parts):
    h = hashlib.blake2b(digest_size=8)
    for part in parts:
        h.update(repr(part).encode('utf-8'))
        h.update(b'\x00')
    return int.from_bytes(h.digest(), 'big')


def owner_of(item_index, members, seed, epoch):
    ordered = sorted(members)
    scores = [(stable_hash(seed, epoch, m, item_index), m) for m in ordered]
    return max(scores)[1]


def global_order(num_items, seed, epoch):
    rng = np.random.default_rng(stable_hash(seed, epoch))
    return rng.permutation(num_items)


def assign(members, items):
    assignment = {}
    for member in sorted(set(members)):
        assignment[member] = []
    return assignment


def ranks(members):
    return sorted(set(members))
