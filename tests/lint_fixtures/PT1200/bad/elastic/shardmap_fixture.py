"""Bad fixture: shard-map construction that diverges across hosts."""

import random
import time

import numpy as np


def owner_of(item_index, members, seed, epoch):
    # PT1200: wall clock — no two hosts read the same value
    salt = time.time()
    return sorted(members)[int(salt + item_index) % len(members)]


def global_order(num_items, seed, epoch):
    # PT1200: module-global RNG stream is per-process, not per-pod
    order = list(range(num_items))
    random.shuffle(order)
    return order


def tie_break(num_items):
    # PT1200: unseeded constructor draws from OS entropy
    rng = np.random.default_rng()
    return rng.permutation(num_items)


def assign(members, items):
    assignment = {}
    # PT1200: set iteration order varies under hash randomization
    for member in set(members):
        assignment[member] = []
    return assignment


def ranks(members):
    # PT1200: list(set(...)) bakes hash order into the result
    return list(set(members))
