import threading


class AB(object):
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._x = 0

    def one(self):
        with self._a:
            with self._b:
                self._x = 1

    def two(self):
        with self._a:
            with self._b:
                self._x = 2
