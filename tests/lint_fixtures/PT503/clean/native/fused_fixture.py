def fill(desc, buf):
    desc.out = buf.ctypes.data
    desc.out_cap = buf.nbytes
    desc.chunk = buf.ctypes.data
    desc.chunk_len = buf.nbytes
