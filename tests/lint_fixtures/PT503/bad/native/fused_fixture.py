def fill(desc, buf):
    desc.out = buf.ctypes.data
    desc.chunk = buf.ctypes.data
    desc.chunk_len = buf.nbytes
