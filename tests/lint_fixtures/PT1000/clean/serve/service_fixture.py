def evict_slowest(self, stream, tenant):
    with obs.span('serve.evict', cat='serve', tenant=tenant.tenant_id):
        stream.ring.evict(tenant.token)


def admit(self, stream, tenant_id):
    with obs.span('serve.admit', cat='serve', tenant=tenant_id):
        return stream.ring.join()
