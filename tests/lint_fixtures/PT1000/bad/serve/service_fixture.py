def evict_slowest(self, stream, tenant):
    stream.ring.evict(tenant.token)


def admit(self, stream):
    return stream.ring.join()
