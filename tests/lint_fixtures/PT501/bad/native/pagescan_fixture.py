import pyarrow as pa


def chunk_to_view(mm, off, nbytes):
    if off + nbytes > mm.size:
        return None
    return pa.py_buffer(memoryview(mm)[off:off + nbytes])
