import pyarrow as pa


def chunk_to_view(mm, off, nbytes, region_len):
    if nbytes > region_len:
        return None
    if off + nbytes > mm.size:
        return None
    return pa.py_buffer(memoryview(mm)[off:off + nbytes])
