"""Clean twin: the borrow is copied into owned bytes before it is queued —
the wire payload no longer aliases the producer's slot."""


def forward_batch(ring, out_queue):
    view = ring.try_read_zero_copy()
    out_queue.put(bytes(view))
