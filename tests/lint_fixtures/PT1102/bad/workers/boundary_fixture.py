"""A borrow crossing a process boundary uncopied: the queued bytes alias the
producer-owned ring slot, which is reclaimed on the producer's schedule — the
receiver sees torn data (or a guard fault) with no local cause."""


def forward_batch(ring, out_queue):
    view = ring.try_read_zero_copy()
    out_queue.put(view)
