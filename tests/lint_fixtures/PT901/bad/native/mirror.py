import ctypes


class DemoColStruct(ctypes.Structure):
    """Field-for-field mirror of ``struct DemoCol`` (kernel.cpp)."""

    _fields_ = [
        ('chunk', ctypes.c_void_p),
        ('chunk_len', ctypes.c_uint64),
        ('out', ctypes.c_void_p),
        ('out_cap', ctypes.c_uint64),
        ('mode', ctypes.c_int32),
        ('status', ctypes.c_int32),
    ]


def register(lib):
    lib.demo_read.restype = ctypes.c_longlong
    lib.demo_read.argtypes = [ctypes.POINTER(DemoColStruct), ctypes.c_int]
    lib.demo_write.restype = ctypes.c_int
    lib.demo_write.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_uint32]
