"""A borrow-returning function with no ``:borrows:`` docstring section: the
caller inherits the mapping's lifetime obligation without any visible
contract at the definition."""

import numpy as np


def map_shard(path):
    """The whole shard as one flat byte view."""
    return np.memmap(path, dtype=np.uint8, mode='r')
