"""Clean twin: the hand-off is declared with a ``:borrows:`` section, so the
obligation is visible at every call site's definition (docs/analysis.md)."""

import numpy as np


def map_shard(path):
    """The whole shard as one flat byte view.

    :borrows: the returned memmap aliases the file; keep it (or any array
        built over it) no longer than the shard stays on disk.
    """
    return np.memmap(path, dtype=np.uint8, mode='r')
