class RetryingHandler(object):
    def __eq__(self, other):
        return self.fs == other.fs
