"""Clean twin: the parked borrow is handed to the lifetime registry, whose
slot refcount now blocks reclamation until the stored view dies."""

from petastorm_tpu.native.lifetime import registry as lifetime_registry


class StashingConsumer(object):
    def __init__(self):
        self._last_view = None

    def poll(self, ring):
        view = ring.try_read_zero_copy()
        slot = lifetime_registry().open_slot(label='stash')
        slot.adopt(view)
        slot.seal()
        self._last_view = view  # registered: reclaim waits on the refcount
        return bytes(view)
