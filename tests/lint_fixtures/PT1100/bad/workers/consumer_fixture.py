"""Seeded use-after-release, the static view: the consumer parks a borrowed
ring view on ``self``, so it outlives the frame while the slot registry knows
nothing about it — the next release reclaims the slot and the parked view
reads recycled bytes. The runtime twin of this exact defect is provoked under
the PROT_NONE guard in tests/test_sanitized_native.py."""


class StashingConsumer(object):
    def __init__(self):
        self._last_view = None

    def poll(self, ring):
        view = ring.try_read_zero_copy()
        self._last_view = view  # kept past the slot's release
        return bytes(view)
