def pump(q):
    try:
        q.get()
    except Exception:
        pass
