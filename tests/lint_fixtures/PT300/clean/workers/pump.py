import logging

logger = logging.getLogger(__name__)


def pump(q):
    try:
        q.get()
    except Exception:
        logger.exception('boom')
