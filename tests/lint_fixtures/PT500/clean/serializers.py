import numpy as np


def copies(buf):
    return np.frombuffer(buf, np.uint8).copy()
