import numpy as np


def returns_view(buf):
    return np.frombuffer(buf, np.uint8)
