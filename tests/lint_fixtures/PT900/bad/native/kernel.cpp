#include <cstdint>

struct DemoCol {
  const uint8_t* chunk;
  uint64_t chunk_len;
  uint8_t* out;
  uint64_t out_cap;
  int32_t mode;
  int32_t status;
};

extern "C" {

long long demo_read(struct DemoCol* cols, int n_cols) {
  (void)cols;
  (void)n_cols;
  return 0;
}

int demo_write(void* h, const void* data, uint64_t len) {
  (void)h;
  (void)data;
  (void)len;
  return 0;
}

}  // extern "C"
