def grow(self):
    self._pool.add_worker_slot()
