def grow(self):
    with decision_span(knob='workers'):
        self._pool.add_worker_slot()
