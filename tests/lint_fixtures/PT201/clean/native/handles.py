class Fine(object):
    def close(self):
        pass

    def __del__(self):
        self.close()
