class Leaky(object):
    def __del__(self):
        self._free()
