"""PT1301 clean twin: every read of the guarded container holds the lock —
including one inside a private helper whose lock is INFERRED from its call
sites (the guarded-by inference following self helper calls)."""

import threading


class Tracker(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def add(self, item):
        with self._lock:
            self._items.append(item)

    def snapshot(self):
        with self._lock:
            return list(self._items)

    def drain(self):
        with self._lock:
            return self._emit()

    def _emit(self):
        # no syntactic lock here: every call site holds _lock, so the
        # guarded-by inference credits this read with the ambient lock
        return list(self._items)
