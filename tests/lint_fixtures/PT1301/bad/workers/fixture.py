"""PT1301 bad fixture: a container mutated under a lock is read with no
lock held — iteration can observe the list mid-append."""

import threading


class Tracker(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def add(self, item):
        with self._lock:
            self._items.append(item)

    def snapshot(self):
        out = []
        for item in self._items:
            out.append(item)
        return out
