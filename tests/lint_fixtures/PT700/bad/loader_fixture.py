from petastorm_tpu import observability as obs


def process():
    obs.stage('decode')
    do_work()


def do_work():
    pass
