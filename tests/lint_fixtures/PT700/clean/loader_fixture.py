from petastorm_tpu import observability as obs


def process():
    with obs.stage('decode', cat='worker'):
        do_work()


def do_work():
    pass
