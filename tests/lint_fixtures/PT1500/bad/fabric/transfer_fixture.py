"""Bad fixture: fabric socket ops with no timeout arming or deadline budget."""

import socket


def fetch_from_peer(endpoint, request):
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    # PT1500: connect with no settimeout and no deadline — a dead peer
    # blocks this thread until the kernel gives up
    sock.connect(endpoint)
    # PT1500: unbounded sends/recvs with no end-to-end budget either
    sock.sendall(request)
    return sock.recv(65536)


def accept_loop(listener, handle):
    while True:
        # PT1500: an un-armed accept cannot notice a stop request
        conn, _addr = listener.accept()
        handle(conn)


def drain(sock, n):
    parts = []
    while n > 0:
        # PT1500: timeout armed nowhere; slow-but-not-stalled peers stack
        part = sock.recv(min(4096, n))
        if not part:
            break
        parts.append(part)
        n -= len(part)
    return b''.join(parts)
