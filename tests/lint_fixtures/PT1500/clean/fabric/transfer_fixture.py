"""Clean twin: every socket op armed per-operation and deadline-bounded."""

import socket

from petastorm_tpu.fabric import protocol as P


def fetch_from_peer(endpoint, request, deadline, io_timeout_s):
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.settimeout(deadline.op_timeout(io_timeout_s))
    sock.connect(endpoint)
    sock.settimeout(deadline.op_timeout(io_timeout_s))
    sock.sendall(request)
    sock.settimeout(deadline.op_timeout(io_timeout_s))
    return sock.recv(65536)


def accept_loop(listener, handle, poll_s, stop):
    while not stop.is_set():
        listener.settimeout(poll_s)
        try:
            conn, _addr = listener.accept()
        except socket.timeout:
            continue
        handle(conn)


def drain(sock, n, io_timeout_s):
    deadline = P.Deadline(10.0)
    parts = []
    while n > 0:
        sock.settimeout(deadline.op_timeout(io_timeout_s))
        part = sock.recv(min(4096, n))
        if not part:
            break
        parts.append(part)
        n -= len(part)
    return b''.join(parts)
