import time

from petastorm_tpu import observability as obs
from petastorm_tpu.observability.trace import record_span


def process_item(worker, args, ctx):
    t0 = time.time()
    worker.process(*args)
    # orphan: the raw emitter stamps no TraceContext
    record_span('decode', 'worker', t0, time.time() - t0)


def decode_block(block, ctx):
    # orphan: hand-rolled identity diverges from the propagated context
    with obs.stage('decode', cat='worker', trace=ctx.trace, parent=ctx.span):
        return block.decode()
