from petastorm_tpu import observability as obs


def process_item(worker, args, ctx):
    # the propagated context is installed; every span inside inherits it
    with obs.use_trace(ctx):
        with obs.stage('decode', cat='worker'):
            worker.process(*args)


def wait_for_result(pool):
    with obs.stage('pool_wait', cat='pool') as sp:
        payload = pool.get()
        # identity discovered mid-flight is adopted via link, never kwargs
        sp.link(pool.last_result_trace)
        return payload
