#include <cstdint>

extern "C" {

int demo_write(void* h, const void* data, uint64_t len) {
  (void)h;
  (void)data;
  (void)len;
  return 0;
}

}  // extern "C"
