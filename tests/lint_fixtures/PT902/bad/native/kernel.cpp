#include <cstdint>

extern "C" {

int demo_write(void* h, const void* data) {
  (void)h;
  (void)data;
  return 0;
}

}  // extern "C"
