"""PT1302 clean twin: the guarded dict is copied out under the lock — the
caller owns an independent snapshot."""

import threading


class Registry(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}

    def record(self, key, value):
        with self._lock:
            self._entries[key] = value

    def entries(self):
        with self._lock:
            return dict(self._entries)
