"""PT1302 bad fixture: a lock-guarded dict escapes by reference — the
caller iterates/mutates it after the lock is released."""

import threading


class Registry(object):
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}

    def record(self, key, value):
        with self._lock:
            self._entries[key] = value

    def entries(self):
        with self._lock:
            return self._entries
