"""PT1300 bad fixture: a cross-class ABBA lock-order cycle.

Pool.grow acquires Pool._counter_lock then (via a constructor-typed
attribute call) Ventilator._cv; Ventilator.drain acquires the same two
locks in the opposite order. Neither class sees anything wrong on its own —
only the whole-program graph closes the cycle.
"""

import threading


class Pool(object):
    def __init__(self):
        self._counter_lock = threading.Lock()
        self._workers = 0
        self._vent = Ventilator()

    def grow(self):
        with self._counter_lock:
            self._workers += 1
            self._vent.set_quota(self._workers)

    def shrink(self):
        with self._counter_lock:
            self._workers -= 1


class Ventilator(object):
    def __init__(self):
        self._cv = threading.Condition()
        self._quota = 0
        self._pool = Pool()

    def set_quota(self, n):
        with self._cv:
            self._quota = n
            self._cv.notify_all()

    def drain(self):
        with self._cv:
            self._pool.shrink()
