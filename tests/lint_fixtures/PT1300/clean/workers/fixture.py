"""PT1300 clean twin: the same two classes with the cycle broken (the
cross-class call happens after the lock is released), plus a CLASS-LOCAL
ABBA cycle that PT101 owns — PT1300 must stay silent on it (the dedup
contract: the same cycle never fires twice)."""

import threading


class Pool(object):
    def __init__(self):
        self._counter_lock = threading.Lock()
        self._workers = 0
        self._vent = Ventilator()

    def grow(self):
        with self._counter_lock:
            self._workers += 1
            n = self._workers
        self._vent.set_quota(n)

    def shrink(self):
        with self._counter_lock:
            self._workers -= 1


class Ventilator(object):
    def __init__(self):
        self._cv = threading.Condition()
        self._quota = 0
        self._pool = Pool()

    def set_quota(self, n):
        with self._cv:
            self._quota = n
            self._cv.notify_all()

    def drain(self):
        with self._cv:
            self._quota = 0
        self._pool.shrink()


class LocalOrder(object):
    """Class-local ABBA: PT101 territory, not PT1300's."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._x = 0
        self._y = 0

    def one(self):
        with self._a:
            with self._b:
                self._x = 1

    def two(self):
        with self._b:
            with self._a:
                self._y = 1
