#include <cstdint>
#include <cstring>

void fill(uint8_t* dst, const uint8_t* src, uint64_t n) {
  const uint64_t need = n + 8;
  std::memcpy(dst, src, need);
}
