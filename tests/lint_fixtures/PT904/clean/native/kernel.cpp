#include <cstdint>
#include <cstring>

void fill(uint8_t* dst, uint64_t dst_cap, const uint8_t* src, uint64_t n) {
  const uint64_t need = n + 8;
  if (need > dst_cap) return;
  std::memcpy(dst, src, need);
}
