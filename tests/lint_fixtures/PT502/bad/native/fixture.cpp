struct TReader {
  void skip_struct() {
    skip_value(12);
  }
  void skip_value(int type);
};

void TReader::skip_value(int type) {
  if (type == 12) skip_struct();
}
