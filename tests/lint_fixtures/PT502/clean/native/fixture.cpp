struct TReader {
  void skip_struct(int depth) {
    if (depth > 32) return;
    skip_value(12, depth);
  }
  void skip_value(int type, int depth);
};

void TReader::skip_value(int type, int depth) {
  if (type == 12) skip_struct(depth + 1);
}
