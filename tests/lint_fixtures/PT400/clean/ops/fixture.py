import jax


@jax.jit
def pure(x, key):
    return x + jax.random.normal(key, x.shape)
