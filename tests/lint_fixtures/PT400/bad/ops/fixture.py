import jax
import numpy as np


@jax.jit
def noisy(x):
    return x + np.random.rand()
