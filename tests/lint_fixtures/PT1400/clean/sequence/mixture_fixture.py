"""Clean twin: every decision consumes the seeded constructor stream."""

import numpy as np


def draw_source(cum_weights, rng):
    return int(np.searchsorted(cum_weights, rng.random()))


def release_order(count, seed):
    rng = np.random.default_rng(seed)
    return rng.permutation(count)


def pool_salt(seed, epoch):
    return (seed * 31 + epoch) % 97


def shuffle_pool(rows, rng):
    order = rng.permutation(len(rows))
    return [rows[i] for i in order]
