"""Bad fixture: sequence sampling/packing decisions that change every run."""

import random
import time

import numpy as np


def draw_source(cum_weights):
    # PT1400: module-global RNG — any other import of random perturbs order
    return int(np.searchsorted(cum_weights, random.random()))


def release_order(count):
    # PT1400: unseeded constructor draws from OS entropy
    rng = np.random.default_rng()
    return rng.permutation(count)


def pool_salt():
    # PT1400: wall clock in a packing decision — different every run
    return int(time.time()) % 97


def shuffle_pool(rows):
    # PT1400: np.random module-level call is the legacy global stream
    np.random.shuffle(rows)
    return rows
