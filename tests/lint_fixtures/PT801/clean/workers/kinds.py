from petastorm_tpu.workers.protocol import MSG_DATA


def is_data(kind):
    return kind == MSG_DATA
