_DATA = 0
