#include <cstdint>

int check(uint64_t num_values, uint64_t width, uint64_t cap) {
  if (width == 0) return -1;
  if (num_values > cap / width) return -1;
  return 0;
}
