#include <cstdint>

int check(uint64_t num_values, uint64_t width, uint64_t cap) {
  if (num_values * width > cap) return -1;
  return 0;
}
