def worker_loop(q):
    try:
        q.get()
    except BaseException:
        raise
