"""PT704 bad fixture: a signal handler whose reachable cone locks, logs,
imports, opens files and allocates — every violation class the rule names."""

import json
import logging
import signal
import struct
import threading

logger = logging.getLogger(__name__)
_state_lock = threading.Lock()
_FMT = struct.Struct('<id')


def _stamp_crash(signum):
    with _state_lock:  # PT704: lock acquire inside the handler cone
        pass
    logger.warning('crash signal %s', signum)  # PT704: logging locks/allocates
    import os  # PT704: import machinery inside the handler cone
    open('/tmp/crash-{}'.format(os.getpid()), 'w')  # PT704: open() allocates
    json.dumps({'signal': signum})  # PT704: serializer allocates
    return _FMT.pack(signum, 0.0)  # PT704: Struct.pack allocates fresh bytes


def _marker(signum, frame):
    _stamp_crash(signum)


def install():
    signal.signal(signal.SIGTERM, _marker)
