"""PT704 clean twin: the handler cone only stamps a preallocated buffer
(``pack_into``) and re-raises; code OUTSIDE the cone may freely lock, log
and serialize — the rule constrains handler-reachable code only."""

import json
import logging
import os
import signal
import struct
import threading

logger = logging.getLogger(__name__)
_state_lock = threading.Lock()
_FMT = struct.Struct('<id')
_BUF = bytearray(_FMT.size)


def _stamp_crash(signum):
    _FMT.pack_into(_BUF, 0, signum, 0.0)


def _marker(signum, frame):
    _stamp_crash(signum)
    signal.signal(signum, signal.SIG_DFL)
    os.kill(os.getpid(), signum)


def install():
    signal.signal(signal.SIGTERM, _marker)


def ordinary_path(payload):
    """Not handler-reachable: locks, logging and serialization are fine."""
    with _state_lock:
        line = json.dumps(payload)
    logger.info('recorded %d bytes', len(line))
    return _FMT.pack(0, 0.0)
