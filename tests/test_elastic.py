"""Elastic pod sharding: membership leases, generation shard maps, the
exactly-once handoff protocol, and the churn-stable global shuffle
(docs/parallelism.md, "Elastic pod sharding").

The contract under test, layer by layer:

  * **shard map purity** — ownership and global order are functions of
    ``(seed, epoch, members)`` alone; the emission ORDER depends only on
    ``(seed, epoch)``, so churn never changes the shuffle;
  * **membership** — a lease kept fresh by a heartbeat is alive, a stale one
    is expired, and lease I/O rides the retry machinery so a flaky shared
    filesystem cannot masquerade as a host death;
  * **coordination** — a live peer's in-flight row groups are pinned, a dead
    peer's are adopted (counted as handoffs), commits are exactly-once by
    ``O_CREAT|O_EXCL`` construction;
  * **verification closes the loop** — the executable spec exhausts its
    default scope clean, every seeded mutation yields a counterexample, and
    random violating schedules replayed through the runtime
    :class:`ElasticMonitor` raise;
  * **end to end** — real subprocess hosts with a SIGKILL mid-epoch and a
    concurrent join still deliver every row group exactly once, and
    ``elastic=False`` stays structurally free.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.elastic import (ElasticConfig, MembershipRegistry, ShardMap,
                                   global_order, owner_of, stable_hash)

#: wall budget for the tier-1 model-check gate — far above the ~3s
#: uncontended runtime so a loaded CI host cannot flake it
TIER1_BUDGET_S = 300

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# shard map: deterministic, churn-stable (the PT1200-guarded module)
# ---------------------------------------------------------------------------

def test_owner_assignment_partitions_items():
    members = ('h0', 'h1', 'h2')
    smap = ShardMap(generation=1, members=members, num_items=20, seed=7, epoch=0)
    owned = [smap.owned_items(m) for m in members]
    flat = sorted(i for part in owned for i in part)
    assert flat == list(range(20))
    for m, part in zip(members, owned):
        assert all(smap.owner(i) == m for i in part)


def test_rendezvous_reassigns_only_departed_hosts_items():
    # THE rendezvous property: when h1 leaves, items owned by h0/h2 do not
    # move — only h1's items are redistributed. Static modulo sharding
    # reshuffles nearly everything on any membership change.
    before = ShardMap(1, ('h0', 'h1', 'h2'), num_items=40, seed=3, epoch=0)
    after = ShardMap(2, ('h0', 'h2'), num_items=40, seed=3, epoch=0)
    for i in range(40):
        if before.owner(i) != 'h1':
            assert after.owner(i) == before.owner(i)


def test_global_order_is_member_set_independent():
    # the churn-stable shuffle: emission order depends only on (seed, epoch)
    a = ShardMap(1, ('h0',), num_items=30, seed=11, epoch=2)
    b = ShardMap(7, ('h0', 'h1', 'h2', 'h3'), num_items=30, seed=11, epoch=2)
    assert list(a.order()) == list(b.order())
    assert list(a.order()) == list(global_order(30, seed=11, epoch=2))
    # different epoch/seed: different permutation
    assert list(a.order()) != list(global_order(30, seed=11, epoch=3))
    assert list(a.order()) != list(global_order(30, seed=12, epoch=2))


def test_global_order_shuffle_off_is_identity():
    assert list(global_order(9, seed=5, epoch=1, shuffle=False)) == list(range(9))


def test_stable_hash_is_stable():
    # blake2b over repr-encoded parts: immune to PYTHONHASHSEED, so every
    # host derives the identical map. Pin a value to catch accidental
    # algorithm drift (which would break mixed-version pods mid-run).
    assert stable_hash('a', 1) == stable_hash('a', 1)
    assert stable_hash('a', 1) != stable_hash('a', 2)
    assert stable_hash('ab', 'c') != stable_hash('a', 'bc')
    out = subprocess.run(
        [sys.executable, '-c',
         'from petastorm_tpu.elastic import stable_hash;'
         "print(stable_hash('pod', 3))"],
        capture_output=True, text=True, timeout=60,
        env=dict(os.environ, PYTHONHASHSEED='271', PYTHONPATH=REPO_ROOT))
    assert int(out.stdout) == stable_hash('pod', 3)


def test_shard_map_rejects_empty_members():
    with pytest.raises(ValueError):
        ShardMap(1, (), num_items=4, seed=0, epoch=0)


def test_owned_items_are_rank_ordered():
    smap = ShardMap(1, ('h0', 'h1'), num_items=16, seed=9, epoch=0)
    for m in ('h0', 'h1'):
        ranks = [smap.rank(i) for i in smap.owned_items(m)]
        assert ranks == sorted(ranks)


# ---------------------------------------------------------------------------
# membership: leases, expiry, flaky-fs hardening
# ---------------------------------------------------------------------------

def _write_lease(coord_dir, host, renewed, lease_s=0.5, machine='elsewhere',
                 pid=1):
    members = os.path.join(coord_dir, 'members')
    os.makedirs(members, exist_ok=True)
    with open(os.path.join(members, host + '.lease'), 'w') as f:
        json.dump({'host': host, 'pid': pid, 'machine': machine,
                   'lease_s': lease_s, 'renewed': renewed}, f)


def test_lease_join_scan_leave(tmp_path):
    coord = str(tmp_path)
    with MembershipRegistry(coord, 'h0', lease_s=5.0) as reg:
        assert reg.alive_members() == ('h0',)
        assert reg.expired_members() == ()
    # leave() unlinks the lease
    assert MembershipRegistry(coord, 'h1', lease_s=5.0).alive_members() == ()


def test_stale_lease_expires_and_rejoin_revives(tmp_path):
    coord = str(tmp_path)
    _write_lease(coord, 'ghost', renewed=time.time() - 60)
    reg = MembershipRegistry(coord, 'h0', lease_s=5.0)
    assert reg.expired_members() == ('ghost',)
    assert 'ghost' not in reg.alive_members()
    _write_lease(coord, 'ghost', renewed=time.time())
    assert 'ghost' in reg.alive_members()


def test_same_machine_dead_pid_is_dead_despite_fresh_lease(tmp_path):
    # fast-path crash detection: the lease is fresh, but the writing process
    # (provably on THIS machine) is gone — no need to wait out the lease
    coord = str(tmp_path)
    dead = subprocess.Popen([sys.executable, '-c', 'pass'])
    dead.wait()
    _write_lease(coord, 'ghost', renewed=time.time(),
                 machine=os.uname().nodename, pid=dead.pid)
    reg = MembershipRegistry(coord, 'h0', lease_s=5.0)
    assert 'ghost' in reg.expired_members()


def test_heartbeat_keeps_short_lease_alive(tmp_path):
    with MembershipRegistry(str(tmp_path), 'h0', lease_s=0.2) as reg:
        time.sleep(1.0)  # many lease periods: only the heartbeat keeps it fresh
        assert reg.alive_members() == ('h0',)


def test_flaky_fs_does_not_masquerade_as_departure(tmp_path):
    # satellite: lease I/O rides the retry machinery. The first N storage ops
    # raise transient OSErrors (the faults storage hook), and membership must
    # come out unchanged — a slow/flaky shared fs is NOT a host death.
    from petastorm_tpu import faults
    coord = str(tmp_path)
    with MembershipRegistry(coord, 'h0', lease_s=5.0):
        reg = MembershipRegistry(coord, 'peer', lease_s=5.0)
        faults.install(faults.FaultPlan(storage_fail_first=3))
        try:
            assert reg.alive_members() == ('h0',)
        finally:
            faults.uninstall()


# ---------------------------------------------------------------------------
# coordinator: pinning, adoption, exactly-once commit
# ---------------------------------------------------------------------------

def _make_coordinator(tmp_path, host='h0', num_items=6, lease_s=5.0, seed=0):
    from petastorm_tpu.elastic import resolve_elastic
    from petastorm_tpu.elastic.coordinator import ElasticCoordinator
    cfg = resolve_elastic(ElasticConfig(coord_dir=str(tmp_path), host_id=host,
                                        lease_s=lease_s, monitor=False))
    return ElasticCoordinator(cfg, num_items=num_items, seed=seed)


def test_live_peers_inflight_is_pinned_dead_peers_is_adopted(tmp_path):
    coord = _make_coordinator(tmp_path, num_items=6)
    coord.start()
    try:
        _write_lease(str(tmp_path), 'peer', renewed=time.time())
        coord.poll(force=True)
        assert set(coord.members) == {'h0', 'peer'}
        coord.begin_epoch(0)
        pinned = coord.shard_map(0).owned_items('h0')[0]
        inflight_dir = os.path.join(str(tmp_path), 'epochs', '000000', 'inflight')
        os.makedirs(inflight_dir, exist_ok=True)
        with open(os.path.join(inflight_dir, 'peer.json'), 'w') as f:
            json.dump({'host': 'peer', 'generation': coord.generation,
                       'items': [int(pinned)]}, f)
        coord.poll(epoch=0, force=True)
        # pinned while the peer lives, even though h0 owns it
        assert pinned not in coord.claimable_items(0)
        # the peer dies: its lease goes stale, its claim becomes adoptable
        _write_lease(str(tmp_path), 'peer', renewed=time.time() - 60)
        coord.poll(epoch=0, force=True)
        assert set(coord.members) == {'h0'}
        assert pinned in coord.claimable_items(0)
    finally:
        coord.close()


def test_commit_markers_are_exactly_once(tmp_path):
    a = _make_coordinator(tmp_path, host='a', num_items=4)
    b = _make_coordinator(tmp_path, host='b', num_items=4)
    a.start(); b.start()
    try:
        a.begin_epoch(0); b.begin_epoch(0)
        assert a.commit(0, 2) is True
        assert b.commit(0, 2) is False   # the marker already exists
        assert a.commit(0, 2) is False   # not even the winner wins twice
        assert a.is_done(0, 2)
        assert b.is_done(0, 2)
    finally:
        a.close(); b.close()


def test_torn_generation_file_is_skipped_not_fatal(tmp_path):
    """A half-visible peer publish (torn write on an eventually-consistent
    shared fs) must not kill the poll: the torn file is skipped this scan
    and picked up once complete — json garbage used to escape poll() and
    take down the whole feed thread."""
    coord = _make_coordinator(tmp_path)
    coord.start()
    try:
        assert coord.generation == 1
        torn = os.path.join(str(tmp_path), 'generations', '00000005.json')
        with open(torn, 'w') as f:
            f.write('{"generation":')     # truncated mid-write
        coord.poll(force=True)            # must not raise
        assert coord.generation == 1
        with open(torn, 'w') as f:        # the write completes
            json.dump({'generation': 5, 'members': ['h0'],
                       'proposed_by': 'peer'}, f)
        coord.poll(force=True)
        assert coord.generation == 5
        # own proposals are published atomically: every file parses, no
        # staging files linger
        gen_dir = os.path.join(str(tmp_path), 'generations')
        assert all(n.endswith('.json') for n in os.listdir(gen_dir))
        for name in os.listdir(gen_dir):
            with open(os.path.join(gen_dir, name)) as f:
                json.load(f)
    finally:
        coord.close()


def test_feed_thread_crash_marks_ventilation_complete(tmp_path):
    """An unexpected exception on the feed thread must mark the ventilator
    completed (consumers drain and stop) instead of hanging every consumer
    on a queue that will never fill."""
    from petastorm_tpu.elastic.coordinator import ElasticVentilator
    coord = _make_coordinator(tmp_path, num_items=2)

    def boom(epoch):
        raise RuntimeError('injected feed-thread crash')

    coord.begin_epoch = boom
    vent = ElasticVentilator(lambda **kw: None,
                             [{'piece_index': i} for i in range(2)], coord)
    vent.start()
    deadline = time.time() + 30
    while not vent.completed() and time.time() < deadline:
        time.sleep(0.01)
    assert vent.completed(), 'feed-thread death left the ventilator hanging'
    vent.stop()


def test_persistent_marker_failure_keeps_item_uncommitted(tmp_path):
    """A commit whose O_EXCL marker could not be created (fs error past the
    retry budget) must NOT count the item done locally: no marker on disk
    means peers could never see the epoch complete. The item stays
    uncommitted and the marker is retried from the poll loop."""
    from petastorm_tpu import faults
    coord = _make_coordinator(tmp_path, num_items=2)
    coord.start()
    try:
        coord.begin_epoch(0)
        coord.note_ventilated(0, 1)
        faults.install(faults.FaultPlan(storage_fail_first=10))
        try:
            assert coord.commit(0, 1) is False
        finally:
            faults.uninstall()
        done_dir = os.path.join(str(tmp_path), 'epochs', '000000', 'done')
        assert os.listdir(done_dir) == []
        assert not coord.is_done(0, 1)
        assert 1 in coord.undone_items(0)       # still checkpoint-visible
        assert not coord.epoch_complete(0)
        # the next poll retries the marker and wins it durably
        coord.poll(epoch=0, force=True)
        assert coord.is_done(0, 1)
        assert os.listdir(done_dir) == ['00000001']
        assert 1 not in coord.undone_items(0)
    finally:
        coord.close()


def test_generation_advances_monotonically_on_churn(tmp_path):
    coord = _make_coordinator(tmp_path)
    coord.start()
    try:
        g1 = coord.generation
        _write_lease(str(tmp_path), 'peer', renewed=time.time())
        coord.poll(force=True)
        g2 = coord.generation
        _write_lease(str(tmp_path), 'peer', renewed=time.time() - 60)
        coord.poll(force=True)
        g3 = coord.generation
        assert g1 < g2 < g3
        names = sorted(os.listdir(os.path.join(str(tmp_path), 'generations')))
        assert len(names) == g3
    finally:
        coord.close()


# ---------------------------------------------------------------------------
# the verification loop: spec, mutations, monitor conformance
# ---------------------------------------------------------------------------

def test_elastic_modelcheck_default_scope_exhausts_clean():
    """THE tier-1 gate: the default elastic scope exhausts within budget with
    zero invariant violations, above the declared canonical-state floor."""
    from petastorm_tpu.analysis.protocol import elastic_spec as EL
    cfg = EL.ElasticSpecConfig(**EL.DEFAULT_ELASTIC_SCOPE)
    result = EL.check(cfg, budget_s=TIER1_BUDGET_S)
    assert result.exhausted, 'elastic scope not exhausted in budget'
    assert result.violation is None, result.trace
    assert result.states >= EL.DEFAULT_ELASTIC_STATE_FLOOR, result.states


@pytest.mark.parametrize('mutation', ['reassign_before_expiry',
                                      'skip_done_check',
                                      'drop_on_expire',
                                      'generation_rollback'])
def test_elastic_mutations_have_teeth(mutation):
    from petastorm_tpu.analysis.protocol import elastic_spec as EL
    cfg = EL.ElasticSpecConfig(mutation=mutation, **EL.DEFAULT_ELASTIC_SCOPE)
    result = EL.check(cfg, budget_s=120.0)
    assert result.violation is not None, \
        'mutation {} produced no counterexample'.format(mutation)
    assert result.trace


def test_elastic_monitor_accepts_legal_and_rejects_illegal():
    from petastorm_tpu.analysis.protocol.monitor import ElasticMonitor
    from petastorm_tpu.errors import ProtocolViolation
    m = ElasticMonitor()
    m.on_join('h0'); m.on_join('h1')
    m.on_reshard(1, ('h0', 'h1'))
    m.on_claim('h0', 3)
    m.on_deliver('h0', 3)
    m.on_lease_expire('h1')
    with pytest.raises(ProtocolViolation):
        m.on_deliver('h0', 3)            # double commit
    m2 = ElasticMonitor()
    m2.on_claim('h0', 1)
    with pytest.raises(ProtocolViolation):
        m2.on_claim('h1', 1)             # in-flight moved before lease expiry
    m3 = ElasticMonitor()
    m3.on_claim('h0', 1)
    m3.on_lease_expire('h0')
    m3.on_claim('h1', 1)                 # legal: expiry released the claim
    m3.on_deliver('h1', 1)
    m4 = ElasticMonitor()
    m4.on_reshard(2, ('h0',))
    with pytest.raises(ProtocolViolation):
        m4.on_reshard(2, ('h0',))        # generation must strictly increase
    m5 = ElasticMonitor()
    with pytest.raises(ProtocolViolation):
        m5.on_deliver('h0', 4)           # commit without a live claim


def test_random_walks_replay_through_monitor():
    """Satellite: seeded schedule fuzz. Healthy walks replay clean through
    the runtime monitor; walks that violate the spec under a mutation make
    the monitor raise — the spec and its runtime projection agree."""
    pytest.importorskip('hypothesis')
    from hypothesis import HealthCheck, given, settings, strategies as st
    from petastorm_tpu.analysis.protocol import elastic_spec as EL
    from petastorm_tpu.analysis.protocol.monitor import ElasticMonitor
    from petastorm_tpu.errors import ProtocolViolation

    clean_cfg = EL.ElasticSpecConfig(**EL.DEFAULT_ELASTIC_SCOPE)

    @settings(max_examples=40, deadline=None,
              suppress_health_check=[HealthCheck.too_slow])
    @given(seed=st.integers(min_value=0, max_value=10**6))
    def healthy(seed):
        trace, violation = EL.random_walk(clean_cfg, seed)
        assert violation is None
        EL.replay_into_monitor(trace, ElasticMonitor('fuzz'))

    @settings(max_examples=40, deadline=None,
              suppress_health_check=[HealthCheck.too_slow])
    @given(seed=st.integers(min_value=0, max_value=10**6),
           mutation=st.sampled_from(sorted(EL.MUTATIONS)))
    def mutant(seed, mutation):
        cfg = EL.ElasticSpecConfig(mutation=mutation,
                                   **EL.DEFAULT_ELASTIC_SCOPE)
        trace, violation = EL.random_walk(cfg, seed)
        if violation is None:
            return  # this seed never tripped the mutated behavior
        with pytest.raises(ProtocolViolation):
            EL.replay_into_monitor(trace, ElasticMonitor('fuzz'))

    healthy()
    mutant()


# ---------------------------------------------------------------------------
# reader integration
# ---------------------------------------------------------------------------

def test_single_host_elastic_reader_covers_dataset(synthetic_dataset, tmp_path):
    cfg = ElasticConfig(coord_dir=str(tmp_path / 'coord'), host_id='h0')
    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type='dummy', seed=7, elastic=cfg) as reader:
        ids = [int(row.id) for row in reader]
    assert sorted(ids) == sorted(r['id'] for r in synthetic_dataset.data)
    done = os.listdir(str(tmp_path / 'coord' / 'epochs' / '000000' / 'done'))
    assert len(done) == 10  # one exclusive marker per row group


def test_two_inprocess_hosts_split_the_epoch(synthetic_dataset, tmp_path):
    coord = str(tmp_path / 'coord')
    results, errors = {}, []

    def consume(host):
        try:
            cfg = ElasticConfig(coord_dir=coord, host_id=host, lease_s=5.0,
                                poll_s=0.05)
            with make_reader(synthetic_dataset.url, schema_fields=['id'],
                             reader_pool_type='dummy', seed=21,
                             elastic=cfg) as reader:
                results[host] = [int(row.id) for row in reader]
        except Exception as e:       # surfaced by the main thread's assert
            errors.append((host, e))

    threads = [threading.Thread(target=consume, args=('h%d' % i,))
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    all_ids = {r['id'] for r in synthetic_dataset.data}
    delivered = results['h0'] + results['h1']
    assert set(delivered) == all_ids, 'pod-wide coverage hole'
    # commit scoreboard: every row group exactly once
    done = os.listdir(os.path.join(coord, 'epochs', '000000', 'done'))
    assert len(done) == len(set(done)) == 10


def test_elastic_argument_validation(synthetic_dataset, tmp_path):
    url = synthetic_dataset.url
    with pytest.raises(ValueError, match='replaces static sharding'):
        make_reader(url, elastic=True, cur_shard=0, shard_count=2)
    with pytest.raises(ValueError, match='not supported with elastic'):
        make_reader(url, elastic=True,
                    resume_state={'version': 2})
    with pytest.raises(ValueError, match='not supported with serve'):
        make_reader(url, elastic=True, serve=str(tmp_path))
    with pytest.raises(ValueError, match='must be True or an ElasticConfig'):
        make_reader(url, elastic=3)
    with pytest.raises(ValueError, match='lease_s must be positive'):
        ElasticConfig(lease_s=0)


def test_elastic_off_is_structurally_free(synthetic_dataset):
    """Acceptance gate: a plain reader must not import the elastic package,
    create coordination directories, or touch any lock/message machinery —
    elastic=False costs nothing."""
    code = (
        'import sys\n'
        'from petastorm_tpu import make_reader\n'
        'with make_reader({url!r}, schema_fields=["id"], '
        'reader_pool_type="dummy", seed=1) as r:\n'
        '    next(iter(r))\n'
        'assert not any(m.startswith("petastorm_tpu.elastic") '
        'for m in sys.modules), "elastic package loaded on the plain path"\n'
        'import os\n'
        'assert not os.path.exists(os.path.join({path!r}, "_elastic"))\n'
        'print("FREE")\n'.format(url=synthetic_dataset.url,
                                 path=synthetic_dataset.path))
    out = subprocess.run([sys.executable, '-c', code], capture_output=True,
                         text=True, timeout=120,
                         env=dict(os.environ, JAX_PLATFORMS='cpu',
                                  PYTHONPATH=REPO_ROOT))
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'FREE' in out.stdout


# ---------------------------------------------------------------------------
# chaos: SIGKILL one host mid-epoch while another joins (real processes)
# ---------------------------------------------------------------------------

CHAOS_SEED = 5


def _spawn_host(url, coord, host, outdir):
    return subprocess.Popen(
        [sys.executable, '-m', 'petastorm_tpu.elastic._hostproc',
         '--url', url, '--coord', coord, '--host', host,
         '--out', os.path.join(outdir, host + '.jsonl'),
         '--seed', str(CHAOS_SEED), '--lease-s', '1.0',
         '--sleep-per-row', '0.02'],
        env=dict(os.environ, JAX_PLATFORMS='cpu', PYTHONPATH=REPO_ROOT))


def _load_commits(coord):
    commits = {}
    commits_dir = os.path.join(coord, 'commits')
    for name in sorted(os.listdir(commits_dir)):
        with open(os.path.join(commits_dir, name)) as f:
            for line in f:
                rec = json.loads(line)
                commits.setdefault((rec['epoch'], rec['item']), []).append(rec)
    return commits


def test_kill_and_join_mid_epoch_is_exactly_once(synthetic_dataset, tmp_path):
    """Satellite + acceptance gate: SIGKILL one host's reader mid-epoch while
    a second host joins. The pod must still deliver every row group exactly
    once (commit scoreboard), the surviving hosts' epochs must terminate
    (exit 0), the generation must advance past the churn, and every commit's
    rank must match the churn-free global shuffle order."""
    from petastorm_tpu.faults import HostChurnPlan, drive_host_churn
    coord = str(tmp_path / 'coord')
    outdir = str(tmp_path)
    url = synthetic_dataset.url

    procs = {h: _spawn_host(url, coord, h, outdir) for h in ('h0', 'h1')}
    plan = HostChurnPlan(kill_host='h1', kill_after_commits=3, join_host='h2')
    timeline = drive_host_churn(
        coord, procs, plan,
        spawn_joiner=lambda: _spawn_host(url, coord, 'h2', outdir),
        timeout_s=120)
    rcs = {h: p.wait(timeout=180) for h, p in procs.items()}

    assert timeline['killed'] == 'h1' and timeline['joined'] == 'h2'
    assert rcs['h1'] == -signal.SIGKILL
    assert rcs['h0'] == 0 and rcs['h2'] == 0, 'survivor epoch did not terminate'

    # exactly-once pod-wide coverage, from the scoreboard ground truth
    done = os.listdir(os.path.join(coord, 'epochs', '000000', 'done'))
    assert len(done) == len(set(done)) == 10
    commits = _load_commits(coord)
    assert len(commits) == 10
    assert all(len(v) == 1 for v in commits.values()), 'double commit'

    # the survivors adopted work: generation advanced past the kill+join
    generations = os.listdir(os.path.join(coord, 'generations'))
    assert len(generations) >= 3

    # churn-stable shuffle: every commit's recorded rank equals the
    # member-set-independent order derived from (seed, epoch) alone — the
    # emission order is bit-identical to a churn-free run's
    order = list(global_order(10, seed=CHAOS_SEED, epoch=0))
    rank_of = {item: rank for rank, item in enumerate(order)}
    for (_epoch, item), (rec,) in commits.items():
        assert rec['rank'] == rank_of[item]

    # and a churn-free single-host run produces that same order end to end
    solo_coord = str(tmp_path / 'solo')
    cfg = ElasticConfig(coord_dir=solo_coord, host_id='solo')
    with make_reader(url, schema_fields=['id'], reader_pool_type='dummy',
                     seed=CHAOS_SEED, elastic=cfg) as reader:
        for _ in reader:
            pass
    solo = _load_commits(solo_coord)
    assert sorted(solo, key=lambda k: solo[k][0]['rank']) == \
        sorted(commits, key=lambda k: commits[k][0]['rank'])


def test_hostproc_emits_final_membership(synthetic_dataset, tmp_path):
    coord = str(tmp_path / 'coord')
    proc = _spawn_host(synthetic_dataset.url, coord, 'only', str(tmp_path))
    assert proc.wait(timeout=180) == 0
    records = [json.loads(line)
               for line in open(os.path.join(str(tmp_path), 'only.jsonl'))]
    events = [r['event'] for r in records]
    assert events == ['start', 'done', 'exit']
    done = records[1]
    assert done['rows'] == 100 and done['members'] == ['only']
    assert done['generation'] >= 1
