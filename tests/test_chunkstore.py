"""Chunk store: zero-copy page scan for remote Parquet stores.

Covers the subsystem's contract end to end against a mock-remote store (local
files behind the same retry wrapper the object stores get — ``mock-remote://``):

  * population is atomic and idempotent across concurrent writers;
  * a second epoch over a warm cache takes the page-scan path (asserted
    through the ``chunk_cache_*`` diagnostics counters) and returns bytes
    identical to the local read;
  * eviction under a live columnar batch NEVER invalidates the batch's views
    (the refcount pin skips mapped chunks, on record);
  * the prefetcher walks the ventilator's upcoming order under its in-flight
    byte budget;
  * counters surface through ``Reader.diagnostics`` and
    ``JaxDataLoader.diagnostics``.
"""

import gc
import os
import threading

import numpy as np
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.chunkstore import ChunkCacheConfig, cache_diagnostics, resolve_chunk_cache
from petastorm_tpu.chunkstore.store import ChunkStore
from petastorm_tpu.codecs import RawTensorCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
from petastorm_tpu.unischema import Unischema, UnischemaField

native = pytest.importorskip('petastorm_tpu.native')
pytestmark = pytest.mark.skipif(not native.is_available(),
                                reason='native kernel unavailable')


def _write_raw_store(tmp_path, rows=24, image_size=8):
    schema = Unischema('Raw', [
        UnischemaField('image', np.uint8, (image_size, image_size, 3),
                       RawTensorCodec(), False),
        UnischemaField('label', np.int64, (), ScalarCodec(np.int64), False),
    ])
    rng = np.random.default_rng(0)
    data = [{'image': rng.integers(0, 255, (image_size, image_size, 3), np.uint8),
             'label': int(i)} for i in range(rows)]
    store = str(tmp_path / 'raw')
    write_petastorm_dataset('file://' + store, schema, iter(data),
                            rows_per_row_group=8, compression='none')
    return store, data


def _chunk_diag(reader):
    return {k: v for k, v in reader.diagnostics.items() if k.startswith('chunk_cache')}


# ---------------------------------------------------------------------------
# ChunkStore unit behavior
# ---------------------------------------------------------------------------

class TestChunkStore:
    def test_populate_then_hit(self, tmp_path):
        store = ChunkStore(str(tmp_path / 'c'))
        calls = []

        def fetch():
            calls.append(1)
            return b'x' * 100

        path, _, fetched = store.ensure('k1', 100, fetch)
        assert fetched and os.path.getsize(path) == 100
        path2, _, fetched2 = store.ensure('k1', 100, fetch)
        assert path2 == path and not fetched2
        assert len(calls) == 1
        snap = store.stats_snapshot()
        assert snap['misses'] == 1 and snap['hits'] == 1
        assert snap['bytes_fetched'] == 100

    def test_short_fetch_rejected(self, tmp_path):
        store = ChunkStore(str(tmp_path / 'c'))
        with pytest.raises(IOError):
            store.ensure('k1', 100, lambda: b'x' * 50)
        assert not store.contains('k1', 100)

    def test_concurrent_population_is_atomic(self, tmp_path):
        """Racing writers from DIFFERENT processes (modeled as one store
        instance per thread — the per-digest single-flight mutex is
        per-process) must each observe a COMPLETE chunk: the rename is
        atomic, last write wins with identical bytes. In-process racers are
        single-flighted instead (test_fabric.py covers exactly-once)."""
        payload = bytes(range(256)) * 40
        barrier = threading.Barrier(4)
        results = []

        def worker():
            store = ChunkStore(str(tmp_path / 'c'))

            def fetch():
                barrier.wait(timeout=10)
                return payload
            path, _, _ = store.ensure('shared', len(payload), fetch)
            with open(path, 'rb') as f:
                results.append(f.read())

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 4
        assert all(r == payload for r in results)

    def test_lru_eviction_frees_oldest_first(self, tmp_path):
        store = ChunkStore(str(tmp_path / 'c'), size_limit_bytes=250)
        store.ensure('a', 100, lambda: b'a' * 100)
        os.utime(store._entry_path(store.digest('a')),
                 ns=(1, 1))  # force 'a' oldest regardless of clock granularity
        store.ensure('b', 100, lambda: b'b' * 100)
        store.ensure('c', 100, lambda: b'c' * 100)  # 300 > 250: evicts 'a'
        assert not store.contains('a', 100)
        assert store.contains('b', 100) and store.contains('c', 100)
        snap = store.stats_snapshot()
        assert snap['chunks_evicted'] == 1 and snap['bytes_evicted'] == 100

    def test_mmap_refetches_if_evicted_between_ensure_and_map(self, tmp_path):
        store = ChunkStore(str(tmp_path / 'c'))
        fetches = []

        def fetch():
            fetches.append(1)
            return b'z' * 64

        path, _, _ = store.ensure('k', 64, fetch)
        os.unlink(path)  # another process's evictor won the race
        mm = store.mmap_chunk('k', 64, fetch)
        assert bytes(mm) == b'z' * 64
        assert len(fetches) == 2

    def test_strong_pool_serves_warm_hits_without_remapping(self, tmp_path):
        """Warm re-reads must reuse the SAME mapping object (the bounded
        strong-ref pool) even when no external reference keeps it alive
        between reads — the np.memmap round trip is the warm path's cost."""
        store = ChunkStore(str(tmp_path / 'c'))
        mm1 = store.mmap_chunk('k', 64, lambda: b'a' * 64)
        ident = id(mm1)
        del mm1
        gc.collect()
        mm2 = store.mmap_chunk('k', 64, lambda: b'a' * 64)
        assert id(mm2) == ident
        assert store.stats_snapshot()['misses'] == 1

    def test_strong_pool_never_blocks_eviction(self, tmp_path):
        """The store's OWN mapping refs are not pins: with no live batch
        referencing a chunk, over-budget eviction must release the pool entry
        and unlink the chunk rather than skip it."""
        store = ChunkStore(str(tmp_path / 'c'), size_limit_bytes=150)
        store.mmap_chunk('a', 100, lambda: b'a' * 100)
        os.utime(store._entry_path(store.digest('a')), ns=(1, 1))
        store.ensure('b', 100, lambda: b'b' * 100)  # 200 > 150: must evict 'a'
        assert not store.contains('a', 100)
        snap = store.stats_snapshot()
        assert snap['chunks_evicted'] == 1
        assert snap['evict_skipped_pinned'] == 0

    def test_config_resolution(self, tmp_path):
        cfg = resolve_chunk_cache(str(tmp_path / 'x'), 'mock-remote:///d', False)
        assert isinstance(cfg, ChunkCacheConfig)
        assert resolve_chunk_cache(None, 'mock-remote:///d', False) is None
        # local datasets never engage, even with an explicit path
        assert resolve_chunk_cache(str(tmp_path / 'x'), 'file:///d', True) is None
        auto = resolve_chunk_cache('auto', 'mock-remote:///d', False)
        auto2 = resolve_chunk_cache('auto', 'mock-remote:///d', False)
        assert auto == auto2 and hash(auto) == hash(auto2)
        assert auto != resolve_chunk_cache('auto', 'mock-remote:///other', False)
        with pytest.raises(ValueError):
            resolve_chunk_cache(123, 'mock-remote:///d', False)


# ---------------------------------------------------------------------------
# End-to-end: mock-remote reads take the page-scan path on epoch 2
# ---------------------------------------------------------------------------

def test_epoch2_takes_pagescan_path_with_byte_equality(tmp_path):
    """The acceptance check: a mock-remote raw store reads correctly, and the
    SECOND epoch is served from the cache (hits grow, misses do not) with
    zero-copy views — the page-scan path, proven via diagnostics."""
    store_path, data = _write_raw_store(tmp_path)
    url = 'mock-remote://' + store_path
    cache = str(tmp_path / 'chunks')

    with make_reader('file://' + store_path, reader_pool_type='dummy',
                     output='columnar', shuffle_row_groups=False) as r:
        local_blocks = list(r)

    with make_reader(url, reader_pool_type='dummy', output='columnar',
                     shuffle_row_groups=False, chunk_cache=cache) as r1:
        remote_blocks = list(r1)
        diag1 = _chunk_diag(r1)
    assert diag1['chunk_cache_misses'] > 0, 'epoch 1 must populate the cache'
    # zero copy: the image block is a view chain over the chunk mirror
    assert np.asarray(remote_blocks[0].image).base is not None

    # byte equality with the local page-scan path
    local = np.concatenate([np.asarray(b.image) for b in local_blocks])
    remote = np.concatenate([np.asarray(b.image) for b in remote_blocks])
    np.testing.assert_array_equal(local, remote)

    with make_reader(url, reader_pool_type='dummy', output='columnar',
                     shuffle_row_groups=False, chunk_cache=cache) as r2:
        remote2 = np.concatenate([np.asarray(b.image) for b in list(r2)])
        diag2 = _chunk_diag(r2)
    np.testing.assert_array_equal(local, remote2)
    assert diag2['chunk_cache_hits'] > diag1['chunk_cache_hits'], \
        'epoch 2 must be served from the cache'
    assert diag2['chunk_cache_misses'] == diag1['chunk_cache_misses'], \
        'epoch 2 must not refetch anything'
    assert diag2['chunk_cache_bytes_fetched'] == diag1['chunk_cache_bytes_fetched']


def test_row_output_and_thread_pool_match_data(tmp_path):
    store_path, data = _write_raw_store(tmp_path)
    url = 'mock-remote://' + store_path
    with make_reader(url, reader_pool_type='thread', workers_count=2,
                     shuffle_row_groups=False,
                     chunk_cache=str(tmp_path / 'chunks')) as reader:
        rows = {int(r.label): r for r in reader}
    assert len(rows) == len(data)
    for d in data:
        np.testing.assert_array_equal(rows[d['label']].image, d['image'])


def test_batch_reader_plain_parquet_mock_remote(tmp_path):
    """make_batch_reader over a plain (non-petastorm) store rides the same
    chunk-cached path for its qualifying numeric columns."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    path = tmp_path / 'plain'
    path.mkdir()
    table = pa.table({'x': pa.array(np.arange(50, dtype=np.int64)),
                      'y': pa.array(np.linspace(0, 1, 50).astype(np.float64))})
    pq.write_table(table, str(path / 'f.parquet'), compression='none',
                   use_dictionary=False)
    url = 'mock-remote://' + str(path)
    cache = str(tmp_path / 'chunks')
    with make_batch_reader(url, reader_pool_type='dummy', shuffle_row_groups=False,
                           chunk_cache=cache) as reader:
        xs = [x for b in reader for x in b.x.tolist()]
        diag = _chunk_diag(reader)
    assert xs == list(range(50))
    assert diag['chunk_cache_misses'] > 0


def test_local_dataset_ignores_chunk_cache(tmp_path):
    """file:// datasets must not engage the chunk layer (the scanner mmaps
    them directly) — no counters in diagnostics, no cache dir created."""
    store_path, _ = _write_raw_store(tmp_path)
    cache = str(tmp_path / 'chunks_unused')
    with make_reader('file://' + store_path, reader_pool_type='dummy',
                     shuffle_row_groups=False, chunk_cache=cache) as reader:
        next(iter(reader))
        assert not any(k.startswith('chunk_cache') for k in reader.diagnostics)
    assert not os.path.exists(cache)


def test_diagnostics_through_jax_loader(tmp_path):
    from petastorm_tpu.jax import JaxDataLoader
    store_path, _ = _write_raw_store(tmp_path)
    url = 'mock-remote://' + store_path
    reader = make_reader(url, reader_pool_type='dummy', output='columnar',
                         shuffle_row_groups=False,
                         chunk_cache=str(tmp_path / 'chunks'))
    with JaxDataLoader(reader, batch_size=8) as loader:
        for _ in loader:
            pass
        diag = loader.diagnostics
    assert diag['chunk_cache_misses'] > 0
    assert 'chunk_cache_hits' in diag and 'chunk_cache_bytes_fetched' in diag


# ---------------------------------------------------------------------------
# Eviction-under-use safety (the PT500-series contract)
# ---------------------------------------------------------------------------

def test_eviction_under_live_batch_never_invalidates_views(tmp_path):
    """Stress the evictor against live zero-copy batches: a tiny size bound
    forces eviction while a columnar view batch is still referenced. The
    pinned chunk must be SKIPPED (refcount pin, on record in the counters)
    and the batch's bytes must stay intact throughout."""
    store_path, data = _write_raw_store(tmp_path, rows=48, image_size=16)
    url = 'mock-remote://' + store_path
    # bound ~2 image chunks: reading 6 row groups must evict continuously
    config = ChunkCacheConfig(str(tmp_path / 'chunks'), size_limit_bytes=4096)
    with make_reader(url, reader_pool_type='dummy', output='columnar',
                     shuffle_row_groups=False, chunk_cache=config) as reader:
        blocks = list(reader)  # every block holds live views over its mirror
        diag = _chunk_diag(reader)
        expected = np.stack([d['image'] for d in data])
        got = np.concatenate([np.asarray(b.image) for b in blocks])
        np.testing.assert_array_equal(got, expected)
        assert diag['chunk_cache_evict_skipped_pinned'] > 0, \
            'the evictor must have skipped pinned (live-mapped) chunks'
        assert diag['chunk_cache_chunks_pinned'] > 0
        # the views must STILL be intact after further eviction pressure
        store = ChunkStore(config.root, size_limit_bytes=config.size_limit_bytes)
        store.ensure('pressure', 4096, lambda: b'p' * 4096)
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(b.image) for b in blocks]), expected)

    # once the batches are released, the pins lift and eviction can reclaim
    del blocks, got
    gc.collect()
    store._evict_if_needed()
    snap = store.stats_snapshot()
    assert snap['chunks_evicted'] > 0


def test_unlinked_chunk_keeps_serving_live_mmap(tmp_path):
    """POSIX backstop: even a chunk unlinked behind our back (external
    cleanup) keeps serving an already-built view."""
    store = ChunkStore(str(tmp_path / 'c'))
    payload = bytes(range(256))
    mm = store.mmap_chunk('k', 256, lambda: payload)
    os.unlink(store._entry_path(store.digest('k')))
    assert bytes(mm) == payload


# ---------------------------------------------------------------------------
# Prefetcher
# ---------------------------------------------------------------------------

class _FakeVentilator(object):
    def __init__(self, items):
        self._items = items

    def upcoming_items(self, max_items):
        return self._items[:max_items]


class _Piece(object):
    def __init__(self, path, row_group):
        self.path = path
        self.row_group = row_group


def _mock_remote_fs_factory():
    import pyarrow.fs as pafs
    from petastorm_tpu.retry import wrap_retrying
    return wrap_retrying(pafs.LocalFileSystem())


def test_prefetcher_populates_upcoming_chunks(tmp_path):
    store_path, _ = _write_raw_store(tmp_path)
    parquet = str(next(p for p in (tmp_path / 'raw').iterdir()
                       if p.suffix == '.parquet'))
    pieces = [_Piece(parquet, rg) for rg in range(3)]
    items = [{'piece_index': i} for i in range(3)]
    config = ChunkCacheConfig(str(tmp_path / 'chunks'))

    from petastorm_tpu.chunkstore.prefetch import ChunkPrefetcher
    pf = ChunkPrefetcher(_FakeVentilator(items), pieces, ['image', 'label'],
                         _mock_remote_fs_factory, config)
    pf.start()
    try:
        deadline = 10.0
        import time
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline:
            diag = cache_diagnostics(config)
            if diag['chunk_cache_prefetch_chunks'] >= 6:  # 3 rgs x 2 columns
                break
            time.sleep(0.05)
    finally:
        pf.stop()
        pf.join()
    diag = cache_diagnostics(config)
    assert diag['chunk_cache_prefetch_chunks'] >= 6
    assert diag['chunk_cache_prefetch_bytes'] > 0


def test_prefetcher_respects_inflight_byte_budget(tmp_path):
    """With a budget smaller than two chunks and nothing consuming them, the
    prefetcher must stall after the first fetch; bumping the fetched mirror's
    mtime (the demand-hit signal) releases the budget."""
    import time
    store_path, _ = _write_raw_store(tmp_path, rows=24, image_size=16)
    parquet = str(next(p for p in (tmp_path / 'raw').iterdir()
                       if p.suffix == '.parquet'))
    pieces = [_Piece(parquet, rg) for rg in range(3)]
    items = [{'piece_index': i} for i in range(3)]
    # image chunks are 8*16*16*3 = 6KB+; budget below 2 of them
    config = ChunkCacheConfig(str(tmp_path / 'chunks'),
                              prefetch_budget_bytes=8000)

    from petastorm_tpu.chunkstore.prefetch import ChunkPrefetcher
    pf = ChunkPrefetcher(_FakeVentilator(items), pieces, ['image'],
                         _mock_remote_fs_factory, config)
    pf.start()
    try:
        t0 = time.monotonic()
        while time.monotonic() - t0 < 10:
            if cache_diagnostics(config)['chunk_cache_prefetch_chunks'] >= 1:
                break
            time.sleep(0.02)
        time.sleep(0.5)  # give it every chance to (wrongly) run ahead
        stalled = cache_diagnostics(config)['chunk_cache_prefetch_chunks']
        assert stalled == 1, 'budget must hold the prefetcher at one chunk'
        # simulate consumption: a demand hit bumps the mirror mtime
        for out_path, _size, _ns in list(pf._outstanding):
            os.utime(out_path, None)
        t0 = time.monotonic()
        while time.monotonic() - t0 < 10:
            if cache_diagnostics(config)['chunk_cache_prefetch_chunks'] > stalled:
                break
            time.sleep(0.02)
        assert cache_diagnostics(config)['chunk_cache_prefetch_chunks'] > stalled
    finally:
        pf.stop()
        pf.join()


def test_chunk_plan_covers_fused_dict_snappy_chunks(tmp_path):
    """ROADMAP PR 6 follow-up: the prefetcher's work list must include chunks
    only the FUSED kernel can decode from the mirror (dictionary/snappy), not
    just view-qualified ones — and their fetches ride the store's prefetch
    path, so they count under the existing ``chunk_cache_prefetch_*``
    counters the autotuner's prefetch knob watches."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from petastorm_tpu.chunkstore.reader import ChunkCachedParquetFile
    from petastorm_tpu.chunkstore.store import open_store
    path = tmp_path / 'dict_store'
    path.mkdir()
    table = pa.table({'x': pa.array((np.arange(64) % 8).astype(np.int64)),
                      'y': pa.array(np.linspace(0, 1, 64).astype(np.float64))})
    pq.write_table(table, str(path / 'f.parquet'), compression='snappy',
                   use_dictionary=True, row_group_size=32)
    config = ChunkCacheConfig(str(tmp_path / 'chunks'))
    fs = _mock_remote_fs_factory()
    pf = ChunkCachedParquetFile(str(path / 'f.parquet'), fs, config)
    # neither column view-qualifies (snappy + dictionary encoding) ...
    assert pf._qualifying(0, ['x', 'y']) == []
    # ... yet BOTH must be in the prefetcher's work list via the fused plan
    plan = pf.chunk_plan(0, ['x', 'y'])
    assert len(plan) == 2
    store = open_store(config)
    for key, length, fetch_fn in plan:
        _, _, fetched = store.ensure(key, length, fetch_fn, for_prefetch=True)
        assert fetched
    diag = cache_diagnostics(config)
    assert diag['chunk_cache_prefetch_chunks'] >= 2
    assert diag['chunk_cache_prefetch_bytes'] > 0
    # and the fused kernel decodes the warm mirror bit-exact
    block, rest = pf.read_fused(0, ['x', 'y'])
    assert rest == []
    np.testing.assert_array_equal(block['x'], (np.arange(32) % 8).astype(np.int64))
    np.testing.assert_array_equal(block['y'],
                                  np.linspace(0, 1, 64).astype(np.float64)[:32])
