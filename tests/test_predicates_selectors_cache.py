"""Unit tests for predicates, weighted sampling, and the disk cache."""

import numpy as np
import pytest

from petastorm_tpu.cache import NullCache
from petastorm_tpu.local_disk_cache import LocalDiskCache
from petastorm_tpu.predicates import (in_intersection, in_lambda, in_negate,
                                      in_pseudorandom_split, in_reduce, in_set)
from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader
from petastorm_tpu.errors import PetastormTpuError


class TestPredicates:
    def test_in_set(self):
        p = in_set({1, 2}, 'f')
        assert p.do_include({'f': 1}) and not p.do_include({'f': 3})
        assert p.get_fields() == {'f'}

    def test_in_intersection(self):
        p = in_intersection({1, 2}, 'f')
        assert p.do_include({'f': np.array([5, 2])})
        assert not p.do_include({'f': np.array([5, 6])})
        assert not p.do_include({'f': None})

    def test_in_negate_and_reduce(self):
        p = in_negate(in_set({1}, 'f'))
        assert p.do_include({'f': 2})
        both = in_reduce([in_set({1, 2}, 'f'), in_set({2, 3}, 'f')], all)
        assert both.do_include({'f': 2}) and not both.do_include({'f': 1})
        either = in_reduce([in_set({1}, 'f'), in_set({3}, 'f')], any)
        assert either.do_include({'f': 3})

    def test_in_lambda_with_state(self):
        seen = []
        p = in_lambda(['f'], lambda v, s: s.append(v['f']) or True, seen)
        assert p.do_include({'f': 9})
        assert seen == [9]

    def test_pseudorandom_split_deterministic(self):
        p = in_pseudorandom_split([0.3, 0.7], 0, 'f')
        r1 = [p.do_include({'f': i}) for i in range(100)]
        r2 = [p.do_include({'f': i}) for i in range(100)]
        assert r1 == r2
        assert 10 <= sum(r1) <= 60

    def test_pseudorandom_split_validation(self):
        with pytest.raises(ValueError):
            in_pseudorandom_split([0.5, 0.6], 0, 'f')
        with pytest.raises(ValueError):
            in_pseudorandom_split([0.5], 2, 'f')



class TestVectorizedPredicates:
    """do_include_batch must agree exactly with per-row do_include."""

    def _check(self, pred, block):
        import numpy as np
        from petastorm_tpu.columnar import block_to_rows
        batched = pred.do_include_batch(dict(block))
        per_row = [pred.do_include(r) for r in block_to_rows(dict(block))]
        if batched is None:
            return None
        assert np.asarray(batched, dtype=bool).tolist() == per_row
        return batched

    def test_in_set_batch(self):
        import numpy as np
        block = {'id': np.array([1, 5, 9, 5, 2])}
        out = self._check(in_set([5, 2], 'id'), block)
        assert out is not None and out.tolist() == [False, True, False, True, True]

    def test_in_set_batch_strings(self):
        import numpy as np
        col = np.array(['a', 'b', 'c', 'b'], dtype=object)
        out = self._check(in_set(['b'], 'name'), {'name': col})
        # either vectorized or declined; equality with per-row already asserted
        if out is not None:
            assert out.tolist() == [False, True, False, True]

    def test_negate_and_reduce_batch(self):
        import numpy as np
        block = {'a': np.array([1, 2, 3, 4]), 'b': np.array([10, 20, 30, 40])}
        p = in_reduce([in_set([1, 2], 'a'), in_negate(in_set([20], 'b'))], all)
        out = self._check(p, block)
        assert out is not None and out.tolist() == [True, False, False, False]
        p_any = in_reduce([in_set([1], 'a'), in_set([40], 'b')], any)
        out = self._check(p_any, block)
        assert out is not None and out.tolist() == [True, False, False, True]

    def test_composed_duck_typed_predicate_falls_back_to_row_path(self):
        # A user predicate with only do_include/get_fields (no do_include_batch)
        # must keep working when wrapped in in_negate / in_reduce (ADVICE r3).
        import numpy as np

        class RowOnly(object):
            def get_fields(self):
                return {'a'}

            def do_include(self, values):
                return values['a'] > 2

        block = {'a': np.array([1, 2, 3, 4])}
        assert in_negate(RowOnly()).do_include_batch(dict(block)) is None
        assert in_reduce([RowOnly(), in_set([1], 'a')], all).do_include_batch(dict(block)) is None
        # and the row path still composes correctly
        assert in_negate(RowOnly()).do_include({'a': 1}) is True
        assert in_reduce([RowOnly(), in_set([3], 'a')], all).do_include({'a': 3}) is True

    def test_reduce_custom_func_declines(self):
        import numpy as np
        block = {'a': np.array([1, 2])}
        p = in_reduce([in_set([1], 'a')], lambda bools: bools[0])
        assert p.do_include_batch(dict(block)) is None

    def test_pseudorandom_split_batch(self):
        import numpy as np
        block = {'k': np.array(['r%d' % i for i in range(50)], dtype=object)}
        p = in_pseudorandom_split([0.5, 0.5], 0, 'k')
        out = self._check(p, block)
        assert out is not None and 0 < out.sum() < 50

    def test_lambda_declines_batch(self):
        import numpy as np
        p = in_lambda(['x'], lambda v: v['x'] > 0)
        assert p.do_include_batch({'x': np.array([1, -1])}) is None

    def test_worker_pushdown_uses_batch_path(self, synthetic_dataset):
        from petastorm_tpu import make_reader

        class CountingInSet(in_set):
            calls = {'batch': 0, 'row': 0}

            def do_include_batch(self, block):
                CountingInSet.calls['batch'] += 1
                return super().do_include_batch(block)

            def do_include(self, values):
                CountingInSet.calls['row'] += 1
                return super().do_include(values)

        keep = {r['id'] for r in synthetic_dataset.data if r['id'] % 3 == 0}
        pred = CountingInSet(sorted(keep), 'id')
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         predicate=pred, shuffle_row_groups=False,
                         schema_fields=['id', 'id2']) as reader:
            got = {row.id for row in reader}
        assert got == keep
        assert CountingInSet.calls['batch'] > 0
        assert CountingInSet.calls['row'] == 0  # vectorized path served every row group


class TestLocalDiskCache:
    def test_read_through(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path))
        calls = []

        def fill():
            calls.append(1)
            return {'data': np.arange(5)}

        v1 = cache.get('k1', fill)
        v2 = cache.get('k1', fill)
        assert len(calls) == 1
        np.testing.assert_array_equal(v1['data'], v2['data'])

    def test_eviction_under_size_limit(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path), size_limit_bytes=50_000)
        for i in range(20):
            cache.get('key_{}'.format(i), lambda i=i: np.zeros(1000, dtype=np.float64))
        import os
        total = sum(os.path.getsize(os.path.join(dp, f))
                    for dp, _, fs in os.walk(str(tmp_path)) for f in fs)
        assert total <= 60_000  # bounded (some slack for in-flight entry)

    def test_cleanup(self, tmp_path):
        d = tmp_path / 'c'
        cache = LocalDiskCache(str(d), cleanup=True)
        cache.get('k', lambda: 1)
        cache.cleanup()
        assert not d.exists()

    def test_null_cache_never_stores(self):
        calls = []
        c = NullCache()
        c.get('k', lambda: calls.append(1))
        c.get('k', lambda: calls.append(1))
        assert len(calls) == 2

    def test_picklable(self, tmp_path):
        import pickle
        cache = LocalDiskCache(str(tmp_path))
        restored = pickle.loads(pickle.dumps(cache))
        assert restored.get('k', lambda: 7) == 7


class TestWeightedSampling:
    class FakeReader:
        def __init__(self, value, schema):
            self.value = value
            self.batched_output = False
            self.ngram = None
            self.transformed_schema = schema
            self.stopped = False

        def __next__(self):
            return self.value

        def stop(self):
            self.stopped = True

        def join(self):
            pass

    def _schema(self):
        from petastorm_tpu.codecs import ScalarCodec
        from petastorm_tpu.unischema import Unischema, UnischemaField
        return Unischema('S', [UnischemaField('x', np.int64, (), ScalarCodec(), False)])

    def test_mixing_ratio(self):
        schema = self._schema()
        readers = [self.FakeReader('a', schema), self.FakeReader('b', schema)]
        mixed = WeightedSamplingReader(readers, [0.8, 0.2], seed=0)
        out = [next(mixed) for _ in range(1000)]
        frac_a = out.count('a') / 1000
        assert 0.75 < frac_a < 0.85

    def test_mismatched_schema_rejected(self):
        from petastorm_tpu.codecs import ScalarCodec
        from petastorm_tpu.unischema import Unischema, UnischemaField
        s1 = self._schema()
        s2 = Unischema('S2', [UnischemaField('y', np.int64, (), ScalarCodec(), False)])
        with pytest.raises(PetastormTpuError):
            WeightedSamplingReader([self.FakeReader('a', s1), self.FakeReader('b', s2)],
                                   [0.5, 0.5])

    def test_stop_propagates(self):
        schema = self._schema()
        readers = [self.FakeReader('a', schema), self.FakeReader('b', schema)]
        mixed = WeightedSamplingReader(readers, [0.5, 0.5])
        mixed.stop(); mixed.join()
        assert all(r.stopped for r in readers)


def test_weighted_sampling_end_to_end(synthetic_dataset):
    from petastorm_tpu import make_reader
    r1 = make_reader(synthetic_dataset.url, reader_pool_type='dummy', num_epochs=None,
                     schema_fields=['id'], predicate=None, shuffle_row_groups=False)
    r2 = make_reader(synthetic_dataset.url, reader_pool_type='dummy', num_epochs=None,
                     schema_fields=['id'], shuffle_row_groups=False)
    mixed = WeightedSamplingReader([r1, r2], [0.5, 0.5], seed=1)
    rows = [next(mixed) for _ in range(50)]
    assert len(rows) == 50
    mixed.stop(); mixed.join()


def test_native_clauses_decline_on_overridden_semantics():
    """A subclass that overrides do_include/do_include_batch changed the
    predicate's meaning — the inherited native_clauses must decline so the
    fused pushdown never evaluates the BASE semantics below the GIL."""
    from petastorm_tpu.predicates import in_range

    class RowOverride(in_set):
        def do_include(self, values):
            return True

    class BatchOverride(in_range):
        def do_include_batch(self, block):
            return None

    class PlainSub(in_set):
        pass

    assert RowOverride([1], 'x').native_clauses() is None
    assert BatchOverride('x', lo=0).native_clauses() is None
    # wrappers around an overridden inner predicate decline transitively
    assert in_negate(RowOverride([1], 'x')).native_clauses() is None
    assert in_reduce([RowOverride([1], 'x')], all).native_clauses() is None
    # an overridden WRAPPER declines even over a clean inner predicate
    class NegOverride(in_negate):
        def do_include(self, values):
            return True
    assert NegOverride(in_set([1], 'x')).native_clauses() is None
    # a subclass that overrides neither keeps the native path
    assert PlainSub([1], 'x').native_clauses() is not None


def test_in_set_mixed_type_values_keep_row_semantics():
    # np.isin silently coerces ['a', 1] to unicode and stops matching ints;
    # the batched path must decline so per-row semantics win
    pred = in_set(['a', 1], 'x')
    col = np.array([1, 2, 3])
    assert pred.do_include_batch({'x': col}) is None
    assert pred.do_include({'x': 1}) is True


@pytest.mark.filterwarnings('ignore::pytest.PytestUnhandledThreadExceptionWarning')
def test_do_include_batch_scalar_return_fails_loudly(synthetic_dataset):
    # the DummyPool ventilator thread re-raises after forwarding the error to
    # the consumer; that secondary raise is expected noise here
    from petastorm_tpu import make_reader

    class BadPredicate(in_set):
        def do_include_batch(self, block):
            return np.True_  # 0-d: a buggy reduction

    with pytest.raises(ValueError, match='1-D mask'):
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         predicate=BadPredicate([1], 'id'), shuffle_row_groups=False,
                         schema_fields=['id']) as reader:
            next(iter(reader))


def test_batch_reader_pushdown_uses_batch_path(scalar_dataset):
    from petastorm_tpu import make_batch_reader

    class CountingInSet(in_set):
        calls = {'batch': 0, 'row': 0}

        def do_include_batch(self, block):
            CountingInSet.calls['batch'] += 1
            return super().do_include_batch(block)

        def do_include(self, values):
            CountingInSet.calls['row'] += 1
            return super().do_include(values)

    keep = {r['id'] for r in scalar_dataset.data if r['id'] % 2 == 0}
    pred = CountingInSet(sorted(keep), 'id')
    got = set()
    with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                           predicate=pred, shuffle_row_groups=False) as reader:
        for batch in reader:
            got.update(np.asarray(batch.id).tolist())
    assert got == keep
    assert CountingInSet.calls['batch'] > 0
    assert CountingInSet.calls['row'] == 0


def test_in_intersection_batch_uniform_and_ragged():
    from petastorm_tpu.columnar import block_to_rows
    pred = in_intersection([3, 7], 'arr')
    # uniform stacked [N, 2] cells
    uni = {'arr': np.array([[1, 3], [4, 5], [7, 7], [2, 9]])}
    out = pred.do_include_batch(dict(uni))
    assert out.tolist() == [True, False, True, False]
    assert out.tolist() == [pred.do_include(r) for r in block_to_rows(dict(uni))]
    # ragged object cells incl. None
    ragged = np.empty(4, dtype=object)
    ragged[0] = np.array([1, 2, 3])
    ragged[1] = np.array([5])
    ragged[2] = None
    ragged[3] = np.array([[7, 1], [2, 2]])  # 2-D cell: .flat semantics
    block = {'arr': ragged}
    out = pred.do_include_batch(dict(block))
    assert out.tolist() == [True, False, False, True]
    assert out.tolist() == [pred.do_include(r) for r in block_to_rows(dict(block))]
    # mixed-type inclusion values decline on uniform numeric columns
    assert in_intersection(['a', 1], 'arr').do_include_batch(dict(uni)) is None
