"""Ventilator tests (modeled on reference workers_pool/tests/test_ventilator.py)."""

import threading
import time

import pytest

from petastorm_tpu.test_util.stub_workers import IdentityWorker
from petastorm_tpu.workers import ConcurrentVentilator, EmptyResultError, ThreadPool


def _drain(pool, limit=None):
    results = []
    while limit is None or len(results) < limit:
        try:
            results.append(pool.get_results())
        except EmptyResultError:
            break
    return results


def test_ventilator_feeds_all_items():
    pool = ThreadPool(2)
    items = [{'value': i} for i in range(40)]
    vent = ConcurrentVentilator(pool.ventilate, items)
    pool.start(IdentityWorker, ventilator=vent)
    assert sorted(_drain(pool)) == list(range(40))
    pool.stop(); pool.join()


def test_bounded_in_flight():
    """Ventilator never exceeds max in-flight items (reference :51)."""
    observed_max = [0]
    in_flight = [0]
    lock = threading.Lock()

    class TrackingPool(ThreadPool):
        def ventilate(self, *args, **kwargs):
            with lock:
                in_flight[0] += 1
                observed_max[0] = max(observed_max[0], in_flight[0])
            super().ventilate(*args, **kwargs)

    pool = TrackingPool(2)
    items = [{'value': i} for i in range(50)]
    vent = ConcurrentVentilator(pool.ventilate, items, max_ventilation_queue_size=5)

    class CountingWorker(IdentityWorker):
        def process(self, value):
            with lock:
                in_flight[0] -= 1
            self.publish(value)

    pool.start(CountingWorker, ventilator=vent)
    results = _drain(pool)
    assert len(results) == 50
    assert observed_max[0] <= 5 + 2  # small slack: decrement happens at process start
    pool.stop(); pool.join()


def test_multiple_iterations():
    pool = ThreadPool(2)
    items = [{'value': i} for i in range(10)]
    vent = ConcurrentVentilator(pool.ventilate, items, iterations=3)
    pool.start(IdentityWorker, ventilator=vent)
    results = _drain(pool)
    assert len(results) == 30
    assert sorted(results) == sorted(list(range(10)) * 3)
    pool.stop(); pool.join()


def test_infinite_iterations_and_stop():
    pool = ThreadPool(2)
    items = [{'value': i} for i in range(5)]
    vent = ConcurrentVentilator(pool.ventilate, items, iterations=None,
                                max_ventilation_queue_size=10)
    pool.start(IdentityWorker, ventilator=vent)
    got = _drain(pool, limit=50)
    assert len(got) == 50
    pool.stop()
    pool.join()


def test_randomized_order_seeded_reproducible():
    orders = []
    for _ in range(2):
        order = []
        vent = ConcurrentVentilator(lambda value: order.append(value),
                                    [{'value': i} for i in range(100)],
                                    randomize_item_order=True, random_seed=7)
        # feed synchronously: report processed as soon as ventilated
        vent.processed_item = lambda: None
        vent.start()
        while not vent.completed():
            time.sleep(0.01)
        orders.append(order)
    assert orders[0] == orders[1]
    assert orders[0] != sorted(orders[0])


def test_unseeded_orders_differ():
    orders = []
    for _ in range(2):
        order = []
        vent = ConcurrentVentilator(lambda value: order.append(value),
                                    [{'value': i} for i in range(100)],
                                    randomize_item_order=True)
        vent.start()
        while not vent.completed():
            time.sleep(0.01)
        orders.append(order)
    assert orders[0] != orders[1]


def test_reset_replays_items():
    pool = ThreadPool(2)
    items = [{'value': i} for i in range(10)]
    vent = ConcurrentVentilator(pool.ventilate, items)
    pool.start(IdentityWorker, ventilator=vent)
    first = _drain(pool)
    assert sorted(first) == list(range(10))
    vent.reset()
    second = _drain(pool)
    assert sorted(second) == list(range(10))
    pool.stop(); pool.join()


def test_reset_while_running_raises():
    vent = ConcurrentVentilator(lambda value: time.sleep(0.001),
                                [{'value': i} for i in range(1000)],
                                max_ventilation_queue_size=1)
    vent.start()
    with pytest.raises(RuntimeError):
        vent.reset()
    vent.stop()


def test_bad_iterations_rejected():
    with pytest.raises(ValueError):
        ConcurrentVentilator(lambda: None, [], iterations=0)
    with pytest.raises(ValueError):
        ConcurrentVentilator(lambda: None, [], iterations=-1)
