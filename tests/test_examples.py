"""End-to-end runs of the examples tree (reference examples/*/tests)."""


import os

import numpy as np
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.jax import JaxDataLoader


@pytest.fixture(scope='module')
def hello_world_url(tmp_path_factory):
    from examples.hello_world.petastorm_dataset.generate_petastorm_dataset import \
        generate_petastorm_dataset
    path = tmp_path_factory.mktemp('hello_world_ds')
    url = 'file://' + str(path)
    generate_petastorm_dataset(url, rows_count=10)
    return url


def test_hello_world_python_read(hello_world_url):
    with make_reader(hello_world_url) as reader:
        rows = list(reader)
    assert sorted(r.id for r in rows) == list(range(10))
    assert rows[0].image1.shape == (128, 256, 3)
    assert rows[0].array_4d.shape[1:3] == (128, 30)


def test_hello_world_jax_read(hello_world_url):
    import jax
    with make_reader(hello_world_url, schema_fields=['id', 'image1']) as reader:
        loader = JaxDataLoader(reader, batch_size=4, drop_last=False,
                               to_device=jax.devices()[0])
        batches = list(loader)
    assert sum(b['id'].shape[0] for b in batches) == 10
    assert batches[0]['image1'].shape[1:] == (128, 256, 3)


def test_hello_world_pytorch_read(hello_world_url):
    from examples.hello_world.petastorm_dataset.pytorch_hello_world import \
        pytorch_hello_world
    pytorch_hello_world(hello_world_url)


def test_hello_world_tensorflow_read(hello_world_url):
    pytest.importorskip('tensorflow')
    from examples.hello_world.petastorm_dataset.tensorflow_hello_world import \
        tensorflow_hello_world
    tensorflow_hello_world(hello_world_url)


@pytest.fixture(scope='module')
def external_dataset_url(tmp_path_factory):
    from examples.hello_world.external_dataset.generate_external_dataset import \
        generate_external_dataset
    url = 'file://' + str(tmp_path_factory.mktemp('ext_ds'))
    generate_external_dataset(url, rows_count=50)
    return url


def test_external_dataset_roundtrip(external_dataset_url):
    with make_batch_reader(external_dataset_url) as reader:
        ids = np.concatenate([batch.id for batch in reader])
    assert sorted(ids.tolist()) == list(range(50))


def test_external_dataset_tensorflow_read(external_dataset_url):
    pytest.importorskip('tensorflow')
    from examples.hello_world.external_dataset.tensorflow_hello_world import \
        tensorflow_hello_world
    tensorflow_hello_world(external_dataset_url)


def test_external_dataset_pytorch_read(external_dataset_url):
    from examples.hello_world.external_dataset.pytorch_hello_world import \
        pytorch_hello_world
    pytorch_hello_world(external_dataset_url)


@pytest.fixture(scope='module')
def mnist_url(tmp_path_factory):
    from examples.mnist.generate_petastorm_mnist import mnist_data_to_petastorm_dataset
    path = tmp_path_factory.mktemp('mnist_ds')
    url = 'file://' + str(path)
    mnist_data_to_petastorm_dataset(url, train_rows=96, test_rows=32,
                                    rows_per_row_group=32)
    return url


def test_mnist_jax_training(mnist_url):
    from examples.mnist.jax_example import train_and_test
    state = train_and_test(mnist_url, batch_size=16, epochs=1, lr=0.05)
    assert state.step > 0


def test_mnist_pytorch_training(mnist_url):
    from examples.mnist.pytorch_example import train_and_test
    train_and_test(mnist_url, batch_size=16, epochs=1)


def test_mnist_tf_training(mnist_url):
    pytest.importorskip('tensorflow')
    from examples.mnist.tf_example import train_and_test
    acc = train_and_test(mnist_url, training_iterations=6, batch_size=16,
                         evaluation_interval=6, shuffle_buffer_size=64)
    assert 0.0 <= acc <= 1.0


def test_imagenet_synthetic_generate_and_read(tmp_path):
    import jax
    import jax.numpy as jnp
    from examples.imagenet.generate_petastorm_imagenet import generate_synthetic_imagenet
    from examples.imagenet.jax_resnet_example import device_preprocess, make_transform
    url = 'file://' + str(tmp_path / 'imagenet')
    generate_synthetic_imagenet(url, num_synsets=2, images_per_synset=4)
    with make_reader(url, transform_spec=make_transform(32, 16), num_epochs=1) as reader:
        loader = JaxDataLoader(reader, batch_size=4, drop_last=False)
        batches = list(loader)
    total = sum(b['image'].shape[0] for b in batches)
    assert total == 8
    assert batches[0]['image'].shape[1:] == (32, 32, 3)
    # host ships compact uint8; cast/normalize/augment happen on device
    assert batches[0]['image'].dtype == np.uint8
    processed = device_preprocess(batches[0]['image'], jax.random.key(0))
    assert processed.dtype == jnp.bfloat16
    assert processed.shape == batches[0]['image'].shape
    assert all(0 <= l < 16 for b in batches for l in np.atleast_1d(b['label']))


def test_imagenet_directory_ingest(tmp_path):
    import cv2
    from examples.imagenet.generate_petastorm_imagenet import \
        imagenet_directory_to_petastorm_dataset
    root = tmp_path / 'raw'
    rng = np.random.default_rng(0)
    for synset in ('n001', 'n002'):
        d = root / synset
        d.mkdir(parents=True)
        for i in range(3):
            img = rng.integers(0, 255, (40, 50, 3), dtype=np.uint8)
            cv2.imwrite(str(d / 'img_{}.png'.format(i)), img)
    url = 'file://' + str(tmp_path / 'imagenet_real')
    imagenet_directory_to_petastorm_dataset(str(root), url)
    with make_reader(url, num_epochs=1) as reader:
        rows = list(reader)
    assert len(rows) == 6
    assert {r.noun_id for r in rows} == {'n001', 'n002'}
    assert rows[0].image.shape == (40, 50, 3)


@pytest.mark.parametrize('context', ['ring', 'ulysses'])
def test_sequence_example_end_to_end(tmp_path, context):
    """Long-context example: telemetry store -> columnar NGram -> context-
    parallel transformer training steps on the virtual mesh, under both
    strategies."""
    from examples.sequence.generate_petastorm_sequence import generate_sequence_dataset
    from examples.sequence.jax_sequence_example import train
    url = 'file://' + str(tmp_path / 'seq')
    generate_sequence_dataset(url, rows=512, rows_per_row_group=64)
    state = train(url, steps=4, batch_size=8, window=4, context=context)
    assert int(state.step) == 4


def test_hello_world_pyspark_read(hello_world_url):
    # runs against real pyspark when importable, else the minispark engine —
    # executed in a subprocess so minispark.install() never touches this
    # process's sys.modules
    import subprocess
    import sys
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    # `python script.py` puts the SCRIPT's dir on sys.path, not the cwd: the
    # repo root must ride PYTHONPATH for uninstalled (source-tree) runs
    env['PYTHONPATH'] = root + os.pathsep + env.get('PYTHONPATH', '')
    out = subprocess.run(
        [sys.executable, 'examples/hello_world/petastorm_dataset/pyspark_hello_world.py',
         '--dataset-url', hello_world_url],
        capture_output=True, text=True, timeout=300, cwd=root, env=env)
    assert out.returncode == 0, out.stderr[-800:]
    assert 'total rows: 10' in out.stdout


def test_mnist_resume_example_continues_after_crash(mnist_url, tmp_path):
    """Joint model+data checkpointing: a 'crashed' run resumes from the latest
    complete checkpoint and continues to the target step count; resuming twice
    from the same checkpoint is deterministic."""
    import jax
    from examples.mnist.resume_example import _latest, train_with_checkpointing

    ckpt = str(tmp_path / 'ckpt')
    # dummy pool: deterministic delivery order, so resumed streams replay
    # bitwise (multi-worker pools guarantee coverage, not order)
    kw = dict(checkpoint_every=2, batch_size=16, reader_pool_type='dummy')
    # phase 1: train to step 4, checkpointing every 2 — simulates dying at 4
    state = train_with_checkpointing(mnist_url, ckpt, total_steps=4, **kw)
    assert int(state.step) == 4
    assert _latest(ckpt) is not None and _latest(ckpt).endswith('step_00000004')

    # phase 2: "restart the job" with a higher target — resumes, not restarts
    state2 = train_with_checkpointing(mnist_url, ckpt, total_steps=6, **kw)
    assert int(state2.step) == 6

    # determinism: two independent resumes from the same checkpoint agree
    import shutil
    for name in os.listdir(ckpt):
        if name > 'step_00000004':
            shutil.rmtree(os.path.join(ckpt, name))
    a = train_with_checkpointing(mnist_url, ckpt, total_steps=6, **kw)
    for name in os.listdir(ckpt):
        if name > 'step_00000004':
            shutil.rmtree(os.path.join(ckpt, name))
    b = train_with_checkpointing(mnist_url, ckpt, total_steps=6, **kw)
    import numpy as np_mod
    la = jax.tree_util.tree_leaves(a.params)
    lb = jax.tree_util.tree_leaves(b.params)
    for x, y in zip(la, lb):
        np_mod.testing.assert_array_equal(np_mod.asarray(x), np_mod.asarray(y))


def test_mnist_resume_recovers_from_crash_inside_save(mnist_url, tmp_path):
    """A crash BETWEEN the orbax save and the DONE marker leaves a stale
    markerless step dir; the next run must sweep it and save over it instead
    of crash-looping on orbax's existing-destination refusal."""
    from examples.mnist.resume_example import train_with_checkpointing

    ckpt = str(tmp_path / 'ckpt')
    train_with_checkpointing(mnist_url, ckpt, total_steps=2,
                             checkpoint_every=2, batch_size=16)
    # simulate the partial save: a future step dir with train_state but no DONE
    stale = os.path.join(ckpt, 'step_00000004')
    os.makedirs(os.path.join(stale, 'train_state'))
    state = train_with_checkpointing(mnist_url, ckpt, total_steps=4,
                                     checkpoint_every=2, batch_size=16)
    assert int(state.step) == 4
    assert os.path.exists(os.path.join(stale, 'DONE'))
