"""Sequence data plane: ragged collation, bucketed batching, token packing,
hot-swappable mixtures, tail-following ingest (docs/sequence.md)."""

import pickle
import threading
import time

import numpy as np
import pytest

from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.etl.dataset_metadata import DatasetWriter, materialize_dataset
from petastorm_tpu.reader import make_reader
from petastorm_tpu.sequence import (BucketBatchBuffer, CollateSpec, MixtureReader,
                                    MixtureSchedule, PackedSequenceLoader, PadSpec,
                                    TailFollowingReader, collate_ragged_rows,
                                    first_fit_decreasing, latest_snapshot,
                                    list_snapshots, pack_rows, padded_length,
                                    publish_snapshot)
from petastorm_tpu.unischema import Unischema, UnischemaField

TokenSchema = Unischema('TokenSchema', [
    UnischemaField('id', np.int64, (), ScalarCodec(), False),
    UnischemaField('tokens', np.int32, (None,), NdarrayCodec(), False),
])


def _token_rows(num_rows, seed=7, max_len=64):
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(num_rows):
        # zipf-ish mix: mostly short rows, a heavy tail
        n = int(min(rng.zipf(1.6), max_len))
        rows.append({'id': i, 'tokens': rng.integers(0, 1000, n, dtype=np.int32)})
    return rows


def _write_token_dataset(path, num_rows=60, rows_per_row_group=10, seed=7,
                         id_offset=0):
    url = 'file://' + str(path)
    rows = _token_rows(num_rows, seed=seed)
    for r in rows:
        r['id'] += id_offset
    with materialize_dataset(url, TokenSchema,
                             rows_per_row_group=rows_per_row_group) as writer:
        for row in rows:
            writer.write(row)
    return url, rows


@pytest.fixture(scope='module')
def token_dataset(tmp_path_factory):
    path = tmp_path_factory.mktemp('token_ds')
    url, rows = _write_token_dataset(path, num_rows=60)
    return url, rows


def _token_reader(url, **kwargs):
    kwargs.setdefault('reader_pool_type', 'dummy')
    kwargs.setdefault('shuffle_row_groups', False)
    return make_reader(url, **kwargs)


# -- padded_length / collate_ragged_rows ------------------------------------

def test_padded_length_rounding_and_buckets():
    assert padded_length(5, PadSpec(pad_to=8)) == 8
    assert padded_length(8, PadSpec(pad_to=8)) == 8
    assert padded_length(9, PadSpec(pad_to=8)) == 16
    assert padded_length(3, PadSpec(buckets=(4, 16, 64))) == 4
    assert padded_length(17, PadSpec(buckets=(4, 16, 64))) == 64
    # past the ladder: pad_to rounding (default 1) takes over
    assert padded_length(65, PadSpec(buckets=(4, 16, 64))) == 65
    assert padded_length(100, PadSpec(pad_to=8, max_length=32)) == 32
    assert padded_length(0, PadSpec(pad_to=1)) == 1


def test_collate_ragged_rows_pads_and_reports_waste():
    rows = [{'id': i, 'tokens': np.arange(n, dtype=np.int32)}
            for i, n in enumerate([3, 5, 2])]
    spec = CollateSpec({'tokens': PadSpec(pad_to=4, pad_value=-1)})
    stats = {'real_tokens': 0, 'padded_tokens': 0}
    batch = collate_ragged_rows(rows, spec, stats)
    assert batch['tokens'].shape == (3, 8)  # max len 5 -> pad_to 4 -> 8
    assert batch['tokens'].dtype == np.int32
    assert list(batch['tokens_lengths']) == [3, 5, 2]
    assert batch['id'].tolist() == [0, 1, 2]
    np.testing.assert_array_equal(batch['tokens'][0], [0, 1, 2, -1, -1, -1, -1, -1])
    assert stats['real_tokens'] == 10
    assert stats['padded_tokens'] == 24


def test_collate_ragged_rows_truncates_at_max_length():
    rows = [{'tokens': np.arange(n, dtype=np.int32)} for n in (2, 9)]
    spec = CollateSpec({'tokens': PadSpec(pad_to=1, max_length=4)})
    batch = collate_ragged_rows(rows, spec)
    assert batch['tokens'].shape == (2, 4)
    assert list(batch['tokens_lengths']) == [2, 4]
    np.testing.assert_array_equal(batch['tokens'][1], [0, 1, 2, 3])


def test_collate_rows_error_points_at_collate_spec(token_dataset):
    from petastorm_tpu.jax.loader import collate_rows
    rows = [{'tokens': np.arange(3)}, {'tokens': np.arange(5)}]
    with pytest.raises(PetastormTpuError, match='collate_spec=CollateSpec'):
        collate_rows(rows)


# -- loader integration ------------------------------------------------------

def test_loader_ragged_collation_end_to_end(token_dataset):
    from petastorm_tpu.jax import JaxDataLoader
    url, rows = token_dataset
    by_id = {r['id']: r for r in rows}
    spec = CollateSpec({'tokens': PadSpec(pad_to=8)})
    with _token_reader(url) as reader:
        loader = JaxDataLoader(reader, batch_size=10, drop_last=False,
                               collate_spec=spec)
        seen = 0
        for batch in loader:
            lengths = batch['tokens_lengths']
            assert batch['tokens'].shape[1] % 8 == 0
            assert batch['tokens'].shape[1] >= int(lengths.max())
            for row_id, length, padded in zip(batch['id'], lengths, batch['tokens']):
                np.testing.assert_array_equal(
                    padded[:length], by_id[int(row_id)]['tokens'])
                assert not padded[length:].any()  # pad_value 0
                seen += 1
        assert seen == len(rows)
        waste = loader.diagnostics['padding_waste_fraction']
        assert 0.0 < waste < 1.0


def test_loader_diagnostics_carry_padding_waste_key(token_dataset):
    from petastorm_tpu.jax import JaxDataLoader
    url, _ = token_dataset
    with _token_reader(url) as reader:
        loader = JaxDataLoader(reader, batch_size=10)
        # key-set-always-present contract, zero before iteration
        assert loader.diagnostics['padding_waste_fraction'] == 0.0


def test_loader_collate_spec_rejects_columnar(token_dataset):
    from petastorm_tpu.jax import JaxDataLoader
    url, _ = token_dataset
    with _token_reader(url, output='columnar') as reader:
        with pytest.raises(ValueError, match='row-oriented'):
            JaxDataLoader(reader, batch_size=10,
                          collate_spec=CollateSpec({'tokens': PadSpec(pad_to=8)}))


def test_loader_bucket_boundaries_require_collate_spec(token_dataset):
    from petastorm_tpu.jax import JaxDataLoader
    url, _ = token_dataset
    with _token_reader(url) as reader:
        with pytest.raises(ValueError, match='collate_spec'):
            JaxDataLoader(reader, batch_size=10, bucket_boundaries=(8, 32))
        with pytest.raises(ValueError, match='shuffling buffer'):
            JaxDataLoader(reader, batch_size=10, shuffling_queue_capacity=20,
                          collate_spec=CollateSpec({'tokens': PadSpec(pad_to=8)}),
                          bucket_boundaries=(8, 32))


def _bucketed_batches(url, seed, limit=None):
    from petastorm_tpu.jax import JaxDataLoader
    spec = CollateSpec({'tokens': PadSpec(buckets=(4, 8, 16, 64))})
    batches = []
    with _token_reader(url, seed=seed) as reader:
        loader = JaxDataLoader(reader, batch_size=5, drop_last=False, seed=seed,
                               collate_spec=spec, bucket_boundaries=(4, 8, 16, 64))
        for batch in loader:
            batches.append(batch)
            if limit is not None and len(batches) >= limit:
                break
    return batches


def test_bucketed_batching_groups_by_length_and_is_deterministic(token_dataset):
    url, rows = token_dataset
    first = _bucketed_batches(url, seed=21)
    again = _bucketed_batches(url, seed=21)
    assert len(first) == len(again)
    for a, b in zip(first, again):
        np.testing.assert_array_equal(a['id'], b['id'])
        np.testing.assert_array_equal(a['tokens'], b['tokens'])
    # full batches released from a filled bucket span one bucket each; the
    # boundary ladder means their padded width is the bucket boundary
    boundaries = (4, 8, 16, 64)
    full = [b for b in first if len(b['id']) == 5]
    assert full, 'expected at least one full bucket release'
    for batch in full[:len(full) - len(boundaries)]:
        assert batch['tokens'].shape[1] in boundaries
    # every row is delivered exactly once
    delivered = [int(i) for b in first for i in b['id']]
    assert sorted(delivered) == [r['id'] for r in rows]


def test_bucketed_batching_checkpoint_resume(token_dataset):
    from petastorm_tpu.jax import JaxDataLoader
    url, rows = token_dataset
    spec = CollateSpec({'tokens': PadSpec(buckets=(4, 8, 16, 64))})

    def build(resume=None, reader_state=None):
        reader = _token_reader(url, seed=33, resume_state=reader_state)
        loader = JaxDataLoader(reader, batch_size=5, drop_last=False, seed=33,
                               collate_spec=spec, bucket_boundaries=(4, 8, 16, 64),
                               resume_state=resume)
        return reader, loader

    reader, loader = build()
    it = iter(loader)
    first = [int(i) for _ in range(4) for i in next(it)['id']]
    state = pickle.loads(pickle.dumps(loader.state_dict()))
    reader.stop(); reader.join()

    reader2, resumed = build(resume=state, reader_state=state['reader'])
    rest = [int(i) for b in resumed for i in b['id']]
    reader2.stop(); reader2.join()

    combined = first + rest
    all_ids = {r['id'] for r in rows}
    assert set(combined) == all_ids
    # dupes only from the row group partially pulled out of the reader
    dupes = [i for i in all_ids if combined.count(i) > 1]
    assert len(dupes) <= 10, sorted(dupes)


def test_bucket_buffer_rejects_bad_args():
    with pytest.raises(ValueError):
        BucketBatchBuffer((), 4, 'tokens')
    with pytest.raises(ValueError):
        BucketBatchBuffer((4, 8), 0, 'tokens')


# -- packing -----------------------------------------------------------------

def test_first_fit_decreasing_respects_capacity():
    lengths = [7, 2, 5, 5, 3, 1]
    bins = first_fit_decreasing(lengths, capacity=8)
    flat = sorted(i for b in bins for i in b)
    assert flat == list(range(len(lengths)))
    for b in bins:
        assert sum(lengths[i] for i in b) <= 8
    with pytest.raises(PetastormTpuError, match='exceeds tokens_per_batch'):
        first_fit_decreasing([9], capacity=8)


def test_pack_rows_segments_and_positions():
    rows = [{'tokens': np.arange(n, dtype=np.int32) + 10 * n} for n in (5, 3, 4)]
    batch, stats = pack_rows(rows, tokens_per_batch=8, sequence_fields=['tokens'])
    # FFD order: 5 then 4 won't fit slot 0 (5+4>8) -> new slot; 3 joins slot 0
    assert batch['tokens'].shape == (2, 8)
    np.testing.assert_array_equal(batch['segment_ids'][0], [1, 1, 1, 1, 1, 2, 2, 2])
    np.testing.assert_array_equal(batch['positions'][0], [0, 1, 2, 3, 4, 0, 1, 2])
    np.testing.assert_array_equal(batch['segment_ids'][1], [1, 1, 1, 1, 0, 0, 0, 0])
    assert batch['num_segments'].tolist() == [2, 1]
    assert stats['real_tokens'] == 12
    assert stats['slot_tokens'] == 16
    assert stats['packing_efficiency'] == 0.75


def test_packed_sequence_loader_delivers_all_tokens(token_dataset):
    url, rows = token_dataset
    total_real = sum(len(r['tokens']) for r in rows)
    with _token_reader(url) as reader:
        loader = PackedSequenceLoader(reader, tokens_per_batch=64,
                                      sequence_fields=['tokens'],
                                      slots_per_batch=4, pool_rows=32)
        delivered = 0
        for batch in loader:
            mask = batch['segment_ids'] > 0
            delivered += int(mask.sum())
            assert batch['tokens'].shape[1] == 64
        assert delivered == total_real
        assert loader.packing_efficiency > 0.5
        diag = loader.diagnostics
        assert diag['packed_real_tokens'] == total_real
        assert diag['packed_batches'] > 0


def test_packed_sequence_loader_deterministic(token_dataset):
    url, _ = token_dataset

    def run():
        out = []
        with _token_reader(url) as reader:
            loader = PackedSequenceLoader(reader, tokens_per_batch=64,
                                          sequence_fields=['tokens'],
                                          slots_per_batch=4, pool_rows=32)
            for batch in loader:
                out.append(batch['tokens'].copy())
        return out

    a, b = run(), run()
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_packed_sequence_loader_checkpoint_roundtrip(token_dataset):
    url, _ = token_dataset
    with _token_reader(url) as reader:
        loader = PackedSequenceLoader(reader, tokens_per_batch=64,
                                      sequence_fields=['tokens'],
                                      slots_per_batch=2, pool_rows=16)
        it = iter(loader)
        next(it)
        state = pickle.loads(pickle.dumps(loader.state_dict()))
    assert state['version'] == 1
    assert isinstance(state['rows'], list)
    with _token_reader(url, resume_state=state['reader']) as reader2:
        resumed = PackedSequenceLoader(reader2, tokens_per_batch=64,
                                       sequence_fields=['tokens'],
                                       slots_per_batch=2, pool_rows=16,
                                       resume_state=state)
        batches = list(resumed)
        assert batches  # pooled rows + remaining stream keep flowing


# -- mixtures ----------------------------------------------------------------

def _two_source_urls(tmp_path_factory):
    p1 = tmp_path_factory.mktemp('mix_a')
    p2 = tmp_path_factory.mktemp('mix_b')
    url_a, rows_a = _write_token_dataset(p1, num_rows=40, seed=1)
    url_b, rows_b = _write_token_dataset(p2, num_rows=10, seed=2, id_offset=1000)
    return (url_a, rows_a), (url_b, rows_b)


@pytest.fixture(scope='module')
def mixture_sources(tmp_path_factory):
    return _two_source_urls(tmp_path_factory)


def test_weighted_sampling_renormalizes_after_exhaustion(mixture_sources):
    # regression: one dry source used to end the WHOLE mixture, silently
    # truncating every longer source
    (url_a, rows_a), (url_b, rows_b) = mixture_sources
    with _token_reader(url_a) as ra, _token_reader(url_b) as rb:
        mixed = MixtureReader([ra, rb], weights=[0.5, 0.5], seed=17)
        ids = [int(r.id) for r in mixed]
    assert len(ids) == len(rows_a) + len(rows_b)
    assert {i for i in ids if i >= 1000} == {r['id'] for r in rows_b}
    assert mixed.diagnostics['mixture_source_1_exhausted'] == 1


def test_weighted_sampling_stop_policy_preserves_reference_behavior(mixture_sources):
    from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader
    (url_a, rows_a), (url_b, rows_b) = mixture_sources
    with _token_reader(url_a) as ra, _token_reader(url_b) as rb:
        mixed = WeightedSamplingReader([ra, rb], [0.5, 0.5], seed=17,
                                       on_exhausted='stop')
        ids = [int(r.id) for r in mixed]
    assert len(ids) < len(rows_a) + len(rows_b)
    assert mixed.last_row_consumed


def test_weighted_sampling_rejects_bad_policy(mixture_sources):
    from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader
    (url_a, _), (url_b, _) = mixture_sources
    with _token_reader(url_a) as ra, _token_reader(url_b) as rb:
        with pytest.raises(PetastormTpuError, match='on_exhausted'):
            WeightedSamplingReader([ra, rb], [1, 1], on_exhausted='ignore')


def test_mixture_set_weights_live_and_validated(mixture_sources):
    (url_a, _), (url_b, _) = mixture_sources
    with _token_reader(url_a, num_epochs=None) as ra, \
            _token_reader(url_b, num_epochs=None) as rb:
        mixed = MixtureReader([ra, rb], weights=[1, 0], seed=5,
                              token_field='tokens')
        for _ in range(20):
            next(mixed)
        assert mixed.diagnostics['mixture_source_1_rows'] == 0
        mixed.set_weights([0, 1])
        for _ in range(20):
            next(mixed)
        diag = mixed.diagnostics
        assert diag['mixture_source_0_rows'] == 20
        assert diag['mixture_source_1_rows'] == 20
        assert diag['mixture_source_1_tokens'] > 0
        assert diag['mixture_weight_updates'] == 1
        with pytest.raises(PetastormTpuError):
            mixed.set_weights([1])  # wrong arity
        with pytest.raises(PetastormTpuError):
            mixed.set_weights([-1, 2])
        mixed.stop(); mixed.join()


def test_mixture_determinism_under_seed(mixture_sources):
    (url_a, _), (url_b, _) = mixture_sources

    def run():
        with _token_reader(url_a) as ra, _token_reader(url_b) as rb:
            mixed = MixtureReader([ra, rb], weights=[0.7, 0.3], seed=99)
            return [int(r.id) for r in mixed]

    assert run() == run()


def test_mixture_schedule_applies_at_epoch_boundary(mixture_sources):
    (url_a, _), (url_b, _) = mixture_sources
    schedule = MixtureSchedule({0: [1, 0], 1: [0, 1]})
    assert schedule.weights_for(0) == (1.0, 0.0)
    assert schedule.weights_for(5) == (0.0, 1.0)
    with _token_reader(url_a, num_epochs=None) as ra, \
            _token_reader(url_b, num_epochs=None) as rb:
        mixed = MixtureReader([ra, rb], seed=3, schedule=schedule)
        assert mixed.weights == (1.0, 0.0)
        for _ in range(5):
            next(mixed)
        mixed.reset()
        assert mixed.epoch == 1
        assert mixed.weights == (0.0, 1.0)
        for _ in range(5):
            next(mixed)
        diag = mixed.diagnostics
        assert diag['mixture_epoch'] == 1
        assert diag['mixture_weight_updates'] == 0  # schedule steps don't count
        assert diag['mixture_source_0_rows'] == 5
        assert diag['mixture_source_1_rows'] == 5
        mixed.stop(); mixed.join()


def test_mixture_schedule_requires_epoch_zero():
    with pytest.raises(PetastormTpuError, match='epoch 0'):
        MixtureSchedule({1: [1, 1]})


def test_stall_report_renders_mixture_sources(mixture_sources):
    from petastorm_tpu.observability.report import format_stall_report, stall_report
    (url_a, _), (url_b, _) = mixture_sources
    with _token_reader(url_a) as ra, _token_reader(url_b) as rb:
        mixed = MixtureReader([ra, rb], weights=[0.5, 0.5], seed=17,
                              token_field='tokens')
        for _ in range(10):
            next(mixed)
        report = stall_report(mixed.diagnostics)
        assert set(report['mixture']) == {0, 1}
        rendered = format_stall_report(report)
        assert 'mixture sources' in rendered
        assert 'source 0' in rendered
        mixed.stop(); mixed.join()


# -- tail following ----------------------------------------------------------

def _append_rows(url, rows, rows_per_row_group=5, final=False):
    writer = DatasetWriter(url, TokenSchema, rows_per_row_group=rows_per_row_group,
                           append=True)
    for row in rows:
        writer.write(row)
    snap = writer.publish(final=final)
    writer.close()
    return snap


def test_publish_snapshot_and_listing(tmp_path):
    url, _ = _write_token_dataset(tmp_path / 'ds', num_rows=10,
                                  rows_per_row_group=5)
    snap0 = publish_snapshot(url)
    assert snap0 == 0
    snaps = list_snapshots(url)
    assert [s for s, _ in snaps] == [0]
    info = latest_snapshot(url)
    assert len(info['pieces']) == 2  # 10 rows / 5 per group
    assert info['final'] is False


def test_append_writer_extends_dataset(tmp_path):
    url, rows = _write_token_dataset(tmp_path / 'ds', num_rows=10,
                                     rows_per_row_group=5)
    publish_snapshot(url)
    extra = _token_rows(10, seed=11)
    for r in extra:
        r['id'] += 100
    snap = _append_rows(url, extra)
    assert snap == 1
    info = latest_snapshot(url)
    assert len(info['pieces']) == 4  # cumulative inventory
    # the whole dataset reads back: no part-file collision clobbered anything
    with _token_reader(url, schema_fields=['id']) as reader:
        ids = sorted(int(r.id) for r in reader)
    assert ids == sorted([r['id'] for r in rows] + [r['id'] for r in extra])


def test_tail_following_exactly_once_across_cycles(tmp_path):
    url, rows = _write_token_dataset(tmp_path / 'ds', num_rows=10,
                                     rows_per_row_group=5)
    publish_snapshot(url)
    expected = [r['id'] for r in rows]
    # three append/publish cycles beyond the initial snapshot
    for cycle in range(3):
        extra = _token_rows(10, seed=20 + cycle)
        for r in extra:
            r['id'] += 100 * (cycle + 1)
        _append_rows(url, extra, final=(cycle == 2))
        expected.extend(r['id'] for r in extra)

    with TailFollowingReader(url, poll_interval=0.05, idle_timeout=30,
                             reader_pool_type='dummy',
                             shuffle_row_groups=False) as tail:
        ids = [int(r.id) for r in tail]
    assert sorted(ids) == sorted(expected)
    assert len(ids) == len(set(ids)), 'duplicate delivery'
    diag = tail.diagnostics
    assert diag['dataset_grew'] == 4  # initial + 3 growth snapshots
    assert diag['tail_rows_delivered'] == len(expected)


def test_tail_following_concurrent_writer(tmp_path):
    url, rows = _write_token_dataset(tmp_path / 'ds', num_rows=10,
                                     rows_per_row_group=5)
    publish_snapshot(url)
    expected = {r['id'] for r in rows}
    lock = threading.Lock()

    def writer_thread():
        for cycle in range(3):
            time.sleep(0.2)
            extra = _token_rows(10, seed=40 + cycle)
            for r in extra:
                r['id'] += 100 * (cycle + 1)
            with lock:
                expected.update(r['id'] for r in extra)
            _append_rows(url, extra, final=(cycle == 2))

    t = threading.Thread(target=writer_thread)
    t.start()
    try:
        with TailFollowingReader(url, poll_interval=0.05, idle_timeout=30,
                                 reader_pool_type='dummy',
                                 shuffle_row_groups=False) as tail:
            ids = [int(r.id) for r in tail]
    finally:
        t.join()
    assert len(ids) == len(set(ids)), 'duplicate delivery under concurrency'
    assert set(ids) == expected


def test_tail_following_checkpoint_resume(tmp_path):
    url, rows = _write_token_dataset(tmp_path / 'ds', num_rows=10,
                                     rows_per_row_group=5)
    publish_snapshot(url)
    expected = [r['id'] for r in rows]
    for cycle in range(2):
        extra = _token_rows(10, seed=60 + cycle)
        for r in extra:
            r['id'] += 100 * (cycle + 1)
        _append_rows(url, extra, final=(cycle == 1))
        expected.extend(r['id'] for r in extra)

    tail = TailFollowingReader(url, poll_interval=0.05, idle_timeout=30,
                               reader_pool_type='dummy',
                               shuffle_row_groups=False)
    first = [int(next(tail).id) for _ in range(15)]  # 3 full 5-row groups
    state = pickle.loads(pickle.dumps(tail.state_dict()))
    tail.stop(); tail.join()

    resumed = TailFollowingReader(url, poll_interval=0.05, idle_timeout=30,
                                  reader_pool_type='dummy',
                                  shuffle_row_groups=False, resume_state=state)
    rest = [int(r.id) for r in resumed]
    resumed.stop(); resumed.join()

    combined = first + rest
    assert sorted(combined) == sorted(expected)
    assert len(combined) == len(set(combined)), 'resume re-delivered rows'


def test_tail_following_idle_timeout(tmp_path):
    url, _ = _write_token_dataset(tmp_path / 'ds', num_rows=10,
                                  rows_per_row_group=5)
    publish_snapshot(url)  # never marked final
    tail = TailFollowingReader(url, poll_interval=0.05, idle_timeout=0.3,
                               reader_pool_type='dummy',
                               shuffle_row_groups=False)
    with pytest.raises(PetastormTpuError, match='idle_timeout'):
        for _ in tail:
            pass
    tail.stop(); tail.join()


def test_tail_following_rejects_owned_kwargs(tmp_path):
    url, _ = _write_token_dataset(tmp_path / 'ds', num_rows=10,
                                  rows_per_row_group=5)
    with pytest.raises(PetastormTpuError, match='num_epochs'):
        TailFollowingReader(url, num_epochs=3)
