"""Device-side ops: fused normalize (Pallas kernel vs reference math), augment."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from petastorm_tpu.ops import normalize_images, random_crop, random_flip

MEAN = np.array([123.675, 116.28, 103.53], np.float32)
STD = np.array([58.395, 57.12, 57.375], np.float32)


def _reference(images, mean, std):
    return (images.astype(np.float32) - mean) / std


@pytest.mark.parametrize('shape', [
    (4, 32, 32, 3),     # W*C = 96 < one lane block (masked edge)
    (2, 17, 224, 3),    # W*C = 672: non-divisible by 512 lanes, odd rows
    (1, 8, 128, 1),     # single channel
])
def test_normalize_pallas_matches_reference(shape, rng):
    images = rng.integers(0, 256, shape, dtype=np.uint8)
    c = shape[-1]
    mean, std = MEAN[:c], STD[:c]
    out = normalize_images(jnp.asarray(images), mean, std, out_dtype=jnp.float32,
                           interpret=True)
    np.testing.assert_allclose(np.asarray(out), _reference(images, mean, std),
                               rtol=1e-5, atol=1e-5)


def test_normalize_pallas_float_input_not_truncated(rng):
    # Regression: the kernel used to widen through int32 unconditionally,
    # flattening fractional float inputs to -1.0 (advisor finding r1).
    images = rng.random((2, 8, 128, 3)).astype(np.float32)  # values in [0, 1)
    out = normalize_images(jnp.asarray(images), 0.5, 0.5, out_dtype=jnp.float32,
                           interpret=True)
    np.testing.assert_allclose(np.asarray(out), _reference(images, 0.5, 0.5),
                               rtol=1e-5, atol=1e-5)


def test_normalize_jnp_fallback_matches_reference(rng):
    images = rng.integers(0, 256, (3, 16, 24, 3), dtype=np.uint8)
    out = normalize_images(jnp.asarray(images), MEAN, STD, out_dtype=jnp.float32,
                           use_pallas=False)
    np.testing.assert_allclose(np.asarray(out), _reference(images, MEAN, STD),
                               rtol=1e-5, atol=1e-5)


def test_normalize_bfloat16_output_and_scalar_stats(rng):
    images = rng.integers(0, 256, (2, 8, 16, 3), dtype=np.uint8)
    out = normalize_images(jnp.asarray(images), 127.5, 127.5, interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               _reference(images, 127.5, 127.5), rtol=2e-2, atol=2e-2)


def test_normalize_single_image_and_validation(rng):
    img = rng.integers(0, 256, (8, 16, 3), dtype=np.uint8)
    out = normalize_images(jnp.asarray(img), MEAN, STD, out_dtype=jnp.float32,
                           use_pallas=False)
    assert out.shape == (8, 16, 3)
    with pytest.raises(ValueError, match='std must be non-zero'):
        normalize_images(jnp.asarray(img), MEAN, 0.0)
    with pytest.raises(ValueError, match='mean must be'):
        normalize_images(jnp.asarray(img), np.ones(4), STD)


def test_normalize_jits_inside_train_step(rng):
    # the op must compose with jit (static shapes, no python control flow)
    images = jnp.asarray(rng.integers(0, 256, (2, 8, 16, 3), dtype=np.uint8))

    @jax.jit
    def step(x):
        return normalize_images(x, MEAN, STD, out_dtype=jnp.float32,
                                use_pallas=False).sum()

    assert np.isfinite(float(step(images)))


def test_random_flip_values_and_determinism(rng):
    images = jnp.asarray(rng.integers(0, 256, (8, 4, 6, 3), dtype=np.uint8))
    key = jax.random.key(0)
    out1 = random_flip(images, key)
    out2 = random_flip(images, key)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    # every output image is either the original or its horizontal mirror
    img_np, out_np = np.asarray(images), np.asarray(out1)
    n_flipped = 0
    for i in range(img_np.shape[0]):
        same = np.array_equal(out_np[i], img_np[i])
        mirrored = np.array_equal(out_np[i], img_np[i, :, ::-1, :])
        assert same or mirrored
        n_flipped += int(mirrored and not same)
    assert 0 < n_flipped < img_np.shape[0]  # prob=0.5 over 8 images


def test_random_crop_shape_and_content(rng):
    images = jnp.asarray(rng.integers(0, 256, (4, 10, 12, 3), dtype=np.uint8))
    out = random_crop(images, jax.random.key(1), 6, 8)
    assert out.shape == (4, 6, 8, 3)
    # each crop must be a contiguous window of its source image
    img_np, out_np = np.asarray(images), np.asarray(out)
    for i in range(4):
        found = any(
            np.array_equal(out_np[i], img_np[i, y:y + 6, x:x + 8])
            for y in range(5) for x in range(5))
        assert found
    with pytest.raises(ValueError, match='larger than image'):
        random_crop(images, jax.random.key(2), 20, 8)


# -- ring attention (context parallelism over a virtual mesh) ----------------

def _reference_attention(q, k, v, causal):
    d = q.shape[-1]
    s = np.einsum('bhqd,bhkd->bhqk', q, k) / np.sqrt(d)
    if causal:
        t = q.shape[2]
        mask = np.tril(np.ones((t, t), bool))
        s = np.where(mask[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum('bhqk,bhkd->bhqd', p, v)


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('ring', [2, 8])
def test_ring_attention_matches_full_attention(causal, ring, rng):
    from jax.sharding import Mesh
    from petastorm_tpu.ops.ring_attention import make_ring_attention

    b, h, t, d = 2, 3, 32, 8
    q = rng.standard_normal((b, h, t, d), dtype=np.float32)
    k = rng.standard_normal((b, h, t, d), dtype=np.float32)
    v = rng.standard_normal((b, h, t, d), dtype=np.float32)

    mesh = Mesh(np.array(jax.devices()[:ring]), ('seq',))
    attn = make_ring_attention(mesh, seq_axis='seq', causal=causal)
    out = np.asarray(attn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    expected = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=2e-4)


def test_ring_attention_with_data_and_seq_axes(rng):
    from jax.sharding import Mesh
    from petastorm_tpu.ops.ring_attention import make_ring_attention

    b, h, t, d = 4, 2, 16, 4
    q = rng.standard_normal((b, h, t, d), dtype=np.float32)
    k = rng.standard_normal((b, h, t, d), dtype=np.float32)
    v = rng.standard_normal((b, h, t, d), dtype=np.float32)
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4), ('data', 'seq'))
    attn = make_ring_attention(mesh, seq_axis='seq', batch_axis='data', causal=True)
    out = np.asarray(attn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(out, _reference_attention(q, k, v, True),
                               rtol=2e-4, atol=2e-4)


# -- Ulysses all-to-all sequence parallelism ---------------------------------

@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('shards', [2, 4])
def test_ulysses_attention_matches_full_attention(causal, shards, rng):
    from jax.sharding import Mesh
    from petastorm_tpu.ops.ulysses_attention import make_ulysses_attention

    b, h, t, d = 2, 4, 32, 8  # h divisible by both shard counts
    q = rng.standard_normal((b, h, t, d), dtype=np.float32)
    k = rng.standard_normal((b, h, t, d), dtype=np.float32)
    v = rng.standard_normal((b, h, t, d), dtype=np.float32)

    mesh = Mesh(np.array(jax.devices()[:shards]), ('seq',))
    attn = make_ulysses_attention(mesh, seq_axis='seq', causal=causal)
    out = np.asarray(attn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(out, _reference_attention(q, k, v, causal),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_matches_ring_attention(rng):
    # the two context-parallel strategies are interchangeable: same math,
    # different data movement
    from jax.sharding import Mesh
    from petastorm_tpu.ops.ring_attention import make_ring_attention
    from petastorm_tpu.ops.ulysses_attention import make_ulysses_attention

    b, h, t, d = 2, 8, 64, 4
    q = rng.standard_normal((b, h, t, d), dtype=np.float32)
    k = rng.standard_normal((b, h, t, d), dtype=np.float32)
    v = rng.standard_normal((b, h, t, d), dtype=np.float32)
    mesh = Mesh(np.array(jax.devices()[:8]), ('seq',))
    ring = make_ring_attention(mesh, causal=True)
    uly = make_ulysses_attention(mesh, causal=True)
    np.testing.assert_allclose(
        np.asarray(uly(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))),
        np.asarray(ring(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))),
        rtol=2e-4, atol=2e-4)


def test_ulysses_attention_with_data_axis_and_chunking(rng):
    from jax.sharding import Mesh
    from petastorm_tpu.ops.ulysses_attention import make_ulysses_attention

    b, h, t, d = 4, 4, 32, 4
    q = rng.standard_normal((b, h, t, d), dtype=np.float32)
    k = rng.standard_normal((b, h, t, d), dtype=np.float32)
    v = rng.standard_normal((b, h, t, d), dtype=np.float32)
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4), ('data', 'seq'))
    attn = make_ulysses_attention(mesh, seq_axis='seq', batch_axis='data',
                                  causal=True, kv_chunk=4)
    out = np.asarray(attn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(out, _reference_attention(q, k, v, True),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_rejects_indivisible_heads(rng):
    from jax.sharding import Mesh
    from petastorm_tpu.ops.ulysses_attention import make_ulysses_attention

    mesh = Mesh(np.array(jax.devices()[:4]), ('seq',))
    attn = make_ulysses_attention(mesh)
    x = jnp.zeros((1, 3, 16, 4))  # 3 heads, 4-way seq axis
    with pytest.raises(ValueError, match='divisible'):
        attn(x, x, x)


# -- pipeline parallelism (GPipe over a mesh axis) ---------------------------

def _pipeline_stage(params, act):
    w, b = params
    return jax.nn.gelu(act @ w + b)


def _stacked_stage_params(n_stages, dim, rng):
    w = jnp.asarray(rng.standard_normal((n_stages, dim, dim)).astype(np.float32) * 0.3)
    b = jnp.asarray(rng.standard_normal((n_stages, dim)).astype(np.float32) * 0.1)
    return w, b


def _sequential_ref(params, x):
    w, b = params
    for s in range(w.shape[0]):
        x = jax.nn.gelu(x @ w[s] + b[s])
    return x


@pytest.mark.parametrize('stages,microbatches', [(2, 4), (4, 8), (8, 8)])
def test_pipeline_matches_sequential(stages, microbatches, rng):
    from jax.sharding import Mesh
    from petastorm_tpu.parallel import make_pipelined_apply

    mesh = Mesh(np.array(jax.devices()[:stages]), ('stage',))
    params = _stacked_stage_params(stages, 16, rng)
    apply = make_pipelined_apply(mesh, _pipeline_stage, num_microbatches=microbatches)
    x = jnp.asarray(rng.standard_normal((32, 16)).astype(np.float32))
    with mesh:
        y = apply(params, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(_sequential_ref(params, x)),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_grads_match_sequential(rng):
    from jax.sharding import Mesh
    from petastorm_tpu.parallel import make_pipelined_apply

    stages = 4
    mesh = Mesh(np.array(jax.devices()[:stages]), ('stage',))
    params = _stacked_stage_params(stages, 8, rng)
    apply = make_pipelined_apply(mesh, _pipeline_stage, num_microbatches=stages)
    x = jnp.asarray(rng.standard_normal((16, 8)).astype(np.float32))
    with mesh:
        g = jax.grad(lambda p, xx: jnp.sum(apply(p, xx) ** 2))(params, x)
    ref = jax.grad(lambda p, xx: jnp.sum(_sequential_ref(p, xx) ** 2))(params, x)
    for a, b in zip(jax.tree_util.tree_leaves(g), jax.tree_util.tree_leaves(ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_pipeline_rejects_indivisible_batch(rng):
    from jax.sharding import Mesh
    from petastorm_tpu.parallel import make_pipelined_apply

    mesh = Mesh(np.array(jax.devices()[:2]), ('stage',))
    params = _stacked_stage_params(2, 8, rng)
    apply = make_pipelined_apply(mesh, _pipeline_stage, num_microbatches=4)
    with mesh, pytest.raises(ValueError, match='divisible'):
        apply(params, jnp.zeros((6, 8)))


def test_pipeline_rejects_wrong_stage_count(rng):
    # a 4-stage stack over a 2-device axis would silently keep stages 0 and 2
    from jax.sharding import Mesh
    from petastorm_tpu.parallel import make_pipelined_apply

    mesh = Mesh(np.array(jax.devices()[:2]), ('stage',))
    params = _stacked_stage_params(4, 8, rng)
    apply = make_pipelined_apply(mesh, _pipeline_stage, num_microbatches=2)
    with mesh, pytest.raises(ValueError, match='one stage per device'):
        apply(params, jnp.zeros((4, 8)))


def test_mixup_blend_and_labels(rng):
    from petastorm_tpu.ops import mixup

    images = jnp.asarray(rng.integers(0, 255, (8, 6, 6, 3), dtype=np.uint8))
    labels = jnp.asarray(rng.integers(0, 5, (8,)))
    key = jax.random.PRNGKey(3)
    out, soft = jax.jit(lambda i, l, k: mixup(i, l, k, num_classes=5))(images, labels, key)
    assert out.shape == images.shape and out.dtype == images.dtype
    assert soft.shape == (8, 5)
    np.testing.assert_allclose(np.asarray(soft).sum(axis=1), 1.0, atol=1e-5)
    # deterministic under the same key
    out2, soft2 = mixup(images, labels, key, num_classes=5)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    # lam >= 0.5: the original image dominates every blend
    orig = images.astype(np.float32)
    assert np.abs(np.asarray(out).astype(np.float32) - orig).max() <= 255 * 0.5 + 1
    # already-soft labels pass through the same blend
    _, soft3 = mixup(images, jax.nn.one_hot(labels, 5), key)
    np.testing.assert_allclose(np.asarray(soft3), np.asarray(soft), atol=1e-6)
    with pytest.raises(ValueError, match='num_classes'):
        mixup(images, labels, key)  # int labels need num_classes


def test_cutmix_box_and_label_fraction(rng):
    from petastorm_tpu.ops import cutmix

    images = jnp.asarray(rng.integers(0, 255, (6, 16, 16, 3), dtype=np.uint8))
    labels = jnp.asarray(rng.integers(0, 4, (6,)))
    key = jax.random.PRNGKey(11)
    out, soft = jax.jit(lambda i, l, k: cutmix(i, l, k, num_classes=4))(images, labels, key)
    assert out.shape == images.shape and out.dtype == images.dtype
    np.testing.assert_allclose(np.asarray(soft).sum(axis=1), 1.0, atol=1e-5)
    # every pixel comes from either the original or SOME other batch image
    out_np, img_np = np.asarray(out), np.asarray(images)
    from_self = (out_np == img_np).all(axis=3)
    changed_frac = 1.0 - from_self.mean()
    # the label fraction and the pixel fraction agree (same realized box);
    # soft rows are lam*self + (1-lam)*partner, so off-own-class mass = 1-lam
    own = np.take_along_axis(np.asarray(soft), np.asarray(labels)[:, None], axis=1).ravel()
    # box fraction bound: pixels equal by coincidence can only OVERSTATE
    # from_self, so changed_frac <= 1-lam_adj
    assert changed_frac <= (1.0 - own.min()) + 1e-6
