"""Reader read-position checkpoint/resume (state_dict / resume_state).

This capability does not exist in the reference (SURVEY.md §5: "Checkpoint /
resume: None for read state") — it is a deliberate TPU-build extension, so the
tests define its contract:

  * no data loss: every row of the remaining work is delivered after resume;
  * row-group granularity: only groups in flight at checkpoint time may be
    re-delivered (each at most once more per remaining epoch);
  * exactness: when the consumer buffer is empty at checkpoint (row-group or
    epoch boundaries with the dummy pool), the resumed stream continues the
    original seeded stream exactly;
  * the state is picklable and pool-independent.
"""

import pickle

import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.predicates import in_lambda


def _read_ids(reader, limit=None):
    ids = []
    for row in reader:
        ids.append(int(row.id))
        if limit is not None and len(ids) >= limit:
            break
    return ids


def _read_batch_ids(reader, limit_batches=None):
    ids = []
    n = 0
    for batch in reader:
        ids.extend(int(i) for i in batch.id)
        n += 1
        if limit_batches is not None and n >= limit_batches:
            break
    return ids


@pytest.mark.parametrize('pool', ['thread', 'process'])
def test_row_reader_resume_covers_all_rows(synthetic_dataset, pool):
    workers = {'thread': 3, 'process': 2}[pool]
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type=pool, workers_count=workers, seed=11)
    first = _read_ids(reader, limit=33)
    state = pickle.loads(pickle.dumps(reader.state_dict()))  # must survive pickling
    reader.stop(); reader.join()

    resumed = make_reader(synthetic_dataset.url, schema_fields=['id'],
                          reader_pool_type=pool, workers_count=workers, seed=11,
                          resume_state=state)
    rest = _read_ids(resumed)
    resumed.stop(); resumed.join()

    all_ids = {r['id'] for r in synthetic_dataset.data}
    assert set(first) | set(rest) == all_ids, 'checkpoint/resume lost rows'
    # duplicates only from in-flight row groups, each re-read at most once
    assert all((first + rest).count(i) <= 2 for i in all_ids)


def test_row_reader_exact_resume_at_group_boundary(synthetic_dataset):
    # dummy pool + seed: fully deterministic row stream. 30 rows = 3 full
    # 10-row groups, so the consumer buffer is empty at checkpoint and the
    # resumed stream must continue the original stream exactly.
    expected = _read_ids(make_reader(synthetic_dataset.url, schema_fields=['id'],
                                     reader_pool_type='dummy', seed=5, num_epochs=2))
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', seed=5, num_epochs=2)
    first = _read_ids(reader, limit=30)
    state = reader.state_dict()
    reader.stop(); reader.join()

    resumed = make_reader(synthetic_dataset.url, schema_fields=['id'],
                          reader_pool_type='dummy', seed=5, num_epochs=2,
                          resume_state=state)
    rest = _read_ids(resumed)
    assert first + rest == expected


def test_row_reader_exact_resume_at_epoch_boundary(synthetic_dataset):
    expected = _read_ids(make_reader(synthetic_dataset.url, schema_fields=['id'],
                                     reader_pool_type='dummy', seed=7, num_epochs=3))
    assert len(expected) == 300
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', seed=7, num_epochs=3)
    first = _read_ids(reader, limit=100)
    state = reader.state_dict()
    reader.stop(); reader.join()

    resumed = make_reader(synthetic_dataset.url, schema_fields=['id'],
                          reader_pool_type='dummy', seed=7, num_epochs=3,
                          resume_state=state)
    rest = _read_ids(resumed)
    assert first + rest == expected
    # epochs 2-3 of the resumed run reshuffle from the restored RNG state, so
    # they are NOT a replay of epoch 1's order (decorrelation is preserved)
    assert rest[:100] != first or rest[100:200] != first


def test_mid_group_checkpoint_reraeds_partial_group_only(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', seed=3)
    first = _read_ids(reader, limit=25)  # 2 full groups + 5 rows of the third
    state = reader.state_dict()
    reader.stop(); reader.join()

    resumed = make_reader(synthetic_dataset.url, schema_fields=['id'],
                          reader_pool_type='dummy', seed=3, resume_state=state)
    rest = _read_ids(resumed)
    combined = first + rest
    all_ids = {r['id'] for r in synthetic_dataset.data}
    assert set(combined) == all_ids
    dupes = {i for i in all_ids if combined.count(i) > 1}
    # only the partially-consumed third group may duplicate
    assert dupes == set(first[20:25])


def test_batch_reader_checkpoint_resume(scalar_dataset):
    reader = make_batch_reader(scalar_dataset.url, schema_fields=['id'],
                               reader_pool_type='dummy', seed=13)
    first = _read_batch_ids(reader, limit_batches=4)
    state = reader.state_dict()
    reader.stop(); reader.join()

    resumed = make_batch_reader(scalar_dataset.url, schema_fields=['id'],
                                reader_pool_type='dummy', seed=13, resume_state=state)
    rest = _read_batch_ids(resumed)
    all_ids = {r['id'] for r in scalar_dataset.data}
    combined = first + rest
    assert set(combined) == all_ids
    # batches are delivered whole: no row may appear twice at a batch boundary
    assert len(combined) == len(all_ids)


def test_rebatch_checkpoint_resume(scalar_dataset):
    reader = make_batch_reader(scalar_dataset.url, schema_fields=['id'],
                               reader_pool_type='dummy', seed=17, batch_size=7)
    first = _read_batch_ids(reader, limit_batches=5)  # 35 rows
    state = reader.state_dict()
    reader.stop(); reader.join()

    resumed = make_batch_reader(scalar_dataset.url, schema_fields=['id'],
                                reader_pool_type='dummy', seed=17, batch_size=7,
                                resume_state=state)
    rest = _read_batch_ids(resumed)
    all_ids = {r['id'] for r in scalar_dataset.data}
    combined = first + rest
    assert set(combined) == all_ids
    # re-delivery bounded: only groups with rows still buffered in the
    # rebatching queue at checkpoint time may repeat
    assert all(combined.count(i) <= 2 for i in all_ids)


def test_checkpoint_with_predicate_filtered_groups(synthetic_dataset):
    predicate = in_lambda(['id'], lambda values: values['id'] < 30)
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'], predicate=predicate,
                         reader_pool_type='dummy', seed=19)
    first = _read_ids(reader, limit=15)
    state = reader.state_dict()
    reader.stop(); reader.join()

    resumed = make_reader(synthetic_dataset.url, schema_fields=['id'], predicate=predicate,
                          reader_pool_type='dummy', seed=19, resume_state=state)
    rest = _read_ids(resumed)
    matching = {r['id'] for r in synthetic_dataset.data if r['id'] < 30}
    assert set(first) | set(rest) == matching


def test_state_dict_picklable_with_lambda_predicate(synthetic_dataset):
    # the state stores item indices, not item dicts, so unpicklable predicate
    # objects (lambdas) never leak into it
    predicate = in_lambda(['id'], lambda values: values['id'] % 2 == 0)
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'], predicate=predicate,
                         reader_pool_type='dummy', seed=37)
    _read_ids(reader, limit=10)
    blob = pickle.dumps(reader.state_dict())
    reader.stop(); reader.join()
    assert len(blob) < 100_000  # compact: indices + RNG state, no payloads


def test_failed_item_stays_undelivered(synthetic_dataset):
    # a worker error must not mark the failing row group delivered: a
    # checkpoint taken after the error re-reads it on resume
    from petastorm_tpu.transform import TransformSpec

    calls = {'n': 0}

    def explode_once(row):
        calls['n'] += 1
        if calls['n'] == 1:
            raise RuntimeError('decode exploded')
        return row

    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='thread', workers_count=1, seed=41,
                         transform_spec=TransformSpec(explode_once))
    ids, errors = [], 0
    while True:
        try:
            ids.append(int(next(reader).id))
        except StopIteration:
            break
        except RuntimeError:
            errors += 1
    assert errors == 1
    state = reader.state_dict()
    reader.stop(); reader.join()

    resumed = make_reader(synthetic_dataset.url, schema_fields=['id'],
                          reader_pool_type='thread', workers_count=1, seed=41,
                          transform_spec=TransformSpec(lambda r: r),
                          resume_state=state)
    rest = _read_ids(resumed)
    resumed.stop(); resumed.join()
    all_ids = {r['id'] for r in synthetic_dataset.data}
    assert set(ids) | set(rest) == all_ids, 'failed row group was lost after resume'


def test_resume_state_is_pool_independent(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='thread', workers_count=3, seed=23)
    first = _read_ids(reader, limit=20)
    state = reader.state_dict()
    reader.stop(); reader.join()

    resumed = make_reader(synthetic_dataset.url, schema_fields=['id'],
                          reader_pool_type='dummy', seed=23, resume_state=state)
    rest = _read_ids(resumed)
    all_ids = {r['id'] for r in synthetic_dataset.data}
    assert set(first) | set(rest) == all_ids


def test_resume_state_mismatch_rejected(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', seed=29)
    _read_ids(reader, limit=5)
    state = reader.state_dict()
    reader.stop(); reader.join()

    with pytest.raises(ValueError, match='does not match'):
        # different work-item structure: shuffle_row_drop_partitions doubles items
        make_reader(synthetic_dataset.url, schema_fields=['id'], reader_pool_type='dummy',
                    seed=29, shuffle_row_drop_partitions=2, resume_state=state)
    with pytest.raises(ValueError, match='Unrecognized'):
        make_reader(synthetic_dataset.url, schema_fields=['id'], reader_pool_type='dummy',
                    seed=29, resume_state={'bogus': True})


def test_finished_reader_state_resumes_empty(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', seed=31)
    ids = _read_ids(reader)
    assert len(ids) == 100
    state = reader.state_dict()
    reader.stop(); reader.join()

    resumed = make_reader(synthetic_dataset.url, schema_fields=['id'],
                          reader_pool_type='dummy', seed=31, resume_state=state)
    assert _read_ids(resumed) == []


def test_jax_loader_checkpoint_with_shuffle_buffer(synthetic_dataset):
    # loader-level checkpoint: rows sitting in the client-side shuffling buffer
    # are embedded in the state, so nothing yielded-to-loader is lost
    from petastorm_tpu.jax import JaxDataLoader

    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', seed=43)
    loader = JaxDataLoader(reader, batch_size=10, shuffling_queue_capacity=30,
                           seed=43, drop_last=False)
    it = iter(loader)
    first = [int(i) for _ in range(3) for i in next(it)['id']]
    state = pickle.loads(pickle.dumps(loader.state_dict()))
    reader.stop(); reader.join()

    resumed_reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                                 reader_pool_type='dummy', seed=43,
                                 resume_state=state['reader'])
    resumed = JaxDataLoader(resumed_reader, batch_size=10, shuffling_queue_capacity=30,
                            seed=43, drop_last=False, resume_state=state)
    rest = [int(i) for b in resumed for i in b['id']]
    resumed_reader.stop(); resumed_reader.join()

    combined = first + rest
    all_ids = set(range(100))
    assert set(combined) == all_ids
    # dupes only from the row group partially pulled out of the reader
    dupes = [i for i in all_ids if combined.count(i) > 1]
    assert len(dupes) <= 10, (len(dupes), sorted(dupes))


def test_jax_loader_reiter_with_buffered_rows_rejected(synthetic_dataset):
    # a second iter() used to rebind the buffer, silently dropping the first
    # iterator's rows from future checkpoints (advisor finding r1)
    from petastorm_tpu.jax import JaxDataLoader

    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', seed=7)
    with JaxDataLoader(reader, batch_size=10, shuffling_queue_capacity=30,
                       seed=7) as loader:
        it = iter(loader)
        next(it)
        with pytest.raises(RuntimeError, match='buffered rows'):
            iter(loader)


def test_jax_loader_multi_epoch_after_drop_last(synthetic_dataset):
    # drop_last leftovers must not trip the re-iteration guard: the standard
    # `for epoch in range(n): for batch in loader:` pattern works
    from petastorm_tpu.jax import JaxDataLoader

    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', seed=7)
    with JaxDataLoader(reader, batch_size=30, drop_last=True) as loader:
        epoch1 = sum(len(b['id']) for b in loader)  # 100 rows -> 3x30, 10 dropped
        assert epoch1 == 90
        # the 10 dropped leftovers must not trip the buffered-rows guard here
        assert sum(len(b['id']) for b in loader) == 0  # reader exhausted


def test_jax_loader_state_dict_before_resume_iteration_preserves_rows(synthetic_dataset):
    # checkpointing a resume-constructed loader BEFORE its first next() must
    # re-emit the restored rows/RNG, not an empty state
    from petastorm_tpu.jax import JaxDataLoader

    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', seed=43)
    loader = JaxDataLoader(reader, batch_size=10, shuffling_queue_capacity=30, seed=43)
    it = iter(loader)
    next(it)
    state = loader.state_dict()
    reader.stop(); reader.join()
    assert state['rows']

    r2 = make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type='dummy', seed=43, resume_state=state['reader'])
    with JaxDataLoader(r2, batch_size=10, shuffling_queue_capacity=30, seed=43,
                       resume_state=state) as resumed:
        state2 = resumed.state_dict()
    assert state2['rows'] == state['rows']
    assert state2['buffer_rng'] == state['buffer_rng']


def test_jax_loader_resume_with_empty_rows_then_checkpoint(synthetic_dataset):
    # a checkpoint with zero buffered rows must not leave the resumed loader's
    # state_dict() permanently stuck on the (empty) resume branch
    from petastorm_tpu.jax import JaxDataLoader

    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', seed=11)
    loader = JaxDataLoader(reader, batch_size=10)  # no shuffle buffer: rows=[]
    state = loader.state_dict()
    reader.stop(); reader.join()
    assert state['rows'] == []

    r2 = make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type='dummy', seed=11, resume_state=state['reader'])
    with JaxDataLoader(r2, batch_size=10, shuffling_queue_capacity=30, seed=11,
                       resume_state=state) as resumed:
        it = iter(resumed)
        next(it)
        state2 = resumed.state_dict()
    # the mid-iteration checkpoint must reflect the live buffer, not the
    # stale empty resume state
    assert state2['rows']
    assert state2['buffer_rng'] is not None


def test_jax_loader_seeded_resume_is_deterministic(synthetic_dataset):
    # the checkpoint carries the shuffling buffer's mid-stream RNG state
    # (state['buffer_rng']); two resumes from the same state must replay the
    # identical row order. (Exact equality with the uninterrupted run is not a
    # guarantee: a mid-row-group reader resume re-reads the partial group —
    # at-least-once, not exactly-once.)
    from petastorm_tpu.jax import JaxDataLoader

    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', seed=43)
    loader = JaxDataLoader(reader, batch_size=10, shuffling_queue_capacity=30,
                           seed=43, drop_last=False)
    it = iter(loader)
    for _ in range(3):
        next(it)
    state = pickle.loads(pickle.dumps(loader.state_dict()))
    reader.stop(); reader.join()
    assert state['buffer_rng'] is not None
    # the saved RNG state has advanced past the fresh seeded state: restoring
    # it is observable (a fresh seed-43 buffer would shuffle differently)
    from petastorm_tpu.shuffling_buffer import RandomShufflingBuffer
    fresh = RandomShufflingBuffer(30, 15, seed=43)
    assert fresh.rng_state != state['buffer_rng']

    def resume():
        r = make_reader(synthetic_dataset.url, schema_fields=['id'],
                        reader_pool_type='dummy', seed=43,
                        resume_state=state['reader'])
        with JaxDataLoader(r, batch_size=10, shuffling_queue_capacity=30,
                           seed=43, drop_last=False, resume_state=state) as ld:
            return [[int(i) for i in b['id']] for b in ld]

    assert resume() == resume()


def test_loader_columnar_resume_through_process_pool_blob_transport(tmp_path):
    """Loader checkpoint/resume where the buffered blocks arrived via the
    /dev/shm blob sidechannel: the snapshot rows are views over unlinked
    mmapped files and must survive pickling into the state dict."""
    import numpy as np

    from petastorm_tpu.codecs import RawTensorCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.jax import JaxDataLoader
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('S', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('big', np.uint8, (128, 64, 3), RawTensorCodec(), False),
    ])
    url = 'file://' + str(tmp_path / 'ds')
    rng = np.random.default_rng(4)
    # 24KB/row x 50-row groups = 1.2MB blocks: over the 1MB blob threshold
    write_petastorm_dataset(url, schema, ({'id': i, 'big': rng.integers(
        0, 255, (128, 64, 3), dtype=np.uint8)} for i in range(150)),
        rows_per_row_group=50)

    reader = make_reader(url, output='columnar', reader_pool_type='process',
                         workers_count=1, seed=13)
    loader = JaxDataLoader(reader, 16, shuffling_queue_capacity=64, seed=13)
    it = iter(loader)
    seen = [int(i) for _ in range(3) for i in next(it)['id']]
    state = pickle.loads(pickle.dumps(loader.state_dict()))
    reader.stop(); reader.join()

    resumed_reader = make_reader(url, output='columnar', reader_pool_type='process',
                                 workers_count=1, seed=13, resume_state=state['reader'])
    resumed = JaxDataLoader(resumed_reader, 16, shuffling_queue_capacity=64, seed=13,
                            drop_last=False, resume_state=state)
    rest = [int(i) for b in resumed for i in b['id']]
    resumed_reader.stop(); resumed_reader.join()
    combined = seen + rest
    # every row delivered; in-flight groups may re-read (each at most once)
    assert set(combined) == set(range(150))
    assert all(combined.count(i) <= 2 for i in range(150))


# ---------------------------------------------------------------------------
# Multi-host (pod) checkpoint/resume: N simulated hosts, exactly-once
# ---------------------------------------------------------------------------

def _host_stream(url, host, n_hosts, seed, resume=None):
    """One simulated pod host: a sharded columnar reader + JaxDataLoader.
    Returns (loader, reader). batch_size == rows_per_row_group (10), so with
    the dummy pool every checkpoint lands on an exact block boundary."""
    from petastorm_tpu.jax import JaxDataLoader
    reader = make_reader(url, schema_fields=['id'], output='columnar',
                         reader_pool_type='dummy', seed=seed,
                         shuffle_row_groups=True,
                         cur_shard=host, shard_count=n_hosts,
                         resume_state=resume['reader'] if resume else None)
    loader = JaxDataLoader(reader, batch_size=10, drop_last=False,
                           resume_state=resume)
    return loader, reader


def test_pod_wide_checkpoint_resume_exactly_once(synthetic_dataset):
    """The pod scenario (docs/parallelism.md): N hosts each hold a disjoint
    shard (cur_shard/shard_count). Every host checkpoints its
    Reader.state_dict() + loader state MID-EPOCH (a different position per
    host, as real preemption would), all N resume, and:

      * pod-wide delivery is EXACTLY once — the union of pre- and
        post-checkpoint rows across hosts covers the dataset with no row
        delivered twice on any host;
      * each host's interrupted-and-resumed batch stream is IDENTICAL to its
        uninterrupted stream under the same seed.
    """
    n_hosts, seed = 4, 101
    url = synthetic_dataset.url
    all_ids = {r['id'] for r in synthetic_dataset.data}

    # uninterrupted baselines, one per host
    baselines = []
    for host in range(n_hosts):
        loader, reader = _host_stream(url, host, n_hosts, seed)
        with loader:
            baselines.append([[int(i) for i in b['id']] for b in loader])

    # interrupted run: host h checkpoints after 1 or 2 batches (mid-epoch —
    # every shard holds >= 2 of the 10 row groups — at a different position
    # per host, as real preemption would), then resumes from its own state
    streams = []
    for host in range(n_hosts):
        loader, reader = _host_stream(url, host, n_hosts, seed)
        it = iter(loader)
        first = [[int(i) for i in next(it)['id']] for _ in range(1 + host % 2)]
        state = pickle.loads(pickle.dumps(loader.state_dict()))
        reader.stop(); reader.join()

        resumed_loader, resumed_reader = _host_stream(url, host, n_hosts, seed,
                                                      resume=state)
        with resumed_loader:
            rest = [[int(i) for i in b['id']] for b in resumed_loader]
        streams.append(first + rest)

    # identical batch streams per host, uninterrupted vs resumed
    for host in range(n_hosts):
        assert streams[host] == baselines[host], \
            'host {} resumed stream diverged from its seeded baseline'.format(host)

    # pod-wide exactly-once delivery
    delivered = [i for stream in streams for batch in stream for i in batch]
    assert set(delivered) == all_ids, 'pod-wide delivery lost rows'
    assert len(delivered) == len(all_ids), \
        'pod-wide delivery duplicated rows across the checkpoint'


def test_pod_wide_shards_are_disjoint_after_resume(synthetic_dataset):
    """Resume must preserve the shard assignment: no host may drift onto
    another host's row groups (the share-nothing invariant)."""
    n_hosts, seed = 4, 7
    url = synthetic_dataset.url
    per_host = []
    for host in range(n_hosts):
        loader, reader = _host_stream(url, host, n_hosts, seed)
        it = iter(loader)
        first = [int(i) for i in next(it)['id']]
        state = pickle.loads(pickle.dumps(loader.state_dict()))
        reader.stop(); reader.join()
        resumed_loader, _rr = _host_stream(url, host, n_hosts, seed, resume=state)
        with resumed_loader:
            rest = [int(i) for b in resumed_loader for i in b['id']]
        per_host.append(set(first) | set(rest))
    for a in range(n_hosts):
        for b in range(a + 1, n_hosts):
            assert not (per_host[a] & per_host[b]), \
                'hosts {} and {} delivered overlapping rows'.format(a, b)


def test_resume_state_on_wrong_shard_remaps_instead_of_exact_replay(synthetic_dataset):
    """A v2 checkpoint records the shard that took it. Restoring it onto a
    DIFFERENT shard of the same layout must not silently take the exact
    path — that would replay the checkpointing shard's local positions as
    this shard's (row groups double-read on one shard and dropped on
    another). It falls through to the portable global-cursor remap, which
    replays only the cells that actually belong to the restoring shard."""
    url = synthetic_dataset.url
    reader = make_reader(url, schema_fields=['id'], reader_pool_type='dummy',
                         seed=9, cur_shard=0, shard_count=2)
    _read_ids(reader, limit=18)
    state = pickle.loads(pickle.dumps(reader.state_dict()))
    reader.stop(); reader.join()
    assert state['shard'] == [0, 2]
    assert state['remaining_global_parts'], 'checkpoint must be mid-epoch'

    resumed = make_reader(url, schema_fields=['id'], reader_pool_type='dummy',
                          seed=9, cur_shard=1, shard_count=2,
                          resume_state=state)
    rest = _read_ids(resumed)
    resumed.stop(); resumed.join()
    # every group shard 0 had left is a shard-0 group; none land on shard 1,
    # so the remap yields nothing to replay — the exact path would instead
    # have replayed shard-0 POSITIONS as shard-1 groups
    assert rest == [], ('restoring a shard-0 checkpoint onto shard 1 replayed '
                        'the wrong shard\'s positions: {!r}'.format(rest[:10]))


def test_portable_resume_across_shard_counts(synthetic_dataset):
    """Satellite contract for elastic pods: checkpoint a 2-shard pod
    mid-epoch, merge the per-host states with merge_resume_states, and
    restore onto a 3-shard pod. The merged state is a pod-wide cursor in
    GLOBAL piece indices, so the new shard layout replays exactly the
    unfinished groups — none lost, each on exactly one new shard."""
    from petastorm_tpu import merge_resume_states
    url = synthetic_dataset.url
    all_ids = {r['id'] for r in synthetic_dataset.data}

    first, states = [], []
    for shard in range(2):
        reader = make_reader(url, schema_fields=['id'], reader_pool_type='dummy',
                             seed=9, cur_shard=shard, shard_count=2)
        first.append(_read_ids(reader, limit=18))  # 1 full group + 8 in flight
        states.append(reader.state_dict())
        reader.stop(); reader.join()

    merged = pickle.loads(pickle.dumps(merge_resume_states(states)))

    rest = []
    for shard in range(3):
        resumed = make_reader(url, schema_fields=['id'],
                              reader_pool_type='dummy', seed=9,
                              cur_shard=shard, shard_count=3,
                              resume_state=merged)
        rest.append(_read_ids(resumed))
        resumed.stop(); resumed.join()

    delivered = [i for part in first + rest for i in part]
    assert set(delivered) == all_ids, 'portable resume lost rows'
    # only the two groups in flight at checkpoint may repeat, once each
    assert all(delivered.count(i) <= 2 for i in all_ids)
    # every remaining global group lands on exactly ONE new shard
    replayed = [i for part in rest for i in part]
    assert len(replayed) == len(set(replayed)), \
        'a row group was replayed on more than one new shard'


def test_merge_resume_states_rejects_mismatched_selections(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='dummy', seed=1)
    _read_ids(reader, limit=5)
    state = reader.state_dict()
    reader.stop(); reader.join()
    from petastorm_tpu import merge_resume_states
    other = dict(state, num_global_pieces=state['num_global_pieces'] + 1)
    with pytest.raises(ValueError, match='disagree on the dataset-wide'):
        merge_resume_states([state, other])
    with pytest.raises(ValueError, match='version-2'):
        merge_resume_states([{'version': 1}])
    with pytest.raises(ValueError, match='at least one'):
        merge_resume_states([])
