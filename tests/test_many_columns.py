"""Wide-schema (1000-column) datasets through the batch-reader path
(reference tests/test_end_to_end.py many_columns cases + the >255-field
namedtuple concern, unischema.py:106-117 — CPython 3.7+ removed the 255-arg
limit, so the framework relies on plain namedtuples; these tests prove the
full stack holds at 1000 fields)."""

import numpy as np

from petastorm_tpu import make_batch_reader
from petastorm_tpu.etl.dataset_metadata import infer_or_load_unischema


def test_many_columns_schema_inference(many_columns_dataset):
    schema = infer_or_load_unischema(many_columns_dataset.url)
    assert set(schema.fields) == set(many_columns_dataset.column_names)
    assert all(schema.fields[n].numpy_dtype == np.int64
               for n in many_columns_dataset.column_names)


def test_many_columns_read_all(many_columns_dataset):
    with make_batch_reader(many_columns_dataset.url, reader_pool_type='dummy',
                           shuffle_row_groups=False) as reader:
        batches = list(reader)
    assert len(batches[0]._fields) == 1000
    total = sum(len(b.col_0) for b in batches)
    assert total == 10
    # values survive: every column holds row indices
    ids = np.sort(np.concatenate([np.asarray(b.col_999) for b in batches]))
    np.testing.assert_array_equal(ids, np.arange(10))


def test_many_columns_regex_subset(many_columns_dataset):
    # regex column selection prunes the parquet reads to 10 of 1000 columns
    with make_batch_reader(many_columns_dataset.url, schema_fields=['col_99\\d'],
                           reader_pool_type='dummy', shuffle_row_groups=False) as reader:
        batch = next(iter(reader))
    assert sorted(batch._fields) == sorted('col_99{}'.format(i) for i in range(10))


def test_many_columns_rebatch_and_namedtuple(many_columns_dataset):
    with make_batch_reader(many_columns_dataset.url, reader_pool_type='thread',
                           workers_count=2, batch_size=3, drop_last=False,
                           shuffle_row_groups=False) as reader:
        batches = list(reader)
    assert all(len(b._fields) == 1000 for b in batches)
    sizes = sorted(len(b.col_0) for b in batches)
    assert sum(sizes) == 10 and max(sizes) == 3
