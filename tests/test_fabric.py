"""Fault-tolerant peer-to-peer chunk fabric (docs/fabric.md).

Covers the fabric's failure contract end to end:

  * wire protocol: framed round-trips, truncation/garbage rejection, the
    end-to-end Deadline budget;
  * per-peer circuit breaker state machine (trip, cooldown, half-open probe);
  * client behavior against a live server: verified peer fetch, miss vs
    failure classification, corrupt/reset/truncated/stalled payloads all
    degrading to the object-store fallback without failing the fetch;
  * mirror files pinned against eviction while being served to a peer;
  * the chunkstore's per-digest single-flight (exactly-once population);
  * the executable spec (analysis/protocol/fabric_spec.py): exhaustion over
    the default scope above the state floor, a counterexample per seeded
    mutation, and random-walk conformance between spec and runtime monitor;
  * the chaos drill: >=3 hosts on a mock-remote store, one peer SIGKILLed
    mid-transfer and another serving reset+truncated payloads — the reader
    completes its epoch, mirrors hash-verify, no chunk is populated twice,
    and every failed peer fetch is accounted as a fallback.
"""

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from petastorm_tpu import fabric, faults
from petastorm_tpu.analysis.protocol import fabric_spec
from petastorm_tpu.analysis.protocol.monitor import FabricMonitor
from petastorm_tpu.chunkstore import ChunkCacheConfig, cache_diagnostics
from petastorm_tpu.chunkstore.store import ChunkStore
from petastorm_tpu.errors import ProtocolViolation
from petastorm_tpu.fabric import protocol as P
from petastorm_tpu.fabric.breaker import CircuitBreaker
from petastorm_tpu.fabric.peers import PeerInfo, rank_peers
from petastorm_tpu.fabric.server import FabricServer


# ---------------------------------------------------------------------------
# wire protocol
# ---------------------------------------------------------------------------

def _pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


class TestProtocol:
    def test_frame_roundtrip(self):
        a, b = _pair()
        try:
            deadline = P.Deadline(5.0)
            P.send_frame(a, P.encode_request('chunk-key', 123), deadline, 1.0)
            msg = P.decode_message(P.recv_frame(b, deadline, 1.0))
            assert msg == {'v': 1, 'op': 'get', 'key': 'chunk-key',
                           'length': 123}
        finally:
            a.close()
            b.close()

    def test_message_encodings(self):
        ok = P.decode_message(P.encode_ok(42, 'ab' * 32))
        assert ok['status'] == 'ok' and ok['length'] == 42
        assert ok['sha256'] == 'ab' * 32
        assert P.decode_message(P.encode_miss())['status'] == 'miss'
        err = P.decode_message(P.encode_error('x' * 2000))
        assert err['status'] == 'error' and len(err['message']) <= 512

    def test_truncated_stream_is_protocol_error(self):
        """EOF mid-payload must raise, never return short bytes."""
        a, b = _pair()
        try:
            a.sendall(b'abc')
            a.close()
            with pytest.raises(P.FabricProtocolError):
                P.recv_exactly(b, 10, P.Deadline(5.0), 1.0)
        finally:
            b.close()

    def test_bad_magic_rejected(self):
        a, b = _pair()
        try:
            a.sendall(struct.pack('>4sI', b'NOPE', 4) + b'body')
            with pytest.raises(P.FabricProtocolError):
                P.recv_frame(b, P.Deadline(5.0), 1.0)
        finally:
            a.close()
            b.close()

    def test_oversized_frame_rejected(self):
        a, b = _pair()
        try:
            a.sendall(struct.pack('>4sI', P.MAGIC, P.MAX_FRAME_BYTES + 1))
            with pytest.raises(P.FabricProtocolError):
                P.recv_frame(b, P.Deadline(5.0), 1.0)
        finally:
            a.close()
            b.close()

    def test_content_hash_is_sha256(self):
        import hashlib
        assert P.content_hash(b'abc') == hashlib.sha256(b'abc').hexdigest()

    def test_deadline_budget(self):
        clock = [0.0]
        d = P.Deadline(10.0, clock=lambda: clock[0])
        assert d.remaining() == pytest.approx(10.0)
        # per-op timeout is capped by BOTH the op cap and what remains
        assert d.op_timeout(2.0) == pytest.approx(2.0)
        clock[0] = 9.5
        assert d.op_timeout(2.0) == pytest.approx(0.5)
        clock[0] = 10.5
        assert d.expired
        with pytest.raises(P.FabricTimeout):
            d.op_timeout(2.0)


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def test_trips_after_threshold_and_reports_transition(self):
        clock = [0.0]
        b = CircuitBreaker(failure_threshold=3, reset_after_s=5.0,
                           clock=lambda: clock[0])
        assert b.state == fabric.CLOSED and b.allow()
        assert b.record_failure() is False
        assert b.record_failure() is False
        assert b.record_failure() is True  # THIS failure opened it
        assert b.state == fabric.OPEN
        assert not b.allow()
        assert b.record_failure() is False  # already open: no new transition

    def test_half_open_probe_is_single_flight(self):
        clock = [0.0]
        b = CircuitBreaker(failure_threshold=1, reset_after_s=5.0,
                           clock=lambda: clock[0])
        assert b.record_failure() is True
        clock[0] = 4.9
        assert not b.allow()
        clock[0] = 5.1
        assert b.allow()            # the one half-open probe
        assert b.state == fabric.HALF_OPEN
        assert not b.allow()        # a second concurrent probe is refused
        b.record_success()
        assert b.state == fabric.CLOSED and b.allow()

    def test_half_open_failure_reopens_immediately(self):
        clock = [0.0]
        b = CircuitBreaker(failure_threshold=3, reset_after_s=1.0,
                           clock=lambda: clock[0])
        for _ in range(3):
            b.record_failure()
        clock[0] = 1.5
        assert b.allow()
        assert b.record_failure() is True  # a failed probe re-opens at once
        assert b.state == fabric.OPEN and not b.allow()

    def test_success_resets_consecutive_count(self):
        b = CircuitBreaker(failure_threshold=2)
        b.record_failure()
        b.record_success()
        assert b.record_failure() is False  # count restarted
        assert b.state == fabric.CLOSED


# ---------------------------------------------------------------------------
# rendezvous ranking
# ---------------------------------------------------------------------------

def test_rank_peers_is_stable_and_spreads_load():
    peerset = [PeerInfo('p{}'.format(i), '127.0.0.1', 9000 + i)
               for i in range(4)]
    first = {}
    for i in range(64):
        digest = ChunkStore.digest('chunk-{}'.format(i))
        ranked = rank_peers(digest, peerset)
        assert sorted(p.host for p in ranked) == ['p0', 'p1', 'p2', 'p3']
        assert [p.host for p in rank_peers(digest, peerset)] == \
            [p.host for p in ranked]  # deterministic
        first[ranked[0].host] = first.get(ranked[0].host, 0) + 1
    # every peer is rendezvous-best for SOME chunks (no hot spot by design)
    assert len(first) == 4


# ---------------------------------------------------------------------------
# runtime monitor
# ---------------------------------------------------------------------------

class TestFabricMonitor:
    def test_double_populate_without_invalidation_raises(self):
        m = FabricMonitor('t')
        m.on_populate('d1', verified=True)
        with pytest.raises(ProtocolViolation):
            m.on_populate('d1', verified=True)

    def test_invalidation_reopens_population(self):
        m = FabricMonitor('t')
        m.on_populate('d1', verified=True)
        m.on_invalidate('d1')
        m.on_populate('d1', verified=True)  # legitimate after eviction

    def test_unverified_bytes_raise(self):
        m = FabricMonitor('t')
        with pytest.raises(ProtocolViolation):
            m.on_populate('d1', verified=False)

    def test_request_to_open_breaker_raises(self):
        m = FabricMonitor('t')
        m.on_request('pA', allowed=True)
        with pytest.raises(ProtocolViolation):
            m.on_request('pA', allowed=False)

    def test_unknown_outcome_raises(self):
        m = FabricMonitor('t')
        m.on_outcome('k', 'peer')
        m.on_outcome('k', 'fallback')
        m.on_outcome('k', 'error')
        with pytest.raises(ProtocolViolation):
            m.on_outcome('k', 'gave-up')


# ---------------------------------------------------------------------------
# chunkstore: per-digest single-flight + send pins
# ---------------------------------------------------------------------------

def test_concurrent_ensure_fetches_exactly_once(tmp_path):
    """The whole miss path is single-flight per digest: N threads demanding
    the same chunk produce ONE fetch and ONE mirror write; the rest account
    hits. This is the per-host exactly-once the fabric spec demands."""
    store = ChunkStore(str(tmp_path / 'c'))
    calls = []
    gate = threading.Event()

    def fetch():
        calls.append(1)
        gate.wait(timeout=5.0)  # hold the leader so followers really queue
        return b'x' * 64

    results = []
    threads = [threading.Thread(
        target=lambda: results.append(store.ensure('k', 64, fetch)))
        for _ in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.2)  # let every follower reach the fetch mutex
    gate.set()
    for t in threads:
        t.join(timeout=10)
    assert len(calls) == 1
    assert len(results) == 6
    assert len({r[0] for r in results}) == 1
    snap = store.stats_snapshot()
    assert snap['misses'] == 1
    assert snap['hits'] == 5


def test_send_pin_refuses_eviction_then_releases(tmp_path):
    """A mirror being streamed to a peer must survive the evictor: the
    in-flight send holds a pin, the skip is counted, and eviction proceeds
    once the transfer ends."""
    store = ChunkStore(str(tmp_path / 'c'), size_limit_bytes=150)
    path_a, _, _ = store.ensure('a', 100, lambda: b'a' * 100)
    os.utime(path_a, ns=(1, 1))  # unambiguously the LRU victim
    with store.pin_for_send('a') as pinned:
        assert pinned == path_a
        store.ensure('b', 100, lambda: b'b' * 100)  # 200 > 150: wants 'a'
        assert os.path.exists(path_a), 'evictor truncated an in-flight send'
        snap = store.stats_snapshot()
        assert snap['evict_skipped_pinned'] >= 1
    # pin released: the next over-budget population may now take 'a'
    store.ensure('c', 100, lambda: b'c' * 100)
    assert not os.path.exists(path_a)
    snap = store.stats_snapshot()
    assert snap['chunks_evicted'] >= 1


def test_pin_for_send_reports_absent_chunk(tmp_path):
    store = ChunkStore(str(tmp_path / 'c'))
    with store.pin_for_send('never-populated') as pinned:
        assert pinned is None


# ---------------------------------------------------------------------------
# client vs live server: the degradation matrix
# ---------------------------------------------------------------------------

class _StaticPeers(object):
    """PeerRegistry stand-in: a fixed peer list, no membership machinery."""

    def __init__(self, host_id, peerset):
        self.host_id = host_id
        self._peers = list(peerset)

    def alive_peers(self):
        return list(self._peers)


def _serving_pair(tmp_path, chunks=('k1', 'k2', 'k3'), length=4096):
    """A served store (populated) + an empty local store for the fetcher."""
    served = ChunkStore(str(tmp_path / 'served'))
    payloads = {}
    for i, key in enumerate(chunks):
        payloads[key] = bytes([i % 251]) * length
        served.ensure(key, length, lambda i=i: payloads[chunks[i]])
    local = ChunkStore(str(tmp_path / 'local'))
    server = FabricServer(served).start()
    return served, local, server, payloads


def _client_for(local, server, tmp_path, **kwargs):
    peerset = [PeerInfo('pA', server.endpoint[0], server.endpoint[1])]
    defaults = dict(deadline_s=5.0, io_timeout_s=1.0, connect_timeout_s=1.0,
                    failure_threshold=3, breaker_reset_s=5.0)
    defaults.update(kwargs)
    return fabric.FabricClient(local, _StaticPeers('pSelf', peerset),
                               str(tmp_path / 'coord'), **defaults)


class TestClientServer:
    def test_peer_fetch_verified_end_to_end(self, tmp_path):
        served, local, server, payloads = _serving_pair(tmp_path)
        fallback_calls = []
        try:
            monitor = FabricMonitor('t')
            with _client_for(local, server, tmp_path,
                             monitor=monitor) as client:
                def fetch_fn():
                    fallback_calls.append(1)
                    return payloads['k1']
                data = client.fetch('k1', len(payloads['k1']), fetch_fn)
            assert data == payloads['k1']
            assert not fallback_calls, 'peer path must not touch the store'
            assert monitor.events_checked > 0
        finally:
            server.stop()
        stats_dir = os.path.join(str(tmp_path / 'coord'), 'fabric', 'stats')
        files = os.listdir(stats_dir)
        assert len(files) == 1
        with open(os.path.join(stats_dir, files[0])) as f:
            snap = json.load(f)
        assert snap['peers']['pA']['hits'] == 1
        assert snap['peers']['pA']['bytes'] == len(payloads['k1'])
        assert snap['breakers']['pA'] == fabric.CLOSED

    def test_peer_miss_falls_back_without_breaker_penalty(self, tmp_path):
        served, local, server, payloads = _serving_pair(tmp_path)
        try:
            with _client_for(local, server, tmp_path) as client:
                data = client.fetch('absent-key', 128, lambda: b'f' * 128)
                assert data == b'f' * 128
                # a miss means "healthy peer, does not mirror this chunk":
                # the breaker must not move
                assert client._breaker_for('pA').state == fabric.CLOSED
        finally:
            server.stop()

    def test_corrupt_payload_discarded_and_degrades(self, tmp_path):
        """A payload failing the sha256 must be discarded (fallback bytes
        win) and count as a peer failure."""
        served, local, server, payloads = _serving_pair(tmp_path)
        faults.install_net(faults.NetFaultPlan(corrupt_payloads=1))
        try:
            with _client_for(local, server, tmp_path) as client:
                data = client.fetch('k1', len(payloads['k1']),
                                    lambda: payloads['k1'])
                assert data == payloads['k1']
                b = client._breaker_for('pA')
                assert b.state == fabric.CLOSED  # one failure, threshold 3
                # next fetch is clean again: the peer still serves
                assert client.fetch('k2', len(payloads['k2']),
                                    lambda: payloads['k2']) == payloads['k2']
        finally:
            faults.uninstall_net()
            server.stop()

    def test_reset_and_truncation_degrade_to_fallback(self, tmp_path):
        served, local, server, payloads = _serving_pair(tmp_path)
        faults.install_net(faults.NetFaultPlan(reset_payloads=1,
                                               truncate_payloads=1))
        try:
            with _client_for(local, server, tmp_path) as client:
                for key in ('k1', 'k2'):
                    data = client.fetch(key, len(payloads[key]),
                                        lambda key=key: payloads[key])
                    assert data == payloads[key]
        finally:
            faults.uninstall_net()
            server.stop()

    def test_stalled_peer_bounded_by_deadline(self, tmp_path):
        """A stalled transfer must cost at most the deadline budget, then
        degrade — never wedge the fetch."""
        served, local, server, payloads = _serving_pair(tmp_path)
        faults.install_net(faults.NetFaultPlan(stall_payloads=1, stall_s=30.0))
        try:
            with _client_for(local, server, tmp_path, deadline_s=1.0,
                             io_timeout_s=0.3) as client:
                t0 = time.monotonic()
                data = client.fetch('k1', len(payloads['k1']),
                                    lambda: payloads['k1'])
                elapsed = time.monotonic() - t0
            assert data == payloads['k1']
            assert elapsed < 10.0
        finally:
            faults.uninstall_net()
            server.stop()

    def test_breaker_opens_and_sheds_after_k_failures(self, tmp_path):
        """A dead peer costs exactly K connection attempts, then zero: the
        open breaker routes every later fetch straight to the fallback."""
        served, local, server, payloads = _serving_pair(tmp_path)
        endpoint = server.endpoint
        server.stop()  # peer is now refusing connections
        peerset = [PeerInfo('pA', endpoint[0], endpoint[1])]
        connect_attempts = []
        orig_on_net_connect = faults.on_net_connect

        def counting_connect():
            connect_attempts.append(1)
            return orig_on_net_connect()

        faults.on_net_connect = counting_connect
        try:
            with fabric.FabricClient(
                    local, _StaticPeers('pSelf', peerset),
                    str(tmp_path / 'coord'), deadline_s=5.0,
                    io_timeout_s=1.0, connect_timeout_s=0.5,
                    failure_threshold=2, breaker_reset_s=5.0) as client:
                for i in range(5):
                    data = client.fetch('k-{}'.format(i), 64, lambda: b'z' * 64)
                    assert data == b'z' * 64
                assert client._breaker_for('pA').state == fabric.OPEN
        finally:
            faults.on_net_connect = orig_on_net_connect
        assert len(connect_attempts) == 2, \
            'an open breaker must shed load (zero round trips)'


# ---------------------------------------------------------------------------
# executable spec + model checker
# ---------------------------------------------------------------------------

class TestFabricSpec:
    def test_default_scope_exhausts_above_state_floor(self):
        cfg = fabric_spec.FabricSpecConfig(**fabric_spec.DEFAULT_FABRIC_SCOPE)
        res = fabric_spec.check(cfg, budget_s=300)
        assert res.exhausted, 'default scope must exhaust in the tier-1 budget'
        assert res.violation is None
        assert res.states >= fabric_spec.DEFAULT_FABRIC_STATE_FLOOR

    @pytest.mark.parametrize('mutation,invariant', [
        ('skip_hash_check', 'hash_verified'),
        ('double_populate', 'populate_once'),
        ('request_open_peer', 'breaker_discipline'),
        ('no_fallback', 'fetch_termination'),
    ])
    def test_every_mutation_yields_a_counterexample(self, mutation, invariant):
        """Each seeded protocol bug must be CAUGHT: a checker that exhausts
        cleanly over a broken protocol is checking nothing."""
        cfg = fabric_spec.FabricSpecConfig(
            mutation=mutation, **fabric_spec.DEFAULT_FABRIC_SCOPE)
        res = fabric_spec.check(cfg, budget_s=300)
        assert res.violation is not None
        assert res.violation == invariant
        assert res.trace, 'a counterexample must carry its trace'

    @pytest.mark.parametrize('mutation', ['skip_hash_check', 'double_populate',
                                          'request_open_peer'])
    def test_counterexample_replays_into_monitor(self, mutation):
        """The runtime monitor is the spec's observable projection: a safety
        counterexample trace must trip it too."""
        cfg = fabric_spec.FabricSpecConfig(
            mutation=mutation, **fabric_spec.DEFAULT_FABRIC_SCOPE)
        res = fabric_spec.check(cfg, budget_s=300)
        with pytest.raises(ProtocolViolation):
            fabric_spec.replay_into_monitor(res.trace, FabricMonitor('t'))

    def test_random_walks_conform_to_monitor(self):
        """Healthy-protocol walks must never trip the monitor (no false
        positives), across many seeds."""
        cfg = fabric_spec.FabricSpecConfig(**fabric_spec.DEFAULT_FABRIC_SCOPE)
        checked = 0
        for seed in range(25):
            trace, violation = fabric_spec.random_walk(cfg, seed=seed)
            assert violation is None, \
                'seed {}: healthy walk hit {}'.format(seed, violation)
            monitor = FabricMonitor('walk-{}'.format(seed))
            fabric_spec.replay_into_monitor(trace, monitor)
            checked += monitor.events_checked
        assert checked > 0

    def test_modelcheck_cli_exit_code_contract(self):
        """--fabric honors the worker/serve/elastic exit-code contract:
        0 exhausted-clean, 1 counterexample, 2 usage, 3 below the floor."""
        base = [sys.executable, '-m',
                'petastorm_tpu.analysis.protocol.modelcheck']
        clean = subprocess.run(
            base + ['--fabric', '--budget-s', '300',
                    '--min-states',
                    str(fabric_spec.DEFAULT_FABRIC_STATE_FLOOR)],
            capture_output=True, text=True, timeout=420)
        assert clean.returncode == 0, clean.stdout + clean.stderr
        assert 'exhausted: all invariants hold' in clean.stdout

        bad = subprocess.run(
            base + ['--fabric', '--mutate', 'double_populate',
                    '--budget-s', '300'],
            capture_output=True, text=True, timeout=420)
        assert bad.returncode == 1, bad.stdout + bad.stderr
        assert 'counterexample' in bad.stdout

        usage = subprocess.run(base + ['--fabric', '--elastic'],
                               capture_output=True, text=True, timeout=120)
        assert usage.returncode == 2
        assert 'mutually exclusive' in usage.stderr


# ---------------------------------------------------------------------------
# diagnose --fabric
# ---------------------------------------------------------------------------

def test_diagnose_fabric_merges_stats(tmp_path):
    from petastorm_tpu.observability import diagnose
    stats_dir = tmp_path / 'coord' / 'fabric' / 'stats'
    stats_dir.mkdir(parents=True)
    (stats_dir / 'hA-pid1.json').write_text(json.dumps({
        'host': 'hA',
        'peers': {'pX': {'hits': 4, 'failures': 1, 'fallbacks': 1,
                         'bytes': 4096, 'latency_sum': 0.2, 'latency_n': 4}},
        'breakers': {'pX': 'closed'}}))
    (stats_dir / 'hB-pid2.json').write_text(json.dumps({
        'host': 'hB',
        'peers': {'pX': {'hits': 2, 'failures': 3, 'fallbacks': 3,
                         'bytes': 2048, 'latency_sum': 0.1, 'latency_n': 2}},
        'breakers': {'pX': 'open'}}))
    table = diagnose.fabric_peer_table(str(tmp_path / 'coord'))
    assert table['pX']['hits'] == 6
    assert table['pX']['failures'] == 4
    assert table['pX']['fallbacks'] == 4
    assert table['pX']['bytes'] == 6144
    assert table['pX']['breaker'] == 'open'  # worst observed view wins
    assert table['pX']['mean_latency_ms'] == pytest.approx(50.0)
    rendered = diagnose.format_fabric_peers(table)
    assert 'pX' in rendered and 'open' in rendered
    assert diagnose.diagnose_fabric(str(tmp_path / 'coord')) == 0
    assert diagnose.diagnose_fabric(str(tmp_path / 'empty')) == 1


# ---------------------------------------------------------------------------
# the chaos drill
# ---------------------------------------------------------------------------

def _native_available():
    try:
        from petastorm_tpu import native
    except ImportError:
        return False
    return native.is_available()


def _write_raw_store(tmp_path, rows=48, image_size=16):
    from petastorm_tpu.codecs import RawTensorCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('Raw', [
        UnischemaField('image', np.uint8, (image_size, image_size, 3),
                       RawTensorCodec(), False),
        UnischemaField('label', np.int64, (), ScalarCodec(np.int64), False),
    ])
    rng = np.random.default_rng(0)
    data = [{'image': rng.integers(0, 255, (image_size, image_size, 3),
                                   np.uint8),
             'label': int(i)} for i in range(rows)]
    store = str(tmp_path / 'raw')
    write_petastorm_dataset('file://' + store, schema, iter(data),
                            rows_per_row_group=8, compression='none')
    return store, data


def _chunk_files(root):
    out = {}
    for dirpath, _dirs, files in os.walk(root):
        for name in files:
            if name.endswith('.chunk'):
                out[name[:-len('.chunk')]] = os.path.join(dirpath, name)
    return out


@pytest.mark.skipif(not _native_available(),
                    reason='chunk mirrors need the native page scanner')
def test_chaos_drill_sigkill_and_network_faults(tmp_path):
    """The drill from docs/fabric.md: three hosts on one mock-remote store.
    Peer A (a real subprocess) stalls every payload and is SIGKILLed
    mid-transfer; peer B serves one reset and one truncated payload; host C
    reads a full epoch through a thread pool. C must finish the epoch with
    byte-correct data, every mirror hash-verified against B's, exactly-once
    population, and peer hits + fallbacks exactly accounting every miss."""
    from petastorm_tpu import make_reader

    store_path, data = _write_raw_store(tmp_path)
    url = 'mock-remote://' + store_path
    coord = str(tmp_path / 'coord')
    marker = str(tmp_path / 'pA-request')
    ready = str(tmp_path / 'pA-ready')

    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env['PYTHONPATH'] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get('PYTHONPATH', ''))
    proc_a = subprocess.Popen(
        [sys.executable, '-m', 'petastorm_tpu.fabric._peerproc',
         '--url', url, '--coord', coord, '--host', 'pA',
         '--cache-root', str(tmp_path / 'cacheA'), '--lease-s', '2.0',
         '--stall-s', '30.0', '--request-marker', marker,
         '--ready-file', ready], env=env)

    cache_b = ChunkCacheConfig(str(tmp_path / 'cacheB'))
    cache_c = ChunkCacheConfig(str(tmp_path / 'cacheC'))
    node_b = node_c = None
    killed = []

    def killer():
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if os.path.exists(marker):
                os.kill(proc_a.pid, signal.SIGKILL)  # mid-transfer: A is
                killed.append(True)                  # stalling inside a send
                return
            time.sleep(0.05)

    try:
        # peer B: warm its full mirror (no fabric yet), then serve it
        with make_reader(url, reader_pool_type='dummy',
                         shuffle_row_groups=False,
                         chunk_cache=cache_b) as reader:
            for _ in reader:
                pass
        node_b = fabric.start_node(fabric.FabricConfig(
            coord, 'pB', cache_b, lease_s=2.0))

        deadline = time.monotonic() + 120
        while not os.path.exists(ready):
            assert proc_a.poll() is None, 'peer A died during warmup'
            assert time.monotonic() < deadline, 'peer A never became ready'
            time.sleep(0.1)

        # peer B mangles its first two payloads
        faults.install_net(faults.NetFaultPlan(reset_payloads=1,
                                               truncate_payloads=1))
        kill_thread = threading.Thread(target=killer, daemon=True)
        kill_thread.start()

        node_c = fabric.start_node(
            fabric.FabricConfig(coord, 'pC', cache_c, lease_s=2.0,
                                deadline_s=1.5, io_timeout_s=0.5,
                                connect_timeout_s=0.5, failure_threshold=3,
                                breaker_reset_s=5.0),
            monitor=FabricMonitor('drill'))
        fabric.install(node_c)
        with make_reader(url, reader_pool_type='thread', workers_count=3,
                         shuffle_row_groups=False, num_epochs=1,
                         chunk_cache=cache_c) as reader:
            rows = {int(r.label): r.image for r in reader}
        kill_thread.join(timeout=60)
    finally:
        fabric.uninstall()
        faults.uninstall_net()
        if node_c is not None:
            node_c.stop()
        if node_b is not None:
            node_b.stop()
        proc_a.kill()
        proc_a.wait(timeout=30)

    # the epoch completed with byte-correct data despite every fault
    assert sorted(rows) == [row['label'] for row in data]
    for row in data:
        np.testing.assert_array_equal(rows[row['label']], row['image'])
    assert killed, 'peer A was never killed — the drill did not run'
    assert os.path.exists(marker), 'peer A never received a request'

    # every mirror hash-verifies: C's chunk files must be byte-identical to
    # B's warm mirror of the same digests
    files_b = _chunk_files(cache_b.root)
    files_c = _chunk_files(cache_c.root)
    assert files_c, 'host C mirrored nothing'
    for digest, path in files_c.items():
        assert digest in files_b
        with open(path, 'rb') as fc, open(files_b[digest], 'rb') as fb:
            assert fc.read() == fb.read(), \
                'mirror {} differs from the reference'.format(digest)

    # exactly-once population per host: one fetch per distinct chunk
    # (demand misses + prefetch fetches together cover the mirror exactly),
    # and a second epoch over the warm mirror adds none
    diag = cache_diagnostics(cache_c)
    populated = (diag['chunk_cache_misses'] +
                 diag['chunk_cache_prefetch_chunks'])
    assert populated == len(files_c)
    with make_reader(url, reader_pool_type='dummy', shuffle_row_groups=False,
                     num_epochs=1, chunk_cache=cache_c) as reader:
        for _ in reader:
            pass
    diag2 = cache_diagnostics(cache_c)
    assert (diag2['chunk_cache_misses'] +
            diag2['chunk_cache_prefetch_chunks']) == populated

    # accounting: every miss resolved as a peer copy or a fallback — and
    # every failed peer fetch is visible as a fallback, never a retry loop
    stats_path = os.path.join(coord, 'fabric', 'stats',
                              'pC-pid{}.json'.format(os.getpid()))
    with open(stats_path) as f:
        stats = json.load(f)
    peer_hits = sum(s['hits'] for s in stats['peers'].values())
    fallbacks = sum(s['fallbacks'] for s in stats['peers'].values())
    assert peer_hits + fallbacks == populated
    assert peer_hits > 0, 'no chunk ever rode the fabric'
    assert fallbacks > 0, 'the faults never forced a fallback'
    # the stalled/killed peer contributed failures, never a hit
    assert stats['peers'].get('pA', {}).get('hits', 0) == 0
    assert stats['peers'].get('pA', {}).get('failures', 0) >= 1


@pytest.mark.skipif(not _native_available(),
                    reason='chunk mirrors need the native page scanner')
def test_healthy_two_host_fabric_copies_chunks_once(tmp_path):
    """No faults: host 2's epoch sources every chunk from host 1's mirror —
    zero object-store fallbacks after the first host's reads."""
    from petastorm_tpu import make_reader

    store_path, data = _write_raw_store(tmp_path, rows=24)
    url = 'mock-remote://' + store_path
    coord = str(tmp_path / 'coord')
    cache_1 = ChunkCacheConfig(str(tmp_path / 'cache1'))
    cache_2 = ChunkCacheConfig(str(tmp_path / 'cache2'))
    node_1 = node_2 = None
    try:
        with make_reader(url, reader_pool_type='dummy',
                         shuffle_row_groups=False,
                         chunk_cache=cache_1) as reader:
            for _ in reader:
                pass
        node_1 = fabric.start_node(fabric.FabricConfig(coord, 'h1', cache_1))
        node_2 = fabric.start_node(fabric.FabricConfig(coord, 'h2', cache_2),
                                   monitor=FabricMonitor('healthy'))
        fabric.install(node_2)
        with make_reader(url, reader_pool_type='thread', workers_count=2,
                         shuffle_row_groups=False, num_epochs=1,
                         chunk_cache=cache_2) as reader:
            labels = sorted(int(r.label) for r in reader)
        assert labels == [row['label'] for row in data]
    finally:
        fabric.uninstall()
        if node_2 is not None:
            node_2.stop()
        if node_1 is not None:
            node_1.stop()
    stats_path = os.path.join(coord, 'fabric', 'stats',
                              'h2-pid{}.json'.format(os.getpid()))
    with open(stats_path) as f:
        stats = json.load(f)
    diag = cache_diagnostics(cache_2)
    assert stats['peers']['h1']['hits'] == (
        diag['chunk_cache_misses'] + diag['chunk_cache_prefetch_chunks'])
    assert sum(s['fallbacks'] for s in stats['peers'].values()) == 0
