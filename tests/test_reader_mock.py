"""generator + ReaderMock: schema-conformant synthetic rows, and adapter tests
that need no storage (reference test_util/reader_mock.py pattern)."""

import numpy as np
import pytest

from petastorm_tpu.generator import generate_datapoint
from petastorm_tpu.test_util.dataset_utils import TestSchema
from petastorm_tpu.test_util.reader_mock import ReaderMock
from petastorm_tpu.unischema import Unischema, UnischemaField, encode_row, decode_row


def test_generate_datapoint_conforms_and_roundtrips(rng):
    row = generate_datapoint(TestSchema, rng=rng)
    assert set(row) == set(TestSchema.fields)
    # proof of schema conformance: every codec accepts the generated value
    encoded = encode_row(TestSchema, dict(row))
    decoded = decode_row(encoded, TestSchema)
    assert set(decoded) == set(row)
    assert decoded['matrix'].shape == (32, 16, 3)
    assert decoded['image_png'].dtype == np.uint8


def test_generate_datapoint_deterministic_with_seed():
    a = generate_datapoint(TestSchema, rng=np.random.default_rng(7))
    b = generate_datapoint(TestSchema, rng=np.random.default_rng(7))
    np.testing.assert_array_equal(a['matrix'], b['matrix'])
    assert a['decimal'] == b['decimal']


def test_generate_datapoint_wildcard_dims():
    schema = Unischema('S', [UnischemaField('v', np.float32, (None, 4))])
    row = generate_datapoint(schema, list_size=5)
    assert row['v'].shape == (5, 4)


def test_reader_mock_row_iteration():
    with ReaderMock(TestSchema, num_rows=7) as reader:
        rows = list(reader)
    assert len(rows) == 7
    assert not reader.batched_output
    assert rows[0].matrix.shape == (32, 16, 3)
    assert isinstance(rows[0].id, (int, np.integer))


def test_reader_mock_infinite_by_default():
    reader = ReaderMock(TestSchema)
    taken = [next(reader) for _ in range(3)]
    assert len(taken) == 3
    reader.stop()


def test_reader_mock_batched_output():
    schema = Unischema('S', [UnischemaField('id', np.int64, ()),
                             UnischemaField('x', np.float32, (3,))])
    with ReaderMock(schema, num_rows=4, batch_size=5) as reader:
        batches = list(reader)
    assert reader.batched_output
    assert len(batches) == 4
    assert batches[0].x.shape == (5, 3)


def test_reader_mock_reset():
    reader = ReaderMock(TestSchema, num_rows=2)
    assert len(list(reader)) == 2
    reader.reset()
    assert len(list(reader)) == 2


def test_reader_mock_rejects_ngram():
    with pytest.raises(ValueError, match='NGram'):
        ReaderMock(TestSchema, ngram=object())


def test_jax_loader_over_reader_mock():
    """Adapter tested in isolation — no storage (reference test_tf_utils pattern)."""
    from petastorm_tpu.jax import JaxDataLoader
    schema = Unischema('S', [UnischemaField('id', np.int64, ()),
                             UnischemaField('x', np.float32, (4,))])
    with ReaderMock(schema, num_rows=10, seed=3) as reader:
        loader = JaxDataLoader(reader, batch_size=4, drop_last=True)
        batches = list(loader)
    assert len(batches) == 2
    assert batches[0]['x'].shape == (4, 4)


def test_jax_loader_over_batched_reader_mock():
    from petastorm_tpu.jax import JaxDataLoader
    schema = Unischema('S', [UnischemaField('x', np.float32, (2,))])
    with ReaderMock(schema, num_rows=6, batch_size=5, seed=3) as reader:
        loader = JaxDataLoader(reader, batch_size=10, drop_last=True)
        batches = list(loader)
    assert len(batches) == 3
    assert batches[0]['x'].shape == (10, 2)
