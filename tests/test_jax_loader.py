"""JAX adapter tests: loader collation, device staging, mesh sharding
(runs on 8 virtual CPU devices — see conftest)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from petastorm_tpu import make_batch_reader, make_reader, TransformSpec
from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.jax import JaxDataLoader, make_jax_dataset, prefetch_to_device
from petastorm_tpu.parallel import (data_sharding, make_global_batch, make_mesh,
                                    process_local_batch_size, reader_shard_for_process)


FIXED_FIELDS = ['id', 'matrix', 'id_float']


def test_loader_batches_fixed_shapes(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     schema_fields=FIXED_FIELDS, shuffle_row_groups=False) as reader:
        batches = list(JaxDataLoader(reader, batch_size=32))
    assert len(batches) == 3  # 100 rows, drop_last=True
    b = batches[0]
    assert b['matrix'].shape == (32, 32, 16, 3)
    assert b['id'].shape == (32,)
    assert isinstance(b['id'], np.ndarray)  # host batch by default


def test_loader_keep_last(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     schema_fields=['id'], shuffle_row_groups=False) as reader:
        batches = list(JaxDataLoader(reader, batch_size=32, drop_last=False))
    assert [len(b['id']) for b in batches] == [32, 32, 32, 4]
    all_ids = np.concatenate([b['id'] for b in batches])
    assert sorted(all_ids.tolist()) == list(range(100))


def test_loader_to_device(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     schema_fields=['id', 'matrix'], shuffle_row_groups=False) as reader:
        batch = next(iter(JaxDataLoader(reader, batch_size=16,
                                        to_device=jax.devices()[0])))
    assert isinstance(batch['id'], jax.Array)
    assert batch['matrix'].dtype == jnp.float32


def test_loader_sharded_across_mesh(synthetic_dataset):
    mesh = make_mesh(('data',))
    sharding = data_sharding(mesh)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     schema_fields=['id', 'matrix'], shuffle_row_groups=False) as reader:
        batch = next(iter(JaxDataLoader(reader, batch_size=16, to_device=sharding)))
    assert isinstance(batch['id'], jax.Array)
    assert batch['id'].sharding == sharding
    # each of the 8 devices holds 2 rows
    assert len(batch['id'].addressable_shards) == 8
    assert batch['id'].addressable_shards[0].data.shape == (2,)
    # jit computation over the sharded array works
    total = jax.jit(lambda x: jnp.sum(x))(batch['id'])
    assert int(total) == sum(range(16))


def test_loader_shuffling_buffer(synthetic_dataset):
    def ids_with(capacity, seed):
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         schema_fields=['id'], shuffle_row_groups=False) as reader:
            loader = JaxDataLoader(reader, batch_size=10, drop_last=False,
                                   shuffling_queue_capacity=capacity, seed=seed)
            return np.concatenate([b['id'] for b in loader]).tolist()

    plain = ids_with(0, None)
    assert plain == list(range(100))
    shuffled = ids_with(50, 3)
    assert sorted(shuffled) == list(range(100))
    assert shuffled != plain
    assert ids_with(50, 3) == shuffled  # seeded => reproducible


def test_loader_strings_stay_on_host(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     schema_fields=['id', 'partition_key'],
                     shuffle_row_groups=False) as reader:
        batch = next(iter(JaxDataLoader(reader, batch_size=8,
                                        to_device=jax.devices()[0])))
    assert isinstance(batch['id'], jax.Array)
    assert isinstance(batch['partition_key'], np.ndarray)
    assert batch['partition_key'].dtype == object


def test_loader_nonuniform_shape_raises_helpfully(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     schema_fields=['id', 'matrix_string'],
                     shuffle_row_groups=False) as reader:
        with pytest.raises(PetastormTpuError, match='TransformSpec'):
            next(iter(JaxDataLoader(reader, batch_size=8)))


def test_loader_from_batch_reader(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                           schema_fields=['id', 'float64', 'int_fixed_size_list'],
                           shuffle_row_groups=False) as reader:
        batches = list(JaxDataLoader(reader, batch_size=25))
    assert len(batches) == 4
    assert batches[0]['int_fixed_size_list'].shape == (25, 3)
    ids = np.concatenate([b['id'] for b in batches])
    assert sorted(ids.tolist()) == list(range(100))


def test_loader_decimal_promoted(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     schema_fields=['id', 'decimal'], shuffle_row_groups=False) as reader:
        batch = next(iter(JaxDataLoader(reader, batch_size=8)))
    assert batch['decimal'].dtype == np.float64


def test_ngram_loader_batches(synthetic_dataset):
    from petastorm_tpu.ngram import NGram
    from petastorm_tpu.test_util.dataset_utils import TestSchema
    ngram = NGram({0: [TestSchema.id, TestSchema.matrix], 1: [TestSchema.id]},
                  delta_threshold=1, timestamp_field=TestSchema.id)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                     shuffle_row_groups=False) as reader:
        batch = next(iter(JaxDataLoader(reader, batch_size=4)))
    assert sorted(batch.keys()) == [0, 1]
    assert batch[0]['matrix'].shape == (4, 32, 16, 3)
    np.testing.assert_array_equal(batch[1]['id'], batch[0]['id'] + 1)


def test_prefetch_to_device(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     schema_fields=['id'], shuffle_row_groups=False) as reader:
        host_batches = JaxDataLoader(reader, batch_size=20)
        staged = list(prefetch_to_device(host_batches, jax.devices()[0], size=2))
    assert len(staged) == 5
    assert all(isinstance(b['id'], jax.Array) for b in staged)
    ids = np.concatenate([np.asarray(b['id']) for b in staged])
    assert sorted(ids.tolist()) == list(range(100))


def test_prefetch_with_sharding(synthetic_dataset):
    mesh = make_mesh(('data',))
    sharding = data_sharding(mesh)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     schema_fields=['id'], shuffle_row_groups=False) as reader:
        staged = list(prefetch_to_device(JaxDataLoader(reader, batch_size=16),
                                         sharding, size=2))
    assert all(b['id'].sharding == sharding for b in staged)


def test_make_jax_dataset(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     schema_fields=['id'], shuffle_row_groups=False) as reader:
        it = make_jax_dataset(reader, 50)
        assert len(next(it)['id']) == 50


class TestMeshHelpers:
    def test_make_mesh_default(self):
        mesh = make_mesh(('data',))
        assert mesh.devices.shape == (8,)

    def test_make_mesh_2d_with_wildcard(self):
        mesh = make_mesh(('data', 'model'), axis_shapes=(-1, 2))
        assert mesh.devices.shape == (4, 2)

    def test_make_mesh_bad_shape(self):
        with pytest.raises(ValueError):
            make_mesh(('data', 'model'), axis_shapes=(3, 2))

    def test_reader_shard_for_process(self):
        cur, count = reader_shard_for_process()
        assert (cur, count) == (0, 1)  # single-process test env

    def test_process_local_batch_size(self):
        assert process_local_batch_size(64) == 64

    def test_make_global_batch(self):
        mesh = make_mesh(('data',))
        sharding = data_sharding(mesh)
        local = {'x': np.arange(16, dtype=np.float32), 's': np.array(['a'] * 16, dtype=object)}
        global_batch = make_global_batch(local, sharding)
        assert isinstance(global_batch['x'], jax.Array)
        assert global_batch['s'].dtype == object


def test_shuffling_with_batch_reader_large_rowgroup(tmp_path):
    """A whole row group added at once must not overflow the shuffling buffer
    (regression: extra_capacity too small for columnar adds)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from petastorm_tpu.fs import path_to_url
    path = tmp_path / 'big_rg'
    path.mkdir()
    pq.write_table(pa.table({'id': np.arange(3000)}), str(path / 'f.parquet'),
                   row_group_size=3000)
    with make_batch_reader(path_to_url(path), reader_pool_type='dummy') as reader:
        loader = JaxDataLoader(reader, batch_size=64, shuffling_queue_capacity=100, seed=0)
        ids = np.concatenate([b['id'] for b in loader])
    assert len(ids) == 2944  # 3000 - ragged last batch dropped


def test_make_mesh_dict_shapes():
    mesh = make_mesh(('data', 'model'), axis_shapes={'model': 2})
    assert mesh.devices.shape == (4, 2)
    with pytest.raises(ValueError):
        make_mesh(('data',), axis_shapes={'bogus': 2})


def test_make_global_batch_datetime_stays_host():
    mesh = make_mesh(('data',))
    sharding = data_sharding(mesh)
    local = {'ts': np.array(['2024-01-01'] * 8, dtype='datetime64[ns]'),
             'x': np.arange(8, dtype=np.float32)}
    out = make_global_batch(local, sharding)
    assert isinstance(out['ts'], np.ndarray)  # host-side
    import jax as _jax
    assert isinstance(out['x'], _jax.Array)


def test_ngram_time_stack_feeds_sequence_sharding(synthetic_dataset):
    # windowed readout -> [B, T, ...] -> staged over a ('data','seq') mesh:
    # the data-side half of context parallelism (ring attention consumes this)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from petastorm_tpu.jax.loader import stack_ngram_time_axis
    from petastorm_tpu.ngram import NGram
    from petastorm_tpu.test_util.dataset_utils import TestSchema

    fields = {i: [TestSchema.id] for i in range(4)}
    ngram = NGram(fields, delta_threshold=1, timestamp_field=TestSchema.id)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                     shuffle_row_groups=False) as reader:
        batch = next(iter(JaxDataLoader(reader, batch_size=4)))
    stacked = stack_ngram_time_axis(batch)
    assert stacked['id'].shape == (4, 4)
    np.testing.assert_array_equal(stacked['id'][:, 1], stacked['id'][:, 0] + 1)

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4), ('data', 'seq'))
    sharding = NamedSharding(mesh, P('data', 'seq'))
    from petastorm_tpu.jax.infeed import stage_batch
    staged = stage_batch(stacked, sharding)
    assert staged['id'].sharding.is_equivalent_to(sharding, 2)
    np.testing.assert_array_equal(np.asarray(staged['id']), stacked['id'])
