"""Batched native PNG/JPEG decode (native/image_codec.cpp) vs the OpenCV path.

The native decoder must be bit-exact with ``CompressedImageCodec.decode`` for
every flavor it claims (PNG gray/RGB 8/16-bit, JPEG gray/RGB) and must cleanly
reject — so the codec falls back to OpenCV — everything else (palette/alpha
PNG, corrupt bytes). Reference behavior being matched:
/root/reference/petastorm/codecs.py:92-111 (per-image decode, RGB output).
"""

import io

import numpy as np
import pytest

from petastorm_tpu.codecs import CompressedImageCodec
from petastorm_tpu.native import image_codec
from petastorm_tpu.unischema import UnischemaField

cv2 = pytest.importorskip('cv2')

pytestmark = pytest.mark.skipif(not image_codec.is_available(),
                                reason='native image codec not built')

rng = np.random.default_rng(7)


def _png(arr):
    ok, buf = cv2.imencode('.png', arr if arr.ndim == 2 else cv2.cvtColor(arr, cv2.COLOR_RGB2BGR))
    assert ok
    return buf.tobytes()


def _jpeg(arr, quality=85):
    ok, buf = cv2.imencode('.jpeg', arr if arr.ndim == 2 else cv2.cvtColor(arr, cv2.COLOR_RGB2BGR),
                           [int(cv2.IMWRITE_JPEG_QUALITY), quality])
    assert ok
    return buf.tobytes()


def _cv2_decode(blob):
    img = cv2.imdecode(np.frombuffer(blob, np.uint8), cv2.IMREAD_UNCHANGED)
    if img.ndim == 3 and img.shape[2] == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return img


@pytest.mark.parametrize('shape,dtype,fmt', [
    ((37, 53, 3), np.uint8, 'png'),
    ((64, 64), np.uint8, 'png'),
    ((21, 33), np.uint16, 'png'),
    ((40, 56, 3), np.uint16, 'png'),
    ((37, 53, 3), np.uint8, 'jpeg'),
    ((64, 64), np.uint8, 'jpeg'),
    ((1, 1, 3), np.uint8, 'png'),
    ((1, 7), np.uint8, 'png'),
])
def test_native_matches_cv2(shape, dtype, fmt):
    hi = 65536 if dtype == np.uint16 else 256
    img = rng.integers(0, hi, shape, dtype=dtype)
    blob = _png(img) if fmt == 'png' else _jpeg(img)
    (out,) = image_codec.decode_images([blob])
    np.testing.assert_array_equal(out, _cv2_decode(blob))


def test_natural_content_filtered_rows():
    # smooth content makes the encoder choose Sub/Up/Average/Paeth filters —
    # exercises every unfilter branch including the SSE2 Paeth path
    x = np.linspace(0, 6 * np.pi, 96)
    img = np.clip(np.sin(x)[None, :, None] * 90 + np.cos(x)[:, None, None] * 90 + 128
                  + rng.normal(0, 5, (96, 96, 3)), 0, 255).astype(np.uint8)
    blob = _png(img)
    (out,) = image_codec.decode_images([blob])
    np.testing.assert_array_equal(out, _cv2_decode(blob))


def test_interlaced_png_via_libpng_fallback():
    from PIL import Image

    img = rng.integers(0, 256, (48, 32, 3), np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format='png', interlace=True)
    blob = buf.getvalue()
    (out,) = image_codec.decode_images([blob])  # fast path bails; libpng path
    np.testing.assert_array_equal(out, img)


def test_mixed_batch_sizes_and_formats():
    imgs = [rng.integers(0, 256, s, np.uint8)
            for s in [(16, 24, 3), (50, 10), (33, 47, 3)]]
    blobs = [_png(imgs[0]), _png(imgs[1]), _jpeg(imgs[2])]
    outs = image_codec.decode_images(blobs)
    np.testing.assert_array_equal(outs[0], imgs[0])
    np.testing.assert_array_equal(outs[1], imgs[1])
    np.testing.assert_array_equal(outs[2], _cv2_decode(blobs[2]))


def test_memoryview_input():
    img = rng.integers(0, 256, (20, 20, 3), np.uint8)
    blob = _png(img)
    (out,) = image_codec.decode_images([memoryview(blob)])
    np.testing.assert_array_equal(out, img)


def test_threads_fanout_matches_single():
    imgs = [rng.integers(0, 256, (31 + i, 17 + i, 3), np.uint8) for i in range(20)]
    blobs = [_png(im) for im in imgs]
    single = image_codec.decode_images(blobs, threads=1)
    fanned = image_codec.decode_images(blobs, threads=4)
    for s, f in zip(single, fanned):
        np.testing.assert_array_equal(s, f)


@pytest.mark.parametrize('bad', [
    b'not an image at all',
    b'\x89PNG\r\n\x1a\n' + b'\x00' * 20,  # corrupt header
])
def test_unsupported_raises_native_decode_error(bad):
    with pytest.raises(image_codec.NativeDecodeError):
        image_codec.decode_images([bad])


def test_rgba_png_rejected_natively():
    rgba = rng.integers(0, 256, (12, 12, 4), np.uint8)
    ok, buf = cv2.imencode('.png', rgba)
    assert ok
    with pytest.raises(image_codec.NativeDecodeError) as info:
        image_codec.decode_images([buf.tobytes()])
    assert info.value.index == 0


def test_codec_decode_batch_equals_decode_and_handles_none():
    codec = CompressedImageCodec('png')
    field = UnischemaField('im', np.uint8, (None, None, 3), codec, True)
    imgs = [rng.integers(0, 256, (14 + i, 9, 3), np.uint8) for i in range(4)]
    cells = [codec.encode(field, im) for im in imgs]
    cells.insert(2, None)  # nullable cell
    out = codec.decode_batch(field, cells)
    assert out[2] is None
    expect = [codec.decode(field, c) for c in cells if c is not None]
    got = [o for o in out if o is not None]
    for e, g in zip(expect, got):
        np.testing.assert_array_equal(e, g)


def test_codec_decode_batch_falls_back_on_unsupported():
    # an alpha png in the column forces the whole-column OpenCV fallback;
    # results must still match per-image decode of the supported cells
    codec = CompressedImageCodec('png')
    field = UnischemaField('im', np.uint8, None, codec, False)
    rgb = rng.integers(0, 256, (10, 11, 3), np.uint8)
    rgba = rng.integers(0, 256, (10, 11, 4), np.uint8)
    ok, rgba_blob = cv2.imencode('.png', rgba)
    assert ok
    cells = [codec.encode(field, rgb), rgba_blob.tobytes()]
    out = codec.decode_batch(field, cells)
    np.testing.assert_array_equal(out[0], rgb)
    np.testing.assert_array_equal(out[1], cv2.imdecode(np.frombuffer(cells[1], np.uint8),
                                                       cv2.IMREAD_UNCHANGED))


def test_uint16_rgb_png_roundtrip_through_codec():
    codec = CompressedImageCodec('png')
    field = UnischemaField('im', np.uint16, (18, 22, 3), codec, False)
    img = rng.integers(0, 65536, (18, 22, 3), np.uint16)
    (out,) = codec.decode_batch(field, [codec.encode(field, img)])
    np.testing.assert_array_equal(out, img)
