"""Batched native PNG/JPEG decode (native/image_codec.cpp) vs the OpenCV path.

The native decoder must be bit-exact with ``CompressedImageCodec.decode`` for
every flavor it claims (PNG gray/RGB 8/16-bit, JPEG gray/RGB) and must cleanly
reject — so the codec falls back to OpenCV — everything else (palette/alpha
PNG, corrupt bytes). Reference behavior being matched:
/root/reference/petastorm/codecs.py:92-111 (per-image decode, RGB output).
"""

import io

import numpy as np
import pytest

from petastorm_tpu.codecs import CompressedImageCodec
from petastorm_tpu.native import image_codec
from petastorm_tpu.unischema import UnischemaField

cv2 = pytest.importorskip('cv2')

pytestmark = pytest.mark.skipif(not image_codec.is_available(),
                                reason='native image codec not built')

rng = np.random.default_rng(7)


def _png(arr):
    ok, buf = cv2.imencode('.png', arr if arr.ndim == 2 else cv2.cvtColor(arr, cv2.COLOR_RGB2BGR))
    assert ok
    return buf.tobytes()


def _jpeg(arr, quality=85):
    ok, buf = cv2.imencode('.jpeg', arr if arr.ndim == 2 else cv2.cvtColor(arr, cv2.COLOR_RGB2BGR),
                           [int(cv2.IMWRITE_JPEG_QUALITY), quality])
    assert ok
    return buf.tobytes()


def _cv2_decode(blob):
    img = cv2.imdecode(np.frombuffer(blob, np.uint8), cv2.IMREAD_UNCHANGED)
    if img.ndim == 3 and img.shape[2] == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return img


@pytest.mark.parametrize('shape,dtype,fmt', [
    ((37, 53, 3), np.uint8, 'png'),
    ((64, 64), np.uint8, 'png'),
    ((21, 33), np.uint16, 'png'),
    ((40, 56, 3), np.uint16, 'png'),
    ((37, 53, 3), np.uint8, 'jpeg'),
    ((64, 64), np.uint8, 'jpeg'),
    ((1, 1, 3), np.uint8, 'png'),
    ((1, 7), np.uint8, 'png'),
])
def test_native_matches_cv2(shape, dtype, fmt):
    hi = 65536 if dtype == np.uint16 else 256
    img = rng.integers(0, hi, shape, dtype=dtype)
    blob = _png(img) if fmt == 'png' else _jpeg(img)
    (out,) = image_codec.decode_images([blob])
    np.testing.assert_array_equal(out, _cv2_decode(blob))


def test_natural_content_filtered_rows():
    # smooth content makes the encoder choose Sub/Up/Average/Paeth filters —
    # exercises every unfilter branch including the SSE2 Paeth path
    x = np.linspace(0, 6 * np.pi, 96)
    img = np.clip(np.sin(x)[None, :, None] * 90 + np.cos(x)[:, None, None] * 90 + 128
                  + rng.normal(0, 5, (96, 96, 3)), 0, 255).astype(np.uint8)
    blob = _png(img)
    (out,) = image_codec.decode_images([blob])
    np.testing.assert_array_equal(out, _cv2_decode(blob))


def test_interlaced_png_via_libpng_fallback():
    from PIL import Image

    img = rng.integers(0, 256, (48, 32, 3), np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format='png', interlace=True)
    blob = buf.getvalue()
    (out,) = image_codec.decode_images([blob])  # fast path bails; libpng path
    np.testing.assert_array_equal(out, img)


def test_mixed_batch_sizes_and_formats():
    imgs = [rng.integers(0, 256, s, np.uint8)
            for s in [(16, 24, 3), (50, 10), (33, 47, 3)]]
    blobs = [_png(imgs[0]), _png(imgs[1]), _jpeg(imgs[2])]
    outs = image_codec.decode_images(blobs)
    np.testing.assert_array_equal(outs[0], imgs[0])
    np.testing.assert_array_equal(outs[1], imgs[1])
    np.testing.assert_array_equal(outs[2], _cv2_decode(blobs[2]))


def test_memoryview_input():
    img = rng.integers(0, 256, (20, 20, 3), np.uint8)
    blob = _png(img)
    (out,) = image_codec.decode_images([memoryview(blob)])
    np.testing.assert_array_equal(out, img)


def test_threads_fanout_matches_single():
    imgs = [rng.integers(0, 256, (31 + i, 17 + i, 3), np.uint8) for i in range(20)]
    blobs = [_png(im) for im in imgs]
    single = image_codec.decode_images(blobs, threads=1)
    fanned = image_codec.decode_images(blobs, threads=4)
    for s, f in zip(single, fanned):
        np.testing.assert_array_equal(s, f)


@pytest.mark.parametrize('bad', [
    b'not an image at all',
    b'\x89PNG\r\n\x1a\n' + b'\x00' * 20,  # corrupt header
])
def test_unsupported_raises_native_decode_error(bad):
    with pytest.raises(image_codec.NativeDecodeError):
        image_codec.decode_images([bad])


def test_rgba_png_rejected_natively():
    rgba = rng.integers(0, 256, (12, 12, 4), np.uint8)
    ok, buf = cv2.imencode('.png', rgba)
    assert ok
    with pytest.raises(image_codec.NativeDecodeError) as info:
        image_codec.decode_images([buf.tobytes()])
    assert info.value.index == 0


def test_codec_decode_batch_equals_decode_and_handles_none():
    codec = CompressedImageCodec('png')
    field = UnischemaField('im', np.uint8, (None, None, 3), codec, True)
    imgs = [rng.integers(0, 256, (14 + i, 9, 3), np.uint8) for i in range(4)]
    cells = [codec.encode(field, im) for im in imgs]
    cells.insert(2, None)  # nullable cell
    out = codec.decode_batch(field, cells)
    assert out[2] is None
    expect = [codec.decode(field, c) for c in cells if c is not None]
    got = [o for o in out if o is not None]
    for e, g in zip(expect, got):
        np.testing.assert_array_equal(e, g)


def test_codec_decode_batch_falls_back_on_unsupported():
    # an alpha png in the column forces the whole-column OpenCV fallback;
    # results must still match per-image decode of the supported cells
    codec = CompressedImageCodec('png')
    field = UnischemaField('im', np.uint8, None, codec, False)
    rgb = rng.integers(0, 256, (10, 11, 3), np.uint8)
    rgba = rng.integers(0, 256, (10, 11, 4), np.uint8)
    ok, rgba_blob = cv2.imencode('.png', rgba)
    assert ok
    cells = [codec.encode(field, rgb), rgba_blob.tobytes()]
    out = codec.decode_batch(field, cells)
    np.testing.assert_array_equal(out[0], rgb)
    np.testing.assert_array_equal(out[1], cv2.imdecode(np.frombuffer(cells[1], np.uint8),
                                                       cv2.IMREAD_UNCHANGED))


def test_uint16_rgb_png_roundtrip_through_codec():
    codec = CompressedImageCodec('png')
    field = UnischemaField('im', np.uint16, (18, 22, 3), codec, False)
    img = rng.integers(0, 65536, (18, 22, 3), np.uint16)
    (out,) = codec.decode_batch(field, [codec.encode(field, img)])
    np.testing.assert_array_equal(out, img)


# -- scaled JPEG decode (round 3) --------------------------------------------

def _jpeg_bytes(h, w, quality=85, seed=0):
    import cv2
    rng = np.random.default_rng(seed)
    img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
    ok, enc = cv2.imencode('.jpeg', img, [int(cv2.IMWRITE_JPEG_QUALITY), quality])
    assert ok
    return enc.tobytes()


@pytest.mark.skipif(not image_codec.is_available(), reason='native codec unavailable')
def test_scaled_jpeg_dims_cover_min_size():
    enc = _jpeg_bytes(1200, 900)
    out = image_codec.decode_images([enc], min_size=(160, 160))[0]
    # smallest m/8 covering 160: m=2 -> ceil(1200*2/8)=300, ceil(900*2/8)=225
    assert out.shape == (300, 225, 3)
    assert out.shape[0] >= 160 and out.shape[1] >= 160


@pytest.mark.skipif(not image_codec.is_available(), reason='native codec unavailable')
def test_scaled_jpeg_small_image_stays_full_size():
    enc = _jpeg_bytes(100, 80)
    out = image_codec.decode_images([enc], min_size=(160, 160))[0]
    assert out.shape == (100, 80, 3)  # cannot upscale; full size


@pytest.mark.skipif(not image_codec.is_available(), reason='native codec unavailable')
def test_scaled_decode_png_ignores_hint():
    import cv2
    img = np.random.default_rng(1).integers(0, 255, (400, 300, 3), dtype=np.uint8)
    ok, enc = cv2.imencode('.png', img)
    out = image_codec.decode_images([enc.tobytes()], min_size=(100, 100))[0]
    assert out.shape == (400, 300, 3)


@pytest.mark.skipif(not image_codec.is_available(), reason='native codec unavailable')
def test_scaled_jpeg_approximates_area_resize():
    import cv2
    enc = _jpeg_bytes(800, 600, seed=3)
    full = image_codec.decode_images([enc])[0]
    scaled = image_codec.decode_images([enc], min_size=(160, 160))[0]
    ref = cv2.resize(full, (scaled.shape[1], scaled.shape[0]),
                     interpolation=cv2.INTER_AREA)
    diff = np.abs(scaled.astype(int) - ref.astype(int)).mean()
    assert diff < 20  # DCT scaling ~= area resampling (random noise is worst case)


@pytest.mark.skipif(not image_codec.is_available(), reason='native codec unavailable')
def test_scaled_mixed_batch_per_image_scales():
    encs = [_jpeg_bytes(640, 480, seed=4), _jpeg_bytes(120, 90, seed=5),
            _jpeg_bytes(1600, 1200, seed=6)]
    outs = image_codec.decode_images(encs, min_size=(160, 160))
    assert outs[0].shape == (240, 180, 3)   # m=3
    assert outs[1].shape == (120, 90, 3)    # smaller than min: full
    assert outs[2].shape == (400, 300, 3)   # m=2 (m=1 would give width 150 < 160)


def test_codec_decode_batch_min_size_passthrough():
    codec = CompressedImageCodec('jpeg')
    field = UnischemaField('im', np.uint8, (None, None, 3), codec, False)
    enc = _jpeg_bytes(800, 600, seed=7)
    outs = codec.decode_batch(field, [enc, None], min_size=(160, 160))
    assert outs[1] is None
    assert outs[0].shape[0] >= 160 and outs[0].shape[0] < 800


def test_transform_decode_hints_end_to_end(tmp_path):
    """A jpeg dataset read with TransformSpec(image_decode_hints=...) resizes
    through scaled decode and still yields exact target shapes."""
    import cv2
    from examples.imagenet.generate_petastorm_imagenet import generate_synthetic_imagenet
    from examples.imagenet.jax_resnet_example import make_transform
    from petastorm_tpu import make_reader
    url = 'file://' + str(tmp_path / 'jpg_ds')
    generate_synthetic_imagenet(url, num_synsets=2, images_per_synset=8,
                                rows_per_row_group=8, image_codec='jpeg',
                                min_dim=200, max_dim=400)
    with make_reader(url, reader_pool_type='dummy', output='columnar',
                     shuffle_row_groups=False,
                     transform_spec=make_transform(96, 10)) as reader:
        blocks = [b._asdict() for b in reader]
    images = np.concatenate([b['image'] for b in blocks])
    assert images.shape == (16, 96, 96, 3)
    labels = np.concatenate([b['label'] for b in blocks])
    assert set(labels.tolist()) <= set(range(10))


# -- decode_images_block: whole-column decode into one allocation ------------

def test_block_decode_matches_per_image():
    rng = np.random.default_rng(11)
    imgs = [rng.integers(0, 255, (40, 56, 3), dtype=np.uint8) for _ in range(7)]
    blobs = [_png(im) for im in imgs[:4]] + [_jpeg(im) for im in imgs[4:]]
    block = image_codec.decode_images_block(blobs)
    singles = image_codec.decode_images(blobs)
    assert block.shape == (7, 40, 56, 3) and block.dtype == np.uint8
    for i in range(7):
        np.testing.assert_array_equal(block[i], singles[i])


def test_block_decode_mixed_dims_returns_none():
    rng = np.random.default_rng(12)
    blobs = [_png(rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)),
             _png(rng.integers(0, 255, (16, 20, 3), dtype=np.uint8))]
    assert image_codec.decode_images_block(blobs) is None


def test_block_decode_grayscale():
    rng = np.random.default_rng(13)
    imgs = [rng.integers(0, 255, (24, 24), dtype=np.uint8) for _ in range(3)]
    block = image_codec.decode_images_block([_png(im) for im in imgs])
    assert block.shape == (3, 24, 24)
    for i, im in enumerate(imgs):
        np.testing.assert_array_equal(block[i], im)


def test_block_decode_bad_cell_raises():
    with pytest.raises(image_codec.NativeDecodeError):
        image_codec.decode_images_block([b'not an image'])


def test_codec_decode_column_matches_batch():
    import pyarrow as pa
    rng = np.random.default_rng(14)
    codec = CompressedImageCodec('png')
    field = UnischemaField('im', np.uint8, (18, 22, 3), codec, False)
    imgs = [rng.integers(0, 255, (18, 22, 3), dtype=np.uint8) for _ in range(5)]
    cells = [codec.encode(field, im) for im in imgs]
    column = pa.chunked_array([pa.array(cells, type=pa.binary())])
    block = codec.decode_column(field, column)
    assert block.shape == (5, 18, 22, 3)
    for i, im in enumerate(imgs):
        np.testing.assert_array_equal(block[i], im)


def test_codec_decode_column_nulls_defer():
    import pyarrow as pa
    codec = CompressedImageCodec('png')
    field = UnischemaField('im', np.uint8, (8, 8, 3), codec, True)
    cells = [codec.encode(field, np.zeros((8, 8, 3), np.uint8)), None]
    column = pa.chunked_array([pa.array(cells, type=pa.binary())])
    assert codec.decode_column(field, column) is None


def test_codec_decode_column_scaled_jpeg_hint():
    import pyarrow as pa
    codec = CompressedImageCodec('jpeg')
    field = UnischemaField('im', np.uint8, (None, None, 3), codec, False)
    cells = [_jpeg_bytes(400, 600, seed=i) for i in range(3)]
    column = pa.chunked_array([pa.array(cells, type=pa.binary())])
    block = codec.decode_column(field, column, min_size=(100, 150))
    assert block is not None
    n, h, w, c = block.shape
    assert 100 <= h < 400 and 150 <= w < 600  # decoded at a reduced DCT scale


def test_auto_decode_mixed_dims_returns_per_image_list():
    rng = np.random.default_rng(15)
    imgs = [rng.integers(0, 255, (16, 16, 3), dtype=np.uint8),
            rng.integers(0, 255, (16, 20, 3), dtype=np.uint8)]
    out = image_codec.decode_images_auto([_png(im) for im in imgs])
    assert isinstance(out, list) and len(out) == 2
    for got, want in zip(out, imgs):
        np.testing.assert_array_equal(got, want)


def test_codec_decode_column_mixed_dims_single_probe_object_column():
    import pyarrow as pa
    rng = np.random.default_rng(16)
    codec = CompressedImageCodec('png')
    field = UnischemaField('im', np.uint8, (None, None, 3), codec, False)
    imgs = [rng.integers(0, 255, (10, 12, 3), dtype=np.uint8),
            rng.integers(0, 255, (14, 12, 3), dtype=np.uint8)]
    cells = [codec.encode(field, im) for im in imgs]
    column = pa.chunked_array([pa.array(cells, type=pa.binary())])
    out = codec.decode_column(field, column)
    assert out is not None and out.dtype == object
    for got, want in zip(out, imgs):
        np.testing.assert_array_equal(got, want)


# -- fused decode+resize (TransformSpec.image_resize) ------------------------

def test_decode_images_resized_matches_cv2_area():
    rng = np.random.default_rng(17)
    imgs = [rng.integers(0, 255, (90, 120, 3), dtype=np.uint8) for _ in range(4)]
    out = image_codec.decode_images_resized([_png(im) for im in imgs], (32, 48))
    assert out.shape == (4, 32, 48, 3) and out.dtype == np.uint8
    for got, src in zip(out, imgs):
        ref = cv2.resize(src, (48, 32), interpolation=cv2.INTER_AREA)
        assert np.abs(got.astype(int) - ref.astype(int)).max() <= 1


def test_decode_images_resized_grayscale_and_identity():
    rng = np.random.default_rng(18)
    img = rng.integers(0, 255, (20, 24), dtype=np.uint8)
    out = image_codec.decode_images_resized([_png(img)], (20, 24))
    assert out.shape == (1, 20, 24)
    np.testing.assert_array_equal(out[0], img)  # identity resize = plain decode


@pytest.fixture(scope='module')
def mixed_size_png_dataset(tmp_path_factory):
    from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    path = tmp_path_factory.mktemp('mixed_png_store')
    url = 'file://' + str(path)
    schema = Unischema('MixedPng', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('image', np.uint8, (None, None, 3), CompressedImageCodec('png'), False),
    ])
    rng = np.random.default_rng(19)
    data = [{'id': i,
             'image': rng.integers(0, 255, (40 + 8 * (i % 4), 50 + 4 * (i % 3), 3),
                                   dtype=np.uint8)}
            for i in range(24)]
    write_petastorm_dataset(url, schema, iter(data), rows_per_row_group=8)
    return url, data


def _resize_ref(img, size):
    # the shared policy: bilinear under 2x decimation, area at >= 2x
    from petastorm_tpu.codecs import _mild_ratio
    interp = cv2.INTER_LINEAR if _mild_ratio(img.shape[0], img.shape[1], size[0], size[1]) \
        else cv2.INTER_AREA
    return cv2.resize(img, (size[1], size[0]), interpolation=interp)


def test_image_resize_end_to_end_row_reader(mixed_size_png_dataset):
    from petastorm_tpu import TransformSpec, make_reader
    url, data = mixed_size_png_dataset
    by_id = {r['id']: r['image'] for r in data}
    spec = TransformSpec(image_resize={'image': (32, 32)})
    n = 0
    with make_reader(url, reader_pool_type='dummy', shuffle_row_groups=False,
                     transform_spec=spec) as reader:
        for row in reader:
            assert row.image.shape == (32, 32, 3)
            ref = _resize_ref(by_id[row.id], (32, 32))
            assert np.abs(row.image.astype(int) - ref.astype(int)).max() <= 1
            n += 1
    assert n == len(data)


def test_image_resize_end_to_end_columnar_uniform_blocks(mixed_size_png_dataset):
    from petastorm_tpu import TransformSpec, make_reader
    url, data = mixed_size_png_dataset
    spec = TransformSpec(image_resize={'image': (28, 36)})
    ids = []
    with make_reader(url, reader_pool_type='dummy', output='columnar',
                     shuffle_row_groups=False, transform_spec=spec) as reader:
        for block in reader:
            assert block.image.shape[1:] == (28, 36, 3)  # one uniform block
            assert block.image.dtype == np.uint8
            ids.extend(block.id.tolist())
    assert sorted(ids) == [r['id'] for r in data]


def test_image_resize_opencv_fallback_same_contract(mixed_size_png_dataset, monkeypatch):
    from petastorm_tpu import TransformSpec, make_reader
    url, data = mixed_size_png_dataset
    monkeypatch.setattr(image_codec, '_load_failed', True)  # native codec "absent"
    monkeypatch.setattr(image_codec, '_lib', None)
    assert not image_codec.is_available()
    spec = TransformSpec(image_resize={'image': (32, 32)})
    by_id = {r['id']: r['image'] for r in data}
    with make_reader(url, reader_pool_type='dummy', shuffle_row_groups=False,
                     transform_spec=spec) as reader:
        for row in reader:
            assert row.image.shape == (32, 32, 3)
            ref = _resize_ref(by_id[row.id], (32, 32))
            np.testing.assert_array_equal(row.image, ref)  # same cv2 path = exact


def test_image_resize_transform_schema_autoedit():
    from petastorm_tpu import TransformSpec
    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.transform import transform_schema
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('S', [
        UnischemaField('image', np.uint8, (None, None, 3), CompressedImageCodec('png'), False)])
    out = transform_schema(schema, TransformSpec(image_resize={'image': (64, 48)}))
    assert out.fields['image'].shape == (64, 48, 3)
    # explicit edit wins over the auto-derived shape
    out2 = transform_schema(schema, TransformSpec(
        image_resize={'image': (64, 48)},
        edit_fields=[UnischemaField('image', np.uint8, (10, 10, 3), None, False)]))
    assert out2.fields['image'].shape == (10, 10, 3)


def test_image_resize_rejects_bad_target():
    from petastorm_tpu import TransformSpec
    with pytest.raises(ValueError):
        TransformSpec(image_resize={'image': (0, 10)})
    with pytest.raises(ValueError):
        TransformSpec(image_resize={'image': (10,)})


def test_native_resize_area_image_matches_cv2():
    rng = np.random.default_rng(20)
    img = rng.integers(0, 255, (60, 80, 3), dtype=np.uint8)
    out = image_codec.resize_area_image(img, (30, 40))
    ref = cv2.resize(img, (40, 30), interpolation=cv2.INTER_AREA)
    assert np.abs(out.astype(int) - ref.astype(int)).max() <= 1


def test_image_resize_rejects_non_image_codec():
    from petastorm_tpu import TransformSpec
    from petastorm_tpu.codecs import NdarrayCodec
    from petastorm_tpu.transform import transform_schema
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('S', [
        UnischemaField('arr', np.uint8, (None, None, 3), NdarrayCodec(), False)])
    with pytest.raises(ValueError, match='does not support decode-time resize'):
        transform_schema(schema, TransformSpec(image_resize={'arr': (8, 8)}))
    with pytest.raises(ValueError, match='unknown field'):
        transform_schema(schema, TransformSpec(image_resize={'nope': (8, 8)}))


def test_decode_hint_overrides_resize_scale():
    # explicit image_decode_hints wins: jpeg decodes at a scale covering the
    # hint (2x supersample), not just the resize target
    blob = _jpeg_bytes(800, 1200, seed=3)
    small = image_codec.decode_images_resized([blob], (100, 150))
    big = image_codec.decode_images_resized([blob], (100, 150), min_size=(400, 600))
    assert small.shape == big.shape == (1, 100, 150, 3)
    # both valid; a supersampled source reduces aliasing so outputs differ
    assert not np.array_equal(small, big)


def test_cache_key_distinguishes_resize(tmp_path):
    from petastorm_tpu.row_worker import _cache_key

    class Piece:
        path = 'p.parquet'
        row_group = 0
    k_plain = _cache_key('/d', Piece, ['image'])
    k_hint = _cache_key('/d', Piece, ['image'], decode_hints={'image': (32, 32)})
    k_resize = _cache_key('/d', Piece, ['image'], decode_hints={'image': (32, 32)},
                          resize_hints={'image': (32, 32)})
    assert len({k_plain, k_hint, k_resize}) == 3


def test_image_resize_uint16_without_opencv_uses_numpy_fallback(tmp_path, monkeypatch):
    # 16-bit PNG column + image_resize on an OpenCV-less host: the native fast
    # path declines (depth != 8) and decode_batch's resize must fall back to
    # the numpy area resampler instead of crashing
    import petastorm_tpu.codecs as codecs_mod
    from petastorm_tpu import TransformSpec, make_reader
    from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    url = 'file://' + str(tmp_path)
    schema = Unischema('U16', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('image', np.uint16, (None, None, 3), CompressedImageCodec('png'), False),
    ])
    rng = np.random.default_rng(21)
    data = [{'id': i, 'image': rng.integers(0, 65535, (20 + 4 * i, 24, 3), dtype=np.uint16)}
            for i in range(6)]
    write_petastorm_dataset(url, schema, iter(data), rows_per_row_group=3)

    def no_cv2():
        raise ImportError('cv2 disabled for test')
    monkeypatch.setattr(codecs_mod, '_import_cv2', no_cv2)

    spec = TransformSpec(image_resize={'image': (16, 16)})
    with make_reader(url, reader_pool_type='dummy', shuffle_row_groups=False,
                     transform_spec=spec) as reader:
        rows = list(reader)
    assert len(rows) == 6
    assert all(r.image.shape == (16, 16, 3) and r.image.dtype == np.uint16 for r in rows)


def test_numpy_area_resize_matches_cv2():
    from petastorm_tpu.codecs import _area_resize_numpy
    rng = np.random.default_rng(22)
    img = rng.integers(0, 255, (50, 70, 3), dtype=np.uint8)
    out = _area_resize_numpy(img, 25, 35)
    ref = cv2.resize(img, (35, 25), interpolation=cv2.INTER_AREA)
    assert np.abs(out.astype(int) - ref.astype(int)).max() <= 1


def test_native_resize_bilinear_matches_cv2_linear():
    rng = np.random.default_rng(21)
    for shape, target in [((60, 80, 3), (40, 56)), ((45, 45), (32, 32)),
                          ((33, 57, 3), (60, 70))]:  # down-mild and upscale
        img = rng.integers(0, 255, shape, dtype=np.uint8)
        out = image_codec.resize_bilinear_image(img, target)
        ref = cv2.resize(img, (target[1], target[0]), interpolation=cv2.INTER_LINEAR)
        assert np.abs(out.astype(int) - ref.astype(int)).max() <= 1, (shape, target)


def test_resize_policy_dispatch():
    """_resize_image must pick bilinear under 2x decimation and area at >= 2x,
    and the native fused path must follow the same split."""
    from petastorm_tpu.codecs import _mild_ratio, _resize_image
    rng = np.random.default_rng(22)
    # mild (1.5x): matches cv2 INTER_LINEAR
    img = rng.integers(0, 255, (48, 48, 3), dtype=np.uint8)
    got = _resize_image(img, 32, 32)
    ref = cv2.resize(img, (32, 32), interpolation=cv2.INTER_LINEAR)
    np.testing.assert_array_equal(got, ref)
    # real decimation (3x): matches cv2 INTER_AREA
    img2 = rng.integers(0, 255, (96, 96, 3), dtype=np.uint8)
    got2 = _resize_image(img2, 32, 32)
    ref2 = cv2.resize(img2, (32, 32), interpolation=cv2.INTER_AREA)
    np.testing.assert_array_equal(got2, ref2)
    assert _mild_ratio(48, 48, 32, 32) and not _mild_ratio(96, 96, 32, 32)
    assert not _mild_ratio(64, 40, 32, 32)  # boundary: exactly 2x is NOT mild
    # mixed down+up (h 3x down, w upscaled): bilinear on EVERY backend — the
    # same store must decode identically with or without OpenCV installed
    assert _mild_ratio(96, 24, 32, 32)
    img3 = rng.integers(0, 255, (96, 24, 3), dtype=np.uint8)
    got3 = _resize_image(img3, 32, 32)
    ref3 = cv2.resize(img3, (32, 32), interpolation=cv2.INTER_LINEAR)
    np.testing.assert_array_equal(got3, ref3)
    native3 = image_codec.resize_bilinear_image(img3, (32, 32))
    assert np.abs(native3.astype(int) - ref3.astype(int)).max() <= 1
    out3 = image_codec.decode_images_resized([_png(img3)], (32, 32))
    assert np.abs(out3[0].astype(int) - ref3.astype(int)).max() <= 1
    # fused native path agrees within rounding on the mild branch
    out = image_codec.decode_images_resized([_png(img)], (32, 32))
    assert np.abs(out[0].astype(int) - ref.astype(int)).max() <= 1


def test_thread_budget_cooperative_grants(monkeypatch):
    """threads=None callers share the process budget: the first concurrent
    caller gets the free budget, later ones get the floor of 1, and every
    grant is returned."""
    monkeypatch.setattr(image_codec, '_default_threads', lambda: 4)
    with image_codec._thread_grant(None) as g1:
        assert g1 == 4
        with image_codec._thread_grant(None) as g2:
            assert g2 == 1  # budget exhausted: floor keeps the caller moving
        with image_codec._thread_grant(None) as g3:
            assert g3 == 1
    with image_codec._thread_grant(None) as g4:
        assert g4 == 4  # fully returned
    assert image_codec._threads_in_use == 0
    # explicit request bypasses the accounting entirely
    with image_codec._thread_grant(2) as g5:
        assert g5 == 2
    assert image_codec._threads_in_use == 0


def test_thread_budget_decode_results_identical(monkeypatch):
    monkeypatch.setattr(image_codec, '_default_threads', lambda: 3)
    imgs = [rng.integers(0, 256, (30 + i, 20, 3), np.uint8) for i in range(12)]
    blobs = [_png(im) for im in imgs]
    budgeted = image_codec.decode_images(blobs)  # threads=None -> grant path
    single = image_codec.decode_images(blobs, threads=1)
    for b, s in zip(budgeted, single):
        np.testing.assert_array_equal(b, s)
    assert image_codec._threads_in_use == 0


def test_default_thread_budget_safety(monkeypatch):
    # garbage env degrades to the safe floor, never the full budget
    monkeypatch.setenv('PSTPU_IMG_THREADS', 'auto')
    assert image_codec._default_threads() == 1
    monkeypatch.setenv('PSTPU_IMG_THREADS', '')
    assert image_codec._default_threads() == 1
    monkeypatch.setenv('PSTPU_IMG_THREADS', '6')
    assert image_codec._default_threads() == 6
    # unset in a top-level process: CPU count
    monkeypatch.delenv('PSTPU_IMG_THREADS')
    import os as os_mod
    assert image_codec._default_threads() == max(1, os_mod.cpu_count() or 1)


def _child_budget(q):
    import os
    os.environ.pop('PSTPU_IMG_THREADS', None)
    from petastorm_tpu.native import image_codec as ic
    q.put(ic._default_threads())


def test_default_thread_budget_in_mp_child_is_one(monkeypatch):
    """A multiprocessing child NOT configured by our pool bootstrap defaults
    to 1 — N sibling processes each claiming cpu_count would oversubscribe."""
    import multiprocessing
    monkeypatch.delenv('PSTPU_IMG_THREADS', raising=False)
    ctx = multiprocessing.get_context('spawn')
    q = ctx.Queue()
    p = ctx.Process(target=_child_budget, args=(q,))
    p.start()
    assert q.get(timeout=60) == 1
    p.join()


def test_native_resamplers_fuzz_vs_cv2():
    """Random shapes (tiny, 1-px axes, extreme aspect) through both native
    resamplers stay within 1 LSB of the cv2 references. Bilinear everywhere;
    area wherever at least the promised regime applies (both axes downscale,
    or both upscale — cv2's MIXED down+up INTER_AREA is a non-separable
    special case that disagrees even with cv2's own two-step composition by
    ~100 LSB, so bit-parity there is not a meaningful contract; the shared
    resize policy never routes such shapes to area with cv2 absent AND
    present simultaneously anyway)."""
    fuzz = np.random.default_rng(99)
    checked_area = 0
    for _ in range(40):
        sh = int(fuzz.integers(1, 80))
        sw = int(fuzz.integers(1, 80))
        dh = int(fuzz.integers(1, 64))
        dw = int(fuzz.integers(1, 64))
        c = int(fuzz.choice([1, 3]))
        shape = (sh, sw) if c == 1 else (sh, sw, c)
        img = fuzz.integers(0, 256, shape, dtype=np.uint8)
        got_b = image_codec.resize_bilinear_image(img, (dh, dw))
        ref_b = cv2.resize(img, (dw, dh), interpolation=cv2.INTER_LINEAR)
        assert np.abs(got_b.astype(int) - ref_b.astype(int)).max() <= 1, \
            ('bilinear', shape, (dh, dw))
        both_down = dh <= sh and dw <= sw
        both_up = dh >= sh and dw >= sw
        if both_down or both_up:
            checked_area += 1
            got_a = image_codec.resize_area_image(img, (dh, dw))
            ref_a = cv2.resize(img, (dw, dh), interpolation=cv2.INTER_AREA)
            assert np.abs(got_a.astype(int) - ref_a.astype(int)).max() <= 1, \
                ('area', shape, (dh, dw))
    assert checked_area >= 10  # the area contract actually got exercised
