"""NGram tests (modeled on reference tests/test_ngram_end_to_end.py)."""

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.ngram import NGram
from petastorm_tpu.test_util.dataset_utils import TestSchema


def _ts_ngram(length=3, delta_threshold=1, overlap=True, fields=None):
    per_step = fields or [TestSchema.id, TestSchema.id2]
    return NGram({i: list(per_step) for i in range(length)},
                 delta_threshold=delta_threshold,
                 timestamp_field=TestSchema.id,
                 timestamp_overlap=overlap)


class TestFormNgram:
    def test_basic_window(self):
        ngram = _ts_ngram(length=3)
        rows = [{'id': i, 'id2': i * 10} for i in range(5)]
        out = ngram.form_ngram(rows, TestSchema)
        assert len(out) == 3  # windows starting at 0,1,2
        assert [out[0][t]['id'] for t in range(3)] == [0, 1, 2]
        assert out[1][0]['id'] == 1

    def test_delta_threshold_drops_gaps(self):
        ngram = _ts_ngram(length=2, delta_threshold=1)
        rows = [{'id': i, 'id2': 0} for i in [0, 1, 5, 6]]
        out = ngram.form_ngram(rows, TestSchema)
        pairs = [(w[0]['id'], w[1]['id']) for w in out]
        assert pairs == [(0, 1), (5, 6)]  # (1,5) violates the threshold

    def test_no_overlap(self):
        ngram = _ts_ngram(length=2, overlap=False)
        rows = [{'id': i, 'id2': 0} for i in range(6)]
        out = ngram.form_ngram(rows, TestSchema)
        starts = [w[0]['id'] for w in out]
        assert starts == [0, 2, 4]

    def test_unsorted_input_gets_sorted(self):
        ngram = _ts_ngram(length=2)
        rows = [{'id': i, 'id2': 0} for i in [3, 1, 0, 2]]
        out = ngram.form_ngram(rows, TestSchema)
        assert [(w[0]['id'], w[1]['id']) for w in out] == [(0, 1), (1, 2), (2, 3)]

    def test_different_fields_per_timestep(self):
        ngram = NGram({0: [TestSchema.id, TestSchema.id2], 1: [TestSchema.id]},
                      delta_threshold=1, timestamp_field=TestSchema.id)
        rows = [{'id': i, 'id2': i} for i in range(3)]
        out = ngram.form_ngram(rows, TestSchema)
        assert set(out[0][0].keys()) == {'id', 'id2'}
        assert set(out[0][1].keys()) == {'id'}

    def test_negative_offsets(self):
        ngram = NGram({-1: [TestSchema.id], 0: [TestSchema.id], 1: [TestSchema.id]},
                      delta_threshold=1, timestamp_field=TestSchema.id)
        rows = [{'id': i} for i in range(4)]
        out = ngram.form_ngram(rows, TestSchema)
        assert len(out) == 2
        assert sorted(out[0].keys()) == [-1, 0, 1]

    def test_non_consecutive_offsets_rejected(self):
        with pytest.raises(PetastormTpuError):
            NGram({0: [TestSchema.id], 2: [TestSchema.id]}, 1, TestSchema.id)

    def test_regex_resolution(self):
        ngram = NGram({0: ['id.*'], 1: ['id']}, delta_threshold=1, timestamp_field='id')
        ngram.resolve_regex_field_names(TestSchema)
        names = set(ngram.get_field_names_at_timestep(0))
        assert {'id', 'id2', 'id_float', 'id_odd'} == names


class TestNgramEndToEnd:
    def test_ngram_read(self, synthetic_dataset):
        ngram = _ts_ngram(length=3, delta_threshold=1)
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                         shuffle_row_groups=False) as reader:
            windows = list(reader)
        # 10 row groups x 10 rows, windows within groups: 8 per group
        assert len(windows) == 80
        w = windows[0]
        assert sorted(w.keys()) == [0, 1, 2]
        ids = [w[t].id for t in range(3)]
        assert ids[1] == ids[0] + 1 and ids[2] == ids[0] + 2
        # namedtuples carry only that timestep's fields
        assert set(w[0]._fields) == {'id', 'id2'}

    def test_ngram_never_crosses_rowgroup_boundary(self, synthetic_dataset):
        ngram = _ts_ngram(length=3, delta_threshold=1)
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                         shuffle_row_groups=False) as reader:
            starts = sorted(w[0].id for w in reader)
        # starts 8,9 of each group of 10 can't fit a 3-window
        expected = sorted(i for i in range(100) if i % 10 <= 7)
        assert starts == expected

    def test_ngram_with_images(self, synthetic_dataset):
        ngram = NGram({0: [TestSchema.id, TestSchema.image_png], 1: [TestSchema.id]},
                      delta_threshold=1, timestamp_field=TestSchema.id)
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                         shuffle_row_groups=False) as reader:
            w = next(iter(reader))
        expected = {r['id']: r for r in synthetic_dataset.data}
        np.testing.assert_array_equal(w[0].image_png, expected[w[0].id]['image_png'])

    def test_ngram_shuffle_row_drop_spillover(self, synthetic_dataset):
        """Row-drop partitions must not lose windows at partition boundaries."""
        ngram = _ts_ngram(length=2, delta_threshold=1)
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                         shuffle_row_groups=False, shuffle_row_drop_partitions=2) as reader:
            starts = sorted(w[0].id for w in reader)
        expected = sorted(i for i in range(100) if i % 10 <= 8)
        assert starts == expected


class TestFormNgramColumnarParity:
    """form_ngram_columnar must agree window-for-window with the row path."""

    def _block(self, ids):
        ids = np.asarray(ids, dtype=np.int64)
        return {'id': ids, 'id2': ids * 10}

    def _row_windows(self, ngram, ids):
        rows = [{'id': int(i), 'id2': int(i) * 10} for i in ids]
        return ngram.form_ngram(rows, TestSchema)

    def _assert_parity(self, ngram, ids):
        row_out = self._row_windows(ngram, ids)
        col_out = ngram.form_ngram_columnar(self._block(ids))
        if not row_out:
            assert col_out is None
            return
        offsets = sorted(row_out[0])
        for t in offsets:
            col_ids = col_out[t]['id'] if 'id' in col_out[t] else None
            if col_ids is not None:
                assert [w[t]['id'] for w in row_out] == list(col_ids)
            if 'id2' in col_out[t]:
                assert [w[t]['id2'] for w in row_out] == list(col_out[t]['id2'])

    def test_parity_sorted_contiguous(self):
        self._assert_parity(_ts_ngram(length=3), range(8))

    def test_parity_unsorted_with_gaps(self):
        self._assert_parity(_ts_ngram(length=2, delta_threshold=1),
                            [9, 3, 1, 0, 5, 6, 2, 12, 13])

    def test_parity_no_overlap_greedy(self):
        self._assert_parity(_ts_ngram(length=2, overlap=False),
                            [4, 0, 1, 2, 3, 5, 8, 9])

    def test_parity_no_qualifying_window(self):
        self._assert_parity(_ts_ngram(length=2, delta_threshold=1), [0, 5, 10])

    def test_parity_per_timestep_fields(self):
        ngram = NGram({0: [TestSchema.id, TestSchema.id2], 1: [TestSchema.id]},
                      delta_threshold=1, timestamp_field=TestSchema.id)
        row_out = self._row_windows(ngram, range(5))
        col_out = ngram.form_ngram_columnar(self._block(range(5)))
        assert set(col_out[0]) == {'id', 'id2'}
        assert set(col_out[1]) == {'id'}
        assert [w[1]['id'] for w in row_out] == list(col_out[1]['id'])


class TestColumnarNgramEndToEnd:
    def test_columnar_reader_parity_on_shuffled_store(self, synthetic_dataset):
        """Same windows from the row and columnar paths over a shuffled
        multi-row-group store (order may differ; the window SET must not)."""
        def starts(output):
            ngram = _ts_ngram(length=3, delta_threshold=1)
            with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                             ngram=ngram, output=output,
                             shuffle_row_groups=True, seed=123) as reader:
                result = []
                for item in reader:
                    if output == 'columnar':
                        result.extend(int(i) for i in item[0]['id'])
                    else:
                        result.append(int(item[0].id))
                return sorted(result)

        assert starts('rows') == starts('columnar')

    def test_stack_ngram_time_axis_parity(self, synthetic_dataset):
        from petastorm_tpu.jax.loader import stack_ngram_time_axis
        ngram = _ts_ngram(length=3, delta_threshold=1)
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         ngram=ngram, output='columnar',
                         shuffle_row_groups=False) as reader:
            block = next(iter(reader))
        stacked = stack_ngram_time_axis(block)
        w = len(block[0]['id'])
        assert stacked['id'].shape == (w, 3)
        # time axis is offset order: consecutive ids within each window
        np.testing.assert_array_equal(stacked['id'][:, 1], stacked['id'][:, 0] + 1)
        np.testing.assert_array_equal(stacked['id'][:, 2], stacked['id'][:, 0] + 2)
        by_id = {r['id']: r['id2'] for r in synthetic_dataset.data}
        expected_id2 = np.vectorize(by_id.get)(stacked['id'])
        np.testing.assert_array_equal(stacked['id2'], expected_id2)


def test_stack_ngram_time_axis_ragged_field_error():
    from petastorm_tpu.jax.loader import stack_ngram_time_axis
    batch = {0: {'id': np.zeros((4, 3))}, 1: {'id': np.zeros((4, 5))}}
    with pytest.raises(PetastormTpuError, match="'id'.*TransformSpec"):
        stack_ngram_time_axis(batch)
