"""NGram tests (modeled on reference tests/test_ngram_end_to_end.py)."""

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.ngram import NGram
from petastorm_tpu.test_util.dataset_utils import TestSchema


def _ts_ngram(length=3, delta_threshold=1, overlap=True, fields=None):
    per_step = fields or [TestSchema.id, TestSchema.id2]
    return NGram({i: list(per_step) for i in range(length)},
                 delta_threshold=delta_threshold,
                 timestamp_field=TestSchema.id,
                 timestamp_overlap=overlap)


class TestFormNgram:
    def test_basic_window(self):
        ngram = _ts_ngram(length=3)
        rows = [{'id': i, 'id2': i * 10} for i in range(5)]
        out = ngram.form_ngram(rows, TestSchema)
        assert len(out) == 3  # windows starting at 0,1,2
        assert [out[0][t]['id'] for t in range(3)] == [0, 1, 2]
        assert out[1][0]['id'] == 1

    def test_delta_threshold_drops_gaps(self):
        ngram = _ts_ngram(length=2, delta_threshold=1)
        rows = [{'id': i, 'id2': 0} for i in [0, 1, 5, 6]]
        out = ngram.form_ngram(rows, TestSchema)
        pairs = [(w[0]['id'], w[1]['id']) for w in out]
        assert pairs == [(0, 1), (5, 6)]  # (1,5) violates the threshold

    def test_no_overlap(self):
        ngram = _ts_ngram(length=2, overlap=False)
        rows = [{'id': i, 'id2': 0} for i in range(6)]
        out = ngram.form_ngram(rows, TestSchema)
        starts = [w[0]['id'] for w in out]
        assert starts == [0, 2, 4]

    def test_unsorted_input_gets_sorted(self):
        ngram = _ts_ngram(length=2)
        rows = [{'id': i, 'id2': 0} for i in [3, 1, 0, 2]]
        out = ngram.form_ngram(rows, TestSchema)
        assert [(w[0]['id'], w[1]['id']) for w in out] == [(0, 1), (1, 2), (2, 3)]

    def test_different_fields_per_timestep(self):
        ngram = NGram({0: [TestSchema.id, TestSchema.id2], 1: [TestSchema.id]},
                      delta_threshold=1, timestamp_field=TestSchema.id)
        rows = [{'id': i, 'id2': i} for i in range(3)]
        out = ngram.form_ngram(rows, TestSchema)
        assert set(out[0][0].keys()) == {'id', 'id2'}
        assert set(out[0][1].keys()) == {'id'}

    def test_negative_offsets(self):
        ngram = NGram({-1: [TestSchema.id], 0: [TestSchema.id], 1: [TestSchema.id]},
                      delta_threshold=1, timestamp_field=TestSchema.id)
        rows = [{'id': i} for i in range(4)]
        out = ngram.form_ngram(rows, TestSchema)
        assert len(out) == 2
        assert sorted(out[0].keys()) == [-1, 0, 1]

    def test_non_consecutive_offsets_rejected(self):
        with pytest.raises(PetastormTpuError):
            NGram({0: [TestSchema.id], 2: [TestSchema.id]}, 1, TestSchema.id)

    def test_regex_resolution(self):
        ngram = NGram({0: ['id.*'], 1: ['id']}, delta_threshold=1, timestamp_field='id')
        ngram.resolve_regex_field_names(TestSchema)
        names = set(ngram.get_field_names_at_timestep(0))
        assert {'id', 'id2', 'id_float', 'id_odd'} == names


class TestNgramEndToEnd:
    def test_ngram_read(self, synthetic_dataset):
        ngram = _ts_ngram(length=3, delta_threshold=1)
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                         shuffle_row_groups=False) as reader:
            windows = list(reader)
        # 10 row groups x 10 rows, windows within groups: 8 per group
        assert len(windows) == 80
        w = windows[0]
        assert sorted(w.keys()) == [0, 1, 2]
        ids = [w[t].id for t in range(3)]
        assert ids[1] == ids[0] + 1 and ids[2] == ids[0] + 2
        # namedtuples carry only that timestep's fields
        assert set(w[0]._fields) == {'id', 'id2'}

    def test_ngram_never_crosses_rowgroup_boundary(self, synthetic_dataset):
        ngram = _ts_ngram(length=3, delta_threshold=1)
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                         shuffle_row_groups=False) as reader:
            starts = sorted(w[0].id for w in reader)
        # starts 8,9 of each group of 10 can't fit a 3-window
        expected = sorted(i for i in range(100) if i % 10 <= 7)
        assert starts == expected

    def test_ngram_with_images(self, synthetic_dataset):
        ngram = NGram({0: [TestSchema.id, TestSchema.image_png], 1: [TestSchema.id]},
                      delta_threshold=1, timestamp_field=TestSchema.id)
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                         shuffle_row_groups=False) as reader:
            w = next(iter(reader))
        expected = {r['id']: r for r in synthetic_dataset.data}
        np.testing.assert_array_equal(w[0].image_png, expected[w[0].id]['image_png'])

    def test_ngram_shuffle_row_drop_spillover(self, synthetic_dataset):
        """Row-drop partitions must not lose windows at partition boundaries."""
        ngram = _ts_ngram(length=2, delta_threshold=1)
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy', ngram=ngram,
                         shuffle_row_groups=False, shuffle_row_drop_partitions=2) as reader:
            starts = sorted(w[0].id for w in reader)
        expected = sorted(i for i in range(100) if i % 10 <= 8)
        assert starts == expected
