"""Row-group indexing tests (modeled on reference tests/test_rowgroup_indexing.py)."""

import pytest

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.etl.rowgroup_indexers import FieldNotNullIndexer, SingleFieldIndexer
from petastorm_tpu.etl.rowgroup_indexing import build_rowgroup_index, get_row_group_indexes


def test_indexes_loaded(synthetic_dataset):
    indexes = get_row_group_indexes(synthetic_dataset.url)
    assert set(indexes) == {'id_index', 'sensor_name_index', 'partition_index',
                            'matrix_nullable_index'}


def test_single_field_index_lookup(synthetic_dataset):
    indexes = get_row_group_indexes(synthetic_dataset.url)
    id_index = indexes['id_index']
    # id=5 lives in row group 0 (rows 0-9 with 10 rows per group)
    assert id_index.get_row_group_indexes(5) == {0}
    assert id_index.get_row_group_indexes(95) == {9}
    assert id_index.get_row_group_indexes(12345) == set()


def test_sensor_name_index_covers_all_groups(synthetic_dataset):
    indexes = get_row_group_indexes(synthetic_dataset.url)
    # each group of 10 consecutive ids contains all 4 sensor names (idx % 4)
    sensors = indexes['sensor_name_index']
    for s in range(4):
        assert indexes['sensor_name_index'].get_row_group_indexes('sensor_{}'.format(s)) == set(range(10))
    assert sorted(sensors.indexed_values) == ['sensor_0', 'sensor_1', 'sensor_2', 'sensor_3']


def test_not_null_index(synthetic_dataset):
    indexes = get_row_group_indexes(synthetic_dataset.url)
    # matrix_nullable is null when idx % 5 == 0; every group of 10 has non-null rows
    assert indexes['matrix_nullable_index'].get_row_group_indexes() == set(range(10))


def test_indexer_merge():
    a = SingleFieldIndexer('ix', 'f')
    a.build_index([{'f': 1}, {'f': 2}], piece_index=0)
    b = SingleFieldIndexer('ix', 'f')
    b.build_index([{'f': 2}, {'f': 3}], piece_index=1)
    merged = a + b
    assert merged.get_row_group_indexes(2) == {0, 1}
    assert merged.get_row_group_indexes(1) == {0}
    with pytest.raises(PetastormTpuError):
        a + SingleFieldIndexer('ix', 'other_field')


def test_not_null_indexer_merge():
    a = FieldNotNullIndexer('ix', 'f')
    a.build_index([{'f': None}], piece_index=0)
    b = FieldNotNullIndexer('ix', 'f')
    b.build_index([{'f': 3}], piece_index=1)
    assert (a + b).get_row_group_indexes() == {1}


def test_empty_indexers_raises(synthetic_dataset):
    with pytest.raises(PetastormTpuError):
        build_rowgroup_index(synthetic_dataset.url, [])
