"""Pool protocol tests across thread/process/dummy pools
(modeled on reference workers_pool/tests/test_workers_pool.py)."""

import os
import tempfile

import pytest

from petastorm_tpu.serializers import ArrowTableSerializer, PickleSerializer
from petastorm_tpu.test_util.stub_workers import (DoubleOutputWorker, ExceptionEveryNWorker,
                                                  IdentityWorker, SetupArgsEchoWorker,
                                                  SleepyIdentityWorker, ZeroOutputWorker)
from petastorm_tpu.workers import (ConcurrentVentilator, DummyPool, EmptyResultError, ProcessPool,
                                   ThreadPool)

ALL_POOLS = [lambda n=3: ThreadPool(n), lambda n=3: DummyPool(n)]
POOL_IDS = ['thread', 'dummy']


def _drain(pool):
    results = []
    while True:
        try:
            results.append(pool.get_results())
        except EmptyResultError:
            return results


@pytest.mark.parametrize('make_pool', ALL_POOLS, ids=POOL_IDS)
def test_identity_all_items(make_pool):
    pool = make_pool()
    pool.start(IdentityWorker)
    for i in range(50):
        pool.ventilate(i)
    results = _drain(pool)
    assert sorted(results) == list(range(50))
    pool.stop(); pool.join()


@pytest.mark.parametrize('make_pool', ALL_POOLS, ids=POOL_IDS)
def test_multiple_results_per_item(make_pool):
    pool = make_pool()
    pool.start(DoubleOutputWorker)
    for i in range(10):
        pool.ventilate(i)
    results = _drain(pool)
    assert len(results) == 20
    pool.stop(); pool.join()


@pytest.mark.parametrize('make_pool', ALL_POOLS, ids=POOL_IDS)
def test_zero_output_workers(make_pool):
    """Items that publish nothing still count as processed (reference :268-297)."""
    pool = make_pool()
    pool.start(ZeroOutputWorker)
    for i in range(20):
        pool.ventilate(i)
    assert _drain(pool) == []
    pool.stop(); pool.join()


def test_thread_pool_exception_propagates():
    pool = ThreadPool(2)
    pool.start(ExceptionEveryNWorker, worker_setup_args=1)  # fail on every item
    pool.ventilate(5)
    with pytest.raises(ValueError, match='stub failure on 5'):
        _drain(pool)
    pool.stop(); pool.join()


def test_thread_pool_continues_after_exception():
    pool = ThreadPool(1)
    pool.start(ExceptionEveryNWorker, worker_setup_args=5)
    for i in [1, 2, 5, 3]:
        pool.ventilate(i)
    results, errors = [], []
    while True:
        try:
            results.append(pool.get_results())
        except EmptyResultError:
            break
        except ValueError as e:
            errors.append(e)
    assert sorted(results) == [1, 2, 3]
    assert len(errors) == 1
    pool.stop(); pool.join()


def test_thread_pool_fifo_single_worker():
    pool = ThreadPool(1)
    pool.start(IdentityWorker)
    for i in range(30):
        pool.ventilate(i)
    assert _drain(pool) == list(range(30))
    pool.stop(); pool.join()


def test_stop_mid_work_does_not_hang():
    pool = ThreadPool(4, results_queue_size=2)
    pool.start(SleepyIdentityWorker)
    for i in range(100):
        pool.ventilate(i, sleep_s=0.005)
    # consume a few then stop: workers blocked on the full results queue must exit
    for _ in range(3):
        pool.get_results()
    pool.stop()
    pool.join()


def test_diagnostics():
    # the unified pool diagnostics schema (docs/observability.md): identical
    # key set and units for every pool type
    pool = ThreadPool(2)
    pool.start(IdentityWorker)
    diag = pool.diagnostics
    assert {'workers_count', 'items_ventilated', 'items_completed',
            'items_in_flight', 'results_queue_depth'} <= set(diag)
    assert diag['workers_count'] == 2
    pool.stop(); pool.join()


# ---------------------------------------------------------------------------
# Process pool (spawned subprocesses; heavier — keep the matrix small)
# ---------------------------------------------------------------------------

class TestProcessPool:
    def test_identity(self):
        pool = ProcessPool(2)
        pool.start(IdentityWorker)
        for i in range(20):
            pool.ventilate(i)
        results = _drain(pool)
        assert sorted(results) == list(range(20))
        pool.stop(); pool.join()

    def test_setup_args_survive_spawn(self):
        pool = ProcessPool(2)
        pool.start(SetupArgsEchoWorker, worker_setup_args={'key': [1, 2, 3]})
        pool.ventilate(7)
        value, args = pool.get_results()
        assert value == 7 and args == {'key': [1, 2, 3]}
        pool.stop(); pool.join()

    def test_exception_propagates(self):
        pool = ProcessPool(1)
        pool.start(ExceptionEveryNWorker, worker_setup_args=1)
        pool.ventilate(5)
        with pytest.raises(ValueError, match='stub failure on 5'):
            _drain(pool)
        pool.stop(); pool.join()

    @pytest.mark.parametrize('transport', ['shm', 'zmq'])
    def test_arrow_table_serializer(self, transport):
        import pyarrow as pa
        from petastorm_tpu.test_util.stub_workers import ArrowTableWorker

        pool = ProcessPool(1, serializer=ArrowTableSerializer(), transport=transport)
        pool.start(ArrowTableWorker)
        pool.ventilate(5)
        table = pool.get_results()
        assert isinstance(table, pa.Table)
        assert table.num_rows == 5
        pool.stop(); pool.join()


def test_serializers_roundtrip():
    import numpy as np
    import pyarrow as pa
    for s in (PickleSerializer(), ArrowTableSerializer()):
        assert s.deserialize(s.serialize({'a': 1})) == {'a': 1}
    s = ArrowTableSerializer()
    t = pa.table({'x': np.arange(10), 'y': ['a'] * 10})
    out = s.deserialize(s.serialize(t))
    assert out.equals(t)
    # The shm transport hands deserialize a memoryview, not bytes — both the
    # table and the pickle-fallback branches must still dispatch correctly.
    for payload in (t, {'a': 1}):
        blob = memoryview(s.serialize(payload))
        out = s.deserialize(blob)
        if isinstance(payload, pa.Table):
            assert out.equals(payload)
        else:
            assert out == payload


class TestProcessPoolTransports:
    """Both results transports (first-party C++ shm ring, reference-style zmq)
    must behave identically through the pool protocol."""

    @pytest.mark.parametrize('transport', ['shm', 'zmq'])
    def test_identity_roundtrip(self, transport):
        pool = ProcessPool(2, transport=transport)
        assert pool.transport == transport
        pool.start(IdentityWorker)
        for i in range(30):
            pool.ventilate(i)
        results = _drain(pool)
        assert sorted(results) == list(range(30))
        pool.stop(); pool.join()

    @pytest.mark.parametrize('transport', ['shm', 'zmq'])
    def test_exception_propagates(self, transport):
        pool = ProcessPool(1, transport=transport)
        pool.start(ExceptionEveryNWorker, worker_setup_args=1)
        pool.ventilate(3)  # 3 % 1 == 0 -> raises
        with pytest.raises(ValueError, match='stub failure'):
            pool.get_results()
        pool.stop(); pool.join()

    def test_shm_large_payload_backpressure(self):
        # payloads larger than the ring force the blocking-write path and the
        # never-fits error path
        from petastorm_tpu.native.shm_ring import ShmRing
        import os
        name = '/pstpu_bp_{}'.format(os.getpid())
        ring = ShmRing.create(name, 1 << 20)
        w = ShmRing.attach(name)
        payload = b'z' * (400 << 10)
        assert w.try_write(payload)
        assert w.try_write(payload)
        assert not w.try_write(payload)  # full: 2x400KB + headers in a 1MB ring
        assert ring.try_read() == payload
        assert w.try_write(payload)  # space reclaimed
        with pytest.raises(ValueError, match='exceeds ring capacity'):
            w.try_write(b'z' * (2 << 20))
        w.close(); ring.close()

    def test_unknown_transport_rejected(self):
        with pytest.raises(ValueError, match='transport'):
            ProcessPool(1, transport='carrier-pigeon')

    def test_shm_writev_gather_segments(self):
        """writev lands N segments as ONE message, byte-identical to their
        concatenation — including wrap-around and numpy (read-only) inputs."""
        import os

        import numpy as np

        from petastorm_tpu.native.shm_ring import ShmRing
        name = '/pstpu_wv_{}'.format(os.getpid())
        ring = ShmRing.create(name, 64 << 10)
        w = ShmRing.attach(name)
        arr = np.arange(777, dtype=np.uint8)
        arr.setflags(write=False)  # Arrow-buffer views are read-only too
        parts = [b'H' + b'\x01' * 8, arr, b'', np.full((3, 5), 7, np.int32)]
        expect = b''.join(bytes(p) if not isinstance(p, np.ndarray) else p.tobytes()
                          for p in parts)
        for spin in range(40):  # enough messages to wrap the 64KB ring
            assert w.writev(parts)
            got = ring.try_read()
            assert got == expect, 'mismatch at message {}'.format(spin)
        with pytest.raises(ValueError, match='exceeds ring capacity'):
            w.writev([np.zeros(128 << 10, np.uint8)])
        w.close(); ring.close()


class TestNumpyBlockSerializer:
    """Raw-buffer block serializer: the process-pool default (round 3)."""

    def _rt(self, obj):
        from petastorm_tpu.serializers import NumpyBlockSerializer
        s = NumpyBlockSerializer()
        return s.deserialize(s.serialize(obj))

    def test_numeric_block_roundtrip_values_and_dtypes(self):
        import numpy as np
        block = {'img': np.arange(2 * 3 * 4, dtype=np.uint8).reshape(2, 3, 4),
                 'f': np.linspace(0, 1, 5, dtype=np.float32),
                 'ts': np.array(['2024-01-01', '2024-01-02'], dtype='datetime64[ns]')}
        out = self._rt(block)
        assert set(out) == set(block)
        for k in block:
            np.testing.assert_array_equal(out[k], block[k])
            assert out[k].dtype == block[k].dtype

    def test_mixed_block_object_columns_via_pickle(self):
        import numpy as np
        ragged = np.empty(2, dtype=object)
        ragged[0], ragged[1] = np.ones(2), np.ones(5)
        block = {'a': np.arange(3), 'ragged': ragged, 's': np.array(['x', 'yy'], dtype=object)}
        out = self._rt(block)
        np.testing.assert_array_equal(out['a'], np.arange(3))
        assert out['ragged'][1].shape == (5,)
        assert out['s'].tolist() == ['x', 'yy']

    def test_ragged_object_column_rides_raw_buffers(self):
        """Uniform-dtype ndarray cells (variable-size decoded images) must ride
        the raw-buffer channel — one buffer per cell, shapes in the header —
        not a pickle copy of the pixels; None cells (nullable) pass through."""
        import numpy as np
        from petastorm_tpu.serializers import NumpyBlockSerializer
        rng = np.random.default_rng(5)
        ragged = np.empty(5, dtype=object)
        for i in range(4):
            ragged[i] = rng.integers(0, 255, (8 + i, 6, 3), dtype=np.uint8)
        ragged[4] = None
        strings = np.array(['a', 'bb'], dtype=object)  # non-ndarray cells: pickled
        block = {'img': ragged, 'label': np.arange(5), 's': strings}
        s = NumpyBlockSerializer()
        data = s.serialize(block)
        # the pixels appear as raw bytes exactly once (no embedded pickle copy)
        assert data.count(ragged[0].tobytes()) == 1
        out = s.deserialize(bytearray(data))
        for i in range(4):
            np.testing.assert_array_equal(out['img'][i], ragged[i])
            assert out['img'][i].flags.writeable
        assert out['img'][4] is None
        assert out['s'].tolist() == ['a', 'bb']
        # mixed-dtype cells cannot share a buffer framing: whole column pickles
        mixed = np.empty(2, dtype=object)
        mixed[0], mixed[1] = np.ones(2, np.float32), np.ones(2, np.int64)
        out2 = s.deserialize(bytearray(s.serialize({'m': mixed, 'x': np.arange(2)})))
        np.testing.assert_array_equal(out2['m'][1], np.ones(2, np.int64))

    def test_ragged_cells_writable_after_immutable_transport(self):
        """Over zmq the message arrives as immutable bytes, so np.frombuffer
        views over it are read-only; deserialize must hand out WRITABLE object
        cells regardless of transport (in-place image ops, torch.from_numpy)
        — the ADVICE r5 / PT500 known-positive. Writable transports (shm ring
        / blob) must keep the zero-copy view."""
        import numpy as np
        from petastorm_tpu.serializers import NumpyBlockSerializer
        s = NumpyBlockSerializer()
        ragged = np.empty(2, dtype=object)
        ragged[0] = np.arange(12, dtype=np.uint8).reshape(3, 4)
        ragged[1] = np.arange(6, dtype=np.uint8).reshape(2, 3)
        block = {'img': ragged, 'label': np.arange(2)}
        out = s.deserialize(bytes(s.serialize(block)))  # zmq-style immutable
        for i, cell in enumerate(out['img']):
            assert cell.flags.writeable
            cell += 1  # the consumer contract: in-place ops must not raise
            np.testing.assert_array_equal(cell, ragged[i] + 1)
        # writable message (ring/blob channel): cells stay zero-copy views
        out2 = s.deserialize(bytearray(s.serialize(block)))
        assert out2['img'][0].flags.writeable
        assert out2['img'][0].base is not None

    def test_serialize_parts_matches_serialize_framing(self):
        """The gather-write channel's concatenated segments must be
        byte-identical to serialize() output (one deserializer serves both)."""
        import numpy as np
        from petastorm_tpu.serializers import NumpyBlockSerializer
        rng = np.random.default_rng(6)
        ragged = np.empty(3, dtype=object)
        for i in range(3):
            ragged[i] = rng.integers(0, 255, (4 + i, 5), dtype=np.uint8)
        block = {'img': ragged, 'label': np.arange(3),
                 'ts': np.array(['2024-01-01'], dtype='datetime64[ns]')}
        s = NumpyBlockSerializer()
        parts = s.serialize_parts(block)
        joined = b''.join(bytes(p) if not isinstance(p, np.ndarray) else p.tobytes()
                          for p in parts)
        assert joined == s.serialize(block)
        assert s.serialize_parts([1, 2]) is None  # non-block: caller pickles

    def test_empty_block_roundtrip(self):
        """Zero-row blocks (a predicate filtering a row group to nothing) must
        serialize: memoryview.cast('B') rejects zeros in shape/strides, so the
        serializer routes empties through tobytes (r5 e2e-matrix regression)."""
        import numpy as np
        block = {'id': np.empty((0,), np.int64),
                 'img': np.empty((0, 4, 4, 3), np.uint8),
                 'f': np.arange(3, dtype=np.float32)}
        out = self._rt(block)
        assert out['id'].shape == (0,)
        assert out['img'].shape == (0, 4, 4, 3) and out['img'].dtype == np.uint8
        np.testing.assert_array_equal(out['f'], block['f'])

    def test_non_block_payloads_roundtrip(self):
        import numpy as np
        rows = [{'x': np.ones(2)}, {'x': np.zeros(2)}]  # ngram-style list
        out = self._rt(rows)
        assert isinstance(out, list) and len(out) == 2
        exc = self._rt(ValueError('boom'))
        assert isinstance(exc, ValueError)
        assert self._rt({}) == {}

    def test_views_reference_message_not_copies(self):
        import numpy as np
        from petastorm_tpu.serializers import NumpyBlockSerializer
        s = NumpyBlockSerializer()
        data = s.serialize({'a': np.arange(10, dtype=np.int64)})
        out = s.deserialize(data)
        assert out['a'].base is not None  # a view over the message, not a copy

    @pytest.mark.parametrize('serializer_name', ['numpy_block', 'pickle'])
    def test_process_pool_block_payloads(self, serializer_name, tmp_path):
        """A process-pool columnar read returns identical data under both the
        raw-buffer default and plain pickle (reference reader.py:269 analog)."""
        import numpy as np
        from petastorm_tpu import make_reader
        from petastorm_tpu import reader as reader_mod
        from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
        from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
        from petastorm_tpu.serializers import NumpyBlockSerializer, PickleSerializer
        from petastorm_tpu.unischema import Unischema, UnischemaField

        schema = Unischema('S', [
            UnischemaField('id', np.int64, (), ScalarCodec(), False),
            UnischemaField('vec', np.float32, (4,), NdarrayCodec(), False),
        ])
        url = 'file://' + str(tmp_path / 'ds')
        rng = np.random.default_rng(0)
        expected = {i: rng.standard_normal(4).astype(np.float32) for i in range(40)}
        write_petastorm_dataset(url, schema, ({'id': i, 'vec': expected[i]}
                                              for i in range(40)), rows_per_row_group=10)

        serializer = NumpyBlockSerializer() if serializer_name == 'numpy_block' else PickleSerializer()
        orig = reader_mod._make_pool

        def patched(pool_type, workers, qsize, serializer_arg=None, **kwargs):
            return orig(pool_type, workers, qsize, serializer=serializer, **kwargs)

        reader_mod._make_pool = patched
        try:
            with make_reader(url, reader_pool_type='process', workers_count=2,
                             output='columnar', shuffle_row_groups=False) as reader:
                seen = {}
                for block in reader:
                    for i, row_id in enumerate(block.id.tolist()):
                        seen[int(row_id)] = np.asarray(block.vec[i])
        finally:
            reader_mod._make_pool = orig
        assert sorted(seen) == sorted(expected)
        for k in expected:
            np.testing.assert_array_equal(seen[k], expected[k])


@pytest.mark.skipif(
    not __import__('petastorm_tpu.native.shm_ring', fromlist=['is_available']).is_available(),
    reason='shm ring unavailable')
class TestShmRingStress:
    """Round-3 stress coverage of the default process-pool transport: ring
    wrap-around under sustained load, payloads exceeding ring capacity,
    worker crash mid-run, and /dev/shm exhaustion -> zmq fallback."""

    def test_wraparound_many_payloads_intact(self):
        from petastorm_tpu.test_util.stub_workers import BlobWorker
        # 30 items x 3 blobs x 200KB = ~18MB through a 1MB ring
        pool = ProcessPool(1, transport='shm', ring_bytes=1 << 20)
        pool.start(BlobWorker, {'size': 200 * 1024, 'count': 3})
        try:
            for i in range(30):
                pool.ventilate(i)
            got = []
            for _ in range(90):
                r = pool.get_results(timeout_s=60)
                assert r['blob'] == bytes([(r['item'] + r['j']) % 251]) * (200 * 1024)
                got.append((r['item'], r['j']))
            assert sorted(got) == [(i, j) for i in range(30) for j in range(3)]
        finally:
            pool.stop()
            pool.join()

    def test_payload_larger_than_ring_raises_not_hangs(self):
        from petastorm_tpu.test_util.stub_workers import BlobWorker
        pool = ProcessPool(1, transport='shm', ring_bytes=1 << 20)
        pool.start(BlobWorker, {'size': 2 << 20})  # 2MB > 1MB ring
        try:
            pool.ventilate(0)
            with pytest.raises(ValueError, match='exceeds ring capacity'):
                pool.get_results(timeout_s=60)
        finally:
            pool.stop()
            pool.join()

    def test_worker_crash_poison_item_raises_after_retries(self):
        """A crash-looping item is bounded by max_item_retries: the supervisor
        respawns + requeues, then surfaces PoisonItemError — no timeout, no
        hang (supervision replaced the old strand-until-timeout behavior)."""
        from petastorm_tpu.errors import PoisonItemError
        from petastorm_tpu.test_util.stub_workers import HardExitWorker
        pool = ProcessPool(1, transport='shm', ring_bytes=1 << 20, max_item_retries=1)
        pool.start(HardExitWorker, {'crash_on': 1})
        try:
            pool.ventilate(0)
            assert pool.get_results(timeout_s=60) == [0]
            pool.ventilate(1)  # kills every worker that touches it
            with pytest.raises(PoisonItemError, match='killed 2 consecutive worker'):
                while True:
                    pool.get_results(timeout_s=60)
            assert pool.diagnostics['worker_restarts'] >= 1
            assert pool.diagnostics['items_in_flight'] == 0
        finally:
            pool.stop()
            pool.join()

    def test_worker_crash_unsupervised_times_out_with_liveness_snapshot(self):
        """supervision=False restores the legacy behavior (a dead worker
        strands its items until the results timeout) — and the timeout message
        now carries the per-worker liveness snapshot."""
        from petastorm_tpu.test_util.stub_workers import HardExitWorker
        from petastorm_tpu.workers.process_pool import TimeoutWaitingForResultError
        pool = ProcessPool(1, transport='shm', ring_bytes=1 << 20, results_timeout_s=3,
                           supervision=False)
        pool.start(HardExitWorker, {'crash_on': 1})
        try:
            pool.ventilate(0)
            assert pool.get_results() == [0]
            pool.ventilate(1)  # worker dies here
            with pytest.raises(TimeoutWaitingForResultError) as exc_info:
                while True:
                    pool.get_results()
            msg = str(exc_info.value)
            assert 'items in flight' in msg
            assert 'Worker liveness' in msg and 'DEAD exitcode=13' in msg
            assert 'petastorm-tpu-diagnose' in msg
        finally:
            pool.stop()
            pool.join()

    @pytest.mark.parametrize('transport', ['shm', 'zmq'])
    def test_worker_crash_recovers_and_delivers_exactly_once(self, transport):
        """SIGKILL mid-item with a crash that does NOT repeat (the worker dies
        once, its replacement succeeds): every item is delivered exactly once
        and the restart is visible in diagnostics. Both transports: shm drains
        the dead worker's retired ring; zmq sweeps its lost dispatch pipe."""
        from petastorm_tpu.test_util.stub_workers import CrashOnceWorker
        pool = ProcessPool(2, transport=transport, ring_bytes=1 << 20)
        crash_flag = os.path.join(tempfile.mkdtemp(prefix='pstpu_crash_once_'), 'fired')
        pool.start(CrashOnceWorker, {'crash_on': 3, 'flag_path': crash_flag})
        try:
            for i in range(10):
                pool.ventilate(i)
            got = []
            while True:
                try:
                    got.append(pool.get_results(timeout_s=60))
                except EmptyResultError:
                    break
            assert sorted(got) == list(range(10))
            assert pool.diagnostics['worker_restarts'] >= 1
            assert pool.diagnostics['items_requeued'] >= 1
            assert pool.diagnostics['items_in_flight'] == 0
        finally:
            pool.stop()
            pool.join()

    def test_dev_shm_exhaustion_falls_back_to_zmq(self):
        from petastorm_tpu.test_util.stub_workers import IdentityWorker
        # absurd ring size: statvfs guard trips, pool degrades to zmq
        pool = ProcessPool(1, transport='shm', ring_bytes=1 << 45)
        pool.start(IdentityWorker)
        try:
            assert pool.transport == 'zmq'
            pool.ventilate(7)
            assert pool.get_results(timeout_s=30) == 7
        finally:
            pool.stop()
            pool.join()


class TestBlobSidechannel:
    """The large-payload /dev/shm blob path: single-copy serialize_into, COW
    mmap on read, unlink-on-read + sweep-on-join hygiene."""

    def test_serialize_into_bytes_match_serialize(self):
        import numpy as np
        from petastorm_tpu.serializers import NumpyBlockSerializer
        s = NumpyBlockSerializer()
        obj = {'a': np.arange(12, dtype=np.int64).reshape(3, 4),
               'b': np.ones((2, 5), np.float32),
               's': np.array(['x', 'y'], dtype=object)}
        regular = s.serialize(obj)
        buf = bytearray(len(regular))
        out = s.serialize_into(obj, lambda size: memoryview(buf)[:size])
        assert out is not None
        assert bytes(buf) == regular  # byte-identical framing
        back = s.deserialize(bytes(buf))
        np.testing.assert_array_equal(back['a'], obj['a'])
        np.testing.assert_array_equal(back['b'], obj['b'])
        assert back['s'].tolist() == ['x', 'y']

    def test_serialize_into_declines_small_and_nonblock(self):
        import numpy as np
        from petastorm_tpu.serializers import NumpyBlockSerializer
        s = NumpyBlockSerializer()
        called = []
        assert s.serialize_into({'a': np.arange(4)}, called.append, min_size=1 << 20) is None
        assert s.serialize_into(['not', 'a', 'block'], called.append) is None
        assert s.serialize_into({'only': np.array([None, 1], dtype=object)},
                                called.append) is None
        assert not called  # alloc never invoked on declined payloads

    @pytest.mark.skipif(not os.path.isdir('/dev/shm'), reason='needs /dev/shm')
    def test_process_pool_blob_payloads_roundtrip_and_cleanup(self, tmp_path):
        import glob
        import numpy as np
        from petastorm_tpu import make_reader
        from petastorm_tpu.codecs import RawTensorCodec, ScalarCodec
        from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
        from petastorm_tpu.unischema import Unischema, UnischemaField

        schema = Unischema('S', [
            UnischemaField('id', np.int64, (), ScalarCodec(), False),
            UnischemaField('big', np.uint8, (64, 64, 3), RawTensorCodec(), False),
        ])
        url = 'file://' + str(tmp_path / 'ds')
        rng = np.random.default_rng(1)
        expected = {i: rng.integers(0, 255, (64, 64, 3), dtype=np.uint8) for i in range(30)}
        write_petastorm_dataset(url, schema, ({'id': i, 'big': expected[i]}
                                              for i in range(30)), rows_per_row_group=10)

        # 10 rows x 12KB > the tiny threshold: every block rides the blob path
        from petastorm_tpu import reader as reader_mod
        orig = reader_mod._make_pool

        def patched(pool_type, workers, qsize, serializer=None, **kwargs):
            pool = orig(pool_type, workers, qsize, serializer=serializer, **kwargs)
            if hasattr(pool, '_blob_threshold'):
                pool._blob_threshold = 1024
            return pool

        reader_mod._make_pool = patched
        try:
            with make_reader(url, reader_pool_type='process', workers_count=1,
                             output='columnar', shuffle_row_groups=False,
                             num_epochs=1) as reader:
                blob_dir = reader._pool._blob_dir
                assert blob_dir is not None
                seen = {}
                for block in reader:
                    for i, row_id in enumerate(block.id.tolist()):
                        seen[row_id] = np.array(block.big[i])
                    # consumed blobs are unlinked on read
                    assert len(glob.glob(os.path.join(blob_dir, '*'))) <= 2
        finally:
            reader_mod._make_pool = orig
        assert len(seen) == 30
        for i, arr in expected.items():
            np.testing.assert_array_equal(seen[i], arr)
        assert not os.path.exists(blob_dir)  # swept on join

    def test_parts_channel_blob_write_roundtrip(self):
        """The split-once publish path: serialize_parts -> write_parts_into a
        blob-style buffer -> deserialize, and join_parts for the in-band
        fallback — one classification, every channel byte-identical."""
        import numpy as np
        from petastorm_tpu.serializers import NumpyBlockSerializer
        s = NumpyBlockSerializer()
        big = {'a': np.zeros((1 << 18,), np.uint8)}
        parts = s.serialize_parts(big)
        total = s.parts_size(parts)
        buf = bytearray(total)
        s.write_parts_into(parts, memoryview(buf))
        np.testing.assert_array_equal(s.deserialize(bytes(buf))['a'], big['a'])
        assert bytes(buf) == s.join_parts(parts) == s.serialize(big)
        # non-block: no parts; the pickle channel serves it
        assert s.serialize_parts(['x']) is None
        assert s.deserialize(s.serialize(['x'])) == ['x']

    @pytest.mark.skipif(not os.path.isdir('/dev/shm'), reason='needs /dev/shm')
    @pytest.mark.parametrize('rows_per_group,label', [(30, 'blob'), (4, 'inband')])
    def test_blocks_writable_on_every_channel(self, tmp_path, rows_per_group, label):
        # the uniform contract: process-pool blocks are WRITABLE whichever
        # channel they rode (blob COW mmap / ring bytearray / zmq copies)
        import numpy as np
        from petastorm_tpu import make_reader
        from petastorm_tpu.codecs import RawTensorCodec, ScalarCodec
        from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
        from petastorm_tpu.unischema import Unischema, UnischemaField

        schema = Unischema('S', [
            UnischemaField('id', np.int64, (), ScalarCodec(), False),
            UnischemaField('big', np.uint8, (128, 128, 3), RawTensorCodec(), False),
        ])
        url = 'file://' + str(tmp_path / 'ds')
        rng = np.random.default_rng(2)
        write_petastorm_dataset(url, schema, ({'id': i, 'big': rng.integers(
            0, 255, (128, 128, 3), dtype=np.uint8)} for i in range(30)),
            rows_per_row_group=rows_per_group)
        with make_reader(url, reader_pool_type='process', workers_count=1,
                         output='columnar', shuffle_row_groups=False, num_epochs=1) as r:
            block = next(iter(r))
            arr = block.big
            assert arr.flags.writeable, label
            arr[0, 0, 0, 0] = 7  # must not raise
            assert arr[0, 0, 0, 0] == 7


def test_dummy_pool_drops_pending_after_stop():
    # parity with ThreadPool: stop() discards ventilated-but-unprocessed items;
    # get_results after stop+join raises EmptyResultError, never AttributeError
    from petastorm_tpu.test_util.stub_workers import IdentityWorker
    pool = DummyPool()
    pool.start(IdentityWorker)
    pool.ventilate(1)
    pool.ventilate(2)
    assert pool.get_results() == 1
    pool.stop()
    pool.join()
    with pytest.raises(EmptyResultError):
        pool.get_results()


def test_dummy_pool_processes_on_consumer_thread():
    # the pool's reason to exist: worker code runs where a profiler sees it
    import threading
    from petastorm_tpu.workers.worker_base import WorkerBase

    class ThreadRecorder(WorkerBase):
        seen = []

        def process(self, x):
            ThreadRecorder.seen.append(threading.current_thread())
            self.publish(x)

    pool = DummyPool()
    pool.start(ThreadRecorder)
    pool.ventilate(1)
    assert pool.get_results() == 1
    assert ThreadRecorder.seen == [threading.main_thread()]
    pool.stop()
    pool.join()


@pytest.mark.skipif(not os.path.isdir('/dev/shm'), reason='needs /dev/shm')
def test_blob_allocation_failure_degrades_in_band(tmp_path):
    """A vanished blob dir (stand-in for tmpfs exhaustion; deletion works even
    under root, where chmod would be bypassed via CAP_DAC_OVERRIDE) must
    degrade every payload to the in-band channel — data complete and correct,
    no worker crash. Row groups are >= the 1MB blob threshold (1.38MB), so
    every payload genuinely attempts the blob path; mkdtemp is patched to
    hand the pool an already-deleted path, so the dir NEVER exists for any
    worker — no blob can land first, race-free. 4 failing groups also ride
    the worker through its self-disable threshold (3), though that flag is
    child-process state this test cannot observe directly."""
    import shutil
    import tempfile as tempfile_mod
    import numpy as np
    from petastorm_tpu import make_reader
    from petastorm_tpu.codecs import RawTensorCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('S', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('big', np.uint8, (96, 96, 3), RawTensorCodec(), False),
    ])
    url = 'file://' + str(tmp_path / 'ds')
    rng = np.random.default_rng(5)
    expected = {i: rng.integers(0, 255, (96, 96, 3), dtype=np.uint8) for i in range(200)}
    write_petastorm_dataset(url, schema, ({'id': i, 'big': expected[i]}
                                          for i in range(200)), rows_per_row_group=50)

    real_mkdtemp = tempfile_mod.mkdtemp
    hijacked = []

    def fake_mkdtemp(*args, **kwargs):
        d = real_mkdtemp(*args, **kwargs)
        if str(kwargs.get('prefix', '')).startswith('pstpu_blobs_'):
            shutil.rmtree(d)  # the pool gets a path that never exists
            hijacked.append(d)
        return d

    tempfile_mod.mkdtemp = fake_mkdtemp
    try:
        with make_reader(url, reader_pool_type='process', workers_count=1,
                         output='columnar', shuffle_row_groups=False, num_epochs=1) as r:
            seen = {}
            for block in r:
                for i, row_id in enumerate(block.id.tolist()):
                    seen[row_id] = np.array(block.big[i])
    finally:
        tempfile_mod.mkdtemp = real_mkdtemp
    assert hijacked, 'blob dir was never requested: test did not cover the sidechannel'
    assert len(seen) == 200
    for i, a in expected.items():
        np.testing.assert_array_equal(seen[i], a)

def test_stale_blob_dirs_swept_on_pool_start(tmp_path):
    """Blob dirs orphaned by a hard-killed process (dead pid in the name, or a
    name with no parseable pid) are reaped by the next pool start once past
    the mtime grace; dirs owned by a live process — own pid, a real foreign
    live pid, or any fresh dir — survive (ADVICE r3)."""
    import os
    import subprocess
    import sys
    import time as time_mod
    from petastorm_tpu.workers.process_pool import _BLOB_SWEEP_GRACE_S, _sweep_stale_blob_dirs

    root = tmp_path / 'shm'
    root.mkdir()
    # find a pid that is certainly dead
    dead_pid = 999999
    while True:
        try:
            os.kill(dead_pid, 0)
            dead_pid -= 1
        except ProcessLookupError:
            break
        except PermissionError:
            dead_pid -= 1
    # a real foreign live process, to exercise the os.kill success branch
    child = subprocess.Popen([sys.executable, '-c', 'import time; time.sleep(60)'])
    try:
        stale = root / ('pstpu_blobs_%d_abc' % dead_pid)
        legacy = root / 'pstpu_blobs_legacyname'
        own = root / ('pstpu_blobs_%d_xyz' % os.getpid())
        foreign_live = root / ('pstpu_blobs_%d_qrs' % child.pid)
        fresh_dead = root / ('pstpu_blobs_%d_new' % dead_pid)
        weird = root / 'pstpu_blobs_²_x'  # non-ASCII digit: must not crash the sweep
        other = root / 'unrelated_dir'
        for d in (stale, legacy, own, foreign_live, fresh_dead, weird, other):
            d.mkdir()
            (d / 'blob').write_bytes(b'x' * 128)
        old = time_mod.time() - _BLOB_SWEEP_GRACE_S - 5
        for d in (stale, legacy, own, foreign_live, weird):
            os.utime(d, (old, old))  # past the grace period; fresh_dead stays fresh

        _sweep_stale_blob_dirs(str(root))

        assert not stale.exists()
        assert not legacy.exists()
        assert not weird.exists()  # unparseable pid + old: reaped, not crashed
        assert own.exists()
        assert foreign_live.exists()
        assert fresh_dead.exists()  # dead owner but inside the grace window
        assert other.exists()
    finally:
        child.kill()
        child.wait()

def test_process_pool_divides_image_thread_budget(monkeypatch):
    """Spawned workers cannot see each other's in-process decode-thread
    accounting, so each gets cpu_count // workers_count via the env var —
    unless the user pinned it, which children inherit untouched."""
    from petastorm_tpu.test_util.stub_workers import EnvEchoWorker

    monkeypatch.delenv('PSTPU_IMG_THREADS', raising=False)
    pool = ProcessPool(2)
    pool.start(EnvEchoWorker, worker_setup_args='PSTPU_IMG_THREADS')
    pool.ventilate(1)
    _, value = pool.get_results()
    pool.stop(); pool.join()
    expected = max(1, (os.cpu_count() or 1) // 2)
    assert value == str(expected)

    monkeypatch.setenv('PSTPU_IMG_THREADS', '7')
    pool = ProcessPool(2)
    pool.start(EnvEchoWorker, worker_setup_args='PSTPU_IMG_THREADS')
    pool.ventilate(1)
    _, value = pool.get_results()
    pool.stop(); pool.join()
    assert value == '7'  # explicit pin inherited as-is
