"""Shutdown-path hardening: the reference's known race spots (SURVEY.md §5 —
zmq slow joiners, stop-aware puts, mid-epoch stop) exercised under repetition.
Every scenario must terminate promptly — a hang here is a deadlock regression.
"""

import threading
import time

import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.jax import JaxDataLoader


def _assert_finishes(fn, seconds, label):
    done = threading.Event()
    err = []

    def run():
        try:
            fn()
        except Exception as e:  # noqa: BLE001
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert done.wait(seconds), '{} did not finish within {}s (deadlock?)'.format(label, seconds)
    if err:
        raise err[0]


@pytest.mark.parametrize('pool', ['thread', 'process'])
def test_stop_mid_iteration_repeatedly(synthetic_dataset, pool):
    # stop with rows still in flight: workers blocked on a full results queue
    # must unblock and exit (reference thread_pool.py:200-214 stop-aware put)
    def cycle():
        reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                             reader_pool_type=pool, workers_count=2,
                             results_queue_size=2, num_epochs=None)
        it = iter(reader)
        for _ in range(5):
            next(it)
        reader.stop()
        reader.join()

    n = 2 if pool == 'process' else 5
    for _ in range(n):
        _assert_finishes(cycle, 60, 'stop mid-iteration ({})'.format(pool))


def test_immediate_stop_without_reading(synthetic_dataset):
    def cycle():
        reader = make_reader(synthetic_dataset.url, schema_fields=['id'],
                             reader_pool_type='thread', workers_count=3)
        reader.stop()
        reader.join()

    for _ in range(5):
        _assert_finishes(cycle, 30, 'immediate stop')


def test_loader_context_exit_mid_batch(synthetic_dataset):
    def cycle():
        with make_reader(synthetic_dataset.url, schema_fields=['id'],
                         reader_pool_type='thread', workers_count=2,
                         num_epochs=None) as reader:
            loader = JaxDataLoader(reader, batch_size=7, shuffling_queue_capacity=20)
            it = iter(loader)
            next(it)
            next(it)
        # context exit stops the reader while the loader generator is live

    for _ in range(3):
        _assert_finishes(cycle, 30, 'loader context exit')


def test_loader_diagnostics_counters(synthetic_dataset):
    with make_reader(synthetic_dataset.url, schema_fields=['id'],
                     reader_pool_type='dummy') as reader:
        loader = JaxDataLoader(reader, batch_size=10, drop_last=False)
        it = iter(loader)
        next(it)
        time.sleep(0.01)
        d = loader.diagnostics
        assert d['rows_emitted'] == 10
        assert 0.0 <= d['reader_wait_fraction'] <= 1.0
        assert d['reader_wait_s'] >= 0.0
        list(it)
        assert loader.diagnostics['rows_emitted'] == 100


def test_prefetch_checkpoint_churn_no_deadlock(synthetic_dataset):
    """Soak the round-3 concurrency: background prefetch pump + loader state
    lock + thread pool, with state_dict() hammered from the consumer thread and
    early iterator abandonment — must neither deadlock nor leak pump threads."""
    import threading
    import jax
    from petastorm_tpu import make_reader
    from petastorm_tpu.jax import JaxDataLoader, prefetch_to_device

    before_threads = threading.active_count()
    for round_i in range(3):
        reader = make_reader(synthetic_dataset.url, reader_pool_type='thread',
                             workers_count=2, output='columnar',
                             schema_fields=['id', 'matrix'],
                             shuffle_row_groups=True, seed=round_i, num_epochs=None)
        loader = JaxDataLoader(reader, batch_size=8, shuffling_queue_capacity=32,
                               seed=round_i)
        it = prefetch_to_device(iter(loader), jax.devices()[0], size=2)
        for _ in range(5):
            next(it)
            state = loader.state_dict()
            assert state['version'] == 1
        it.close()  # abandon mid-stream
        reader.stop()
        reader.join()
    # give daemon pump threads a moment to exit, then check for leaks
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        leaked = [t for t in threading.enumerate() if t.name == 'pstpu-prefetch']
        if not leaked:
            break
        time.sleep(0.05)
    assert not [t for t in threading.enumerate() if t.name == 'pstpu-prefetch']
    assert threading.active_count() <= before_threads + 2
