"""Transient-storage retry: policy, classifier, and fault-injected reads
through a flaky pyarrow filesystem (SURVEY §2.9 elasticity; the object-store
analog of the HDFS failover tests in test_hdfs_namenode.py)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.fs as pafs
import pyarrow.parquet as pq
import pytest

from petastorm_tpu.pafs_util import DelegatingHandler
from petastorm_tpu.retry import (RetryPolicy, is_transient_io_error, wrap_retrying)

FAST = RetryPolicy(max_attempts=4, initial_backoff_s=0.001, max_backoff_s=0.004)


# ---------------------------------------------------------------------------
# Fault injection: a pyarrow filesystem whose chosen operations fail with a
# configurable transient error for the first N calls, then delegate for real.
# ---------------------------------------------------------------------------

class _FlakyFile(object):
    """File-like that raises on the first ``fail_reads`` read() calls (shared
    across reopens via the ``counters`` dict), then reads for real."""

    def __init__(self, inner, key, counters, fail_reads, exc_factory):
        self._inner = inner
        self._key = key
        self._counters = counters
        self._fail_reads = fail_reads
        self._exc_factory = exc_factory

    def read(self, nbytes=None):
        n = self._counters.setdefault(self._key, 0)
        if n < self._fail_reads:
            self._counters[self._key] = n + 1
            raise self._exc_factory()
        return self._inner.read(nbytes) if nbytes is not None else self._inner.read()

    def seek(self, offset, whence=0):
        return self._inner.seek(offset, whence)

    def tell(self):
        return self._inner.tell()

    def size(self):
        return self._inner.size()

    @property
    def closed(self):
        return self._inner.closed

    def close(self):
        self._inner.close()


class FlakyHandler(DelegatingHandler):
    """Delegates to a real pyarrow filesystem; the first ``fail_opens`` input
    opens and the first ``fail_reads`` stream reads raise ``exc_factory()``."""

    def __init__(self, fs, fail_opens=0, fail_reads=0,
                 exc_factory=lambda: OSError('connection reset by peer')):
        super(FlakyHandler, self).__init__(fs)
        self.fail_opens = fail_opens
        self.fail_reads = fail_reads
        self.exc_factory = exc_factory
        self.open_calls = 0
        self.read_fail_counters = {}

    def __eq__(self, other):
        return self is other

    def __ne__(self, other):
        return self is not other

    def get_type_name(self):
        return 'flaky+' + self.fs.type_name

    def _open(self, path):
        self.open_calls += 1
        if self.open_calls <= self.fail_opens:
            raise self.exc_factory()
        inner = self.fs.open_input_file(path)
        return pa.PythonFile(
            _FlakyFile(inner, path, self.read_fail_counters, self.fail_reads,
                       self.exc_factory), mode='r')

    def open_input_stream(self, path):
        return self._open(path)

    def open_input_file(self, path):
        return self._open(path)


def _flaky_fs(**kwargs):
    handler = FlakyHandler(pafs.LocalFileSystem(), **kwargs)
    return pafs.PyFileSystem(handler), handler


def _write_table(path, rows=500):
    table = pa.table({'id': np.arange(rows, dtype=np.int64),
                      'payload': np.random.default_rng(1).random(rows)})
    pq.write_table(table, path)
    return table


# ---------------------------------------------------------------------------
# Classifier
# ---------------------------------------------------------------------------

def test_classifier_transient_cases():
    assert is_transient_io_error(ConnectionResetError('peer'))
    assert is_transient_io_error(TimeoutError())
    assert is_transient_io_error(OSError('AWS Error SLOW_DOWN during GetObject'))
    assert is_transient_io_error(OSError('HTTP 503 Service Unavailable'))
    assert is_transient_io_error(OSError('When reading gs://b/o: curl error 56'))
    import errno
    assert is_transient_io_error(OSError(errno.ECONNRESET, 'reset'))


def test_classifier_permanent_cases():
    assert not is_transient_io_error(FileNotFoundError('gone'))
    assert not is_transient_io_error(PermissionError('denied'))
    assert not is_transient_io_error(ValueError('bad parquet magic'))
    assert not is_transient_io_error(OSError('Invalid Parquet file size'))
    # numbers that are NOT http statuses must not trip the status markers
    assert not is_transient_io_error(OSError('Unexpected end of stream: got 500 bytes, expected 4096'))
    assert not is_transient_io_error(OSError('Max retries exceeded with url'))


# ---------------------------------------------------------------------------
# Policy
# ---------------------------------------------------------------------------

def test_policy_retries_then_succeeds():
    calls = {'n': 0}

    def flaky():
        calls['n'] += 1
        if calls['n'] < 3:
            raise OSError('connection reset by peer')
        return 'ok'

    assert FAST.call(flaky) == 'ok'
    assert calls['n'] == 3


def test_policy_exhausts_and_raises_original():
    def always():
        raise OSError('HTTP 503 Service Unavailable')

    with pytest.raises(OSError, match='503'):
        FAST.call(always)


def test_policy_permanent_error_not_retried():
    calls = {'n': 0}

    def notfound():
        calls['n'] += 1
        raise FileNotFoundError('nope')

    with pytest.raises(FileNotFoundError):
        FAST.call(notfound)
    assert calls['n'] == 1


def test_policy_backoff_bounded():
    p = RetryPolicy(max_attempts=10, initial_backoff_s=0.1, multiplier=2.0,
                    max_backoff_s=0.5, jitter=0.25)
    for attempt in range(1, 10):
        s = p.backoff_s(attempt)
        assert 0 < s <= 0.5 * 1.25 + 1e-9


def test_policy_validates():
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(deadline_s=0)
    with pytest.raises(ValueError):
        RetryPolicy(deadline_s=-1.0)


def test_policy_deadline_cuts_retries_short():
    """With an end-to-end deadline the policy surfaces the last error as soon
    as the NEXT backoff would blow the budget — long before max_attempts."""
    import time as _time
    p = RetryPolicy(max_attempts=50, initial_backoff_s=0.05, multiplier=1.0,
                    max_backoff_s=0.05, jitter=0.0, deadline_s=0.12)
    calls = []

    def always_reset():
        calls.append(1)
        raise ConnectionResetError('reset')

    t0 = _time.monotonic()
    with pytest.raises(ConnectionResetError):
        p.call(always_reset)
    assert _time.monotonic() - t0 < 1.0
    assert 1 <= len(calls) < 50


def test_with_deadline_clones_without_mutating():
    p = RetryPolicy(max_attempts=7, initial_backoff_s=0.01)
    bounded = p.with_deadline(2.5)
    assert bounded is not p
    assert bounded.deadline_s == 2.5 and p.deadline_s is None
    assert bounded.max_attempts == 7
    # the budget participates in identity: configs differing only in
    # deadline must not collapse under caching keyed by the policy
    assert bounded != p and hash(bounded) != hash(p)
    assert p.with_deadline(None) == p


def test_fetch_range_deadline_bounds_the_whole_fetch(tmp_path):
    """The fabric fallback hands its remaining transfer budget to
    fetch_range: a store that keeps resetting must surface the error within
    the budget instead of grinding through every attempt."""
    import time as _time
    from petastorm_tpu.retry import fetch_range
    path = str(tmp_path / 'blob.bin')
    with open(path, 'wb') as f:
        f.write(b'q' * 1000)
    flaky, _handler = _flaky_fs(
        fail_reads=10**6,
        exc_factory=lambda: ConnectionResetError('connection reset'))
    slow = RetryPolicy(max_attempts=50, initial_backoff_s=0.05,
                       multiplier=1.0, max_backoff_s=0.05, jitter=0.0)
    t0 = _time.monotonic()
    with pytest.raises(ConnectionResetError):
        fetch_range(flaky, path, 0, 10, policy=slow, deadline_s=0.12)
    assert _time.monotonic() - t0 < 1.0


# ---------------------------------------------------------------------------
# Filesystem wrapper: real parquet reads through injected faults
# ---------------------------------------------------------------------------

def test_parquet_read_survives_flaky_opens(tmp_path):
    path = str(tmp_path / 'data.parquet')
    expected = _write_table(path)
    flaky, handler = _flaky_fs(fail_opens=2)
    fs = wrap_retrying(flaky, FAST)
    got = pq.ParquetFile(fs.open_input_file(path)).read()
    assert got.equals(expected)
    assert handler.open_calls >= 3  # 2 failures + >=1 success


def test_parquet_read_survives_midstream_failures(tmp_path):
    path = str(tmp_path / 'data.parquet')
    expected = _write_table(path)
    flaky, handler = _flaky_fs(fail_reads=2)
    fs = wrap_retrying(flaky, FAST)
    got = pq.ParquetFile(fs.open_input_file(path)).read()
    assert got.equals(expected)
    assert handler.read_fail_counters  # faults were actually injected


def test_permanent_error_propagates_through_wrapper(tmp_path):
    flaky, _ = _flaky_fs()
    fs = wrap_retrying(flaky, FAST)
    with pytest.raises(FileNotFoundError):
        fs.open_input_file(str(tmp_path / 'missing.parquet')).read()


def test_exhausted_retries_raise_last_error(tmp_path):
    path = str(tmp_path / 'data.parquet')
    _write_table(path)
    flaky, _ = _flaky_fs(fail_opens=50)
    fs = wrap_retrying(flaky, FAST)
    with pytest.raises(OSError, match='connection reset'):
        fs.open_input_file(path)


def test_metadata_ops_retried(tmp_path):
    path = str(tmp_path / 'data.parquet')
    _write_table(path)

    calls = {'n': 0}

    class FlakyInfoHandler(FlakyHandler):
        def get_file_info(self, paths):
            calls['n'] += 1
            if calls['n'] <= 2:
                raise OSError('HTTP 429 Too Many Requests')
            return super(FlakyInfoHandler, self).get_file_info(paths)

    fs = wrap_retrying(pafs.PyFileSystem(FlakyInfoHandler(pafs.LocalFileSystem())), FAST)
    info = fs.get_file_info([path])[0]
    assert info.type == pafs.FileType.File
    assert calls['n'] == 3


# ---------------------------------------------------------------------------
# End-to-end: make_reader over a flaky "object store"
# ---------------------------------------------------------------------------

def test_make_reader_survives_flaky_object_store(tmp_path, monkeypatch):
    """A full reader run over a gs:// URL whose filesystem drops the first
    opens and mid-stream reads: the resolver's default retry wrapping must
    deliver every row exactly once, with the user's policy honored via
    ``make_reader(storage_retry_policy=...)``."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('S', [UnischemaField('id', np.int64, (), ScalarCodec(), False)])
    local_url = 'file://' + str(tmp_path / 'ds')
    write_petastorm_dataset(local_url, schema, ({'id': i} for i in range(100)),
                            rows_per_row_group=25)

    handlers = []

    def fake_gcs(*args, **kwargs):
        # "gs://<netloc>/<path>" resolves to netloc+path, a root-relative local
        # path: serve it from / with injected faults
        h = FlakyHandler(pafs.SubTreeFileSystem('/', pafs.LocalFileSystem()),
                         fail_opens=1, fail_reads=1)
        handlers.append(h)
        return pafs.PyFileSystem(h)

    import petastorm_tpu.fs as fs_mod
    monkeypatch.setattr(fs_mod.pafs, 'GcsFileSystem', fake_gcs)

    gs_url = 'gs:/' + str(tmp_path / 'ds')  # gs://<tmp_path>/ds
    with make_reader(gs_url, reader_pool_type='dummy', shuffle_row_groups=False,
                     num_epochs=1, storage_retry_policy=FAST) as r:
        ids = sorted(row.id for row in r)
    assert ids == list(range(100))
    assert any(h.open_calls > 0 for h in handlers)


def test_retry_policy_survives_factory_pickle():
    """The resolver's picklable filesystem factory must carry the user's
    policy into worker processes — a tuned/disabled policy silently reverting
    to defaults in workers was a reviewed failure mode."""
    import pickle
    from petastorm_tpu.fs import FilesystemResolver

    policy = RetryPolicy(max_attempts=7, initial_backoff_s=0.01)
    resolver = FilesystemResolver('file:///tmp/x', retry_policy=policy)
    factory = pickle.loads(pickle.dumps(resolver.filesystem_factory()))
    assert factory._retry_policy.max_attempts == 7
    # and through resolver pickling itself
    r2 = pickle.loads(pickle.dumps(resolver))
    assert r2._retry_policy.max_attempts == 7


def test_retrying_fs_equality_respects_policy():
    """PyFileSystems wrapping the same store under DIFFERENT policies must not
    compare equal — pyarrow dataset machinery dedupes on filesystem equality,
    so policy-blind equality could silently swap a tuned policy for another."""
    local = pafs.LocalFileSystem()
    fast = wrap_retrying(local, RetryPolicy(max_attempts=2, initial_backoff_s=0.01))
    slow = wrap_retrying(local, RetryPolicy(max_attempts=9, initial_backoff_s=0.01))
    same = wrap_retrying(local, RetryPolicy(max_attempts=2, initial_backoff_s=0.01))
    assert fast.equals(same)
    assert not fast.equals(slow)


def test_get_schema_from_dataset_url_honors_policy(tmp_path):
    """The reference-parity alias must thread storage_retry_policy through
    (ADVICE r4: it silently used default wrapping)."""
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import (get_schema_from_dataset_url,
                                                    write_petastorm_dataset)
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('S', [UnischemaField('id', np.int64, (), ScalarCodec(), False)])
    url = 'file://' + str(tmp_path / 'ds')
    write_petastorm_dataset(url, schema, ({'id': i} for i in range(4)),
                            rows_per_row_group=2)
    loaded = get_schema_from_dataset_url(url, storage_retry_policy=False)
    assert [f for f in loaded.fields] == ['id']


def test_retry_policy_false_reaches_discovery_path(tmp_path, monkeypatch):
    """storage_retry_policy=False must disable retries EVERYWHERE, including
    schema/row-group discovery — a transient failure during get_schema then
    surfaces immediately instead of silently retrying with defaults."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('S', [UnischemaField('id', np.int64, (), ScalarCodec(), False)])
    write_petastorm_dataset('file://' + str(tmp_path / 'ds'),
                            schema, ({'id': i} for i in range(10)), rows_per_row_group=5)

    import petastorm_tpu.fs as fs_mod
    monkeypatch.setattr(
        fs_mod.pafs, 'GcsFileSystem',
        lambda *a, **k: pafs.PyFileSystem(FlakyHandler(
            pafs.SubTreeFileSystem('/', pafs.LocalFileSystem()), fail_opens=1)))

    gs_url = 'gs:/' + str(tmp_path / 'ds')
    with pytest.raises(OSError, match='connection reset'):
        make_reader(gs_url, reader_pool_type='dummy', storage_retry_policy=False)


def test_retry_policy_false_disables_wrapping(monkeypatch):
    import petastorm_tpu.fs as fs_mod

    local = pafs.LocalFileSystem()
    monkeypatch.setattr(fs_mod.pafs, 'GcsFileSystem', lambda *a, **k: local)
    wrapped = fs_mod.FilesystemResolver('gs://bucket/ds').filesystem()
    assert wrapped.type_name.startswith('py::retrying+')
    raw = fs_mod.FilesystemResolver('gs://bucket/ds', retry_policy=False).filesystem()
    assert raw is local

def test_mutating_ops_not_retried(tmp_path):
    """Deletes/moves must pass through unretried: a lost success response would
    otherwise resurface as a spurious FileNotFoundError on the retry."""
    calls = {'delete': 0, 'move': 0}

    class CountingHandler(DelegatingHandler):
        def get_type_name(self):
            return 'counting+' + self.fs.type_name

        def delete_file(self, path):
            calls['delete'] += 1
            raise OSError('connection reset by peer')

        def move(self, src, dest):
            calls['move'] += 1
            raise OSError('connection reset by peer')

    fs = wrap_retrying(pafs.PyFileSystem(CountingHandler(pafs.LocalFileSystem())), FAST)
    with pytest.raises(OSError):
        fs.delete_file(str(tmp_path / 'x'))
    with pytest.raises(OSError):
        fs.move(str(tmp_path / 'a'), str(tmp_path / 'b'))
    assert calls == {'delete': 1, 'move': 1}  # exactly one attempt each

def test_open_parquet_prebuffers_remote_reads(tmp_path, monkeypatch):
    """Remote (non-local) filesystems get pre_buffer coalescing — asserted on
    the actual kwarg, and whole row groups still read correctly through a
    wrapped PyFileSystem with faults."""
    import pyarrow.parquet as pq_mod

    from petastorm_tpu.native import open_parquet

    seen_kwargs = []
    real_parquet_file = pq_mod.ParquetFile

    def recording_parquet_file(*args, **kwargs):
        seen_kwargs.append(kwargs)
        return real_parquet_file(*args, **kwargs)

    monkeypatch.setattr(pq_mod, 'ParquetFile', recording_parquet_file)

    path = str(tmp_path / 'data.parquet')
    expected = _write_table(path)
    flaky, _ = _flaky_fs(fail_opens=1, fail_reads=1)
    fs = wrap_retrying(flaky, FAST)
    pf = open_parquet(path, filesystem=fs)
    assert seen_kwargs and seen_kwargs[-1].get('pre_buffer') is True
    got = pa.concat_tables(pf.read_row_group(i) for i in range(pf.num_row_groups))
    assert got.equals(expected)
    # local filesystems keep the non-prebuffered open
    import pyarrow.fs as pafs_mod
    seen_kwargs.clear()
    open_parquet(path, filesystem=pafs_mod.LocalFileSystem())
    if seen_kwargs:  # native kernel absent -> pyarrow fallback took this path
        assert not seen_kwargs[-1].get('pre_buffer')


def test_retrying_handler_is_hashable():
    """RetryingHandler defines __eq__ (policy-aware filesystem dedup); without
    a matching __hash__, Python sets __hash__ = None and the handler can never
    live in a set/dict — the ADVICE r5 / PT600 known-positive. Equal handlers
    must hash equal; distinct policies must not compare equal."""
    import pyarrow.fs as pafs_mod

    from petastorm_tpu.pafs_util import DelegatingHandler as Delegating
    from petastorm_tpu.retry import RetryingHandler

    a = RetryingHandler(pafs_mod.LocalFileSystem(), FAST)
    b = RetryingHandler(pafs_mod.LocalFileSystem(), FAST)
    assert a == b and hash(a) == hash(b)
    assert len({a, b}) == 1
    other = RetryingHandler(pafs_mod.LocalFileSystem(),
                            RetryPolicy(max_attempts=9))
    assert a != other
    # the shared base handler stays hashable too (same defect class)
    assert isinstance(hash(Delegating(pafs_mod.LocalFileSystem())), int)
    # wrap_retrying still yields a working PyFileSystem (hashability of the
    # PyFileSystem itself is a pyarrow property, not ours to grant)
    fs = wrap_retrying(pafs_mod.LocalFileSystem(), FAST)
    assert fs.get_file_info('/').type is not None


# ---------------------------------------------------------------------------
# fetch_range (the chunk store's fetch primitive) + mock-remote resolution
# ---------------------------------------------------------------------------

def test_fetch_range_reads_exact_window(tmp_path):
    from petastorm_tpu.retry import fetch_range
    path = str(tmp_path / 'blob.bin')
    payload = bytes(range(256)) * 4
    with open(path, 'wb') as f:
        f.write(payload)
    got = fetch_range(pafs.LocalFileSystem(), path, 100, 300, policy=FAST)
    assert got == payload[100:400]


def test_fetch_range_retries_transient_then_succeeds(tmp_path):
    """Each attempt opens a FRESH stream: a mid-read connection reset on
    attempt 1 must not poison attempt 2."""
    from petastorm_tpu.retry import fetch_range
    path = str(tmp_path / 'blob.bin')
    with open(path, 'wb') as f:
        f.write(b'q' * 1000)
    flaky, handler = _flaky_fs(
        fail_reads=2, exc_factory=lambda: ConnectionResetError('connection reset'))
    got = fetch_range(flaky, path, 10, 50, policy=FAST)
    assert got == b'q' * 50
    assert handler.read_fail_counters  # the fault actually fired


def test_fetch_range_short_read_is_transient():
    """A truncated body must classify transient (retry on a fresh stream),
    never cache garbage."""
    err = IOError('short read: got 10 of 50 bytes at offset 0 from /x')
    assert is_transient_io_error(err)


def test_mock_remote_scheme_resolves_to_wrapped_local_fs(tmp_path):
    """mock-remote:// is the hermetic remote: local files behind the SAME
    retry wrapper object stores get, reporting non-local so remote-only code
    paths (chunk store, pre_buffer reads) engage."""
    from petastorm_tpu.fs import FilesystemResolver
    (tmp_path / 'f.txt').write_bytes(b'hello')
    resolver = FilesystemResolver('mock-remote://' + str(tmp_path))
    assert resolver.scheme == 'mock-remote'
    assert not resolver.is_local
    fs = resolver.filesystem()
    assert isinstance(fs, pafs.PyFileSystem)  # retry-wrapped, not bare local
    with fs.open_input_file(str(tmp_path / 'f.txt')) as f:
        assert f.read() == b'hello'
    # picklable factory re-resolves in workers
    import pickle
    factory = pickle.loads(pickle.dumps(resolver.filesystem_factory()))
    assert isinstance(factory(), pafs.PyFileSystem)


def test_file_scheme_reports_local(tmp_path):
    from petastorm_tpu.fs import FilesystemResolver
    resolver = FilesystemResolver('file://' + str(tmp_path))
    assert resolver.scheme == 'file' and resolver.is_local
