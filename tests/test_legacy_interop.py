"""Interop with datasets written by the ORIGINAL petastorm library.

Strategy (no petastorm/pyspark installs): fake `petastorm.*` and
`pyspark.sql.types` modules are synthesized with the reference's exact class
layouts (unischema.py:46-80, codecs.py:54-231, etl/rowgroup_indexers.py:28-86),
instances are pickled, the fakes are torn down, and our restricted unpickler
must decode the bytes — then a real dataset whose _common_metadata carries only
the reference's pickled keys must read end-to-end through make_reader.
"""

import pickle
import sys
import types
from collections import OrderedDict, defaultdict
from decimal import Decimal

import numpy as np
import pyarrow.parquet as pq
import pytest

from petastorm_tpu import codecs as our_codecs
from petastorm_tpu.etl import legacy


def _install_fake_reference_modules():
    """Create sys.modules entries shaped like the reference's pickled classes."""
    created = []

    def module(name):
        mod = types.ModuleType(name)
        sys.modules[name] = mod
        created.append(name)
        return mod

    pyspark = module('pyspark')
    sql = module('pyspark.sql')
    sql_types = module('pyspark.sql.types')
    pyspark.sql = sql
    sql.types = sql_types
    for tname in ('ByteType', 'ShortType', 'IntegerType', 'LongType', 'FloatType',
                  'DoubleType', 'BooleanType', 'StringType', 'BinaryType',
                  'TimestampType', 'DateType'):
        setattr(sql_types, tname, type(tname, (object,), {'__module__': 'pyspark.sql.types'}))

    class DecimalType(object):
        __module__ = 'pyspark.sql.types'

        def __init__(self, precision=10, scale=0):
            self.precision = precision
            self.scale = scale
    sql_types.DecimalType = DecimalType

    petastorm = module('petastorm')
    unischema_mod = module('petastorm.unischema')
    codecs_mod = module('petastorm.codecs')
    etl_mod = module('petastorm.etl')
    indexers_mod = module('petastorm.etl.rowgroup_indexers')
    petastorm.unischema = unischema_mod
    petastorm.codecs = codecs_mod
    petastorm.etl = etl_mod
    etl_mod.rowgroup_indexers = indexers_mod

    from collections import namedtuple

    class UnischemaField(namedtuple('UnischemaField',
                                    ['name', 'numpy_dtype', 'shape', 'codec', 'nullable'])):
        __module__ = 'petastorm.unischema'
    unischema_mod.UnischemaField = UnischemaField

    class Unischema(object):
        __module__ = 'petastorm.unischema'

        def __init__(self, name, fields):
            self._name = name
            self._fields = OrderedDict((f.name, f) for f in sorted(fields, key=lambda t: t.name))
            for f in fields:
                if not hasattr(self, f.name):
                    setattr(self, f.name, f)
    unischema_mod.Unischema = Unischema

    class ScalarCodec(object):
        __module__ = 'petastorm.codecs'

        def __init__(self, spark_type):
            self._spark_type = spark_type

    class NdarrayCodec(object):
        __module__ = 'petastorm.codecs'

    class CompressedNdarrayCodec(object):
        __module__ = 'petastorm.codecs'

    class CompressedImageCodec(object):
        __module__ = 'petastorm.codecs'

        def __init__(self, image_codec='png', quality=80):
            self._image_codec = '.' + image_codec
            self._quality = quality

    codecs_mod.ScalarCodec = ScalarCodec
    codecs_mod.NdarrayCodec = NdarrayCodec
    codecs_mod.CompressedNdarrayCodec = CompressedNdarrayCodec
    codecs_mod.CompressedImageCodec = CompressedImageCodec

    class SingleFieldIndexer(object):
        __module__ = 'petastorm.etl.rowgroup_indexers'

        def __init__(self, index_name, index_field):
            self._index_name = index_name
            self._column_name = index_field
            self._index_data = defaultdict(set)

    class FieldNotNullIndexer(object):
        __module__ = 'petastorm.etl.rowgroup_indexers'

        def __init__(self, index_name, index_field):
            self._index_name = index_name
            self._column_name = index_field
            self._index_data = set()

    indexers_mod.SingleFieldIndexer = SingleFieldIndexer
    indexers_mod.FieldNotNullIndexer = FieldNotNullIndexer

    # classes are defined in a function: fix qualnames so pickle can resolve
    # them through their (fake) modules
    for cls in (UnischemaField, Unischema, ScalarCodec, NdarrayCodec,
                CompressedNdarrayCodec, CompressedImageCodec,
                SingleFieldIndexer, FieldNotNullIndexer, DecimalType):
        cls.__qualname__ = cls.__name__

    ns = dict(UnischemaField=UnischemaField, Unischema=Unischema,
              ScalarCodec=ScalarCodec, NdarrayCodec=NdarrayCodec,
              CompressedNdarrayCodec=CompressedNdarrayCodec,
              CompressedImageCodec=CompressedImageCodec,
              SingleFieldIndexer=SingleFieldIndexer,
              FieldNotNullIndexer=FieldNotNullIndexer,
              sql_types=sql_types)
    return ns, created


@pytest.fixture()
def ref(request):
    ns, created = _install_fake_reference_modules()

    def teardown():
        for name in created:
            sys.modules.pop(name, None)
    request.addfinalizer(teardown)
    return types.SimpleNamespace(**ns)


def _ref_schema_pickle(ref, protocol):
    schema = ref.Unischema('LegacySchema', [
        ref.UnischemaField('id', np.int64, (), ref.ScalarCodec(ref.sql_types.LongType()), False),
        ref.UnischemaField('name', np.unicode_ if hasattr(np, 'unicode_') else np.str_, (),
                           ref.ScalarCodec(ref.sql_types.StringType()), False),
        ref.UnischemaField('image', np.uint8, (4, 6, 3), ref.CompressedImageCodec('jpeg', 55), False),
        ref.UnischemaField('matrix', np.float32, (2, 3), ref.NdarrayCodec(), False),
        ref.UnischemaField('packed', np.uint16, (None,), ref.CompressedNdarrayCodec(), True),
        ref.UnischemaField('price', Decimal, (), ref.ScalarCodec(ref.sql_types.DecimalType(10, 2)), False),
    ])
    return pickle.dumps(schema, protocol=protocol)


@pytest.mark.parametrize('protocol', [2, pickle.HIGHEST_PROTOCOL])
def test_legacy_unischema_decodes(ref, protocol):
    data = _ref_schema_pickle(ref, protocol)
    schema = legacy.load_legacy_unischema(data)
    assert schema.name == 'LegacySchema'
    assert set(schema.fields) == {'id', 'name', 'image', 'matrix', 'packed', 'price'}
    assert schema.fields['id'].numpy_dtype is np.int64
    assert isinstance(schema.fields['id'].codec, our_codecs.ScalarCodec)
    img = schema.fields['image'].codec
    assert isinstance(img, our_codecs.CompressedImageCodec)
    assert img._format == 'jpeg' and img._quality == 55
    assert isinstance(schema.fields['matrix'].codec, our_codecs.NdarrayCodec)
    assert isinstance(schema.fields['packed'].codec, our_codecs.CompressedNdarrayCodec)
    assert schema.fields['packed'].nullable
    assert schema.fields['packed'].shape == (None,)
    assert schema.fields['price'].numpy_dtype is Decimal


def test_legacy_row_group_counts(ref):
    # the reference stores this key as JSON, not pickle (etl/dataset_metadata.py:226-228)
    import json
    data = json.dumps({'part-0.parquet': 3, 'part-1.parquet': 2}).encode('utf-8')
    counts = legacy.load_legacy_row_group_counts(data)
    assert counts == {'part-0.parquet': 3, 'part-1.parquet': 2}


def test_legacy_rowgroup_indexes(ref):
    single = ref.SingleFieldIndexer('by_name', 'name')
    single._index_data['alice'].add(0)
    single._index_data['bob'].update({1, 2})
    notnull = ref.FieldNotNullIndexer('has_packed', 'packed')
    notnull._index_data.update({0, 2})
    data = pickle.dumps({'by_name': single, 'has_packed': notnull}, protocol=2)

    indexes = legacy.load_legacy_rowgroup_indexes(data)
    assert indexes['by_name'].get_row_group_indexes('alice') == {0}
    assert indexes['by_name'].get_row_group_indexes('bob') == {1, 2}
    assert set(indexes['has_packed'].get_row_group_indexes()) == {0, 2}


def test_unpickler_refuses_arbitrary_classes(ref):
    evil = pickle.dumps(types.SimpleNamespace(x=1), protocol=2)
    with pytest.raises(pickle.UnpicklingError, match='Refusing to depickle'):
        legacy.restricted_loads(evil)


def test_unpickler_refuses_os_system():
    # classic RCE payload shape: GLOBAL os.system + REDUCE
    payload = b"cos\nsystem\np0\n(S'true'\np1\ntp2\nRp3\n."
    with pytest.raises(pickle.UnpicklingError, match='Refusing to depickle'):
        legacy.restricted_loads(payload)


def test_legacy_dataset_reads_end_to_end(ref, tmp_path):
    """A dataset carrying ONLY the reference's pickled metadata keys must read
    through make_reader: schema from the legacy pickle, row-group counts from
    the legacy counts dict, payloads via the wire-compatible codecs."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.etl.dataset_metadata import write_petastorm_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    # write payload files with our writer (byte-compatible formats)...
    our_schema = Unischema('LegacySchema', [
        UnischemaField('id', np.int64, (), our_codecs.ScalarCodec(), False),
        UnischemaField('matrix', np.float32, (2, 3), our_codecs.NdarrayCodec(), False),
    ])
    url = 'file://' + str(tmp_path)
    rows = [{'id': i, 'matrix': np.full((2, 3), i, dtype=np.float32)} for i in range(20)]
    write_petastorm_dataset(url, our_schema, rows, rows_per_row_group=5)

    # ...then REPLACE _common_metadata with reference-style pickled keys only
    ref_schema_bytes = pickle.dumps(ref.Unischema('LegacySchema', [
        ref.UnischemaField('id', np.int64, (), ref.ScalarCodec(ref.sql_types.LongType()), False),
        ref.UnischemaField('matrix', np.float32, (2, 3), ref.NdarrayCodec(), False),
    ]), protocol=2)
    import pyarrow.fs as pafs
    fs = pafs.LocalFileSystem()
    files = [f.path for f in fs.get_file_info(pafs.FileSelector(str(tmp_path)))
             if f.path.endswith('.parquet')]
    counts = {}
    for f in sorted(files):
        counts[f.rsplit('/', 1)[1]] = pq.ParquetFile(f).metadata.num_row_groups
    arrow_schema = pq.ParquetFile(sorted(files)[0]).schema_arrow
    import json
    arrow_schema = arrow_schema.with_metadata({
        legacy.REF_UNISCHEMA_KEY: ref_schema_bytes,
        # reference writes counts as JSON (etl/dataset_metadata.py:226-228)
        legacy.REF_ROW_GROUPS_PER_FILE_KEY: json.dumps(counts).encode('utf-8'),
    })
    pq.write_metadata(arrow_schema, str(tmp_path / '_common_metadata'))

    with make_reader(url, shuffle_row_groups=False, reader_pool_type='dummy') as reader:
        out = list(reader)
    assert len(out) == 20
    ids = sorted(r.id for r in out)
    assert ids == list(range(20))
    np.testing.assert_array_equal(out[0].matrix, np.full((2, 3), out[0].id, dtype=np.float32))


def test_copy_tool_migrates_legacy_dataset(ref, tmp_path):
    """petastorm-copy-dataset parity as a MIGRATION path: a store carrying only
    the reference's pickled metadata reads in and copies out as a native store
    (JSON schema metadata), which then reads without any legacy machinery."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.etl.dataset_metadata import read_metadata_dict, write_petastorm_dataset
    from petastorm_tpu.tools.copy_dataset import copy_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    src = tmp_path / 'legacy'
    src.mkdir()
    our_schema = Unischema('LegacySchema', [
        UnischemaField('id', np.int64, (), our_codecs.ScalarCodec(), False),
        UnischemaField('matrix', np.float32, (2, 3), our_codecs.NdarrayCodec(), False),
    ])
    url = 'file://' + str(src)
    rows = [{'id': i, 'matrix': np.full((2, 3), i, dtype=np.float32)} for i in range(20)]
    write_petastorm_dataset(url, our_schema, rows, rows_per_row_group=5)

    ref_schema_bytes = pickle.dumps(ref.Unischema('LegacySchema', [
        ref.UnischemaField('id', np.int64, (), ref.ScalarCodec(ref.sql_types.LongType()), False),
        ref.UnischemaField('matrix', np.float32, (2, 3), ref.NdarrayCodec(), False),
    ]), protocol=2)
    import json
    import pyarrow.fs as pafs
    fs = pafs.LocalFileSystem()
    files = [f.path for f in fs.get_file_info(pafs.FileSelector(str(src)))
             if f.path.endswith('.parquet')]
    counts = {f.rsplit('/', 1)[1]: pq.ParquetFile(f).metadata.num_row_groups
              for f in sorted(files)}
    arrow_schema = pq.ParquetFile(sorted(files)[0]).schema_arrow.with_metadata({
        legacy.REF_UNISCHEMA_KEY: ref_schema_bytes,
        legacy.REF_ROW_GROUPS_PER_FILE_KEY: json.dumps(counts).encode('utf-8'),
    })
    pq.write_metadata(arrow_schema, str(src / '_common_metadata'))

    target = 'file://' + str(tmp_path / 'native')
    copied = copy_dataset(url, target, rows_per_row_group=10)
    assert copied == 20

    from petastorm_tpu.etl.dataset_metadata import UNISCHEMA_KEY
    meta = read_metadata_dict(target)
    key = UNISCHEMA_KEY if isinstance(UNISCHEMA_KEY, bytes) else UNISCHEMA_KEY.encode()
    assert key in {k if isinstance(k, bytes) else k.encode() for k in meta}  # native JSON schema
    with make_reader(target, shuffle_row_groups=False, reader_pool_type='dummy') as reader:
        out = sorted(r.id for r in reader)
    assert out == list(range(20))
